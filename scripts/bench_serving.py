"""Serving latency bench — the reference's only serving perf claim is
"sub-millisecond latency" for continuous Spark Serving
(``website/docs/features/spark_serving/about.md:18,150-153``); this measures
the same request→pipeline→reply loop here with hard numbers.

Two configs, one JSON line each:

* ``echo``   — trivial transform (adds a constant column): pure serving-stack
  latency (HTTP parse, queue, batch, route, reply), the reference's claim.
* ``model``  — a jitted linear scorer in the loop: what a real pipeline adds.

Latency is measured client-side over sequential keep-alive requests
(p50/p99), plus a concurrent-burst throughput figure from 8 threads.
CPU-only — the serving stack is host code; run anywhere.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# serving latency is host-side by definition; without this the jitted scorer
# lands on the session's tunneled TPU and every request pays a ~70 ms RTT
os.environ.pop("JAX_PLATFORMS", None)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _post(url: str, body: bytes) -> bytes:
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.read()


def _measure(url: str, payload: dict, n: int, warmup: int = 20):
    body = json.dumps(payload).encode()
    for _ in range(warmup):
        _post(url, body)
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        _post(url, body)
        lat.append((time.perf_counter() - t0) * 1e3)
    lat = np.sort(np.array(lat))
    return (round(float(np.percentile(lat, 50)), 3),
            round(float(np.percentile(lat, 99)), 3))


def _burst(url: str, payload: dict, threads: int = 8, per_thread: int = 50):
    """Aggregate req/s over a thread burst on PERSISTENT keep-alive
    connections (one per worker — a fresh TCP connection per request would
    measure ThreadingHTTPServer's thread-spawn path, not the serving loop).
    Failed requests are counted and excluded from the rate so an overloaded
    run reads as degraded, not as a crash or an inflated number."""
    import http.client
    from urllib.parse import urlparse
    u = urlparse(url)
    body = json.dumps(payload).encode()
    ok, errs = [0], [0]
    lock = threading.Lock()

    def worker():
        conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
        o = e = 0
        for _ in range(per_thread):
            try:
                conn.request("POST", u.path or "/", body,
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                r.read()
                o += 1
            except Exception:
                e += 1
                conn.close()    # reconnect after an error
        conn.close()
        with lock:
            ok[0] += o
            errs[0] += e

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    dt = time.perf_counter() - t0
    return round(ok[0] / dt, 1), errs[0]


def main():
    from mmlspark_tpu.serving.engine import ServingEngine

    n = int(os.environ.get("BENCH_SERVING_N", "300"))

    # --- echo: serving-stack floor --------------------------------------
    def echo(df):
        out = df.with_column("reply", [{"ok": True, "x": float(x)}
                                       for x in df["x"]])
        return out

    with ServingEngine(echo, schema={"x": float}, poll_timeout=0.001) as eng:
        url = eng.address
        p50, p99 = _measure(url, {"x": 1.5}, n)
        rps, _ = _burst(url, {"x": 1.5})
    print(json.dumps({"metric": "serving_echo_latency_ms", "p50": p50,
                      "p99": p99, "burst_rps_8threads": rps,
                      "n": n}), flush=True)

    # --- model: jitted scorer in the loop -------------------------------
    import jax
    import jax.numpy as jnp

    w = jnp.asarray(np.random.default_rng(0).normal(0, 1, (16,)), jnp.float32)
    score = jax.jit(lambda X: jnp.tanh(X @ w))

    def model(df):
        X = jnp.asarray(np.stack([np.asarray(v, np.float32)
                                  for v in df["features"]]))
        y = np.asarray(score(X))
        return df.with_column("reply", [{"score": float(s)} for s in y])

    feats = [0.1] * 16
    with ServingEngine(model, schema={"features": list},
                       poll_timeout=0.001) as eng:
        url = eng.address
        _post(url, json.dumps({"features": feats}).encode())  # compile
        p50, p99 = _measure(url, {"features": feats}, n)
        rps, _ = _burst(url, {"features": feats})
    print(json.dumps({"metric": "serving_model_latency_ms", "p50": p50,
                      "p99": p99, "burst_rps_8threads": rps,
                      "n": n}), flush=True)

    # --- load curve: transport x dispatchers x concurrent clients --------
    # the single-dispatcher engine serializes batch formation with the
    # transform; this shows what each extra dispatcher buys at each client
    # concurrency level, for both transports. Caveat recorded with the
    # numbers: clients are co-located threads, so past ~CPU-count
    # concurrency the curve increasingly measures the client, not the
    # server (this image is a 1-core host).
    ncpu = os.cpu_count() or 1
    for transport in ("threaded", "async"):
        for nd in (1, 2, 4):
            with ServingEngine(model, schema={"features": list},
                               poll_timeout=0.001, n_dispatchers=nd,
                               transport=transport) as eng:
                url = eng.address
                _post(url, json.dumps({"features": feats}).encode())
                curve = {}
                for clients in (1, 8, 64):
                    per = max(400 // clients, 6)
                    rate, nerr = _burst(url, {"features": feats},
                                        threads=clients, per_thread=per)
                    curve[str(clients)] = rate
                    if nerr:
                        curve[f"{clients}_errors"] = nerr
            print(json.dumps({"metric": "serving_load_curve_rps",
                              "transport": transport, "dispatchers": nd,
                              "host_cpus": ncpu, "clients_rps": curve}),
                  flush=True)


if __name__ == "__main__":
    main()
