"""Serving latency bench — the reference's only serving perf claim is
"sub-millisecond latency" for continuous Spark Serving
(``website/docs/features/spark_serving/about.md:18,150-153``); this measures
the same request→pipeline→reply loop here with hard numbers.

Two configs, one JSON line each:

* ``echo``   — trivial transform (adds a constant column): pure serving-stack
  latency (HTTP parse, queue, batch, route, reply), the reference's claim.
* ``model``  — a jitted linear scorer in the loop: what a real pipeline adds.

Latency is measured client-side over sequential keep-alive requests
(p50/p99), plus a concurrent-burst throughput figure from 8 threads.
The load curve is driven by ``scripts/serving_client.py`` — an open-loop
rate-controlled generator in a SEPARATE process that flags its own
saturation, so curve points are honest about when they stop measuring the
server (round-3 weakness: co-located thread bursts measured the client).

Default CPU-only (the serving stack is host code; run anywhere).
``BENCH_SERVING_TPU=1`` additionally serves a real ONNX model on the
default (TPU) backend through the batching dispatcher — the chip-in-the-
loop row, where every request pays the host↔device round trip.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TPU_MODE = os.environ.get("BENCH_SERVING_TPU", "0") == "1"

if not TPU_MODE:
    # serving latency is host-side by definition; without this the jitted
    # scorer lands on the session's tunneled TPU and every request pays a
    # ~70 ms RTT
    from mmlspark_tpu.utils.device import force_cpu  # noqa: E402
    force_cpu()


def _post(url: str, body: bytes) -> bytes:
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.read()


def _measure(url: str, payload: dict, n: int, warmup: int = 20):
    body = json.dumps(payload).encode()
    for _ in range(warmup):
        _post(url, body)
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        _post(url, body)
        lat.append((time.perf_counter() - t0) * 1e3)
    lat = np.sort(np.array(lat))
    return (round(float(np.percentile(lat, 50)), 3),
            round(float(np.percentile(lat, 99)), 3))


def _burst(url: str, payload: dict, threads: int = 8, per_thread: int = 50):
    """Aggregate req/s over a thread burst on PERSISTENT keep-alive
    connections (one per worker — a fresh TCP connection per request would
    measure ThreadingHTTPServer's thread-spawn path, not the serving loop).
    Failed requests are counted and excluded from the rate so an overloaded
    run reads as degraded, not as a crash or an inflated number."""
    import http.client
    from urllib.parse import urlparse
    u = urlparse(url)
    body = json.dumps(payload).encode()
    ok, errs = [0], [0]
    lock = threading.Lock()

    def worker():
        conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
        o = e = 0
        for _ in range(per_thread):
            try:
                conn.request("POST", u.path or "/", body,
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                r.read()
                o += 1
            except Exception:
                e += 1
                conn.close()    # reconnect after an error
        conn.close()
        with lock:
            ok[0] += o
            errs[0] += e

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    dt = time.perf_counter() - t0
    return round(ok[0] / dt, 1), errs[0]


def _driven(url, rate, duration, conns, payload):
    """One rate-controlled curve point from the separate-process client."""
    import subprocess
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "serving_client.py"),
         url, str(rate), str(duration), str(conns)],
        input=json.dumps(payload).encode(),
        capture_output=True, timeout=duration * 4 + 60)
    if r.returncode != 0:
        raise RuntimeError(r.stderr.decode()[-500:])
    return json.loads(r.stdout)


def main():
    from mmlspark_tpu.serving.engine import ServingEngine

    n = int(os.environ.get("BENCH_SERVING_N", "300"))

    # --- echo: serving-stack floor --------------------------------------
    def echo(df):
        out = df.with_column("reply", [{"ok": True, "x": float(x)}
                                       for x in df["x"]])
        return out

    with ServingEngine(echo, schema={"x": float}, poll_timeout=0.001) as eng:
        url = eng.address
        p50, p99 = _measure(url, {"x": 1.5}, n)
        rps, _ = _burst(url, {"x": 1.5})
    print(json.dumps({"metric": "serving_echo_latency_ms", "p50": p50,
                      "p99": p99, "burst_rps_8threads": rps,
                      "n": n}), flush=True)

    if TPU_MODE:
        # chip-in-the-loop ONLY: the host-side scorer rows below would land
        # their jax.jit on the tunneled TPU (≈70 ms RTT per request) and
        # corrupt the host-serving curve — those rows are produced by the
        # default CPU-pinned run
        _tpu_section(ServingEngine, n)
        return

    # --- model: jitted scorer in the loop -------------------------------
    import jax
    import jax.numpy as jnp

    w = jnp.asarray(np.random.default_rng(0).normal(0, 1, (16,)), jnp.float32)
    score = jax.jit(lambda X: jnp.tanh(X @ w))

    def model(df):
        X = jnp.asarray(np.stack([np.asarray(v, np.float32)
                                  for v in df["features"]]))
        y = np.asarray(score(X))
        return df.with_column("reply", [{"score": float(s)} for s in y])

    feats = [0.1] * 16
    with ServingEngine(model, schema={"features": list},
                       poll_timeout=0.001) as eng:
        url = eng.address
        _post(url, json.dumps({"features": feats}).encode())  # compile
        p50, p99 = _measure(url, {"features": feats}, n)
        rps, _ = _burst(url, {"features": feats})
    print(json.dumps({"metric": "serving_model_latency_ms", "p50": p50,
                      "p99": p99, "burst_rps_8threads": rps,
                      "n": n}), flush=True)

    # --- load curve: rate-controlled clients in a SEPARATE process -------
    # For each transport × dispatcher count, step the offered rate up until
    # the server degrades (errors / p99 blow-up) or the CLIENT saturates —
    # and report which of the two stopped the sweep. The client process
    # flags its own saturation, so a curve point never silently
    # under-reports the server (round-3 weakness #8).
    ncpu = os.cpu_count() or 1
    duration = float(os.environ.get("BENCH_SERVING_DURATION", "3"))
    conns = int(os.environ.get("BENCH_SERVING_CONNS", "16"))
    for transport in ("threaded", "async"):
        for nd in (1, 2, 4):
            with ServingEngine(model, schema={"features": list},
                               poll_timeout=0.001, n_dispatchers=nd,
                               transport=transport) as eng:
                url = eng.address
                _post(url, json.dumps({"features": feats}).encode())
                best, first_bad, why = None, None, None
                rate = 100.0
                while rate <= 12800:
                    pt = _driven(url, rate, duration, conns,
                                 {"features": feats})
                    if pt["errors"] or pt.get("p99_ms", 0) > 250:
                        first_bad, why = pt, "server"
                        break
                    if pt["client_saturated"]:
                        first_bad, why = pt, "client"
                        break
                    best = pt
                    rate *= 2
            print(json.dumps({
                "metric": "serving_rate_curve",
                "transport": transport, "dispatchers": nd,
                "host_cpus": ncpu, "connections": conns,
                "max_clean_point": best,
                "limited_by": why or "sweep_ceiling",
                "first_degraded_point": first_bad}), flush=True)

    # (chip-in-the-loop section runs in TPU_MODE via the early return above)


def _tpu_section(ServingEngine, n):
    """Chip in the loop: request → batching dispatcher → ONNXModel on the
    default (TPU) backend → reply. Reference claim anchor:
    HTTPSourceV2.scala:476-697 + ONNXModel. Every request pays
    host→device→host; the batching dispatcher amortizes it across the
    requests it drains together."""
    import jax

    from mmlspark_tpu.core import DataFrame as MDF
    from mmlspark_tpu.models.onnx_model import ONNXModel
    from mmlspark_tpu.models.zoo.resnet import (ResNetConfig,
                                                export_resnet_onnx)

    duration = float(os.environ.get("BENCH_SERVING_DURATION", "3"))
    plat = jax.devices()[0].platform
    # a ResNet-18-ish backbone at 64px: a real conv model, small
    # enough that serving latency is not dominated by one forward
    cfg = ResNetConfig([2, 2, 2, 2], num_classes=100, width=32)
    m = ONNXModel(export_resnet_onnx(cfg, seed=0),
                  feed_dict={"input": "image"},
                  fetch_dict={"logits": "logits"},
                  argmax_dict={"pred": "logits"},
                  transpose_dict={"input": [0, 3, 1, 2]},
                  mini_batch_size=64, compute_dtype="bfloat16")

    def tpu_model(df):
        k = len(df["image"])
        col = np.empty(k, dtype=object)
        for i, v in enumerate(df["image"]):
            col[i] = np.asarray(v, np.uint8).reshape(64, 64, 3)
        out = m.transform(MDF({"image": col}))
        return df.with_column(
            "reply", [{"pred": int(p)} for p in out["pred"]])

    img = np.random.default_rng(0).integers(
        0, 256, (64, 64, 3), np.uint8).reshape(-1).tolist()
    # warm every jit bucket the driven phase can hit: concurrent requests
    # drain as ragged groups padded to pow2 buckets (1/2/4/8) and each
    # unseen bucket is a fresh REMOTE compile — the r5 campaign's rate
    # point (0.3 achieved rps, 7 errors at target 32) was those compiles
    # landing inside the 3 s window, not serving capacity. Compile
    # directly through the model (an HTTP-side warmup would time out
    # while a remote compile runs); same discipline as bench_decode's
    # full-pool warmup.
    arr = np.asarray(img, np.uint8).reshape(64, 64, 3)
    for k in (1, 2, 4, 8):
        col = np.empty(k, dtype=object)
        col[:] = [arr] * k
        m.transform(MDF({"image": col}))
    with ServingEngine(tpu_model, schema={"image": list},
                       poll_timeout=0.001, n_dispatchers=2,
                       transport="async") as eng:
        url = eng.address
        _post(url, json.dumps({"image": img}).encode())   # engine-path warm
        _burst(url, {"image": img}, threads=8, per_thread=2)
        p50, p99 = _measure(url, {"image": img}, max(n // 4, 40))
        pt = _driven(url, 32.0, duration, 8, {"image": img})
    print(json.dumps({"metric": "serving_onnx_model_latency_ms",
                      "platform": plat, "p50": p50, "p99": p99,
                      "rate_point": pt}), flush=True)


if __name__ == "__main__":
    main()
