"""Benches for BASELINE.json configs #2-#4: BERT embeddings, ImageFeaturizer
transfer-learning, and explainer (repeated-inference) throughput.

Each prints one JSON line. Sized by env:
  BENCH_BERT_ROWS / BENCH_FEAT_ROWS / BENCH_SHAP_ROWS, BENCH_SCALE=small
(small = CPU-friendly shapes for smoke tests; default = benchmark shapes).

Run on the chip: ``python scripts/bench_configs.py [bert|featurizer|shap]``.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMALL = os.environ.get("BENCH_SCALE", "") == "small"


def _bench_transform(model, df, n_rows, passes=3):
    """Best-of-N e2e rate + spread fields (every campaign row carries them:
    a single tunnel-window artifact must be visible in the row itself)."""
    out = model.transform(df.head(min(8, n_rows)))  # warmup/compile
    assert len(out) > 0
    rates = []
    for _ in range(passes):
        t0 = time.perf_counter()
        out = model.transform(df)
        rates.append(n_rows / (time.perf_counter() - t0))
    assert len(out) == n_rows
    return {"value": round(max(rates), 2), "best_of": len(rates),
            "pass_spread": round((max(rates) - min(rates)) / max(rates), 3)}


def _device_resident_rate(onnx_model, feeds_np, reps=10):
    """Rows/sec once inputs are already on device — separates the chip from
    the tunnel (same convention as the headline bench's
    ``device_resident_ips``). Fencing via a fetched scalar on the LAST
    dispatch (in-order execution fences the earlier ones)."""
    import jax
    import jax.numpy as jnp
    jitted = onnx_model._ensure_jitted()
    params = onnx_model._params_for_device(None)
    devs = {k: jax.device_put(v) for k, v in feeds_np.items()}
    n = next(iter(feeds_np.values())).shape[0]

    def tail(outs):
        leaf = jax.tree_util.tree_leaves(outs)[0]
        return float(jnp.sum(leaf.reshape(-1)[:2].astype(jnp.float32)))

    tail(jitted(params, devs))          # compile + warm
    t0 = time.perf_counter()
    outs = None
    for _ in range(reps):
        outs = jitted(params, devs)
    tail(outs)
    return round(n * reps / (time.perf_counter() - t0), 2)


def _device_resident_rate_fused(onnx_model, feeds_np, R=10, reps=3):
    """Fused-scan variant of ``_device_resident_rate``: R forwards inside
    ONE compiled program, each iteration's input data-dependent on the
    previous output (the carry perturbs one feed, so XLA cannot hoist the
    loop-invariant forward out of the scan) — the ~ms per-dispatch
    runtime floor amortizes R×. Same methodology and mean-of-reps
    estimator as the headline's ``device_resident_ips_fused``."""
    import jax
    import jax.numpy as jnp
    jitted = onnx_model._ensure_jitted()
    params = onnx_model._params_for_device(None)
    devs = {k: jax.device_put(v) for k, v in feeds_np.items()}
    n = next(iter(feeds_np.values())).shape[0]
    key0 = next(iter(feeds_np))     # first feed in caller order (BERT:
    #                                 ids, not the all-ones mask)

    @jax.jit
    def fused(params, devs):
        def body(t, _):
            f = dict(devs)
            x = f[key0]
            if jnp.issubdtype(x.dtype, jnp.unsignedinteger):
                # uint8 pixels: xor the lowest bit — stays in range
                # (subtraction would wrap 0 -> 255 before any clamp)
                f[key0] = x ^ t.astype(x.dtype)
            elif jnp.issubdtype(x.dtype, jnp.integer):
                # token-id-safe perturbation: stays within [0, vocab)
                f[key0] = jnp.maximum(x - t.astype(x.dtype), 0)
            else:
                f[key0] = x + t.astype(x.dtype)
            outs = jitted(params, f)
            leaf = jax.tree_util.tree_leaves(outs)[0]
            nxt = (jnp.abs(leaf.reshape(-1)[0].astype(jnp.float32))
                   > 0).astype(jnp.int32)
            return nxt, None
        t, _ = jax.lax.scan(body, jnp.int32(0), None, length=R)
        return t

    int(fused(params, devs))                  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        int(fused(params, devs))              # fetched scalar = fence
    return round(n * R * reps / (time.perf_counter() - t0), 2)


def _fused_or_none(onnx_model, feeds_np, **kw):
    """Failure-tolerant wrapper (parity with bench.py's fused field): a
    scan-trace/compile failure must not abort the bench after the e2e and
    per-dispatch measurements already ran — the row ships with None."""
    try:
        return _device_resident_rate_fused(onnx_model, feeds_np, **kw)
    except Exception:                           # noqa: BLE001
        return None


def bench_bert():
    """Config #3: BERT-base-shaped sentence embeddings over a token column
    through the foreign-ONNX importer (torch-exporter-style graph)."""
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.models.onnx_model import ONNXModel
    from mmlspark_tpu.models.zoo.bert_onnx import (BertOnnxConfig,
                                                   export_bert_onnx)

    if SMALL:
        cfg = BertOnnxConfig()
        n_rows, batch, seq = 32, 8, 64
    else:
        # BERT-base dimensions (vocab kept small: embedding lookup cost is
        # row-gather, invariant to vocab beyond cache effects)
        cfg = BertOnnxConfig(vocab=8192, layers=12, d_model=768, heads=12,
                             d_ff=3072, max_len=128)
        n_rows, batch, seq = 2048, 128, 128
    n_rows = int(os.environ.get("BENCH_BERT_ROWS", n_rows))
    rng = np.random.default_rng(0)
    model_bytes = export_bert_onnx(cfg, seed=0)
    # fetch the mean-pooled sentence embedding (B, D), not the full
    # (B, S, D) hidden states: a sentence-embedding pipeline only needs the
    # pooled vector, and the device→host transfer shrinks by S× (800 MB →
    # 6 MB at 2048×128×768 — behind a congested tunnel that difference IS
    # the benchmark)
    m = ONNXModel(model_bytes,
                  feed_dict={"input_ids": "ids", "attention_mask": "mask"},
                  fetch_dict={"emb": "pooled"},
                  mini_batch_size=batch, compute_dtype="bfloat16")
    ids = rng.integers(0, cfg.vocab, (n_rows, seq), dtype=np.int64)
    mask = np.ones((n_rows, seq), dtype=np.int64)
    df = DataFrame({"ids": [r for r in ids], "mask": [r for r in mask]})
    res = _bench_transform(m, df, n_rows)
    bert_feeds = {"input_ids": ids[:batch], "attention_mask": mask[:batch]}
    dev = _device_resident_rate(m, bert_feeds)
    dev_fused = _fused_or_none(m, bert_feeds)
    print(json.dumps({"metric": "bert_base_embeddings_seq_per_sec",
                      **res, "unit": "sequences/sec/chip",
                      "device_resident_sps": dev,
                      "device_resident_sps_fused": dev_fused,
                      "seq_len": seq, "layers": cfg.layers,
                      "d_model": cfg.d_model,
                      "platform": _platform()}), flush=True)


def bench_featurizer():
    """Config #4: ImageFeaturizer (ONNX backbone, cut layer) over images."""
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.models.featurizer import ImageFeaturizer
    from mmlspark_tpu.models.zoo.resnet import (RESNET18_CFG, RESNET50,
                                                export_resnet_onnx)

    cfg = RESNET18_CFG if SMALL else RESNET50
    n_rows = 16 if SMALL else 1024
    n_rows = int(os.environ.get("BENCH_FEAT_ROWS", n_rows))
    size = 64 if SMALL else 224
    rng = np.random.default_rng(0)
    feat = ImageFeaturizer(onnx_model=export_resnet_onnx(cfg, seed=0),
                           input_col="image", output_col="features",
                           input_size=size,
                           mini_batch_size=(8 if SMALL else 128))
    imgs = rng.integers(0, 256, (n_rows, size, size, 3), dtype=np.uint8)
    df = DataFrame({"image": [i for i in imgs]})
    res = _bench_transform(feat, df, n_rows)
    # device-resident: the inner backbone on a pre-staged uint8 batch with
    # the same on-device transpose+normalize prep the e2e path uses
    inner = feat._inner()
    feed_name = list(inner.model_inputs())[0]
    inner_cfg = inner.copy({
        "feed_dict": {feed_name: "image"},
        "fetch_dict": {"features": feat.get("feature_output")},
        "transpose_dict": {feed_name: [0, 3, 1, 2]},
        "normalize_dict": {feed_name: {"scale": float(feat.get("scale"))}}})
    feat_feeds = {feed_name: imgs[:min(128, n_rows)]}
    dev = _device_resident_rate(inner_cfg, feat_feeds)
    dev_fused = _fused_or_none(inner_cfg, feat_feeds)
    print(json.dumps({"metric": "image_featurizer_images_per_sec",
                      **res, "unit": "images/sec/chip",
                      "device_resident_ips": dev,
                      "device_resident_ips_fused": dev_fused,
                      "platform": _platform()}), flush=True)


def bench_shap():
    """Config #5: KernelSHAP over an ONNXModel — stresses repeated batched
    inference (the explainer hot path, KernelSHAPBase.scala:43-94)."""
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.explainers.shap import VectorSHAP
    from mmlspark_tpu.models.onnx_model import ONNXModel
    from mmlspark_tpu.onnx import builder as O

    d = 8
    rng = np.random.default_rng(0)
    w1 = rng.normal(0, 0.5, (d, 32)).astype(np.float32)
    w2 = rng.normal(0, 0.5, (32, 2)).astype(np.float32)
    g = O.make_graph(
        [O.make_node("MatMul", ["x", "w1"], ["h"]),
         O.make_node("Relu", ["h"], ["r"]),
         O.make_node("MatMul", ["r", "w2"], ["logits"]),
         O.make_node("Softmax", ["logits"], ["probs"], axis=-1)],
        "mlp",
        inputs=[O.make_tensor_value_info("x", np.float32, ["N", d])],
        outputs=[O.make_tensor_value_info("probs", np.float32, ["N", 2])],
        initializers={"w1": w1, "w2": w2})
    # one jitted dispatch scores THOUSANDS of coalition rows: the explainer
    # already batches all rows x samples through one _score_frame pass, so
    # the inner batch size should match that scale — 256-row batches made
    # the leg dispatch-count-bound (32 tiny dispatches per explain pass)
    m_samples = 8 if SMALL else 128
    n_rows = 4 if SMALL else 64
    n_rows = int(os.environ.get("BENCH_SHAP_ROWS", n_rows))
    inner = ONNXModel(O.make_model(g), feed_dict={"x": "features"},
                      fetch_dict={"probs": "probs"},
                      mini_batch_size=max(256, n_rows * m_samples),
                      pin_devices=False)
    X = rng.normal(0, 1, (n_rows, d)).astype(np.float32)
    bg = rng.normal(0, 1, (16, d)).astype(np.float32)
    shap = VectorSHAP(model=inner, input_col="features",
                      target_col="probs", target_classes=[1],
                      num_samples=m_samples,
                      background_data=DataFrame(
                          {"features": [b for b in bg]}))
    df = DataFrame({"features": [x for x in X]})
    res = _bench_transform(shap, df, n_rows)
    # device-resident: the coalition-scoring dispatch on a pre-staged
    # (n*m, d) matrix, divided back to explained-rows/sec
    flat = rng.normal(0, 1, (n_rows * m_samples, d)).astype(np.float32)
    dev_score = _device_resident_rate(inner, {"x": flat})
    dev_score_fused = _fused_or_none(inner, {"x": flat})
    print(json.dumps({"metric": "kernel_shap_rows_per_sec",
                      **res,
                      "unit": "explained rows/sec/chip",
                      "device_resident_rows_per_sec":
                          round(dev_score / m_samples, 2),
                      "device_resident_rows_per_sec_fused":
                          (round(dev_score_fused / m_samples, 2)
                           if dev_score_fused is not None else None),
                      "samples_per_row": m_samples,
                      "platform": _platform()}), flush=True)


def _platform():
    import jax
    return jax.default_backend()


ALL = {"bert": bench_bert, "featurizer": bench_featurizer,
       "shap": bench_shap}


def main():
    targets = sys.argv[1:] or list(ALL)
    for t in targets:
        ALL[t]()


if __name__ == "__main__":
    main()
