"""Wide-sparse GBDT benchmark: EFB bundled vs unbundled training.

The shape LightGBM's EFB exists for (hashed/one-hot features): groups of
mutually exclusive columns, each row holding one value per group. Prints
one JSON line with sec/iter for both paths and the bundle compression
factor. Parity anchor: LightGBM ``enable_bundle`` (native C++ behind the
reference's param passthrough, ``params/TrainParams.scala:10-100``).

Usage: python scripts/bench_gbdt_sparse.py [n_rows] [n_groups] [per_group]
Env: SPARSE_ITERS (default 10), SPARSE_LEAVES (31).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_exclusive(n, groups, per_group, seed=0):
    import scipy.sparse as sp
    rng = np.random.default_rng(seed)
    F = groups * per_group
    # CSR built directly: one entry per (row, group)
    indptr = np.arange(n + 1, dtype=np.int64) * groups
    cols = (np.arange(groups)[None, :] * per_group
            + rng.integers(0, per_group, (n, groups))).ravel()
    vals = rng.normal(1, 1, n * groups).astype(np.float32)
    X = sp.csr_matrix((vals, cols.astype(np.int32), indptr), shape=(n, F))
    y = (np.asarray(X[:, 0].todense()).ravel()
         + np.asarray(X[:, per_group].todense()).ravel()
         + rng.normal(0, 0.3, n) > 0.8).astype(np.float64)
    return X, y


def time_train(params, X, y, iters):
    from mmlspark_tpu.models.gbdt import train
    t0 = time.perf_counter()
    train(dict(params, num_iterations=2), X, y)     # compile + bin warmup
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    train(dict(params, num_iterations=iters), X, y)
    total = time.perf_counter() - t0
    return warm, total / iters


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
    groups = int(sys.argv[2]) if len(sys.argv) > 2 else 200
    per_group = int(sys.argv[3]) if len(sys.argv) > 3 else 10
    iters = int(os.environ.get("SPARSE_ITERS", "10"))
    leaves = int(os.environ.get("SPARSE_LEAVES", "31"))

    X, y = make_exclusive(n, groups, per_group)
    params = {"objective": "binary", "num_leaves": leaves,
              "min_data_in_leaf": 20, "max_bin": 63}

    # reporting-only bundler fit on a row subsample — the timed train()
    # calls plan their own bundles; a full extra O(nnz) pass here would
    # burn healthy-chip-window time for a single JSON field
    from mmlspark_tpu.models.gbdt.binning import BinMapper
    from mmlspark_tpu.models.gbdt.bundling import FeatureBundler
    Xs = X[:min(n, 50_000)].tocsr()
    mapper = BinMapper(max_bin=63).fit(Xs)
    bundler = FeatureBundler(0.0).fit(Xs, mapper)

    warm_b, sec_b = time_train(dict(params, enable_bundle=True), X, y, iters)
    warm_u, sec_u = time_train(dict(params, enable_bundle=False), X, y, iters)

    import jax
    d = jax.devices()[0]
    print(json.dumps({
        "metric": "gbdt_sparse_efb_sec_per_iter",
        "n_rows": n, "n_features": groups * per_group,
        "n_bundles": bundler.n_bundles,
        "compression": round(groups * per_group / bundler.n_bundles, 2),
        "value": sec_b, "unit": "sec/iter",
        "sec_per_iter_bundled": round(sec_b, 4),
        "sec_per_iter_unbundled": round(sec_u, 4),
        "speedup": round(sec_u / max(sec_b, 1e-9), 2),
        "warmup_bundled_sec": round(warm_b, 2),
        "platform": d.platform, "device": d.device_kind}))


if __name__ == "__main__":
    main()
