#!/usr/bin/env python
"""Regenerate tools/tpulint/baseline.json from the current tree.

Run after fixing a baselined finding (shrinks the baseline) or after
deliberately accepting a new one (grows it — prefer an inline
``# tpulint: disable=RULE`` with a justification for point exceptions).

Usage:
    python scripts/gen_tpulint_baseline.py            # scan mmlspark_tpu
    python scripts/gen_tpulint_baseline.py pkg other  # custom paths
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
os.chdir(REPO_ROOT)  # fingerprints are repo-relative; pin the root

from tools.tpulint.cli import main  # noqa: E402

BASELINE = os.path.join(REPO_ROOT, "tools", "tpulint", "baseline.json")


if __name__ == "__main__":
    paths = sys.argv[1:] or ["mmlspark_tpu"]
    sys.exit(main(paths + ["--write-baseline", BASELINE]))
