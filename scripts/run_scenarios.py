#!/usr/bin/env python
"""Run a named loadgen scenario against a local 3-worker ServingCluster.

Examples::

    python scripts/run_scenarios.py --list
    python scripts/run_scenarios.py smoke
    python scripts/run_scenarios.py smoke --duration 2 --rate 40 --check
    python scripts/run_scenarios.py mixed-tenant-chaos --json card.json

The cluster, echo engine, and generator all live in this process (the
same shape the federation tests use), so the run is deterministic,
CPU-only, and CI-safe. ``--check`` exits nonzero when the run loses a
request or the federated reconciliation fails — the scenario-smoke CI
gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    from mmlspark_tpu.loadgen import SCENARIOS, get_scenario
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("scenario", nargs="?", help="scenario name")
    ap.add_argument("--list", action="store_true",
                    help="list registered scenarios and exit")
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--duration", type=float, default=None,
                    help="override Scenario.duration_s")
    ap.add_argument("--rate", type=float, default=None,
                    help="override Scenario.rate (requests/second)")
    ap.add_argument("--seed", type=int, default=None,
                    help="override Scenario.seed")
    ap.add_argument("--max-queue", type=int, default=256,
                    help="per-worker admission queue depth")
    ap.add_argument("--service-ms", type=float, default=5.0,
                    help="echo-engine hold per batch (saturation knob)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the scorecard JSON here ('-' = stdout)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on lost requests or failed reconciliation")
    args = ap.parse_args(argv)

    if args.list or not args.scenario:
        for name in sorted(SCENARIOS):
            sc = SCENARIOS[name]
            print(f"{name:>20}  {sc.duration_s:>4.1f}s @ {sc.rate:>5.1f}/s"
                  f"  {sc.arrival:<8} {sc.description}")
        return 0

    overrides = {}
    if args.duration is not None:
        overrides["duration_s"] = args.duration
    if args.rate is not None:
        overrides["rate"] = args.rate
    if args.seed is not None:
        overrides["seed"] = args.seed
    scenario = get_scenario(args.scenario, **overrides)

    from mmlspark_tpu.loadgen import cluster_echo_engine, run_scenario
    from mmlspark_tpu.observability.federation import FEDERATION_INTERVAL_ENV
    from mmlspark_tpu.serving.distributed import ServingCluster

    os.environ.setdefault(FEDERATION_INTERVAL_ENV, "0")
    cluster = ServingCluster(args.workers, reply_timeout=10.0,
                             max_queue=args.max_queue)
    stop = threading.Event()
    engine = cluster_echo_engine(cluster, stop,
                                 service_s=args.service_ms / 1e3, batch=16)
    try:
        card = run_scenario(scenario, cluster, log=print)
    finally:
        stop.set()
        engine.join(timeout=2.0)
        cluster.close()

    if args.json == "-":
        json.dump(card, sys.stdout, indent=2)
        print()
    elif args.json:
        with open(args.json, "w") as fh:
            json.dump(card, fh, indent=2)
        print(f"scorecard written to {args.json}")

    lat = card.get("latency_ms") or {}
    print(f"== {scenario.name}: arrivals={card['arrivals']} "
          f"ok={card['ok']} shed={card['shed']} errors={card['errors']} "
          f"lost={card['lost']} goodput={card['goodput_rps']}/s "
          f"p99={lat.get('p99_ms')}ms "
          f"fairness_err={card['fairness_error']}")
    if card.get("sessions"):
        s = card["sessions"]
        print(f"   sessions={s['sessions']} lost={s['lost']} "
              f"recovered={s['recovered']} "
              f"recovery_p99={s['recovery_p99_ms']}ms")
    if args.check:
        cluster_view = card.get("cluster") or {}
        problems = []
        if card["lost"]:
            problems.append(f"lost {card['lost']} requests")
        if not cluster_view.get("reconciled"):
            problems.append("federated counter reconciliation failed")
        if card["arrivals"] == 0:
            problems.append("empty arrival plan")
        timeline = card.get("timeline") or {}
        if not timeline.get("buckets"):
            problems.append("scorecard timeline is empty")
        elif sum(b["ok"] + b["shed"] + b["errors"]
                 for b in timeline["buckets"]) != card["ok"] + \
                card["shed"] + card["errors"]:
            problems.append("timeline buckets do not sum to card outcomes")
        sessions = card.get("sessions") or {}
        if sessions.get("lost"):
            problems.append(
                f"lost {sessions['lost']} decode sessions "
                f"(recovered={sessions.get('recovered')})")
        if problems:
            print("CHECK FAILED: " + "; ".join(problems), file=sys.stderr)
            return 1
        print("check passed: zero lost, counters reconciled, "
              "timeline populated")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
