"""Decoder (LLM) serving throughput: prefill and KV-cached decode.

Beyond reference parity — SynapseML has no autoregressive serving story at
all (its deep-learning module is batch ONNX inference,
``deep-learning/.../onnx/ONNXModel.scala:305-355``). A TPU-native framework
needs one: this bench measures the two phases every LLM-serving stack is
judged on, on the native zoo decoder (``models/zoo/transformer.py``):

* **prefill** — one batched causal forward over the prompt,
  ``transformer_apply``; compute-bound, rides the MXU.
* **decode** — ``lax.scan`` over ``decode_step`` with the static-shape
  KV-cache updated in place via ``dynamic_update_slice``; one compiled
  program serves the whole loop (no per-token dispatch), the TPU answer to
  ORT's GroupQueryAttention decode loop.

Prints one JSON line per phase. Sized by env: BENCH_DECODE_B (batch),
BENCH_DECODE_P (prompt len), BENCH_DECODE_T (new tokens),
BENCH_SCALE=small for CPU-friendly shapes. All timings fenced by fetched
scalars (block_until_ready lies behind the tunnel — BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMALL = os.environ.get("BENCH_SCALE", "") == "small"


def _env_int(name, default):
    return int(os.environ.get(name, default))


def main():
    if SMALL:
        from mmlspark_tpu.utils.device import force_cpu
        jax = force_cpu()
    else:
        import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models.zoo.transformer import (
        TransformerConfig, decode_step, init_kv_cache, init_transformer,
        transformer_apply)
    from mmlspark_tpu.utils.device import is_tpu

    if SMALL or not is_tpu():
        cfg = TransformerConfig(vocab=1024, layers=4, d_model=256, heads=8,
                                d_ff=1024, max_len=256, causal=True,
                                norm="rmsnorm", position="rope")
        B, P, T = 4, 32, 32
    else:
        # GPT-2-small-class decoder (Llama-style: RMSNorm + RoPE), bf16
        cfg = TransformerConfig(vocab=32000, layers=12, d_model=768,
                                heads=12, d_ff=3072, max_len=2048,
                                causal=True, norm="rmsnorm",
                                position="rope")
        B, P, T = 32, 128, 128
    B = _env_int("BENCH_DECODE_B", B)
    P = _env_int("BENCH_DECODE_P", P)
    T = _env_int("BENCH_DECODE_T", T)

    params = init_transformer(cfg, seed=0)
    params = jax.device_put(jax.tree.map(jnp.asarray, params))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (B, P), dtype=np.int32))

    # sweep mode: skip straight to the continuous-batching row (each
    # skipped section is an extra remote compile per sweep point)
    cb_only = os.environ.get("BENCH_CB_ONLY", "0") == "1"

    if not cb_only:
        # ---- prefill: one causal forward over the prompt ----
        @jax.jit
        def prefill(params, ids):
            h = transformer_apply(params, ids, cfg)
            return h[:, -1].astype(jnp.float32) @ params["lm_head"]["w"]

        logits = prefill(params, prompt)                   # compile
        float(jnp.sum(logits))                             # fence
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(jnp.sum(prefill(params, prompt)))
            best = min(best, time.perf_counter() - t0)
        prefill_tps = B * P / best
        print(json.dumps({
            "metric": "decoder_prefill_tokens_per_sec",
            "value": round(prefill_tps, 1), "unit": "tokens/sec/chip",
            "batch": B, "prompt_len": P,
            "params_m": round(n_params / 1e6, 1),
            "ms": round(best * 1e3, 2),
            "platform": jax.default_backend()}), flush=True)

        # ---- decode: whole loop as ONE compiled scan over decode_step ----
        L = P + T
        cache0 = init_kv_cache(cfg, B, L)

        @jax.jit
        def decode(params, first_tok, cache):
            def step(carry, t):
                tok, cache = carry
                logits, cache = decode_step(params, tok, P + t, cache, cfg)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, cache), None

            (tok, cache), _ = jax.lax.scan(step, (first_tok, cache),
                                           jnp.arange(T))
            return tok

        first = prompt[:, -1]
        tok = decode(params, first, cache0)                # compile
        float(jnp.sum(tok))                                # fence
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(jnp.sum(decode(params, first, cache0)))
            best = min(best, time.perf_counter() - t0)
        decode_tps = B * T / best
        print(json.dumps({
            "metric": "decoder_cached_decode_tokens_per_sec",
            "value": round(decode_tps, 1), "unit": "tokens/sec/chip",
            "batch": B, "new_tokens": T, "kv_len": L,
            "params_m": round(n_params / 1e6, 1),
            "ms_per_token": round(best * 1e3 / T, 3),
            "platform": jax.default_backend()}), flush=True)

    # ---- continuous batching: staggered requests through the slot pool ----
    from mmlspark_tpu.serving.continuous import ContinuousDecoder

    n_req = _env_int("BENCH_DECODE_REQS", 2 * B)
    # k decode steps per dispatch: behind the network-attached chip every
    # dispatch pays ~RTT, which the r4 campaign showed dominating this
    # bench (231 tok/s with the chip mostly idle)
    # defaults from the r5 on-chip sweep (record: BASELINE.md §round-5
    # continuation): k=16 ≈ 1.5× k=8 at every measured depth (best 4,265
    # vs 2,888 tok/s) and k=32 bought nothing more; at k=8 depth is
    # monotone harmful (retirement lag), while the k=16 d=1-vs-d=2
    # ordering is within-window noise — d=2 kept as the engine default.
    k_steps = _env_int("BENCH_CB_STEPS", 16)
    cb_depth = _env_int("BENCH_CB_DEPTH", 2)
    # prefill-ahead: stage the next wave's prefills while the pool is
    # full, so wave boundaries pay one insert dispatch instead of
    # prefill + a first-token round-trip (default: one full wave)
    cb_ahead = _env_int("BENCH_CB_AHEAD", B)
    eng = ContinuousDecoder(params, cfg, max_slots=B, max_len=P + T + 1,
                            steps_per_dispatch=k_steps,
                            pipeline_depth=cb_depth,
                            prefill_ahead=cb_ahead)
    rng2 = np.random.default_rng(1)
    # warm the steady-state program set: a full-pool burst compiles the
    # max-size prefill bucket, the power-of-two insert chunks, and the
    # ragged tick — first-time remote compiles are minutes of wall clock
    # that must not land inside the timed region (the r5 campaign caught
    # a 23 s in-run stall from exactly this)
    warm = [eng.submit(rng2.integers(0, cfg.vocab, P), max_new_tokens=2)
            for _ in range(B)]
    while not all(w.done for w in warm):
        eng.step()
    reqs = [eng.submit(rng2.integers(0, cfg.vocab, P), max_new_tokens=T)
            for _ in range(n_req)]
    t0 = time.perf_counter()
    while not all(r.done for r in reqs):
        eng.step()
    dt = time.perf_counter() - t0
    total_toks = sum(len(r.tokens) for r in reqs)
    ttft = [r.first_token_at - r.submitted_at for r in reqs]
    print(json.dumps({
        "metric": "decoder_continuous_batching_tokens_per_sec",
        "value": round(total_toks / dt, 1), "unit": "tokens/sec/chip",
        "slots": B, "requests": n_req, "prompt_len": P, "new_tokens": T,
        "steps_per_dispatch": k_steps, "pipeline_depth": cb_depth,
        "prefill_ahead": cb_ahead,
        "staged_prefills": eng.stats.get("staged_prefills", 0),
        "ttft_p50_ms": round(1e3 * sorted(ttft)[len(ttft) // 2], 1),
        "ttft_max_ms": round(1e3 * max(ttft), 1),
        "platform": jax.default_backend()}), flush=True)

    if cb_only:
        return  # sweep mode: just the continuous-batching row

    # -- speculative decoding: draft-then-verify vs plain cached greedy --
    from mmlspark_tpu.models.zoo.speculative import generate_speculative_fused as generate_speculative
    from mmlspark_tpu.models.zoo.transformer import generate_cached
    d_cfg = cfg._replace(layers=max(1, cfg.layers // 4),
                         d_model=cfg.d_model // 2, heads=cfg.heads // 2,
                         d_ff=cfg.d_ff // 2)
    d_params = init_transformer(d_cfg, seed=1)
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab, (1, P)))
    gamma = _env_int("BENCH_SPEC_GAMMA", 4)
    # warm + check output parity (exact in fp32; under bf16 near-tie
    # argmaxes can flip between the window and step compositions, so the
    # fraction is reported rather than asserted)
    ref = generate_cached(params, prompt, cfg, max_new_tokens=T,
                          temperature=0.0)
    spec, stats = generate_speculative(params, d_params, prompt, cfg,
                                       d_cfg, max_new_tokens=T, gamma=gamma)
    match_frac = float((np.asarray(ref) == np.asarray(spec)).mean())
    t0 = time.perf_counter()
    int(np.asarray(generate_cached(params, prompt, cfg, max_new_tokens=T,
                                   temperature=0.0))[0, -1])   # fence
    plain_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    _, stats = generate_speculative(params, d_params, prompt, cfg, d_cfg,
                                    max_new_tokens=T, gamma=gamma)
    spec_s = time.perf_counter() - t0
    # perfect-draft upper bound: draft == target, acceptance == gamma —
    # what the machinery delivers when the draft is good
    generate_speculative(params, params, prompt, cfg, cfg,
                         max_new_tokens=T, gamma=gamma)       # warm
    t0 = time.perf_counter()
    _, ub = generate_speculative(params, params, prompt, cfg, cfg,
                                 max_new_tokens=T, gamma=gamma)
    ub_s = time.perf_counter() - t0
    print(json.dumps({
        "metric": "decoder_speculative_tokens_per_sec",
        "value": round(T / spec_s, 1), "unit": "tokens/sec/chip",
        "plain_tokens_per_sec": round(T / plain_s, 1),
        "speedup_random_draft": round(plain_s / spec_s, 2),
        "speedup_perfect_draft": round(plain_s / ub_s, 2),
        "gamma": gamma,
        "acceptance_per_round": round(
            stats["accepted_drafts"] / max(stats["rounds"], 1), 2),
        "target_forwards": stats["target_forwards"],
        "perfect_draft_target_forwards": ub["target_forwards"],
        "greedy_match_frac": round(match_frac, 4),
        "platform": jax.default_backend()}), flush=True)

    # -- speculative with a DISTILLED draft: the configuration the feature
    # exists for. The target first trains on a low-entropy synthetic
    # language (markov_sampler — zero-egress stand-in for natural text,
    # which is likewise far below vocab-uniform entropy), then a 2-layer
    # draft distills from the frozen target; acceptance and the wall-clock
    # speedup are reported on prompts from that language. Random-weight
    # rows above stay for continuity — they measure pure machinery cost.
    if os.environ.get("BENCH_SPEC_DISTILL", "1") == "1":
        from mmlspark_tpu.models.zoo.distill import (distill_draft,
                                                     markov_sampler,
                                                     train_lm)
        from mmlspark_tpu.models.zoo.speculative import \
            generate_speculative_fused
        t_steps = _env_int("BENCH_SPEC_TRAIN_STEPS", 30 if SMALL else 200)
        d_steps = _env_int("BENCH_SPEC_DISTILL_STEPS", 30 if SMALL else 300)
        bt = 4 if SMALL else 16
        batch_fn = markov_sampler(cfg.vocab, batch=bt, seq=min(P, 64),
                                  seed=5)
        t0 = time.perf_counter()
        t_trained, _ = train_lm(params, cfg, batch_fn, steps=t_steps,
                                learning_rate=3e-4)
        dd_cfg = cfg._replace(layers=2, d_model=cfg.d_model // 2,
                              heads=max(2, cfg.heads // 2),
                              d_ff=cfg.d_ff // 2)
        dd_params, _ = distill_draft(t_trained, cfg, dd_cfg, batch_fn,
                                     steps=d_steps, learning_rate=1e-3)
        train_s = time.perf_counter() - t0
        mk_prompt = jnp.asarray(batch_fn(777)[:1, :P].astype(np.int32))
        ref = generate_cached(t_trained, mk_prompt, cfg, max_new_tokens=T,
                              temperature=0.0)
        spec, dstats = generate_speculative_fused(
            t_trained, dd_params, mk_prompt, cfg, dd_cfg,
            max_new_tokens=T, gamma=gamma)
        d_match = float((np.asarray(ref) == np.asarray(spec)).mean())
        plain_ts, spec_ts = [], []
        for _ in range(3):               # interleaved best-of (tunnel)
            t0 = time.perf_counter()
            int(np.asarray(generate_cached(
                t_trained, mk_prompt, cfg, max_new_tokens=T,
                temperature=0.0))[0, -1])                      # fence
            plain_ts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            _, dstats = generate_speculative_fused(
                t_trained, dd_params, mk_prompt, cfg, dd_cfg,
                max_new_tokens=T, gamma=gamma)
            spec_ts.append(time.perf_counter() - t0)
        print(json.dumps({
            "metric": "decoder_speculative_distilled_tokens_per_sec",
            "value": round(T / min(spec_ts), 1), "unit": "tokens/sec/chip",
            "plain_tokens_per_sec": round(T / min(plain_ts), 1),
            "speedup_distilled_draft": round(min(plain_ts) / min(spec_ts),
                                             2),
            "best_of": 3,
            "pass_spread": round((max(spec_ts) - min(spec_ts))
                                 / max(spec_ts), 3),
            "gamma": gamma,
            "acceptance_per_round": round(
                dstats["accepted_drafts"] / max(dstats["rounds"], 1), 2),
            "target_forwards": dstats["target_forwards"],
            "greedy_match_frac": round(d_match, 4),
            "train_steps": t_steps, "distill_steps": d_steps,
            "train_plus_distill_sec": round(train_s, 1),
            "draft_layers": 2, "draft_d_model": dd_cfg.d_model,
            "platform": jax.default_backend()}), flush=True)

        # -- speculative CONTINUOUS BATCHING: the distilled draft inside
        # the slot pool (per-slot accept via decode_window_ragged). The
        # plain-engine control runs the SAME trained target on the SAME
        # markov-language prompts, so the row reads as: what does
        # drafting buy a saturated serving pool. Outputs are asserted
        # request-identical between the two engines.
        if os.environ.get("BENCH_CB_SPEC", "1") == "1":
            spec_k = _env_int("BENCH_CB_SPEC_STEPS", 4 if SMALL else 8)
            n_req2 = _env_int("BENCH_DECODE_REQS", 2 * B)
            # one trained target + distilled draft serve every swept
            # gamma — a per-gamma retrain would cost ~80 s of window each
            gammas = [int(g) for g in os.environ.get(
                "BENCH_CB_SPEC_GAMMAS", str(gamma)).split(",")
                if g.strip()] or [gamma]
            prompts2 = [np.asarray(batch_fn(1000 + i)[0, :P], np.int32)
                        for i in range(n_req2)]

            def run_cb(with_draft, g=gamma):
                eng = ContinuousDecoder(
                    t_trained, cfg, max_slots=B, max_len=P + T + 1,
                    steps_per_dispatch=spec_k if with_draft else k_steps,
                    pipeline_depth=cb_depth, prefill_ahead=cb_ahead,
                    draft_params=dd_params if with_draft else None,
                    draft_cfg=dd_cfg if with_draft else None,
                    gamma=g)
                warm2 = [eng.submit(p, max_new_tokens=2)
                         for p in prompts2[:B]]
                while not all(w.done for w in warm2):
                    eng.step()
                reqs2 = [eng.submit(p, max_new_tokens=T)
                         for p in prompts2]
                t0 = time.perf_counter()
                while not all(r.done for r in reqs2):
                    eng.step()
                dt = time.perf_counter() - t0
                return (sum(len(r.tokens) for r in reqs2) / dt,
                        [tuple(r.tokens) for r in reqs2], eng.stats)

            plain_tps, plain_out, _ = run_cb(False)
            for g in gammas:
                spec_tps, spec_out, st = run_cb(True, g)
                assert spec_out == plain_out, \
                    "speculative pool diverged from the plain engine"
                acc = (st.get("spec_emitted", 0)
                       / max(st.get("spec_round_slots", 1), 1))
                print(json.dumps({
                    "metric":
                        "decoder_continuous_batching_spec_tokens_per_sec",
                    "value": round(spec_tps, 1),
                    "unit": "tokens/sec/chip",
                    "plain_tokens_per_sec": round(plain_tps, 1),
                    "speedup": round(spec_tps / plain_tps, 2),
                    "outputs_match": spec_out == plain_out,
                    "slots": B, "requests": n_req2, "prompt_len": P,
                    "new_tokens": T, "gamma": g,
                    "rounds_per_dispatch": spec_k,
                    "tokens_per_round_slot": round(acc, 2),
                    "pipeline_depth": cb_depth,
                    "prefill_ahead": cb_ahead,
                    "platform": jax.default_backend()}), flush=True)


if __name__ == "__main__":
    main()
