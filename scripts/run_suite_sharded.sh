#!/bin/bash
# Full test suite, one pytest process per test file, with one automatic
# retry when a shard dies on the environment's XLA-CPU-compiler SEGFAULT
# (see VERDICT_RESPONSE.md: nondeterministic native crashes in
# backend_compile_and_load on an otherwise idle host; not repo code — a
# monolithic run loses ~an hour per crash, a shard loses one file).
#
# Usage: bash scripts/run_suite_sharded.sh [results_file]
set -u
OUT="${1:-/tmp/sharded_results.txt}"
cd "$(dirname "$0")/.."
: > "$OUT"
pass=0; fail=0; failed_files=""
for f in tests/test_*.py; do
    rc=1
    for attempt in 1 2; do
        python -m pytest "$f" -q --tb=line > /tmp/shard_out.$$ 2>&1
        rc=$?
        [ $rc -eq 0 ] && break
        # rc=139 is the reliable SIGSEGV signal (bash's own "Segmentation
        # fault" notice never lands in the redirected file; faulthandler's
        # text only appears when it managed to flush)
        if [ $rc -ne 139 ] && ! grep -q "Segmentation fault" /tmp/shard_out.$$; then
            break
        fi
        echo "RETRY(segv) $f" >> "$OUT"
    done
    line=$(grep -E "passed|failed|error" /tmp/shard_out.$$ | tail -1)
    echo "$f rc=$rc :: $line" >> "$OUT"
    if [ $rc -eq 0 ]; then pass=$((pass+1));
    else fail=$((fail+1)); failed_files="$failed_files $f"; fi
done
rm -f /tmp/shard_out.$$
echo "SHARDED DONE: $pass files ok, $fail files failed:$failed_files" >> "$OUT"
[ $fail -eq 0 ]
