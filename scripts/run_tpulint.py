#!/usr/bin/env python
"""CI entry point for tpulint: baseline-diff mode against the shipped tree.

Usage:
    python scripts/run_tpulint.py mmlspark_tpu            # CI gate
    python scripts/run_tpulint.py --format json mmlspark_tpu
    python scripts/run_tpulint.py --no-baseline mmlspark_tpu  # raw findings

Exits 0 when the tree is clean modulo the checked-in baseline
(tools/tpulint/baseline.json); exits 1 on any new finding at or above the
``--fail-on`` threshold (default: warning). Regenerate the baseline with
scripts/gen_tpulint_baseline.py after fixing or deliberately accepting
findings.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
os.chdir(REPO_ROOT)  # fingerprints are repo-relative; pin the root

from tools.tpulint.cli import main  # noqa: E402

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "tpulint",
                                "baseline.json")


def run(argv):
    argv = list(argv)
    if "--no-baseline" in argv:
        argv.remove("--no-baseline")
    elif "--baseline" not in argv and "--write-baseline" not in argv \
            and "--list-rules" not in argv \
            and os.path.exists(DEFAULT_BASELINE):
        argv += ["--baseline", DEFAULT_BASELINE]
    return main(argv)


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
