#!/bin/bash
# Packaging execution test: build the wheel, install it into a CLEAN venv,
# and run the quickstart + one doctest file AGAINST THE INSTALLED PACKAGE
# (not the repo checkout). This is the executable slice of the reference's
# packagePython/testPython discipline (project/CodegenPlugin.scala:55-67)
# that needs no pyspark/R in the image.
#
# Zero-egress rules: the venv reuses the image's site-packages for deps
# (--system-site-packages) and pip runs --no-index --no-deps — the wheel
# itself is the only thing installed, which is exactly what this test is
# about: does the PACKAGED artifact work, files and all.
set -euo pipefail
cd "$(dirname "$0")/.."
REPO="$PWD"
WORK="${PACKAGING_WORKDIR:-$(mktemp -d /tmp/pkgtest.XXXXXX)}"
echo "workdir: $WORK"

# 1. build the wheel (no build isolation: setuptools is baked in, no net)
rm -rf "$WORK/dist"
python -m pip wheel . --no-deps --no-build-isolation -w "$WORK/dist" -q
WHEEL=$(ls "$WORK"/dist/mmlspark_tpu-*.whl)
echo "wheel: $WHEEL"

# 2. clean venv. Deps (numpy/jax/...) come from the OUTER environment's
# site-packages via a .pth link — the image's python is itself a venv, so
# --system-site-packages would point past it at the bare base install.
python -m venv "$WORK/venv"
OUTER_SP=$(python -c "import sysconfig; print(sysconfig.get_paths()['purelib'])")
VENV_SP=$("$WORK/venv/bin/python" -c "import sysconfig; print(sysconfig.get_paths()['purelib'])")
echo "$OUTER_SP" > "$VENV_SP/outer-deps.pth"
"$WORK/venv/bin/pip" install --no-index --no-deps -q "$WHEEL"

# 3. quickstart from a scratch dir: the repo must NOT be importable
cp "$REPO/scripts/packaging_quickstart.py" "$WORK/quickstart.py"
cd "$WORK"
"$WORK/venv/bin/python" "$WORK/quickstart.py"

# 4. one doctest file executed against the installed package
DOCTEST_INSTALLED=1 "$WORK/venv/bin/python" \
    "$REPO/scripts/doctest_docs.py" "$REPO/docs/guide.md"

echo "PACKAGING OK"
