"""On-chip SPMD check: ONNXModel ``mesh_sharded`` mode vs plain mode.

Round-3 verdict item 8: the mesh-mode SPMD path had only ever executed on
the virtual 8-CPU mesh; running it on a 1-device mesh on the REAL chip
retires its compile risk (GSPMD partitioning + sharding annotations compile
for the TPU target even when the mesh is trivial). Multi-device correctness
stays pinned by the CPU-mesh tests; this records mesh-mode img/s ≈
non-mesh img/s on hardware. One JSON line.

Parity anchor: the reference's per-partition ORT session placement
(``deep-learning/.../onnx/ONNXModel.scala:293-303``); here placement is a
``jax.sharding`` annotation over a Mesh instead of a device id.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMALL = os.environ.get("BENCH_SCALE", "") == "small"


def main():
    if SMALL:
        from mmlspark_tpu.utils.device import force_cpu
        jax = force_cpu()
    else:
        import jax

    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.models.onnx_model import ONNXModel
    from mmlspark_tpu.models.zoo.resnet import ResNetConfig, \
        export_resnet_onnx
    from mmlspark_tpu.parallel.mesh import MeshContext

    batch = int(os.environ.get("BENCH_BATCH", "16" if SMALL else "256"))
    rng = np.random.default_rng(0)
    cfg = ResNetConfig([2, 2, 2, 2], num_classes=200)
    model_bytes = export_resnet_onnx(cfg, seed=0)

    X = rng.integers(0, 256, (batch * 2, 64, 64, 3), dtype=np.uint8)
    col = np.empty(len(X), dtype=object)
    for i in range(len(X)):
        col[i] = X[i]
    df = DataFrame({"image": col})

    def build(mesh_sharded):
        return ONNXModel(model_bytes,
                         feed_dict={"input": "image"},
                         fetch_dict={"logits": "logits"},
                         argmax_dict={"pred": "logits"},
                         transpose_dict={"input": [0, 3, 1, 2]},
                         mini_batch_size=batch,
                         compute_dtype="bfloat16",
                         mesh_sharded=mesh_sharded)

    def timed_ips(m, ctx):
        with ctx:
            m.transform(df.head(batch))        # compile + first transfer
            t0 = time.perf_counter()
            out = m.transform(df)
            # DataFrame.transform materializes host-side numpy — the
            # fetch IS the fence
            assert len(out) == len(X)
            return round(len(X) / (time.perf_counter() - t0), 2)

    import contextlib

    # interleave the two modes and keep per-mode bests: behind the tunnel
    # h2d bandwidth swings several-fold over minutes (BASELINE.md), so two
    # back-to-back single runs measure the LINK drift, not the mesh-mode
    # overhead (r4 campaign recorded 0.61x that way). Models build once;
    # each round re-times the same transforms.
    rounds = int(os.environ.get("BENCH_MESH_ROUNDS", "3"))
    m_plain, m_mesh = build(False), build(True)
    plain_runs, mesh_runs = [], []
    for _ in range(rounds):
        plain_runs.append(timed_ips(m_plain, contextlib.nullcontext()))
        mesh_runs.append(timed_ips(m_mesh, MeshContext({"data": -1})))
    plain_ips, mesh_ips = max(plain_runs), max(mesh_runs)
    # the headline ratio uses per-mode MEDIANS: a single lucky link
    # window on one mode's best makes a best-vs-best ratio read as mode
    # overhead (the r5 campaign row's 0.653 was exactly that — medians of
    # the same runs said 0.96); best-of values stay for continuity.
    # statistics.median averages the middle pair — an upper-middle pick
    # would degenerate back to best-of at BENCH_MESH_ROUNDS=2
    from statistics import median
    ratio_med = (round(median(mesh_runs) / median(plain_runs), 3)
                 if median(plain_runs) else None)

    d = jax.devices()[0]
    print(json.dumps({
        "metric": "onnx_mesh_spmd_images_per_sec",
        "plain_ips": plain_ips,
        "mesh_ips": mesh_ips,
        "ratio": ratio_med,
        "ratio_best_of": round(mesh_ips / plain_ips, 3)
        if plain_ips else None,
        "plain_runs": plain_runs, "mesh_runs": mesh_runs,
        "n_devices": len(jax.devices()),
        "platform": d.platform, "device": d.device_kind}), flush=True)


if __name__ == "__main__":
    main()
