#!/bin/bash
# Wait for a healthy chip, then run ONE command once. The single-stage
# sibling of chip_campaign_loop.sh, with the same claim discipline
# (BASELINE.md): one probe child at a time, nothing ever killed, a pause
# between attempts. Use when a specific bench leg needs a healthy window
# and a full campaign re-run would waste it.
#
# Usage: bash scripts/chip_stage_loop.sh <log> <max_attempts> cmd [args...]
set -u
LOG="${1:?log file}"; MAX="${2:?max attempts}"; shift 2
cd "$(dirname "$0")/.."
attempt=0
while [ "$attempt" -lt "$MAX" ]; do
    if pgrep -f 'import jax.*bench_probe_' > /dev/null 2>&1; then
        echo "--- prior probe child still pending $(date -u) ---" >> "$LOG"
        sleep "${CHIP_RETRY_SLEEP:-120}"
        continue
    fi
    attempt=$((attempt + 1))
    probe=$(python scripts/probe_chip.py 2>> "$LOG") || probe=error
    echo "--- attempt $attempt/$MAX probe=$probe $(date -u) ---" >> "$LOG"
    if [ "$probe" = "tpu" ]; then
        echo "--- stage start: $* $(date -u) ---" >> "$LOG"
        "$@"
        rc=$?
        echo "--- stage done rc=$rc $(date -u) ---" >> "$LOG"
        exit "$rc"
    fi
    sleep "${CHIP_RETRY_SLEEP:-120}"
done
echo "--- gave up after $MAX attempts $(date -u) ---" >> "$LOG"
exit 3
