"""Quickstart executed against the INSTALLED wheel (scripts/test_packaging.sh).

Asserts the import resolves from site-packages (not a repo checkout), then
runs the canonical first-user pipeline: DataFrame → estimator fit →
transform → save → reload → identical predictions. Mirrors the reference's
generated PyTestFuzzing smoke surface (core/src/test/.../codegen/TestGen.scala)
in the one slice executable without pyspark.
"""

import os
import sys

from mmlspark_tpu.utils.device import force_cpu  # noqa: E402

force_cpu()

import numpy as np  # noqa: E402

import mmlspark_tpu  # noqa: E402

pkg_dir = os.path.dirname(os.path.abspath(mmlspark_tpu.__file__))
if "site-packages" not in pkg_dir:
    sys.exit(f"FAIL: mmlspark_tpu imported from {pkg_dir}, "
             "not the installed wheel")

from mmlspark_tpu.core import DataFrame                     # noqa: E402
from mmlspark_tpu.core.pipeline import PipelineStage        # noqa: E402
from mmlspark_tpu.models.gbdt import LightGBMClassifier     # noqa: E402

rng = np.random.default_rng(0)
n = 1200
X = rng.normal(0, 1, (n, 6)).astype(np.float32)
y = (X[:, 0] - 0.7 * X[:, 1] + 0.2 * rng.normal(size=n) > 0).astype(float)
col = np.empty(n, dtype=object)
col[:] = list(X)
df = DataFrame({"features": col, "label": y})

model = LightGBMClassifier(num_iterations=15, num_leaves=15).fit(df)
pred = np.asarray(list(model.transform(df)["prediction"]), dtype=float)
acc = float((pred == y).mean())
assert acc > 0.85, f"quickstart accuracy {acc}"

model.save("model_out")
pred2 = np.asarray(list(PipelineStage.load("model_out").transform(df)
                        ["prediction"]), dtype=float)
assert np.array_equal(pred, pred2), "reloaded model diverges"

# the native fast path must be usable (or cleanly absent) from the wheel
from mmlspark_tpu import native                             # noqa: E402

print(f"quickstart OK from {pkg_dir} (acc={acc:.3f}, "
      f"native={native.available()})")
