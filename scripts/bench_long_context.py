"""Long-context attention bench: ring / Ulysses sequence parallelism.

The reference never scales sequence length (SURVEY §5 — it scales rows);
this framework's sequence-parallel kernels (`parallel/ring.py`) are the
beyond-parity capability. This bench measures attention wall-clock and the
max sequence length that fits, full (single-device) vs ring/Ulysses over a
sequence-sharded mesh. Prints one JSON line per config.

CPU smoke: BENCH_SCALE=small runs tiny shapes on the virtual 8-device mesh.
On hardware, the mesh axis rides ICI.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMALL = os.environ.get("BENCH_SCALE", "") == "small"


def main():
    if SMALL:
        from mmlspark_tpu.utils.device import force_cpu
        jax = force_cpu(virtual_devices=8)
    else:
        import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mmlspark_tpu.ops.flash_attention import flash_attention
    from mmlspark_tpu.parallel.ring import (local_attention,
                                            plan_attention_impl,
                                            wrap_ring_attention)

    sp = 4 if SMALL else min(4, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    B, H, D = (1, 4, 16) if SMALL else (1, 12, 64)
    seqs = [256, 512] if SMALL else [4096, 16384, 65536]
    # remote compiles at 64K take minutes each; let a driver scope a run
    if os.environ.get("BENCH_SEQS"):
        seqs = [int(s) for s in os.environ["BENCH_SEQS"].split(",")]
    impls = tuple(s.strip() for s in os.environ.get(
        "BENCH_IMPLS", "full,flash,ring,ring_flash,ulysses").split(",")
        if s.strip())
    unknown = set(impls) - {"full", "flash", "ring", "ring_flash", "ulysses"}
    if unknown:
        # an unvalidated name would silently fall through to the ulysses
        # branch and publish a mislabeled timing
        raise SystemExit(f"unknown BENCH_IMPLS {sorted(unknown)}")

    # HBM budget for the feasibility gate (0 disables). The O(S²) legs at
    # 16k-bwd/64k fail at COMPILE time on one chip — the r4/r5 campaigns
    # recorded those as opaque remote-compile HTTP 500s and re-paid the
    # doomed multi-minute compile every window. The planner (calibrated
    # against exactly those campaign outcomes) now classifies them up
    # front; the row says WHY and what would fit instead.
    if os.environ.get("BENCH_HBM_BYTES"):
        hbm = float(os.environ["BENCH_HBM_BYTES"])
    elif SMALL:
        hbm = 0.0
    else:
        try:  # the real per-device budget when the runtime exposes it
            hbm = float(jax.devices()[0].memory_stats()["bytes_limit"])
        except Exception:
            hbm = 16e9  # TPU v5e; axon tunnels often hide memory_stats

    def infeasible_verdict(impl, direction, S, sp):
        # hbm == 0 in SMALL mode unless BENCH_HBM_BYTES is set explicitly
        # (the explicit knob always wins — it is how the gate is driven
        # and CPU-tested without a chip)
        if not hbm:
            return None
        plan = plan_attention_impl(impl, direction, B, H, S,
                                   sp=sp, hbm_bytes=hbm)
        if plan["feasible"]:
            return None
        gb = plan["transient_bytes"] / 1e9
        fix = (f"feasible at sp>={plan['min_sp']}" if plan["min_sp"]
               else "no sp helps")
        return (f"infeasible: ~{gb:.3g} GB f32 scores > {hbm/1e9:.3g} GB "
                f"HBM at sp={sp} ({fix}; O(S) impls: flash/ring_flash)")

    def impl_fn_args(impl, q, k, v):
        """(fn, device args) per impl — ONE dispatch shared by the forward
        and backward timing loops so specs cannot drift between them."""
        if impl == "full":
            return local_attention, [jax.device_put(x) for x in (q, k, v)]
        if impl == "flash":
            # single-device Pallas streaming-softmax kernel: the O(S)
            # alternative when the score matrix no longer fits
            return (lambda a, b, c: flash_attention(a, b, c),
                    [jax.device_put(x) for x in (q, k, v)])
        sh = NamedSharding(mesh, P(None, None, "sp", None))
        return (wrap_ring_attention(mesh, "sp", impl=impl),
                [jax.device_put(x, sh) for x in (q, k, v)])

    rng = np.random.default_rng(0)
    for S in seqs:
        q = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
        k = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
        v = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
        results = {}
        full_out = None
        for impl in impls:
            verdict = infeasible_verdict(impl, "fwd", S,
                                         int(mesh.shape["sp"]))
            if verdict:
                results[impl] = verdict
                continue
            try:
                base_fn, args = impl_fn_args(impl, q, k, v)
                # tpulint: disable=TPU002 — one compile per (impl, S)
                # config is the benchmark design; shapes change every
                # iteration so no cache could be reused anyway
                fn = jax.jit(base_fn)
                # a fetched scalar is the only reliable completion fence
                # behind the axon tunnel (block_until_ready can return
                # before the device finishes, reporting ~0 ms for 100-ms
                # kernels); fetching only the LAST of the dispatched calls
                # fences all of them — device programs run in order — so a
                # single ~70 ms round-trip amortizes over the repeats
                reps = 5
                # bind the output ONCE — two _f(*a) calls inside one jit
                # would run attention twice per rep unless XLA CSE merges
                # the inlined subgraphs, inflating ms/step up to 2x
                # tpulint: disable=TPU002 — compiled once per config, then
                # reused for all reps inside this iteration
                timed = jax.jit(
                    lambda *a, _f=fn: (lambda o: (
                        jnp.sum(o.astype(jnp.float32)), o))(_f(*a)))
                _, out = timed(*args)   # the one compile
                float(_)
                t0 = time.perf_counter()
                rs = [timed(*args)[0] for _ in range(reps)]
                float(rs[-1])
                results[impl] = round(
                    (time.perf_counter() - t0) / reps * 1e3, 2)
                if impl == "full":
                    full_out = np.asarray(out)
                elif full_out is not None:
                    # accuracy vs the already-computed full output — when
                    # full OOMs (the headline case: ring fits, full cannot)
                    # the sequence-parallel timings must survive
                    np.testing.assert_allclose(np.asarray(out), full_out,
                                               rtol=2e-3, atol=2e-3)
            except Exception as e:
                msg = (str(e).splitlines() or [repr(e)])[0][:80]
                results[impl] = f"error: {msg}"
        print(json.dumps({"metric": "long_context_attention_ms",
                          "seq_len": S, "heads": H, "head_dim": D,
                          "sp": int(mesh.shape["sp"]), **results,
                          # amortized-fence design: one window, mean of
                          # reps (per-rep fences would add ~RTT each)
                          "reps": 5, "timing": "mean-of-reps-single-fence",
                          "platform": jax.default_backend()}), flush=True)

        # --- backward: the flash bwd kernels vs XLA-differentiated dense.
        # (round-3 verdict: the bwd kernels had only ever run in interpret
        # mode; this times them on whatever backend is live.)
        if os.environ.get("BENCH_GRADS", "1") != "1":
            continue
        bwd, full_grads = {}, None
        for impl in impls:
            verdict = infeasible_verdict(impl, "bwd", S,
                                         int(mesh.shape["sp"]))
            if verdict:
                bwd[impl] = verdict
                continue
            try:
                # the sequence-parallel impls train too (ring-level VJP)
                base, args = impl_fn_args(impl, q, k, v)

                def loss(a, b, c, _f=base):
                    return jnp.sum(_f(a, b, c).astype(jnp.float32))

                # tpulint: disable=TPU002 — per-config compile by design
                gfn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                gs = gfn(*args)                      # the one compile
                float(jnp.sum(gs[0][0, 0, 0, :2].astype(jnp.float32)))
                reps = 3
                t0 = time.perf_counter()
                for _ in range(reps):
                    gs = gfn(*args)
                # fetched scalar depending on the LAST dispatch fences all
                float(jnp.sum(gs[2][0, 0, -1, :2].astype(jnp.float32)))
                bwd[impl] = round(
                    (time.perf_counter() - t0) / reps * 1e3, 2)
                if impl == "full":
                    full_grads = [np.asarray(g) for g in gs]
                elif full_grads is not None:
                    # accuracy is a SEPARATE verdict: a tolerance miss must
                    # not clobber a valid hardware timing with an "error:"
                    # string indistinguishable from a crash
                    try:
                        for g, fg in zip(gs, full_grads):
                            np.testing.assert_allclose(
                                np.asarray(g), fg, rtol=5e-3, atol=5e-3)
                        bwd[f"{impl}_grad_match"] = True
                    except AssertionError as e:
                        bwd[f"{impl}_grad_match"] = False
                        bwd[f"{impl}_grad_diff"] = \
                            (str(e).splitlines() or [""])[0][:80]
            except Exception as e:
                msg = (str(e).splitlines() or [repr(e)])[0][:80]
                bwd[impl] = f"error: {msg}"
        if bwd:
            print(json.dumps({"metric": "long_context_attention_bwd_ms",
                              "seq_len": S, "heads": H, "head_dim": D,
                              **bwd,
                              "platform": jax.default_backend()}),
                  flush=True)


if __name__ == "__main__":
    main()
