#!/bin/bash
# Retry bench_all_tpu.sh until one full campaign lands on a healthy chip.
#
# The v5e claim behind this session's tunnel wedges for stretches of hours
# and frees without notice; the only workable strategy (BASELINE.md) is a
# patient serialized loop: one probe-and-campaign attempt at a time, no
# process ever killed, a pause between attempts. bench_all_tpu.sh exits 3
# when its headline bench degraded to CPU (chip still wedged) — only then
# do we sleep and retry; exit 0 means the campaign ran on chip and we stop.
#
# Usage: bash scripts/chip_campaign_loop.sh [results.jsonl] [max_attempts]
set -u
OUT="${1:-/tmp/tpu_campaign.jsonl}"
MAX="${2:-120}"       # real probe attempts; at ~2+2 min each ≈ 8 h patience
cd "$(dirname "$0")/.."
attempt=0
while [ "$attempt" -lt "$MAX" ]; do
    # one claimant at a time (BASELINE.md discipline): while an abandoned
    # probe child from an earlier attempt is still stuck inside backend
    # init, spawning another can neither succeed nor be killed safely —
    # wait for it to die on its own. Waiting does NOT consume an attempt.
    # The pattern matches the probe child's own cmdline (its -c code plus
    # the result path), not merely any process mentioning the temp dir
    # (a tail/less on a probe file must not stall the loop).
    if pgrep -f 'import jax.*bench_probe_' > /dev/null 2>&1; then
        echo "--- prior probe child still pending $(date -u) ---" >> "$OUT.log"
        sleep "${CHIP_RETRY_SLEEP:-120}"
        continue
    fi
    attempt=$((attempt + 1))
    # cheap gate first: one non-wedging probe child (bench.py's machinery —
    # atomic result file, never killed). A wedged claim costs ~2 min here
    # vs ~10 min of degraded bench.py, so the loop samples the chip ~3x
    # more often and a short healthy window is less likely to be missed.
    # Window chain (CHIP_PROBE_WINDOW → BENCH_PROBE_WINDOW → 120) and
    # diagnostics live in the shared scripts/probe_chip.py.
    probe=$(python scripts/probe_chip.py 2>> "$OUT.log") || probe=error
    echo "--- attempt $attempt/$MAX probe=$probe $(date -u) ---" >> "$OUT.log"
    if [ "$probe" = "tpu" ]; then
        bash scripts/bench_all_tpu.sh "$OUT"
        rc=$?
        if [ "$rc" -ne 3 ]; then
            echo "--- campaign finished rc=$rc attempt $attempt $(date -u) ---" >> "$OUT.log"
            exit "$rc"
        fi
    fi
    sleep "${CHIP_RETRY_SLEEP:-120}"
done
echo "--- campaign gave up after $MAX degraded attempts $(date -u) ---" >> "$OUT.log"
exit 3
