#!/bin/bash
# Retry bench_all_tpu.sh until one full campaign lands on a healthy chip.
#
# The v5e claim behind this session's tunnel wedges for stretches of hours
# and frees without notice; the only workable strategy (BASELINE.md) is a
# patient serialized loop: one probe-and-campaign attempt at a time, no
# process ever killed, a pause between attempts. bench_all_tpu.sh exits 3
# when its headline bench degraded to CPU (chip still wedged) — only then
# do we sleep and retry; exit 0 means the campaign ran on chip and we stop.
#
# Usage: bash scripts/chip_campaign_loop.sh [results.jsonl] [max_attempts]
set -u
OUT="${1:-/tmp/tpu_campaign.jsonl}"
MAX="${2:-40}"
cd "$(dirname "$0")/.."
for i in $(seq 1 "$MAX"); do
    echo "--- campaign attempt $i/$MAX $(date -u) ---" >> "$OUT.log"
    bash scripts/bench_all_tpu.sh "$OUT"
    rc=$?
    if [ "$rc" -ne 3 ]; then
        echo "--- campaign finished rc=$rc attempt $i $(date -u) ---" >> "$OUT.log"
        exit "$rc"
    fi
    sleep "${CHIP_RETRY_SLEEP:-240}"
done
echo "--- campaign gave up after $MAX degraded attempts $(date -u) ---" >> "$OUT.log"
exit 3
