"""On-chip microbench: Pallas MXU histogram vs the XLA segment_sum fallback.

The GBDT hot loop's histogram build is the TPU answer to LightGBM's C++
scatter-add (reached via ``LGBM_BoosterUpdateOneIter``,
``lightgbm/.../booster/LightGBMBooster.scala:351-361``). Prints one JSON
line per config with both builders' ms/level and the speedup, e.g. for
BASELINE.md. Run on the real chip: ``python scripts/bench_pallas_hist.py``.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def time_fn(fn, xb, node, g, h, w, **kw):
    """Dependency-chained timing robust to the tunnel's async quirks.

    The remote runtime's completion signals are unreliable for
    block_until_ready (fast programs report ~0ms), and per-call sync costs
    a ~70ms round-trip. So: dispatch L builder calls where call i+1's
    gradients data-depend on call i's histogram (no elision, strictly
    sequential on device), then force ONE scalar fetch that depends on the
    last call — the fetch cannot complete before all L executions have.
    """
    import jax
    import jax.numpy as jnp

    bump = jax.jit(lambda g, hist: g + hist[0, 0, 0, 0] * 1e-30)
    tail = jax.jit(lambda hist: jnp.sum(hist[0, 0, :2, 0]))

    def chain(length):
        gc = g
        hist = None
        for _ in range(length):
            hist = fn(xb, node, gc, h, w, **kw)
            gc = bump(gc, hist)
        return float(tail(hist))

    L = 6
    chain(1)  # compile everything
    t0 = time.perf_counter()
    chain(L)
    total = time.perf_counter() - t0
    return max((total - _rtt_baseline()) / L, 1e-9)


_RTT = [None]


def _rtt_baseline():
    """Dispatch+fetch cost of a trivial program — the tunnel constant to
    subtract from loop timings."""
    if _RTT[0] is None:
        import jax
        import jax.numpy as jnp
        f = jax.jit(lambda x: x + 1.0)
        float(f(jnp.float32(0.0)))
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(f(jnp.float32(1.0)))
            ts.append(time.perf_counter() - t0)
        _RTT[0] = min(ts)
    return _RTT[0]


def segment_sum_hist(xb, node_rel, g, h, w, n_nodes, n_bins):
    import jax
    import jax.numpy as jnp

    data = jnp.stack([g, h, w], axis=-1)

    def per_feature(bins_col):
        seg = node_rel * n_bins + bins_col.astype(jnp.int32)
        return jax.ops.segment_sum(data, seg, num_segments=n_nodes * n_bins)

    hist = jax.vmap(per_feature, in_axes=1)(xb)
    return jnp.transpose(hist.reshape(xb.shape[1], n_nodes, n_bins, 3),
                         (1, 0, 2, 3))


def main():
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.ops.pallas_kernels import level_histogram_pallas

    from mmlspark_tpu.utils.device import is_tpu
    backend = jax.default_backend()
    on_tpu = is_tpu()
    seg_jit = jax.jit(segment_sum_hist,
                      static_argnames=("n_nodes", "n_bins"))

    rng = np.random.default_rng(0)
    results = []
    # default: a full level sweep (levels 0-6 = 1..64 nodes) at 1M rows plus
    # the OOM-class 4M configs; BENCH_ROWS / BENCH_NODES scope a run so it
    # never needs to be killed mid-flight (the chip claim wedges on SIGKILL)
    rows = [int(r) for r in os.environ.get(
        "BENCH_ROWS", "1000000,4000000").split(",")]
    nodes_for = {1_000_000: [1, 2, 4, 8, 16, 32, 64], 4_000_000: [8, 32]}
    if os.environ.get("BENCH_NODES"):
        nd = [int(x) for x in os.environ["BENCH_NODES"].split(",")]
        nodes_for = {r: nd for r in rows}
    configs = [(n, 28, nn, 255) for n in rows
               for nn in nodes_for.get(n, [8, 32])]
    for n, F, n_nodes, n_bins in configs:
        xb = jnp.asarray(rng.integers(0, n_bins, (n, F), dtype=np.int32))
        node = jnp.asarray(rng.integers(0, n_nodes, n, dtype=np.int32))
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        h = jnp.asarray(np.abs(rng.normal(size=n)).astype(np.float32))
        w = jnp.ones(n, dtype=jnp.float32)

        rec = {"metric": "gbdt_level_histogram_ms",
               "n": n, "features": F, "nodes": n_nodes, "bins": n_bins,
               # per-op cost from a dependency-chained mean inside ONE
               # window (per-rep fences would cost ~RTT each); a window
               # artifact shows up as disagreement with the neighboring
               # rows of the same sweep
               "timing": "dependency-chain-mean",
               "platform": backend}
        try:
            t_pal = time_fn(level_histogram_pallas, xb, node, g, h, w,
                            n_nodes=n_nodes, n_bins=n_bins,
                            interpret=not on_tpu)
            rec["pallas_ms"] = round(t_pal * 1e3, 2)
        except Exception as e:
            rec["pallas_error"] = str(e).splitlines()[0][:120]
            t_pal = None
        try:
            t_seg = time_fn(seg_jit, xb, node, g, h, w,
                            n_nodes=n_nodes, n_bins=n_bins)
            rec["segment_sum_ms"] = round(t_seg * 1e3, 2)
        except Exception as e:
            # the vmapped segment_sum materializes an (F, n, 3) temp and can
            # blow HBM at HIGGS scale — that is the kernel's reason to exist
            rec["segment_sum_error"] = str(e).splitlines()[0][:120]
            t_seg = None
        if t_pal and t_seg:
            rec["speedup"] = round(t_seg / t_pal, 2)
            a = np.asarray(seg_jit(xb, node, g, h, w,
                                   n_nodes=n_nodes, n_bins=n_bins))
            b = np.asarray(level_histogram_pallas(
                xb, node, g, h, w, n_nodes=n_nodes, n_bins=n_bins,
                interpret=not on_tpu))
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-3)
        results.append(rec)
        print(json.dumps(rec), flush=True)
    return results


if __name__ == "__main__":
    sys.exit(0 if main() else 1)
