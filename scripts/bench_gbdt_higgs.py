"""HIGGS-scale GBDT training benchmark (BASELINE.json configs[1]).

The reference's north-star training config is distributed LightGBM on
HIGGS-11M (28 features, binary). With zero egress we generate a synthetic
HIGGS-shaped matrix (11M x 28 float32, mixed gaussian signal/background);
for sec/iter timing the data distribution is irrelevant — the cost is
histogram building + split finding over n x F x bins.

Prints one JSON line per size with bin time and sec/iter.

Usage: python scripts/bench_gbdt_higgs.py [sizes...]  (default 1e6 2e6 4e6)
Env: HIGGS_ITERS (default 10), HIGGS_LEAVES (31), HIGGS_BIN (255);
HIGGS_SKLEARN=1 additionally times sklearn HistGradientBoosting (a
LightGBM-class CPU implementation) on the identical matrix — the external
wall-clock yardstick next to the quality yardstick the test suite pins.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_higgs_like(n: int, f: int = 28, seed: int = 0) -> tuple:
    rng = np.random.default_rng(seed)
    y = (rng.random(n) < 0.53).astype(np.float64)  # HIGGS class balance
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    # signal rows get a correlated shift so trees have real structure to find
    shift = (0.3 * rng.normal(1, 0.2, f)).astype(np.float32)
    X[y == 1] += shift
    return X, y


def main():
    sizes = [int(float(s)) for s in sys.argv[1:]] or [1_000_000, 2_000_000,
                                                      4_000_000]
    iters = int(os.environ.get("HIGGS_ITERS", "10"))
    leaves = int(os.environ.get("HIGGS_LEAVES", "31"))
    max_bin = int(os.environ.get("HIGGS_BIN", "255"))
    quant = os.environ.get("HIGGS_QUANT", "0") == "1"

    import importlib

    import jax
    gtrain = importlib.import_module("mmlspark_tpu.models.gbdt.train")

    platform = jax.devices()[0].platform
    for n in sizes:
        X, y = make_higgs_like(n)
        params = {"objective": "binary", "num_iterations": iters,
                  "num_leaves": leaves, "max_bin": max_bin,
                  "learning_rate": 0.1, "min_data_in_leaf": 20,
                  "use_quantized_grad": quant}
        # warmup run compiles the tree builder for this shape
        t0 = time.perf_counter()
        gtrain.train({**params, "num_iterations": 1}, X, y)
        warm = time.perf_counter() - t0
        best_of = int(os.environ.get("HIGGS_BEST_OF", "2"))
        secs = []
        for _ in range(max(1, best_of)):
            t0 = time.perf_counter()
            booster = gtrain.train(params, X, y)
            secs.append((time.perf_counter() - t0) / iters)
        auc_in = _auc(y, booster.predict(X))
        print(json.dumps({
            "metric": "gbdt_higgs_sec_per_iter",
            "n_rows": n, "n_features": X.shape[1],
            "value": round(min(secs), 4), "unit": "sec/iter",
            "best_of": len(secs),
            "pass_spread": round((max(secs) - min(secs)) / max(secs), 3),
            "warmup_sec": round(warm, 2),
            "train_auc": round(float(auc_in), 4),
            "quantized": quant,
            "platform": platform,
        }), flush=True)
        if os.environ.get("HIGGS_SKLEARN", "0") == "1":
            from sklearn.ensemble import HistGradientBoostingClassifier
            clf = HistGradientBoostingClassifier(
                max_iter=iters, max_leaf_nodes=leaves,
                max_bins=min(max_bin, 255),     # sklearn's hard cap
                learning_rate=0.1, early_stopping=False,
                min_samples_leaf=20)
            t0 = time.perf_counter()
            clf.fit(X, y)
            sk_total = time.perf_counter() - t0
            sk_auc = _auc(y, clf.predict_proba(X)[:, 1])
            print(json.dumps({
                "metric": "gbdt_higgs_sklearn_hgb_sec_per_iter",
                "n_rows": n, "value": round(sk_total / iters, 4),
                "unit": "sec/iter", "train_auc": round(float(sk_auc), 4),
                "platform": "cpu"}), flush=True)
            del clf     # the binned copy must not survive into the next,
            #             larger size's allocation
        del X, y, booster


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p)); ranks[order] = np.arange(1, len(p) + 1)
    pos = y == 1
    n1, n0 = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


if __name__ == "__main__":
    main()
