"""Open-loop, rate-controlled HTTP load driver — run as its OWN process.

Round-3 lesson (BASELINE.md): thread-burst clients co-located in the server
process measure the client as much as the server. This driver (a) lives in a
separate process so the server's GIL is not shared, and (b) is open-loop:
each connection sends on a fixed schedule (target_rate/connections per
second) instead of as-fast-as-possible, the standard way to measure latency
at a controlled utilization (the coordinated-omission-aware shape). When the
client cannot keep its own schedule it SAYS so (``client_saturated``) rather
than silently under-reporting the server.

Usage:
    python serving_client.py URL TARGET_RPS DURATION_S CONNECTIONS < body.json

Prints one JSON line:
    {"target_rps": ..., "achieved_rps": ..., "p50_ms": ..., "p99_ms": ...,
     "errors": N, "late_frac": ..., "client_saturated": bool}
"""

import http.client
import json
import sys
import threading
import time
from urllib.parse import urlparse


def run(url: str, target_rps: float, duration_s: float, connections: int,
        body: bytes) -> dict:
    u = urlparse(url)
    interval = connections / target_rps       # per-connection send period
    lock = threading.Lock()
    all_lat, totals = [], {"sent": 0, "errors": 0, "late": 0}
    start = time.perf_counter() + 0.05        # common start line
    stop_at = start + duration_s

    def worker(idx: int):
        conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
        lats, sent, errors, late = [], 0, 0, 0
        # stagger connections across one period so sends interleave evenly
        next_t = start + (idx / connections) * interval
        while True:
            now = time.perf_counter()
            if now >= stop_at:
                break
            if now < next_t:
                time.sleep(next_t - now)
            elif now - next_t > interval:
                late += 1                     # fell ≥1 full period behind
            t0 = time.perf_counter()
            try:
                conn.request("POST", u.path or "/", body,
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                r.read()
                if r.status >= 400:
                    # a fast 503 is a server failure, not a clean sample —
                    # counting it as success would let an overloaded server
                    # report a spotless curve
                    errors += 1
                else:
                    lats.append((time.perf_counter() - t0) * 1e3)
                    sent += 1
            except Exception:
                errors += 1
                conn.close()
                conn = http.client.HTTPConnection(u.hostname, u.port,
                                                  timeout=10)
            next_t += interval
        conn.close()
        with lock:
            all_lat.extend(lats)
            totals["sent"] += sent
            totals["errors"] += errors
            totals["late"] += late

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(connections)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    elapsed = time.perf_counter() - start
    achieved = totals["sent"] / elapsed if elapsed > 0 else 0.0
    late_frac = totals["late"] / max(totals["sent"] + totals["late"], 1)
    out = {
        "target_rps": target_rps,
        "achieved_rps": round(achieved, 1),
        "errors": totals["errors"],
        "late_frac": round(late_frac, 4),
        # the client admits it could not hold the schedule: numbers past
        # this point measure the load generator, not the server
        "client_saturated": bool(achieved < 0.95 * target_rps
                                 or late_frac > 0.05),
    }
    if all_lat:
        import statistics
        s = sorted(all_lat)
        out["p50_ms"] = round(s[len(s) // 2], 3)
        out["p99_ms"] = round(s[min(len(s) - 1, int(len(s) * 0.99))], 3)
        out["mean_ms"] = round(statistics.fmean(s), 3)
    return out


if __name__ == "__main__":
    url, rps, dur, conns = (sys.argv[1], float(sys.argv[2]),
                            float(sys.argv[3]), int(sys.argv[4]))
    body = sys.stdin.buffer.read() or b"{}"
    print(json.dumps(run(url, rps, dur, conns, body)))
