"""Run every ```python code block in docs/*.md and README.md.

Parity: the reference tests its website code blocks with
``website/doctest.py`` (wired via ``build.sbt:337-344``) so documentation
cannot rot. Blocks run in one namespace per file, in order; a block marked
with ``<!-- no-test -->`` on the preceding line is skipped.
"""

import os
import re
import sys
import traceback

if os.environ.get("DOCTEST_INSTALLED", "0") != "1":
    sys.path.insert(0,
                    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
else:
    # packaging test (scripts/test_packaging.sh): blocks must import the
    # INSTALLED wheel, so the repo checkout stays off sys.path
    import importlib.util
    spec = importlib.util.find_spec("mmlspark_tpu")
    if spec is None or "site-packages" not in (spec.origin or ""):
        sys.exit("DOCTEST_INSTALLED=1 but mmlspark_tpu does not resolve "
                 f"to an installed wheel (found {spec and spec.origin})")

# docs examples run on CPU: deterministic, fast, no TPU claim needed
from mmlspark_tpu.utils.device import force_cpu  # noqa: E402

force_cpu()

BLOCK_RE = re.compile(r"(<!--\s*no-test\s*-->\s*\n)?```python\n(.*?)```",
                      re.DOTALL)


def extract_blocks(text):
    for m in BLOCK_RE.finditer(text):
        yield m.group(1) is not None, m.group(2)


def run_file(path: str):
    """Returns (blocks_run, failures)."""
    with open(path) as f:
        text = f.read()
    ns = {"__name__": f"doctest:{os.path.basename(path)}"}
    ran = failures = 0
    for i, (skip, code) in enumerate(extract_blocks(text)):
        if skip:
            continue
        ran += 1
        try:
            exec(compile(code, f"{path}:block{i}", "exec"), ns)
        except Exception:
            failures += 1
            print(f"FAIL {path} block {i}:")
            traceback.print_exc()
    return ran, failures


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if len(sys.argv) > 1:
        targets = [os.path.abspath(a) for a in sys.argv[1:]]
    else:
        targets = [os.path.join(repo, "README.md")]
        docs = os.path.join(repo, "docs")
        for root, _dirs, files in os.walk(docs):
            for f in sorted(files):
                if f.endswith(".md"):
                    targets.append(os.path.join(root, f))
    total, failures = 0, 0
    for path in targets:
        if not os.path.exists(path):
            continue
        n, f = run_file(path)
        total += n
        failures += f
    print(f"doctest_docs: {total - failures}/{total} blocks passed")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
