"""Micro-op timing for the GBDT iteration's device ops.

Times each candidate hot op standalone at HIGGS-like scale so the
per-iteration cost model (BASELINE.md, VERDICT r4 weak #1) is grounded in
measured per-op numbers instead of the summed-kernel guess:

  * level histogram (Pallas kernel) per level at several node counts
  * bottom-level leaf ``segment_sum`` (the scatter XLA lowers)
  * row routing via ``take_along_axis`` vs one-hot multiply-sum
  * objective grad/hess
  * score update gather

Usage: python scripts/prof_gbdt_microops.py [n_rows]  (default 4e6)
Prints one JSON line per op: {"op": ..., "ms": ..., "best_of": N}.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(fn, *args, reps=5):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)          # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        # tpulint: disable=TPU001 — micro-benchmark: the per-rep fence IS
        # the measurement (min-of-reps wall time per op)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best * 1e3, reps


def main():
    n = int(float(sys.argv[1])) if len(sys.argv) > 1 else 4_000_000
    F, B = 28, 256
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models.gbdt.objectives import get_objective
    from mmlspark_tpu.ops.pallas_kernels import level_histogram_pallas

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.integers(1, B, (n, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray(rng.random(n).astype(np.float32))
    w = jnp.ones(n, jnp.float32)
    y = jnp.asarray((rng.random(n) < 0.5).astype(np.float32))
    scores = jnp.zeros(n, jnp.float32)
    jax.block_until_ready((xb, g, h, w, y))

    def emit(op, ms, reps, **kw):
        print(json.dumps({"op": op, "ms": round(ms, 2), "best_of": reps,
                          "n_rows": n, "platform": platform, **kw}),
              flush=True)

    # per-level histogram at the node counts a depth-5 tree visits
    for nodes in (1, 4, 16):
        node_rel = jnp.asarray(rng.integers(0, nodes, n, dtype=np.int32))
        ms, reps = timed(
            lambda nr=node_rel, nn=nodes: level_histogram_pallas(
                xb, nr, g, h, w, nn, B), reps=3)
        emit("pallas_hist", ms, reps, nodes=nodes)

    # bottom-level leaf stats: segment_sum over 32 leaves (current) ...
    node32 = jnp.asarray(rng.integers(0, 32, n, dtype=np.int32))

    @jax.jit
    def leaf_segsum(nr, g_, h_):
        data = jnp.stack([g_, h_], axis=-1)
        return jax.ops.segment_sum(data, nr, num_segments=32)

    ms, reps = timed(leaf_segsum, node32, g, h)
    emit("leaf_segment_sum", ms, reps)

    # ... vs a one-hot matmul formulation of the same reduction
    @jax.jit
    def leaf_onehot(nr, g_, h_):
        oh = jax.nn.one_hot(nr, 32, dtype=jnp.float32)     # (n, 32)
        return jnp.stack([g_ @ oh, h_ @ oh], axis=-1)

    ms, reps = timed(leaf_onehot, node32, g, h)
    emit("leaf_onehot_matmul", ms, reps)

    # row routing: per-row dynamic column gather (current) ...
    bf = jnp.asarray(rng.integers(0, F, 16, dtype=np.int32))
    node16 = jnp.asarray(rng.integers(0, 16, n, dtype=np.int32))

    @jax.jit
    def route_gather(nr, bf_):
        row_feat = jnp.clip(bf_[nr], 0, F - 1)
        return jnp.take_along_axis(
            xb, row_feat[:, None].astype(jnp.int32), axis=1)[:, 0] \
            .astype(jnp.int32)

    ms, reps = timed(route_gather, node16, bf)
    emit("route_take_along_axis", ms, reps)

    # ... vs one-hot multiply-sum over the 28 feature lanes
    @jax.jit
    def route_onehot(nr, bf_):
        row_feat = jnp.clip(bf_[nr], 0, F - 1)
        oh = jax.nn.one_hot(row_feat, F, dtype=jnp.float32)  # (n, F)
        return (xb.astype(jnp.float32) * oh).sum(axis=1).astype(jnp.int32)

    ms, reps = timed(route_onehot, node16, bf)
    emit("route_onehot_sum", ms, reps)

    # objective grad/hess (binary logloss)
    obj = get_objective("binary", num_class=1, alpha=0.9,
                        tweedie_variance_power=1.5)
    grad_fn = jax.jit(obj.grad_hess)
    ms, reps = timed(grad_fn, scores, y, w)
    emit("grad_hess", ms, reps)

    # score update: leaf-value gather + add
    leaf_val = jnp.asarray(rng.normal(size=32).astype(np.float32))

    @jax.jit
    def score_update(s, lv, nr):
        return s + jnp.take(lv, nr) * 0.1

    ms, reps = timed(score_update, scores, leaf_val, node32)
    emit("score_update", ms, reps)


if __name__ == "__main__":
    main()
