#!/bin/bash
# Run every TPU benchmark in sequence, appending JSON lines to
# ${1:-/tmp/tpu_bench_results.jsonl}. Intended for a healthy-chip window;
# each bench degrades rather than crashes if the chip goes away mid-run.
set -u
OUT="${1:-/tmp/tpu_bench_results.jsonl}"
cd "$(dirname "$0")/.."

run() {
    name="$1"; shift
    echo "=== $name $(date -u +%H:%M:%SZ) ===" >> "$OUT.log"
    # JSON lines to $OUT; human log (incl. stderr diagnostics) to $OUT.log
    timeout "${BENCH_TIMEOUT:-600}" "$@" > >(tee -a "$OUT.log" | grep '^{' >> "$OUT") 2>> "$OUT.log"
    echo "($name rc=$?)" >> "$OUT.log"
}

run headline  python bench.py
run pallas    python scripts/bench_pallas_hist.py
run configs   python scripts/bench_configs.py
run gbdt_1m   python scripts/bench_gbdt_higgs.py 1000000
run longctx   python scripts/bench_long_context.py
echo "ALL DONE $(date -u)" >> "$OUT"
