#!/bin/bash
# Run every TPU benchmark in sequence, appending JSON lines to
# ${1:-/tmp/tpu_bench_results.jsonl}. Intended for a healthy-chip window;
# each bench degrades rather than crashes if the chip goes away mid-run.
set -u
OUT="${1:-/tmp/tpu_bench_results.jsonl}"
cd "$(dirname "$0")/.."

run() {
    name="$1"; shift
    echo "=== $name $(date -u +%H:%M:%SZ) ===" >> "$OUT.log"
    # JSON lines to $OUT; human log (incl. stderr diagnostics) to $OUT.log.
    # A real pipeline (not process substitution) so bash waits for the
    # writers before the next run's output can interleave.
    timeout "${BENCH_TIMEOUT:-600}" "$@" 2>> "$OUT.log" \
        | tee -a "$OUT.log" | grep '^{' >> "$OUT"
    echo "($name rc=${PIPESTATUS[0]})" >> "$OUT.log"
}

run headline  python bench.py
run pallas    python scripts/bench_pallas_hist.py
run configs   python scripts/bench_configs.py
run gbdt_1m   python scripts/bench_gbdt_higgs.py 1000000
run longctx   python scripts/bench_long_context.py
run serving   python scripts/bench_serving.py
echo "ALL DONE $(date -u)" >> "$OUT"
