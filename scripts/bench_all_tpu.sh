#!/bin/bash
# Run every TPU benchmark in sequence, appending JSON lines to
# ${1:-/tmp/tpu_bench_results.jsonl}. Intended for a healthy-chip window;
# each bench degrades rather than crashes if the chip goes away mid-run.
#
# NO `timeout` wrappers: a killed TPU-holding process wedges the chip claim
# for hours (BASELINE.md postmortem). Runs are sized by env knobs instead —
# set them BEFORE invoking if a shorter window is needed:
#   BENCH_ROWS/BENCH_BATCH (headline), HIGGS_ITERS/HIGGS_SIZES (gbdt),
#   SPARSE_ROWS/SPARSE_ITERS (gbdt_efb),
#   BENCH_SEQS/BENCH_IMPLS/BENCH_GRADS (long context),
#   BENCH_SERVING_N/BENCH_SERVING_DURATION (serving).
# Order follows the round-4 verdict: headline first (the artifact of
# record), then HIGGS, flash fwd+bwd, Pallas histogram, mesh SPMD,
# serving-with-chip.
set -u
OUT="${1:-/tmp/tpu_bench_results.jsonl}"
cd "$(dirname "$0")/.."

# Persistent XLA compile cache across stages AND campaign retries: a leg
# that compiled once never waits on (or 500s in) the remote-compile
# service again. Timed regions are post-warmup so steady-state numbers
# are unaffected; compile-INCLUSIVE fields do change — GBDT warmup_s
# reflects what repeat jobs see (BASELINE.md: 98 s cold → 29 s cached),
# and bench.py's warm_ips last-resort fallback (reported only when every
# timed pass died) is faster on a retry than on a cold first attempt.
export MMLSPARK_TPU_COMPILE_CACHE="${MMLSPARK_TPU_COMPILE_CACHE:-/tmp/mmlspark_xla_cache}"

# $OUT is APPEND-ONLY across retries: a mid-campaign abort (exit 3) makes
# chip_campaign_loop.sh re-run the whole campaign in the next healthy
# window, so stages that already succeeded get a second JSON line —
# consumers read the last (or best tpu-labeled) record per metric.
GATED_ONCE=0
run() {
    name="$1"; shift
    # re-gate before every stage: the chip can wedge MID-campaign (it did
    # at 03:43 on 2026-07-31), and each wedged stage would hang ~25-50 min
    # inside backend init before dying. Between stages the claim is free,
    # so a cheap non-wedging probe (scripts/probe_chip.py — shared with
    # chip_campaign_loop.sh) is accurate; a failed gate aborts the
    # remaining stages and hands control back to the loop. The stage right
    # after the headline skips the gate — the headline's own three-
    # condition check just proved the chip.
    if [ "${CAMPAIGN_GATES:-1}" = "1" ] && [ "$name" != "headline" ]; then
        if [ "$GATED_ONCE" = "0" ]; then
            GATED_ONCE=1
        else
            gate=$(python scripts/probe_chip.py 2>> "$OUT.log") || gate=error
            if [ "$gate" != "tpu" ]; then
                echo "(gate before $name: probe=$gate — aborting campaign $(date -u +%H:%M:%SZ))" >> "$OUT.log"
                exit 3
            fi
        fi
    fi
    echo "=== $name $(date -u +%H:%M:%SZ) ===" >> "$OUT.log"
    # JSON lines to $OUT; human log (incl. stderr diagnostics) to $OUT.log.
    # A real pipeline (not process substitution) so bash waits for the
    # writers before the next run's output can interleave.
    "$@" 2>> "$OUT.log" | tee -a "$OUT.log" | grep '^{' >> "$OUT"
    echo "($name rc=${PIPESTATUS[0]} $(date -u +%H:%M:%SZ))" >> "$OUT.log"
}

pre_lines=$(wc -l < "$OUT" 2>/dev/null || echo 0)
run headline  python bench.py
# Gate the TPU-only stages on the headline's outcome: when the chip claim
# is wedged each of these would otherwise wait ~25-50 min inside backend
# init and then die — serially, for hours. A degraded headline means
# skip-and-let-the-caller-retry (chip_campaign_loop.sh), not grind.
# Three conditions: the headline actually APPENDED a line (a stale tpu
# record from a previous attempt must not pass), it labeled itself tpu,
# and it carried no midrun_error (a mid-run backend loss predicts the
# same death for every following stage).
post_lines=$(wc -l < "$OUT" 2>/dev/null || echo 0)
last=$(tail -1 "$OUT" 2>/dev/null)
if [ "$post_lines" -gt "$pre_lines" ] \
        && echo "$last" | grep -q '"platform": "tpu"' \
        && ! echo "$last" | grep -q 'midrun_error'; then
    # shellcheck disable=SC2086 — word-splitting of HIGGS_SIZES is intended
    run gbdt      python scripts/bench_gbdt_higgs.py ${HIGGS_SIZES:-1000000 4000000 11000000}
    # same group shape as the BASELINE.md CPU row (50 groups × 8) so the
    # TPU cell fills from a comparable problem, larger only in rows
    run gbdt_efb  python scripts/bench_gbdt_sparse.py ${SPARSE_ROWS:-1000000} 50 8
    run longctx   python scripts/bench_long_context.py
    run pallas    python scripts/bench_pallas_hist.py
    run mesh_spmd python scripts/bench_mesh_spmd.py
    run configs   python scripts/bench_configs.py
    run decode    python scripts/bench_decode.py
    run serving_tpu env BENCH_SERVING_TPU=1 python scripts/bench_serving.py
    echo "ALL DONE $(date -u)" >> "$OUT"
else
    echo "CHIP DEGRADED $(date -u) — TPU-only stages skipped" >> "$OUT.log"
    exit 3
fi
