"""One non-wedging chip probe, shared by every gate.

Prints the platform string ("tpu" / "cpu" / "none") on stdout and the
probe diagnostics (hang vs crash reason) on stderr, so callers can log
both. Window resolution: CHIP_PROBE_WINDOW → BENCH_PROBE_WINDOW → 120 s
— the one chain every gate honors (divergent hand-rolled copies of this
snippet previously ignored the documented knob).

Exit code is always 0; the caller branches on stdout (a crash in HERE
must read as an environment error, not as a wedged chip).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    try:
        import bench
        window = float(os.environ.get(
            "CHIP_PROBE_WINDOW",
            os.environ.get("BENCH_PROBE_WINDOW", "120")))
        platform, kind, info = bench._probe_default_backend(window)
        print(f"probe: platform={platform} kind={kind} "
              f"reason={info.get('reason')!r}", file=sys.stderr)
        print(platform or "none")
    except Exception as e:              # noqa: BLE001
        print(f"probe harness error: {type(e).__name__}: {e}",
              file=sys.stderr)
        print("error")


if __name__ == "__main__":
    main()
