from .builder import (make_graph, make_model, make_node, make_tensor,
                      make_tensor_value_info)
from .convert import ConvertedModel, OP_HANDLERS, convert_model, register_op
from .proto import (DataType, ModelProto, model_content_digest, parse_model,
                    tensor_to_numpy)

__all__ = ["convert_model", "ConvertedModel", "OP_HANDLERS", "register_op",
           "parse_model", "model_content_digest", "ModelProto", "DataType",
           "tensor_to_numpy",
           "make_node", "make_tensor", "make_tensor_value_info", "make_graph",
           "make_model"]
