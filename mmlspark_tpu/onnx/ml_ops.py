"""ai.onnx.ml domain: the sklearn/LightGBM interchange operators.

Parity surface: the reference's flagship ONNX story converts a trained
LightGBM booster to ONNX (``TreeEnsembleClassifier``) and serves it through
``ONNXModel`` on onnxruntime (``website/docs/features/onnx/about.md``,
``deep-learning/.../onnx/ONNXModel.scala:173-193``). skl2onnx emits the same
family for sklearn models (Scaler/Imputer/Normalizer/LinearClassifier/...).

The tree walk is TPU-first: node tables are padded to flat ``(T, max_nodes)``
arrays at CONVERT time (attributes are static numpy), and evaluation is a
fixed-depth vectorized descent — every (row, tree) pair advances through one
gather per level with leaves self-looping, so the whole forest costs
``max_depth`` batched gathers instead of onnxruntime's per-row pointer
chase. No data-dependent control flow; jit-stable shapes throughout.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .convert import UnsupportedOp, register_op

_ML = "ai.onnx.ml"

_MODES = {"BRANCH_LEQ": 0, "BRANCH_LT": 1, "BRANCH_GTE": 2, "BRANCH_GT": 3,
          "BRANCH_EQ": 4, "BRANCH_NEQ": 5, "LEAF": 6}


def _require_ml(node):
    if node.domain not in (_ML,):
        raise UnsupportedOp(f"{node.op_type} in domain {node.domain!r}")


# -- tree ensembles ----------------------------------------------------------

def _parse_tree_tables(node):
    """Static node attributes → padded (T, M) numpy tables + max depth."""
    tids = np.asarray(node.attr("nodes_treeids"), np.int64)
    nids = np.asarray(node.attr("nodes_nodeids"), np.int64)
    feats = np.asarray(node.attr("nodes_featureids"), np.int64)
    vals = np.asarray(node.attr("nodes_values"), np.float32)
    modes = np.asarray([_MODES[m] for m in node.attr("nodes_modes")],
                       np.int32)
    trues = np.asarray(node.attr("nodes_truenodeids"), np.int64)
    falses = np.asarray(node.attr("nodes_falsenodeids"), np.int64)
    miss = node.attr("nodes_missing_value_tracks_true")
    miss = (np.asarray(miss, np.int32) if miss
            else np.zeros(len(nids), np.int32))

    trees = sorted(set(int(t) for t in tids))
    tree_index = {t: i for i, t in enumerate(trees)}
    T = len(trees)
    M = int(nids.max()) + 1 if len(nids) else 1

    feat = np.zeros((T, M), np.int32)
    val = np.zeros((T, M), np.float32)
    mode = np.full((T, M), _MODES["LEAF"], np.int32)
    tnext = np.tile(np.arange(M, dtype=np.int32), (T, 1))
    fnext = np.tile(np.arange(M, dtype=np.int32), (T, 1))
    mtrue = np.zeros((T, M), np.int32)
    for i in range(len(nids)):
        t, n = tree_index[int(tids[i])], int(nids[i])
        feat[t, n] = feats[i]
        val[t, n] = vals[i]
        mode[t, n] = modes[i]
        mtrue[t, n] = miss[i]
        if modes[i] != _MODES["LEAF"]:
            tnext[t, n] = trues[i]
            fnext[t, n] = falses[i]
        # leaves keep the self-loop defaults

    # longest root→leaf path (BFS per tree); cycle-guarded by the node count
    max_depth = 1
    for t in range(T):
        depth = np.full(M, -1, np.int64)
        depth[0] = 0
        frontier = [0]
        steps = 0
        while frontier and steps <= M:
            steps += 1
            nxt = []
            for n in frontier:
                if mode[t, n] == _MODES["LEAF"]:
                    continue
                for c in (int(tnext[t, n]), int(fnext[t, n])):
                    if depth[c] == -1:
                        depth[c] = depth[n] + 1
                        nxt.append(c)
            frontier = nxt
        max_depth = max(max_depth, int(depth.max()) + 1)
    return tree_index, (feat, val, mode, tnext, fnext, mtrue), max_depth


def _walk_trees(X, tables, max_depth):
    """(N, F) rows × (T, M) node tables → (N, T) leaf node indices."""
    feat, val, mode, tnext, fnext, mtrue = (jnp.asarray(a) for a in tables)
    N, F = X.shape
    T, M = feat.shape
    tr = jnp.arange(T)[None, :]                       # (1, T)

    def level(_, idx):
        nf = feat[tr, idx]                            # (N, T)
        nv = val[tr, idx]
        nm = mode[tr, idx]
        x = jnp.take_along_axis(X, jnp.clip(nf, 0, F - 1), axis=1)
        cond = jnp.select(
            [nm == 0, nm == 1, nm == 2, nm == 3, nm == 4],
            [x <= nv, x < nv, x >= nv, x > nv, x == nv],
            x != nv)
        cond = jnp.where(jnp.isnan(x), mtrue[tr, idx] > 0, cond)
        return jnp.where(cond, tnext[tr, idx], fnext[tr, idx])

    idx = jnp.zeros((N, T), jnp.int32)
    return jax.lax.fori_loop(0, max_depth, level, idx)


def _leaf_weight_table(node, tree_index, M, n_out, prefix,
                       collapse_ids=False):
    """(T, M, n_out) dense weights from the class_*/target_* attributes.
    ``collapse_ids``: binary single-class form — every entry scores the one
    output column regardless of its class id."""
    tids = np.asarray(node.attr(f"{prefix}_treeids"), np.int64)
    nids = np.asarray(node.attr(f"{prefix}_nodeids"), np.int64)
    outs = np.asarray(node.attr(f"{prefix}_ids"), np.int64)
    ws = np.asarray(node.attr(f"{prefix}_weights"), np.float32)
    W = np.zeros((len(tree_index), M, n_out), np.float32)
    for i in range(len(tids)):
        col = 0 if collapse_ids else int(outs[i])
        # += not =: a leaf may carry several entries for the same output
        W[tree_index[int(tids[i])], int(nids[i]), col] += ws[i]
    return W


def _post_transform(scores, kind):
    if kind in (None, "", "NONE"):
        return scores
    if kind == "SOFTMAX":
        return jax.nn.softmax(scores, axis=-1)
    if kind == "LOGISTIC":
        return jax.nn.sigmoid(scores)
    if kind == "SOFTMAX_ZERO":
        # softmax over the nonzero entries only (spec): zero logits keep
        # probability zero
        nz = scores != 0.0
        e = jnp.where(nz, jnp.exp(scores - jnp.max(
            jnp.where(nz, scores, -jnp.inf), axis=-1, keepdims=True)), 0.0)
        return e / jnp.maximum(jnp.sum(e, axis=-1, keepdims=True), 1e-30)
    if kind == "PROBIT":
        return 0.5 * (1.0 + jax.lax.erf(scores / np.sqrt(2.0)))
    raise UnsupportedOp(f"post_transform {kind!r}")


@register_op("TreeEnsembleClassifier")
def _tree_classifier(node, inputs, ctx):
    _require_ml(node)
    labels = node.attr("classlabels_int64s")
    if labels is None:
        raise UnsupportedOp("TreeEnsembleClassifier with string class "
                            "labels (int64 labels only under jit)")
    labels = np.asarray(labels, np.int64)
    C = len(labels)
    tree_index, tables, max_depth = _parse_tree_tables(node)
    class_ids = set(int(c) for c in node.attr("class_ids"))
    binary_single = C == 2 and len(class_ids) == 1
    n_out = 1 if binary_single else C
    W = _leaf_weight_table(node, tree_index, tables[0].shape[1], n_out,
                           "class", collapse_ids=binary_single)
    base = np.asarray(node.attr("base_values") or [0.0] * n_out, np.float32)
    post = node.attr("post_transform", "NONE")

    X = inputs[0].astype(jnp.float32)
    if X.ndim == 1:
        X = X[None, :]
    leaf = _walk_trees(X, tables, max_depth)           # (N, T)
    contrib = jnp.asarray(W)[jnp.arange(W.shape[0])[None, :], leaf]
    scores = jnp.sum(contrib, axis=1) + jnp.asarray(base)   # (N, n_out)
    if binary_single:
        s = scores[:, 0]
        if post == "LOGISTIC":
            p1 = jax.nn.sigmoid(s)
            scores = jnp.stack([1.0 - p1, p1], axis=-1)
        elif post in (None, "", "NONE"):
            # sklearn forest exports carry leaf PROBABILITIES for class 1
            scores = jnp.stack([1.0 - s, s], axis=-1)
        else:
            raise UnsupportedOp(
                f"binary single-class TreeEnsemble with {post}")
    else:
        scores = _post_transform(scores, post)
    pred = jnp.take(jnp.asarray(labels), jnp.argmax(scores, axis=-1))
    return pred, scores


@register_op("TreeEnsembleRegressor")
def _tree_regressor(node, inputs, ctx):
    _require_ml(node)
    n_out = int(node.attr("n_targets", 1))
    tree_index, tables, max_depth = _parse_tree_tables(node)
    W = _leaf_weight_table(node, tree_index, tables[0].shape[1], n_out,
                           "target")
    base = np.asarray(node.attr("base_values") or [0.0] * n_out, np.float32)
    agg = node.attr("aggregate_function", "SUM")

    X = inputs[0].astype(jnp.float32)
    if X.ndim == 1:
        X = X[None, :]
    leaf = _walk_trees(X, tables, max_depth)
    contrib = jnp.asarray(W)[jnp.arange(W.shape[0])[None, :], leaf]
    if agg == "SUM":
        scores = jnp.sum(contrib, axis=1)
    elif agg == "AVERAGE":
        scores = jnp.mean(contrib, axis=1)
    elif agg == "MIN":
        scores = jnp.min(contrib, axis=1)
    elif agg == "MAX":
        scores = jnp.max(contrib, axis=1)
    else:
        raise UnsupportedOp(f"aggregate_function {agg!r}")
    scores = scores + jnp.asarray(base)
    return _post_transform(scores, node.attr("post_transform", "NONE"))


# -- linear / preprocessing --------------------------------------------------

@register_op("LinearClassifier")
def _linear_classifier(node, inputs, ctx):
    _require_ml(node)
    labels = node.attr("classlabels_ints")
    if labels is None:
        raise UnsupportedOp("LinearClassifier with string class labels")
    labels = np.asarray(labels, np.int64)
    C = len(labels)
    coef = np.asarray(node.attr("coefficients"), np.float32)
    # row count comes from the intercepts (skl2onnx emits one per score
    # row); a single row with two labels is the binary-one form
    inter = np.asarray(node.attr("intercepts") or [0.0], np.float32)
    rows = len(inter)
    coef = coef.reshape(rows, -1)
    post = node.attr("post_transform", "NONE")
    X = inputs[0].astype(jnp.float32)
    if X.ndim == 1:
        X = X[None, :]
    s = X @ jnp.asarray(coef).T + jnp.asarray(inter)
    if rows == 1 and C == 2:
        p1 = jax.nn.sigmoid(s[:, 0]) if post == "LOGISTIC" else s[:, 0]
        scores = jnp.stack([1.0 - p1, p1], axis=-1)
    else:
        scores = _post_transform(s, post)
    pred = jnp.take(jnp.asarray(labels), jnp.argmax(scores, axis=-1))
    return pred, scores


@register_op("LinearRegressor")
def _linear_regressor(node, inputs, ctx):
    _require_ml(node)
    n = int(node.attr("targets", 1))
    coef = np.asarray(node.attr("coefficients"), np.float32).reshape(n, -1)
    inter = np.asarray(node.attr("intercepts") or [0.0] * n, np.float32)
    X = inputs[0].astype(jnp.float32)
    if X.ndim == 1:
        X = X[None, :]
    return _post_transform(X @ jnp.asarray(coef).T + jnp.asarray(inter),
                           node.attr("post_transform", "NONE"))


@register_op("Scaler")
def _scaler(node, inputs, ctx):
    _require_ml(node)
    off = np.asarray(node.attr("offset") or [0.0], np.float32)
    sc = np.asarray(node.attr("scale") or [1.0], np.float32)
    return (inputs[0].astype(jnp.float32) - off) * sc


@register_op("Normalizer")
def _normalizer(node, inputs, ctx):
    _require_ml(node)
    norm = node.attr("norm", "MAX")
    x = inputs[0].astype(jnp.float32)
    if norm == "MAX":
        d = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    elif norm == "L1":
        d = jnp.sum(jnp.abs(x), axis=-1, keepdims=True)
    elif norm == "L2":
        d = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    else:
        raise UnsupportedOp(f"Normalizer norm {norm!r}")
    return x / jnp.maximum(d, 1e-30)


@register_op("Imputer")
def _imputer(node, inputs, ctx):
    _require_ml(node)
    x = inputs[0]
    if np.issubdtype(np.dtype(x.dtype), np.floating):
        fill = np.asarray(node.attr("imputed_value_floats"), np.float32)
        missing = node.attr("replaced_value_float", float("nan"))
        hit = (jnp.isnan(x) if np.isnan(missing)
               else x == jnp.float32(missing))
    else:
        fill = np.asarray(node.attr("imputed_value_int64s"), np.int64)
        hit = x == node.attr("replaced_value_int64", 0)
    fill = jnp.asarray(fill if fill.size > 1 else fill.reshape(()))
    return jnp.where(hit, fill, x)


@register_op("Binarizer")
def _binarizer(node, inputs, ctx):
    _require_ml(node)
    thr = node.attr("threshold", 0.0)
    x = inputs[0]
    return (x > jnp.asarray(thr, x.dtype)).astype(x.dtype)


@register_op("ArrayFeatureExtractor")
def _array_feature_extractor(node, inputs, ctx):
    _require_ml(node)
    x, idx = inputs
    return jnp.take(x, idx.astype(jnp.int32).reshape(-1), axis=-1)


@register_op("FeatureVectorizer")
def _feature_vectorizer(node, inputs, ctx):
    _require_ml(node)
    cols = [x.astype(jnp.float32) for x in inputs if x is not None]
    cols = [c[:, None] if c.ndim == 1 else c for c in cols]
    return jnp.concatenate(cols, axis=-1)


@register_op("LabelEncoder")
def _label_encoder(node, inputs, ctx):
    _require_ml(node)
    x = inputs[0]
    for kk, vk in (("keys_int64s", "values_int64s"),
                   ("keys_int64s", "values_floats"),
                   ("keys_floats", "values_int64s"),
                   ("keys_floats", "values_floats")):
        keys, vals = node.attr(kk), node.attr(vk)
        if keys is not None and vals is not None:
            break
    else:
        raise UnsupportedOp("LabelEncoder with string keys/values "
                            "(jit-incompatible)")
    keys = np.asarray(keys)
    vals = np.asarray(vals)
    default = node.attr(
        "default_int64" if vals.dtype.kind == "i" else "default_float",
        -1 if vals.dtype.kind == "i" else -0.0)
    hit = x[..., None] == jnp.asarray(keys)                # (..., K)
    found = jnp.any(hit, axis=-1)
    picked = jnp.einsum("...k,k->...", hit.astype(vals.dtype),
                        jnp.asarray(vals))
    return jnp.where(found, picked, jnp.asarray(default, picked.dtype))


@register_op("ZipMap")
def _zipmap(node, inputs, ctx):
    # ZipMap decorates probabilities into per-row dicts for python callers;
    # under jit the tensor IS the useful output — pass it through (the
    # label keys live in the node attrs for any host-side consumer)
    _require_ml(node)
    return inputs[0]


# -- SVMs (skl2onnx SVC/SVR) -------------------------------------------------

def _svm_kernel(X, SV, kind, params):
    """(N, F) × (M, F) kernel matrix. ``params`` = [gamma, coef0, degree]
    (the attribute order skl2onnx emits)."""
    gamma, coef0, degree = (list(params) + [0.0, 0.0, 3.0])[:3]
    if kind == "LINEAR":
        return X @ SV.T
    if kind == "POLY":
        return (gamma * (X @ SV.T) + coef0) ** int(degree)
    if kind == "RBF":
        d2 = (jnp.sum(X * X, axis=1)[:, None]
              - 2.0 * (X @ SV.T) + jnp.sum(SV * SV, axis=1)[None, :])
        return jnp.exp(-gamma * d2)
    if kind == "SIGMOID":
        return jnp.tanh(gamma * (X @ SV.T) + coef0)
    raise UnsupportedOp(f"SVM kernel {kind!r}")


@register_op("SVMRegressor")
def _svm_regressor(node, inputs, ctx):
    _require_ml(node)
    if node.attr("one_class", 0):
        raise UnsupportedOp("SVMRegressor one_class (OneClassSVM ±1 "
                            "labeling semantics)")
    coefs = np.asarray(node.attr("coefficients"), np.float32)
    sv = np.asarray(node.attr("support_vectors"), np.float32)
    rho = np.asarray(node.attr("rho") or [0.0], np.float32)
    kind = node.attr("kernel_type", "LINEAR")
    params = node.attr("kernel_params") or []
    X = inputs[0].astype(jnp.float32)
    if X.ndim == 1:
        X = X[None, :]
    M = len(coefs)
    SV = jnp.asarray(sv.reshape(M, -1))
    K = _svm_kernel(X, SV, kind, params)                   # (N, M)
    out = K @ jnp.asarray(coefs) + rho[0]
    return _post_transform(out[:, None],
                           node.attr("post_transform", "NONE"))


@register_op("SVMClassifier")
def _svm_classifier(node, inputs, ctx):
    """libsvm-style one-vs-one voting (the skl2onnx SVC export). Decision
    values for each class pair come from the dual coefficients; labels by
    majority vote with decision-sum tiebreak — matching onnxruntime when no
    probability calibration (prob_a/prob_b) is present."""
    _require_ml(node)
    if node.attr("prob_a"):
        raise UnsupportedOp("SVMClassifier with Platt scaling (prob_a/b)")
    labels = node.attr("classlabels_ints")
    if labels is None:
        raise UnsupportedOp("SVMClassifier with string class labels")
    labels = np.asarray(labels, np.int64)
    C = len(labels)
    vpc = np.asarray(node.attr("vectors_per_class"), np.int64)
    sv = np.asarray(node.attr("support_vectors"), np.float32)
    coefs = np.asarray(node.attr("coefficients"), np.float32)
    rho = np.asarray(node.attr("rho"), np.float32)
    kind = node.attr("kernel_type", "LINEAR")
    params = node.attr("kernel_params") or []
    M = int(vpc.sum())
    SV = jnp.asarray(sv.reshape(M, -1))
    A = jnp.asarray(coefs.reshape(C - 1, M))   # dual coefs, libsvm layout
    starts = np.r_[0, np.cumsum(vpc)]

    X = inputs[0].astype(jnp.float32)
    if X.ndim == 1:
        X = X[None, :]
    K = _svm_kernel(X, SV, kind, params)                   # (N, M)

    votes = jnp.zeros((X.shape[0], C), jnp.float32)
    sums = jnp.zeros((X.shape[0], C), jnp.float32)
    decisions = []
    p = 0
    for i in range(C):
        for j in range(i + 1, C):
            si, sj = slice(starts[i], starts[i + 1]), \
                slice(starts[j], starts[j + 1])
            # + rho: skl2onnx stores sklearn's intercept_ in rho (decision
            # = dual sum + intercept), same sign as SVMRegressor above
            dec = (K[:, si] @ A[j - 1, si] + K[:, sj] @ A[i, sj]
                   + rho[p])
            decisions.append(dec)
            win_i = dec > 0
            votes = votes.at[:, i].add(win_i.astype(jnp.float32))
            votes = votes.at[:, j].add((~win_i).astype(jnp.float32))
            sums = sums.at[:, i].add(dec)
            sums = sums.at[:, j].add(-dec)
            p += 1
    scores = jnp.stack(decisions, axis=1) if decisions else sums
    # majority vote, ties broken by accumulated decision sums
    rank = votes + jax.nn.sigmoid(sums) * 0.5
    pred = jnp.take(jnp.asarray(labels), jnp.argmax(rank, axis=-1))
    return pred, _post_transform(scores,
                                 node.attr("post_transform", "NONE"))


# -- core-domain stragglers commonly found next to ml graphs -----------------
# (Mod lives in convert.py's core table — fmod handled there; Mish too.)

@register_op("Hardmax")
def _hardmax(node, inputs, ctx):
    x = inputs[0]
    axis = node.attr("axis", -1 if ctx.opset >= 13 else 1)
    oh = jax.nn.one_hot(jnp.argmax(x, axis=axis), x.shape[axis],
                        axis=axis if axis >= 0 else x.ndim + axis,
                        dtype=x.dtype)
    return oh


@register_op("ScatterElements")
def _scatter_elements(node, inputs, ctx):
    data, indices, updates = (jnp.asarray(t) for t in inputs)
    axis = node.attr("axis", 0)
    reduction = node.attr("reduction", "none")
    idx = indices.astype(jnp.int64)
    idx = jnp.where(idx < 0, idx + data.shape[axis], idx)
    # jnp's put_along_axis-free formulation: build full index grids
    grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape],
                         indexing="ij")
    grids[axis if axis >= 0 else data.ndim + axis] = idx
    coords = tuple(g.reshape(-1) for g in grids)
    upd = updates.reshape(-1)
    if reduction == "none":
        return data.at[coords].set(upd)
    if reduction == "add":
        return data.at[coords].add(upd)
    if reduction in ("mul", "max", "min"):
        return getattr(data.at[coords], reduction)(upd)
    raise UnsupportedOp(f"ScatterElements reduction {reduction!r}")
