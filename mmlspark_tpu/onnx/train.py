"""Training on imported ONNX graphs — inference artifacts become tunable.

The reference's ONNX path is inference-only by construction: ORT executes
a frozen session (``deep-learning/.../onnx/ONNXModel.scala:330``) and
fine-tuning means going back to the exporting framework. Here an imported
graph IS a pure JAX function over an explicit ``params`` dict
(``ConvertedModel.__call__(params, feeds)``), so ``jax.grad`` flows
through every differentiable handler and any ONNX model — including a
genuine ``torch.onnx.export`` artifact — fine-tunes on TPU without torch
in the loop. With the standard loss ops (SoftmaxCrossEntropyLoss /
NegativeLogLikelihoodLoss) a graph can even carry its own training
objective.

Two entry points:

* :func:`value_and_grad` — differentiate a scalar derived from the
  graph's outputs w.r.t. its params.
* :func:`make_train_step` — one jitted optax update step; scan it or loop
  it, params stay on device.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from .convert import ConvertedModel

__all__ = ["value_and_grad", "make_train_step", "fine_tune",
           "lora_targets", "init_lora", "lora_merge",
           "make_lora_train_step", "lora_fine_tune"]


def _scalar_loss(model: ConvertedModel, loss_fn, output: Optional[str]):
    """Build loss(params, feeds) -> scalar from either a graph output that
    is already a loss, or a callable over the outputs dict."""
    if loss_fn is None:
        if output is None:
            if len(model.output_names) != 1:
                raise ValueError(
                    "graph has several outputs; pass output= (a scalar "
                    "loss output) or loss_fn=(outputs, feeds) -> scalar")
            output = model.output_names[0]

        def loss(params, feeds):
            return jnp.sum(model(params, feeds)[output])
    else:
        def loss(params, feeds):
            return loss_fn(model(params, feeds), feeds)
    return loss


def value_and_grad(model: ConvertedModel,
                   loss_fn: Optional[Callable] = None,
                   output: Optional[str] = None):
    """``(params, feeds) -> (scalar, grads)`` for an imported graph.

    ``output`` names a graph output that already IS the loss (e.g. a
    SoftmaxCrossEntropyLoss node with reduction='mean'); alternatively
    ``loss_fn(outputs_dict, feeds) -> scalar`` computes one.
    """
    return jax.value_and_grad(_scalar_loss(model, loss_fn, output))


def make_train_step(model: ConvertedModel, optimizer,
                    loss_fn: Optional[Callable] = None,
                    output: Optional[str] = None,
                    trainable: Optional[Callable[[str], bool]] = None):
    """One jitted optimizer step over the graph's params.

    ``optimizer`` is any optax GradientTransformation. ``trainable`` is an
    optional name predicate — params it rejects get zero updates (the
    cut-layer / frozen-backbone pattern ImageFeaturizer uses). Returns
    ``(step, init_state)`` where
    ``step(params, opt_state, feeds) -> (params, opt_state, loss)``.
    """
    loss = _scalar_loss(model, loss_fn, output)

    @jax.jit
    def step(params, opt_state, feeds):
        import optax
        val, grads = jax.value_and_grad(loss)(params, feeds)
        if trainable is not None:
            grads = {k: (g if trainable(k) else jnp.zeros_like(g))
                     for k, g in grads.items()}
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, val

    def init(params):
        return optimizer.init(
            {k: jnp.asarray(v) for k, v in params.items()})

    return step, init


def fine_tune(model: ConvertedModel, feeds_iter, optimizer=None,
              loss_fn: Optional[Callable] = None,
              output: Optional[str] = None,
              steps: Optional[int] = None,
              trainable: Optional[Callable[[str], bool]] = None,
              params: Optional[Dict] = None):
    """Convenience loop: iterate ``feeds_iter`` (dicts of graph inputs),
    one optimizer step each; returns (params, losses). ``params`` defaults
    to the graph's own initializers — the imported weights are the warm
    start."""
    import optax
    if optimizer is None:
        optimizer = optax.adam(1e-3)
    step, init = make_train_step(model, optimizer, loss_fn=loss_fn,
                                 output=output, trainable=trainable)
    params = {k: jnp.asarray(v)
              for k, v in (params if params is not None
                           else model.params).items()}
    opt_state = init(params)
    losses = []
    for i, feeds in enumerate(feeds_iter):
        if steps is not None and i >= steps:
            break
        params, opt_state, val = step(params, opt_state, feeds)
        losses.append(float(val))
    return params, losses


# ---- LoRA: low-rank adapters over imported graphs -------------------------
# Full fine-tuning updates every n×m weight and carries an optimizer state
# of the same size; a LoRA adapter trains rank·(n+m) parameters per matrix
# instead — on TPU that shrinks the optimizer state and per-step update
# traffic by orders of magnitude, and the frozen base composes with
# serving-side weight-only int8 (merge first, then quantize). The merged
# deltas serve through ONNXModel's existing ``weights_override`` layering,
# so inference needs no adapter-aware code path.


def lora_targets(model: ConvertedModel, rank: int,
                 trainable: Optional[Callable[[str], bool]] = None):
    """Params eligible for adaptation: 2-D float weights with both dims
    larger than ``rank`` (a low-rank delta on anything smaller would cost
    more than the dense update), filtered by ``trainable``."""
    import numpy as np
    out = []
    for k, v in model.params.items():
        a = np.asarray(v)
        if (a.ndim == 2 and a.dtype.kind == "f" and min(a.shape) > rank
                and (trainable is None or trainable(k))):
            out.append(k)
    return sorted(out)


def init_lora(model: ConvertedModel, rank: int,
              targets=None, seed: int = 0) -> Dict:
    """Fresh adapters {name: {"a": (n, r), "b": (r, m)}}: ``a`` fan-in
    gaussian, ``b`` zeros, so the initial delta is exactly zero and the
    first forward equals the imported graph."""
    if rank < 1:
        raise ValueError("rank must be >= 1")
    targets = (lora_targets(model, rank) if targets is None
               else sorted(targets))
    if not targets:
        raise ValueError(
            f"no 2-D params wider than rank {rank} to adapt (an explicit "
            "targets= / trainable filter may have excluded every matrix)")
    unknown = [t for t in targets if t not in model.params]
    if unknown:
        raise ValueError(f"unknown target params {unknown[:5]}")
    import numpy as _np
    bad = [t for t in targets if _np.asarray(model.params[t]).ndim != 2]
    if bad:
        raise ValueError(f"LoRA targets must be 2-D weights; {bad[:5]} "
                         "are not")
    key = jax.random.PRNGKey(seed)
    lora = {}
    for i, k in enumerate(targets):
        n, m = model.params[k].shape
        lora[k] = {
            "a": (jax.random.normal(jax.random.fold_in(key, i), (n, rank),
                                    jnp.float32) / jnp.sqrt(n)),
            "b": jnp.zeros((rank, m), jnp.float32),
        }
    return lora


def lora_merge(params: Dict, lora: Dict, alpha: float) -> Dict:
    """Base params with every adapter's ``(alpha/rank)·a@b`` delta folded
    in — the artifact that serves (and quantizes) like any fine-tune."""
    out = dict(params)
    for k, ab in lora.items():
        r = ab["a"].shape[1]
        delta = (jnp.float32(alpha / r)
                 * (ab["a"] @ ab["b"])).astype(out[k].dtype)
        out[k] = out[k] + delta
    return out


def make_lora_train_step(model: ConvertedModel, optimizer,
                         alpha: Optional[float] = None,
                         loss_fn: Optional[Callable] = None,
                         output: Optional[str] = None):
    """One jitted LoRA step: gradients flow ONLY into the adapters
    (``base`` is a frozen argument, never updated, so its optimizer state
    is never allocated). ``alpha`` defaults to the adapters' rank (scale
    1). Returns ``(step, init)`` with
    ``step(base, lora, opt_state, feeds) -> (lora, opt_state, loss)``.
    """
    loss = _scalar_loss(model, loss_fn, output)

    @jax.jit
    def step(base, lora, opt_state, feeds):
        import optax
        rank = next(iter(lora.values()))["a"].shape[1]
        scale = rank if alpha is None else alpha

        def lora_loss(lora_):
            return loss(lora_merge(base, lora_, scale), feeds)

        val, grads = jax.value_and_grad(lora_loss)(lora)
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        return optax.apply_updates(lora, updates), opt_state, val

    def init(lora):
        return optimizer.init(jax.tree.map(jnp.asarray, lora))

    return step, init


def lora_fine_tune(model: ConvertedModel, feeds_iter, rank: int = 8,
                   optimizer=None, alpha: Optional[float] = None,
                   loss_fn: Optional[Callable] = None,
                   output: Optional[str] = None,
                   targets=None, seed: int = 0,
                   steps: Optional[int] = None):
    """Convenience loop mirroring :func:`fine_tune`; returns
    ``(merged_params, lora, losses)`` — serve ``merged_params`` (or just
    the adapted names) via ``ONNXModel.weights_override``."""
    import optax
    if optimizer is None:
        optimizer = optax.adam(1e-3)
    lora = init_lora(model, rank, targets=targets, seed=seed)
    step, init = make_lora_train_step(model, optimizer, alpha=alpha,
                                      loss_fn=loss_fn, output=output)
    base = {k: jnp.asarray(v) for k, v in model.params.items()}
    opt_state = init(lora)
    losses = []
    for i, feeds in enumerate(feeds_iter):
        if steps is not None and i >= steps:
            break
        lora, opt_state, val = step(base, lora, opt_state, feeds)
        losses.append(float(val))
    scale = (rank if alpha is None else alpha)
    return lora_merge(base, lora, scale), lora, losses
