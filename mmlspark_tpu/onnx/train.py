"""Training on imported ONNX graphs — inference artifacts become tunable.

The reference's ONNX path is inference-only by construction: ORT executes
a frozen session (``deep-learning/.../onnx/ONNXModel.scala:330``) and
fine-tuning means going back to the exporting framework. Here an imported
graph IS a pure JAX function over an explicit ``params`` dict
(``ConvertedModel.__call__(params, feeds)``), so ``jax.grad`` flows
through every differentiable handler and any ONNX model — including a
genuine ``torch.onnx.export`` artifact — fine-tunes on TPU without torch
in the loop. With the standard loss ops (SoftmaxCrossEntropyLoss /
NegativeLogLikelihoodLoss) a graph can even carry its own training
objective.

Two entry points:

* :func:`value_and_grad` — differentiate a scalar derived from the
  graph's outputs w.r.t. its params.
* :func:`make_train_step` — one jitted optax update step; scan it or loop
  it, params stay on device.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from .convert import ConvertedModel

__all__ = ["value_and_grad", "make_train_step", "fine_tune"]


def _scalar_loss(model: ConvertedModel, loss_fn, output: Optional[str]):
    """Build loss(params, feeds) -> scalar from either a graph output that
    is already a loss, or a callable over the outputs dict."""
    if loss_fn is None:
        if output is None:
            if len(model.output_names) != 1:
                raise ValueError(
                    "graph has several outputs; pass output= (a scalar "
                    "loss output) or loss_fn=(outputs, feeds) -> scalar")
            output = model.output_names[0]

        def loss(params, feeds):
            return jnp.sum(model(params, feeds)[output])
    else:
        def loss(params, feeds):
            return loss_fn(model(params, feeds), feeds)
    return loss


def value_and_grad(model: ConvertedModel,
                   loss_fn: Optional[Callable] = None,
                   output: Optional[str] = None):
    """``(params, feeds) -> (scalar, grads)`` for an imported graph.

    ``output`` names a graph output that already IS the loss (e.g. a
    SoftmaxCrossEntropyLoss node with reduction='mean'); alternatively
    ``loss_fn(outputs_dict, feeds) -> scalar`` computes one.
    """
    return jax.value_and_grad(_scalar_loss(model, loss_fn, output))


def make_train_step(model: ConvertedModel, optimizer,
                    loss_fn: Optional[Callable] = None,
                    output: Optional[str] = None,
                    trainable: Optional[Callable[[str], bool]] = None):
    """One jitted optimizer step over the graph's params.

    ``optimizer`` is any optax GradientTransformation. ``trainable`` is an
    optional name predicate — params it rejects get zero updates (the
    cut-layer / frozen-backbone pattern ImageFeaturizer uses). Returns
    ``(step, init_state)`` where
    ``step(params, opt_state, feeds) -> (params, opt_state, loss)``.
    """
    loss = _scalar_loss(model, loss_fn, output)

    @jax.jit
    def step(params, opt_state, feeds):
        import optax
        val, grads = jax.value_and_grad(loss)(params, feeds)
        if trainable is not None:
            grads = {k: (g if trainable(k) else jnp.zeros_like(g))
                     for k, g in grads.items()}
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, val

    def init(params):
        return optimizer.init(
            {k: jnp.asarray(v) for k, v in params.items()})

    return step, init


def fine_tune(model: ConvertedModel, feeds_iter, optimizer=None,
              loss_fn: Optional[Callable] = None,
              output: Optional[str] = None,
              steps: Optional[int] = None,
              trainable: Optional[Callable[[str], bool]] = None,
              params: Optional[Dict] = None):
    """Convenience loop: iterate ``feeds_iter`` (dicts of graph inputs),
    one optimizer step each; returns (params, losses). ``params`` defaults
    to the graph's own initializers — the imported weights are the warm
    start."""
    import optax
    if optimizer is None:
        optimizer = optax.adam(1e-3)
    step, init = make_train_step(model, optimizer, loss_fn=loss_fn,
                                 output=output, trainable=trainable)
    params = {k: jnp.asarray(v)
              for k, v in (params if params is not None
                           else model.params).items()}
    opt_state = init(params)
    losses = []
    for i, feeds in enumerate(feeds_iter):
        if steps is not None and i >= steps:
            break
        params, opt_state, val = step(params, opt_state, feeds)
        losses.append(float(val))
    return params, losses
