"""ONNX model builder — serialize graphs without the onnx package.

Used by tests (golden models for the converter), the model-zoo exporter, and
anyone who wants to hand a self-built graph to :class:`ONNXModel`. API shape
mirrors the public ``onnx.helper`` so snippets translate directly:
``make_node / make_tensor / make_graph / make_model → bytes``.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Union

import numpy as np

from .proto import DataType, NUMPY_TO_ONNX
from .wire import WireWriter

__all__ = ["make_node", "make_tensor", "make_external_tensor",
           "make_tensor_value_info", "make_graph", "make_model", "Node"]


class Node:
    def __init__(self, op_type: str, inputs: Sequence[str],
                 outputs: Sequence[str], name: str = "", domain: str = "",
                 **attrs):
        self.op_type = op_type
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.name = name or f"{op_type}_{id(self) & 0xffff:x}"
        self.domain = domain
        self.attrs = attrs


def make_node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
              name: str = "", domain: str = "", **attrs) -> Node:
    return Node(op_type, inputs, outputs, name, domain, **attrs)


def _encode_tensor(name: str, arr: np.ndarray) -> WireWriter:
    w = WireWriter()
    arr = np.asarray(arr)
    if arr.dtype.kind == "U" or arr.dtype == object:
        w.packed_varints(1, arr.shape)
        w.varint(2, DataType.STRING)
        for s in arr.ravel():
            w.bytes(6, str(s).encode("utf-8"))
        w.string(8, name)
        return w
    onnx_dtype = NUMPY_TO_ONNX.get(arr.dtype)
    if onnx_dtype is None:
        raise TypeError(f"no ONNX dtype for numpy {arr.dtype}")
    if arr.shape:
        w.packed_varints(1, arr.shape)
    w.varint(2, onnx_dtype)
    w.string(8, name)
    w.bytes(9, np.ascontiguousarray(arr).tobytes())
    return w


def make_tensor(name: str, arr: np.ndarray) -> WireWriter:
    return _encode_tensor(name, arr)


def make_external_tensor(name: str, arr: np.ndarray, location: str,
                         data_dir: str, offset: int = 0) -> WireWriter:
    """Emit a TensorProto with ``data_location=EXTERNAL`` and write the
    payload into ``data_dir/location`` at ``offset`` (the torch exporter's
    ``save_as_external_data`` layout). Returns the proto writer."""
    import os
    arr = np.ascontiguousarray(arr)
    onnx_dtype = NUMPY_TO_ONNX.get(arr.dtype)
    if onnx_dtype is None:
        raise TypeError(f"no ONNX dtype for numpy {arr.dtype}")
    payload = arr.tobytes()
    path = os.path.join(data_dir, location)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    mode = "r+b" if os.path.exists(path) else "wb"
    with open(path, mode) as f:
        f.seek(offset)
        f.write(payload)

    w = WireWriter()
    if arr.shape:
        w.packed_varints(1, arr.shape)
    w.varint(2, onnx_dtype)
    w.string(8, name)
    for key, val in (("location", location), ("offset", str(offset)),
                     ("length", str(len(payload)))):
        entry = WireWriter()
        entry.string(1, key)
        entry.string(2, val)
        w.message(13, entry)
    w.varint(14, 1)  # data_location = EXTERNAL
    return w


def _encode_attribute(name: str, value) -> WireWriter:
    from .proto import AttrType
    w = WireWriter()
    w.string(1, name)
    if isinstance(value, bool):
        w.varint(3, int(value)).varint(20, AttrType.INT)
    elif isinstance(value, int):
        w.varint(3, value).varint(20, AttrType.INT)
    elif isinstance(value, float):
        w.float32(2, value).varint(20, AttrType.FLOAT)
    elif isinstance(value, str):
        w.string(4, value).varint(20, AttrType.STRING)
    elif isinstance(value, bytes):
        w.bytes(4, value).varint(20, AttrType.STRING)
    elif isinstance(value, np.ndarray):
        w.message(5, _encode_tensor("", value)).varint(20, AttrType.TENSOR)
    elif isinstance(value, WireWriter):
        # a subgraph built by make_graph (If/Loop/Scan bodies)
        w.message(6, value).varint(20, AttrType.GRAPH)
    elif isinstance(value, (list, tuple)):
        if not value:
            w.packed_varints(8, []).varint(20, AttrType.INTS)
        elif all(isinstance(x, (int, np.integer)) for x in value):
            w.packed_varints(8, value).varint(20, AttrType.INTS)
        elif all(isinstance(x, (int, float, np.floating)) for x in value):
            w.packed_floats(7, value).varint(20, AttrType.FLOATS)
        elif all(isinstance(x, str) for x in value):
            for s in value:
                w.string(9, s)
            w.varint(20, AttrType.STRINGS)
        else:
            raise TypeError(f"mixed attribute list for {name!r}")
    else:
        raise TypeError(f"unsupported attribute {name!r}: {type(value).__name__}")
    return w


def _encode_node(node: Node) -> WireWriter:
    w = WireWriter()
    for i in node.inputs:
        w.string(1, i)
    for o in node.outputs:
        w.string(2, o)
    w.string(3, node.name)
    w.string(4, node.op_type)
    if node.domain:
        w.string(7, node.domain)
    for k, v in node.attrs.items():
        w.message(5, _encode_attribute(k, v))
    return w


def make_tensor_value_info(name: str, elem_type: Union[int, np.dtype, type],
                           shape: Sequence[Optional[Union[int, str]]]) -> WireWriter:
    if not isinstance(elem_type, int):
        elem_type = NUMPY_TO_ONNX[np.dtype(elem_type)]
    w = WireWriter()
    w.string(1, name)
    tensor_type = WireWriter()
    tensor_type.varint(1, elem_type)
    shape_w = WireWriter()
    for d in shape:
        dim = WireWriter()
        if isinstance(d, str):
            dim.string(2, d)
        elif d is not None:
            dim.varint(1, int(d))
        shape_w.message(1, dim)
    tensor_type.message(2, shape_w)
    type_w = WireWriter()
    type_w.message(1, tensor_type)
    w.message(2, type_w)
    return w


def make_graph(nodes: Sequence[Node], name: str,
               inputs: Sequence[WireWriter], outputs: Sequence[WireWriter],
               initializers: Optional[Dict[str, np.ndarray]] = None) -> WireWriter:
    w = WireWriter()
    for n in nodes:
        w.message(1, _encode_node(n))
    w.string(2, name)
    for tname, arr in (initializers or {}).items():
        # pre-encoded writers (e.g. make_external_tensor) pass through
        w.message(5, arr if isinstance(arr, WireWriter)
                  else _encode_tensor(tname, arr))
    for vi in inputs:
        w.message(11, vi)
    for vi in outputs:
        w.message(12, vi)
    return w


def make_model(graph: WireWriter, opset: int = 17,
               producer: str = "mmlspark_tpu",
               extra_opsets: Optional[dict] = None) -> bytes:
    """``extra_opsets``: additional domain→version imports (e.g.
    ``{"ai.onnx.ml": 3}`` for TreeEnsemble graphs)."""
    w = WireWriter()
    w.varint(1, 8)  # ir_version
    w.string(2, producer)
    w.message(7, graph)
    opset_w = WireWriter()
    opset_w.string(1, "")
    opset_w.varint(2, opset)
    w.message(8, opset_w)
    for domain, version in (extra_opsets or {}).items():
        ow = WireWriter()
        ow.string(1, domain)
        ow.varint(2, version)
        w.message(8, ow)
    return w.to_bytes()
