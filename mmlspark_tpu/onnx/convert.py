"""ONNX graph → JAX function.

Replaces onnxruntime as the execution engine behind ONNXModel (reference:
``deep-learning/.../onnx/ONNXModel.scala:173-193`` builds an ORT session with
the CUDA execution provider; here the graph becomes a pure jittable function
XLA compiles for TPU).

Design notes:

* Node handlers are written with ``jax.numpy``; anything derived only from
  initializers/constants stays **concrete** during tracing (jnp on ndarrays
  executes eagerly), so shape-carrying ops (``Shape`` → ``Reshape``/``Slice``)
  fold at trace time instead of producing dynamic shapes XLA can't tile.
* ``Shape`` returns the static shape as a numpy array — even for tracers the
  shape is known at trace time, which is what makes BERT-style graphs with
  shape arithmetic compile to static-shape XLA programs.
* The converted callable has signature ``fn(params, feeds) -> {name: out}``
  with ``params`` passed explicitly so jit can donate/shard them.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .proto import (GraphProto, ModelProto, NodeProto, ValueInfo,
                    ONNX_TO_NUMPY, parse_model, tensor_to_numpy)

__all__ = ["ConvertedModel", "convert_model", "OP_HANDLERS", "register_op"]


class UnsupportedOp(NotImplementedError):
    pass


def _concrete(v, what: str) -> np.ndarray:
    """Require a trace-time-constant value (e.g. a Reshape target)."""
    try:
        return np.asarray(v)
    except Exception as e:
        raise UnsupportedOp(
            f"{what} must be computable at trace time, got a traced value; "
            "this graph is data-dependently shaped") from e


OP_HANDLERS: Dict[str, Callable] = {}


def register_op(name: str):
    def deco(fn):
        OP_HANDLERS[name] = fn
        return fn
    return deco


# -- elementwise -------------------------------------------------------------

def _variadic(fn):
    def h(node, inputs, ctx):
        out = inputs[0]
        for x in inputs[1:]:
            out = fn(out, x)
        return out
    return h


def _onnx_div(a, b):
    # integer Div truncates toward zero (C semantics), float Div is true div
    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.integer):
        q = jnp.asarray(a) / jnp.asarray(b)
        return jnp.trunc(q).astype(jnp.asarray(a).dtype)
    return jnp.divide(a, b)


def _onnx_pow(a, b):
    b = jnp.asarray(b)
    if b.dtype != jnp.asarray(a).dtype:
        b = b.astype(jnp.asarray(a).dtype)
    return jnp.power(a, b)


for _name, _fn in [
    ("Add", jnp.add), ("Sub", jnp.subtract), ("Mul", jnp.multiply),
    ("Div", _onnx_div), ("Pow", _onnx_pow),
    ("And", jnp.logical_and), ("Or", jnp.logical_or), ("Xor", jnp.logical_xor),
]:
    OP_HANDLERS[_name] = _variadic(_fn)


@register_op("Mod")
def _onnx_mod(node, inputs, ctx):
    # fmod=1 truncates toward zero (C fmod); default follows the divisor's
    # sign (python %)
    fn = jnp.fmod if node.attr("fmod", 0) else jnp.mod
    return fn(inputs[0], inputs[1])

OP_HANDLERS["Min"] = _variadic(jnp.minimum)
OP_HANDLERS["Max"] = _variadic(jnp.maximum)
OP_HANDLERS["Sum"] = _variadic(jnp.add)


@register_op("Mean")
def _mean(node, inputs, ctx):
    return _variadic(jnp.add)(node, inputs, ctx) / len(inputs)


for _name, _u in [
    ("Abs", jnp.abs), ("Neg", jnp.negative), ("Exp", jnp.exp), ("Log", jnp.log),
    ("Sqrt", jnp.sqrt), ("Floor", jnp.floor), ("Ceil", jnp.ceil),
    ("Round", jnp.round), ("Sign", jnp.sign), ("Tanh", jnp.tanh),
    ("Sin", jnp.sin), ("Cos", jnp.cos), ("Tan", jnp.tan),
    ("Asin", jnp.arcsin), ("Acos", jnp.arccos), ("Atan", jnp.arctan),
    ("Sinh", jnp.sinh), ("Cosh", jnp.cosh),
    ("Asinh", jnp.arcsinh), ("Acosh", jnp.arccosh), ("Atanh", jnp.arctanh),
    ("Not", jnp.logical_not), ("Erf", lambda x: jax.scipy.special.erf(x)),
    ("Reciprocal", lambda x: 1.0 / x), ("Identity", lambda x: x),
    ("Relu", jax.nn.relu), ("Sigmoid", jax.nn.sigmoid),
    ("Softsign", jax.nn.soft_sign), ("IsNaN", jnp.isnan),
    ("Mish", lambda x: x * jnp.tanh(jax.nn.softplus(x))),
]:
    OP_HANDLERS[_name] = (lambda f: lambda node, inputs, ctx: f(inputs[0]))(_u)


@register_op("IsInf")
def _isinf(node, inputs, ctx):
    x = inputs[0]
    pos = jnp.isposinf(x) if node.attr("detect_positive", 1) else \
        jnp.zeros(x.shape, bool)
    neg = jnp.isneginf(x) if node.attr("detect_negative", 1) else \
        jnp.zeros(x.shape, bool)
    return jnp.logical_or(pos, neg)


@register_op("ThresholdedRelu")
def _thresholded_relu(node, inputs, ctx):
    alpha = node.attr("alpha", 1.0)
    return jnp.where(inputs[0] > alpha, inputs[0], 0.0)


@register_op("Shrink")
def _shrink(node, inputs, ctx):
    lambd = node.attr("lambd", 0.5)
    bias = node.attr("bias", 0.0)
    x = inputs[0]
    return jnp.where(x < -lambd, x + bias, jnp.where(x > lambd, x - bias,
                                                     jnp.zeros_like(x)))


@register_op("BitShift")
def _bitshift(node, inputs, ctx):
    x, y = inputs
    if node.attr("direction", "LEFT") == "LEFT":
        return jnp.left_shift(x, y)
    return jnp.right_shift(x, y)


@register_op("ReverseSequence")
def _reverse_sequence(node, inputs, ctx):
    x, seq_lens = inputs
    batch_axis = node.attr("batch_axis", 1)
    time_axis = node.attr("time_axis", 0)
    # one explicit permutation to (batch, time, *rest) — chained moveaxis
    # shifts the other axis's index when batch_axis > time_axis
    rest = [a for a in range(x.ndim) if a not in (batch_axis, time_axis)]
    perm = [batch_axis, time_axis] + rest
    xt = jnp.transpose(x, perm)

    def rev_row(row, ln):
        t = row.shape[0]
        idx = jnp.where(jnp.arange(t) < ln,
                        ln - 1 - jnp.arange(t), jnp.arange(t))
        return row[idx]

    out = jax.vmap(rev_row)(xt, seq_lens.astype(jnp.int32))
    inv = np.argsort(perm)
    return jnp.transpose(out, inv)

for _name, _cmp in [("Equal", jnp.equal), ("Greater", jnp.greater),
                    ("GreaterOrEqual", jnp.greater_equal),
                    ("Less", jnp.less), ("LessOrEqual", jnp.less_equal)]:
    OP_HANDLERS[_name] = (lambda f: lambda n, i, c: f(i[0], i[1]))(_cmp)


@register_op("LeakyRelu")
def _leaky(node, inputs, ctx):
    return jax.nn.leaky_relu(inputs[0], node.attr("alpha", 0.01))


@register_op("Elu")
def _elu(node, inputs, ctx):
    return jax.nn.elu(inputs[0], node.attr("alpha", 1.0))


@register_op("Selu")
def _selu(node, inputs, ctx):
    alpha = node.attr("alpha", 1.6732632423543772)
    gamma = node.attr("gamma", 1.0507009873554805)
    x = inputs[0]
    return gamma * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@register_op("Celu")
def _celu(node, inputs, ctx):
    return jax.nn.celu(inputs[0], node.attr("alpha", 1.0))


@register_op("Softplus")
def _softplus(node, inputs, ctx):
    return jax.nn.softplus(inputs[0])


@register_op("HardSigmoid")
def _hardsigmoid(node, inputs, ctx):
    a, b = node.attr("alpha", 0.2), node.attr("beta", 0.5)
    return jnp.clip(a * inputs[0] + b, 0.0, 1.0)


@register_op("HardSwish")
def _hardswish(node, inputs, ctx):
    x = inputs[0]
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@register_op("Gelu")
def _gelu(node, inputs, ctx):
    approx = node.attr("approximate", "none") == "tanh"
    return jax.nn.gelu(inputs[0], approximate=approx)


# -- com.microsoft contrib ops (ORT transformer-optimizer output) ------------
# Real BERT-class deployments usually ship through onnxruntime's
# transformer optimizer, which fuses subgraphs into contrib ops
# (parity target: ONNXModel runs ORT, which executes these natively).
# Dispatch is by op_type, domain-agnostic — same table.

@register_op("FusedMatMul")
def _fused_matmul(node, inputs, ctx):
    a, b = inputs
    if node.attr("transBatchA", 0) or node.attr("transBatchB", 0):
        # batch-dim transpose is a different permutation than transA/transB;
        # silently ignoring it would multiply the wrong operands
        raise UnsupportedOp("FusedMatMul with transBatchA/transBatchB")
    if node.attr("transA", 0):
        a = jnp.swapaxes(a, -1, -2)
    if node.attr("transB", 0):
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b) * node.attr("alpha", 1.0)


@register_op("BiasGelu")
def _bias_gelu(node, inputs, ctx):
    return jax.nn.gelu(inputs[0] + inputs[1], approximate=False)


@register_op("FastGelu")
def _fast_gelu(node, inputs, ctx):
    x = inputs[0]
    if len(inputs) > 1 and inputs[1] is not None:
        x = x + inputs[1]
    return jax.nn.gelu(x, approximate=True)


@register_op("QuickGelu")
def _quick_gelu(node, inputs, ctx):
    alpha = node.attr("alpha", 1.702)
    return inputs[0] * jax.nn.sigmoid(alpha * inputs[0])


def _layernorm_last(x, gamma, beta, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = (xf - mu) * inv * gamma
    if beta is not None:
        y = y + beta
    return y.astype(x.dtype), mu, inv


@register_op("SkipLayerNormalization")
def _skip_layernorm(node, inputs, ctx):
    x, skip = inputs[0], inputs[1]
    gamma = inputs[2]
    beta = inputs[3] if len(inputs) > 3 else None
    bias = inputs[4] if len(inputs) > 4 else None
    total = x + skip
    if bias is not None:
        total = total + bias
    y, mu, inv = _layernorm_last(total, gamma, beta,
                                 node.attr("epsilon", 1e-12))
    # outputs: out, (mean), (inv_std_var), (input_skip_bias_sum)
    return y, mu[..., 0], inv[..., 0], total


@register_op("EmbedLayerNormalization")
def _embed_layernorm(node, inputs, ctx):
    (ids, seg_ids, word_emb, pos_emb) = inputs[0], inputs[1], inputs[2], inputs[3]
    seg_emb = inputs[4] if len(inputs) > 4 else None
    gamma = inputs[5] if len(inputs) > 5 else None
    beta = inputs[6] if len(inputs) > 6 else None
    mask = inputs[7] if len(inputs) > 7 else None
    pos_ids = inputs[8] if len(inputs) > 8 else None
    B, S = ids.shape
    x = jnp.take(word_emb, ids.astype(jnp.int32), axis=0)
    if pos_ids is None:
        x = x + pos_emb[:S][None, :, :]
    else:
        x = x + jnp.take(pos_emb, pos_ids.astype(jnp.int32), axis=0)
    if seg_emb is not None and seg_ids is not None:
        x = x + jnp.take(seg_emb, seg_ids.astype(jnp.int32), axis=0)
    y, _mu, _inv = _layernorm_last(x, gamma, beta,
                                   node.attr("epsilon", 1e-12))
    mask_index = (jnp.sum(mask.astype(jnp.int32), axis=1)
                  if mask is not None
                  else jnp.full((B,), S, jnp.int32))
    return y, mask_index.astype(jnp.int32), x


def _decode_mask_index(mask_index, B, S, op_name):
    """ORT mask forms shared by Attention/MultiHeadAttention:
    (B, S) 0/1 mask or (B,) right-pad lengths → (B, S) bool."""
    if mask_index is None:
        return None
    if mask_index.ndim == 2:
        return mask_index.astype(bool)
    if mask_index.ndim == 1 and mask_index.shape[0] == B:
        return (jnp.arange(S)[None, :]
                < mask_index.astype(jnp.int32)[:, None])
    raise UnsupportedOp(f"{op_name} mask_index shape {mask_index.shape}")


def _attn_scale(node, head_size):
    """ORT reads GetAttrOrDefault("scale", 0.0f) and substitutes
    1/sqrt(head_size) when the stored value is 0 — so an explicitly
    serialized scale=0.0 means "unset", not "zero the logits"."""
    s = node.attr("scale", 0.0)
    return float(s) if s else 1.0 / float(head_size) ** 0.5


def _attention_core(q, k, v, kv_mask, causal, scale, pair_mask=None):
    """(B, H, S, D) attention shared by the fused ops: Pallas flash kernel
    on TPU, dense XLA elsewhere. ``pair_mask`` is an optional (Sq, Sk)
    boolean mask (the ai.onnx 2-D form, trailing-dim aligned)."""
    from ..utils.device import is_tpu
    if is_tpu() and q.shape[2] == k.shape[2] and pair_mask is None:
        from ..ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, kv_mask=kv_mask,
                               scale=scale)
    S_q, S_k = q.shape[2], k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    neg = jnp.float32(-1e30)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, neg)
    if pair_mask is not None:
        s = jnp.where(pair_mask[None, None, :, :], s, neg)
    if causal:
        # query i sees keys j <= i + (S_k - S_q): ORT's convention aligns
        # the diagonal to the END of the key sequence when lengths differ
        tri = jnp.tril(jnp.ones((S_q, S_k), bool), k=S_k - S_q)
        s = jnp.where(tri[None, None], s, neg)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _rms_norm(x, gamma, eps):
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * inv * gamma).astype(x.dtype), inv


@register_op("SimplifiedLayerNormalization")
def _simplified_layernorm(node, inputs, ctx):
    # RMS norm (the Llama-family normalization; ORT emits this contrib op)
    if node.attr("axis", -1) not in (-1, inputs[0].ndim - 1):
        raise UnsupportedOp("SimplifiedLayerNormalization over a "
                            "non-last axis")
    y, _ = _rms_norm(inputs[0], inputs[1], node.attr("epsilon", 1e-6))
    return y


@register_op("RMSNormalization")
def _rms_normalization(node, inputs, ctx):
    # standard ai.onnx RMSNormalization (opset 23) — same math
    if node.attr("axis", -1) not in (-1, inputs[0].ndim - 1):
        raise UnsupportedOp("RMSNormalization over a non-last axis")
    y, _ = _rms_norm(inputs[0], inputs[1], node.attr("epsilon", 1e-5))
    return y


@register_op("SkipSimplifiedLayerNormalization")
def _skip_simplified_layernorm(node, inputs, ctx):
    x, skip, gamma = inputs[0], inputs[1], inputs[2]
    bias = inputs[3] if len(inputs) > 3 else None
    if len(node.output) > 1 and node.output[1]:
        # RMS norm has no mean; a consumer of output 1 would receive None
        raise UnsupportedOp(
            "SkipSimplifiedLayerNormalization mean output")
    total = x + skip
    if bias is not None:
        total = total + bias
    y, inv = _rms_norm(total, gamma, node.attr("epsilon", 1e-12))
    return y, None, inv[..., 0], total


@register_op("RotaryEmbedding")
def _rotary_embedding(node, inputs, ctx):
    """com.microsoft RotaryEmbedding: (B, S, H) or (B, heads, S, D) input
    with position_ids + cos/sin caches; ``interleaved`` pairs (x0,x1) as
    adjacent elements, else split-half rotation."""
    if node.domain == "com.microsoft":
        x, pos_ids, cos_cache, sin_cache = inputs[:4]
    else:
        # standard ai.onnx RotaryEmbedding (opset 23) orders the caches
        # before position_ids
        x, cos_cache, sin_cache = inputs[:3]
        pos_ids = inputs[3] if len(inputs) > 3 else None
        if pos_ids is None:
            raise UnsupportedOp("RotaryEmbedding without position_ids")
    interleaved = bool(node.attr("interleaved", 0))
    rot_dim = 2 * cos_cache.shape[-1]
    orig_rank = x.ndim
    if orig_rank == 3:
        heads = node.attr("num_heads", 0)
        if not heads:
            raise UnsupportedOp("RotaryEmbedding 3-D input without num_heads")
        B, S, H = x.shape
        x = x.reshape(B, S, heads, H // heads).transpose(0, 2, 1, 3)
    B, NH, S, D = x.shape
    if pos_ids.ndim == 1 and pos_ids.shape[0] == 1:
        # spec: shape (1) is a per-sequence OFFSET — position s rotates at
        # pos_ids[0] + s (the decode-phase form), not a constant position
        pos_ids = pos_ids[0] + jnp.arange(S)[None, :]
        pos_ids = jnp.broadcast_to(pos_ids, (B, S))
    elif pos_ids.ndim != 2:
        raise UnsupportedOp(
            f"RotaryEmbedding position_ids shape {pos_ids.shape}")
    cos = jnp.take(cos_cache, pos_ids.astype(jnp.int32), axis=0)  # (B,S,rd/2)
    sin = jnp.take(sin_cache, pos_ids.astype(jnp.int32), axis=0)
    cos = cos[:, None, :, :]
    sin = sin[:, None, :, :]
    xr, xpass = x[..., :rot_dim], x[..., rot_dim:]
    out = jnp.concatenate(
        [_rope_rotate(xr, cos, sin, interleaved), xpass], axis=-1)
    if orig_rank == 3:
        out = out.transpose(0, 2, 1, 3).reshape(B, S, NH * D)
    return out


@register_op("MultiHeadAttention")
def _msft_mha(node, inputs, ctx):
    """com.microsoft MultiHeadAttention: separate (B, S, H) q/k/v inputs.
    Supported surface: optional packed bias, key_padding_mask as (B, S_kv)
    0/1 or (B,) lengths, additive attention_bias, and past_key/past_value
    concatenated along the sequence axis (present outputs carry the grown
    cache — MHA's spec is concat-grow, unlike GQA's static buffers)."""
    if node.domain != "com.microsoft":
        raise UnsupportedOp(
            f"MultiHeadAttention in domain {node.domain!r}")
    q_in, k_in, v_in = inputs[0], inputs[1], inputs[2]
    bias = inputs[3] if len(inputs) > 3 else None
    mask_index = inputs[4] if len(inputs) > 4 else None
    attn_bias = inputs[5] if len(inputs) > 5 else None
    past_k = inputs[6] if len(inputs) > 6 else None
    past_v = inputs[7] if len(inputs) > 7 else None
    if k_in.ndim != 3 or v_in.ndim != 3:
        raise UnsupportedOp("MultiHeadAttention packed/5-D KV layouts")
    heads = node.attr("num_heads")
    if heads is None:
        raise UnsupportedOp("MultiHeadAttention without num_heads")
    B, Sq, H = q_in.shape
    Sk = k_in.shape[1]
    D = H // heads
    if bias is not None:
        qb, kb, vb = bias[:H], bias[H:2 * H], bias[2 * H:]
        q_in, k_in, v_in = q_in + qb, k_in + kb, v_in + vb

    def split(t, S):
        return t.reshape(B, S, heads, D).transpose(0, 2, 1, 3)

    q, k, v = split(q_in, Sq), split(k_in, Sk), split(v_in, Sk)
    if past_k is not None:
        k = jnp.concatenate([past_k, k], axis=2)
        v = jnp.concatenate([past_v, v], axis=2)
        Sk = k.shape[2]
    present_k, present_v = k, v
    scale = _attn_scale(node, D)
    kv_mask = _decode_mask_index(mask_index, B, Sk, "MultiHeadAttention")
    causal = bool(node.attr("unidirectional", 0))
    if attn_bias is not None:
        out = _dense_masked_attn(q, k, v, _qk_valid_mask(Sq, Sk, kv_mask,
                                                         causal),
                                 scale, bias=attn_bias)
    else:
        out = _attention_core(q, k, v, kv_mask, causal, scale)
    out = out.transpose(0, 2, 1, 3).reshape(B, Sq, H)
    if len(node.output) > 1:
        return out, present_k, present_v
    return out


def _std_attention(node, inputs, ctx):
    """Standard ai.onnx Attention (opset 23): Q (B, Hq, Sq, D), K/V
    (B, Hkv, Skv, D) — 4-D form, or 3-D (B, S, H·D) with the
    q_num_heads/kv_num_heads attributes; GQA via Hq % Hkv == 0 head
    repetition; optional past_key/past_value concatenated per the spec
    (present outputs carry the grown cache)."""
    q, k, v = inputs[0], inputs[1], inputs[2]
    attn_mask = inputs[3] if len(inputs) > 3 else None
    past_k = inputs[4] if len(inputs) > 4 else None
    past_v = inputs[5] if len(inputs) > 5 else None
    three_d = q.ndim == 3
    if three_d:
        qnh = node.attr("q_num_heads", 0)
        kvnh = node.attr("kv_num_heads", 0)
        if not qnh or not kvnh:
            raise UnsupportedOp("ai.onnx Attention 3-D form without "
                                "q_num_heads/kv_num_heads")
        B, Sq, HD = q.shape
        D = HD // qnh
        Dv = v.shape[2] // kvnh      # spec allows v_head_size != head_size
        q = q.reshape(B, Sq, qnh, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, k.shape[1], kvnh, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, v.shape[1], kvnh, Dv).transpose(0, 2, 1, 3)
    elif q.ndim != 4:
        raise UnsupportedOp(f"ai.onnx Attention rank-{q.ndim} inputs")
    if past_k is not None:
        # spec: present = concat(past, current) along the sequence axis
        k = jnp.concatenate([past_k, k], axis=2)
        v = jnp.concatenate([past_v, v], axis=2)
    present_k, present_v = k, v
    Hq, Hkv = q.shape[1], k.shape[1]
    if Hq % Hkv:
        raise UnsupportedOp(f"Attention q_num_heads {Hq} not a multiple of "
                            f"kv_num_heads {Hkv}")
    if Hkv != Hq:                      # GQA: repeat KV heads
        k = jnp.repeat(k, Hq // Hkv, axis=1)
        v = jnp.repeat(v, Hq // Hkv, axis=1)
    causal = bool(node.attr("is_causal", 0))
    if len(node.output) > 3 and node.output[3]:
        raise UnsupportedOp("ai.onnx Attention qk_matmul_output")
    if node.attr("qk_matmul_output_mode", 0):
        raise UnsupportedOp("ai.onnx Attention qk_matmul_output_mode != 0")
    # standard ai.onnx Attention (unlike ORT contrib): the default applies
    # only when the attribute is ABSENT — an explicit 0.0 is honored
    s = node.attr("scale", None)
    scale = float(s) if s is not None else 1.0 / float(q.shape[-1]) ** 0.5
    softcap = float(node.attr("softcap", 0.0))
    pair_mask = None
    if attn_mask is not None:
        # spec: the mask broadcasts against (B, H, Sq, Skv) aligned at the
        # TRAILING dims, so a 2-D mask is (Sq, Skv) — not a padding mask
        if attn_mask.ndim == 2 and attn_mask.dtype == jnp.bool_ \
                and attn_mask.shape == (q.shape[2], k.shape[2]):
            pair_mask = attn_mask
        else:
            raise UnsupportedOp(
                f"Attention mask shape {attn_mask.shape} dtype "
                f"{attn_mask.dtype} (only boolean (q_seq, kv_seq))")
    if softcap:
        Sq, Sk = q.shape[2], k.shape[2]
        mask = jnp.ones((Sq, Sk), bool)
        if pair_mask is not None:
            mask = mask & pair_mask
        if causal:
            mask = mask & jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        out = _dense_masked_attn(q, k, v, mask[None, None], scale, softcap)
    else:
        out = _attention_core(q, k, v, None, causal, scale,
                              pair_mask=pair_mask)
    if three_d:
        B, _, Sq, Do = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(B, Sq, Hq * Do)
    if len(node.output) > 1:
        return out, present_k, present_v
    return out


def _qk_valid_mask(Sq, Sk, kv_mask, causal):
    """(1|B, 1, Sq, Sk) boolean validity mask from the shared ORT
    conventions: optional (B, Sk) key-padding mask, causal diagonal
    end-aligned to the key sequence (same convention as
    :func:`_attention_core`)."""
    mask = jnp.ones((1, 1, Sq, Sk), bool)
    if kv_mask is not None:
        mask = mask & kv_mask[:, None, None, :]
    if causal:
        mask = mask & jnp.tril(jnp.ones((Sq, Sk), bool),
                               k=Sk - Sq)[None, None]
    return mask


def _rope_rotate(xr, cos, sin, interleaved):
    """The rotation core shared by RotaryEmbedding and fused-attention
    rotary: ``xr`` (..., rot_dim) with broadcastable half-dim cos/sin."""
    if interleaved:
        x0, x1 = xr[..., 0::2], xr[..., 1::2]
        r0 = x0 * cos - x1 * sin
        r1 = x0 * sin + x1 * cos
        return jnp.stack([r0, r1], axis=-1).reshape(xr.shape)
    half = xr.shape[-1] // 2
    x0, x1 = xr[..., :half], xr[..., half:]
    return jnp.concatenate([x0 * cos - x1 * sin,
                            x0 * sin + x1 * cos], axis=-1)


def _apply_rope4(x, pos, cos_cache, sin_cache, interleaved):
    """Rotate a (B, nh, S, D) tensor at absolute positions ``pos`` (B, S)
    using half-dim cos/sin caches (max_pos, rot_dim/2)."""
    rot_dim = 2 * cos_cache.shape[-1]
    cos = jnp.take(cos_cache, pos.astype(jnp.int32), axis=0)[:, None]
    sin = jnp.take(sin_cache, pos.astype(jnp.int32), axis=0)[:, None]
    xr, xpass = x[..., :rot_dim], x[..., rot_dim:]
    return jnp.concatenate(
        [_rope_rotate(xr, cos, sin, interleaved), xpass], axis=-1)


def _dense_masked_attn(q, k, v, mask, scale, softcap=0.0,
                       smooth_softmax=False, bias=None):
    """(B, Hq, Sq, D) × (B, Hkv, Sk, D) attention with a (B, 1|H, Sq, Sk)
    boolean mask, optional logit softcapping, and optional ORT
    smooth-softmax (an implicit extra zero logit in the denominator) —
    the decode-phase path where Sq is tiny and flash brings nothing.

    GQA (Hkv < Hq) runs as a GROUPED einsum over (group, rep) head axes —
    the KV cache is never materialized ``rep`` times, which is the whole
    point of an in-place static cache on the decode hot path."""
    B, Hq, Sq, D = q.shape
    Hkv = k.shape[1]
    rep = Hq // Hkv
    qg = q.reshape(B, Hkv, rep, Sq, D)
    s = jnp.einsum("bgrqd,bgkd->bgrqk", qg, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        # additive attention_bias (B|1, H|1, Sq, Sk), ORT semantics: added
        # to the scaled scores before masking/softmax
        bb = jnp.broadcast_to(bias, (bias.shape[0], Hq, Sq, s.shape[-1]))
        s = s + bb.reshape(bias.shape[0], Hkv, rep, Sq, s.shape[-1]) \
            .astype(jnp.float32)
    if softcap:
        s = softcap * jnp.tanh(s / softcap)
    if mask.ndim == 4:
        mask = mask[:, :, None]          # (B, 1|Hkv, 1, Sq, Sk)
    s = jnp.where(mask, s, jnp.float32(-1e30))
    if smooth_softmax:
        # softmax_i = exp(s_i) / (1 + Σ exp(s_j)): stabilize against
        # m = max(s, 0) so the implicit zero logit is included
        m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), 0.0)
        e = jnp.exp(s - m)
        p = (e / (jnp.exp(-m) + jnp.sum(e, axis=-1, keepdims=True))) \
            .astype(v.dtype)
    else:
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    out = jnp.einsum("bgrqk,bgkd->bgrqd", p, v)
    return out.reshape(B, Hq, Sq, v.shape[-1])


@register_op("GroupQueryAttention")
def _gqa(node, inputs, ctx):
    """com.microsoft GroupQueryAttention — prefill AND decode (kv-cache)
    forms, packed or separate QKV, optional fused rotary embedding.

    The decode design is TPU-first: the past_key/past_value buffers keep
    their STATIC (B, kv_heads, S_max, D) shape and the new K/V chunk is
    written in place with ``lax.dynamic_update_slice`` per batch row — no
    concat-and-grow dynamic shapes, which is exactly the cache layout a
    jitted decode loop wants (XLA donates the buffer and updates in place).
    Parity anchor: onnxruntime contrib GroupQueryAttention, the op the
    reference's ONNXModel path executes via ORT CUDA
    (``deep-learning/.../onnx/ONNXModel.scala:173-193``)."""
    q_in, k_in, v_in = inputs[0], inputs[1], inputs[2]
    past_k = inputs[3] if len(inputs) > 3 else None
    past_v = inputs[4] if len(inputs) > 4 else None
    seqlens_k = inputs[5] if len(inputs) > 5 else None
    cos_cache = inputs[7] if len(inputs) > 7 else None
    sin_cache = inputs[8] if len(inputs) > 8 else None
    heads = node.attr("num_heads")
    kv_heads = node.attr("kv_num_heads")
    if not heads or not kv_heads:
        raise UnsupportedOp("GroupQueryAttention without num_heads/"
                            "kv_num_heads")
    if node.attr("local_window_size", -1) != -1:
        raise UnsupportedOp("GroupQueryAttention local_window_size")
    softcap = float(node.attr("softcap", 0.0))
    smooth = bool(node.attr("smooth_softmax", 0))
    do_rotary = bool(node.attr("do_rotary", 0))
    interleaved = bool(node.attr("rotary_interleaved", 0))
    if do_rotary and (cos_cache is None or sin_cache is None):
        raise UnsupportedOp("GroupQueryAttention do_rotary without "
                            "cos/sin caches")
    B, S = q_in.shape[0], q_in.shape[1]
    if k_in is None or v_in is None:
        # packed layout: query carries (heads + 2*kv_heads)·D lanes
        D = q_in.shape[2] // (heads + 2 * kv_heads)
        q_in, k_in, v_in = jnp.split(
            q_in, [heads * D, (heads + kv_heads) * D], axis=2)
    D = q_in.shape[2] // heads

    def split(t, nh):
        return t.reshape(B, S, nh, D).transpose(0, 2, 1, 3)

    q, k_new, v_new = split(q_in, heads), split(k_in, kv_heads), \
        split(v_in, kv_heads)
    scale = _attn_scale(node, D)
    rep = heads // kv_heads
    if seqlens_k is not None:
        # seqlens_k[b] = total valid key count (past + new) - 1
        last = seqlens_k.astype(jnp.int32).reshape(-1)      # (B,)
    else:
        last = jnp.full((B,), S - 1, jnp.int32)
    # clamped at 0: a right-padded prefill row (valid < S) has its new
    # tokens at positions 0..valid-1 with the tail masked by `last`, NOT at
    # negative positions — matching ORT's slot-i-is-position-i prefill
    past_len = jnp.maximum(last + 1 - S, 0)                  # (B,)
    if do_rotary:
        pos = past_len[:, None] + jnp.arange(S)[None, :]     # (B, S)
        q = _apply_rope4(q, pos, cos_cache, sin_cache, interleaved)
        k_new = _apply_rope4(k_new, pos, cos_cache, sin_cache, interleaved)

    if past_k is not None:
        # decode: write the new chunk into the static cache buffer
        S_max = past_k.shape[2]

        def write(buf, chunk, start):
            return jax.lax.dynamic_update_slice(buf, chunk, (0, start, 0))

        present_k = jax.vmap(write)(past_k, k_new, past_len)
        present_v = jax.vmap(write)(past_v, v_new, past_len)
        # query i (absolute position past_len+i) sees keys j <= past_len+i
        # (grouped attention: the cache is NOT repeated across q heads)
        mask = (jnp.arange(S_max)[None, None, None, :]
                <= (past_len[:, None, None, None]
                    + jnp.arange(S)[None, None, :, None]))
        out = _dense_masked_attn(q, present_k, present_v, mask, scale,
                                 softcap, smooth)
    else:
        present_k, present_v = k_new, v_new
        if softcap or smooth:
            mask = ((jnp.arange(S)[None, None, None, :]
                     <= last[:, None, None, None])
                    & (jnp.arange(S)[None, None, :, None]
                       >= jnp.arange(S)[None, None, None, :]))
            out = _dense_masked_attn(q, k_new, v_new, mask, scale,
                                     softcap, smooth)
        else:
            k = jnp.repeat(k_new, rep, axis=1)
            v = jnp.repeat(v_new, rep, axis=1)
            kv_mask = jnp.arange(S)[None, :] <= last[:, None]
            # GQA is causal by construction in ORT's decoder graphs
            out = _attention_core(q, k, v, kv_mask, True, scale)
    out = out.transpose(0, 2, 1, 3).reshape(B, S, heads * D)
    if len(node.output) > 1:
        return out, present_k, present_v
    return out


@register_op("Attention")
def _msft_attention(node, inputs, ctx):
    """ORT fused multi-head attention. Supported surface: equal q/k/v hidden
    sizes, no past state; mask as (B, S) 0/1 or (B,) right-pad lengths;
    ``unidirectional`` → causal. Runs the Pallas flash kernel on TPU, dense
    XLA attention elsewhere."""
    if node.domain != "com.microsoft":
        # the standard ai.onnx Attention (opset 23) takes Q/K/V tensors
        return _std_attention(node, inputs, ctx)
    x, w = inputs[0], inputs[1]
    b = inputs[2] if len(inputs) > 2 else None
    mask_index = inputs[3] if len(inputs) > 3 else None
    if len(inputs) > 4 and inputs[4] is not None:
        raise UnsupportedOp("Attention with past state")
    attn_bias = inputs[5] if len(inputs) > 5 else None
    if node.attr("do_rotary", 0):
        raise UnsupportedOp("Attention with do_rotary (use a separate "
                            "RotaryEmbedding node)")
    heads = node.attr("num_heads")
    if heads is None:
        raise UnsupportedOp("Attention without num_heads")
    qkv_sizes = node.attr("qkv_hidden_sizes")
    if qkv_sizes and len(set(qkv_sizes)) != 1:
        raise UnsupportedOp(f"Attention qkv_hidden_sizes {qkv_sizes}")
    causal = bool(node.attr("unidirectional", 0))
    B, S, _ = x.shape
    hidden = w.shape[1] // 3
    D = hidden // heads
    qkv = jnp.matmul(x, w)                              # (B, S, 3*hidden)
    if b is not None:                                   # bias is optional
        qkv = qkv + b
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def split_heads(t):
        return t.reshape(B, S, heads, D).transpose(0, 2, 1, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    scale = _attn_scale(node, D)
    kv_mask = _decode_mask_index(mask_index, B, S, "Attention")
    if attn_bias is not None:
        # additive attention_bias / extra_add_qk (B|1, H|1, S, S)
        ctx_out = _dense_masked_attn(q, k, v, _qk_valid_mask(S, S, kv_mask,
                                                             causal),
                                     scale, bias=attn_bias)
    else:
        ctx_out = _attention_core(q, k, v, kv_mask, causal, scale)
    return ctx_out.transpose(0, 2, 1, 3).reshape(B, S, hidden)


@register_op("PRelu")
def _prelu(node, inputs, ctx):
    x, slope = inputs
    return jnp.where(x >= 0, x, slope * x)


@register_op("Clip")
def _clip(node, inputs, ctx):
    x = inputs[0]
    lo = node.attr("min") if ctx.opset < 11 else (inputs[1] if len(inputs) > 1 and inputs[1] is not None else None)
    hi = node.attr("max") if ctx.opset < 11 else (inputs[2] if len(inputs) > 2 and inputs[2] is not None else None)
    return jnp.clip(x, lo, hi)


@register_op("Dropout")
def _dropout(node, inputs, ctx):
    x = inputs[0]
    if len(node.output) > 1:
        return x, jnp.ones_like(x, dtype=bool)
    return x


@register_op("Cast")
def _cast(node, inputs, ctx):
    to = ONNX_TO_NUMPY[node.attr("to")]
    x = inputs[0]
    if isinstance(x, np.ndarray):
        return x.astype(to)
    return x.astype(to)


@register_op("CastLike")
def _castlike(node, inputs, ctx):
    return inputs[0].astype(jnp.asarray(inputs[1]).dtype)


@register_op("Where")
def _where(node, inputs, ctx):
    return jnp.where(inputs[0], inputs[1], inputs[2])


# -- matmul family -----------------------------------------------------------

@register_op("MatMul")
def _matmul(node, inputs, ctx):
    return jnp.matmul(inputs[0], inputs[1],
                      preferred_element_type=jnp.asarray(inputs[0]).dtype)


@register_op("Gemm")
def _gemm(node, inputs, ctx):
    a, b = inputs[0], inputs[1]
    if node.attr("transA", 0):
        a = jnp.swapaxes(a, -1, -2)
    if node.attr("transB", 0):
        b = jnp.swapaxes(b, -1, -2)
    y = node.attr("alpha", 1.0) * jnp.matmul(a, b)
    if len(inputs) > 2 and inputs[2] is not None:
        y = y + node.attr("beta", 1.0) * inputs[2]
    return y


@register_op("Einsum")
def _einsum(node, inputs, ctx):
    return jnp.einsum(node.attr("equation"), *inputs)


# -- conv / pool -------------------------------------------------------------

def _onnx_pads_to_lax(pads: Optional[Sequence[int]], rank: int,
                      auto_pad: str, x_shape, k_shape, strides, dilations):
    if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        out = []
        for i in range(rank):
            eff_k = (k_shape[i] - 1) * dilations[i] + 1
            out_dim = -(-x_shape[i] // strides[i])
            total = max(0, (out_dim - 1) * strides[i] + eff_k - x_shape[i])
            lo = total // 2 if auto_pad == "SAME_UPPER" else (total + 1) // 2
            out.append((lo, total - lo))
        return out
    if pads is None:
        return [(0, 0)] * rank
    return [(pads[i], pads[i + rank]) for i in range(rank)]


def _conv_nhwc_enabled() -> bool:
    """Channels-last convs (``MMLSPARK_TPU_CONV_NHWC``: 1/0/auto).

    ONNX graphs are NCHW by convention, but the TPU's conv units want
    channels on lanes: measured on v5e, the ResNet stem runs ~1.5-3x
    faster as NHWC. The op still CONSUMES and PRODUCES NCHW tensors —
    each conv locally transposes in/out, and XLA's transpose folding
    cancels the pairs between consecutive convs/elementwise ops, so the
    effective graph is channels-last end-to-end without a graph rewrite.
    """
    flag = os.environ.get("MMLSPARK_TPU_CONV_NHWC", "auto").lower()
    if flag in ("1", "true", "on"):
        return True
    if flag in ("0", "false", "off"):
        return False
    from ..utils.device import is_tpu
    return is_tpu()


def _conv_raw(node, x, w, preferred=None):
    """Shared Conv body (attrs → lax.conv_general_dilated), without bias —
    QLinearConv reuses it with integer operands + int32 accumulation."""
    x, w = jnp.asarray(x), jnp.asarray(w)
    rank = w.ndim - 2
    strides = node.attr("strides", [1] * rank)
    dilations = node.attr("dilations", [1] * rank)
    group = node.attr("group", 1)
    auto_pad = node.attr("auto_pad", "NOTSET")
    k_shape = node.attr("kernel_shape", list(w.shape[2:]))
    pads = _onnx_pads_to_lax(node.attr("pads"), rank, auto_pad,
                             x.shape[2:], k_shape, strides, dilations)
    spatial = "DHW"[-rank:] if rank <= 3 else None
    if spatial is None:
        raise UnsupportedOp(f"Conv rank {rank}")
    if rank == 2 and _conv_nhwc_enabled():
        xh = jnp.transpose(x, (0, 2, 3, 1))
        wh = jnp.transpose(w, (2, 3, 1, 0))
        dn = lax.conv_dimension_numbers(xh.shape, wh.shape,
                                        ("NHWC", "HWIO", "NHWC"))
        out = lax.conv_general_dilated(
            xh, wh, window_strides=tuple(strides), padding=pads,
            rhs_dilation=tuple(dilations), dimension_numbers=dn,
            feature_group_count=group,
            preferred_element_type=preferred or x.dtype)
        return jnp.transpose(out, (0, 3, 1, 2))
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, (f"NC{spatial}", f"OI{spatial}", f"NC{spatial}"))
    return lax.conv_general_dilated(
        x, w, window_strides=tuple(strides), padding=pads,
        rhs_dilation=tuple(dilations), dimension_numbers=dn,
        feature_group_count=group,
        preferred_element_type=preferred or x.dtype)


@register_op("Conv")
def _conv(node, inputs, ctx):
    out = _conv_raw(node, inputs[0], inputs[1])
    if len(inputs) > 2 and inputs[2] is not None:
        b = inputs[2]
        rank = jnp.asarray(inputs[1]).ndim - 2
        out = out + b.reshape((1, -1) + (1,) * rank)
    return out


@register_op("FusedConv")
def _fused_conv(node, inputs, ctx):
    """ORT contrib ``com.microsoft.FusedConv``: Conv (+ optional residual
    ``Z`` input) with the activation folded in by ORT's CNN graph
    optimizer — optimized CNN exports carry these instead of Conv+Relu
    pairs. XLA fuses the activation anyway; the handler exists so such
    graphs load at all."""
    out = _conv(node, inputs[:3], ctx)
    if len(inputs) > 3 and inputs[3] is not None:
        out = out + inputs[3]
    act = node.attr("activation", "")
    if isinstance(act, bytes):
        act = act.decode()
    p = [float(v) for v in node.attr("activation_params", [])]
    if not act:
        return out
    if act == "Relu":
        return jnp.maximum(out, 0)
    if act == "Tanh":
        return jnp.tanh(out)
    if act == "Sigmoid":
        return jax.nn.sigmoid(out)
    if act == "LeakyRelu":
        alpha = p[0] if p else 0.01
        return jnp.where(out < 0, alpha * out, out)
    if act == "Clip":
        return jnp.clip(out, p[0], p[1])
    if act == "HardSigmoid":
        a = p[0] if len(p) > 0 else 0.2
        b = p[1] if len(p) > 1 else 0.5
        return jnp.clip(a * out + b, 0.0, 1.0)
    raise UnsupportedOp(f"FusedConv activation {act!r}")


@register_op("RelativePositionBias")
def _relative_position_bias(node, inputs, ctx):
    """ORT contrib ``com.microsoft.RelativePositionBias`` — T5's bucketed
    relative attention bias as one op (T5 exports through ORT's
    transformer optimizer carry it). Output (1, num_heads, q_len, k_len)
    gathered from the (num_buckets, num_heads) bias table with the T5
    log-bucketing: near offsets get exact buckets, far offsets share
    logarithmically-spaced ones up to ``max_distance``."""
    table = jnp.asarray(inputs[0])               # (num_buckets, num_heads)
    q_len = int(np.asarray(_concrete(inputs[1], "RelativePositionBias "
                                     "query_length")).ravel()[0])
    k_len = int(np.asarray(_concrete(inputs[2], "RelativePositionBias "
                                     "key_length")).ravel()[0])
    num_buckets = int(table.shape[0])
    max_distance = int(node.attr("max_distance", 128))
    bidirectional = bool(node.attr("is_bidirectional", 0))
    context = jnp.arange(q_len)[:, None]
    memory = jnp.arange(k_len)[None, :]
    n = context - memory                         # = -(memory - context)
    ret = jnp.zeros((q_len, k_len), jnp.int32)
    nb = num_buckets
    if bidirectional:
        nb = num_buckets // 2
        ret = ret + (n < 0).astype(jnp.int32) * nb
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = nb // 2
    large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / np.log(max_distance / max_exact)
        * (nb - max_exact)).astype(jnp.int32)
    large = jnp.minimum(large, nb - 1)
    bucket = ret + jnp.where(n < max_exact, n, large)
    return table[bucket].transpose(2, 0, 1)[None]    # (1, H, q, k)


@register_op("ConvTranspose")
def _conv_transpose(node, inputs, ctx):
    x, w = inputs[0], inputs[1]
    rank = jnp.asarray(w).ndim - 2
    strides = tuple(node.attr("strides", [1] * rank))
    dilations = tuple(node.attr("dilations", [1] * rank))
    group = node.attr("group", 1)
    if group != 1:
        raise UnsupportedOp("grouped ConvTranspose")
    pads = node.attr("pads", [0] * (2 * rank))
    output_padding = node.attr("output_padding", [0] * rank)
    spatial = "DHW"[-rank:]
    dn = lax.conv_dimension_numbers(
        jnp.asarray(x).shape, jnp.asarray(w).shape,
        (f"NC{spatial}", f"IO{spatial}", f"NC{spatial}"))
    # lax.conv_transpose padding: ONNX pads shrink the output
    pad_cfg = [(dilations[i] * (jnp.asarray(w).shape[2 + i] - 1) - pads[i],
                dilations[i] * (jnp.asarray(w).shape[2 + i] - 1) - pads[i + rank]
                + output_padding[i])
               for i in range(rank)]
    return lax.conv_general_dilated(
        x, w, window_strides=(1,) * rank, padding=pad_cfg,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=dn, transpose_kernel=True)


def _pool(node, inputs, ctx, reducer, init, is_avg=False):
    x = jnp.asarray(inputs[0])
    k = node.attr("kernel_shape")
    rank = len(k)
    strides = node.attr("strides", [1] * rank)
    dilations = node.attr("dilations", [1] * rank)
    auto_pad = node.attr("auto_pad", "NOTSET")
    pads = _onnx_pads_to_lax(node.attr("pads"), rank, auto_pad,
                             x.shape[2:], k, strides, dilations)
    if node.attr("ceil_mode", 0):
        # grow the trailing pad so the last partial window is included
        new_pads = []
        for i in range(rank):
            eff_k = (k[i] - 1) * dilations[i] + 1
            span = x.shape[2 + i] + pads[i][0] + pads[i][1] - eff_k
            rem = span % strides[i]
            extra = (strides[i] - rem) if rem else 0
            new_pads.append((pads[i][0], pads[i][1] + extra))
        pads = new_pads
    window = (1, 1) + tuple(k)
    strides_full = (1, 1) + tuple(strides)
    dil_full = (1, 1) + tuple(dilations)
    pads_full = [(0, 0), (0, 0)] + list(pads)
    if is_avg:
        count_include_pad = node.attr("count_include_pad", 0)
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides_full,
                                   pads_full, window_dilation=dil_full)
        if count_include_pad:
            denom = float(np.prod(k))
            return summed / denom
        ones = jnp.ones(x.shape[2:], dtype=x.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, tuple(k), tuple(strides),
                                   pads, window_dilation=tuple(dilations))
        return summed / counts
    return lax.reduce_window(x, init, reducer, window, strides_full,
                             pads_full, window_dilation=dil_full)


@register_op("MaxPool")
def _maxpool(node, inputs, ctx):
    if len(node.output) > 1:
        raise UnsupportedOp("MaxPool with Indices output")
    return _pool(node, inputs, ctx, lax.max, -jnp.inf)


@register_op("AveragePool")
def _avgpool(node, inputs, ctx):
    return _pool(node, inputs, ctx, lax.add, 0.0, is_avg=True)


@register_op("GlobalAveragePool")
def _gap(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    axes = tuple(range(2, x.ndim))
    return jnp.mean(x, axis=axes, keepdims=True)


@register_op("GlobalMaxPool")
def _gmp(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    return jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@register_op("LpNormalization")
def _lpnorm(node, inputs, ctx):
    x = inputs[0]
    axis, p = node.attr("axis", -1), node.attr("p", 2)
    if p == 1:
        n = jnp.sum(jnp.abs(x), axis=axis, keepdims=True)
    else:
        n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    return x / jnp.maximum(n, 1e-12)


# -- normalization -----------------------------------------------------------

@register_op("BatchNormalization")
def _batchnorm(node, inputs, ctx):
    x, scale, bias, mean, var = inputs[:5]
    eps = node.attr("epsilon", 1e-5)
    rank = jnp.asarray(x).ndim
    shape = (1, -1) + (1,) * (rank - 2)
    inv = lax.rsqrt(jnp.asarray(var, dtype=jnp.float32) + eps).astype(jnp.asarray(x).dtype)
    return (x - mean.reshape(shape)) * (inv.reshape(shape) * scale.reshape(shape)) \
        + bias.reshape(shape)


@register_op("InstanceNormalization")
def _instancenorm(node, inputs, ctx):
    x, scale, bias = inputs
    eps = node.attr("epsilon", 1e-5)
    rank = jnp.asarray(x).ndim
    axes = tuple(range(2, rank))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (rank - 2)
    return (x - mean) * lax.rsqrt(var + eps) * scale.reshape(shape) + bias.reshape(shape)


@register_op("LayerNormalization")
def _layernorm(node, inputs, ctx):
    x = inputs[0]
    scale = inputs[1]
    bias = inputs[2] if len(inputs) > 2 and inputs[2] is not None else None
    axis = node.attr("axis", -1)
    eps = node.attr("epsilon", 1e-5)
    rank = jnp.asarray(x).ndim
    if axis < 0:
        axis += rank
    axes = tuple(range(axis, rank))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    inv = lax.rsqrt(var + eps)
    y = (x - mean) * inv * scale
    if bias is not None:
        y = y + bias
    if len(node.output) > 1:
        return tuple([y, mean, inv][:len(node.output)])
    return y


@register_op("GroupNormalization")
def _groupnorm(node, inputs, ctx):
    x, scale, bias = inputs
    g = node.attr("num_groups")
    eps = node.attr("epsilon", 1e-5)
    xs = jnp.asarray(x)
    n, c = xs.shape[:2]
    grouped = xs.reshape((n, g, c // g) + xs.shape[2:])
    axes = tuple(range(2, grouped.ndim))
    mean = jnp.mean(grouped, axis=axes, keepdims=True)
    var = jnp.var(grouped, axis=axes, keepdims=True)
    y = ((grouped - mean) * lax.rsqrt(var + eps)).reshape(xs.shape)
    shape = (1, -1) + (1,) * (xs.ndim - 2)
    return y * scale.reshape(shape) + bias.reshape(shape)


@register_op("Softmax")
def _softmax(node, inputs, ctx):
    axis = node.attr("axis", -1 if ctx.opset >= 13 else 1)
    x = inputs[0]
    if ctx.opset >= 13:
        return jax.nn.softmax(x, axis=axis)
    xs = jnp.asarray(x)
    flat = xs.reshape(int(np.prod(xs.shape[:axis]) or 1), -1)
    return jax.nn.softmax(flat, axis=-1).reshape(xs.shape)


@register_op("LogSoftmax")
def _logsoftmax(node, inputs, ctx):
    axis = node.attr("axis", -1 if ctx.opset >= 13 else 1)
    return jax.nn.log_softmax(inputs[0], axis=axis)


# -- reductions --------------------------------------------------------------

def _reduce(jfn, axes_as_input_since: int):
    def h(node, inputs, ctx):
        x = inputs[0]
        axes = None
        if ctx.opset >= axes_as_input_since and len(inputs) > 1 and inputs[1] is not None:
            axes = tuple(int(a) for a in _concrete(inputs[1], "reduce axes"))
        else:
            a = node.attr("axes")
            axes = tuple(a) if a else None
        if axes == ():
            axes = None
        keepdims = bool(node.attr("keepdims", 1))
        if axes is None and node.attr("noop_with_empty_axes", 0):
            return x
        return jfn(x, axis=axes, keepdims=keepdims)
    return h


OP_HANDLERS["ReduceSum"] = _reduce(jnp.sum, 13)
OP_HANDLERS["ReduceMean"] = _reduce(jnp.mean, 18)
OP_HANDLERS["ReduceMax"] = _reduce(jnp.max, 18)
OP_HANDLERS["ReduceMin"] = _reduce(jnp.min, 18)
OP_HANDLERS["ReduceProd"] = _reduce(jnp.prod, 18)
OP_HANDLERS["ReduceL1"] = _reduce(lambda x, axis, keepdims:
                                  jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims), 18)
OP_HANDLERS["ReduceL2"] = _reduce(lambda x, axis, keepdims:
                                  jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdims)), 18)
OP_HANDLERS["ReduceSumSquare"] = _reduce(lambda x, axis, keepdims:
                                         jnp.sum(x * x, axis=axis, keepdims=keepdims), 18)
OP_HANDLERS["ReduceLogSumExp"] = _reduce(
    lambda x, axis, keepdims: jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims), 18)


@register_op("ArgMax")
def _argmax(node, inputs, ctx):
    axis = node.attr("axis", 0)
    out = jnp.argmax(inputs[0], axis=axis)
    if node.attr("keepdims", 1):
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.int64)


@register_op("ArgMin")
def _argmin(node, inputs, ctx):
    axis = node.attr("axis", 0)
    out = jnp.argmin(inputs[0], axis=axis)
    if node.attr("keepdims", 1):
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.int64)


@register_op("TopK")
def _topk(node, inputs, ctx):
    k = int(_concrete(inputs[1], "TopK k").ravel()[0])
    axis = node.attr("axis", -1)
    largest = node.attr("largest", 1)
    x = jnp.asarray(inputs[0])
    x_moved = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(x_moved if largest else -x_moved, k)
    if not largest:
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx, -1, axis).astype(jnp.int64))


# -- shape ops ---------------------------------------------------------------

@register_op("Shape")
def _shape(node, inputs, ctx):
    shape = np.asarray(jnp.asarray(inputs[0]).shape, dtype=np.int64)
    start = node.attr("start", 0)
    end = node.attr("end")
    return shape[start:end if end is not None else len(shape)]


@register_op("Size")
def _size(node, inputs, ctx):
    return np.asarray(jnp.asarray(inputs[0]).size, dtype=np.int64)


@register_op("Reshape")
def _reshape(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    target = [int(d) for d in _concrete(inputs[1], "Reshape shape").ravel()]
    if not node.attr("allowzero", 0):
        target = [x.shape[i] if d == 0 else d for i, d in enumerate(target)]
    return jnp.reshape(x, target)


@register_op("Flatten")
def _flatten(node, inputs, ctx):
    axis = node.attr("axis", 1)
    x = jnp.asarray(inputs[0])
    if axis < 0:
        axis += x.ndim
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return jnp.reshape(x, (lead, -1))


@register_op("Transpose")
def _transpose(node, inputs, ctx):
    perm = node.attr("perm")
    x = jnp.asarray(inputs[0])
    return jnp.transpose(x, perm if perm else tuple(reversed(range(x.ndim))))


@register_op("Squeeze")
def _squeeze(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    if ctx.opset >= 13 and len(inputs) > 1 and inputs[1] is not None:
        axes = tuple(int(a) for a in _concrete(inputs[1], "Squeeze axes"))
    else:
        a = node.attr("axes")
        axes = tuple(a) if a else None
    if axes is None:
        return jnp.squeeze(x)
    return jnp.squeeze(x, axis=axes)


@register_op("Unsqueeze")
def _unsqueeze(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    if ctx.opset >= 13 and len(inputs) > 1 and inputs[1] is not None:
        axes = [int(a) for a in _concrete(inputs[1], "Unsqueeze axes")]
    else:
        axes = list(node.attr("axes"))
    out_rank = x.ndim + len(axes)
    axes = sorted(a + out_rank if a < 0 else a for a in axes)
    for a in axes:
        x = jnp.expand_dims(x, a)
    return x


@register_op("Concat")
def _concat(node, inputs, ctx):
    axis = node.attr("axis")
    if all(isinstance(x, np.ndarray) for x in inputs):
        return np.concatenate(inputs, axis=axis)
    return jnp.concatenate(inputs, axis=axis)


@register_op("Split")
def _split(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    axis = node.attr("axis", 0)
    if len(inputs) > 1 and inputs[1] is not None:
        sizes = [int(s) for s in _concrete(inputs[1], "Split sizes")]
    elif node.attr("split"):
        sizes = list(node.attr("split"))
    else:
        n_out = node.attr("num_outputs", len(node.output))
        dim = x.shape[axis]
        base = -(-dim // n_out)
        sizes = [base] * (n_out - 1) + [dim - base * (n_out - 1)]
    offsets = np.cumsum([0] + sizes)
    return tuple(lax.slice_in_dim(x, int(offsets[i]), int(offsets[i + 1]), axis=axis)
                 for i in range(len(sizes)))


@register_op("Slice")
def _slice(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    if ctx.opset >= 10:
        starts = [int(v) for v in _concrete(inputs[1], "Slice starts")]
        ends = [int(v) for v in _concrete(inputs[2], "Slice ends")]
        axes = ([int(v) for v in _concrete(inputs[3], "Slice axes")]
                if len(inputs) > 3 and inputs[3] is not None else list(range(len(starts))))
        steps = ([int(v) for v in _concrete(inputs[4], "Slice steps")]
                 if len(inputs) > 4 and inputs[4] is not None else [1] * len(starts))
    else:
        starts = list(node.attr("starts"))
        ends = list(node.attr("ends"))
        axes = list(node.attr("axes", range(len(starts))))
        steps = [1] * len(starts)
    slices = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        INT_MAX = np.iinfo(np.int64).max
        en_val = None if en >= INT_MAX // 2 else (None if sp < 0 and en == -INT_MAX - 1 else en)
        slices[ax] = slice(st, en_val, sp)
    return x[tuple(slices)]


@register_op("Gather")
def _gather(node, inputs, ctx):
    axis = node.attr("axis", 0)
    x, idx = inputs
    return jnp.take(x, jnp.asarray(idx), axis=axis)


@register_op("GatherElements")
def _gather_elements(node, inputs, ctx):
    axis = node.attr("axis", 0)
    return jnp.take_along_axis(jnp.asarray(inputs[0]), jnp.asarray(inputs[1]),
                               axis=axis)


@register_op("GatherND")
def _gathernd(node, inputs, ctx):
    if node.attr("batch_dims", 0):
        raise UnsupportedOp("GatherND batch_dims")
    x, idx = jnp.asarray(inputs[0]), jnp.asarray(inputs[1])
    return x[tuple(jnp.moveaxis(idx, -1, 0))]


@register_op("ScatterND")
def _scatternd(node, inputs, ctx):
    x, idx, upd = (jnp.asarray(v) for v in inputs)
    return x.at[tuple(jnp.moveaxis(idx, -1, 0))].set(upd)


@register_op("Expand")
def _expand(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    shape = [int(d) for d in _concrete(inputs[1], "Expand shape")]
    # ONNX Expand uses broadcasting semantics: dims of 1 broadcast, and the
    # input may have more dims than the target
    out_shape = list(np.broadcast_shapes(tuple(x.shape), tuple(shape)))
    return jnp.broadcast_to(x, out_shape)


@register_op("Tile")
def _tile(node, inputs, ctx):
    reps = [int(r) for r in _concrete(inputs[1], "Tile repeats")]
    return jnp.tile(jnp.asarray(inputs[0]), reps)


@register_op("Pad")
def _pad(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    mode = node.attr("mode", "constant")
    if ctx.opset >= 11:
        pads = [int(p) for p in _concrete(inputs[1], "Pad pads")]
        cval = (float(np.asarray(_concrete(inputs[2], "Pad value")).ravel()[0])
                if len(inputs) > 2 and inputs[2] is not None else 0.0)
        axes = ([int(a) for a in _concrete(inputs[3], "Pad axes")]
                if len(inputs) > 3 and inputs[3] is not None else list(range(x.ndim)))
    else:
        pads = list(node.attr("pads"))
        cval = node.attr("value", 0.0)
        axes = list(range(x.ndim))
    half = len(pads) // 2
    widths = [(0, 0)] * x.ndim
    for i, ax in enumerate(axes):
        widths[ax] = (pads[i], pads[i + half])
    jmode = {"constant": "constant", "reflect": "reflect", "edge": "edge",
             "wrap": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, widths, mode="constant", constant_values=cval)
    return jnp.pad(x, widths, mode=jmode)


@register_op("Resize")
def _resize(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    mode = node.attr("mode", "nearest")
    sizes = None
    if len(inputs) > 3 and inputs[3] is not None:
        sizes = [int(s) for s in _concrete(inputs[3], "Resize sizes")]
    elif len(inputs) > 2 and inputs[2] is not None:
        scales = np.asarray(_concrete(inputs[2], "Resize scales")).ravel()
        if scales.size:
            sizes = [int(round(d * s)) for d, s in zip(x.shape, scales)]
    if sizes is None:
        raise UnsupportedOp("Resize without sizes/scales")
    method = {"nearest": "nearest", "linear": "linear", "cubic": "cubic"}[mode]
    return jax.image.resize(x, sizes, method=method)


@register_op("Upsample")
def _upsample(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    scales = np.asarray(_concrete(inputs[1], "Upsample scales")).ravel() \
        if len(inputs) > 1 else np.asarray(node.attr("scales"))
    sizes = [int(round(d * s)) for d, s in zip(x.shape, scales)]
    method = {"nearest": "nearest", "linear": "linear"}[node.attr("mode", "nearest")]
    return jax.image.resize(x, sizes, method=method)


@register_op("DepthToSpace")
def _depth_to_space(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    b = node.attr("blocksize")
    n, c, h, w = x.shape
    if node.attr("mode", "DCR") == "DCR":
        y = x.reshape(n, b, b, c // (b * b), h, w)
        y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))
    else:
        y = x.reshape(n, c // (b * b), b, b, h, w)
        y = jnp.transpose(y, (0, 1, 4, 2, 5, 3))
    return y.reshape(n, c // (b * b), h * b, w * b)


@register_op("SpaceToDepth")
def _space_to_depth(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    b = node.attr("blocksize")
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // b, b, w // b, b)
    y = jnp.transpose(y, (0, 3, 5, 1, 2, 4))
    return y.reshape(n, c * b * b, h // b, w // b)


@register_op("Constant")
def _constant(node, inputs, ctx):
    for key in ("value", "value_float", "value_int", "value_floats",
                "value_ints", "value_string", "value_strings"):
        v = node.attr(key)
        if v is not None:
            return np.asarray(v) if not isinstance(v, np.ndarray) else v
    raise ValueError(f"Constant node {node.name} has no value")


@register_op("ConstantOfShape")
def _constant_of_shape(node, inputs, ctx):
    shape = [int(d) for d in _concrete(inputs[0], "ConstantOfShape shape")]
    value = node.attr("value")
    if value is None:
        return np.zeros(shape, dtype=np.float32)
    value = np.asarray(value)
    return np.full(shape, value.ravel()[0], dtype=value.dtype)


@register_op("Range")
def _range(node, inputs, ctx):
    s, l, d = (np.asarray(_concrete(v, "Range args")).ravel()[0] for v in inputs)
    return np.arange(s, l, d)


@register_op("OneHot")
def _onehot(node, inputs, ctx):
    idx = jnp.asarray(inputs[0])
    depth = int(np.asarray(_concrete(inputs[1], "OneHot depth")).ravel()[0])
    values = inputs[2]
    axis = node.attr("axis", -1)
    off, on = values[0], values[1]
    oh = jax.nn.one_hot(jnp.mod(idx, depth), depth, axis=axis)
    return oh * (on - off) + off


@register_op("CumSum")
def _cumsum(node, inputs, ctx):
    axis = int(np.asarray(_concrete(inputs[1], "CumSum axis")).ravel()[0])
    x = jnp.asarray(inputs[0])
    out = jnp.cumsum(jnp.flip(x, axis) if node.attr("reverse", 0) else x, axis=axis)
    if node.attr("exclusive", 0):
        out = jnp.roll(out, 1, axis=axis)
        idx = [slice(None)] * x.ndim
        idx[axis] = slice(0, 1)
        out = out.at[tuple(idx)].set(0)
    if node.attr("reverse", 0):
        out = jnp.flip(out, axis)
    return out


@register_op("Trilu")
def _trilu(node, inputs, ctx):
    k = int(np.asarray(_concrete(inputs[1], "Trilu k")).ravel()[0]) \
        if len(inputs) > 1 and inputs[1] is not None else 0
    x = jnp.asarray(inputs[0])
    return jnp.tril(x, k) if node.attr("upper", 1) == 0 else jnp.triu(x, k)


@register_op("EyeLike")
def _eyelike(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    dt = ONNX_TO_NUMPY.get(node.attr("dtype"), x.dtype)
    return jnp.eye(x.shape[0], x.shape[1], k=node.attr("k", 0), dtype=dt)


@register_op("QuantizeLinear")
def _quantize(node, inputs, ctx):
    x, scale = inputs[0], inputs[1]
    zp = inputs[2] if len(inputs) > 2 and inputs[2] is not None else np.int8(0)
    zp_arr = jnp.asarray(zp)
    info = jnp.iinfo(zp_arr.dtype)
    return jnp.clip(jnp.round(x / scale) + zp_arr.astype(jnp.int32),
                    info.min, info.max).astype(zp_arr.dtype)


@register_op("DequantizeLinear")
def _dequantize(node, inputs, ctx):
    x, scale = inputs[0], inputs[1]
    zp = inputs[2] if len(inputs) > 2 and inputs[2] is not None else 0
    return (jnp.asarray(x).astype(jnp.float32)
            - jnp.asarray(zp).astype(jnp.float32)) * scale


# -- int8 compute ops (QLinear*) ---------------------------------------------
#
# The reference runs int8-quantized graphs through whatever ORT 1.8 executes
# (`ONNXModel.scala:330`, `build.sbt:257-259`). TPU-native: when both zero
# points are 0 (the symmetric-int8 case every serious quantizer emits for
# weights), the int8 operands are fed to the MXU directly with int32
# accumulation; otherwise the zero points are folded in int32 first.

def _maybe_scalar(v, what):
    a = np.asarray(_concrete(v, what)).ravel()
    if a.size != 1:
        raise UnsupportedOp(f"{what} must be per-tensor (scalar), "
                            f"got {a.size} values")
    return a.dtype.type(a[0])


def _int_accum_matmul(a, a_zp, b, b_zp):
    """(a - a_zp) @ (b - b_zp) accumulated in int32; int8 operands ride the
    MXU directly when both zero points are zero."""
    a, b = jnp.asarray(a), jnp.asarray(b)
    if int(a_zp) == 0 and int(b_zp) == 0:
        return jnp.matmul(a, b, preferred_element_type=jnp.int32)
    ai = a.astype(jnp.int32) - jnp.int32(a_zp)
    bi = b.astype(jnp.int32) - jnp.int32(b_zp)
    return jnp.matmul(ai, bi, preferred_element_type=jnp.int32)


def _saturate(y_float, zp):
    """round-half-even, + zp, saturate to zp's integer dtype — the one
    requantization tail shared by every QLinear op."""
    zdt = np.asarray(zp).dtype
    info = jnp.iinfo(zdt)
    return jnp.clip(jnp.round(y_float) + int(zp),
                    info.min, info.max).astype(zdt)


def _requantize(acc_i32, multiplier, y_zp):
    return _saturate(acc_i32.astype(jnp.float32) * multiplier, y_zp)


@register_op("QLinearConv")
def _qlinear_conv(node, inputs, ctx):
    (x, x_scale, x_zp, w, w_scale, w_zp) = inputs[:6]
    y_scale, y_zp = inputs[6], inputs[7]
    bias = inputs[8] if len(inputs) > 8 else None
    x_zp = _maybe_scalar(x_zp, "QLinearConv x_zero_point")
    w_zp_a = np.asarray(_concrete(w_zp, "QLinearConv w_zero_point")).ravel()
    if (w_zp_a != w_zp_a[0]).any():
        raise UnsupportedOp("QLinearConv per-channel w_zero_point")
    w_zp = w_zp_a.dtype.type(w_zp_a[0])
    rank = jnp.asarray(w).ndim - 2
    same_dtype = jnp.asarray(x).dtype == jnp.asarray(w).dtype
    if int(x_zp) == 0 and int(w_zp) == 0 and same_dtype:
        # lax.conv requires identical operand dtypes (uint8 activations +
        # int8 weights — the standard ORT post-ReLU output — must take the
        # widened path)
        acc = _conv_raw(node, x, w, preferred=jnp.int32)
    else:
        xi = jnp.asarray(x).astype(jnp.int32) - jnp.int32(x_zp)
        wi = jnp.asarray(w).astype(jnp.int32) - jnp.int32(w_zp)
        acc = _conv_raw(node, xi, wi, preferred=jnp.int32)
    if bias is not None:       # int32, quantized with scale x_scale*w_scale
        acc = acc + jnp.asarray(bias).reshape((1, -1) + (1,) * rank)
    # w_scale may be per-output-channel: broadcast over (N, M, *spatial)
    mult = (jnp.asarray(x_scale).astype(jnp.float32)
            * jnp.asarray(w_scale).astype(jnp.float32).reshape(
                (1, -1) + (1,) * rank)
            / jnp.asarray(y_scale).astype(jnp.float32))
    return _requantize(acc, mult, _maybe_scalar(y_zp, "QLinearConv y_zp"))


@register_op("QLinearMatMul")
def _qlinear_matmul(node, inputs, ctx):
    (a, a_scale, a_zp, b, b_scale, b_zp, y_scale, y_zp) = inputs[:8]
    acc = _int_accum_matmul(a, _maybe_scalar(a_zp, "QLinearMatMul a_zp"),
                            b, _maybe_scalar(b_zp, "QLinearMatMul b_zp"))
    mult = (jnp.asarray(a_scale).astype(jnp.float32)
            * jnp.asarray(b_scale).astype(jnp.float32)
            / jnp.asarray(y_scale).astype(jnp.float32))
    return _requantize(acc, mult, _maybe_scalar(y_zp, "QLinearMatMul y_zp"))


@register_op("QGemm")
def _qgemm(node, inputs, ctx):
    """com.microsoft QGemm: quantized Gemm with optional int32 C and
    optional output quantization (float32 out when y_scale is absent)."""
    a, a_scale, a_zp, b, b_scale, b_zp = inputs[:6]
    c = inputs[6] if len(inputs) > 6 else None
    y_scale = inputs[7] if len(inputs) > 7 else None
    y_zp = inputs[8] if len(inputs) > 8 else None
    alpha = node.attr("alpha", 1.0)
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if node.attr("transA", 0):
        a = jnp.swapaxes(a, -1, -2)
    if node.attr("transB", 0):
        b = jnp.swapaxes(b, -1, -2)
    acc = _int_accum_matmul(a, _maybe_scalar(a_zp, "QGemm a_zp"),
                            b, _maybe_scalar(b_zp, "QGemm b_zp"))
    if c is not None:          # int32, scale = alpha * a_scale * b_scale
        acc = acc + jnp.asarray(c)
    sab = (alpha * jnp.asarray(a_scale).astype(jnp.float32)
           * jnp.asarray(b_scale).astype(jnp.float32))
    if y_scale is None:
        return acc.astype(jnp.float32) * sab
    return _requantize(acc, sab / jnp.asarray(y_scale).astype(jnp.float32),
                       _maybe_scalar(y_zp, "QGemm y_zp"))


def _qlinear_eltwise(op):
    """com.microsoft QLinearAdd/QLinearMul: dequantize, apply, requantize —
    the pattern ORT's quantizer emits around every ResNet skip connection."""
    def handler(node, inputs, ctx):
        (a, a_scale, a_zp, b, b_scale, b_zp, y_scale, y_zp) = inputs[:8]
        af = (jnp.asarray(a).astype(jnp.float32)
              - float(_maybe_scalar(a_zp, "QLinear a_zp"))) \
            * jnp.asarray(a_scale).astype(jnp.float32)
        bf = (jnp.asarray(b).astype(jnp.float32)
              - float(_maybe_scalar(b_zp, "QLinear b_zp"))) \
            * jnp.asarray(b_scale).astype(jnp.float32)
        y = op(af, bf) / jnp.asarray(y_scale).astype(jnp.float32)
        return _saturate(y, _maybe_scalar(y_zp, "QLinear y_zp"))
    return handler


register_op("QLinearAdd")(_qlinear_eltwise(jnp.add))
register_op("QLinearMul")(_qlinear_eltwise(jnp.multiply))


@register_op("QLinearGlobalAveragePool")
def _qlinear_gap(node, inputs, ctx):
    x, x_scale, x_zp, y_scale, y_zp = inputs[:5]
    if node.attr("channels_last", 0):
        raise UnsupportedOp("QLinearGlobalAveragePool channels_last")
    x = jnp.asarray(x)
    spatial = tuple(range(2, x.ndim))
    # exact integer mean in int32, then one requantization
    acc = jnp.sum(x.astype(jnp.int32), axis=spatial, keepdims=True)
    count = int(np.prod([x.shape[i] for i in spatial]))
    mean = acc.astype(jnp.float32) / count \
        - float(_maybe_scalar(x_zp, "QLinearGAP x_zp"))
    y = mean * jnp.asarray(x_scale).astype(jnp.float32) \
        / jnp.asarray(y_scale).astype(jnp.float32)
    return _saturate(y, _maybe_scalar(y_zp, "QLinearGAP y_zp"))


# -- detection ops -----------------------------------------------------------

@register_op("NonMaxSuppression")
def _nms(node, inputs, ctx):
    """Exact ONNX semantics require a data-dependent output shape, so this
    runs on concrete values (eager execution or trace-time constants) and
    rejects tracers. The reference delegates to ORT's CPU kernel
    (`ONNXModel.scala:330`) — also a host-side op there."""
    boxes = np.asarray(_concrete(inputs[0], "NonMaxSuppression boxes"))
    scores = np.asarray(_concrete(inputs[1], "NonMaxSuppression scores"))
    max_out = (int(np.ravel(_concrete(inputs[2], "max_output"))[0])
               if len(inputs) > 2 and inputs[2] is not None else 0)
    iou_thr = (float(np.ravel(_concrete(inputs[3], "iou_threshold"))[0])
               if len(inputs) > 3 and inputs[3] is not None else 0.0)
    score_thr = (float(np.ravel(_concrete(inputs[4], "score_threshold"))[0])
                 if len(inputs) > 4 and inputs[4] is not None else None)
    center = bool(node.attr("center_point_box", 0))
    if max_out <= 0:        # spec: "Default to 0, which means no output"
        return np.zeros((0, 3), np.int64)
    sel = []
    for bi in range(scores.shape[0]):
        for ci in range(scores.shape[1]):
            s = scores[bi, ci]
            order = np.argsort(-s, kind="stable")
            if score_thr is not None:
                order = order[s[order] > score_thr]
            kept: list = []
            for i in order:
                if len(kept) >= max_out:
                    break
                if all(_iou(boxes[bi, i], boxes[bi, j], center) <= iou_thr
                       for j in kept):
                    kept.append(i)
            sel.extend([bi, ci, int(i)] for i in kept)
    return np.asarray(sel, np.int64).reshape(-1, 3)


def _iou(a, b, center: bool) -> float:
    if center:      # [x_center, y_center, w, h]
        ay1, ax1 = a[1] - a[3] / 2, a[0] - a[2] / 2
        ay2, ax2 = a[1] + a[3] / 2, a[0] + a[2] / 2
        by1, bx1 = b[1] - b[3] / 2, b[0] - b[2] / 2
        by2, bx2 = b[1] + b[3] / 2, b[0] + b[2] / 2
    else:           # [y1, x1, y2, x2], either corner order allowed
        ay1, ax1, ay2, ax2 = a
        by1, bx1, by2, bx2 = b
        ay1, ay2 = min(ay1, ay2), max(ay1, ay2)
        ax1, ax2 = min(ax1, ax2), max(ax1, ax2)
        by1, by2 = min(by1, by2), max(by1, by2)
        bx1, bx2 = min(bx1, bx2), max(bx1, bx2)
    ih = max(0.0, min(ay2, by2) - max(ay1, by1))
    iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
    inter = ih * iw
    union = ((ay2 - ay1) * (ax2 - ax1) + (by2 - by1) * (bx2 - bx1) - inter)
    return inter / union if union > 0 else 0.0


@register_op("RoiAlign")
def _roi_align(node, inputs, ctx):
    """torchvision-semantics RoiAlign (the ONNX spec's model): bilinear
    sampling on a fixed grid per output bin, averaged or maxed. Static
    shapes throughout — vmapped over ROIs, gathers ride XLA."""
    x, rois, batch_idx = inputs[0], inputs[1], inputs[2]
    out_h = node.attr("output_height", 1)
    out_w = node.attr("output_width", 1)
    sr = node.attr("sampling_ratio", 0)
    if sr <= 0:
        # adaptive sampling counts are per-ROI data-dependent (ceil of the
        # bin size) and cannot be a static shape; real detector exports set
        # an explicit ratio (torchvision default 2)
        raise UnsupportedOp("RoiAlign sampling_ratio=0 (adaptive)")
    scale = node.attr("spatial_scale", 1.0)
    mode = node.attr("mode", "avg")
    half_pixel = node.attr("coordinate_transformation_mode",
                           "half_pixel") == "half_pixel"
    x = jnp.asarray(x)
    N, C, H, W = x.shape

    def one_roi(roi, b):
        off = 0.5 if half_pixel else 0.0
        x1, y1, x2, y2 = [roi[i] * scale - off for i in range(4)]
        roi_w, roi_h = x2 - x1, y2 - y1
        if not half_pixel:      # legacy mode clamps to min size 1
            roi_w = jnp.maximum(roi_w, 1.0)
            roi_h = jnp.maximum(roi_h, 1.0)
        bin_w, bin_h = roi_w / out_w, roi_h / out_h
        iy = (jnp.arange(sr, dtype=jnp.float32) + 0.5) / sr     # (sr,)
        ys = (y1 + (jnp.arange(out_h, dtype=jnp.float32)[:, None]
                    + iy[None, :]) * bin_h).ravel()             # (out_h*sr,)
        xs = (x1 + (jnp.arange(out_w, dtype=jnp.float32)[:, None]
                    + iy[None, :]) * bin_w).ravel()             # (out_w*sr,)
        img = x[b]                                              # (C, H, W)

        def axis_weights(cs, limit):
            valid = (cs >= -1.0) & (cs <= limit)    # torchvision zero rule
            c = jnp.clip(cs, 0.0, limit - 1)
            lo = jnp.floor(c).astype(jnp.int32)
            hi = jnp.minimum(lo + 1, int(limit) - 1)
            frac = c - lo
            return lo, hi, frac, valid

        y0, y1i, fy, vy = axis_weights(ys, float(H))
        x0, x1i, fx, vx = axis_weights(xs, float(W))
        # gather rows then columns: 4 corner planes (C, Sy, Sx)
        gy0, gy1 = img[:, y0, :], img[:, y1i, :]
        v = ((gy0[:, :, x0] * (1 - fy)[None, :, None]
              + gy1[:, :, x0] * fy[None, :, None]) * (1 - fx)[None, None, :]
             + (gy0[:, :, x1i] * (1 - fy)[None, :, None]
                + gy1[:, :, x1i] * fy[None, :, None]) * fx[None, None, :])
        v = v * (vy[None, :, None] & vx[None, None, :])
        v = v.reshape(C, out_h, sr, out_w, sr)
        if mode == "max":
            return v.max(axis=(2, 4))
        return v.mean(axis=(2, 4))

    return jax.vmap(one_roi)(jnp.asarray(rois),
                             jnp.asarray(batch_idx).astype(jnp.int32))


@register_op("GridSample")
def _grid_sample(node, inputs, ctx):
    x, grid = jnp.asarray(inputs[0]), jnp.asarray(inputs[1])
    if x.ndim != 4:
        raise UnsupportedOp(f"GridSample rank {x.ndim} (4-D NCHW only)")
    mode = node.attr("mode", "linear")
    pad = node.attr("padding_mode", "zeros")
    align = bool(node.attr("align_corners", 0))
    N, C, H, W = x.shape

    def unnormalize(coord, size):
        if align:
            return (coord + 1.0) / 2.0 * (size - 1)
        return ((coord + 1.0) * size - 1.0) / 2.0

    def reflect(c, size):
        # reflect around -0.5 / size-0.5 (align_corners=False convention)
        if align:
            span = 2.0 * (size - 1) if size > 1 else 1.0
            c = jnp.abs(jnp.mod(c, span))
            return jnp.where(c > size - 1, span - c, c)
        span = 2.0 * size
        c = jnp.mod(c + 0.5, span)
        c = jnp.abs(c)
        return jnp.clip(jnp.where(c > size, span - c, c) - 0.5,
                        0.0, size - 1)

    def sample_one(img, g):                     # img (C,H,W), g (Ho,Wo,2)
        gx = unnormalize(g[..., 0].ravel(), W)  # (P,)
        gy = unnormalize(g[..., 1].ravel(), H)
        if pad == "reflection":
            gx, gy = reflect(gx, W), reflect(gy, H)
        flat = img.reshape(C, H * W)

        def fetch(yi, xi):
            valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            idx = (jnp.clip(yi, 0, H - 1) * W
                   + jnp.clip(xi, 0, W - 1)).astype(jnp.int32)
            v = flat[:, idx]                     # (C, P)
            if pad == "zeros":
                v = v * valid[None, :]
            return v

        if mode in ("nearest",):
            yi = jnp.round(gy).astype(jnp.int32)
            xi = jnp.round(gx).astype(jnp.int32)
            # fetch()'s per-corner valid mask already zeroes out-of-image
            # samples in zeros mode; border/reflection are in-range here
            return fetch(yi, xi).reshape(C, g.shape[0], g.shape[1])
        if mode not in ("linear", "bilinear"):
            raise UnsupportedOp(f"GridSample mode {mode!r}")
        if pad == "border":
            gx = jnp.clip(gx, 0.0, W - 1)
            gy = jnp.clip(gy, 0.0, H - 1)
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        fx, fy = gx - x0, gy - y0
        out = (fetch(y0, x0) * ((1 - fy) * (1 - fx))[None, :]
               + fetch(y0, x0 + 1) * ((1 - fy) * fx)[None, :]
               + fetch(y0 + 1, x0) * (fy * (1 - fx))[None, :]
               + fetch(y0 + 1, x0 + 1) * (fy * fx)[None, :])
        return out.reshape(C, g.shape[0], g.shape[1])

    return jax.vmap(sample_one)(x, grid.astype(jnp.float32))


# ---------------------------------------------------------------------------
# Static (trace-time) evaluation.
#
# Under jit, every jnp op is staged — even on constants — so shape arithmetic
# (Shape → Gather → Concat → Reshape chains that every BERT/ResNet exporter
# emits) would produce tracers and kill static shapes. Nodes whose inputs are
# all plain numpy arrays are therefore evaluated with these numpy handlers,
# keeping the shape pipeline concrete through arbitrary arithmetic.
# ---------------------------------------------------------------------------

def _np_slice(node, inputs, ctx):
    x = inputs[0]
    starts = [int(v) for v in np.ravel(inputs[1])]
    ends = [int(v) for v in np.ravel(inputs[2])]
    axes = ([int(v) for v in np.ravel(inputs[3])]
            if len(inputs) > 3 and inputs[3] is not None else list(range(len(starts))))
    steps = ([int(v) for v in np.ravel(inputs[4])]
             if len(inputs) > 4 and inputs[4] is not None else [1] * len(starts))
    sl = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        sl[ax] = slice(st, None if abs(en) >= np.iinfo(np.int64).max // 2 else en, sp)
    return x[tuple(sl)]


def _np_unsqueeze(node, inputs, ctx):
    x = inputs[0]
    axes = ([int(a) for a in np.ravel(inputs[1])] if len(inputs) > 1
            and inputs[1] is not None else list(node.attr("axes")))
    out_rank = x.ndim + len(axes)
    for a in sorted(a + out_rank if a < 0 else a for a in axes):
        x = np.expand_dims(x, a)
    return x


# ---------------------------------------------------------------------------
# Control flow (subgraph attributes) and recurrent cells. These lower to the
# XLA-native structured primitives — lax.cond / lax.scan — instead of the
# interpreter loops an ORT-style runtime uses.
# ---------------------------------------------------------------------------

@register_op("If")
def _if(node, inputs, ctx):
    cond = inputs[0]
    then_g = node.attr("then_branch")
    else_g = node.attr("else_branch")
    if isinstance(cond, (np.ndarray, np.generic, bool)):
        # static predicate (common exporter pattern): evaluate one branch
        branch = then_g if bool(np.asarray(cond).reshape(())) else else_g
        outs = ctx.run_subgraph(branch, [])
        return tuple(outs) if len(outs) > 1 else outs[0]
    pred = jnp.asarray(cond).reshape(()).astype(bool)
    outs = lax.cond(pred,
                    lambda: tuple(jnp.asarray(v) for v in
                                  ctx.run_subgraph(then_g, [])),
                    lambda: tuple(jnp.asarray(v) for v in
                                  ctx.run_subgraph(else_g, [])))
    return outs if len(outs) > 1 else outs[0]


def _cond_is_passthrough(body) -> bool:
    """True when the body's cond_out is an Identity chain back to cond_in —
    the fixed-trip-count exporter pattern where termination never fires."""
    producers = {}
    for n in body.nodes:
        for o in n.output:
            producers[o] = n
    name = body.outputs[0].name
    cond_in = body.inputs[1].name if len(body.inputs) > 1 else None
    for _ in range(len(body.nodes) + 1):
        if name == cond_in:
            return True
        n = producers.get(name)
        if n is None or n.op_type != "Identity":
            return False
        name = n.input[0]
    return False


@register_op("Loop")
def _loop(node, inputs, ctx):
    """ONNX Loop with a static trip count → lax.scan.

    body(iter_num, cond_in, v...) -> (cond_out, v'..., scan_outputs...).
    A body-computed termination condition is honored by masking the carry
    once it turns False; a while-style loop WITH scan outputs would need a
    dynamic output length and is rejected (no static shape exists)."""
    m, cond0 = inputs[0], inputs[1]
    v_init = [jnp.asarray(v) for v in inputs[2:]]
    body = node.attr("body")
    if m is None or not isinstance(m, (np.ndarray, np.generic, int)):
        raise UnsupportedOp(
            "Loop requires a static trip count M (data-dependent loop "
            "termination has no static shape)")
    trip = int(np.asarray(m).reshape(()))
    if cond0 is not None and not isinstance(cond0,
                                            (np.ndarray, np.generic, bool)):
        raise UnsupportedOp("Loop with a traced initial condition is not "
                            "supported (static trip counts only)")
    if cond0 is not None and not bool(np.asarray(cond0).reshape(())):
        trip = 0  # spec: initial cond False runs zero iterations
    n_carry = len(v_init)
    n_scan = len(body.outputs) - 1 - n_carry
    fixed_trip = _cond_is_passthrough(body)
    if not fixed_trip and n_scan > 0:
        raise UnsupportedOp(
            "Loop with data-dependent termination AND scan outputs has a "
            "dynamic output length (no static shape)")

    def step(carry, i):
        active, vals = carry
        outs = ctx.run_subgraph(
            body, [jnp.asarray(i, jnp.int64), jnp.asarray(True)]
            + list(vals))
        cond_out = jnp.asarray(outs[0]).reshape(()).astype(bool)
        new_vals = tuple(
            jnp.where(active, jnp.asarray(v), old)
            for v, old in zip(outs[1:1 + n_carry], vals))
        scans = tuple(jnp.asarray(v) for v in outs[1 + n_carry:])
        return (active & cond_out, new_vals), scans

    (_, carry), scans = lax.scan(
        step, (jnp.asarray(True), tuple(v_init)),
        jnp.arange(trip, dtype=jnp.int64))
    outs = list(carry) + [scans[k] for k in range(n_scan)]
    return tuple(outs) if len(outs) > 1 else outs[0]


@register_op("Scan")
def _scan(node, inputs, ctx):
    """ONNX Scan (forward, axis-0 scans) → lax.scan."""
    body = node.attr("body")
    n_scan_in = int(node.attr("num_scan_inputs"))
    if node.attr("scan_input_directions") or \
            node.attr("scan_output_directions") or \
            node.attr("scan_input_axes") or node.attr("scan_output_axes"):
        raise UnsupportedOp("Scan with non-default directions/axes")
    n_state = len(inputs) - n_scan_in
    state = [jnp.asarray(v) for v in inputs[:n_state]]
    xs = tuple(jnp.asarray(v) for v in inputs[n_state:])
    n_scan_out = len(body.outputs) - n_state

    def step(carry, x_slices):
        outs = ctx.run_subgraph(body, list(carry) + list(x_slices))
        new_state = tuple(jnp.asarray(v) for v in outs[:n_state])
        scans = tuple(jnp.asarray(v) for v in outs[n_state:])
        return new_state, scans

    carry, scans = lax.scan(step, tuple(state), xs)
    outs = list(carry) + [scans[k] for k in range(n_scan_out)]
    return tuple(outs) if len(outs) > 1 else outs[0]


_SIGMOID_TANH_ACTS = (
    ["sigmoid", "tanh"], ["sigmoid", "tanh", "tanh"],
    ["sigmoid", "tanh"] * 2, ["sigmoid", "tanh", "tanh"] * 2)


def _rnn_common(node, inputs, allowed_acts=_SIGMOID_TANH_ACTS):
    """Shared unpacking for RNN/LSTM/GRU: X (T,B,I), W/R/B per direction.

    ``allowed_acts``: the activation lists this op may carry — each op
    passes its own spec defaults (vanilla RNN is Tanh-only; LSTM/GRU are
    Sigmoid-gated) so a nonstandard activation is rejected, never silently
    computed with the wrong function."""
    X = jnp.asarray(inputs[0])
    W = jnp.asarray(inputs[1])
    R = jnp.asarray(inputs[2])
    B = jnp.asarray(inputs[3]) if len(inputs) > 3 and inputs[3] is not None \
        else None
    if len(inputs) > 4 and inputs[4] is not None:
        raise UnsupportedOp("sequence_lens in recurrent ops (pad/mask "
                            "upstream instead — static shapes)")
    # silently computing with the wrong activation would be worse than
    # rejecting: only the ONNX defaults (Sigmoid/Tanh) are implemented
    acts = node.attr("activations")
    if acts and [a.lower() for a in acts] not in allowed_acts:
        raise UnsupportedOp(f"{node.op_type} activations {acts} "
                            "(spec defaults only)")
    if node.attr("clip") is not None:
        raise UnsupportedOp("RNN cell clipping")
    direction = node.attr("direction", "forward")
    if direction not in ("forward", "reverse", "bidirectional"):
        raise UnsupportedOp(f"RNN direction {direction!r}")
    return X, W, R, B, direction


def _run_directions(X, W, R, B, h0s, extra0s, direction, cell):
    """Run ``cell`` over time for each direction; returns per-direction
    (ys (T,B,H), h_final, extra_final)."""
    results = []
    n_dirs = W.shape[0]
    for d in range(n_dirs):
        reverse = (direction == "reverse") or \
            (direction == "bidirectional" and d == 1)
        xs = jnp.flip(X, axis=0) if reverse else X
        carry0 = (h0s[d],) + tuple(e[d] for e in extra0s)
        (carry, ys) = lax.scan(
            partial(cell, W=W[d], R=R[d], B=(B[d] if B is not None
                                             else None)),
            carry0, xs)
        if reverse:
            ys = jnp.flip(ys, axis=0)
        results.append((ys, carry))
    return results


@register_op("LSTM")
def _lstm(node, inputs, ctx):
    """ONNX LSTM → lax.scan (default activations: sigmoid, tanh, tanh;
    gate order iofc per the ONNX spec)."""
    X, W, R, B, direction = _rnn_common(node, inputs)
    H = int(node.attr("hidden_size"))
    T, Bt, _ = X.shape
    n_dirs = W.shape[0]
    h0 = (jnp.asarray(inputs[5]) if len(inputs) > 5 and inputs[5] is not None
          else jnp.zeros((n_dirs, Bt, H), X.dtype))
    c0 = (jnp.asarray(inputs[6]) if len(inputs) > 6 and inputs[6] is not None
          else jnp.zeros((n_dirs, Bt, H), X.dtype))
    if len(inputs) > 7 and inputs[7] is not None:
        raise UnsupportedOp("LSTM peephole weights (input P)")

    def cell(carry, x, W, R, B):
        h, c = carry
        gates = x @ W.T + h @ R.T
        if B is not None:
            gates = gates + B[:4 * H] + B[4 * H:]
        i, o, f, g = jnp.split(gates, 4, axis=-1)   # iofc order
        i, o, f = (jax.nn.sigmoid(v) for v in (i, o, f))
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    res = _run_directions(X, W, R, B, h0, (c0,), direction, cell)
    Y = jnp.stack([ys for ys, _ in res], axis=1)        # (T, dirs, B, H)
    Y_h = jnp.stack([carry[0] for _, carry in res], axis=0)
    Y_c = jnp.stack([carry[1] for _, carry in res], axis=0)
    return Y, Y_h, Y_c


@register_op("GRU")
def _gru(node, inputs, ctx):
    """ONNX GRU → lax.scan (gate order zrh; honors linear_before_reset)."""
    X, W, R, B, direction = _rnn_common(node, inputs)
    H = int(node.attr("hidden_size"))
    lbr = bool(node.attr("linear_before_reset", 0))
    T, Bt, _ = X.shape
    n_dirs = W.shape[0]
    h0 = (jnp.asarray(inputs[5]) if len(inputs) > 5 and inputs[5] is not None
          else jnp.zeros((n_dirs, Bt, H), X.dtype))

    def cell(carry, x, W, R, B):
        (h,) = carry
        wb = B[:3 * H] if B is not None else 0.0
        rb = B[3 * H:] if B is not None else 0.0
        gx = x @ W.T + wb                               # (B, 3H)
        gh = h @ R.T + rb
        zx, rx, hx = jnp.split(gx, 3, axis=-1)
        zh, rh, hh = jnp.split(gh, 3, axis=-1)
        z = jax.nn.sigmoid(zx + zh)
        r = jax.nn.sigmoid(rx + rh)
        if lbr:
            # reset applied AFTER the recurrent matmul (gh already has Rbh)
            n = jnp.tanh(hx + r * hh)
        else:
            # ONNX default: reset applied BEFORE the recurrent matmul
            rbh = (B[5 * H:6 * H] if B is not None else 0.0)
            n = jnp.tanh(hx + (r * h) @ R[2 * H:].T + rbh)
        h_new = (1 - z) * n + z * h
        return (h_new,), h_new

    res = _run_directions(X, W, R, B, h0, (), direction, cell)
    Y = jnp.stack([ys for ys, _ in res], axis=1)
    Y_h = jnp.stack([carry[0] for _, carry in res], axis=0)
    return Y, Y_h


def _np_squeeze(node, inputs, ctx):
    x = inputs[0]
    axes = ([int(a) for a in np.ravel(inputs[1])] if len(inputs) > 1
            and inputs[1] is not None else node.attr("axes"))
    return np.squeeze(x, axis=tuple(axes) if axes else None)


NUMPY_OPS: Dict[str, Callable] = {
    "Add": lambda n, i, c: i[0] + i[1],
    "Sub": lambda n, i, c: i[0] - i[1],
    "Mul": lambda n, i, c: i[0] * i[1],
    "Div": lambda n, i, c: (np.trunc(i[0] / i[1]).astype(i[0].dtype)
                            if i[0].dtype.kind in "iu" else i[0] / i[1]),
    "Mod": lambda n, i, c: (np.fmod(i[0], i[1]) if n.attr("fmod", 0)
                            else np.mod(i[0], i[1])),
    "Neg": lambda n, i, c: -i[0],
    "Abs": lambda n, i, c: np.abs(i[0]),
    "Min": lambda n, i, c: np.minimum.reduce(i),
    "Max": lambda n, i, c: np.maximum.reduce(i),
    "Equal": lambda n, i, c: i[0] == i[1],
    "Greater": lambda n, i, c: i[0] > i[1],
    "Less": lambda n, i, c: i[0] < i[1],
    "Where": lambda n, i, c: np.where(i[0], i[1], i[2]),
    "Cast": lambda n, i, c: i[0].astype(ONNX_TO_NUMPY[n.attr("to")]),
    "Concat": lambda n, i, c: np.concatenate(i, axis=n.attr("axis")),
    "Gather": lambda n, i, c: np.take(i[0], i[1], axis=n.attr("axis", 0)),
    "Reshape": lambda n, i, c: i[0].reshape(
        [i[0].shape[k] if d == 0 and not n.attr("allowzero", 0) else d
         for k, d in enumerate(int(x) for x in np.ravel(i[1]))]),
    "Transpose": lambda n, i, c: np.transpose(
        i[0], n.attr("perm") or tuple(reversed(range(i[0].ndim)))),
    "ReduceProd": lambda n, i, c: np.prod(
        i[0], axis=tuple(n.attr("axes")) if n.attr("axes") else None,
        keepdims=bool(n.attr("keepdims", 1))),
    "ReduceSum": lambda n, i, c: np.sum(
        i[0],
        axis=(tuple(int(a) for a in np.ravel(i[1]))
              if c.opset >= 13 and len(i) > 1 and i[1] is not None
              else (tuple(n.attr("axes")) if n.attr("axes") else None)),
        keepdims=bool(n.attr("keepdims", 1))),
    "Slice": _np_slice,
    "Unsqueeze": _np_unsqueeze,
    "Squeeze": _np_squeeze,
    "Identity": lambda n, i, c: i[0],
    "Floor": lambda n, i, c: np.floor(i[0]),
    "Ceil": lambda n, i, c: np.ceil(i[0]),
    "Sqrt": lambda n, i, c: np.sqrt(i[0]),
    "Expand": lambda n, i, c: np.broadcast_to(
        i[0], np.broadcast_shapes(i[0].shape, tuple(int(d) for d in np.ravel(i[1])))),
    "Tile": lambda n, i, c: np.tile(i[0], [int(r) for r in np.ravel(i[1])]),
    "Range": lambda n, i, c: np.arange(np.ravel(i[0])[0], np.ravel(i[1])[0],
                                       np.ravel(i[2])[0]),
}


class _Ctx:
    def __init__(self, opset: int):
        self.opset = opset
        #: outer-scope env during evaluation — ONNX subgraphs (If/Loop/Scan
        #: bodies) capture enclosing tensors by name
        self.scope_env: Optional[Dict[str, object]] = None

    def run_subgraph(self, graph, inputs: List) -> List:
        """Evaluate a subgraph: child scope = outer scope + bound inputs.
        ``inputs`` bind positionally to ``graph.inputs``."""
        env: Dict[str, object] = dict(self.scope_env or {})
        # initializers first: a bound input that shares an initializer's
        # name must win (ONNX optional-input-with-default semantics, same
        # precedence as feeds over initializers at the top level)
        for t in graph.initializers:
            env[t.name] = tensor_to_numpy(t)
        for vi, val in zip(graph.inputs, inputs):
            env[vi.name] = val
        env[""] = None
        _eval_nodes(graph.nodes, env, self)
        return [env[o.name] for o in graph.outputs]


def _eval_nodes(nodes, env: Dict[str, object], ctx: "_Ctx") -> None:
    """Walk a node list, writing outputs into ``env`` (the single graph
    interpreter — top-level graphs and control-flow subgraphs share it)."""
    outer = ctx.scope_env
    ctx.scope_env = env
    try:
        for node in nodes:
            ins = [env[i] if i else None for i in node.input]
            np_handler = NUMPY_OPS.get(node.op_type)
            if np_handler is not None and all(
                    v is None or isinstance(v, (np.ndarray, np.generic))
                    for v in ins) and any(v is not None for v in ins):
                out = np_handler(node, ins, ctx)
            else:
                handler = OP_HANDLERS.get(node.op_type)
                if handler is None:
                    raise UnsupportedOp(
                        f"ONNX op {node.op_type!r} (node {node.name!r}) is "
                        f"not supported; {len(OP_HANDLERS)} ops available")
                out = handler(node, ins, ctx)
            if isinstance(out, tuple):
                for name, val in zip(node.output, out):
                    if name:
                        env[name] = val
            else:
                env[node.output[0]] = out
    finally:
        ctx.scope_env = outer


class ConvertedModel:
    """An ONNX graph compiled to a JAX callable.

    ``fn(params, feeds)`` returns ``{output_name: array}``; ``params`` is the
    initializer dict so callers can shard/donate/quantize it independently.
    """

    def __init__(self, model: ModelProto, external_data_dir=None):
        self.model = model
        g = model.graph
        all_inits = {t.name: tensor_to_numpy(t, external_data_dir)
                     for t in g.initializers}
        # Integer/bool initializers are shape constants, axes, split sizes,
        # gather indices — they must stay concrete at trace time, so they are
        # baked into the function instead of traveling as (traced) jit args.
        self.const_params: Dict[str, np.ndarray] = {
            k: v for k, v in all_inits.items()
            if v.dtype.kind in "iub" or v.ndim == 0}
        self.params: Dict[str, np.ndarray] = {
            k: v for k, v in all_inits.items() if k not in self.const_params}
        init_names = set(all_inits)
        self.inputs: List[ValueInfo] = [vi for vi in g.inputs
                                        if vi.name not in init_names]
        self.outputs: List[ValueInfo] = list(g.outputs)
        self.input_names = [vi.name for vi in self.inputs]
        self.output_names = [vi.name for vi in self.outputs]
        self._ctx = _Ctx(model.opset)

    def __call__(self, params: Dict[str, np.ndarray],
                 feeds: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        env: Dict[str, object] = {}
        env.update(self.const_params)
        env.update(params)
        for name, val in feeds.items():
            env[name] = val
        env[""] = None
        _eval_nodes(self.model.graph.nodes, env, self._ctx)
        missing = [o for o in self.output_names if o not in env]
        if missing:
            raise ValueError(f"graph did not produce outputs {missing}")
        return {o: jnp.asarray(env[o]) for o in self.output_names}

    def jit(self, donate_params: bool = False):
        return jax.jit(self.__call__,
                       donate_argnums=(0,) if donate_params else ())

    def pruned(self, outputs: List[str]) -> "ConvertedModel":
        """Dead-node-eliminated view computing only ``outputs``.

        A training graph (e.g. one carrying a SoftmaxCrossEntropyLoss
        output and a labels input) serves inference by requesting just the
        prediction outputs: the loss node becomes dead, and with it the
        labels input disappears from ``input_names`` — no dummy labels at
        serving time. Ancestor walk covers control-flow subgraph captures
        (If/Loop/Scan bodies read outer-scope names).
        """
        unknown = [o for o in outputs if o not in
                   {n for node in self.model.graph.nodes for n in node.output}
                   | set(self.input_names) | set(self.const_params)
                   | set(self.params)]
        if unknown:
            raise ValueError(f"pruned(): unknown outputs {unknown}")

        def node_reads(node) -> set:
            names = {i for i in node.input if i}
            for a in node.attributes.values():
                for sub in ([a.g] if a.g is not None else []) + list(a.graphs):
                    produced = {n for sn in sub.nodes for n in sn.output}
                    produced |= {vi.name for vi in sub.inputs}
                    produced |= {t.name for t in sub.initializers}
                    for sn in sub.nodes:
                        names |= node_reads(sn) - produced
            return names

        producer = {}
        for node in self.model.graph.nodes:
            for out in node.output:
                if out:
                    producer[out] = node
        needed_nodes: list = []
        seen_ids: set = set()
        stack = list(outputs)
        visited_names: set = set()
        while stack:
            name = stack.pop()
            if name in visited_names:
                continue
            visited_names.add(name)
            node = producer.get(name)
            if node is None or id(node) in seen_ids:
                continue
            seen_ids.add(id(node))
            needed_nodes.append(node)
            stack.extend(node_reads(node))

        import copy
        clone = copy.copy(self)
        clone.model = copy.copy(self.model)
        clone.model.graph = copy.copy(self.model.graph)
        clone.model.graph.nodes = [n for n in self.model.graph.nodes
                                   if id(n) in seen_ids]   # original order
        clone.outputs = [vi for vi in self.outputs if vi.name in outputs]
        clone.output_names = list(outputs)
        used = visited_names
        clone.inputs = [vi for vi in self.inputs if vi.name in used]
        clone.input_names = [vi.name for vi in clone.inputs]
        clone.const_params = {k: v for k, v in self.const_params.items()
                              if k in used}
        clone.params = {k: v for k, v in self.params.items() if k in used}
        clone._ctx = _Ctx(self.model.opset)
        return clone


def convert_model(model_bytes: bytes,
                  external_data_dir=None) -> ConvertedModel:
    """``external_data_dir``: directory holding sidecar files for models
    saved with external data (torch's ``save_as_external_data``)."""
    return ConvertedModel(parse_model(model_bytes), external_data_dir)


# ai.onnx.ml domain handlers (tree ensembles, linear models, preprocessing)
# register themselves on import; placed at module end so register_op exists
from . import ml_ops  # noqa: E402,F401
# long-tail standard ops (audio/DSP, integer-quantized, RNN, losses, ...)
from . import extra_ops  # noqa: E402,F401
from . import generation_ops  # noqa: E402,F401
