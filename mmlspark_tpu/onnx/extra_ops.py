"""Long-tail ONNX standard ops: audio/DSP, integer-quantized, recurrent,
loss, pooling, and bitwise families.

Registered into :mod:`convert`'s ``OP_HANDLERS`` on import (same pattern
as ``ml_ops``). Parity anchor: the reference executes these through
onnxruntime's full opset (``deep-learning/.../onnx/ONNXModel.scala:330``);
here each lowers to XLA with static shapes — size-like inputs must be
trace-time constants (the importer's standing rule), which is exactly how
real exporters emit them.

The audio family (HannWindow/HammingWindow/BlackmanWindow/DFT/STFT/
MelWeightMatrix, opset 17) covers Whisper-style ASR preprocessing graphs —
the speech-service modality the reference reaches via its cognitive
SpeechToText stack.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .convert import (OP_HANDLERS, UnsupportedOp, _concrete, _conv_raw,
                      _pool, _reduce, _rnn_common, _run_directions,
                      register_op)
from .proto import ONNX_TO_NUMPY

# -- reduce stragglers -------------------------------------------------------

OP_HANDLERS["ReduceLogSum"] = _reduce(
    lambda x, axis, keepdims: jnp.log(jnp.sum(x, axis=axis,
                                              keepdims=keepdims)), 18)


# -- bitwise (opset 18) ------------------------------------------------------

for _name, _fn in [("BitwiseAnd", jnp.bitwise_and),
                   ("BitwiseOr", jnp.bitwise_or),
                   ("BitwiseXor", jnp.bitwise_xor)]:
    OP_HANDLERS[_name] = (lambda f: lambda n, i, c: f(i[0], i[1]))(_fn)
OP_HANDLERS["BitwiseNot"] = lambda n, i, c: jnp.bitwise_not(i[0])


# -- normalization / pooling -------------------------------------------------

@register_op("LRN")
def _lrn(node, inputs, ctx):
    """Local response normalization (AlexNet-era): windowed square-sum over
    the channel axis via reduce_window."""
    x = jnp.asarray(inputs[0])
    size = int(node.attr("size"))
    alpha = node.attr("alpha", 1e-4)
    beta = node.attr("beta", 0.75)
    bias = node.attr("bias", 1.0)
    lo = (size - 1) // 2
    hi = size - 1 - lo
    window = (1, size) + (1,) * (x.ndim - 2)
    pads = [(0, 0), (lo, hi)] + [(0, 0)] * (x.ndim - 2)
    sq = lax.reduce_window(x * x, 0.0, lax.add, window,
                           (1,) * x.ndim, pads)
    return x / jnp.power(bias + (alpha / size) * sq, beta)


@register_op("MeanVarianceNormalization")
def _mvn(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    axes = tuple(node.attr("axes", [0, 2, 3]))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=axes, keepdims=True)
    return (x - mean) / jnp.sqrt(var + 1e-9)


def _lp_reduce(x, p, axes):
    if p == 2:
        return jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True))
    ab = jnp.abs(x)
    return jnp.power(jnp.sum(jnp.power(ab, p), axis=axes, keepdims=True),
                     1.0 / p)


@register_op("GlobalLpPool")
def _global_lp_pool(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    return _lp_reduce(x, int(node.attr("p", 2)), tuple(range(2, x.ndim)))


@register_op("LpPool")
def _lp_pool(node, inputs, ctx):
    p = int(node.attr("p", 2))
    x = jnp.asarray(inputs[0])
    powed = jnp.abs(x) ** p
    summed = _pool(node, [powed], ctx, lax.add, 0.0)
    return jnp.power(summed, 1.0 / p)


@register_op("MaxUnpool")
def _max_unpool(node, inputs, ctx):
    """Scatter pooled values back to the indices MaxPool recorded (global
    row-major flat indices, the ORT layout)."""
    x = jnp.asarray(inputs[0])
    idx = jnp.asarray(inputs[1]).astype(jnp.int32)
    if len(inputs) > 2 and inputs[2] is not None:
        out_shape = tuple(int(v) for v in
                          _concrete(inputs[2], "MaxUnpool output_shape"))
    else:
        k = node.attr("kernel_shape")
        strides = node.attr("strides", [1] * len(k))
        pads = node.attr("pads", [0] * 2 * len(k))
        spatial = tuple(
            (x.shape[2 + i] - 1) * strides[i] + k[i]
            - pads[i] - pads[len(k) + i] for i in range(len(k)))
        out_shape = x.shape[:2] + spatial
    flat = jnp.zeros(int(np.prod(out_shape)), x.dtype)
    flat = flat.at[idx.ravel()].set(x.ravel())
    return flat.reshape(out_shape)


# -- integer-quantized (the pre-QLinear wire ops) ----------------------------

def _sub_zp(t, zp, what):
    t = jnp.asarray(t).astype(jnp.int32)
    if zp is None:
        return t
    zp = jnp.asarray(zp).astype(jnp.int32)
    if zp.ndim == 0 or zp.size == 1:
        return t - zp.reshape(())
    if zp.ndim == 1:
        # per-row for A (second-to-last axis), per-column for B (last axis)
        shape = ([1] * (t.ndim - 2) + [-1, 1]) if what == "a" \
            else ([1] * (t.ndim - 2) + [1, -1])
        return t - zp.reshape(shape)
    raise UnsupportedOp(f"MatMulInteger {what}_zero_point rank {zp.ndim}")


@register_op("MatMulInteger")
def _matmul_integer(node, inputs, ctx):
    a = _sub_zp(inputs[0], inputs[2] if len(inputs) > 2 else None, "a")
    b = _sub_zp(inputs[1], inputs[3] if len(inputs) > 3 else None, "b")
    return jnp.matmul(a, b)


@register_op("ConvInteger")
def _conv_integer(node, inputs, ctx):
    x = jnp.asarray(inputs[0]).astype(jnp.int32)
    w = jnp.asarray(inputs[1]).astype(jnp.int32)
    if len(inputs) > 2 and inputs[2] is not None:
        x = x - jnp.asarray(inputs[2]).astype(jnp.int32).reshape(())
    if len(inputs) > 3 and inputs[3] is not None:
        wz = np.asarray(_concrete(inputs[3], "ConvInteger w_zero_point"))
        if wz.size != 1:
            raise UnsupportedOp("ConvInteger per-channel w_zero_point")
        w = w - jnp.int32(wz.ravel()[0])
    return _conv_raw(node, x, w, preferred=jnp.int32)


@register_op("DynamicQuantizeLinear")
def _dynamic_quantize_linear(node, inputs, ctx):
    x = jnp.asarray(inputs[0]).astype(jnp.float32)
    x_min = jnp.minimum(jnp.min(x), 0.0)
    x_max = jnp.maximum(jnp.max(x), 0.0)
    scale = (x_max - x_min) / 255.0
    scale = jnp.where(scale == 0, jnp.float32(1.0), scale)
    zp = jnp.clip(jnp.round(0.0 - x_min / scale), 0, 255)
    y = jnp.clip(jnp.round(x / scale) + zp, 0, 255).astype(jnp.uint8)
    return y, scale.astype(jnp.float32), zp.astype(jnp.uint8)


# -- vanilla RNN (completes the LSTM/GRU trio) -------------------------------

@register_op("RNN")
def _rnn(node, inputs, ctx):
    """ONNX vanilla RNN → lax.scan (default activation Tanh)."""
    X, W, R, B, direction = _rnn_common(
        node, inputs, allowed_acts=(["tanh"], ["tanh"] * 2))
    H = int(node.attr("hidden_size"))
    T, Bt, _ = X.shape
    n_dirs = W.shape[0]
    h0 = (jnp.asarray(inputs[5]) if len(inputs) > 5 and inputs[5] is not None
          else jnp.zeros((n_dirs, Bt, H), X.dtype))

    def cell(carry, x, W, R, B):
        (h,) = carry
        wb = B[:H] if B is not None else 0.0
        rb = B[H:] if B is not None else 0.0
        h_new = jnp.tanh(x @ W.T + wb + h @ R.T + rb)
        return (h_new,), h_new

    res = _run_directions(X, W, R, B, h0, (), direction, cell)
    Y = jnp.stack([ys for ys, _ in res], axis=1)
    Y_h = jnp.stack([carry[0] for _, carry in res], axis=0)
    return Y, Y_h


# -- losses (training-capable graphs) ----------------------------------------

def _nll_core(log_prob, target, weight, ignore_index, reduction):
    # log_prob (N, C, d...); target (N, d...) int
    C = log_prob.shape[1]
    tgt = jnp.asarray(target).astype(jnp.int32)
    valid = jnp.ones(tgt.shape, jnp.float32) if ignore_index is None else \
        (tgt != ignore_index).astype(jnp.float32)
    tgt_safe = jnp.clip(tgt, 0, C - 1)
    gathered = jnp.take_along_axis(
        log_prob, tgt_safe[:, None], axis=1)[:, 0]        # (N, d...)
    w = (jnp.asarray(weight)[tgt_safe].astype(jnp.float32)
         if weight is not None else jnp.ones(tgt.shape, jnp.float32))
    w = w * valid
    loss = -gathered * w
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)   # mean


@register_op("NegativeLogLikelihoodLoss")
def _nll_loss(node, inputs, ctx):
    weight = inputs[2] if len(inputs) > 2 else None
    return _nll_core(jnp.asarray(inputs[0]), inputs[1], weight,
                     node.attr("ignore_index"),
                     node.attr("reduction", "mean"))


@register_op("SoftmaxCrossEntropyLoss")
def _sce_loss(node, inputs, ctx):
    scores = jnp.asarray(inputs[0])
    log_prob = jax.nn.log_softmax(scores, axis=1)
    weight = inputs[2] if len(inputs) > 2 else None
    loss = _nll_core(log_prob, inputs[1], weight,
                     node.attr("ignore_index"),
                     node.attr("reduction", "mean"))
    if len(node.output) > 1:
        return loss, log_prob
    return loss


# -- misc --------------------------------------------------------------------

@register_op("Det")
def _det(node, inputs, ctx):
    return jnp.linalg.det(jnp.asarray(inputs[0]))


def _random(node, shape, dtype_default, normal):
    dt = ONNX_TO_NUMPY.get(node.attr("dtype"), dtype_default)
    # ONNX: seed is optional and behavior without it is implementation-
    # defined; a fixed derivation keeps the compiled graph pure and runs
    # reproducible (the same stance as jax itself)
    import zlib
    seed = node.attr("seed")
    key = jax.random.PRNGKey(np.int64(seed if seed is not None else 0))
    # stable per-node stream (hash() is salted per process — it would make
    # the compiled graph differ between runs)
    key = jax.random.fold_in(key, zlib.crc32(node.output[0].encode()))
    if normal:
        mean = node.attr("mean", 0.0)
        scale = node.attr("scale", 1.0)
        return (mean + scale
                * jax.random.normal(key, shape)).astype(dt)
    low = node.attr("low", 0.0)
    high = node.attr("high", 1.0)
    return jax.random.uniform(key, shape, minval=low, maxval=high).astype(dt)


@register_op("RandomNormal")
def _random_normal(node, inputs, ctx):
    return _random(node, tuple(node.attr("shape")), np.float32, True)


@register_op("RandomUniform")
def _random_uniform(node, inputs, ctx):
    return _random(node, tuple(node.attr("shape")), np.float32, False)


@register_op("RandomNormalLike")
def _random_normal_like(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    return _random(node, x.shape, x.dtype, True)


@register_op("RandomUniformLike")
def _random_uniform_like(node, inputs, ctx):
    x = jnp.asarray(inputs[0])
    return _random(node, x.shape, x.dtype, False)


# -- audio / DSP family (opset 17) -------------------------------------------

def _cosine_window(node, inputs, coeffs):
    size = int(_concrete(inputs[0], "window size").ravel()[0])
    periodic = int(node.attr("periodic", 1))
    dt = ONNX_TO_NUMPY.get(node.attr("output_datatype"), np.float32)
    N = size if periodic else size - 1
    n = jnp.arange(size, dtype=jnp.float32)
    w = jnp.zeros(size, jnp.float32)
    for k, a in enumerate(coeffs):
        w = w + ((-1.0) ** k) * a * jnp.cos(2.0 * np.pi * k * n
                                            / max(N, 1))
    return w.astype(dt)


@register_op("HannWindow")
def _hann_window(node, inputs, ctx):
    return _cosine_window(node, inputs, [0.5, 0.5])


@register_op("HammingWindow")
def _hamming_window(node, inputs, ctx):
    return _cosine_window(node, inputs, [25.0 / 46.0, 21.0 / 46.0])


@register_op("BlackmanWindow")
def _blackman_window(node, inputs, ctx):
    return _cosine_window(node, inputs, [0.42, 0.5, 0.08])


def _as_complex(x, what):
    """[..., 1] real or [..., 2] interleaved → complex."""
    x = jnp.asarray(x)
    if x.shape[-1] == 1:
        return x[..., 0].astype(jnp.complex64)
    if x.shape[-1] == 2:
        return (x[..., 0] + 1j * x[..., 1]).astype(jnp.complex64)
    raise UnsupportedOp(f"{what}: last dim must be 1 (real) or 2 (complex), "
                        f"got {x.shape[-1]}")


def _stack_complex(z):
    return jnp.stack([jnp.real(z), jnp.imag(z)], axis=-1).astype(jnp.float32)


@register_op("DFT")
def _dft(node, inputs, ctx):
    inverse = int(node.attr("inverse", 0))
    onesided = int(node.attr("onesided", 0))
    if inverse and onesided:
        raise UnsupportedOp("DFT inverse+onesided")
    # axis: opset-20 input 2, else attr (default 1 = the signal dim of
    # [batch, n, 1|2])
    if len(inputs) > 2 and inputs[2] is not None:
        axis = int(_concrete(inputs[2], "DFT axis").ravel()[0])
    else:
        axis = int(node.attr("axis", 1))
    z = _as_complex(inputs[0], "DFT")
    if axis < 0:
        # the spec counts axes on the FULL input rank (incl. the trailing
        # real/imag component dim that _as_complex just dropped)
        axis += z.ndim + 1
    n = None
    if len(inputs) > 1 and inputs[1] is not None:
        n = int(_concrete(inputs[1], "DFT dft_length").ravel()[0])
    if inverse:
        out = jnp.fft.ifft(z, n=n, axis=axis)
    elif onesided:
        sig = jnp.asarray(inputs[0])
        if sig.shape[-1] == 1:
            out = jnp.fft.rfft(sig[..., 0].astype(jnp.float32),
                               n=n, axis=axis)
        else:
            full = jnp.fft.fft(z, n=n, axis=axis)
            keep = (n if n is not None else z.shape[axis]) // 2 + 1
            out = lax.slice_in_dim(full, 0, keep, axis=axis)
    else:
        out = jnp.fft.fft(z, n=n, axis=axis)
    return _stack_complex(out)


@register_op("STFT")
def _stft(node, inputs, ctx):
    """[batch, n, 1|2] signal → [batch, frames, dft_bins, 2]."""
    onesided = int(node.attr("onesided", 1))
    signal = jnp.asarray(inputs[0])
    step = int(_concrete(inputs[1], "STFT frame_step").ravel()[0])
    window = (jnp.asarray(inputs[2]).astype(jnp.float32)
              if len(inputs) > 2 and inputs[2] is not None else None)
    if len(inputs) > 3 and inputs[3] is not None:
        frame_length = int(_concrete(inputs[3],
                                     "STFT frame_length").ravel()[0])
    elif window is not None:
        frame_length = int(window.shape[0])
    else:
        raise UnsupportedOp("STFT needs window or frame_length")
    if signal.shape[-1] == 2 and onesided:
        raise UnsupportedOp("STFT onesided over a complex signal")
    z = _as_complex(signal, "STFT")               # (B, N)
    B, N = z.shape
    n_frames = 1 + (N - frame_length) // step
    starts = jnp.arange(n_frames) * step
    gather = starts[:, None] + jnp.arange(frame_length)[None, :]
    frames = z[:, gather]                          # (B, frames, frame_len)
    if window is not None:
        frames = frames * window[None, None, :]
    if onesided:
        out = jnp.fft.rfft(jnp.real(frames).astype(jnp.float32), axis=-1)
    else:
        out = jnp.fft.fft(frames, axis=-1)
    return _stack_complex(out)


@register_op("MelWeightMatrix")
def _mel_weight_matrix(node, inputs, ctx):
    """[dft//2+1, mel_bins] triangular filterbank (HTK mel scale) — the
    tf.signal.linear_to_mel_weight_matrix layout the ONNX spec adopts."""
    nm = int(_concrete(inputs[0], "num_mel_bins").ravel()[0])
    dft = int(_concrete(inputs[1], "dft_length").ravel()[0])
    sr = float(_concrete(inputs[2], "sample_rate").ravel()[0])
    lo = float(_concrete(inputs[3], "lower_edge_hertz").ravel()[0])
    hi = float(_concrete(inputs[4], "upper_edge_hertz").ravel()[0])
    dt = ONNX_TO_NUMPY.get(node.attr("output_datatype"), np.float32)
    n_spec = dft // 2 + 1

    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + np.asarray(f, np.float64) / 700.0)

    mel_edges = np.linspace(hz_to_mel(lo), hz_to_mel(hi), nm + 2)
    spec_hz = np.arange(n_spec) * sr / dft
    spec_mel = hz_to_mel(spec_hz)
    lower = mel_edges[:-2][None, :]               # (1, nm)
    center = mel_edges[1:-1][None, :]
    upper = mel_edges[2:][None, :]
    s = spec_mel[:, None]                         # (n_spec, 1)
    up = (s - lower) / np.maximum(center - lower, 1e-12)
    down = (upper - s) / np.maximum(upper - center, 1e-12)
    w = np.maximum(0.0, np.minimum(up, down))
    return jnp.asarray(w.astype(dt))
