"""ORT generation meta-ops: ``com.microsoft.GreedySearch`` / ``BeamSearch``.

onnxruntime's ``convert_generation`` tool wraps a GPT-style decoder
subgraph in a single node that runs the whole autoregressive loop inside
the session — the reference executes such models opaquely through ORT
(``deep-learning/.../onnx/ONNXModel.scala:330``). Here the loop lowers to
``lax.scan`` over the converted subgraph with STATIC shapes throughout:

* the KV ``past_*`` state lives in fixed (2, B, H, max_length, hd)
  buffers; each step traces the subgraph once at a padded past length and
  the ``attention_mask`` input hides the unwritten tail, so the compiled
  program count is 2 (prefill + step) regardless of sequence length —
  the same padded-cache discipline the zoo's continuous engine uses;
* the step's fresh K/V arrive as the LAST row of the subgraph's
  ``present_*`` outputs and scatter into the buffers at the true length;
* beams fold into the batch axis with per-layer row gathers on reorder
  (the ``zoo.transformer.generate_beam`` formulation applied to an
  imported subgraph).

Subgraph contract (``model_type = 0``, the GPT one): inputs
``input_ids (B, S) · position_ids (B, S) · attention_mask (B, total)``
then ``past_0..past_{L-1}`` each (2, B, H, past_len, hd); outputs
``logits (B, S, V)`` then ``present_0..`` each (2, B, H, past_len+S, hd).
The mask must gate attention scores (ORT's exported subgraphs do), which
is exactly what makes padded pasts sound.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .convert import UnsupportedOp, _concrete, register_op
from .proto import ONNX_TO_NUMPY

__all__ = []


def _static_int(v, what, default=None):
    if v is None:
        if default is None:
            raise UnsupportedOp(f"{what} is required")
        return int(default)
    return int(np.asarray(_concrete(v, what)).ravel()[0])


def _static_float(v, what, default):
    if v is None:
        return float(default)
    return float(np.asarray(_concrete(v, what)).ravel()[0])


class _Decoder:
    """The converted GPT-style subgraph plus everything derived from its
    declared signature (layer count, head/geometry, mask dtype)."""

    def __init__(self, node, ctx, max_length: int):
        graph = node.attr("decoder")
        if graph is None:
            raise UnsupportedOp("GreedySearch/BeamSearch needs a decoder "
                               "subgraph attribute")
        if int(node.attr("model_type", 0)) != 0:
            raise UnsupportedOp("only model_type=0 (GPT, decoder-only) is "
                               "supported")
        if int(node.attr("no_repeat_ngram_size", 0)):
            raise UnsupportedOp("no_repeat_ngram_size")
        self.graph = graph
        self.ctx = ctx
        self.L = len(graph.inputs) - 3
        if self.L < 1:
            raise UnsupportedOp("decoder subgraph declares no past_* "
                               "inputs")
        past_vi = graph.inputs[3]
        dims = list(past_vi.shape)
        if len(dims) != 5:
            raise UnsupportedOp(f"past input rank {len(dims)} != 5")
        self.H, self.hd = dims[2], dims[4]
        if not (isinstance(self.H, int) and isinstance(self.hd, int)):
            raise UnsupportedOp(
                "decoder past inputs need numeric head-count and head-dim "
                f"dims (got {dims})")
        self.mask_np = ONNX_TO_NUMPY.get(graph.inputs[2].elem_type,
                                         np.float32)
        self.max_length = int(max_length)

    def empty_past(self, rows: int):
        return [jnp.zeros((2, rows, self.H, 0, self.hd), jnp.float32)
                for _ in range(self.L)]

    def padded_past(self, rows: int):
        return [jnp.zeros((2, rows, self.H, self.max_length, self.hd),
                          jnp.float32) for _ in range(self.L)]

    def __call__(self, ids, pos, mask, past):
        outs = self.ctx.run_subgraph(
            self.graph, [jnp.asarray(ids, jnp.int32),
                         jnp.asarray(pos, jnp.int32),
                         jnp.asarray(mask).astype(self.mask_np)]
            + list(past))
        return (jnp.asarray(outs[0], jnp.float32),
                [jnp.asarray(p, jnp.float32) for p in outs[1:]])

    # -- the two compiled phases -------------------------------------------
    def prefill(self, input_ids, prompt_mask):
        """(B, P) prompt → (last-token logits (B, V), padded past, seen
        (B, V) token mask). Left-padded prompts follow ORT's convention:
        position_ids = cumsum(mask) - 1."""
        B, P = input_ids.shape
        pos = jnp.maximum(jnp.cumsum(prompt_mask, axis=1) - 1, 0)
        logits, present = self(input_ids, pos, prompt_mask,
                               self.empty_past(B))
        past = self.padded_past(B)
        past = [lax.dynamic_update_slice(buf, pr, (0, 0, 0, 0, 0))
                for buf, pr in zip(past, present)]
        # duplicate (row, token) scatter targets resolve with max: a pad
        # slot's False must not clobber a real occurrence's True
        seen = jnp.zeros((B, self.vocab(logits)), bool).at[
            jnp.arange(B)[:, None], input_ids].max(
                prompt_mask.astype(bool))
        return logits[:, -1], past, seen

    @staticmethod
    def vocab(logits):
        return logits.shape[-1]

    def step(self, tok, cur_len, past, prompt_mask, P):
        """One decode step at padded past length. The mask exposes the
        REAL prompt slots (``prompt_mask`` — left-padded rows keep their
        pad K/V hidden, ORT's batching convention), every generated slot
        in [P, cur_len), and the fresh token's slot at the very end; the
        new K/V (the last ``present`` row) scatters back at cur_len.
        Per-row positions continue the prefill's cumsum: generated token
        number k sits at position (real prompt length + k)."""
        B = tok.shape[0]
        cols = jnp.arange(self.max_length)[None, :]
        pm_full = jnp.pad(jnp.asarray(prompt_mask, jnp.int32),
                          ((0, 0), (0, self.max_length - P)))
        past_ok = jnp.where(cols < P, pm_full,
                            (cols < cur_len).astype(jnp.int32))
        mask = jnp.concatenate([past_ok, jnp.ones((B, 1), jnp.int32)],
                               axis=1)
        plen = jnp.sum(jnp.asarray(prompt_mask, jnp.int32), axis=1,
                       keepdims=True)                       # (B, 1)
        pos = plen + (cur_len - P)
        logits, present = self(tok[:, None], pos, mask, past)
        new = [pr[:, :, :, self.max_length:, :] for pr in present]
        past = [lax.dynamic_update_slice(buf, nv, (0, 0, 0, cur_len, 0))
                for buf, nv in zip(past, new)]
        return logits[:, -1], past


def _adjust_logits(logits, seen, total_len, min_length, eos_id,
                   rep_penalty, vocab_mask):
    """Shared logit processors (HF conventions, which ORT follows):
    min-length eos ban, repetition penalty over seen tokens, vocab mask."""
    if rep_penalty != 1.0:
        pen = jnp.where(logits > 0, logits / rep_penalty,
                        logits * rep_penalty)
        logits = jnp.where(seen, pen, logits)
    if vocab_mask is not None:
        logits = jnp.where(jnp.asarray(vocab_mask, bool)[None, :],
                           logits, -jnp.inf)
    if min_length > 0:
        banned = total_len < min_length
        logits = logits.at[:, eos_id].set(
            jnp.where(banned, -jnp.inf, logits[:, eos_id]))
    return logits


def _common_setup(node, inputs, ctx):
    input_ids = jnp.asarray(inputs[0], jnp.int32)
    max_length = _static_int(inputs[1], "max_length")
    B, P = input_ids.shape
    if P >= max_length:
        raise UnsupportedOp(f"prompt length {P} >= max_length {max_length}")
    dec = _Decoder(node, ctx, max_length)
    eos = int(node.attr("eos_token_id", -1))
    pad = int(node.attr("pad_token_id", -1))
    if eos < 0 or pad < 0:
        raise UnsupportedOp("eos_token_id and pad_token_id attributes are "
                           "required")
    return input_ids, max_length, dec, eos, pad


@register_op("GreedySearch")
def _greedy_search(node, inputs, ctx):
    input_ids, max_length, dec, eos, pad = _common_setup(node, inputs, ctx)
    B, P = input_ids.shape
    min_length = _static_int(inputs[2] if len(inputs) > 2 else None,
                             "min_length", default=0)
    rep = _static_float(inputs[3] if len(inputs) > 3 else None,
                        "repetition_penalty", 1.0)
    vocab_mask = inputs[4] if len(inputs) > 4 else None
    if len(inputs) > 5 and inputs[5] is not None:
        raise UnsupportedOp("prefix_vocab_mask")
    attn = (jnp.asarray(inputs[6], jnp.int32) if len(inputs) > 6
            and inputs[6] is not None else jnp.ones((B, P), jnp.int32))

    # prefill emits buffer position P; the scan emits P+1 .. max_length-1
    # (one step per position: feed the token at index t, cur_len = t,
    # collect the token for index t+1). eos appears in the output and
    # everything after it is pad_token_id — ORT's layout.
    # min_length follows ORT/HF: eos is banned while the length BEFORE
    # appending the new token is < min_length
    logits0, past, seen = dec.prefill(input_ids, attn)
    logits0 = _adjust_logits(logits0, seen, P, min_length, eos, rep,
                             vocab_mask)
    tok = jnp.argmax(logits0, axis=-1).astype(jnp.int32)
    done = tok == eos

    def body(carry, t):
        tok, done, past, seen = carry
        seen = seen.at[jnp.arange(B), tok].set(True)
        logits, past = dec.step(tok, t, past, attn, P)
        logits = _adjust_logits(logits, seen, t + 1, min_length, eos, rep,
                                vocab_mask)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, pad, nxt)
        return (nxt, done | (nxt == eos), past, seen), nxt

    buf = jnp.zeros((B, max_length), jnp.int32).at[:, :P].set(input_ids)
    buf = buf.at[:, P].set(tok)
    if max_length - 1 > P:
        _, toks = lax.scan(body, (tok, done, past, seen),
                           jnp.arange(P, max_length - 1, dtype=jnp.int32))
        buf = buf.at[:, P + 1:].set(toks.T)
    return buf


@register_op("BeamSearch")
def _beam_search(node, inputs, ctx):
    input_ids, max_length, dec, eos, pad = _common_setup(node, inputs, ctx)
    B, P = input_ids.shape
    min_length = _static_int(inputs[2] if len(inputs) > 2 else None,
                             "min_length", default=0)
    W = _static_int(inputs[3] if len(inputs) > 3 else None, "num_beams")
    R = _static_int(inputs[4] if len(inputs) > 4 else None,
                    "num_return_sequences", default=1)
    lp = _static_float(inputs[5] if len(inputs) > 5 else None,
                       "length_penalty", 1.0)
    rep = _static_float(inputs[6] if len(inputs) > 6 else None,
                        "repetition_penalty", 1.0)
    vocab_mask = inputs[7] if len(inputs) > 7 else None
    if len(inputs) > 8 and inputs[8] is not None:
        raise UnsupportedOp("prefix_vocab_mask")
    attn = (jnp.asarray(inputs[9], jnp.int32) if len(inputs) > 9
            and inputs[9] is not None else jnp.ones((B, P), jnp.int32))
    if W < 1 or R < 1 or R > W:
        raise UnsupportedOp(f"need 1 <= num_return_sequences ({R}) <= "
                           f"num_beams ({W})")
    # scores follow the zoo's convention: cumulative log-prob over the
    # GENERATED tokens, length-penalized as sum / len**length_penalty at
    # banking time (early_stopping attr is accepted; the loop always runs
    # to max_length, i.e. early_stopping=False semantics — hypotheses can
    # only improve)

    def penalize(score, length):
        return score / (jnp.asarray(length, jnp.float32) ** jnp.float32(lp))

    logits0, past, seen = dec.prefill(input_ids, attn)
    V = logits0.shape[-1]
    if W > V:
        raise UnsupportedOp(f"num_beams {W} exceeds vocab {V}")
    logits0 = _adjust_logits(logits0, seen, P, min_length, eos, rep,
                             vocab_mask)
    logp0 = jax.nn.log_softmax(logits0, axis=-1)
    batch_ix = jnp.arange(B)[:, None]
    k0 = min(2 * W, V)
    c_scores, c_tok = lax.top_k(logp0, k0)                  # (B, k0)
    M = max_length
    c_seqs = (jnp.zeros((B, k0, M), jnp.int32)
              .at[:, :, :P].set(input_ids[:, None, :])
              .at[:, :, P].set(c_tok))
    c_eos = c_tok == eos
    bank0 = jnp.where(c_eos, penalize(c_scores, 1), -jnp.inf)
    fin_scores, keep = lax.top_k(bank0, W)
    fin_seqs = c_seqs[batch_ix, keep]
    scores, pick = lax.top_k(jnp.where(c_eos, -jnp.inf, c_scores), W)
    seqs = c_seqs[batch_ix, pick]
    tok = c_tok[batch_ix, pick].reshape(B * W)
    # fold beams into the batch axis of every stateful buffer
    past = [jnp.repeat(buf, W, axis=1) for buf in past]
    seen = jnp.repeat(seen, W, axis=0)                      # (B*W, V)
    attn_w = jnp.repeat(attn, W, axis=0)

    def body(carry, t):
        seqs, scores, fin_scores, fin_seqs, tok, past, seen = carry
        seen = seen.at[jnp.arange(B * W), tok].set(True)
        logits, past = dec.step(tok, t, past, attn_w, P)
        logits = _adjust_logits(logits, seen, t + 1, min_length, eos, rep,
                                vocab_mask)
        logp = jax.nn.log_softmax(logits, axis=-1)          # (B*W, V)
        cand = scores[:, :, None] + logp.reshape(B, W, V)
        c_scores, c_idx = lax.top_k(cand.reshape(B, W * V), 2 * W)
        c_parent = c_idx // V
        c_tok = (c_idx % V).astype(jnp.int32)
        c_seqs = seqs[batch_ix, c_parent]
        c_seqs = jnp.where(jnp.arange(M)[None, None] == t + 1,
                           c_tok[:, :, None], c_seqs)
        c_eos = c_tok == eos
        gen_len = t + 2 - P                    # generated tokens incl. eos
        pool_s = jnp.concatenate(
            [fin_scores, jnp.where(c_eos, penalize(c_scores, gen_len),
                                   -jnp.inf)], axis=1)
        pool_q = jnp.concatenate([fin_seqs, c_seqs], axis=1)
        fin_scores, keep = lax.top_k(pool_s, W)
        fin_seqs = pool_q[batch_ix, keep]
        scores, pick = lax.top_k(jnp.where(c_eos, -jnp.inf, c_scores), W)
        parent = c_parent[batch_ix, pick]
        seqs = c_seqs[batch_ix, pick]
        tok = c_tok[batch_ix, pick].reshape(B * W)
        rows = (jnp.arange(B)[:, None] * W + parent).reshape(B * W)
        past = [buf[:, rows] for buf in past]
        seen = seen[rows]
        return (seqs, scores, fin_scores, fin_seqs, tok, past, seen), None

    if M - 1 > P:
        (seqs, scores, fin_scores, fin_seqs, tok, past, seen), _ = lax.scan(
            body, (seqs, scores, fin_scores, fin_seqs, tok, past, seen),
            jnp.arange(P, M - 1, dtype=jnp.int32))

    all_s = jnp.concatenate([fin_scores, penalize(scores, M - P)], axis=1)
    all_q = jnp.concatenate([fin_seqs, seqs], axis=1)       # (B, 2W, M)
    top_s, top_i = lax.top_k(all_s, R)
    out = all_q[batch_ix, top_i]                            # (B, R, M)
    # pad strictly after the first eos PAST the prompt (a prompt token
    # equal to eos must not trigger padding)
    gen_eos = (out == eos) & (jnp.arange(M)[None, None, :] >= P)
    after = jnp.pad(jnp.cumsum(gen_eos.astype(jnp.int32), axis=-1) > 0,
                    ((0, 0), (0, 0), (1, 0)))[:, :, :-1]
    out = jnp.where(after, pad, out)
    return out, top_s
