"""Protobuf wire-format codec (no protobuf runtime dependency).

The environment ships no ``onnx`` package, and the reference reads ONNX
models through onnxruntime's native session
(``deep-learning/.../onnx/ONNXModel.scala:437-457``). We instead parse the
ONNX protobuf directly: the wire format is tiny — varint tags, four payload
kinds — and decoding it ourselves keeps model metadata reads session-free.

Wire types: 0 = VARINT, 1 = I64, 2 = LEN (length-delimited), 5 = I32.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Tuple, Union

__all__ = ["read_varint", "iter_fields", "decode_zigzag",
           "WireWriter", "encode_varint"]


def read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def decode_zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, Union[int, bytes]]]:
    """Yield (field_number, wire_type, payload) for one serialized message.

    LEN payloads are returned as bytes; VARINT as int; I32/I64 as raw bytes.
    """
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = read_varint(data, pos)
        field, wtype = tag >> 3, tag & 7
        if wtype == 0:
            val, pos = read_varint(data, pos)
            yield field, wtype, val
        elif wtype == 1:
            yield field, wtype, data[pos:pos + 8]
            pos += 8
        elif wtype == 2:
            ln, pos = read_varint(data, pos)
            if pos + ln > n:
                raise ValueError(f"truncated LEN field {field}")
            yield field, wtype, data[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            yield field, wtype, data[pos:pos + 4]
            pos += 4
        elif wtype in (3, 4):  # group markers: obsolete, skip silently
            continue
        else:
            raise ValueError(f"unknown wire type {wtype} for field {field}")


def encode_varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64  # two's-complement for negative int64 (proto semantics)
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class WireWriter:
    """Append-only message builder."""

    def __init__(self):
        self._parts: List[bytes] = []

    def _tag(self, field: int, wtype: int) -> None:
        self._parts.append(encode_varint((field << 3) | wtype))

    def varint(self, field: int, value: int) -> "WireWriter":
        self._tag(field, 0)
        self._parts.append(encode_varint(int(value)))
        return self

    def bool(self, field: int, value: bool) -> "WireWriter":
        return self.varint(field, 1 if value else 0)

    def float32(self, field: int, value: float) -> "WireWriter":
        self._tag(field, 5)
        self._parts.append(struct.pack("<f", value))
        return self

    def double(self, field: int, value: float) -> "WireWriter":
        self._tag(field, 1)
        self._parts.append(struct.pack("<d", value))
        return self

    def bytes(self, field: int, value: bytes) -> "WireWriter":
        self._tag(field, 2)
        self._parts.append(encode_varint(len(value)))
        self._parts.append(bytes(value))
        return self

    def string(self, field: int, value: str) -> "WireWriter":
        return self.bytes(field, value.encode("utf-8"))

    def message(self, field: int, sub: "WireWriter") -> "WireWriter":
        return self.bytes(field, sub.to_bytes())

    def packed_varints(self, field: int, values) -> "WireWriter":
        payload = b"".join(encode_varint(int(v)) for v in values)
        return self.bytes(field, payload)

    def packed_floats(self, field: int, values) -> "WireWriter":
        import numpy as np
        return self.bytes(field, np.asarray(values, dtype="<f4").tobytes())

    def packed_doubles(self, field: int, values) -> "WireWriter":
        import numpy as np
        return self.bytes(field, np.asarray(values, dtype="<f8").tobytes())

    def to_bytes(self) -> bytes:
        return b"".join(self._parts)
