"""ONNX protobuf messages: parse + minimal object model.

Field numbers follow the public ONNX IR spec (onnx/onnx.proto). Only the
messages the converter needs are modeled; unknown fields are skipped, so
models produced by any exporter parse as long as they use the standard IR.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

import numpy as np

from .wire import iter_fields, read_varint

__all__ = ["TensorProto", "AttributeProto", "NodeProto", "GraphProto",
           "ModelProto", "ValueInfo", "DataType", "tensor_to_numpy",
           "parse_model", "model_content_digest", "NUMPY_TO_ONNX",
           "ONNX_TO_NUMPY"]


class DataType:
    FLOAT = 1
    UINT8 = 2
    INT8 = 3
    UINT16 = 4
    INT16 = 5
    INT32 = 6
    INT64 = 7
    STRING = 8
    BOOL = 9
    FLOAT16 = 10
    DOUBLE = 11
    UINT32 = 12
    UINT64 = 13
    COMPLEX64 = 14
    COMPLEX128 = 15
    BFLOAT16 = 16

ONNX_TO_NUMPY = {
    DataType.FLOAT: np.float32,
    DataType.UINT8: np.uint8,
    DataType.INT8: np.int8,
    DataType.UINT16: np.uint16,
    DataType.INT16: np.int16,
    DataType.INT32: np.int32,
    DataType.INT64: np.int64,
    DataType.BOOL: np.bool_,
    DataType.FLOAT16: np.float16,
    DataType.DOUBLE: np.float64,
    DataType.UINT32: np.uint32,
    DataType.UINT64: np.uint64,
}

NUMPY_TO_ONNX = {np.dtype(v): k for k, v in ONNX_TO_NUMPY.items()}


def _unpack_numeric(payload: Union[int, bytes], wtype: int, fmt: str):
    """One repeated-numeric element, or a packed run of them."""
    if wtype == 2:  # packed
        return list(np.frombuffer(payload, dtype=fmt))
    if wtype == 5:
        return [struct.unpack("<f", payload)[0] if fmt == "<f4"
                else struct.unpack("<i", payload)[0]]
    if wtype == 1:
        return [struct.unpack("<d", payload)[0] if fmt == "<f8"
                else struct.unpack("<q", payload)[0]]
    return [payload]


def _unpack_varints(payload: Union[int, bytes], wtype: int,
                    signed: bool = True) -> List[int]:
    if wtype == 0:
        v = payload
        if signed and v >= 1 << 63:
            v -= 1 << 64
        return [int(v)]
    vals, pos = [], 0
    while pos < len(payload):
        v, pos = read_varint(payload, pos)
        if signed and v >= 1 << 63:
            v -= 1 << 64
        vals.append(int(v))
    return vals


@dataclass
class TensorProto:
    dims: List[int] = field(default_factory=list)
    data_type: int = 0
    float_data: List[float] = field(default_factory=list)
    int32_data: List[int] = field(default_factory=list)
    string_data: List[bytes] = field(default_factory=list)
    int64_data: List[int] = field(default_factory=list)
    name: str = ""
    raw_data: bytes = b""
    double_data: List[float] = field(default_factory=list)
    uint64_data: List[int] = field(default_factory=list)
    # torch/onnx exporters spill big initializers to sidecar files
    # (save_as_external_data): data_location=EXTERNAL(1) + external_data
    # entries {location, offset, length}
    data_location: int = 0
    external_data: Dict[str, str] = field(default_factory=dict)

    EXTERNAL = 1

    @staticmethod
    def parse(data: bytes) -> "TensorProto":
        t = TensorProto()
        for f, w, v in iter_fields(data):
            if f == 1:
                t.dims.extend(_unpack_varints(v, w))
            elif f == 2:
                t.data_type = v
            elif f == 4:
                t.float_data.extend(_unpack_numeric(v, w, "<f4"))
            elif f == 5:
                t.int32_data.extend(_unpack_varints(v, w))
            elif f == 6:
                t.string_data.append(v)
            elif f == 7:
                t.int64_data.extend(_unpack_varints(v, w))
            elif f == 8:
                t.name = v.decode("utf-8")
            elif f == 9:
                t.raw_data = v
            elif f == 10:
                t.double_data.extend(_unpack_numeric(v, w, "<f8"))
            elif f == 11:
                t.uint64_data.extend(_unpack_varints(v, w, signed=False))
            elif f == 13:  # StringStringEntryProto {key=1, value=2}
                key = val = ""
                for f2, _w2, v2 in iter_fields(v):
                    if f2 == 1:
                        key = v2.decode("utf-8")
                    elif f2 == 2:
                        val = v2.decode("utf-8")
                t.external_data[key] = val
            elif f == 14:
                t.data_location = v
        return t


def tensor_to_numpy(t: TensorProto,
                    external_dir: Optional[str] = None) -> np.ndarray:
    shape = tuple(t.dims)
    np_dtype = ONNX_TO_NUMPY.get(t.data_type)
    if t.data_location == TensorProto.EXTERNAL:
        import os
        if external_dir is None:
            raise ValueError(
                f"initializer {t.name!r} stores its data externally "
                f"({t.external_data.get('location')!r}); pass "
                "external_data_dir (the directory holding the sidecar files)")
        loc = t.external_data.get("location", "")
        # the location is spec'd relative to the model file; forbid escapes
        base = os.path.abspath(external_dir)
        path = os.path.abspath(os.path.join(base, loc))
        if not path.startswith(base + os.sep):
            raise ValueError(f"external data location {loc!r} escapes "
                             f"{external_dir!r}")
        offset = int(t.external_data.get("offset", 0) or 0)
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if np_dtype is None and t.data_type != DataType.BFLOAT16:
            raise ValueError(
                f"unsupported external tensor dtype {t.data_type}")
        if t.data_type == DataType.BFLOAT16:
            import jax.numpy as jnp
            raw = np.fromfile(path, dtype=np.uint16, count=count,
                              offset=offset)
            return raw.view(jnp.bfloat16.dtype).reshape(shape)
        return np.fromfile(path, dtype=np_dtype, count=count,
                           offset=offset).reshape(shape)
    if t.data_type == DataType.STRING:
        arr = np.array([s.decode("utf-8", "replace") for s in t.string_data],
                       dtype=object)
        return arr.reshape(shape)
    if np_dtype is None:
        raise ValueError(f"unsupported tensor dtype {t.data_type} for {t.name!r}")
    if t.raw_data:
        if t.data_type == DataType.BFLOAT16:
            import jax.numpy as jnp
            raw = np.frombuffer(t.raw_data, dtype=np.uint16)
            return raw.view(jnp.bfloat16.dtype).reshape(shape)  # type: ignore
        return np.frombuffer(t.raw_data, dtype=np_dtype).reshape(shape).copy()
    for data in (t.float_data, t.int64_data, t.int32_data, t.double_data,
                 t.uint64_data):
        if data:
            arr = np.asarray(data)
            if t.data_type == DataType.FLOAT16:
                arr = arr.astype(np.uint16).view(np.float16)
            elif t.data_type == DataType.BFLOAT16:
                import jax.numpy as jnp
                arr = arr.astype(np.uint16).view(jnp.bfloat16.dtype)
            else:
                arr = arr.astype(np_dtype)
            return arr.reshape(shape)
    return np.zeros(shape, dtype=np_dtype)


class AttrType:
    FLOAT = 1
    INT = 2
    STRING = 3
    TENSOR = 4
    GRAPH = 5
    FLOATS = 6
    INTS = 7
    STRINGS = 8
    TENSORS = 9
    GRAPHS = 10


@dataclass
class AttributeProto:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional[TensorProto] = None
    g: Optional["GraphProto"] = None
    floats: List[float] = field(default_factory=list)
    ints: List[int] = field(default_factory=list)
    strings: List[bytes] = field(default_factory=list)
    tensors: List[TensorProto] = field(default_factory=list)
    graphs: List["GraphProto"] = field(default_factory=list)

    @staticmethod
    def parse(data: bytes) -> "AttributeProto":
        a = AttributeProto()
        for f_, w, v in iter_fields(data):
            if f_ == 1:
                a.name = v.decode("utf-8")
            elif f_ == 2:
                a.f = struct.unpack("<f", v)[0]
            elif f_ == 3:
                a.i = _unpack_varints(v, w)[0]
            elif f_ == 4:
                a.s = v
            elif f_ == 5:
                a.t = TensorProto.parse(v)
            elif f_ == 6:
                a.g = GraphProto.parse(v)
            elif f_ == 7:
                a.floats.extend(_unpack_numeric(v, w, "<f4"))
            elif f_ == 8:
                a.ints.extend(_unpack_varints(v, w))
            elif f_ == 9:
                a.strings.append(v)
            elif f_ == 10:
                a.tensors.append(TensorProto.parse(v))
            elif f_ == 11:
                a.graphs.append(GraphProto.parse(v))
            elif f_ == 20:
                a.type = v
        return a

    def value(self):
        if self.type == AttrType.FLOAT:
            return float(self.f)
        if self.type == AttrType.INT:
            return int(self.i)
        if self.type == AttrType.STRING:
            return self.s.decode("utf-8")
        if self.type == AttrType.TENSOR:
            return tensor_to_numpy(self.t)
        if self.type == AttrType.GRAPH:
            return self.g
        if self.type == AttrType.FLOATS:
            return [float(x) for x in self.floats]
        if self.type == AttrType.INTS:
            return [int(x) for x in self.ints]
        if self.type == AttrType.STRINGS:
            return [s.decode("utf-8") for s in self.strings]
        if self.type == AttrType.TENSORS:
            return [tensor_to_numpy(t) for t in self.tensors]
        if self.type == AttrType.GRAPHS:
            return list(self.graphs)
        # exporters sometimes omit `type`; infer from populated slots
        for cand in ("ints", "floats", "strings"):
            if getattr(self, cand):
                return getattr(self, cand)
        if self.t is not None:
            return tensor_to_numpy(self.t)
        if self.s:
            return self.s.decode("utf-8")
        return self.i if self.i else self.f


@dataclass
class NodeProto:
    input: List[str] = field(default_factory=list)
    output: List[str] = field(default_factory=list)
    name: str = ""
    op_type: str = ""
    domain: str = ""
    attributes: Dict[str, AttributeProto] = field(default_factory=dict)

    @staticmethod
    def parse(data: bytes) -> "NodeProto":
        n = NodeProto()
        for f_, w, v in iter_fields(data):
            if f_ == 1:
                n.input.append(v.decode("utf-8"))
            elif f_ == 2:
                n.output.append(v.decode("utf-8"))
            elif f_ == 3:
                n.name = v.decode("utf-8")
            elif f_ == 4:
                n.op_type = v.decode("utf-8")
            elif f_ == 5:
                a = AttributeProto.parse(v)
                n.attributes[a.name] = a
            elif f_ == 7:
                n.domain = v.decode("utf-8")
        return n

    def attr(self, name: str, default=None):
        a = self.attributes.get(name)
        return default if a is None else a.value()

    @property
    def attribute(self) -> List[AttributeProto]:
        """Protobuf-canonical field name (consumers like torch's exporter
        shim walk ``node.attribute``)."""
        return list(self.attributes.values())


@dataclass
class ValueInfo:
    name: str = ""
    elem_type: int = 0
    shape: List[Optional[Union[int, str]]] = field(default_factory=list)

    @staticmethod
    def parse(data: bytes) -> "ValueInfo":
        vi = ValueInfo()
        for f_, _w, v in iter_fields(data):
            if f_ == 1:
                vi.name = v.decode("utf-8")
            elif f_ == 2:
                vi._parse_type(v)
        return vi

    def _parse_type(self, data: bytes):
        for f_, _w, v in iter_fields(data):
            if f_ == 1:  # tensor_type
                for f2, _w2, v2 in iter_fields(v):
                    if f2 == 1:
                        self.elem_type = v2
                    elif f2 == 2:  # shape
                        for f3, _w3, v3 in iter_fields(v2):
                            if f3 == 1:  # dim
                                dim: Optional[Union[int, str]] = None
                                for f4, _w4, v4 in iter_fields(v3):
                                    if f4 == 1:
                                        dim = int(v4)
                                    elif f4 == 2:
                                        dim = v4.decode("utf-8")
                                self.shape.append(dim)

    @property
    def numpy_dtype(self):
        return ONNX_TO_NUMPY.get(self.elem_type, np.float32)


@dataclass
class GraphProto:
    nodes: List[NodeProto] = field(default_factory=list)
    name: str = ""
    initializers: List[TensorProto] = field(default_factory=list)
    inputs: List[ValueInfo] = field(default_factory=list)
    outputs: List[ValueInfo] = field(default_factory=list)
    value_info: List[ValueInfo] = field(default_factory=list)

    @staticmethod
    def parse(data: bytes) -> "GraphProto":
        g = GraphProto()
        for f_, _w, v in iter_fields(data):
            if f_ == 1:
                g.nodes.append(NodeProto.parse(v))
            elif f_ == 2:
                g.name = v.decode("utf-8")
            elif f_ == 5:
                g.initializers.append(TensorProto.parse(v))
            elif f_ == 11:
                g.inputs.append(ValueInfo.parse(v))
            elif f_ == 12:
                g.outputs.append(ValueInfo.parse(v))
            elif f_ == 13:
                g.value_info.append(ValueInfo.parse(v))
        return g

    @property
    def node(self) -> List[NodeProto]:
        """Protobuf-canonical field name (``graph.node`` in onnx proper)."""
        return self.nodes


@dataclass
class ModelProto:
    ir_version: int = 0
    producer_name: str = ""
    graph: Optional[GraphProto] = None
    opset_imports: Dict[str, int] = field(default_factory=dict)
    #: onnxscript FunctionProtos — parsed models never populate this; it
    #: exists so protobuf-shaped consumers (the torch exporter shim) can
    #: check it is empty
    functions: List[object] = field(default_factory=list)

    def SerializeToString(self) -> bytes:
        raise NotImplementedError(
            "this parsed ModelProto is read-only; re-serialization (only "
            "needed when onnxscript custom functions are present) is not "
            "supported — build models with mmlspark_tpu.onnx.builder")

    @staticmethod
    def parse(data: bytes) -> "ModelProto":
        m = ModelProto()
        for f_, w, v in iter_fields(data):
            if f_ == 1:
                m.ir_version = v
            elif f_ == 2:
                m.producer_name = v.decode("utf-8")
            elif f_ == 7:
                m.graph = GraphProto.parse(v)
            elif f_ == 8:
                domain, version = "", 0
                for f2, _w2, v2 in iter_fields(v):
                    if f2 == 1:
                        domain = v2.decode("utf-8")
                    elif f2 == 2:
                        version = v2
                m.opset_imports[domain] = version
        return m

    @property
    def opset(self) -> int:
        return self.opset_imports.get("", 13)


def parse_model(data: bytes) -> ModelProto:
    m = ModelProto.parse(data)
    if m.graph is None:
        raise ValueError("not an ONNX model: no graph found")
    return m


def _digest_tensor(h, t: TensorProto) -> None:
    h.update(repr((t.name, tuple(t.dims), t.data_type,
                   t.data_location)).encode())
    h.update(t.raw_data)
    for lst in (t.float_data, t.int32_data, t.int64_data, t.double_data,
                t.uint64_data):
        if lst:
            h.update(repr(lst).encode())
    for s in t.string_data:
        h.update(s)


def _digest_graph(h, g: GraphProto) -> None:
    for vi in list(g.inputs) + list(g.outputs):
        h.update(repr((vi.name, vi.elem_type, tuple(vi.shape))).encode())
    for t in g.initializers:
        _digest_tensor(h, t)
    for n in g.nodes:
        # n.name deliberately excluded: the builder auto-names nodes from
        # object ids, so identical graphs serialize differently per process
        h.update(repr((n.op_type, n.domain, tuple(n.input),
                       tuple(n.output))).encode())
        for aname in sorted(n.attributes):
            a = n.attributes[aname]
            h.update(repr((aname, a.type, a.f, a.i, a.s, tuple(a.floats),
                           tuple(a.ints), tuple(a.strings))).encode())
            if a.t is not None:
                _digest_tensor(h, a.t)
            for t in a.tensors:
                _digest_tensor(h, t)
            for sub in ([a.g] if a.g is not None else []) + list(a.graphs):
                _digest_graph(h, sub)


def model_content_digest(data: bytes) -> str:
    """SHA-1 hex digest of a serialized model's *semantic* content —
    opsets, graph topology, tensor types/shapes, initializer bytes — but
    not node names, which the builder derives from object ids and which
    therefore differ across processes for identical graphs. Stable
    identity for caches keyed by "what does this model compute" (the
    autotuner's observation store). Unparseable bytes fall back to a hash
    of the bytes themselves."""
    import hashlib
    h = hashlib.sha1()
    try:
        m = parse_model(bytes(data))
    except Exception:
        h.update(bytes(data))
        return h.hexdigest()
    h.update(repr(sorted(m.opset_imports.items())).encode())
    _digest_graph(h, m.graph)
    return h.hexdigest()
