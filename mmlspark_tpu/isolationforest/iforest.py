"""Isolation forest anomaly detection.

Parity surface: ``IsolationForest:18``/``IsolationForestModel:42`` (reference
``core/.../isolationforest/IsolationForest.scala``, wrapping LinkedIn's
isolation-forest). Re-implemented natively: trees are built host-side on
subsamples (cheap, O(n log n)), and scoring is fully vectorized — each tree is
flat arrays (feature, threshold, child pointers) and all rows descend the
tree in lockstep numpy steps.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasFeaturesCol, HasPredictionCol, Param
from ..core.pipeline import Estimator, Model

__all__ = ["IsolationForest", "IsolationForestModel"]


def _avg_path_length(n: float) -> float:
    """c(n): average BST unsuccessful-search depth (the iForest normalizer)."""
    if n <= 1:
        return 0.0
    h = np.log(n - 1) + 0.5772156649
    return 2.0 * h - 2.0 * (n - 1) / n


def _build_tree(X: np.ndarray, rng: np.random.Generator, max_depth: int):
    """Arrays: feature, threshold, left, right, size (leaf: feature=-1)."""
    feats: List[int] = []
    thres: List[float] = []
    lefts: List[int] = []
    rights: List[int] = []
    sizes: List[int] = []

    def rec(idx: np.ndarray, depth: int) -> int:
        node = len(feats)
        feats.append(-1)
        thres.append(0.0)
        lefts.append(-1)
        rights.append(-1)
        sizes.append(len(idx))
        if depth >= max_depth or len(idx) <= 1:
            return node
        d = X.shape[1]
        for _ in range(d):  # find a splittable feature
            f = int(rng.integers(d))
            col = X[idx, f]
            lo, hi = col.min(), col.max()
            if hi > lo:
                t = float(rng.uniform(lo, hi))
                feats[node] = f
                thres[node] = t
                left_idx = idx[col < t]
                right_idx = idx[col >= t]
                lefts[node] = rec(left_idx, depth + 1)
                rights[node] = rec(right_idx, depth + 1)
                break
        return node

    rec(np.arange(len(X)), 0)
    return {"feature": np.asarray(feats, np.int64),
            "threshold": np.asarray(thres, np.float64),
            "left": np.asarray(lefts, np.int64),
            "right": np.asarray(rights, np.int64),
            "size": np.asarray(sizes, np.int64)}


def _tree_path_lengths(tree: dict, X: np.ndarray) -> np.ndarray:
    """All rows descend in lockstep; done rows hold their node."""
    n = len(X)
    node = np.zeros(n, dtype=np.int64)
    depth = np.zeros(n, dtype=np.float64)
    feature = tree["feature"]
    for _ in range(1 + int(np.log2(max(2, len(feature))) * 4)):
        f = feature[node]
        active = f >= 0
        if not active.any():
            break
        x = X[np.arange(n), np.where(active, f, 0)]
        go_left = x < tree["threshold"][node]
        nxt = np.where(go_left, tree["left"][node], tree["right"][node])
        node = np.where(active, nxt, node)
        depth += active
    leaf_size = tree["size"][node].astype(np.float64)
    return depth + np.vectorize(_avg_path_length)(leaf_size)


class IsolationForest(Estimator, HasFeaturesCol, HasPredictionCol):
    num_estimators = Param(int, default=100, doc="trees in the forest")
    max_samples = Param(int, default=256, doc="subsample size per tree")
    max_features = Param(float, default=1.0, doc="parity flag; unused")
    contamination = Param(float, default=0.0,
                          doc="expected outlier fraction (sets the threshold; "
                              "0 keeps the 0.5 score convention)")
    score_col = Param(str, default="outlierScore", doc="anomaly score column")
    seed = Param(int, default=0, doc="PRNG seed")

    def _fit(self, df: DataFrame) -> "IsolationForestModel":
        col = df[self.get("features_col")]
        X = (np.stack([np.asarray(v, dtype=np.float64).ravel() for v in col])
             if col.dtype == object else
             np.asarray(col, dtype=np.float64).reshape(len(df), -1))
        rng = np.random.default_rng(self.get("seed"))
        psi = min(self.get("max_samples"), len(X))
        max_depth = int(np.ceil(np.log2(max(2, psi))))
        trees = []
        for _ in range(self.get("num_estimators")):
            sub = rng.choice(len(X), size=psi, replace=False)
            trees.append(_build_tree(X[sub], rng, max_depth))

        m = IsolationForestModel()
        m.set(features_col=self.get("features_col"),
              prediction_col=self.get("prediction_col"),
              score_col=self.get("score_col"),
              trees=trees, subsample_size=psi)
        if self.get("contamination") > 0:
            scores = m._scores(X)
            thr = float(np.quantile(scores, 1.0 - self.get("contamination")))
            m.set(threshold=thr)
        return m


class IsolationForestModel(Model, HasFeaturesCol, HasPredictionCol):
    score_col = Param(str, default="outlierScore", doc="anomaly score column")
    trees = ComplexParam(default=None, doc="list of flat tree arrays")
    subsample_size = Param(int, default=256, doc="psi used at fit time")
    threshold = Param(float, default=0.5, doc="score above which = outlier")

    def _scores(self, X: np.ndarray) -> np.ndarray:
        trees = self.get("trees")
        depths = np.stack([_tree_path_lengths(t, X) for t in trees])
        e_h = depths.mean(axis=0)
        c = _avg_path_length(self.get("subsample_size"))
        return np.power(2.0, -e_h / c)

    def _transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("features_col")]
        X = (np.stack([np.asarray(v, dtype=np.float64).ravel() for v in col])
             if col.dtype == object else
             np.asarray(col, dtype=np.float64).reshape(len(df), -1))
        scores = self._scores(X)
        pred = (scores >= self.get("threshold")).astype(np.int64)
        return (df.with_column(self.get("score_col"), scores)
                  .with_column(self.get("prediction_col"), pred))
