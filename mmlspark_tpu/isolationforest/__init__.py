from .iforest import IsolationForest, IsolationForestModel

__all__ = ["IsolationForest", "IsolationForestModel"]
