"""Runtime lock-order sanitizer: the dynamic half of the TPU013 story.

tpulint's TPU012–TPU014 see the lock discipline the *source* promises;
this module watches the orders the *process* actually takes. An opt-in
(``MMLSPARK_TPU_LOCK_SANITIZER=1``) factory — :func:`new_lock`,
:func:`new_rlock`, :func:`new_condition` — is adopted by the hot threaded
modules (serving server/engine/distributed/journal, the runner's staging
pool, the residency manager, the compile cache, the breaker registry) in
place of bare ``threading.Lock()`` calls. Instrumented locks record, per
thread, the stack that acquired them; every cross-site acquisition edge
(holding A, taking B) lands once in a process-global graph, and an edge
that closes a cycle is reported with **both** stacks — the A→B path and
the B→A path some other code took earlier — which is exactly the pair a
deadlock post-mortem needs and exactly what a wedged process can no
longer produce.

Holds longer than ``MMLSPARK_TPU_LOCK_HOLD_BUDGET`` seconds (default 1.0)
are observed into ``mmlspark_lock_held_seconds{site}``; cycles increment
``mmlspark_lock_order_cycles_total``. The watchdog's black-box bundle
gains a "locks held per thread" table from :func:`held_by_thread`.

Cost model (the ``FaultInjector.enabled`` idiom, pushed to creation
time): the enabled check happens when a lock is *created* — disabled,
the factories return plain ``threading`` primitives, so steady state
pays literally nothing per acquire, not even an attribute check on the
hot path. The flip side: the env knob must be set (or :func:`configure`
called) before the guarded objects are constructed; module-global locks
adopt whatever the environment said at import.

Sanitizer bookkeeping uses plain ``threading.Lock`` internally and is
never adopted inside ``observability/registry.py`` — its metrics land in
the registry, whose series locks would otherwise recurse into the
sanitizer.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = ["LockSanitizer", "SanitizedLock", "SanitizedRLock",
           "new_lock", "new_rlock", "new_condition", "enabled",
           "configure", "get_sanitizer", "reset", "cycle_reports",
           "held_by_thread", "SANITIZER_ENV", "HOLD_BUDGET_ENV"]

SANITIZER_ENV = "MMLSPARK_TPU_LOCK_SANITIZER"
HOLD_BUDGET_ENV = "MMLSPARK_TPU_LOCK_HOLD_BUDGET"


def _truthy(value: Optional[str]) -> bool:
    return (value or "").strip().lower() in ("1", "true", "yes", "on")


class _Held:
    """One lock a thread currently holds."""

    __slots__ = ("site", "wrapper_id", "acquired_at", "stack")

    def __init__(self, site: str, wrapper_id: int, acquired_at: float,
                 stack: Optional[List[str]]):
        self.site = site
        self.wrapper_id = wrapper_id
        self.acquired_at = acquired_at
        self.stack = stack


class _Edge:
    """First-seen acquisition order between two sites, with the stack
    that established it (captured once — edges are a tiny, stable set)."""

    __slots__ = ("src", "dst", "stack", "thread_name")

    def __init__(self, src: str, dst: str, stack: List[str],
                 thread_name: str):
        self.src = src
        self.dst = dst
        self.stack = stack
        self.thread_name = thread_name


class LockSanitizer:
    """Process-global edge graph + per-thread held tables + hold budget."""

    def __init__(self, *, hold_budget: Optional[float] = None):
        if hold_budget is None:
            hold_budget = float(
                os.environ.get(HOLD_BUDGET_ENV, "1.0") or 1.0)
        self.hold_budget = float(hold_budget)
        # plain lock on purpose: the sanitizer must not sanitize itself
        self._lock = threading.Lock()
        self._edges: Dict[Tuple[str, str], _Edge] = {}
        self._cycles: List[dict] = []
        self._long_holds: List[dict] = []
        self._tls = threading.local()
        #: {thread ident: (thread name, that thread's held list)} — each
        #: list is only ever mutated by its own thread (append/pop are
        #: GIL-atomic); other threads snapshot it best-effort
        self._thread_held: Dict[int, Tuple[str, List[_Held]]] = {}

    # -- per-thread held list ------------------------------------------------
    def _held(self) -> List[_Held]:
        lst = getattr(self._tls, "held", None)
        if lst is None:
            lst = []
            self._tls.held = lst
            t = threading.current_thread()
            with self._lock:
                self._thread_held[t.ident or 0] = (t.name, lst)
        return lst

    # -- acquisition protocol ------------------------------------------------
    def before_acquire(self, site: str, wrapper_id: int) -> None:
        """Record held→new edges and check for cycles BEFORE blocking on
        the lock — a real deadlock would otherwise eat the report."""
        held = self._held()
        if not held:
            return
        for h in held:
            if h.site != site:
                self._note_edge(h.site, site)

    def after_acquire(self, site: str, wrapper_id: int) -> None:
        # bounded capture: the innermost frames are the diagnosis; a full
        # walk on every acquire would tax the very hot paths being watched
        self._held().append(_Held(
            site, wrapper_id, time.monotonic(),
            traceback.format_stack(limit=16)[:-2]))

    def on_release(self, site: str, wrapper_id: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].wrapper_id == wrapper_id:
                entry = held.pop(i)
                dur = time.monotonic() - entry.acquired_at
                if dur >= self.hold_budget:
                    self._note_long_hold(entry, dur)
                return

    # -- edges + cycles ------------------------------------------------------
    def _note_edge(self, src: str, dst: str) -> None:
        with self._lock:
            if (src, dst) in self._edges:
                return   # steady state: one dict probe per nested acquire
        stack = traceback.format_stack()[:-3]
        tname = threading.current_thread().name
        with self._lock:
            if (src, dst) in self._edges:
                return
            edge = _Edge(src, dst, stack, tname)
            self._edges[(src, dst)] = edge
            path = self._find_path(dst, src)
        if path is not None:
            self._report_cycle(edge, path)

    def _find_path(self, start: str, goal: str) -> Optional[List[_Edge]]:
        """DFS over the edge graph (caller holds ``_lock``): a path
        start→…→goal means the just-added goal→start edge closes a cycle."""
        stack = [(start, [])]
        seen = {start}
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for (src, dst), edge in self._edges.items():
                if src == node and dst not in seen:
                    seen.add(dst)
                    stack.append((dst, path + [edge]))
        return None

    def _report_cycle(self, new_edge: _Edge, back_path: List[_Edge]) -> None:
        sites = [new_edge.src, new_edge.dst]
        sites += [e.dst for e in back_path]
        report = {
            "sites": sites,
            "forward": {"order": f"{new_edge.src} -> {new_edge.dst}",
                        "thread": new_edge.thread_name,
                        "stack": new_edge.stack},
            "reverse": [{"order": f"{e.src} -> {e.dst}",
                         "thread": e.thread_name,
                         "stack": e.stack} for e in back_path],
            "t": time.time(),
        }
        with self._lock:
            self._cycles.append(report)
        m = _metrics()
        if m is not None:
            m["cycles"].inc()
        _log_event("lock_order_cycle", sites=" -> ".join(sites))

    def _note_long_hold(self, entry: _Held, dur: float) -> None:
        record = {"site": entry.site, "held_seconds": round(dur, 4),
                  "thread": threading.current_thread().name,
                  "stack": entry.stack}
        with self._lock:
            self._long_holds.append(record)
            if len(self._long_holds) > 256:
                del self._long_holds[:-256]
        m = _metrics()
        if m is not None:
            m["held"].observe(dur, site=entry.site)

    # -- introspection -------------------------------------------------------
    def cycle_reports(self) -> List[dict]:
        with self._lock:
            return list(self._cycles)

    def long_hold_reports(self) -> List[dict]:
        with self._lock:
            return list(self._long_holds)

    def held_by_thread(self) -> Dict[str, List[dict]]:
        """``{"<ident> <name>": [{site, held_seconds}]}`` for every live
        thread holding sanitized locks — the watchdog bundle table."""
        live = {t.ident for t in threading.enumerate()}
        now = time.monotonic()
        out: Dict[str, List[dict]] = {}
        with self._lock:
            for ident in [i for i in self._thread_held if i not in live]:
                del self._thread_held[ident]
            snapshot = {i: (name, list(lst))
                        for i, (name, lst) in self._thread_held.items()}
        for ident, (name, entries) in sorted(snapshot.items()):
            if not entries:
                continue
            out[f"{ident} {name}"] = [
                {"site": e.site,
                 "held_seconds": round(now - e.acquired_at, 4)}
                for e in entries]
        return out


# -- instrumented primitives --------------------------------------------------

class SanitizedLock:
    """``threading.Lock`` wrapper wired into a :class:`LockSanitizer`.

    Supports the full Lock protocol plus enough of the private Condition
    protocol (``_at_fork_reinit`` excluded) that ``threading.Condition``'s
    ``acquire(False)``-probe fallback works against it.
    """

    _reentrant = False

    def __init__(self, san: LockSanitizer, site: str):
        self._san = san
        self.site = site
        self._inner = self._make_inner()

    def _make_inner(self):
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._san.before_acquire(self.site, id(self))
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san.after_acquire(self.site, id(self))
        return got

    def release(self) -> None:
        self._san.on_release(self.site, id(self))
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __repr__(self) -> str:
        return (f"<{type(self).__name__} site={self.site!r} "
                f"inner={self._inner!r}>")


class SanitizedRLock(SanitizedLock):
    """``threading.RLock`` wrapper: bookkeeping fires on the outermost
    acquire/release only, and the private ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` protocol delegates to the inner
    RLock so ``threading.Condition`` works unmodified on top."""

    _reentrant = True

    def __init__(self, san: LockSanitizer, site: str):
        super().__init__(san, site)
        self._owner: Optional[int] = None
        self._depth = 0

    def _make_inner(self):
        return threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        first = self._owner != me
        if first:
            self._san.before_acquire(self.site, id(self))
        got = self._inner.acquire(blocking, timeout)
        if got:
            if first:
                self._owner = me
                self._san.after_acquire(self.site, id(self))
            self._depth += 1
        return got

    def release(self) -> None:
        if self._owner != threading.get_ident():
            # surface the standard error without corrupting bookkeeping
            self._inner.release()
            return
        if self._depth == 1:
            self._san.on_release(self.site, id(self))
            self._owner = None
        self._depth -= 1
        self._inner.release()

    def locked(self) -> bool:
        return self._owner is not None

    # Condition protocol -----------------------------------------------------
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        depth = self._depth
        self._san.on_release(self.site, id(self))
        self._owner = None
        self._depth = 0
        return self._inner._release_save(), depth

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        self._owner = threading.get_ident()
        self._depth = depth
        self._san.after_acquire(self.site, id(self))


# -- process-global sanitizer + factories -------------------------------------

_san_lock = threading.Lock()
_SANITIZER: Optional[LockSanitizer] = None
_ENABLED: Optional[bool] = None


def enabled() -> bool:
    """Whether new locks are instrumented (env read cached on first use)."""
    global _ENABLED
    if _ENABLED is None:
        with _san_lock:
            if _ENABLED is None:
                _ENABLED = _truthy(os.environ.get(SANITIZER_ENV))
    return _ENABLED


def get_sanitizer() -> LockSanitizer:
    global _SANITIZER
    with _san_lock:
        if _SANITIZER is None:
            _SANITIZER = LockSanitizer()
        return _SANITIZER


def configure(*, enabled: bool,
              hold_budget: Optional[float] = None) -> LockSanitizer:
    """Programmatic enable/disable (tests; bench harnesses). Affects
    locks created AFTER the call — existing locks keep their nature."""
    global _ENABLED, _SANITIZER
    with _san_lock:
        _ENABLED = bool(enabled)
        _SANITIZER = LockSanitizer(hold_budget=hold_budget)
        return _SANITIZER


def reset() -> None:
    """Test hook: drop all state; the next use re-reads the environment."""
    global _ENABLED, _SANITIZER
    with _san_lock:
        _ENABLED = None
        _SANITIZER = None


def new_lock(site: str):
    """A mutex for ``site`` (e.g. ``"serving.server.WorkerServer._lock"``):
    instrumented when the sanitizer is enabled, else a plain
    ``threading.Lock`` — the disabled path costs nothing per acquire."""
    if not enabled():
        return threading.Lock()
    return SanitizedLock(get_sanitizer(), site)


def new_rlock(site: str):
    if not enabled():
        return threading.RLock()
    return SanitizedRLock(get_sanitizer(), site)


def new_condition(site: str, lock=None):
    """A ``threading.Condition``; enabled, it rides a sanitized (R)Lock,
    so waits release the instrumented lock correctly."""
    if not enabled():
        return threading.Condition(lock)
    return threading.Condition(lock if lock is not None
                               else new_rlock(site))


def cycle_reports() -> List[dict]:
    """All lock-order cycles seen so far (empty when disabled/clean)."""
    if _SANITIZER is None:
        return []
    return _SANITIZER.cycle_reports()


def held_by_thread() -> Dict[str, List[dict]]:
    """Locks currently held, per live thread (the watchdog bundle table)."""
    if _SANITIZER is None:
        return {}
    return _SANITIZER.held_by_thread()


# -- lazy observability bridge ------------------------------------------------
# imported on first report, not at module import: reliability must stay
# importable without dragging in the observability package (and the
# registry's own locks are deliberately NOT sanitized)

_METRICS: Optional[dict] = None


def _metrics() -> Optional[dict]:
    global _METRICS
    if _METRICS is None:
        try:
            from ..observability.registry import counter, histogram
            _METRICS = {
                "held": histogram(
                    "mmlspark_lock_held_seconds",
                    "Lock holds exceeding the sanitizer budget, by site",
                    labelnames=("site",),
                    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)),
                "cycles": counter(
                    "mmlspark_lock_order_cycles_total",
                    "Dynamic lock-order cycles detected by the sanitizer"),
            }
        except Exception:
            return None
    return _METRICS


def _log_event(kind: str, **fields: object) -> None:
    try:
        from ..observability.events import log_event
        log_event(kind, **fields)
    except Exception:
        pass
