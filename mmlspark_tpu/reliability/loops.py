"""Supervised daemon loops: crash containment for background threads.

Every long-lived daemon thread in the serving stack (heartbeats, the
driver's liveness sweeper, engine ticks) shares one failure mode: an
unhandled exception silently kills the thread, and the process limps on
with its heartbeat/engine/sweeper gone — the exact blind spot tpulint's
TPU025 (``unsupervised-daemon-loop``) flags. This module is the sanctioned
fix: :func:`run_supervised` wraps the loop body with catch + backoff +
restart accounting, and :func:`start_supervised` packages that into a
named daemon thread. ``ContinuousDecoder.serve_forever`` implements the
same contract inline (bounded consecutive failures, exponential backoff);
loops that route through here inherit it for free and stay TPU025-quiet.

Restarts are visible, not silent: each contained crash increments
``mmlspark_supervised_loop_restarts_total{loop}`` and logs a
``supervised_loop_crash`` event with the exception repr.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..observability import counter as _metric_counter
from ..observability import log_event

__all__ = ["run_supervised", "start_supervised"]

M_LOOP_RESTARTS = _metric_counter(
    "mmlspark_supervised_loop_restarts_total",
    "Background-loop crashes contained and restarted, by loop name",
    ("loop",))


def run_supervised(tick: Callable[[], None], *, name: str,
                   stop: threading.Event,
                   interval: float = 0.0,
                   backoff: float = 0.05,
                   max_backoff: float = 2.0,
                   max_failures: Optional[int] = None) -> None:
    """Run ``tick()`` every ``interval`` seconds until ``stop`` is set.

    A tick that raises is contained: the crash is counted and logged, the
    loop sleeps an exponentially growing backoff (reset by the next clean
    tick), and ticking resumes. ``max_failures`` bounds *consecutive*
    failures — exceeding it ends the loop (logged as
    ``supervised_loop_gave_up``) rather than spinning on a permanently
    broken dependency; ``None`` retries forever (a heartbeat must outlive
    any driver outage).
    """
    delay = backoff
    failures = 0
    while not stop.wait(interval):
        try:
            tick()
            failures = 0
            delay = backoff
        except Exception as exc:
            failures += 1
            M_LOOP_RESTARTS.inc(loop=name)
            log_event("supervised_loop_crash", loop=name, error=repr(exc),
                      consecutive=failures)
            if max_failures is not None and failures >= max_failures:
                log_event("supervised_loop_gave_up", loop=name,
                          consecutive=failures)
                return
            if stop.wait(delay):
                return
            delay = min(delay * 2, max_backoff)


def start_supervised(tick: Callable[[], None], *, name: str,
                     stop: threading.Event,
                     interval: float = 0.0,
                     backoff: float = 0.05,
                     max_backoff: float = 2.0,
                     max_failures: Optional[int] = None) -> threading.Thread:
    """Start :func:`run_supervised` on a named daemon thread and return
    it (callers join it on shutdown after setting ``stop``)."""
    t = threading.Thread(
        target=run_supervised, name=name, daemon=True,
        kwargs=dict(tick=tick, name=name, stop=stop, interval=interval,
                    backoff=backoff, max_backoff=max_backoff,
                    max_failures=max_failures))
    t.start()
    return t
