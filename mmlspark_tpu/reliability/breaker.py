"""Per-peer circuit breaker: closed → open → half-open.

A breaker trips OPEN when the failure ratio over a sliding window of
recent calls crosses a threshold; while open, ``allow()`` fails fast so a
dead peer costs one dict lookup instead of a connect timeout. After
``open_seconds`` the breaker admits a single HALF-OPEN probe — success
closes it, failure re-opens it. State is exported as
``mmlspark_breaker_state{peer}`` (0=closed, 1=open, 2=half-open) and every
transition bumps ``mmlspark_breaker_transitions_total{peer,to}`` and lands
as a span event on the active trace.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List

from ..observability import counter as _metric_counter
from ..observability import gauge as _metric_gauge
from ..observability import tracing as _tracing

from .lock_sanitizer import new_lock

__all__ = ["BreakerOpen", "CircuitBreaker", "breaker_for", "reset_breakers",
           "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_VALUE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}

_M_STATE = _metric_gauge(
    "mmlspark_breaker_state",
    "Circuit state per peer: 0=closed, 1=open, 2=half_open",
    ("peer",))
_M_TRANSITIONS = _metric_counter(
    "mmlspark_breaker_transitions_total",
    "Circuit state transitions per peer, by target state",
    ("peer", "to"))


class BreakerOpen(ConnectionError):
    """Raised (or used as a fail-fast signal) when a peer's circuit is open."""

    def __init__(self, peer: str):
        super().__init__(f"circuit open for peer {peer}")
        self.peer = peer


class CircuitBreaker:
    """Sliding-window failure-ratio breaker for one peer.

    ``window`` recent outcomes are kept; once at least ``min_calls`` are
    recorded and the failure ratio reaches ``failure_ratio``, the breaker
    opens for ``open_seconds``. The clock is injectable for tests.
    """

    def __init__(self, peer: str = "", window: int = 20, min_calls: int = 5,
                 failure_ratio: float = 0.5, open_seconds: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.peer = peer
        self.min_calls = int(min_calls)
        self.failure_ratio = float(failure_ratio)
        self.open_seconds = float(open_seconds)
        self._clock = clock
        self._outcomes = deque(maxlen=int(window))  # True = success
        self._state = CLOSED
        self._opened_at = 0.0
        self._probe_inflight = False
        self._lock = new_lock("reliability.breaker.CircuitBreaker._lock")
        _M_STATE.set(0.0, peer=peer)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a call to this peer proceed right now? A ``True`` answer in
        HALF_OPEN claims the single probe slot."""
        with self._lock:
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.open_seconds:
                    return False
                self._transition(HALF_OPEN)
                self._probe_inflight = True
                return True
            if self._state == HALF_OPEN:
                if self._probe_inflight:
                    return False
                self._probe_inflight = True
                return True
            return True

    def record_success(self) -> None:
        with self._lock:
            if self._state in (HALF_OPEN, OPEN):
                # probe (or late straggler) succeeded: peer is back
                self._outcomes.clear()
                self._probe_inflight = False
                self._transition(CLOSED)
                return
            self._outcomes.append(True)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_inflight = False
                self._opened_at = self._clock()
                self._transition(OPEN)
                return
            if self._state == OPEN:
                return
            self._outcomes.append(False)
            n = len(self._outcomes)
            if n >= self.min_calls:
                failures = sum(1 for ok in self._outcomes if not ok)
                if failures / n >= self.failure_ratio:
                    self._opened_at = self._clock()
                    self._transition(OPEN)

    # -- internal (lock held) ----------------------------------------------
    def _transition(self, to: str) -> None:
        if to == self._state:
            return
        self._state = to
        _M_STATE.set(_STATE_VALUE[to], peer=self.peer)
        _M_TRANSITIONS.inc(peer=self.peer, to=to)
        _tracing.add_event("breaker_transition", peer=self.peer, to=to)


_BREAKERS: Dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = new_lock("reliability.breaker._BREAKERS_LOCK")


def breaker_for(peer: str, **kwargs) -> CircuitBreaker:
    """Process-wide breaker registry, one breaker per peer address.

    Keyed by *address* rather than worker id so a worker that re-registers
    on a fresh port starts with a clean circuit (the old incarnation's
    failures do not poison the new one)."""
    with _BREAKERS_LOCK:
        brk = _BREAKERS.get(peer)
        if brk is None:
            brk = _BREAKERS[peer] = CircuitBreaker(peer, **kwargs)
        return brk


def reset_breakers() -> None:
    """Test hook: drop all registered breakers (metric series are cleaned
    up by ``observability.reset_all``)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()


def open_breakers() -> List[str]:
    """Peers whose circuit is currently open — the /healthz degraded
    check (half-open circuits are probing their way back and don't count
    as degraded)."""
    with _BREAKERS_LOCK:
        brks = list(_BREAKERS.values())
    return sorted(b.peer for b in brks if b.state == OPEN)
