"""Reliability substrate for the serving stack.

Five cooperating pieces (PAPERS.md: ORCA/AlpaServe-style overload control
and fail-fast serving):

- :mod:`.policy` — :class:`RetryPolicy` (budgeted exponential backoff with
  full jitter) and :class:`Deadline` (monotonic remaining-budget object,
  propagated across worker hops via the ``X-Mmlspark-Deadline`` header).
- :mod:`.breaker` — per-peer :class:`CircuitBreaker`
  (closed → open → half-open, failure-ratio over a sliding window), state
  exported as ``mmlspark_breaker_state{peer}``.
- :mod:`.faults` — deterministic, seedable :class:`FaultInjector` with
  named sites (``peer_http``, ``heartbeat``, ``device_run``, ``enqueue``)
  driven programmatically or by the ``MMLSPARK_TPU_FAULTS`` env spec.
- :mod:`.loops` — :func:`run_supervised`/:func:`start_supervised`, the
  crash-contained daemon-loop harness (backoff + restart accounting into
  ``mmlspark_supervised_loop_restarts_total{loop}``) that heartbeat and
  sweeper threads run under; tpulint TPU025 flags daemon loops that skip
  it.
- :mod:`.lock_sanitizer` — opt-in (``MMLSPARK_TPU_LOCK_SANITIZER=1``)
  instrumented lock factory: dynamic lock-order-cycle detection with both
  stacks, hold-time budgets into ``mmlspark_lock_held_seconds{site}``, and
  the watchdog bundle's locks-held-per-thread table (the runtime half of
  tpulint's TPU013).

``docs/reliability.md`` is the narrative companion.
"""

from .breaker import (BreakerOpen, CircuitBreaker, breaker_for,
                      open_breakers, reset_breakers)
from .faults import FaultInjector, InjectedFault, get_injector
from .lock_sanitizer import (cycle_reports, held_by_thread, new_condition,
                             new_lock, new_rlock)
from .loops import run_supervised, start_supervised
from .policy import (DEADLINE_HEADER, Deadline, DeadlineExceeded, RetryPolicy,
                     record_retry)

__all__ = [
    "BreakerOpen",
    "CircuitBreaker",
    "breaker_for",
    "open_breakers",
    "reset_breakers",
    "FaultInjector",
    "InjectedFault",
    "get_injector",
    "cycle_reports",
    "held_by_thread",
    "new_condition",
    "new_lock",
    "new_rlock",
    "run_supervised",
    "start_supervised",
    "DEADLINE_HEADER",
    "Deadline",
    "DeadlineExceeded",
    "RetryPolicy",
    "record_retry",
]
