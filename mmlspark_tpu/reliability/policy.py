"""Retry policy (budgeted backoff + full jitter) and deadline propagation.

Every knob that touches time is injectable (``clock``/``sleep``/``rng``) so
the unit tests in tests/test_reliability.py run on a fake clock and are
fully deterministic. :class:`Deadline` carries the *remaining* budget — not
an absolute timestamp — across process hops (monotonic clocks do not
transfer between processes), gRPC ``grpc-timeout`` style.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from ..observability import counter as _metric_counter
from ..observability import tracing as _tracing

__all__ = ["DEADLINE_HEADER", "Deadline", "DeadlineExceeded", "RetryPolicy",
           "record_retry"]

#: Cross-hop header carrying the caller's remaining budget in seconds
#: (decimal string, e.g. ``"2.350"``). A forwarded request must never wait
#: longer than what is left of the client's ``reply_timeout``.
DEADLINE_HEADER = "X-Mmlspark-Deadline"

_M_RETRIES = _metric_counter(
    "mmlspark_retry_attempts_total",
    "Re-attempts after a failed first try, by logical call site",
    ("site",))


class DeadlineExceeded(TimeoutError):
    """The operation's remaining budget reached zero before it completed."""


def record_retry(site: str, attempt: int, delay: float, error: str) -> None:
    """Account one re-attempt: bump the site counter and note it on the
    active trace span (no-ops when no span is active)."""
    _M_RETRIES.inc(site=site)
    _tracing.add_event("retry", site=site, attempt=attempt,
                       delay=round(delay, 6), error=error)


class Deadline:
    """Monotonic remaining-budget object.

    Constructed from a total budget (``Deadline.after(2.5)``) or from the
    wire header of an upstream hop (``Deadline.from_header(value)``).
    ``cap(timeout)`` clamps any local wait to the remaining budget.
    """

    __slots__ = ("_expires_at", "_clock")

    def __init__(self, budget: float,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._expires_at = clock() + float(budget)

    @classmethod
    def after(cls, budget: float,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(budget, clock=clock)

    @classmethod
    def from_header(cls, value: object,
                    clock: Callable[[], float] = time.monotonic
                    ) -> Optional["Deadline"]:
        """Parse a ``X-Mmlspark-Deadline`` header value; ``None`` on garbage
        (a malformed header must degrade to "no deadline", never to a 500)."""
        try:
            budget = float(str(value).strip())
        except (TypeError, ValueError):
            return None
        if budget != budget or budget in (float("inf"), float("-inf")):
            return None
        return cls(budget, clock=clock)

    def remaining(self) -> float:
        return self._expires_at - self._clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def cap(self, timeout: float) -> float:
        """Clamp ``timeout`` to the remaining budget (may be <= 0)."""
        return min(float(timeout), self.remaining())

    def header_value(self) -> str:
        return f"{max(0.0, self.remaining()):.3f}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline(remaining={self.remaining():.3f}s)"


class RetryPolicy:
    """Exponential backoff with full jitter and explicit budgets.

    ``max_attempts`` bounds the per-call attempt count; ``total_budget``
    bounds wall-clock spent across *all* attempts (sleep included); an
    optional :class:`Deadline` bounds the call to the caller's remaining
    budget. Backoff for re-attempt *n* is drawn uniformly from
    ``[0, min(max_delay, base_delay * 2**(n-1))]`` (full jitter — decorrelates
    a thundering herd of workers retrying the same dead peer).
    """

    def __init__(self,
                 max_attempts: int = 3,
                 base_delay: float = 0.05,
                 max_delay: float = 2.0,
                 total_budget: Optional[float] = None,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 giveup: Optional[Callable[[BaseException], bool]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.total_budget = total_budget
        self.retry_on = retry_on
        self.giveup = giveup
        self.clock = clock
        self.sleep = sleep
        self.rng = rng if rng is not None else random.Random()

    def backoff(self, attempt: int) -> float:
        """Jittered delay before re-attempt number ``attempt`` (1-based)."""
        ceiling = min(self.max_delay,
                      self.base_delay * (2.0 ** (attempt - 1)))
        return self.rng.uniform(0.0, ceiling)

    def call(self, fn: Callable[[], object], *, site: str = "default",
             deadline: Optional[Deadline] = None):
        """Run ``fn`` under this policy; re-raises the last error once the
        attempt count, total budget, or deadline is exhausted."""
        start = self.clock()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except self.retry_on as exc:
                if self.giveup is not None and self.giveup(exc):
                    raise
                if attempt >= self.max_attempts:
                    raise
                delay = self.backoff(attempt)
                if (self.total_budget is not None
                        and self.clock() - start + delay > self.total_budget):
                    raise
                if deadline is not None and deadline.remaining() <= delay:
                    raise
                record_retry(site, attempt, delay, type(exc).__name__)
                self.sleep(delay)
