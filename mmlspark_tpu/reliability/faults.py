"""Deterministic, seedable fault injection for chaos tests and drills.

Faults attach to named *sites* — ``peer_http``, ``heartbeat``,
``device_run``, ``enqueue`` — and can ``error`` (raise
:class:`InjectedFault`), ``delay`` (sleep), or ``corrupt`` (mangle the
payload) on a schedule. Scheduling is deterministic: each rule owns a
``random.Random(seed)`` and a call counter guarded by a lock, so a given
(spec, seed, call-order) triple always injects the same faults —
the chaos test in tests/test_serving_distributed.py relies on this.

The injector is a no-op passthrough when disabled: hot paths guard with
``if injector.enabled: injector.fire(site)`` and pay a single attribute
check in production.

Env spec (``MMLSPARK_TPU_FAULTS``), ``;``-separated rules of
``site:kind[:key=value...]``::

    peer_http:error:p=0.3:seed=42
    heartbeat:delay:every=3:seconds=0.05
    enqueue:error:times=2

Keys: ``p`` (probability, default 1.0), ``every`` (every Nth call),
``times`` (cap on total fires), ``seconds`` (delay duration),
``seed`` (rng seed, default 0).
"""

from __future__ import annotations

import os
import threading
import time
from random import Random
from typing import Dict, List, Optional

from ..observability import counter as _metric_counter
from ..observability import log_event as _log_event

__all__ = ["FaultInjector", "FaultRule", "InjectedFault", "get_injector",
           "SITES"]

#: Named injection sites wired through the serving stack.
SITES = ("peer_http", "heartbeat", "device_run", "enqueue")

_KINDS = ("error", "delay", "corrupt")

_M_FAULTS = _metric_counter(
    "mmlspark_faults_injected_total",
    "Faults fired by the injector, by site and kind",
    ("site", "kind"))


class InjectedFault(ConnectionError):
    """Raised by an ``error`` rule. Subclasses ConnectionError so injected
    network faults take the same retry/breaker path as real ones."""

    def __init__(self, site: str, kind: str = "error"):
        super().__init__(f"injected fault at site {site!r}")
        self.site = site
        self.kind = kind


class FaultRule:
    """One scheduled fault. ``decide()`` is called once per matching
    ``fire`` and is deterministic given the seed and call order."""

    def __init__(self, site: str, kind: str = "error", p: float = 1.0,
                 every: Optional[int] = None, times: Optional[int] = None,
                 seconds: float = 0.0, seed: int = 0):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (want {_KINDS})")
        self.site = site
        self.kind = kind
        self.p = float(p)
        self.every = int(every) if every is not None else None
        self.times = int(times) if times is not None else None
        self.seconds = float(seconds)
        self.seed = int(seed)
        self.calls = 0
        self.fires = 0
        self._rng = Random(self.seed)
        self._lock = threading.Lock()

    def decide(self) -> bool:
        with self._lock:
            self.calls += 1
            if self.times is not None and self.fires >= self.times:
                return False
            if self.every is not None and self.calls % self.every != 0:
                return False
            if self.p < 1.0 and self._rng.random() >= self.p:
                return False
            self.fires += 1
            return True

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"FaultRule({self.site}:{self.kind} p={self.p} "
                f"every={self.every} times={self.times} fires={self.fires})")


def _corrupt(payload):
    """Mangle a payload in a type-preserving, detectable way."""
    if payload is None:
        return None
    if isinstance(payload, dict):
        return {**payload, "_corrupted": True}
    if isinstance(payload, (bytes, bytearray)):
        return bytes(payload[:-1]) if payload else b"\x00"
    if isinstance(payload, str):
        return payload[:-1] if payload else "\x00"
    return payload


class FaultInjector:
    """Registry of :class:`FaultRule` keyed by site.

    ``enabled`` is a plain bool kept in sync with the rule table so the
    disabled fast path is one attribute read, no lock.
    """

    def __init__(self, sleep=time.sleep):
        self.enabled = False
        self._sleep = sleep
        self._rules: Dict[str, List[FaultRule]] = {}
        self._lock = threading.Lock()

    # -- configuration -----------------------------------------------------
    def add(self, site: str, kind: str = "error", **kwargs) -> FaultRule:
        rule = FaultRule(site, kind, **kwargs)
        with self._lock:
            self._rules.setdefault(site, []).append(rule)
            self.enabled = True
        return rule

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()
            self.enabled = False

    def configure(self, spec: str) -> None:
        """Parse an ``MMLSPARK_TPU_FAULTS``-style spec (see module doc).
        Raises ValueError on bad grammar."""
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            if len(parts) < 2:
                raise ValueError(f"fault spec entry {entry!r}: "
                                 "want site:kind[:key=value...]")
            site, kind, kwargs = parts[0], parts[1], {}
            for field in parts[2:]:
                key, sep, value = field.partition("=")
                if not sep or key not in ("p", "every", "times",
                                          "seconds", "seed"):
                    raise ValueError(
                        f"fault spec entry {entry!r}: bad field {field!r}")
                try:
                    kwargs[key] = (float(value) if key in ("p", "seconds")
                                   else int(value))
                except ValueError:
                    raise ValueError(f"fault spec entry {entry!r}: "
                                     f"non-numeric value in {field!r}")
            self.add(site, kind, **kwargs)

    def rules(self, site: Optional[str] = None) -> List[FaultRule]:
        with self._lock:
            if site is not None:
                return list(self._rules.get(site, ()))
            return [r for rs in self._rules.values() for r in rs]

    # -- hot path ----------------------------------------------------------
    def fire(self, site: str, payload=None):
        """Apply all matching rules at ``site``; returns the (possibly
        corrupted) payload or raises :class:`InjectedFault`."""
        if not self.enabled:
            return payload
        with self._lock:
            rules = list(self._rules.get(site, ()))
        for rule in rules:
            if not rule.decide():
                continue
            _M_FAULTS.inc(site=site, kind=rule.kind)
            if rule.kind == "error":
                raise InjectedFault(site)
            if rule.kind == "delay":
                self._sleep(rule.seconds)
            else:
                payload = _corrupt(payload)
        return payload


_INJECTOR = FaultInjector()


def get_injector() -> FaultInjector:
    """The process-wide injector (configured from ``MMLSPARK_TPU_FAULTS``
    at import, if set)."""
    return _INJECTOR


_spec = os.environ.get("MMLSPARK_TPU_FAULTS", "")
if _spec:
    try:
        _INJECTOR.configure(_spec)
    except ValueError as exc:
        # a typo'd drill spec must not take the worker down with it
        _log_event("fault_spec_invalid", spec=_spec, error=str(exc))
del _spec
