"""Centralized accelerator detection.

Parity role: the reference picks its execution provider by probing device
strings in one place (``deep-learning/src/main/scala/com/microsoft/azure/
synapse/ml/onnx/ONNXModel.scala:293-303`` — CUDA vs CPU EP selection).
Here every TPU gate (Pallas interpret mode, kernel autotuning, bench
labeling) funnels through :func:`is_tpu` so a PJRT plugin that reports an
unexpected platform string (this session's chip arrives through a plugin
named ``axon``) is handled — and misdetection is visible — in exactly one
place.

``jax.default_backend() == "tpu"`` scattered across modules is the failure
mode this replaces: if the plugin reports any other string, flash-attention
silently drops to interpret mode and the bench mislabels a real TPU run.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

__all__ = ["device_info", "is_tpu", "tpu_generation", "looks_tpu",
           "generation_from_kind", "force_cpu"]


def force_cpu(virtual_devices: Optional[int] = None):
    """Pin this process to the XLA CPU backend; returns the jax module.

    THE one copy of the CPU-smoke workaround every bench/doctest script
    needs (it used to live inline in six of them): under this image,
    ``JAX_PLATFORMS=axon`` may be set while the axon plugin resolves via a
    site dir that a ``PYTHONPATH`` override drops — first backend use then
    hard-crashes; and forcing ``JAX_PLATFORMS=cpu`` via the environment
    HANGS. So: pop the env var, then pin the platform through
    ``jax.config``. Must be called before anything initializes a backend
    (importing jax is fine; running a computation is not).

    ``virtual_devices=N`` also requests an N-device virtual CPU topology
    (``--xla_force_host_platform_device_count``) for mesh smoke tests —
    honored only if no backend is live and the flag isn't already set.
    """
    if virtual_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{int(virtual_devices)}").strip()
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax

_CACHE: Optional[Tuple[str, str]] = None

#: ordered (longest-match-first) generation keys — v5p before v5
_GENERATIONS = ("v6", "v5p", "v5", "v4", "v3", "v2")


def looks_tpu(platform: str, device_kind: str) -> bool:
    """Pure-string TPU check over raw (platform, device_kind) — for callers
    (like bench.py) that probed the strings in a child process and must not
    initialize a backend in their own."""
    return "tpu" in platform.lower() or "tpu" in device_kind.lower()


def generation_from_kind(device_kind: str) -> Optional[str]:
    """Pure-string generation key from a raw device_kind, or None."""
    kind = device_kind.lower()
    for key in _GENERATIONS:
        if key in kind:
            return key
    return None


def device_info() -> Tuple[str, str]:
    """(platform, device_kind) of the default backend's first device, raw
    strings as the plugin reports them. Cached after first success — the
    default backend cannot change within a process."""
    global _CACHE
    if _CACHE is None:
        import jax
        d = jax.devices()[0]
        _CACHE = (str(d.platform or ""), str(d.device_kind or ""))
    return _CACHE


def is_tpu() -> bool:
    """True when the default backend is a TPU, however the plugin spells it.

    Checks, in order: the ``MMLSPARK_TPU_FORCE_PLATFORM`` env override
    (``tpu``/``cpu``, for tests), ``jax.default_backend()``, and the first
    device's platform/device_kind substrings — public TPU PJRT plugins
    always put "tpu" or "TPU" in at least one of the three, whatever the
    plugin's own name (e.g. a tunneled plugin registered as ``axon``).
    """
    forced = os.environ.get("MMLSPARK_TPU_FORCE_PLATFORM")
    if forced:
        return forced.lower() == "tpu"
    try:
        import jax
        if jax.default_backend().lower() == "tpu":
            return True
        platform, kind = device_info()
        return "tpu" in platform.lower() or "tpu" in kind.lower()
    except Exception:
        return False


def tpu_generation() -> Optional[str]:
    """Generation key ("v6" / "v5p" / "v5" / "v4" / ...) parsed from
    device_kind, or None off-TPU — the lookup key for peak-FLOPs tables."""
    if not is_tpu():
        return None
    return generation_from_kind(device_info()[1])
