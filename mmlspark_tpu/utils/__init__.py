from .async_utils import buffered_await, map_buffered
from .cluster import (device_for_partition, get_driver_host, global_devices,
                      local_devices, num_processes, num_tasks, process_index)
from .fault import retry_with_backoff, retry_with_timeout
from .shared import SharedSingleton, SharedVariable, StopWatch

__all__ = [
    "buffered_await", "map_buffered",
    "num_processes", "process_index", "local_devices", "global_devices",
    "num_tasks", "get_driver_host", "device_for_partition",
    "retry_with_timeout", "retry_with_backoff",
    "SharedVariable", "SharedSingleton", "StopWatch",
]
