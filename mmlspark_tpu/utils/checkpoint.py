"""Step-level training checkpoint/resume.

The reference checkpoints at *model* granularity only (SURVEY.md §5:
LightGBM warm-start via model strings ``LightGBMBase.scala:49-61``, VW
``initialModel`` bytes). For long TPU training runs that is not enough —
a preempted pod slice must resume mid-run — so this adds a step-granular
checkpointer used by the GBDT trainer (``checkpoint_dir`` /
``checkpoint_interval`` params) and usable by any loop.

Layout: ``<dir>/step_<N>/`` holding the payload files plus ``meta.json``;
writes go to a temp dir and are atomically renamed, and ``LATEST`` is
updated last — a crash mid-write never corrupts the resumable state.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Dict, Optional, Tuple, Union

import numpy as np

__all__ = ["TrainingCheckpointer", "ShardedCheckpointer"]

Payload = Dict[str, Union[bytes, str, dict, np.ndarray]]


class TrainingCheckpointer:
    def __init__(self, directory: str, keep: int = 2):
        self.directory = directory
        self.keep = max(1, int(keep))
        os.makedirs(directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def save(self, step: int, payload: Payload) -> None:
        tmp = tempfile.mkdtemp(dir=self.directory, prefix=".tmp_")
        try:
            for name, value in payload.items():
                path = os.path.join(tmp, name)
                if isinstance(value, bytes):
                    with open(path, "wb") as f:
                        f.write(value)
                elif isinstance(value, str):
                    with open(path, "w") as f:
                        f.write(value)
                elif isinstance(value, np.ndarray):
                    np.save(path if path.endswith(".npy") else path + ".npy",
                            value, allow_pickle=False)
                else:
                    with open(path, "w") as f:
                        json.dump(value, f)
            final = self._step_dir(step)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        # LATEST is updated last: readers never see a half-written step
        latest_tmp = os.path.join(self.directory, ".LATEST.tmp")
        with open(latest_tmp, "w") as f:
            f.write(str(step))
        os.replace(latest_tmp, os.path.join(self.directory, "LATEST"))
        self._prune()

    def _steps(self):
        out = []
        for d in os.listdir(self.directory):
            if d.startswith("step_"):
                try:
                    out.append(int(d[5:]))
                except ValueError:
                    pass
        return sorted(out)

    def _prune(self):
        for s in self._steps()[:-self.keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def latest_step(self) -> Optional[int]:
        path = os.path.join(self.directory, "LATEST")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            step = int(f.read().strip())
        return step if os.path.isdir(self._step_dir(step)) else None

    def latest(self) -> Optional[Tuple[int, Dict[str, str]]]:
        """Returns (step, {filename: absolute path}) for the newest step."""
        step = self.latest_step()
        if step is None:
            return None
        d = self._step_dir(step)
        return step, {name: os.path.join(d, name) for name in os.listdir(d)}

    # convenience readers ----------------------------------------------------
    @staticmethod
    def read_text(path: str) -> str:
        with open(path) as f:
            return f.read()

    @staticmethod
    def read_json(path: str) -> dict:
        with open(path) as f:
            return json.load(f)


class ShardedCheckpointer:
    """Mesh-sharded training-state checkpoints via orbax.

    :class:`TrainingCheckpointer` handles host-side payloads (GBDT model
    strings, numpy state). Multi-host TPU training needs more: every host
    writes its own shards of a distributed pytree and restore re-places
    them onto the target mesh — orbax's job. Works identically on the
    virtual CPU mesh (tests) and real slices.

    >>> with ShardedCheckpointer(d, max_to_keep=3) as ckpt:
    ...     ckpt.save(step, {"params": params, "opt": opt_state})
    ...     state = ckpt.restore(target=fresh_state)  # keeps shardings
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        # remote URIs (gs://, s3://) pass through untouched — abspath
        # would mangle them into bogus local paths
        self.directory = (directory if "://" in directory
                          else os.path.abspath(directory))
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    def save(self, step: int, state, wait: bool = True) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None, target=None):
        """Restore ``step`` (default latest). With ``target`` (the freshly
        initialized, device_put state), restored arrays land on the
        target leaves' shardings — values are overwritten."""
        import jax
        import orbax.checkpoint as ocp

        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        if target is None:
            return self._mgr.restore(step)
        def leaf_struct(x):
            arr = jax.numpy.asarray(x)  # plain int/float leaves (step ctr)
            return jax.ShapeDtypeStruct(arr.shape, arr.dtype,
                                        sharding=getattr(x, "sharding", None))

        abstract = jax.tree_util.tree_map(leaf_struct, target)
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "ShardedCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
