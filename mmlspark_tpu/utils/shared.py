"""Per-process lazy singletons.

Parity surface: ``SharedVariable``/``SharedSingleton``
(``core/.../io/http/SharedVariable.scala:18,37``) — the reference's idiom for
non-serializable state (HTTP clients, native handles) shared by all tasks in a
JVM. Here: shared by all threads in the process, created once under a lock.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, TypeVar

T = TypeVar("T")

__all__ = ["SharedVariable", "SharedSingleton", "StopWatch"]


class SharedVariable(Generic[T]):
    """Lazily-constructed process-wide value."""

    def __init__(self, factory: Callable[[], T]):
        self._factory = factory
        self._lock = threading.Lock()
        self._value: T = None  # type: ignore[assignment]
        self._created = False

    def get(self) -> T:
        if not self._created:
            with self._lock:
                if not self._created:
                    self._value = self._factory()
                    self._created = True
        return self._value

    def clear(self) -> None:
        with self._lock:
            self._created = False
            self._value = None  # type: ignore[assignment]


class SharedSingleton:
    """Keyed registry of shared values (reference keys by constructor site)."""

    _instances: Dict[str, SharedVariable] = {}
    _lock = threading.Lock()

    @classmethod
    def get(cls, key: str, factory: Callable[[], T]) -> T:
        with cls._lock:
            if key not in cls._instances:
                cls._instances[key] = SharedVariable(factory)
        return cls._instances[key].get()

    @classmethod
    def reset(cls, key: str = None) -> None:
        with cls._lock:
            if key is None:
                cls._instances.clear()
            else:
                cls._instances.pop(key, None)


class StopWatch:
    """Accumulating wall-clock timer (reference: ``core/utils/StopWatch.scala``,
    feeding VW's per-partition ``TrainingStats``)."""

    def __init__(self):
        self.elapsed_ns = 0
        self._start = None

    def start(self) -> None:
        import time
        self._start = time.perf_counter_ns()

    def stop(self) -> None:
        import time
        if self._start is not None:
            # tpulint: disable=TPU007 — reference-parity wall timer:
            # VW's TrainingStats consumes elapsed_ns directly (per
            # partition, reported through the model's own stats surface);
            # callers needing fleet visibility time at their own call
            # sites via mmlspark_tpu.observability
            self.elapsed_ns += time.perf_counter_ns() - self._start
            self._start = None

    def measure(self, fn: Callable[[], T]) -> T:
        self.start()
        try:
            return fn()
        finally:
            self.stop()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    @property
    def elapsed_s(self) -> float:
        return self.elapsed_ns / 1e9
