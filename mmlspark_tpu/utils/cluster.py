"""Cluster topology discovery over the JAX runtime.

Parity surface: ``ClusterUtil`` in the reference
(``core/.../core/utils/ClusterUtil.scala:20,107,126``) which asks Spark for
executor/task topology so LightGBM can size its socket ring. Here topology is
a property of the JAX distributed runtime: processes ↔ hosts, local devices ↔
chips, and the global device count is the world size a mesh can span.
"""

from __future__ import annotations

import os
import socket
from typing import List, Optional

__all__ = [
    "num_processes", "process_index", "local_devices", "global_devices",
    "num_tasks", "get_driver_host", "device_for_partition",
]


def num_processes() -> int:
    """World size in hosts (reference: ``ClusterUtil.getExecutors:126``)."""
    import jax
    return jax.process_count()


def process_index() -> int:
    import jax
    return jax.process_index()


def local_devices() -> List:
    """Chips attached to this host (reference: tasks-per-executor,
    ``ClusterUtil.getNumTasksPerExecutor:20``). Shares the degrading
    implementation in ``parallel.mesh`` — backend-init failure must never
    crash callers."""
    from ..parallel.mesh import local_devices as _ld
    return _ld()


def global_devices() -> List:
    import jax
    return jax.devices()


def num_tasks(requested: Optional[int] = None) -> int:
    """Number of data-parallel workers a training job should shard into.

    The reference sizes this from executor/task counts
    (``LightGBMBase.scala:447-470``); here it is the global chip count unless
    the caller requests fewer.
    """
    n = len(global_devices())
    if requested is not None and requested > 0:
        return min(requested, n)
    return n


def get_driver_host() -> str:
    """Coordinator address (reference: ``ClusterUtil.getDriverHost:107``).

    Used only to bootstrap ``jax.distributed``; collectives themselves ride
    ICI/DCN, never this address.
    """
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if addr:
        return addr.split(":")[0]
    return socket.gethostbyname(socket.gethostname())


def device_for_partition(part_index: int):
    """Pin a partition to a host-local chip round-robin.

    Replaces the reference's GPU pinning from task resources
    (``ONNXModel.scala:293-303`` — ``selectGpuDevice(TaskContext.resources)``).
    Shares the degrading implementation in ``parallel.mesh``.
    """
    from ..parallel.mesh import device_for_partition as _dfp
    return _dfp(part_index)
