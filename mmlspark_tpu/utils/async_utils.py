"""Bounded-concurrency future draining.

Parity surface: ``AsyncUtils.bufferedAwait`` (``core/.../core/utils/AsyncUtils.scala``)
used by the async HTTP client (``io/http/Clients.scala:48-62``): keep at most
``concurrency`` requests in flight while yielding results in input order.
"""

from __future__ import annotations

import collections
import concurrent.futures
from typing import Callable, Iterable, Iterator, Optional, TypeVar

T = TypeVar("T")
R = TypeVar("R")

__all__ = ["buffered_await", "map_buffered"]


def buffered_await(futures: Iterable["concurrent.futures.Future[R]"],
                   concurrency: int,
                   timeout_s: Optional[float] = None) -> Iterator[R]:
    """Yield results in order, never materializing more than ``concurrency``
    outstanding futures. Caller supplies an iterator that *lazily* submits."""
    buf: collections.deque = collections.deque()
    it = iter(futures)
    try:
        for _ in range(max(1, concurrency)):
            buf.append(next(it))
    except StopIteration:
        pass
    while buf:
        fut = buf.popleft()
        # await before pulling the next future: pulling first would let the
        # caller submit while `fut` still runs — concurrency+1 in flight
        result = fut.result(timeout=timeout_s)
        try:
            buf.append(next(it))
        except StopIteration:
            pass
        yield result


def map_buffered(fn: Callable[[T], R], items: Iterable[T], concurrency: int,
                 timeout_s: Optional[float] = None) -> Iterator[R]:
    """Apply ``fn`` with bounded parallelism, yielding in input order."""
    with concurrent.futures.ThreadPoolExecutor(max_workers=max(1, concurrency)) as ex:
        yield from buffered_await((ex.submit(fn, x) for x in items),
                                  concurrency, timeout_s)
