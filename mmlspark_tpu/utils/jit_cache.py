"""Process-wide cache of jitted functions + persistent compilation cache.

Per-call ``@jax.jit`` closures create a fresh function object every
invocation, so jax's jit cache never hits and every transform recompiles.
Stages register their kernels here once, keyed by a stable name.

``enable_persistent_cache()`` additionally turns on JAX's on-disk
compilation cache so compiled executables survive ACROSS PROCESSES —
the measured GBDT warmup at HIGGS-11M is ~29 s of mostly compilation
per fresh process (was 98 s in r4), which repeat jobs should not re-pay.
Enabled automatically at package import when
``MMLSPARK_TPU_COMPILE_CACHE`` names a directory (unset = off: the
cache writes to disk, which a library must not do unasked).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Tuple

_CACHE: Dict[str, Callable] = {}

__all__ = ["jitted", "enable_persistent_cache"]


def enable_persistent_cache(cache_dir: Optional[str] = None) -> bool:
    """Point JAX's compilation cache at ``cache_dir`` (default: the
    ``MMLSPARK_TPU_COMPILE_CACHE`` env var). Returns whether it is on —
    derived from ``jax.config`` itself, the single source of truth (a
    separate flag could desync across reloads or external config edits).
    Safe to call repeatedly; a missing directory is created.

    The wiring itself lives in :mod:`mmlspark_tpu.ops.compile_cache` (one
    implementation for this knob, the serving warm-up path, and
    ``JAX_COMPILATION_CACHE_DIR``); this wrapper keeps the historical
    bool-returning API.
    """
    import jax

    from ..ops.compile_cache import enable_persistent_cache as _enable
    _enable(cache_dir or os.environ.get("MMLSPARK_TPU_COMPILE_CACHE"))
    return bool(jax.config.jax_compilation_cache_dir)


def jitted(name: str, fn: Callable,
           static_argnums: Optional[Tuple[int, ...]] = None) -> Callable:
    """Return a jitted version of ``fn`` cached under ``name``. The first
    caller's ``fn`` wins — callers must pass a pure function whose behavior
    is fully determined by its arguments (+ static args)."""
    if name not in _CACHE:
        import jax
        _CACHE[name] = (jax.jit(fn, static_argnums=static_argnums)
                        if static_argnums is not None else jax.jit(fn))
    return _CACHE[name]
