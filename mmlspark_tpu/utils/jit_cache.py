"""Process-wide cache of jitted functions.

Per-call ``@jax.jit`` closures create a fresh function object every
invocation, so jax's jit cache never hits and every transform recompiles.
Stages register their kernels here once, keyed by a stable name.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

_CACHE: Dict[str, Callable] = {}

__all__ = ["jitted"]


def jitted(name: str, fn: Callable,
           static_argnums: Optional[Tuple[int, ...]] = None) -> Callable:
    """Return a jitted version of ``fn`` cached under ``name``. The first
    caller's ``fn`` wins — callers must pass a pure function whose behavior
    is fully determined by its arguments (+ static args)."""
    if name not in _CACHE:
        import jax
        _CACHE[name] = (jax.jit(fn, static_argnums=static_argnums)
                        if static_argnums is not None else jax.jit(fn))
    return _CACHE[name]
