"""Retry / timeout helpers.

Parity surface: ``FaultToleranceUtils.retryWithTimeout``
(``core/.../core/utils/FaultToleranceUtils.scala:10-22``) and the exponential
backoff used around LightGBM network init (``TrainUtils.scala:280-296``,
constants ``LightGBMConstants.scala:49-56``).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional, Sequence, TypeVar

T = TypeVar("T")

__all__ = ["retry_with_timeout", "retry_with_backoff"]

DEFAULT_WAITS_MS = (0, 100, 500, 1000, 3000, 5000)


def retry_with_timeout(fn: Callable[[], T], timeout_s: float,
                       retries: int = 3) -> T:
    """Run ``fn`` with a wall-clock timeout, retrying on failure/timeout."""
    # Bare daemon threads, not ThreadPoolExecutor: its atexit hook joins
    # worker threads, so a permanently hung fn would block interpreter exit
    # even after the timeout fired here.
    err: Optional[Exception] = None
    for _ in range(max(1, retries)):
        box: "queue.Queue" = queue.Queue(1)

        def run():
            try:
                box.put(("ok", fn()))
            except Exception as e:  # noqa: BLE001 — shipped to the caller
                box.put(("err", e))

        threading.Thread(target=run, daemon=True).start()
        try:
            kind, payload = box.get(timeout=timeout_s)
        except queue.Empty:
            err = TimeoutError(f"call exceeded {timeout_s}s")
            continue
        if kind == "ok":
            return payload
        err = payload
    raise err  # type: ignore[misc]


def retry_with_backoff(fn: Callable[[], T],
                       waits_ms: Sequence[int] = DEFAULT_WAITS_MS) -> T:
    """Retry with fixed backoff schedule (reference default waits)."""
    err: Optional[Exception] = None
    for wait in waits_ms:
        if wait:
            time.sleep(wait / 1e3)
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            err = e
    raise err  # type: ignore[misc]
