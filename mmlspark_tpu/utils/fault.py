"""Retry / timeout helpers.

Parity surface: ``FaultToleranceUtils.retryWithTimeout``
(``core/.../core/utils/FaultToleranceUtils.scala:10-22``) and the exponential
backoff used around LightGBM network init (``TrainUtils.scala:280-296``,
constants ``LightGBMConstants.scala:49-56``).
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Callable, Optional, Sequence, TypeVar

T = TypeVar("T")

__all__ = ["retry_with_timeout", "retry_with_backoff"]

DEFAULT_WAITS_MS = (0, 100, 500, 1000, 3000, 5000)


def retry_with_timeout(fn: Callable[[], T], timeout_s: float,
                       retries: int = 3) -> T:
    """Run ``fn`` with a wall-clock timeout, retrying on failure/timeout."""
    err: Optional[Exception] = None
    for _ in range(max(1, retries)):
        # No context manager: `with` would block in shutdown(wait=True) until
        # a hung fn returns, defeating the timeout entirely.
        ex = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        fut = ex.submit(fn)
        try:
            return fut.result(timeout=timeout_s)
        except Exception as e:  # noqa: BLE001 — retry ladder
            err = e
        finally:
            ex.shutdown(wait=False, cancel_futures=True)
    raise err  # type: ignore[misc]


def retry_with_backoff(fn: Callable[[], T],
                       waits_ms: Sequence[int] = DEFAULT_WAITS_MS) -> T:
    """Retry with fixed backoff schedule (reference default waits)."""
    err: Optional[Exception] = None
    for wait in waits_ms:
        if wait:
            time.sleep(wait / 1e3)
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            err = e
    raise err  # type: ignore[misc]
