"""Profiling: jax.profiler traces + stage annotations.

The reference has no tracer — only ad-hoc ``StopWatch``/``Timer`` timings
(SURVEY.md §5). The TPU-native replacement is the XLA profiler:
:func:`trace` captures a TensorBoard-loadable device trace and
:func:`annotate` scopes host work so stage names appear on the timeline.
``PipelineStage`` fit/transform calls are annotated automatically (see
``core/pipeline.py``), giving per-stage device attribution for free.
"""

from __future__ import annotations

import contextlib
import time

__all__ = ["trace", "annotate", "StopWatch"]

from .shared import StopWatch  # re-export: the reference-style wall timer


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler trace into ``log_dir`` (TensorBoard format)."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named scope on the profiler timeline; no-op outside a trace."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()
