"""Profiling: jax.profiler traces, stage annotations, and a host-side
span tracer exporting Chrome trace format.

The reference has no tracer — only ad-hoc ``StopWatch``/``Timer`` timings
(SURVEY.md §5). Two TPU-native replacements:

* device side — the XLA profiler: :func:`trace` captures a
  TensorBoard-loadable device trace and :func:`annotate` scopes host work
  so stage names appear on the timeline; ``PipelineStage`` fit/transform
  calls are annotated automatically (``core/pipeline.py``).
* host side — :class:`SpanTracer`: nested spans (pipeline → stage →
  partition) recorded per thread and exported as ``chrome://tracing`` /
  Perfetto JSON, so a whole pipeline run is inspectable without
  TensorBoard. :func:`span` writes to the installed tracer (no-op when
  none), so library code can annotate unconditionally.

Installation is **contextvars-based** (observability/tracing.py): the
active tracer rides the context, so worker threads entered through
``tracing.propagate`` inherit it, and :func:`span` additionally records
into the active request trace when one exists — the Chrome-trace,
Prometheus, and /debug/traces views of the same run agree.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time

__all__ = ["trace", "annotate", "StopWatch", "SpanTracer", "span"]

from ..observability import histogram as _metric_histogram
from ..observability import tracing as _tracing
from .shared import StopWatch  # re-export: the reference-style wall timer

_M_SPANS = _metric_histogram(
    "mmlspark_span_seconds",
    "Closed SpanTracer spans, mirrored from the Chrome-trace view when the "
    "tracer is built with mirror_metrics=True", ("name",))


class SpanTracer:
    """Collect nested host-side spans; export Chrome trace JSON.

    >>> with SpanTracer() as t:
    ...     with span("fit"):
    ...         with span("stage:LightGBMClassifier"):
    ...             ...
    >>> t.export("run.trace.json")   # open in chrome://tracing / Perfetto

    ``mirror_metrics=True`` additionally observes every closed span into
    the ``mmlspark_span_seconds{name=...}`` histogram, so the Chrome-trace
    and Prometheus views of a run agree.
    """

    def __init__(self, mirror_metrics: bool = False):
        self._events = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._tids: dict = {}  # thread ident → small sequential track id
        self._mirror = bool(mirror_metrics)

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = self._tids[ident] = len(self._tids)
        return tid

    # -- recording ----------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **args):
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            with self._lock:
                # bounded by the tracer's `with` block, not process
                # lifetime: events are exported/discarded on exit — not a
                # live history (that's observability.timeseries)
                # tpulint: disable=TPU024
                self._events.append({
                    "name": name, "ph": "X", "pid": 0,
                    "tid": self._tid(),
                    "ts": (start - self._t0) * 1e6,
                    "dur": (end - start) * 1e6,
                    **({"args": args} if args else {})})
            if self._mirror:
                _M_SPANS.observe(end - start, name=name)

    # -- lifecycle ----------------------------------------------------------
    def __enter__(self) -> "SpanTracer":
        # contextvars install (was threading.local): child contexts — and
        # workers entered via tracing.propagate — see this tracer; a
        # concurrent tracer in an unrelated context still can't cross-record
        self._token = _tracing.install_tracer(self)
        return self

    def __exit__(self, *exc) -> None:
        _tracing.uninstall_tracer(self._token)

    # -- inspection / export -------------------------------------------------
    @property
    def events(self):
        with self._lock:
            return list(self._events)

    def total(self, name: str) -> float:
        """Total seconds spent in spans with this name."""
        return sum(e["dur"] for e in self.events
                   if e["name"] == name) / 1e6

    def export(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events,
                       "displayTimeUnit": "ms"}, f)
        return path


def span(name: str, **args):
    """Span on the context's active :class:`SpanTracer` AND the active
    request trace (observability/tracing.py), plus a device-timeline
    annotation; cheap no-op when neither is installed.

    Worker threads spawned inside a traced region inherit both through
    ``tracing.propagate`` — wrap the worker's callable at submission time
    (models/runner.py does this for the prefetch worker, core/dataframe.py
    for the partition pool) and spans opened there land in the parent
    trace. The old ``threading.local`` dead-end (workers recording into
    the void) is gone."""
    tracer = _tracing.installed_tracer()
    in_trace = _tracing.current_span() is not None
    if tracer is None and not in_trace:
        return annotate(name)
    stack = contextlib.ExitStack()
    if tracer is not None:
        stack.enter_context(tracer.span(name, **args))
    if in_trace:
        stack.enter_context(_tracing.start_span(name, **args))
    stack.enter_context(annotate(name))
    return stack


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler trace into ``log_dir`` (TensorBoard format)."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named scope on the profiler timeline; no-op outside a trace."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()
