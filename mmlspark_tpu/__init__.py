"""mmlspark_tpu — a TPU-native ML pipeline framework.

A ground-up rebuild of the capability set of SynapseML/MMLSpark (reference:
Scala/Spark + JNI-native compute) as a JAX/XLA/Pallas-first framework:
columnar DataFrames feeding padded device batches, Estimator/Transformer
pipelines, ONNX→JAX compiled inference, distributed histogram-GBDT training
over a device mesh, explainers, featurization, serving, and HTTP transformers.
"""

__version__ = "0.1.0"

import os as _os

from .core import (DataFrame, Estimator, Model, Pipeline, PipelineModel,
                   PipelineStage, Transformer, concat)

if _os.environ.get("MMLSPARK_TPU_COMPILE_CACHE") \
        or _os.environ.get("MMLSPARK_TPU_COMPILE_CACHE_DIR"):
    # opt-in persistent compilation cache: compiled executables survive
    # across processes (repeat jobs skip the multi-second XLA warmup)
    from .utils.jit_cache import enable_persistent_cache as _epc
    _epc()

__all__ = ["DataFrame", "concat", "PipelineStage", "Transformer", "Estimator",
           "Model", "Pipeline", "PipelineModel", "__version__"]
