"""Compiled-HLO collective auditor: the runtime half of the sharding story.

``tools/tpulint/sharding.py`` (TPU019–TPU022) catches the sharding
mistakes visible in the AST; this module catches the ones only the
compiler can see. GSPMD is free to *insert* collectives the source never
wrote — a spec that forces a resharding materializes as an all-gather
nothing in the Python program names, and the bench only notices on a
real TPU pod. The auditor makes the compiled collective structure a
checked artifact instead:

- Opt in with ``MMLSPARK_TPU_COLLECTIVE_AUDIT=1``.
  :func:`audit_program` then wraps each cached decode program
  (``serving/continuous.py`` factories, ``compile_cache.warm_up_jitted``
  buckets) so the first call per argument signature walks
  ``jit(...).lower(...).compile().as_text()`` and counts collective ops
  by kind — all-reduce, all-gather, reduce-scatter, collective-permute,
  all-to-all — with output-shape byte estimates. Disabled (the default)
  it returns the program unchanged: zero overhead, zero imports of jax.
- Counts mirror as ``mmlspark_collective_ops_total{prog,kind}`` /
  ``mmlspark_collective_bytes_total{prog,kind}`` and land in the
  :class:`~mmlspark_tpu.tuning.observations.ObservationStore` via
  ``harvest_collectives`` (``source="collective_audit"``) so the cost
  model's ``collective_ms_per_tick_est`` gets a measured op-count basis.
- The per-program table diffs against a committed, line-number-free
  budget (``tools/tpulint/collective_budget.json``, the same
  versioned-JSON shape as the tpulint baseline). ``python -m
  mmlspark_tpu.parallel.collective_audit`` rebuilds the meshed programs
  on the simulated 8-device mesh, re-audits, and exits 1 when any
  program exceeds its budget — the PR 15 invariant (meshed decode tick
  = exactly one all-reduce, zero all-gathers) breaks the build instead
  of a future TPU round.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple, TypeVar

from ..observability import counter as _metric_counter

_F = TypeVar("_F", bound=Callable[..., Any])

ENV_FLAG = "MMLSPARK_TPU_COLLECTIVE_AUDIT"

#: HLO collective kinds the auditor counts (async ``-start`` forms fold
#: into their base kind; ``-done`` ops carry no payload and are skipped)
KINDS = ("all-reduce", "all-gather", "reduce-scatter",
         "collective-permute", "all-to-all")

#: the committed budget, colocated with the tpulint baseline it mirrors
DEFAULT_BUDGET_PATH = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir, os.pardir,
    "tools", "tpulint", "collective_budget.json"))

M_COLLECTIVE_OPS = _metric_counter(
    "mmlspark_collective_ops_total",
    "Collective ops in audited compiled programs, by program and kind",
    ("prog", "kind"))
M_COLLECTIVE_BYTES = _metric_counter(
    "mmlspark_collective_bytes_total",
    "Estimated bytes moved by audited collectives (output-shape bytes)",
    ("prog", "kind"))


def enabled() -> bool:
    """The audit opt-in: ``MMLSPARK_TPU_COLLECTIVE_AUDIT=1`` (anything
    but empty/0/false/no)."""
    return os.environ.get(ENV_FLAG, "").strip().lower() \
        not in ("", "0", "false", "no")


# ---------------------------------------------------------------------------
# HLO text → collective counts
# ---------------------------------------------------------------------------

# "%x = f32[4,8]{1,0} all-reduce(...)" / "... all-gather-start(...)".
# Requiring "(" right after the (optionally -start) kind keeps the
# payload-free "-done" halves of async pairs out of the count.
_COLLECTIVE_RE = re.compile(
    r"=\s*([^\n]*?)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")

#: dtype token → bytes per element, for the output-shape byte estimate
_DTYPE_BYTES = {"pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
                "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
                "s32": 4, "u32": 4, "f32": 4, "c64": 8,
                "s64": 8, "u64": 8, "f64": 8, "c128": 16}

_SHAPE_TOKEN_RE = re.compile(r"\b([a-z]\w*)\[([0-9,]*)\]")


def _shape_bytes(shape_text: str) -> int:
    """Bytes of every ``dtype[dims]`` token in an HLO shape string —
    tuple shapes sum their elements; layout suffixes don't match."""
    total = 0
    for dtype, dims in _SHAPE_TOKEN_RE.findall(shape_text):
        per = _DTYPE_BYTES.get(dtype)
        if per is None:
            per = 1 if dtype.startswith("f8") else None
        if per is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * per
    return total


def count_collectives(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Collectives in one compiled module's HLO text, by kind:
    ``{kind: {"ops": n, "bytes": estimated_output_bytes}}`` (kinds with
    zero ops are omitted)."""
    out: Dict[str, Dict[str, int]] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        kind = m.group(2)
        row = out.setdefault(kind, {"ops": 0, "bytes": 0})
        row["ops"] += 1
        row["bytes"] += _shape_bytes(m.group(1))
    return out


# ---------------------------------------------------------------------------
# the auditor: per-program table + metrics mirror
# ---------------------------------------------------------------------------

def _call_signature(args: tuple, kwargs: dict) -> Tuple:
    """Hashable (treedef, leaf shape/dtype) signature of one call — the
    unit the audit dedupes on, matching jit's own cache key shape."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            sig.append((tuple(shape), str(getattr(leaf, "dtype", "?"))))
        else:
            sig.append((type(leaf).__name__, repr(leaf)[:64]))
    return str(treedef), tuple(sig)


class CollectiveAuditor:
    """Per-program collective table: ``sigs`` audited signatures and the
    elementwise MAX of each kind's ops/bytes across them (a budget bounds
    the worst signature, so the max is the honest summary)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._table: Dict[str, Dict[str, Any]] = {}

    def record_hlo(self, prog: str, hlo_text: str) -> Dict[str, Dict]:
        counts = count_collectives(hlo_text)
        with self._lock:
            row = self._table.setdefault(prog, {"sigs": 0, "kinds": {}})
            row["sigs"] += 1
            for kind, c in counts.items():
                k = row["kinds"].setdefault(kind, {"ops": 0, "bytes": 0})
                k["ops"] = max(k["ops"], c["ops"])
                k["bytes"] = max(k["bytes"], c["bytes"])
        for kind, c in counts.items():
            M_COLLECTIVE_OPS.inc(c["ops"], prog=prog, kind=kind)
            M_COLLECTIVE_BYTES.inc(c["bytes"], prog=prog, kind=kind)
        return counts

    def record_lowered(self, prog: str, fn, *args,
                       **kwargs) -> Optional[Dict[str, Dict]]:
        """Lower/compile ``fn`` for these arguments and record its HLO.
        Never raises into the serving path — a program that resists
        lowering (donation quirks, non-jitted callable) audits as
        nothing rather than killing the tick."""
        try:
            hlo = fn.lower(*args, **kwargs).compile().as_text()
        except Exception:
            return None
        return self.record_hlo(prog, hlo)

    def table(self) -> Dict[str, Dict[str, Any]]:
        """JSON-safe deep copy of the per-program table."""
        with self._lock:
            return {prog: {"sigs": row["sigs"],
                           "kinds": {k: dict(v)
                                     for k, v in row["kinds"].items()}}
                    for prog, row in self._table.items()}


_AUDITOR = CollectiveAuditor()


def get_auditor() -> CollectiveAuditor:
    return _AUDITOR


def reset_auditor() -> None:
    """Tests: drop the accumulated table (metrics reset separately via
    ``observability.reset_all``)."""
    global _AUDITOR
    _AUDITOR = CollectiveAuditor()


def audit_program(prog: str, fn: _F) -> _F:
    """Wrap a jitted program so each new argument signature is lowered
    once more and its compiled HLO's collectives recorded under ``prog``.

    With the audit disabled (the default) this returns ``fn`` itself —
    the serving path pays nothing, not even a wrapper frame. Enabled, the
    one extra ``lower().compile()`` per signature hits jax's compilation
    cache the jitted call just warmed, so the audit costs a cache lookup
    and a text render, not a second compile.
    """
    if not enabled():
        return fn

    auditor = get_auditor()
    seen: set = set()
    lock = threading.Lock()

    def wrapper(*args, **kwargs):
        try:
            sig = _call_signature(args, kwargs)
        except Exception:
            sig = None
        if sig is not None:
            with lock:
                fresh = sig not in seen
                if fresh:
                    seen.add(sig)
            if fresh:
                auditor.record_lowered(prog, fn, *args, **kwargs)
        return fn(*args, **kwargs)

    wrapper.__name__ = getattr(fn, "__name__", prog)
    wrapper._audited_prog = prog
    wrapper._audited_fn = fn
    cache_size = getattr(fn, "_cache_size", None)
    if cache_size is not None:
        # keep compile_cache.jit_cache_size introspection working
        wrapper._cache_size = cache_size
    return wrapper


# ---------------------------------------------------------------------------
# budget file (same versioned-JSON discipline as the tpulint baseline)
# ---------------------------------------------------------------------------

BUDGET_VERSION = 1


def budget_from_table(table: Dict[str, Dict]) -> Dict[str, Any]:
    """Collapse an auditor table into the committed budget shape:
    ``{"version": 1, "budgets": {prog: {kind: max_ops}}}``. Programs with
    no collectives get an empty dict — their budget is *zero of
    everything*, so a regression inserting any collective trips CI."""
    budgets = {prog: {kind: row["kinds"][kind]["ops"]
                      for kind in sorted(row.get("kinds", {}))
                      if row["kinds"][kind]["ops"] > 0}
               for prog, row in sorted(table.items())}
    return {"version": BUDGET_VERSION, "budgets": budgets}


def load_budget(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BUDGET_VERSION:
        raise ValueError(
            f"unknown collective budget version {data.get('version')!r} "
            f"in {path} (expected {BUDGET_VERSION})")
    return data


def write_budget(table: Dict[str, Dict], path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(budget_from_table(table), fh, indent=2, sort_keys=True)
        fh.write("\n")


def check_budget(table: Dict[str, Dict],
                 budget: Dict[str, Any]) -> Tuple[List[str], List[str]]:
    """Diff an observed table against the committed budget.

    Returns ``(violations, drift)``: violations are observed counts
    above budget or programs the budget has never seen (both gate CI);
    drift is counts *below* budget — an improvement worth re-recording
    with ``--write-budget``, reported but not gating."""
    budgets = budget.get("budgets", {})
    violations: List[str] = []
    drift: List[str] = []
    for prog in sorted(table):
        kinds = table[prog].get("kinds", {})
        allowed = budgets.get(prog)
        if allowed is None:
            observed = {k: v["ops"] for k, v in sorted(kinds.items())}
            desc = json.dumps(observed) if observed else "none"
            violations.append(
                f"{prog}: program not in budget (observed {desc}) — "
                f"record it with --write-budget")
            continue
        for kind in sorted(set(kinds) | set(allowed)):
            ops = kinds.get(kind, {}).get("ops", 0)
            cap = int(allowed.get(kind, 0))
            if ops > cap:
                violations.append(
                    f"{prog}: {kind} x{ops} exceeds budget of {cap} — "
                    f"a resharding crept into the compiled program")
            elif ops < cap:
                drift.append(
                    f"{prog}: {kind} x{ops} under budget of {cap} — "
                    f"improvement; tighten with --write-budget")
    return violations, drift


# ---------------------------------------------------------------------------
# CLI: rebuild the meshed programs, re-audit, diff against the budget
# ---------------------------------------------------------------------------

def _audit_reference_programs() -> None:
    """Build and drive every meshed program on the simulated 8-device
    mesh so the process-wide auditor sees each one at least once: the
    engine's tick/spec-tick/prefill/extend family (plus its page-plumbing
    programs), and standalone ring/flash/MoE steps."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models.zoo.transformer import TransformerConfig, init_transformer
    from ..ops.flash_attention import flash_attention_sharded
    from ..serving.continuous import ContinuousDecoder
    from .mesh import get_shard_map, make_mesh
    from .moe import init_moe_params, moe_capacity, moe_ffn_gspmd
    from .ring import wrap_ring_attention

    devs = jax.devices()
    if len(devs) < 8:
        raise SystemExit(
            "collective_audit: needs 8 (simulated) devices — run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "JAX_PLATFORMS=cpu")
    mesh = make_mesh({"dp": 4, "tp": 2}, devs[:8])

    cfg = TransformerConfig(vocab=128, layers=2, d_model=64, heads=4,
                            d_ff=128, max_len=96, causal=True,
                            norm="rmsnorm", position="rope",
                            dtype=jnp.float32)
    d_cfg = cfg._replace(layers=1, d_model=32, heads=2, d_ff=64)
    params = init_transformer(cfg, seed=0)
    d_params = init_transformer(d_cfg, seed=1)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, cfg.vocab, 4 + 3 * i).astype(np.int32)
               for i in range(4)]

    def drain(eng, ps, max_new=8):
        reqs = [eng.submit(p, max_new_tokens=max_new) for p in ps]
        while any(r is not None for r in eng._slot_req) or eng._waiting:
            eng.step()
        return reqs

    # plain meshed engine: tick, prefill, extend (prefill_chunk smaller
    # than the longest prompt forces the chunked path), page plumbing
    # (defrag_threshold=1 makes retirement compact the pool mid-run)
    eng = ContinuousDecoder(params, cfg, max_slots=4, max_len=64,
                            mesh=mesh, paged_attn="kernel",
                            prefill_chunk=8, defrag_threshold=1)
    drain(eng, prompts)
    # sampled tick
    eng2 = ContinuousDecoder(params, cfg, max_slots=4, max_len=64,
                             mesh=mesh, paged_attn="kernel")
    req = eng2.submit(prompts[0], max_new_tokens=4, temperature=0.7)
    while not req.done:
        eng2.step()
    # speculative tick (draft model riding the same mesh)
    eng3 = ContinuousDecoder(params, cfg, max_slots=4, max_len=64,
                             mesh=mesh, paged_attn="kernel",
                             draft_params=d_params, draft_cfg=d_cfg,
                             gamma=2)
    drain(eng3, prompts[:2], max_new=6)

    # standalone meshed steps: sequence-parallel attention (sp over all
    # 8 devices; the ulysses impl — ring-proper needs lax.pcast, newer
    # than the pinned jax), flash attention (dp×tp), MoE dispatch (ep)
    sp_mesh = make_mesh({"sp": 8}, devs[:8])
    # B divisible by dp (flash), H by sp (ulysses) and tp (flash), S by sp
    B, H, S, D = 4, 8, 64, 16
    k = jax.random.PRNGKey(0)
    q, kk, v = (jax.random.normal(jax.random.fold_in(k, i), (B, H, S, D),
                                  jnp.float32) for i in range(3))
    sp_fn = audit_program(
        "sp_step",
        jax.jit(wrap_ring_attention(sp_mesh, "sp", "ulysses")))
    jax.block_until_ready(sp_fn(q, kk, v))

    # the PR 15 invariant, stated as its own budgeted program: the
    # decode tick's attention core. Heads shard over tp, attention is
    # entirely head-local, and the row-parallel output projection pays
    # the ONE psum that merges head contributions. Its committed budget
    # is exactly {all-reduce: 1} — no all-gathers — so a resharding
    # that re-inserts a gather into this step breaks CI. (The full
    # "tick" program is budgeted too, at its recorded compiled counts:
    # norm statistics and the host-fetch gather legitimately add
    # collectives there that are not part of this invariant.)
    shard_map, uncheck = get_shard_map()
    tp_mesh = make_mesh({"tp": 2}, devs[:2])
    wo = jax.random.normal(jax.random.fold_in(k, 9), (H * D, H * D),
                           jnp.float32) * 0.05

    def _attn_core(ql, kl, vl, wo_shard):
        s = jnp.einsum("bhqd,bhkd->bhqk", ql, kl,
                       preferred_element_type=jnp.float32) / (D ** 0.5)
        o = jnp.einsum("bhqk,bhkd->bhqd",
                       jax.nn.softmax(s, axis=-1).astype(vl.dtype), vl)
        flat = o.transpose(0, 2, 1, 3).reshape(
            ql.shape[0], ql.shape[2], -1)
        return jax.lax.psum(flat @ wo_shard, "tp")

    core = shard_map(_attn_core, mesh=tp_mesh,
                     in_specs=(P(None, "tp", None, None),) * 3
                     + (P("tp", None),),
                     out_specs=P(), **uncheck)
    core_fn = audit_program("tick_core", jax.jit(core))
    jax.block_until_ready(core_fn(q, kk, v, wo))

    flash_fn = audit_program(
        "flash_step",
        jax.jit(lambda a, b, c: flash_attention_sharded(a, b, c, mesh)))
    jax.block_until_ready(flash_fn(q, kk, v))

    # MoE dispatch: the GSPMD variant — XLA inserts the dispatch/return
    # all-to-alls from the sharding constraints, which is exactly the
    # "compiler-inserted collective" class the audit exists to pin down
    # (moe_ffn_sharded's explicit path needs lax.axis_size, newer than
    # the pinned jax)
    n_exp = 4
    cap = moe_capacity(6, n_exp)
    moe_params = init_moe_params(cfg.d_model, cfg.d_ff, n_exp, seed=2)
    t = jax.random.normal(jax.random.fold_in(k, 7),
                          (8, 6, cfg.d_model), jnp.float32)
    from jax.sharding import NamedSharding, PartitionSpec as P
    pd = jax.device_put(moe_params, {
        "gate": NamedSharding(mesh, P()),
        "w1": NamedSharding(mesh, P("dp", None, "tp")),
        "b1": NamedSharding(mesh, P("dp", "tp")),
        "w2": NamedSharding(mesh, P("dp", "tp", None)),
        "b2": NamedSharding(mesh, P("dp", None))})
    td = jax.device_put(t, NamedSharding(mesh, P("dp", None, None)))
    moe_fn = audit_program(
        "moe_dispatch",
        jax.jit(lambda a, p: moe_ffn_gspmd(a, p, n_exp, cap, mesh=mesh,
                                           ep_axis="dp", tp_axis="tp")))
    jax.block_until_ready(moe_fn(td, pd))


def _report(table: Dict[str, Dict], out) -> None:
    for prog in sorted(table):
        row = table[prog]
        kinds = ", ".join(f"{k}:{v['ops']} (~{v['bytes']}B)"
                          for k, v in sorted(row["kinds"].items())) \
            or "no collectives"
        print(f"  {prog:<14} sigs={row['sigs']:<3} {kinds}", file=out)


def main(argv: Optional[List[str]] = None, stdout=None) -> int:
    out = stdout if stdout is not None else sys.stdout
    ap = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.parallel.collective_audit",
        description="Audit compiled-HLO collectives against the "
                    "committed per-program budget.")
    ap.add_argument("--budget", default=DEFAULT_BUDGET_PATH,
                    help="budget JSON path (default: the committed "
                         "tools/tpulint/collective_budget.json)")
    ap.add_argument("--write-budget", action="store_true",
                    help="record the observed table as the new budget "
                         "instead of diffing against it")
    ap.add_argument("--table",
                    help="audit a previously dumped table JSON instead "
                         "of rebuilding the meshed programs (tests)")
    ap.add_argument("--dump-table",
                    help="also write the observed table JSON here")
    ap.add_argument("--harvest", action="store_true",
                    help="land the table in the ObservationStore "
                         "(source=collective_audit)")
    args = ap.parse_args(argv)

    if args.table:
        with open(args.table, encoding="utf-8") as fh:
            table = json.load(fh)
    else:
        os.environ[ENV_FLAG] = "1"
        reset_auditor()
        _audit_reference_programs()
        table = get_auditor().table()

    print(f"collective_audit: {len(table)} program(s)", file=out)
    _report(table, out)

    if args.dump_table:
        with open(args.dump_table, "w", encoding="utf-8") as fh:
            json.dump(table, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.harvest:
        from ..tuning.observations import harvest_collectives
        n = harvest_collectives(table)
        print(f"collective_audit: harvested {n} observation row(s)",
              file=out)

    if args.write_budget:
        write_budget(table, args.budget)
        print(f"collective_audit: wrote budget for {len(table)} "
              f"program(s) to {args.budget}", file=out)
        return 0

    try:
        budget = load_budget(args.budget)
    except OSError:
        print(f"collective_audit: no budget at {args.budget} — record "
              f"one with --write-budget", file=out)
        return 1
    violations, drift = check_budget(table, budget)
    for line in drift:
        print(f"collective_audit: note: {line}", file=out)
    if violations:
        for line in violations:
            print(f"collective_audit: BUDGET EXCEEDED: {line}", file=out)
        return 1
    print("collective_audit: within budget", file=out)
    return 0


if __name__ == "__main__":
    # run the CANONICAL module's main: under ``python -m`` this file
    # executes as ``__main__``, a second module instance whose auditor
    # the engine (which imports the canonical name) would never touch
    from mmlspark_tpu.parallel.collective_audit import main as _main
    sys.exit(_main())
