"""Expert parallelism: mixture-of-experts FFN with all_to_all routing.

Beyond-parity distributed capability (the reference has no intra-model
sharding at all — SURVEY §2.8): a GShard-style top-1 MoE block whose experts
are sharded over an ``ep`` mesh axis. Tokens are locally gated, packed into
per-expert capacity slots, exchanged with ``jax.lax.all_to_all`` (which XLA
lowers onto ICI), processed by the local experts, and returned the same way.

Design notes (TPU-first):
* dispatch/combine are einsums over one-hot masks — MXU work, no scatters;
* static capacity ``C`` keeps every shape fixed for XLA (overflow tokens are
  dropped, standard GShard semantics, exposed via ``aux["dropped"]``);
* the block is written for ``shard_map`` (see :func:`moe_ffn_sharded`) so
  the collective pattern is explicit and testable on a virtual CPU mesh.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["init_moe_params", "moe_ffn_local", "moe_ffn_sharded",
           "moe_ffn_gspmd", "moe_shardings", "moe_capacity"]


def moe_capacity(tokens_per_shard: int, n_experts: int,
                 capacity_factor: float = 1.25) -> int:
    """Static per-expert capacity per source shard."""
    return max(1, math.ceil(tokens_per_shard / n_experts * capacity_factor))


def init_moe_params(d_model: int, d_ff: int, n_experts: int,
                    seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    s1 = 1.0 / math.sqrt(d_model)
    s2 = 1.0 / math.sqrt(d_ff)
    return {
        "gate": (rng.normal(0, s1, (d_model, n_experts))).astype(np.float32),
        "w1": (rng.normal(0, s1, (n_experts, d_model, d_ff))).astype(np.float32),
        "b1": np.zeros((n_experts, d_ff), np.float32),
        "w2": (rng.normal(0, s2, (n_experts, d_ff, d_model))).astype(np.float32),
        "b2": np.zeros((n_experts, d_model), np.float32),
    }


def moe_shardings(mesh: Mesh, ep_axis: str = "ep") -> Dict:
    """Experts sharded over the ep axis; the gate replicated."""
    return {
        "gate": NamedSharding(mesh, P()),
        "w1": NamedSharding(mesh, P(ep_axis, None, None)),
        "b1": NamedSharding(mesh, P(ep_axis, None)),
        "w2": NamedSharding(mesh, P(ep_axis, None, None)),
        "b2": NamedSharding(mesh, P(ep_axis, None)),
    }


def _route_and_pack(x, gate_w, n_experts: int, capacity: int):
    """Core top-1 routing + capacity packing for one token group.
    x (T, D) → slot (T, E, C), gate_prob (T,), onehot (T, E), probs (T, E).
    The single source of truth — every MoE variant (local / shard_map /
    GSPMD-grouped) builds on this."""
    logits = x @ gate_w.astype(x.dtype)                     # (T, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # fp32 router
    expert = jnp.argmax(probs, axis=-1)                     # (T,)
    gate_prob = jnp.max(probs, axis=-1)                     # (T,)
    onehot = jax.nn.one_hot(expert, n_experts,
                            dtype=jnp.float32)              # (T, E)
    # position of each token within its expert's slots, in token order
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0         # (T, E)
    keep = (pos < capacity) & (onehot > 0)
    pos = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    slot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32) * \
        keep[..., None]                                     # (T, E, C)
    return slot, gate_prob, onehot, probs


def _aux_from_routing(slot, onehot, probs, n_experts: int,
                      token_axis: int = -2):
    """Shared auxiliaries: dropped-token count and the Switch/GShard
    load-balance loss E·Σₑ fₑ·Pₑ (fraction routed × mean router prob;
    without it top-1 routing classically collapses onto one expert and
    over-capacity tokens are silently zeroed)."""
    frac_routed = jnp.mean(onehot, axis=token_axis)
    mean_prob = jnp.mean(probs, axis=token_axis)
    return {"dropped": jnp.sum(onehot) - jnp.sum(slot),
            "balance_loss": n_experts * jnp.mean(
                jnp.sum(frac_routed * mean_prob, axis=-1))}


def _gate_and_dispatch(x, gate_w, n_experts: int, capacity: int):
    """Top-1 gating + capacity packing. x (T, D) → slot, probs, aux."""
    slot, gate_prob, onehot, probs = _route_and_pack(
        x, gate_w, n_experts, capacity)
    return slot, gate_prob, _aux_from_routing(slot, onehot, probs, n_experts)


def moe_ffn_local(x, params, n_experts: int, capacity: int):
    """Single-device reference MoE (no collectives): x (T, D) → (T, D).
    Returns (y, aux) with aux = {dropped, balance_loss}."""
    slot, gate_prob, aux = _gate_and_dispatch(
        x, params["gate"], n_experts, capacity)
    expert_in = jnp.einsum("tec,td->ecd", slot,
                           x.astype(jnp.float32))           # (E, C, D)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w1"])
                    + params["b1"][:, None, :])
    out = jnp.einsum("ecf,efd->ecd", h, params["w2"]) \
        + params["b2"][:, None, :]                          # (E, C, D)
    y = jnp.einsum("ecd,tec->td", out, slot)                # (T, D)
    return (y * gate_prob[:, None]).astype(x.dtype), aux


def _moe_shard_body(x_local, gate_w, w1_local, b1_local, w2_local, b2_local,
                    *, n_experts: int, capacity: int, ep_axis: str):
    """Per-shard body under shard_map: local gating, all_to_all dispatch to
    the expert owners, expert FFN, all_to_all combine back."""
    ep = jax.lax.axis_size(ep_axis)
    e_local = n_experts // ep
    slot, gate_prob, aux = _gate_and_dispatch(
        x_local, gate_w, n_experts, capacity)
    D = x_local.shape[-1]
    dispatch = jnp.einsum("tec,td->ecd", slot,
                          x_local.astype(jnp.float32))      # (E, C, D)
    dispatch = dispatch.reshape(ep, e_local, capacity, D)
    # symmetric exchange (split=concat=0 is its own transpose, so autodiff
    # reuses the same collective): shard k gets its e_local experts' slots
    # from every source shard — axis 0 becomes the source shard
    expert_in = jax.lax.all_to_all(dispatch, ep_axis,
                                   split_axis=0, concat_axis=0)
    expert_in = jnp.transpose(expert_in, (1, 0, 2, 3)) \
        .reshape(e_local, ep * capacity, D)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, w1_local)
                    + b1_local[:, None, :])
    out = jnp.einsum("ecf,efd->ecd", h, w2_local) \
        + b2_local[:, None, :]                              # (e_local, ep*C, D)
    # inverse exchange: back to (E, C, D) on the token-owning shard
    out = jnp.transpose(out.reshape(e_local, ep, capacity, D), (1, 0, 2, 3))
    returned = jax.lax.all_to_all(out, ep_axis,
                                  split_axis=0, concat_axis=0)
    returned = returned.reshape(n_experts, capacity, D)
    y = jnp.einsum("ecd,tec->td", returned, slot)
    aux = {"dropped": jax.lax.psum(aux["dropped"], ep_axis),
           "balance_loss": jax.lax.pmean(aux["balance_loss"], ep_axis)}
    return (y * gate_prob[:, None]).astype(x_local.dtype), aux


def moe_ffn_sharded(x, params, mesh: Mesh, n_experts: int,
                    capacity: int, ep_axis: str = "ep") -> Tuple:
    """Expert-parallel MoE over ``mesh[ep_axis]``.

    ``x`` (T, D) is sharded over tokens on the ep axis; expert weights are
    sharded over experts on the same axis (GShard: the data and expert
    meshes coincide). Returns (y, aux) with aux = {dropped, balance_loss}.
    """
    from .mesh import get_shard_map
    shard_map, _ = get_shard_map()

    assert n_experts % mesh.shape[ep_axis] == 0, \
        f"n_experts {n_experts} not divisible by ep={mesh.shape[ep_axis]}"
    body = partial(_moe_shard_body, n_experts=n_experts, capacity=capacity,
                   ep_axis=ep_axis)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(ep_axis, None), P(), P(ep_axis, None, None),
                  P(ep_axis, None), P(ep_axis, None, None), P(ep_axis, None)),
        out_specs=(P(ep_axis, None), P()),
    )(x, params["gate"], params["w1"], params["b1"],
      params["w2"], params["b2"])


def _group_gate_and_dispatch(t, gate_w, n_experts: int, capacity: int):
    """Grouped gating: t (G, Tg, D) → slot (G, Tg, E, C), probs, aux.
    vmap of the core packer over groups — capacity is per (group, expert),
    so the cumsum stays group-local (the GShard grouping trick that keeps
    dispatch free of cross-shard scans)."""
    slot, gate_prob, onehot, probs = jax.vmap(
        partial(_route_and_pack, n_experts=n_experts, capacity=capacity),
        in_axes=(0, None))(t, gate_w)
    return slot, gate_prob, _aux_from_routing(slot, onehot, probs, n_experts)


def moe_ffn_gspmd(t, params, n_experts: int, capacity: int,
                  mesh: Mesh = None, ep_axis: str = "dp",
                  tp_axis: str = None):
    """GSPMD-style expert parallelism: no shard_map — sharding constraints
    express the layout changes and XLA inserts the all-to-alls over ICI.

    ``t`` (G, Tg, D): groups sharded over ``ep_axis`` (in a transformer the
    batch axis is the natural group axis, so ep coincides with dp — the
    GShard deployment). Expert weights (E, ...) are sharded over the same
    axis; ``tp_axis`` additionally shards each expert's hidden dim. This
    variant composes with constraint-style models (zoo transformer); the
    ``shard_map`` variant (:func:`moe_ffn_sharded`) is the explicit-
    collective equivalent used where the mesh is handled manually.
    """
    def constrain(v, *spec):
        if mesh is not None:
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(*spec)))
        return v

    t = constrain(t, ep_axis, None, None)
    slot, gate_prob, aux = _group_gate_and_dispatch(
        t, params["gate"], n_experts, capacity)
    # expert compute and the cross-device dispatch run in the model dtype
    # (bf16 halves the all-to-all bytes and rides the MXU fast path);
    # only the router softmax above stays fp32, GShard practice
    dt = t.dtype
    slot_dt = slot.astype(dt)
    dispatch = jnp.einsum("gtec,gtd->gecd", slot_dt, t)     # (G, E, C, D)
    # groups-sharded → experts-sharded: XLA lowers this re-shard to an
    # all-to-all over ep_axis
    dispatch = constrain(dispatch, None, ep_axis, None, None)
    h = jax.nn.gelu(
        jnp.einsum("gecd,edf->gecf", dispatch, params["w1"].astype(dt))
        + params["b1"].astype(dt)[None, :, None, :])
    if tp_axis is not None:
        h = constrain(h, None, ep_axis, None, tp_axis)
    out = jnp.einsum("gecf,efd->gecd", h, params["w2"].astype(dt)) \
        + params["b2"].astype(dt)[None, :, None, :]
    # experts-sharded → groups-sharded: the return all-to-all
    out = constrain(out, ep_axis, None, None, None)
    y = jnp.einsum("gecd,gtec->gtd", out, slot_dt)
    return y * gate_prob[..., None].astype(dt), aux
