"""Pipeline parallelism: GPipe-style microbatch schedule over ``ppermute``.

Beyond-parity distributed capability (the reference has no intra-model
sharding — SURVEY §2.8): layers are partitioned into ``pp`` stages, each
stage living on one shard of the ``pp`` mesh axis; microbatches stream
through the stages, activations hopping stage→stage with
``jax.lax.ppermute`` (XLA lowers the hop onto ICI neighbours) inside one
``lax.scan`` — a single compiled program, no host round-trips per tick.

The schedule is the classic fill/steady/drain: with M microbatches and pp
stages the scan runs ``M + pp - 1`` ticks; stage 0 injects microbatch t at
tick t, stage pp-1 emits microbatch t at tick ``t + pp - 1``. Autodiff
works through the whole schedule (``ppermute`` transposes to the reverse
permutation), so ``jax.grad`` of a pipelined loss is pipelined backprop.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "stack_stage_params", "stage_shardings"]


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] → one tree with a leading (pp,) axis
    (shard it with :func:`stage_shardings`)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


def _stage_spec(leaf, pp_axis: str) -> P:
    """The one layout rule: leading stage axis over pp, rest replicated."""
    return P(pp_axis, *([None] * (jnp.ndim(leaf) - 1)))


def stage_shardings(params_stacked, mesh: Mesh, pp_axis: str = "pp"):
    """Leading stage axis sharded over pp; everything else replicated."""
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(mesh, _stage_spec(leaf, pp_axis)),
        params_stacked)


def _pipeline_body(params_local, x_all, *, stage_fn, pp_axis: str):
    """Per-stage body under shard_map.

    params_local: this stage's params (leading (1,) stage axis, squeezed).
    x_all (M, mb, ...): the microbatched input, replicated — only stage 0
    reads it. Returns (M, mb, ...) outputs, replicated via psum (only the
    last stage holds non-zero values before the reduction).
    """
    pp = jax.lax.axis_size(pp_axis)
    idx = jax.lax.axis_index(pp_axis)
    params_local = jax.tree_util.tree_map(lambda l: l[0], params_local)
    M = x_all.shape[0]
    mb_shape = x_all.shape[1:]
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        state, outbuf = carry
        inp = jax.lax.dynamic_index_in_dim(
            x_all, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        cur = jnp.where(idx == 0, inp, state)
        y = stage_fn(params_local, cur)
        nxt = jax.lax.ppermute(y, pp_axis, perm)
        slot = t - (pp - 1)
        write = (idx == pp - 1) & (slot >= 0)
        upd = jax.lax.dynamic_update_index_in_dim(
            outbuf, y.astype(outbuf.dtype), jnp.maximum(slot, 0), axis=0)
        outbuf = jnp.where(write, upd, outbuf)
        return (nxt, outbuf), None

    state0 = jnp.zeros(mb_shape, x_all.dtype)
    out0 = jnp.zeros((M,) + mb_shape, x_all.dtype)
    (_, outbuf), _ = jax.lax.scan(tick, (state0, out0),
                                  jnp.arange(M + pp - 1))
    # every stage but the last holds zeros; psum replicates the result
    return jax.lax.psum(outbuf, pp_axis)


def pipeline_apply(params_stacked, x_microbatched, stage_fn: Callable,
                   mesh: Mesh, pp_axis: str = "pp"):
    """Run ``x`` (M, mb, ...) through pp stages of ``stage_fn``.

    ``params_stacked``: tree whose leaves have a leading (pp,) stage axis,
    sharded over ``pp_axis`` (see :func:`stage_shardings`).
    ``stage_fn(stage_params, x_mb) -> y_mb`` must preserve the microbatch
    shape (inter-stage hops reuse one buffer).
    """
    n_stages = mesh.shape[pp_axis]
    leading = {int(jnp.shape(l)[0])
               for l in jax.tree_util.tree_leaves(params_stacked)}
    assert leading == {n_stages}, \
        f"stage axis {leading} != mesh pp={n_stages}"
    body = partial(_pipeline_body, stage_fn=stage_fn, pp_axis=pp_axis)
    pspec = jax.tree_util.tree_map(
        lambda l: _stage_spec(l, pp_axis), params_stacked)
    from .mesh import get_shard_map

    # per-stage control flow (stage-id branches) is not replication-safe,
    # so the vma/rep check is disabled
    shard_map, uncheck = get_shard_map()
    return shard_map(body, mesh=mesh, in_specs=(pspec, P()),
                     out_specs=P(), **uncheck)(
        params_stacked, x_microbatched)
