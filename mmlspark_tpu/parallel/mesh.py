"""Device topology & mesh utilities.

Replaces the reference's cluster-topology discovery + GPU pinning:
``ClusterUtil`` (``core/utils/ClusterUtil.scala:20-126``) and
``ONNXModel.selectGpuDevice`` (``deep-learning/.../onnx/ONNXModel.scala:293-303``).
On TPU the unit of scheduling is the chip within a ``jax.sharding.Mesh``;
partitions of a DataFrame are pinned round-robin to local chips for
embarrassingly-parallel inference, while training shards one global batch
over the mesh with XLA collectives riding ICI.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["local_devices", "device_for_partition", "make_mesh",
           "batch_placement", "feed_placement", "Placement",
           "data_parallel_sharding", "replicated_sharding",
           "MeshContext", "get_default_mesh", "set_default_mesh",
           "mesh_shape"]


def local_devices():
    """Process-local devices, degrading instead of crashing.

    Backend init can fail transiently (e.g. the TPU plugin is briefly
    unavailable); the reference's device pinning is best-effort too
    (``ONNXModel.scala:293-303`` falls through when no GPU resource is
    present). Order: default backend → explicit CPU backend → [].
    """
    try:
        return jax.local_devices()
    except Exception:
        pass
    try:
        return jax.devices("cpu")
    except Exception:
        return []


def device_for_partition(partition_index: int):
    """Pin a data partition to a process-local chip, round-robin.

    TPU-native stand-in for ``TaskContext.resources("gpu")`` pinning
    (``ONNXModel.scala:293-303``). Returns ``None`` (= default placement)
    when no backend is reachable, so callers degrade rather than crash.
    """
    devs = local_devices()
    if not devs:
        return None
    return devs[partition_index % len(devs)]


class Placement(NamedTuple):
    """Where one partition's device feeds go, as one resolved policy.

    ``mesh`` is set for SPMD dispatch (``device`` None), ``device`` for
    chip-pinned dispatch (``mesh`` None), both None for default placement.
    ``shards`` is the multiple the batch's leading dim must pad to; ``put``
    places a host array accordingly. ``key`` is hashable and identifies the
    placement for caching — params caches and warm-up bookkeeping key on it,
    so "warmed for this placement" and "params live on this placement" can
    never disagree about identity.
    """

    mesh: Optional[Mesh]
    device: Optional[object]
    shards: int
    put: object
    key: tuple


def feed_placement(use_mesh: bool, partition_index: int,
                   pin_devices: bool) -> Placement:
    """Resolve where a graph runner's host batches go — the one dispatch
    policy shared by ONNXModel and JaxModel.

    When ``use_mesh`` and a default mesh is installed, batches shard their
    leading axis over the mesh's first axis. Otherwise round-robin chip
    pinning (or default placement), with ``shards == 1``.
    """
    if use_mesh:
        mesh = get_default_mesh()
        if mesh is not None:
            sh = NamedSharding(mesh, P(mesh.axis_names[0]))
            return Placement(mesh, None,
                             int(mesh.shape[mesh.axis_names[0]]),
                             lambda a, _s=sh: jax.device_put(a, _s),
                             ("mesh", mesh))
    device = device_for_partition(partition_index) if pin_devices else None
    if device is not None:
        return Placement(None, device, 1,
                         lambda a, _d=device: jax.device_put(a, _d),
                         ("device", id(device)))
    return Placement(None, None, 1, jax.device_put, ("default",))


def batch_placement(use_mesh: bool, partition_index: int, pin_devices: bool):
    """Back-compat 4-tuple view of :func:`feed_placement`."""
    p = feed_placement(use_mesh, partition_index, pin_devices)
    return p.mesh, p.device, p.shards, p.put


def make_mesh(axis_shapes: Optional[dict] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from {axis_name: size}; -1 means "all remaining devices".

    Default: 1-D data-parallel mesh over every visible device.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not axis_shapes:
        axis_shapes = {"data": len(devices)}
    names, sizes = list(axis_shapes.keys()), list(axis_shapes.values())
    n = len(devices)
    known = int(np.prod([s for s in sizes if s != -1]))
    sizes = [s if s != -1 else max(1, n // known) for s in sizes]
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {n}")
    # tpulint: disable=TPU004 — object array of Device handles, not numerics
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_parallel_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_shape(mesh: Optional[Mesh]) -> str:
    """Canonical string for a mesh's axis layout, e.g. ``"dp4xtp2"``.

    ``"single"`` when ``mesh`` is None. Used to stamp tuning observations
    and decisions so ladders learned on one chip topology are never
    transferred onto another (a dp4xtp2 engine and a single-chip engine
    have different per-tick cost surfaces even at identical batch shapes).
    """
    if mesh is None:
        return "single"
    return "x".join(f"{name}{int(mesh.shape[name])}"
                    for name in mesh.axis_names)


_default_mesh: Optional[Mesh] = None


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _default_mesh


class MeshContext:
    """``with MeshContext({'data': -1}):`` installs a default mesh for stages."""

    def __init__(self, axis_shapes: Optional[dict] = None,
                 devices: Optional[Sequence] = None):
        self.mesh = make_mesh(axis_shapes, devices)
        self._prev: Optional[Mesh] = None

    def __enter__(self) -> Mesh:
        self._prev = get_default_mesh()
        set_default_mesh(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        set_default_mesh(self._prev)
        return False


def get_shard_map():
    """The supported shard_map entry point across jax versions (new
    ``jax.shard_map`` with ``check_vma``, else the experimental one with
    ``check_rep``). Returns (shard_map_fn, uncheck_kwargs) where
    ``uncheck_kwargs`` disables the replication/vma check for bodies with
    per-shard control flow."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, {"check_vma": False}
    from jax.experimental.shard_map import shard_map as legacy
    return legacy, {"check_rep": False}
