"""Long-context attention: ring attention + Ulysses-style all-to-all.

The reference never shards a sequence (SURVEY.md §5 "Long-context … absent");
its longest-document story is byte-bounded text chunking
(``featurize/text/PageSplitter.scala``). For a TPU framework long context is
a first-class design axis, so the mesh layer ships two sequence-parallel
attention schemes that mount on a ``Mesh`` axis (canonically ``sp``):

* :func:`ring_attention` — K/V blocks rotate around the ring via
  ``lax.ppermute`` while each chip keeps a flash-style streaming softmax
  (running max + normalizer), so no chip ever materializes the full S×S
  score matrix and the sequence scales with the number of chips. Comm rides
  ICI neighbor links — bandwidth-optimal for 1-D rings.
* :func:`ulysses_attention` — ``lax.all_to_all`` reshards (seq → heads)
  before attention and back after, trading one collective for fully local
  attention; better when heads ≫ ring hops.

Both are pure SPMD functions meant to be used inside ``shard_map``; see
``wrap_ring_attention`` for the canonical mounting.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention", "wrap_ring_attention",
           "local_attention", "attention_transient_bytes",
           "plan_attention_impl"]


def attention_transient_bytes(impl: str, direction: str, B: int, H: int,
                              S: int, sp: int = 1) -> int:
    """Dominant per-chip transient footprint (bytes) of an attention impl.

    The O(S²) score buffers — not the O(S·D) operands — decide whether a
    long-context config compiles at all, so this is the planning number.
    The model is calibrated against the r4/r5 on-chip campaigns, where it
    predicts every success/failure at 4k/16k/64k on a 16 GB v5e:

    * ``full`` fwd keeps ONE live f32 (B, H, S, S) score buffer (XLA fuses
      the softmax into the PV matmul); XLA-autodiff bwd keeps ~3 (saved
      probabilities + dS + the recompute).
    * ``ring`` (dense hops) materializes per-hop (S/sp, S/sp) scores in
      BOTH directions — the custom-VJP forward recompute re-runs the dense
      forward ring (:func:`_ring_vjp_fwd`), while the backward itself is
      blockwise O(S·block).
    * ``ulysses`` is ``full`` with H/sp heads over the full S.
    * ``flash`` / ``ring_flash`` stream: O(S·block) — returned as 0, they
      never hit the quadratic wall.

    ``direction`` is ``"fwd"`` or ``"bwd"``. The head dim does not appear:
    the O(S·D) operand/output buffers are negligible next to the scores at
    every planning-relevant scale.
    """
    if impl in ("flash", "ring_flash"):
        return 0
    bwd_factor = 1 if direction == "fwd" else 3
    if impl == "full":
        return 4 * B * H * S * S * bwd_factor
    if impl == "ring":
        s_local = S // sp
        return 4 * B * H * s_local * s_local  # vjp-fwd recompute dominates
    if impl == "ulysses":
        return 4 * B * max(H // sp, 1) * S * S * bwd_factor
    raise ValueError(f"unknown attention impl {impl!r}")


def plan_attention_impl(impl: str, direction: str, B: int, H: int, S: int,
                        sp: int = 1,
                        hbm_bytes: Optional[float] = None) -> dict:
    """Feasibility verdict for an attention impl on a given chip budget.

    Returns ``{"feasible": bool, "transient_bytes": int, "min_sp": ...}``.
    ``min_sp`` is the smallest sequence-parallel degree at which the impl
    fits (None when no sp helps: ``full`` never shards, and ulysses' bwd
    keeps full-S buffers once H/sp bottoms out). Infeasible configs fail
    at COMPILE time (XLA buffer assignment), which a remote-compile tunnel
    surfaces as an opaque HTTP 500 — callers should consult this planner
    first and route to flash/ring_flash instead.
    """
    if hbm_bytes is None:
        hbm_bytes = 16e9  # TPU v5e
    need = attention_transient_bytes(impl, direction, B, H, S, sp)
    feasible = need <= hbm_bytes
    min_sp = None
    if not feasible:
        for cand in (2, 4, 8, 16, 32, 64, 128):
            if impl == "ring" and S % cand:
                continue
            if impl == "ulysses" and H % cand:
                continue  # all_to_all splits the head axis exactly
            if attention_transient_bytes(
                    impl, direction, B, H, S, cand) <= hbm_bytes:
                min_sp = cand
                break
    return {"feasible": feasible, "transient_bytes": need, "min_sp": min_sp}


def local_attention(q, k, v, scale: Optional[float] = None):
    """Plain softmax attention, (B, H, S, D) layout, fp32 accumulation."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v, preferred_element_type=v.dtype)


def _ring_fwd_impl(q, k, v, axis_name: str, axis_size: int, scale: float,
                   use_flash: bool):
    """The forward ring: returns (o_normalized, L) where L = m + log(l) is
    the per-query GLOBAL logsumexp across every hop's keys — the residual
    the backward pass needs to re-normalize per-hop probabilities."""
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    # accumulators must carry the same "varying over axis_name" type as the
    # rotating K/V blocks for the fori_loop carry to typecheck under shard_map
    o = lax.pcast(jnp.zeros(q.shape, dtype=jnp.float32), (axis_name,), to='varying')
    m = lax.pcast(jnp.full(q.shape[:-1], -jnp.inf, dtype=jnp.float32),
                  (axis_name,), to='varying')
    l = lax.pcast(jnp.zeros(q.shape[:-1], dtype=jnp.float32), (axis_name,), to='varying')

    def hop_flash(o, m, l, k_cur, v_cur):
        from ..ops.flash_attention import flash_attention_with_stats
        o_i, l_i, m_i = flash_attention_with_stats(q, k_cur, v_cur,
                                                   scale=scale)
        m_new = jnp.maximum(m, m_i)
        c_prev = jnp.exp(m - m_new)
        c_i = jnp.exp(m_i - m_new)
        # o_i comes normalized by l_i; un-normalize inside the merge
        o = o * c_prev[..., None] + \
            o_i.astype(jnp.float32) * (l_i * c_i)[..., None]
        l = l * c_prev + l_i * c_i
        return o, m_new, l

    def hop_dense(o, m, l, k_cur, v_cur):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur,
                       preferred_element_type=jnp.float32) * scale
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return o, m_new, l

    hop = hop_flash if use_flash else hop_dense

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        o, m, l = hop(o, m, l, k_cur, v_cur)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_next, v_next

    o, m, l, _, _ = lax.fori_loop(0, axis_size, body, (o, m, l, k, v))
    return (o / l[..., None]).astype(q.dtype), m + jnp.log(l)


def _pick_block(S: int, cap: int = 1024) -> int:
    """Largest divisor of S not above cap (the bwd recompute block size)."""
    b = min(cap, S)
    while S % b:
        b -= 1
    return b


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring(q, k, v, axis_name, axis_size, scale, use_flash):
    return _ring_fwd_impl(q, k, v, axis_name, axis_size, scale, use_flash)[0]


def _ring_vjp_fwd(q, k, v, axis_name, axis_size, scale, use_flash):
    o, L = _ring_fwd_impl(q, k, v, axis_name, axis_size, scale, use_flash)
    return o, (q, k, v, o, L)


def _ring_vjp_bwd(axis_name, axis_size, scale, use_flash, res, do):
    """Ring backward: a SECOND ring pass. Per hop, the per-chip gradient
    contribution is recovered by the flash blockwise-recompute backward with
    the GLOBAL stats substituted (m ← L, l ← 1, so p = exp(s·scale − L) is
    already globally normalized); the dk/dv accumulators TRAVEL WITH their
    K/V blocks, so after ``axis_size`` hops every block arrives home
    carrying the sum of contributions from every query shard. This is the
    ring-attention paper's backward schedule — O(S_local·block) transients,
    never an S×S matrix."""
    from ..ops.flash_attention import _fa_reference_block_bwd

    q, k, v, o, L = res
    B, H, S, D = q.shape
    BH = B * H
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    # fp32 INPUTS to the hop backward: it casts its outputs back to the
    # input dtype, so bf16 inputs would quantize every hop's contribution
    # before the fp32 accumulation — growing error with ring size
    qf = q.reshape(BH, S, D).astype(jnp.float32)
    of = o.reshape(BH, S, D).astype(jnp.float32)
    dof = do.reshape(BH, S, D).astype(jnp.float32)
    Lf = L.reshape(BH, S)
    ones_l = jnp.ones((BH, S), jnp.float32)
    mask = jnp.ones((BH, S), jnp.int32)
    hop_bwd = jax.vmap(functools.partial(
        _fa_reference_block_bwd, causal=False, scale=scale,
        block_k=_pick_block(S)))

    var = lambda t: lax.pcast(t, (axis_name,), to='varying')
    dq0 = var(jnp.zeros((BH, S, D), jnp.float32))
    dk0 = var(jnp.zeros((BH, S, D), jnp.float32))
    dv0 = var(jnp.zeros((BH, S, D), jnp.float32))

    def body(i, carry):
        dq, dk_acc, dv_acc, k_cur, v_cur = carry
        # K/V rotate in their storage dtype (comm bandwidth); cast at use
        dqh, dkh, dvh = hop_bwd(
            qf, k_cur.reshape(BH, S, D).astype(jnp.float32),
            v_cur.reshape(BH, S, D).astype(jnp.float32), mask, of, ones_l,
            Lf, dof)
        dq = dq + dqh.astype(jnp.float32)
        dk_acc = dk_acc + dkh.astype(jnp.float32)
        dv_acc = dv_acc + dvh.astype(jnp.float32)
        # the accumulators rotate WITH the blocks they belong to
        rot = lambda t: lax.ppermute(t, axis_name, perm)
        return dq, rot(dk_acc), rot(dv_acc), rot(k_cur), rot(v_cur)

    dq, dk, dv, _, _ = lax.fori_loop(
        0, axis_size, body, (dq0, dk0, dv0, k, v))
    shape = (B, H, S, D)
    return (dq.reshape(shape).astype(q.dtype),
            dk.reshape(shape).astype(k.dtype),
            dv.reshape(shape).astype(v.dtype))


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(q, k, v, axis_name: str, axis_size: int,
                   scale: Optional[float] = None, use_flash: bool = False):
    """SPMD ring attention over a sequence-sharded axis.

    Args are local shards (B, H, S/n, D). Returns the local output shard.
    Streaming-softmax accumulators are fp32; K/V rotate ``axis_size`` hops.

    ``use_flash=True`` computes each hop's local attention with the Pallas
    streaming kernel and merges the per-hop ``(o, l, m)`` stats (log-sum-exp
    merge) — per-chip memory drops from O(S_local²) scores to O(S_local),
    which is the ring-attention paper's actual memory claim.

    Differentiable: a ring-level custom VJP runs a second ring pass whose
    per-hop gradients come from the flash blockwise recompute with global
    (L = m + log l) statistics, with dk/dv accumulators traveling alongside
    their K/V blocks. (Before this VJP, autodiff through the flash-inner
    merge produced silently WRONG gradients — the stats path had no VJP.)
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    return _ring(q, k, v, axis_name, axis_size, float(scale),
                 bool(use_flash))


def ulysses_attention(q, k, v, axis_name: str, axis_size: int,
                      scale: Optional[float] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern).

    Local shards are (B, H, S/n, D) with heads replicated; the all-to-all
    swaps to (B, H/n, S, D) — full sequence, a slice of heads — runs plain
    attention locally, and swaps back.
    """
    def scatter_heads(t):
        # (B, H, S/n, D) -> (B, H/n, S, D)
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def gather_heads(t):
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = local_attention(qh, kh, vh, scale)
    return gather_heads(out)


def wrap_ring_attention(mesh: Mesh, axis_name: str = "sp",
                        impl: str = "ring"):
    """Lift the SPMD kernel to global arrays via shard_map.

    Returns ``fn(q, k, v)`` over global (B, H, S, D) arrays sequence-sharded
    on ``axis_name``.
    """
    n = mesh.shape[axis_name]
    if impl not in ("ring", "ring_flash", "ulysses"):
        raise ValueError(f"unknown sequence-parallel impl {impl!r}")
    spec = P(None, None, axis_name, None)

    # the vma/replication check must be off for the ring impls: the
    # pallas_call inside ring_flash cannot declare its varying-axes type,
    # and the ring VJP's blockwise-recompute scan initializes its carry
    # unvarying (mesh.py:get_shard_map)
    from .mesh import get_shard_map
    shard_map, unchecked = get_shard_map()
    kwargs = unchecked if impl in ("ring", "ring_flash") else {}

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, **kwargs)
    def fn(q, k, v):
        if impl == "ulysses":
            return ulysses_attention(q, k, v, axis_name=axis_name,
                                     axis_size=n)
        return ring_attention(q, k, v, axis_name=axis_name, axis_size=n,
                              use_flash=(impl == "ring_flash"))

    return fn
