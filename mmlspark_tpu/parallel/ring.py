"""Long-context attention: ring attention + Ulysses-style all-to-all.

The reference never shards a sequence (SURVEY.md §5 "Long-context … absent");
its longest-document story is byte-bounded text chunking
(``featurize/text/PageSplitter.scala``). For a TPU framework long context is
a first-class design axis, so the mesh layer ships two sequence-parallel
attention schemes that mount on a ``Mesh`` axis (canonically ``sp``):

* :func:`ring_attention` — K/V blocks rotate around the ring via
  ``lax.ppermute`` while each chip keeps a flash-style streaming softmax
  (running max + normalizer), so no chip ever materializes the full S×S
  score matrix and the sequence scales with the number of chips. Comm rides
  ICI neighbor links — bandwidth-optimal for 1-D rings.
* :func:`ulysses_attention` — ``lax.all_to_all`` reshards (seq → heads)
  before attention and back after, trading one collective for fully local
  attention; better when heads ≫ ring hops.

Both are pure SPMD functions meant to be used inside ``shard_map``; see
``wrap_ring_attention`` for the canonical mounting.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention", "wrap_ring_attention",
           "local_attention"]


def local_attention(q, k, v, scale: Optional[float] = None):
    """Plain softmax attention, (B, H, S, D) layout, fp32 accumulation."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v, preferred_element_type=v.dtype)


def ring_attention(q, k, v, axis_name: str, axis_size: int,
                   scale: Optional[float] = None, use_flash: bool = False):
    """SPMD ring attention over a sequence-sharded axis.

    Args are local shards (B, H, S/n, D). Returns the local output shard.
    Streaming-softmax accumulators are fp32; K/V rotate ``axis_size`` hops.

    ``use_flash=True`` computes each hop's local attention with the Pallas
    streaming kernel and merges the per-hop ``(o, l, m)`` stats (log-sum-exp
    merge) — per-chip memory drops from O(S_local²) scores to O(S_local),
    which is the ring-attention paper's actual memory claim. Forward-only
    (the stats path has no VJP); the default einsum body stays for training.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    # accumulators must carry the same "varying over axis_name" type as the
    # rotating K/V blocks for the fori_loop carry to typecheck under shard_map
    o = lax.pcast(jnp.zeros(q.shape, dtype=jnp.float32), (axis_name,), to='varying')
    m = lax.pcast(jnp.full(q.shape[:-1], -jnp.inf, dtype=jnp.float32),
                  (axis_name,), to='varying')
    l = lax.pcast(jnp.zeros(q.shape[:-1], dtype=jnp.float32), (axis_name,), to='varying')

    def hop_flash(o, m, l, k_cur, v_cur):
        from ..ops.flash_attention import flash_attention_with_stats
        o_i, l_i, m_i = flash_attention_with_stats(q, k_cur, v_cur,
                                                   scale=scale)
        m_new = jnp.maximum(m, m_i)
        c_prev = jnp.exp(m - m_new)
        c_i = jnp.exp(m_i - m_new)
        # o_i comes normalized by l_i; un-normalize inside the merge
        o = o * c_prev[..., None] + \
            o_i.astype(jnp.float32) * (l_i * c_i)[..., None]
        l = l * c_prev + l_i * c_i
        return o, m_new, l

    def hop_dense(o, m, l, k_cur, v_cur):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur,
                       preferred_element_type=jnp.float32) * scale
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return o, m_new, l

    hop = hop_flash if use_flash else hop_dense

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        o, m, l = hop(o, m, l, k_cur, v_cur)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_next, v_next

    o, m, l, _, _ = lax.fori_loop(0, axis_size, body, (o, m, l, k, v))
    return (o / l[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, axis_size: int,
                      scale: Optional[float] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern).

    Local shards are (B, H, S/n, D) with heads replicated; the all-to-all
    swaps to (B, H/n, S, D) — full sequence, a slice of heads — runs plain
    attention locally, and swaps back.
    """
    def scatter_heads(t):
        # (B, H, S/n, D) -> (B, H/n, S, D)
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def gather_heads(t):
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = local_attention(qh, kh, vh, scale)
    return gather_heads(out)


def wrap_ring_attention(mesh: Mesh, axis_name: str = "sp",
                        impl: str = "ring"):
    """Lift the SPMD kernel to global arrays via shard_map.

    Returns ``fn(q, k, v)`` over global (B, H, S, D) arrays sequence-sharded
    on ``axis_name``.
    """
    n = mesh.shape[axis_name]
    if impl not in ("ring", "ring_flash", "ulysses"):
        raise ValueError(f"unknown sequence-parallel impl {impl!r}")
    spec = P(None, None, axis_name, None)

    # the pallas_call inside ring_flash cannot declare its varying-axes type,
    # so the vma check must be off for that impl (mesh.py:get_shard_map)
    from .mesh import get_shard_map
    shard_map, unchecked = get_shard_map()
    kwargs = unchecked if impl == "ring_flash" else {}

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, **kwargs)
    def fn(q, k, v):
        if impl == "ulysses":
            return ulysses_attention(q, k, v, axis_name=axis_name,
                                     axis_size=n)
        return ring_attention(q, k, v, axis_name=axis_name, axis_size=n,
                              use_flash=(impl == "ring_flash"))

    return fn
