from .mesh import (MeshContext, data_parallel_sharding, device_for_partition,
                   get_default_mesh, local_devices, make_mesh,
                   replicated_sharding, set_default_mesh)

__all__ = ["MeshContext", "make_mesh", "local_devices", "device_for_partition",
           "data_parallel_sharding", "replicated_sharding",
           "get_default_mesh", "set_default_mesh"]
