from .mesh import (MeshContext, data_parallel_sharding, device_for_partition,
                   get_default_mesh, get_shard_map, local_devices, make_mesh,
                   replicated_sharding, set_default_mesh)
from .moe import (init_moe_params, moe_capacity, moe_ffn_gspmd,
                  moe_ffn_local, moe_ffn_sharded, moe_shardings)
from .pipeline import pipeline_apply, stack_stage_params, stage_shardings

__all__ = ["MeshContext", "make_mesh", "local_devices", "device_for_partition",
           "data_parallel_sharding", "replicated_sharding",
           "get_default_mesh", "set_default_mesh", "get_shard_map",
           "init_moe_params", "moe_capacity", "moe_ffn_gspmd",
           "moe_ffn_local", "moe_ffn_sharded", "moe_shardings",
           "pipeline_apply", "stack_stage_params", "stage_shardings"]
