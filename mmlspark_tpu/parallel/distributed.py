"""Multi-host rendezvous & collectives backend.

Replaces the reference's three hand-rolled TCP mechanisms (SURVEY.md §5):
driver ServerSocket rendezvous (``LightGBMBase.scala:399-437``), LightGBM's
native socket ring (``TrainUtils.scala:280-296``), and VW's spanning-tree
AllReduce (``VowpalWabbitBase.scala:432-460``). On TPU all data-plane
collectives are XLA over ICI/DCN; the only thing left to bootstrap is world
membership, which ``jax.distributed.initialize`` handles given a coordinator
address. A tiny TCP rendezvous helper remains for launchers that have no
shared env (the moral successor of the driver-socket trick, but control-plane
only — it never carries tensor data).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Optional

import jax

__all__ = ["initialize", "is_initialized", "world_info",
           "coordinator_rendezvous", "find_open_port"]

_initialized = False


def find_open_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the JAX distributed world (idempotent).

    Resolution order: explicit args → ``MMLSPARK_TPU_COORDINATOR`` env →
    single-process fallback (no-op).
    """
    global _initialized
    if _initialized:
        return
    addr = coordinator_address or os.environ.get("MMLSPARK_TPU_COORDINATOR")
    if addr is None:
        return  # single-process: jax.devices() is already the world
    nproc = num_processes if num_processes is not None else int(
        os.environ.get("MMLSPARK_TPU_NUM_PROCESSES", "1"))
    pid = process_id if process_id is not None else int(
        os.environ.get("MMLSPARK_TPU_PROCESS_ID", "0"))
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=nproc, process_id=pid)
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def world_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def coordinator_rendezvous(role: str, driver_host: str, driver_port: int,
                           num_workers: int, timeout_s: float = 120.0) -> str:
    """Control-plane rendezvous: workers learn the coordinator address.

    ``role='driver'`` hosts a listener that hands every connecting worker the
    coordinator address (its own host + a fresh port) and returns it;
    ``role='worker'`` connects and reads it. Mirrors the reference's text
    protocol of host:port exchange, but only to bootstrap
    ``jax.distributed`` — no training data ever crosses these sockets.
    """
    if role == "driver":
        coord_port = find_open_port()
        payload = json.dumps({"coordinator": f"{driver_host}:{coord_port}",
                              "num_workers": num_workers}).encode()
        # bind in the caller so an EADDRINUSE (port raced away between the
        # probe and here) surfaces to the driver instead of being swallowed
        # in a daemon thread while workers spin to timeout
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((driver_host, driver_port))
        srv.listen(num_workers)
        srv.settimeout(timeout_s)

        def serve():
            served = 0
            try:
                while served < num_workers:
                    conn, _ = srv.accept()
                    with conn:
                        conn.sendall(payload)
                    served += 1
            except OSError:
                pass  # timeout or close; workers report their own timeout
            finally:
                srv.close()

        # tpulint: disable=TPU025 — run-once bootstrap rendezvous: serves
        # exactly num_workers payloads then exits; OSError containment
        # around the loop is the intended single-shot cleanup, and a
        # restart would re-listen on a closed socket
        threading.Thread(target=serve, daemon=True).start()
        return f"{driver_host}:{coord_port}"
    # worker
    deadline = time.monotonic() + timeout_s
    last_err: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((driver_host, driver_port),
                                          timeout=5) as s:
                data = s.recv(4096)
            return json.loads(data.decode())["coordinator"]
        except OSError as e:
            last_err = e
            time.sleep(0.25)
    raise TimeoutError(f"rendezvous with {driver_host}:{driver_port} timed "
                       f"out after {timeout_s}s") from last_err
