"""Pallas TPU kernels for the GBDT hot loop.

The reference's histogram build lives inside LightGBM's C++
(`LGBM_BoosterUpdateOneIter`, reached from
``lightgbm/.../booster/LightGBMBooster.scala:351-361``) — a hand-tuned
scatter-add over (node, feature, bin). The XLA fallback here is
``segment_sum`` (see ``models/gbdt/trees.py``); this module provides a
hand-written Pallas equivalent that reformulates the scatter as a
one-hot × data matmul so the accumulation rides the MXU instead of a
serialized scatter unit:

    for each (feature, row-block) grid step:
        onehot[b, r] = 1 if bin(row r, feature) == b          (VPU compare)
        for node in nodes:                                     (unrolled)
            hist[node] += (data * node_mask) @ onehot^T        (MXU matmul)

The (3, nodes*bins) accumulator stays resident in VMEM across the row-block
grid dimension, so HBM traffic is one read of the bins plus one write of the
final histogram — the minimum possible.

Selection: ``histogram_enabled()`` — env ``MMLSPARK_TPU_PALLAS`` = ``1``
(force on, interpreted off-TPU), ``0`` (off), default ``auto`` (on when the
default backend is TPU).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

__all__ = ["level_histogram_pallas", "histogram_enabled", "pallas_preferred"]

_LANE = 128


def histogram_enabled() -> bool:
    flag = os.environ.get("MMLSPARK_TPU_PALLAS", "auto").lower()
    if flag in ("1", "true", "on"):
        return True
    if flag in ("0", "false", "off"):
        return False
    return jax.default_backend() == "tpu"


def pallas_preferred(n_rows: int, n_nodes: int, n_bins: int,
                     combined_limit: int = 6 * 1024 * 1024) -> bool:
    """Per-level builder choice, from v5e measurements (1M×28×255 bins):
    Pallas 231 ms vs segment_sum 488 ms at 8 nodes, but 922 vs 488 at 32 —
    the kernel is fast exactly while its autotuned row_block stays large
    enough to keep the single fused MXU matmul busy (≥256 rows/step).
    segment_sum, meanwhile, stops compiling at all somewhere between 1M and
    4M rows (a 57 GB one-hot temp), so above that Pallas is the only
    builder regardless of depth. ``MMLSPARK_TPU_PALLAS=1`` forces the
    kernel everywhere (tests use this to exercise it)."""
    if os.environ.get("MMLSPARK_TPU_PALLAS", "auto").lower() in ("1", "true",
                                                                 "on"):
        return True
    if n_rows > 1_500_000:
        return True
    return _fused_row_block(n_nodes, n_bins, combined_limit) >= 256


def _fused_row_block(n_nodes: int, n_bins: int, combined_limit: int) -> int:
    """Largest lane-aligned row block whose fused (node·bin) one-hot stays
    inside the VMEM budget — shared by the kernel's autotune and the
    builder-choice heuristic so they cannot drift apart."""
    bpad = _round_up(max(n_bins, _LANE), _LANE)
    fused_max = combined_limit // (n_nodes * bpad * 4)
    return max(_LANE, min(512, (fused_max // _LANE) * _LANE))


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _hist_kernel(bins_ref, node_ref, data_ref, out_ref, *, n_nodes, bpad,
                 combined_limit):
    """One (feature, row-block) grid step. Shapes:
    bins_ref (1, 1, R) int32 | node_ref (1, R) int32 | data_ref (3, R) f32
    out_ref (1, 3, n_nodes*bpad) f32 — resident across the row-block dim.
    """
    from jax.experimental import pallas as pl

    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    b = bins_ref[0, 0, :]                                # (R,)
    node = node_ref[0, :]                                # (R,)
    data = data_ref[...]                                 # (3, R)
    R = b.shape[0]
    combined_bytes = n_nodes * bpad * R * 4
    if combined_bytes <= combined_limit:
        # one-hot over the fused (node, bin) id → ONE big MXU matmul
        seg = node * bpad + b                            # (R,)
        iota = jax.lax.broadcasted_iota(jnp.int32, (n_nodes * bpad, R), 0)
        onehot = (iota == seg[None, :]).astype(jnp.float32)
        out_ref[0, :, :] += jnp.dot(
            data, onehot.T, precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)          # (3, nodes*bpad)
    else:
        # deep levels: per-node masked matmul keeps VMEM bounded
        iota = jax.lax.broadcasted_iota(jnp.int32, (bpad, R), 0)
        onehot = (iota == b[None, :]).astype(jnp.float32)    # (bpad, R)
        for nd in range(n_nodes):                        # static unroll
            mask = (node == nd).astype(jnp.float32)      # (R,)
            md = data * mask[None, :]                    # (3, R)
            contrib = jnp.dot(md, onehot.T,
                              precision=jax.lax.Precision.HIGHEST,
                              preferred_element_type=jnp.float32)  # (3, bpad)
            sl = pl.ds(nd * bpad, bpad)
            out_ref[0, :, sl] += contrib


def level_histogram_pallas(xb, node_rel, g, h, w_count, n_nodes: int,
                           n_bins: int, row_block: int = 0,
                           interpret: bool = False,
                           combined_limit: int = 6 * 1024 * 1024):
    """Drop-in for the segment-sum histogram: returns (n_nodes, F, B, 3).

    xb (n, F) int bins; node_rel (n,) int32; g/h/w_count (n,) float32.
    ``row_block=0`` picks the largest block that keeps the fused
    single-matmul path inside the VMEM budget (the per-node unrolled
    fallback is ~MXU-starved once n_nodes grows).
    """
    if row_block == 0:
        row_block = _fused_row_block(n_nodes, n_bins, combined_limit)
    return _level_histogram_pallas(xb, node_rel, g, h, w_count,
                                   n_nodes=n_nodes, n_bins=n_bins,
                                   row_block=row_block, interpret=interpret,
                                   combined_limit=combined_limit)


@functools.partial(jax.jit,
                   static_argnames=("n_nodes", "n_bins", "row_block",
                                    "interpret", "combined_limit"))
def _level_histogram_pallas(xb, node_rel, g, h, w_count, n_nodes: int,
                            n_bins: int, row_block: int,
                            interpret: bool,
                            combined_limit: int):
    from jax.experimental import pallas as pl

    n, F = xb.shape
    bpad = _round_up(max(n_bins, _LANE), _LANE)
    npad = _round_up(max(n, row_block), row_block)
    pad = npad - n

    # (F, 1, npad): the singleton keeps the block's last-two dims legal
    # ((1, R) with 1 == full dim) for the TPU lowering's tiling rules
    xb_t = jnp.pad(xb.astype(jnp.int32).T, ((0, 0), (0, pad)))[:, None, :]
    node = jnp.pad(node_rel.astype(jnp.int32), (0, pad))[None, :]   # (1, npad)
    data = jnp.stack([g, h, w_count]).astype(jnp.float32)           # (3, n)
    data = jnp.pad(data, ((0, 0), (0, pad)))                        # zeros kill
    # padded rows' contributions regardless of their (0) bin/node ids

    nblocks = npad // row_block
    kernel = functools.partial(_hist_kernel, n_nodes=n_nodes, bpad=bpad,
                               combined_limit=combined_limit)
    out = pl.pallas_call(
        kernel,
        grid=(F, nblocks),
        in_specs=[
            pl.BlockSpec((1, 1, row_block), lambda f, r: (f, 0, r)),
            pl.BlockSpec((1, row_block), lambda f, r: (0, r)),
            pl.BlockSpec((3, row_block), lambda f, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((1, 3, n_nodes * bpad), lambda f, r: (f, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 3, n_nodes * bpad), jnp.float32),
        interpret=interpret,
    )(xb_t, node, data)

    hist = out.reshape(F, 3, n_nodes, bpad)[:, :, :, :n_bins]
    return jnp.transpose(hist, (2, 0, 3, 1))            # (nodes, F, B, 3)
