"""Pallas TPU kernels for the GBDT hot loop.

The reference's histogram build lives inside LightGBM's C++
(`LGBM_BoosterUpdateOneIter`, reached from
``lightgbm/.../booster/LightGBMBooster.scala:351-361``) — a hand-tuned
scatter-add over (node, feature, bin). The XLA fallback here is
``segment_sum`` (see ``models/gbdt/trees.py``); this module provides a
hand-written Pallas equivalent that reformulates the scatter as a
one-hot matmul so the accumulation rides the MXU instead of a serialized
scatter unit.

Layout (v2, "stats-as-lanes"): for each (feature, row-block) grid step

    onehot[b, r]   = 1 if bin(row r, feature) == b        (bpad, R)  VPU
    dn[r, s*N + d] = stat_s(row r) if node(row r) == d    (R, 3*N)   VPU
    hist[feature] += onehot @ dn                          (bpad, 3*N) MXU

The first version put the 3 stats on the matmul's M dimension
(``(3, R) @ (R, nodes*bpad)``), which capped MXU utilization at 3/128
(~2.3%) and made both FLOPs and the VMEM-resident one-hot grow linearly
with the node count — measured 231 ms at 8 nodes but 922 ms at 32
(1M×28×255 on v5e) vs segment_sum's flat 488 ms. Putting bins on M and
(stat, node) on the lane dimension instead makes utilization GROW with
depth (3·nodes lanes: 9% at 4 nodes, 75% at 32, saturated from 43), and
the in-kernel one-hot is (bpad, R) — independent of node count — so the
row block no longer collapses at depth.

The (bpad, 3·nodes) accumulator stays resident in VMEM across the
row-block grid dimension, so HBM traffic is one read of the bins plus one
write of the final histogram — the minimum possible.

Selection: ``histogram_enabled()`` — env ``MMLSPARK_TPU_PALLAS`` = ``1``
(force on, interpreted off-TPU), ``0`` (off), default ``auto`` (on when the
default backend is TPU).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

__all__ = ["level_histogram_pallas", "histogram_enabled", "pallas_preferred",
           "prepare_bins_lanes", "tree_row_block", "DEFAULT_ROW_BLOCK"]

DEFAULT_ROW_BLOCK = 2048

_LANE = 128


def histogram_enabled() -> bool:
    flag = os.environ.get("MMLSPARK_TPU_PALLAS", "auto").lower()
    if flag in ("1", "true", "on"):
        return True
    if flag in ("0", "false", "off"):
        return False
    from ..utils.device import is_tpu
    return is_tpu()


def pallas_preferred(n_rows: int, n_nodes: int, n_bins: int) -> bool:
    """Per-level builder choice.

    The v2 kernel's per-level cost is ~flat in node count until 3·nodes
    fills the 128-lane dimension (43 nodes) and linear after; segment_sum
    is flat in node count but pays a serialized scatter (488 ms at
    1M×28×255 on v5e, every level). The cost model puts the crossover far
    past any practical tree depth, so the kernel is preferred up to 256
    nodes/level (= num_leaves 512, leaf-wise); segment_sum additionally
    stops compiling at all somewhere between 1M and 4M rows (a 57 GB
    one-hot temp), so above that the kernel is the only builder
    regardless of depth. ``MMLSPARK_TPU_PALLAS=1`` forces the kernel
    everywhere (tests use this to exercise it)."""
    if os.environ.get("MMLSPARK_TPU_PALLAS", "auto").lower() in ("1", "true",
                                                                 "on"):
        return True
    if n_rows > 1_500_000:
        return True
    # n_bins kept for call-site stability: both builders scale the same way
    # with bin count, so the v2 decision depends only on the node count
    return n_nodes <= 256


def _auto_row_block(n_nodes: int, n_bins: int, vmem_limit: int) -> int:
    """Largest lane-aligned row block whose in-kernel intermediates — the
    (bpad, R) bin one-hot and the (R, 3·nodes) scattered stats (lanes
    padded to the 128 hardware lanes) — fit the VMEM budget."""
    bpad = _round_up(max(n_bins, _LANE), _LANE)
    lanes = _round_up(3 * n_nodes, _LANE)
    per_row = (bpad + lanes) * 4
    return max(_LANE, min(2048, (vmem_limit // per_row // _LANE) * _LANE))


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def tree_row_block(max_nodes: int, n_bins: int,
                   combined_limit: int = 6 * 1024 * 1024) -> int:
    """One row block for a whole tree: sized for the DEEPEST level's node
    count so every level's in-kernel intermediates respect the VMEM budget
    (a fixed 2048 block would blow past it from ~256 nodes/level up).
    Callers pass the same value to ``prepare_bins_lanes`` and every
    ``level_histogram_pallas`` call of that tree."""
    return _auto_row_block(max_nodes, n_bins, combined_limit)


@functools.partial(jax.jit, static_argnames=("row_block",))
def prepare_bins_lanes(xb, row_block: int = DEFAULT_ROW_BLOCK):
    """One-time (F, 1, npad) int32 lane layout for the histogram kernel.

    The kernel wants bins feature-major with rows on lanes; doing this
    transpose+pad per level cost a full read+write of the bin matrix per
    level — at HIGGS-11M that is ~1.2 GB of HBM traffic × levels × trees.
    Callers prepare once per training run and pass ``bins_lanes`` down.
    """
    n = xb.shape[0]
    npad = _round_up(max(n, row_block), row_block)
    return jnp.pad(xb.astype(jnp.int32).T, ((0, 0), (0, npad - n)))[:, None, :]


def _hist_kernel(bins_ref, node_ref, data_ref, out_ref, *, n_nodes, bpad,
                 use_bf16):
    """One (feature, row-block) grid step. Shapes:
    bins_ref (1, 1, R) int32 | node_ref (1, R) int32 | data_ref (3, R) f32
    out_ref (1, bpad, 3*n_nodes) f32 — resident across the row-block dim,
    lane col = stat*n_nodes + node (stats-major).
    """
    from jax.experimental import pallas as pl

    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    b = bins_ref[0, 0, :]                                # (R,)
    node = node_ref[0, :]                                # (R,)
    data = data_ref[...]                                 # (3, R) f32
    R = b.shape[0]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (bpad, R), 0)
    # dn[r, st*n_nodes + nd] = data[st, r] * (node[r] == nd): built with 2-D
    # iota arithmetic in f32 (no 3-D intermediate / minor-dim reshape for
    # Mosaic; 16-bit minor-dim insertion is unsupported, so bf16 happens
    # only at the final cast below)
    c = jax.lax.broadcasted_iota(jnp.int32, (R, 3 * n_nodes), 1)
    st, nd = c // n_nodes, c % n_nodes
    sel = jnp.where(st == 0, data[0, :][:, None],
                    jnp.where(st == 1, data[1, :][:, None],
                              data[2, :][:, None]))
    dn = jnp.where(nd == node[:, None], sel, 0.0)        # (R, 3*n_nodes)
    if use_bf16:
        # bf16 operands ride the MXU at native rate; accumulation stays
        # f32 via preferred_element_type (the one-hot is exact in bf16)
        onehot = (iota_b == b[None, :]).astype(jnp.bfloat16)
        dn = dn.astype(jnp.bfloat16)
        prec = jax.lax.Precision.DEFAULT
    else:
        onehot = (iota_b == b[None, :]).astype(jnp.float32)
        prec = jax.lax.Precision.HIGHEST
    out_ref[0, :, :] += jnp.dot(onehot, dn, precision=prec,
                                preferred_element_type=jnp.float32)


def level_histogram_pallas(xb, node_rel, g, h, w_count, n_nodes: int,
                           n_bins: int, row_block: int = 0,
                           interpret: bool = False,
                           combined_limit: int = 6 * 1024 * 1024,
                           bins_lanes=None, stats_dtype=None):
    """Drop-in for the segment-sum histogram: returns (n_nodes, F, B, 3).

    xb (n, F) int bins; node_rel (n,) int32; g/h/w_count (n,) float32.
    ``row_block=0`` picks the largest block whose intermediates fit the
    ``combined_limit`` VMEM budget. ``bins_lanes`` (from
    ``prepare_bins_lanes``) supplies the kernel's (F, 1, npad) layout
    precomputed once per run, skipping a per-level transpose of the whole
    bin matrix; it must have been built with the same ``row_block``
    (callers pass ``DEFAULT_ROW_BLOCK`` for both). ``stats_dtype``
    ``jnp.bfloat16`` runs the one-hot matmul at native MXU rate
    (accumulation stays f32) — LightGBM's quantized-gradient analog.
    """
    if bins_lanes is not None:
        row_block = row_block or DEFAULT_ROW_BLOCK
        if bins_lanes.shape[2] % row_block:
            raise ValueError(
                f"bins_lanes npad {bins_lanes.shape[2]} is not a multiple "
                f"of row_block {row_block}")
    elif row_block == 0:
        row_block = _auto_row_block(n_nodes, n_bins, combined_limit)
    return _level_histogram_pallas(xb, node_rel, g, h, w_count, bins_lanes,
                                   n_nodes=n_nodes, n_bins=n_bins,
                                   row_block=row_block, interpret=interpret,
                                   stats_dtype=(jnp.dtype(stats_dtype).name
                                                if stats_dtype else None))


@functools.partial(jax.jit,
                   static_argnames=("n_nodes", "n_bins", "row_block",
                                    "interpret", "stats_dtype"))
def _level_histogram_pallas(xb, node_rel, g, h, w_count, bins_lanes,
                            n_nodes: int, n_bins: int, row_block: int,
                            interpret: bool, stats_dtype):
    from jax.experimental import pallas as pl

    n, F = xb.shape
    bpad = _round_up(max(n_bins, _LANE), _LANE)
    if bins_lanes is not None:
        npad = bins_lanes.shape[2]
        xb_t = bins_lanes
    else:
        npad = _round_up(max(n, row_block), row_block)
        # (F, 1, npad): the singleton keeps the block's last-two dims legal
        # ((1, R) with 1 == full dim) for the TPU lowering's tiling rules
        xb_t = jnp.pad(xb.astype(jnp.int32).T,
                       ((0, 0), (0, npad - n)))[:, None, :]
    pad = npad - n
    use_bf16 = stats_dtype == "bfloat16"
    node = jnp.pad(node_rel.astype(jnp.int32), (0, pad))[None, :]   # (1, npad)
    # bf16 stats round HERE (outside the kernel) so the quantization is
    # well-defined; the kernel re-reads them as f32 refs and casts at the
    # dot (Mosaic can't insert minor dims on 16-bit vectors)
    data = jnp.stack([g, h, w_count]).astype(jnp.float32)           # (3, n)
    if use_bf16:
        data = data.astype(jnp.bfloat16).astype(jnp.float32)
    data = jnp.pad(data, ((0, 0), (0, pad)))                        # zeros kill
    # padded rows' contributions regardless of their (0) bin/node ids

    nblocks = npad // row_block
    kernel = functools.partial(_hist_kernel, n_nodes=n_nodes, bpad=bpad,
                               use_bf16=use_bf16)
    out = pl.pallas_call(
        kernel,
        grid=(F, nblocks),
        in_specs=[
            pl.BlockSpec((1, 1, row_block), lambda f, r: (f, 0, r)),
            pl.BlockSpec((1, row_block), lambda f, r: (0, r)),
            pl.BlockSpec((3, row_block), lambda f, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((1, bpad, 3 * n_nodes), lambda f, r: (f, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, bpad, 3 * n_nodes), jnp.float32),
        interpret=interpret,
    )(xb_t, node, data)

    # (F, bpad, 3, n_nodes) -> (n_nodes, F, n_bins, 3)
    hist = out.reshape(F, bpad, 3, n_nodes)[:, :n_bins]
    return jnp.transpose(hist, (3, 0, 1, 2))
