"""Flash attention — Pallas TPU kernel with online softmax.

The reference never shards or tiles attention (its largest models run whole
through ONNX sessions; SURVEY.md §5 "long-context: absent"), so this module
is pure beyond-parity TPU work: the standard attention in
``models/zoo/transformer.py`` and ``parallel/ring.py:36`` materializes the
full ``(B, H, S, S)`` score matrix in HBM — O(S²) memory and two extra HBM
round-trips. This kernel streams K/V blocks through VMEM keeping running
max/denominator accumulators (the FlashAttention recurrence), so HBM traffic
is one read of Q/K/V plus one write of O, and the score block lives only in
VMEM where the MXU consumes it.

Design notes (TPU-first):

* grid = (B*H, S/block_q, S/block_k) with the K dimension innermost; the
  output block index ignores the K step, so Pallas keeps O resident in VMEM
  across the whole K sweep and writes it back once.
* running ``m``/``l`` live in VMEM scratch shaped ``(block_q, LANE)`` —
  scalars-per-row are replicated across the 128-lane axis, the natural VPU
  layout (a ``(block_q, 1)`` buffer would fight the tiling rules).
* masked logits use a large-negative constant, not ``-inf``: with ``-inf``
  a fully-masked row makes ``exp(m - m)`` produce NaN; with ``-1e30`` the
  row cleanly yields ``l == 0`` and the final divide guards it to 0.
* the backward pass is two Pallas kernels (dK/dV sweeping Q-blocks, dQ
  sweeping K-blocks) that recompute probabilities blockwise from the saved
  ``(m, l)`` statistics with VMEM-resident accumulators; set
  ``MMLSPARK_TPU_FLASH_BWD=xla`` (read once at import) to fall back to an
  equivalent ``lax.scan`` recompute.

For sharded use inside a dp×tp jit (where a bare ``pallas_call`` would make
GSPMD gather the operands onto one device) use
:func:`flash_attention_sharded`, which mounts the kernel per-shard via
``shard_map`` — attention is batch- and head-local, so no collectives are
needed.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from .pallas_kernels import _LANE, _round_up

__all__ = ["flash_attention", "flash_attention_sharded",
           "flash_attention_with_stats"]

_NEG = -1e30
#: backward implementation, resolved ONCE at import (the choice is traced
#: into the jit cache, so later env changes could not take effect anyway)
_BWD_IMPL = ("xla" if os.environ.get("MMLSPARK_TPU_FLASH_BWD", "pallas")
             .strip().lower() in ("xla", "reference") else "pallas")


def _auto_interpret() -> bool:
    from ..utils.device import is_tpu
    return not is_tpu()


def _fa_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *rest, scale, causal,
               block_q, block_k, n_k, with_stats):
    """One (bh, iq, ik) grid step of the streaming-softmax recurrence."""
    from jax.experimental import pallas as pl

    if with_stats:
        l_ref, m_ref, macc_ref, lacc_ref, acc_ref = rest
    else:
        macc_ref, lacc_ref, acc_ref = rest

    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        macc_ref[...] = jnp.full_like(macc_ref, _NEG)
        lacc_ref[...] = jnp.zeros_like(lacc_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute():
        q = q_ref[0].astype(jnp.float32)                   # (bq, D)
        k = k_ref[0].astype(jnp.float32)                   # (bk, D)
        v = v_ref[0].astype(jnp.float32)                   # (bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # (bq, bk)

        valid = jnp.broadcast_to(mask_ref[0, 0][None, :] != 0,
                                 (block_q, block_k))
        if causal:
            row = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = jnp.logical_and(valid, row >= col)
        s = jnp.where(valid, s, _NEG)

        m_prev = macc_ref[:, 0:1]                          # (bq, 1)
        l_prev = lacc_ref[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # `valid` (not the _NEG sentinel) zeroes masked probabilities: for a
        # row with every key masked so far, m_new == _NEG and exp(s - m_new)
        # would be exp(0) == 1 on the masked entries.
        p = jnp.exp(s - m_new) * valid.astype(jnp.float32)  # (bq, bk)
        corr = jnp.exp(m_prev - m_new)                      # <= 1
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, D)
        acc_ref[...] = acc_ref[...] * corr + pv
        macc_ref[...] = jnp.broadcast_to(m_new, macc_ref.shape)
        lacc_ref[...] = jnp.broadcast_to(l_new, lacc_ref.shape)

    if causal:
        # blocks strictly above the diagonal band contribute nothing
        @pl.when(ik * block_k < (iq + 1) * block_q)
        def _():
            compute()
    else:
        compute()

    @pl.when(ik == n_k - 1)
    def _fin():
        l = lacc_ref[:, 0:1]
        o_ref[0] = (acc_ref[...] /
                    jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)
        if with_stats:
            # stats stay lane-replicated, (block_q, LANE) — a (1, block_q)
            # block would put 1 in the sublane slot, which Mosaic rejects
            # whenever BH > 1
            l_ref[0] = lacc_ref[...]
            m_ref[0] = macc_ref[...]


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "interpret", "heads",
    "with_stats"))
def _flash_fwd(q, k, v, kv_mask, *, causal, scale, block_q, block_k,
               interpret, heads, with_stats):
    """(BH, S, D) inputs (already padded) → o, or (o, l, m) with the softmax
    stats lane-replicated as (BH, S, LANE) when the VJP needs residuals."""
    from jax.experimental import pallas as pl

    BH, S, D = q.shape
    n_q, n_k = S // block_q, S // block_k
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, n_k=n_k,
                               with_stats=with_stats)
    out_specs = [pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((BH, S, D), q.dtype)]
    if with_stats:
        out_specs += [
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((BH, S, _LANE), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, _LANE), jnp.float32),
        ]
    outs = pl.pallas_call(
        kernel,
        grid=(BH, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            # (B, 1, S) with a (1, 1, block_k) block: the singleton in the
            # sublane slot equals the full dim, keeping Mosaic's tiling rule
            # satisfied for any B (a 2-D (1, block_k) block is rejected
            # whenever B > 1)
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // heads, 0, j)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            # pltpu scratch constructors; resolved lazily so interpret mode
            # keeps working on non-TPU backends
            _vmem((block_q, _LANE), jnp.float32),
            _vmem((block_q, _LANE), jnp.float32),
            _vmem((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, kv_mask[:, None, :])
    if with_stats:
        o, l, m = outs
        return o, l[:, :, 0], m[:, :, 0]
    return outs[0], None, None


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, dtype)


def _bwd_block_recompute(q_ref, do_ref, k_ref, v_ref, mask_ref, delta_ref,
                         m_ref, l_ref, i, j, *, scale, causal, block_q,
                         block_k):
    """Shared q-block×k-block recompute for both backward kernels:
    returns (q, do, k, p, ds) in fp32."""
    q = q_ref[0].astype(jnp.float32)                   # (bq, D)
    do = do_ref[0].astype(jnp.float32)                 # (bq, D)
    k = k_ref[0].astype(jnp.float32)                   # (bk, D)
    v = v_ref[0].astype(jnp.float32)                   # (bk, D)
    m = m_ref[0, 0][:, None]                           # (bq, 1)
    l = l_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]
    linv = jnp.where(l == 0.0, 0.0, 1.0 / l)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    valid = jnp.broadcast_to(mask_ref[0, 0][None, :] != 0,
                             (block_q, block_k))
    if causal:
        row = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        col = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = jnp.logical_and(valid, row >= col)
    p = jnp.exp(jnp.where(valid, s, _NEG) - m) * \
        valid.astype(jnp.float32) * linv                # (bq, bk)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    return q, do, k, p, ds


def _fa_bwd_dkv_kernel(q_ref, do_ref, k_ref, v_ref, mask_ref, delta_ref,
                       m_ref, l_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                       scale, causal, block_q, block_k, n_q):
    """dK/dV for one K-block: sweep Q-blocks, accumulators VMEM-resident.
    Grid (BH, n_k, n_q) — the Q sweep is innermost so dk/dv stay put."""
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute():
        q, do, _k, p, ds = _bwd_block_recompute(
            q_ref, do_ref, k_ref, v_ref, mask_ref, delta_ref, m_ref, l_ref,
            i, j, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, D)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when((i + 1) * block_q > j * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(i == n_q - 1)
    def _fin():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _fa_bwd_dq_kernel(q_ref, do_ref, k_ref, v_ref, mask_ref, delta_ref,
                      m_ref, l_ref, dq_ref, dq_acc, *, scale, causal,
                      block_q, block_k, n_k):
    """dQ for one Q-block: sweep K-blocks (innermost), accumulator resident."""
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def compute():
        _q, _do, k, _p, ds = _bwd_block_recompute(
            q_ref, do_ref, k_ref, v_ref, mask_ref, delta_ref, m_ref, l_ref,
            i, j, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(j * block_k < (i + 1) * block_q)
        def _():
            compute()
    else:
        compute()

    @pl.when(j == n_k - 1)
    def _fin():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "interpret", "heads"))
def _flash_bwd_pallas(q, k, v, kv_mask, o, l, m, do, *, causal, scale,
                      block_q, block_k, interpret, heads):
    """Pallas backward: (BH, S, D) padded operands → (dq, dk, dv)."""
    from jax.experimental import pallas as pl

    BH, S, D = q.shape
    n_q, n_k = S // block_q, S // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                 # (BH, S)
    # stats/delta ride as (BH, 1, S): a (1, 1, block) block keeps the
    # sublane slot equal to the full dim (Mosaic tiling; see _flash_fwd)
    m3, l3, d3 = m[:, None, :], l[:, None, :], delta[:, None, :]
    mask3 = kv_mask[:, None, :]

    qspec = pl.BlockSpec((1, block_q, D), lambda b, x, y: (b, y, 0))
    kspec_j = pl.BlockSpec((1, block_k, D), lambda b, x, y: (b, x, 0))
    row3 = lambda b, x, y: (b, 0, y)       # (BH,1,S) per-Q-block rows
    dkv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_q=n_q),
        grid=(BH, n_k, n_q),
        in_specs=[
            qspec,                                           # q by i (=y)
            qspec,                                           # do by i
            kspec_j,                                         # k by j (=x)
            kspec_j,                                         # v by j
            pl.BlockSpec((1, 1, block_k),
                         lambda b, x, y: (b // heads, 0, x)),  # mask by j
            pl.BlockSpec((1, 1, block_q), row3),             # delta by i
            pl.BlockSpec((1, 1, block_q), row3),             # m by i
            pl.BlockSpec((1, 1, block_q), row3),             # l by i
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, x, y: (b, x, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, x, y: (b, x, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, S, D), v.dtype)],
        scratch_shapes=[_vmem((block_k, D), jnp.float32),
                        _vmem((block_k, D), jnp.float32)],
        interpret=interpret,
    )(q, do, k, v, mask3, d3, m3, l3)

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_k=n_k),
        grid=(BH, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, x, y: (b, x, 0)),  # q by i
            pl.BlockSpec((1, block_q, D), lambda b, x, y: (b, x, 0)),  # do
            pl.BlockSpec((1, block_k, D), lambda b, x, y: (b, y, 0)),  # k by j
            pl.BlockSpec((1, block_k, D), lambda b, x, y: (b, y, 0)),  # v
            pl.BlockSpec((1, 1, block_k),
                         lambda b, x, y: (b // heads, 0, y)),          # mask
            pl.BlockSpec((1, 1, block_q), lambda b, x, y: (b, 0, x)),  # delta
            pl.BlockSpec((1, 1, block_q), lambda b, x, y: (b, 0, x)),  # m
            pl.BlockSpec((1, 1, block_q), lambda b, x, y: (b, 0, x)),  # l
        ],
        out_specs=[pl.BlockSpec((1, block_q, D), lambda b, x, y: (b, x, 0))],
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), q.dtype)],
        scratch_shapes=[_vmem((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, do, k, v, mask3, d3, m3, l3)[0]
    return dq, dkv[0], dkv[1]


def _fa_reference_block_bwd(q, k, v, mask, o, l, m, do, *, causal, scale,
                            block_k):
    """Memory-efficient backward for ONE (S, D) head: lax.scan over K blocks
    recomputing p from the saved (m, l) row statistics."""
    S, D = q.shape
    n_k = S // block_k
    linv = jnp.where(l == 0.0, 0.0, 1.0 / l)               # (S,)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                               # (S,)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    rows = jnp.arange(S)

    kb = k.reshape(n_k, block_k, D)
    vb = v.reshape(n_k, block_k, D)
    mb = mask.reshape(n_k, block_k)

    def body(dq, blk):
        j, kj, vj, mj = blk
        s = (qf @ kj.astype(jnp.float32).T) * scale        # (S, bk)
        valid = jnp.broadcast_to(mj[None, :] != 0, s.shape)
        if causal:
            col = j * block_k + jnp.arange(block_k)
            valid = jnp.logical_and(valid, rows[:, None] >= col[None, :])
        p = jnp.exp(jnp.where(valid, s, _NEG) - m[:, None]) * \
            valid.astype(jnp.float32) * linv[:, None]      # (S, bk)
        dp = dof @ vj.astype(jnp.float32).T                # (S, bk)
        ds = p * (dp - delta[:, None]) * scale
        dq = dq + ds @ kj.astype(jnp.float32)
        dkj = ds.T @ qf                                    # (bk, D)
        dvj = p.T @ dof
        return dq, (dkj, dvj)

    dq, (dk, dv) = jax.lax.scan(
        body, jnp.zeros((S, D), jnp.float32),
        (jnp.arange(n_k), kb, vb, mb))
    return (dq.astype(q.dtype), dk.reshape(S, D).astype(k.dtype),
            dv.reshape(S, D).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, kv_mask, causal, scale, block_q, block_k, interpret,
           heads):
    o, _, _ = _flash_fwd(q, k, v, kv_mask, causal=causal, scale=scale,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret, heads=heads, with_stats=False)
    return o


def _flash_vjp_fwd(q, k, v, kv_mask, causal, scale, block_q, block_k,
                   interpret, heads):
    o, l, m = _flash_fwd(q, k, v, kv_mask, causal=causal, scale=scale,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret, heads=heads, with_stats=True)
    return o, (q, k, v, kv_mask, o, l, m)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, heads,
                   res, do):
    q, k, v, kv_mask, o, l, m = res
    if _BWD_IMPL == "xla":
        # escape hatch: blockwise lax.scan recompute instead of the kernels
        mask_bh = jnp.repeat(kv_mask, heads, axis=0)       # (BH, S)
        bwd = functools.partial(_fa_reference_block_bwd, causal=causal,
                                scale=scale, block_k=block_k)
        dq, dk, dv = jax.vmap(bwd)(q, k, v, mask_bh, o, l, m, do)
        return dq, dk, dv, None
    dq, dk, dv = _flash_bwd_pallas(q, k, v, kv_mask, o, l, m, do,
                                   causal=causal, scale=scale,
                                   block_q=block_q, block_k=block_k,
                                   interpret=interpret, heads=heads)
    return dq, dk, dv, None


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    kv_mask: Optional[jnp.ndarray] = None,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 1024,
                    interpret: Optional[bool] = None):
    """Streaming-softmax attention, ``(B, H, S, D)`` layout.

    Differentiable (custom VJP with blockwise recompute), O(S) memory.
    ``kv_mask`` is a ``(B, S)`` key-validity mask (True = attend), the
    BERT-style padding mask. Sequences are padded internally to the block
    size; padded keys are masked out and padded query rows are sliced off.
    ``interpret=None`` auto-selects Pallas interpret mode off-TPU.

    Default blocks (512, 1024) are the v5e sweep winner: 1.5× faster than
    the XLA dense path at S=16K (82 vs 122 ms, 12 heads, d=64, bf16) while
    the dense path stops compiling at all past ~32K.
    """
    B, H, S, D = q.shape
    if interpret is None:
        interpret = _auto_interpret()
    if scale is None:
        scale = 1.0 / float(D) ** 0.5

    # block sizes must be lane-aligned for the Mosaic lowering, and the
    # padded length must divide by BOTH (the kernel grid and the backward
    # reshape floor-divide by them), hence the LCM; one block covering a
    # short sequence beats padding to 2+ blocks
    block_q = min(_round_up(block_q, _LANE), _round_up(S, _LANE))
    block_k = min(_round_up(block_k, _LANE), _round_up(S, _LANE))
    lcm = block_q * block_k // math.gcd(block_q, block_k)
    Sp = _round_up(S, lcm)

    if kv_mask is None:
        kv_mask = jnp.ones((B, S), jnp.bool_)
    mask_p = jnp.pad(kv_mask.astype(jnp.int32), ((0, 0), (0, Sp - S)))

    def pad(t):
        return jnp.pad(t, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))

    qp = pad(q).reshape(B * H, Sp, D)
    kp = pad(k).reshape(B * H, Sp, D)
    vp = pad(v).reshape(B * H, Sp, D)

    o = _flash(qp, kp, vp, mask_p, causal, float(scale), block_q, block_k,
               bool(interpret), H)
    return o.reshape(B, H, Sp, D)[:, :, :S, :]


def flash_attention_with_stats(q, k, v, *, scale: Optional[float] = None,
                               block_q: int = 512, block_k: int = 1024,
                               interpret: Optional[bool] = None):
    """Forward-only flash attention that also returns the softmax statistics
    ``(o, l, m)`` — o ``(B, H, S, D)``, l/m fp32 ``(B, H, S)``.

    The stats let a caller merge partial attention results computed over
    disjoint key sets (log-sum-exp merge), which is exactly what ring
    attention does as K/V blocks rotate: see ``parallel/ring.ring_attention``
    with ``use_flash=True``. This function itself has no VJP through the
    stats — differentiate the MERGED result instead (ring_attention's
    ring-level custom VJP does exactly that)."""
    B, H, S, D = q.shape
    if interpret is None:
        interpret = _auto_interpret()
    if scale is None:
        scale = 1.0 / float(D) ** 0.5
    block_q = min(_round_up(block_q, _LANE), _round_up(S, _LANE))
    block_k = min(_round_up(block_k, _LANE), _round_up(S, _LANE))
    lcm = block_q * block_k // math.gcd(block_q, block_k)
    Sp = _round_up(S, lcm)

    mask_p = jnp.pad(jnp.ones((B, S), jnp.int32), ((0, 0), (0, Sp - S)))

    def pad(t):
        return jnp.pad(t, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))

    o, l, m = _flash_fwd(pad(q).reshape(B * H, Sp, D),
                         pad(k).reshape(B * H, Sp, D),
                         pad(v).reshape(B * H, Sp, D),
                         mask_p, causal=False, scale=float(scale),
                         block_q=block_q, block_k=block_k,
                         interpret=bool(interpret), heads=H,
                         with_stats=True)
    return (o.reshape(B, H, Sp, D)[:, :, :S, :],
            l.reshape(B, H, Sp)[:, :, :S],
            m.reshape(B, H, Sp)[:, :, :S])


def flash_attention_sharded(q, k, v, mesh, *, dp_axis: str = "dp",
                            tp_axis: str = "tp", **kwargs):
    """Flash attention inside a dp×tp program: batch sharded over
    ``dp_axis``, heads over ``tp_axis``, per-shard Pallas call via
    ``shard_map`` (attention is batch/head-local — no collectives)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import get_shard_map

    shard_map, unchecked = get_shard_map()
    kv_mask = kwargs.pop("kv_mask", None)
    spec = P(dp_axis, tp_axis, None, None)

    if kv_mask is None:
        def fn(q, k, v):
            return flash_attention(q, k, v, **kwargs)
        return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, **unchecked)(q, k, v)

    def fn(q, k, v, m):
        return flash_attention(q, k, v, kv_mask=m, **kwargs)
    return shard_map(fn, mesh=mesh,
                     in_specs=(spec, spec, spec, P(dp_axis, None)),
                     out_specs=spec, **unchecked)(q, k, v, kv_mask)
