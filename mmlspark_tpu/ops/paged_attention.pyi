# Hand-written stub (paged_attention.py defines no PipelineStage, so
# codegen skips it); kept in sync by tpulint rule TPU006 (stub-drift).
from typing import Any, Optional, Tuple

ENV_KNOB: str

def resolve_impl(override: Optional[str] = ...) -> str: ...
def sublane_multiple(dtype: Any) -> int: ...
def aligned_page_size(page_size: int, dtype: Any) -> int: ...
def paged_attention(q: Any, k_pages: Any, v_pages: Any,
                    block_tables: Any, lengths: Any, *,
                    k_scale: Optional[Any] = ...,
                    v_scale: Optional[Any] = ...,
                    scale: Optional[float] = ...,
                    interpret: Optional[bool] = ...,
                    mesh: Optional[Any] = ...,
                    slot_axis: Optional[str] = ...,
                    head_axis: Optional[str] = ...) -> Any: ...
def paged_attention_window(q: Any, k_new: Any, v_new: Any,
                           k_pages: Any, v_pages: Any,
                           block_tables: Any, pos: Any, *,
                           active: Optional[Any] = ...,
                           k_scale: Optional[Any] = ...,
                           v_scale: Optional[Any] = ...,
                           scale: Optional[float] = ...,
                           interpret: Optional[bool] = ...,
                           mesh: Optional[Any] = ...,
                           slot_axis: Optional[str] = ...,
                           head_axis: Optional[str] = ...
                           ) -> Tuple[Any, ...]: ...
