"""KV quantization helpers — the SANCTIONED quant/dequant primitives.

The quantized KV data plane stores K/V pages as int8 (or
``float8_e4m3fn`` where the platform has it) plus a per-position
per-head scale array, and dequantizes INSIDE the paged-attention kernel
(`ops/paged_attention.py`). Every writer — ``paged_scatter_rows``
(prefill), ``_paged_writeback`` (gather impl), ``_pool_write_rows``
(mesh mount) and the fused kernel's in-launch scatter — must produce
bit-identical bytes for the same rows, so they all quantize through
:func:`quantize_kv` below. tpulint TPU018 (``unscaled-quant-cast``)
enforces exactly this: a bare ``.astype(int8/fp8)`` on a KV/activation
tensor anywhere outside this module is flagged.

Scheme: symmetric per-(position, head) absmax scaling over the head
dimension. For a row ``x`` of shape ``(..., hd)``::

    scale = amax(|x|, axis=-1) / qmax        (1.0 where amax == 0)
    q     = clip(round(x / scale), -qmax, qmax).astype(store)
    x'    = q * scale

Scales are stored in **bfloat16**, not f32 — the byte ratio is what the
whole tentpole is about: at ``hd == 64`` a bf16 K/V position is 128
bytes; int8 values + a bf16 scale are 66 (1.94x), while an f32 scale
would make it 68 (1.88x) and miss the 1.9x HBM target. The stored
(rounded) scale is also the one used for the forward division, so
``dequantize_kv(quantize_kv(x))`` reproduces exactly what the kernel
reads.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

__all__ = ["quantize_kv", "dequantize_kv", "resolve_kv_dtype",
           "kv_store_dtype", "kv_qmax", "supports_fp8", "SCALE_DTYPE",
           "kv_bytes_per_position"]

#: dtype of the per-(page, head, position) scale arrays. bf16, so a
#: quantized position costs hd + 2 bytes against bf16's 2*hd.
SCALE_DTYPE = jnp.bfloat16

#: canonical kv_dtype names -> canonical form (None = unquantized bf16
#: pages, the oracle path)
_CANON = {None: None, "": None, "none": None, "bf16": None,
          "bfloat16": None, "int8": "int8", "fp8": "fp8",
          "float8": "fp8", "float8_e4m3fn": "fp8", "e4m3": "fp8"}

#: symmetric clip bound per store dtype: int8 uses +-127 (the -128 code
#: is never produced, keeping the scheme symmetric); e4m3fn saturates
#: at +-448
_QMAX_INT8 = 127.0
_QMAX_FP8 = 448.0


def supports_fp8() -> bool:
    """Whether this jax build can hold and convert ``float8_e4m3fn``
    arrays (gates ``kv_dtype="fp8"`` — no new deps, just a probe)."""
    if not hasattr(jnp, "float8_e4m3fn"):
        return False
    try:
        jnp.zeros((1,), jnp.float8_e4m3fn).astype(jnp.float32)
        return True
    except Exception:
        return False


def resolve_kv_dtype(kv_dtype) -> Optional[str]:
    """Canonicalize a ``kv_dtype`` knob value to ``"int8"``, ``"fp8"``
    or None (bf16 pages). Raises on unknown names and on ``"fp8"`` when
    the platform lacks ``float8_e4m3fn``."""
    key = kv_dtype
    if isinstance(key, str):
        key = key.strip().lower()
    if key not in _CANON:
        raise ValueError(
            f"unknown kv_dtype {kv_dtype!r} (choose 'bf16', 'int8' or 'fp8')")
    canon = _CANON[key]
    if canon == "fp8" and not supports_fp8():
        raise ValueError(
            "kv_dtype='fp8' needs jax.numpy.float8_e4m3fn, which this "
            "platform build lacks — use kv_dtype='int8'")
    return canon


def kv_store_dtype(kv_dtype: Optional[str]):
    """The jnp dtype quantized pages are stored in, or None for the
    unquantized (bf16 oracle) representation."""
    canon = resolve_kv_dtype(kv_dtype)
    if canon is None:
        return None
    if canon == "int8":
        return jnp.int8
    return jnp.float8_e4m3fn


def kv_qmax(dtype) -> float:
    """Symmetric clip bound for a quantized store dtype — derived from
    the POOL BUFFER dtype inside jitted code, so no static string rides
    through the trace."""
    d = jnp.dtype(dtype)
    if d == jnp.dtype(jnp.int8):
        return _QMAX_INT8
    if hasattr(jnp, "float8_e4m3fn") and d == jnp.dtype(jnp.float8_e4m3fn):
        return _QMAX_FP8
    raise ValueError(f"not a quantized KV store dtype: {dtype!r}")


def quantize_kv(x, store_dtype):
    """Quantize ``x`` (..., hd) to ``(q, scale)`` with per-(...,) head-row
    absmax scales: ``q`` has ``x``'s shape in ``store_dtype``; ``scale``
    drops the last axis and is :data:`SCALE_DTYPE`. The division uses
    the ROUNDED (stored) scale so every writer and the in-kernel dequant
    agree bit-for-bit."""
    qm = kv_qmax(store_dtype)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0.0, amax / qm, 1.0).astype(SCALE_DTYPE)
    y = xf / scale.astype(jnp.float32)[..., None]
    if jnp.dtype(store_dtype) == jnp.dtype(jnp.int8):
        q = jnp.clip(jnp.round(y), -qm, qm).astype(store_dtype)
    else:
        q = jnp.clip(y, -qm, qm).astype(store_dtype)
    return q, scale


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Reconstruct ``q * scale`` (scale broadcast over the trailing head
    dimension) in ``dtype`` — exactly the product the Pallas kernel
    forms in VMEM after its page DMA."""
    out = q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]
    return out.astype(dtype)


def kv_bytes_per_position(heads: int, head_dim: int, value_dtype,
                          quantized: bool) -> int:
    """HBM bytes one cached K+V position costs across both tensors of
    ONE layer: ``2 * heads * (hd * itemsize + scale)``. This is the
    number the engine's per-tick byte accounting and the pool's
    residency reservation both derive from, so the bench's
    ``hbm_bytes_saved_per_step`` counter-assert measures the layout that
    is actually allocated."""
    item = jnp.dtype(value_dtype).itemsize
    scale = jnp.dtype(SCALE_DTYPE).itemsize if quantized else 0
    return 2 * int(heads) * (int(head_dim) * item + scale)
