"""Static-shape discipline: padding, masking, and shape bucketing.

XLA compiles one executable per input shape. The reference tolerates ragged
batches everywhere (``DynamicMiniBatchTransformer``, variable last batch —
``stages/MiniBatchTransformer.scala:51-251``); on TPU that would trigger a
recompile per ragged size. This module gives every device feed a bounded
shape vocabulary:

* ``bucket_size(n)`` — smallest allowed batch size ≥ n (powers of two by
  default), so the jit cache holds O(log max_batch) entries, not O(batches).
* ``pad_batch`` / ``unpad`` — pad rows with zeros + boolean validity mask,
  with mask-correct semantics left to the consumer (e.g. mean over mask).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["bucket_size", "default_buckets", "pad_batch", "pad_axis", "unpad",
           "PaddedBatch"]


def default_buckets(max_size: int = 1 << 20) -> List[int]:
    out, b = [], 1
    while b < max_size:
        out.append(b)
        b <<= 1
    out.append(max_size)
    return out


def bucket_size(n: int, buckets: Optional[Sequence[int]] = None) -> int:
    """Smallest bucket ≥ n. Default: next power of two."""
    if n <= 0:
        return 1
    if buckets is None:
        return 1 << (n - 1).bit_length()
    for b in buckets:
        if b >= n:
            return int(b)
    raise ValueError(f"batch of {n} rows exceeds largest bucket {buckets[-1]}")


class PaddedBatch:
    """A dict of equal-leading-dim arrays padded to a common bucket + mask."""

    def __init__(self, arrays: Dict[str, np.ndarray], mask: np.ndarray, n_valid: int):
        self.arrays = arrays
        self.mask = mask
        self.n_valid = int(n_valid)

    def __getitem__(self, k):
        return self.arrays[k]

    @property
    def padded_size(self) -> int:
        return len(self.mask)


def pad_axis(arr: np.ndarray, size: int, axis: int = 0,
             fill=0) -> np.ndarray:
    cur = arr.shape[axis]
    if cur == size:
        return arr
    if cur > size:
        raise ValueError(f"array dim {cur} exceeds pad target {size}")
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, size - cur)
    return np.pad(arr, widths, mode="constant", constant_values=fill)


def pad_axis_device(arr, size: int, axis: int = 0, fill=0):
    """``pad_axis`` for a device array: pads with ``jnp.pad`` so a
    device-resident feed reaches its shape bucket *without* a host
    round-trip (the device-feed path of ``BatchRunner``)."""
    cur = arr.shape[axis]
    if cur == size:
        return arr
    if cur > size:
        raise ValueError(f"array dim {cur} exceeds pad target {size}")
    import jax.numpy as jnp
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, size - cur)
    return jnp.pad(arr, widths, mode="constant", constant_values=fill)


def _coerce_host(v) -> np.ndarray:
    """Host coercion with the same dtype policy as the model feed paths:
    a Python float payload lands as float64, which TPUs have no ALU for —
    every such batch would carry a fresh jit signature and 2x the transfer
    bytes, so normalize f64→f32 here (ints and exotic dtypes pass through).
    """
    arr = np.asarray(v)  # tpulint: disable=TPU004 — dtype normalized below
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


def pad_batch(arrays: Dict[str, np.ndarray],
              buckets: Optional[Sequence[int]] = None,
              pad_to: Optional[int] = None) -> PaddedBatch:
    """Pad every array's leading dim to a shared bucket; returns mask."""
    sizes = {k: len(v) for k, v in arrays.items()}
    ns = set(sizes.values())
    if len(ns) > 1:
        raise ValueError(f"inconsistent batch sizes: {sizes}")
    n = ns.pop() if ns else 0
    target = pad_to if pad_to is not None else bucket_size(n, buckets)
    padded = {k: pad_axis(_coerce_host(v), target) for k, v in arrays.items()}
    mask = np.zeros(target, dtype=bool)
    mask[:n] = True
    return PaddedBatch(padded, mask, n)


def unpad(arr: np.ndarray, n_valid: int) -> np.ndarray:
    # dtype-preserving: the input is already an ndarray/device array, so
    # asarray only materializes on host — it cannot introduce float64
    return np.asarray(arr)[:n_valid]  # tpulint: disable=TPU004
