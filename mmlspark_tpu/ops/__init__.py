from .flash_attention import (flash_attention, flash_attention_sharded,
                              flash_attention_with_stats)
from .padding import (PaddedBatch, bucket_size, default_buckets, pad_axis,
                      pad_batch, unpad)

__all__ = ["PaddedBatch", "bucket_size", "default_buckets", "flash_attention",
           "flash_attention_sharded", "flash_attention_with_stats",
           "pad_axis", "pad_batch", "unpad"]
