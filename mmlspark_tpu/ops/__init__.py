from .compile_cache import (StageCounters, enable_persistent_cache,
                            jit_cache_size, persistent_cache_dir,
                            warm_up_jitted)
from .flash_attention import (flash_attention, flash_attention_sharded,
                              flash_attention_with_stats)
from .padding import (PaddedBatch, bucket_size, default_buckets, pad_axis,
                      pad_batch, unpad)

__all__ = ["PaddedBatch", "StageCounters", "bucket_size", "default_buckets",
           "enable_persistent_cache", "flash_attention",
           "flash_attention_sharded", "flash_attention_with_stats",
           "jit_cache_size", "pad_axis", "pad_batch",
           "persistent_cache_dir", "unpad", "warm_up_jitted"]
