from .padding import (PaddedBatch, bucket_size, default_buckets, pad_axis,
                      pad_batch, unpad)

__all__ = ["PaddedBatch", "bucket_size", "default_buckets", "pad_axis",
           "pad_batch", "unpad"]
