from .flash_attention import flash_attention, flash_attention_sharded
from .padding import (PaddedBatch, bucket_size, default_buckets, pad_axis,
                      pad_batch, unpad)

__all__ = ["PaddedBatch", "bucket_size", "default_buckets", "flash_attention",
           "flash_attention_sharded", "pad_axis", "pad_batch", "unpad"]
