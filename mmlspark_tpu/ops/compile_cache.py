"""Compilation-cache management and AOT bucket warm-up.

XLA compiles one executable per (program, input shapes, placement) triple, and
that compile lands — multi-second for real graphs — on whatever request is
unlucky enough to arrive first in each padding bucket. This module removes the
stall from both ends:

* **Persistent compilation cache** — :func:`enable_persistent_cache` wires
  JAX's on-disk executable cache (env ``MMLSPARK_TPU_COMPILE_CACHE_DIR``), so
  a process restart deserializes yesterday's executables instead of
  recompiling them. TVM (arxiv 1802.04799) and ONNX-MLIR (arxiv 2008.08272)
  both land on the same conclusion: once the graph is static, inference
  performance is decided at the compile-cache and host↔device boundary.
* **AOT warm-up** — :func:`warm_up_jitted` drives a jitted program through
  every padding-bucket shape in the expected vocabulary *before* first
  traffic, populating the in-process jit cache (and, when enabled, the
  persistent cache). ``ONNXModel.warm_up`` / ``JaxModel.warm_up`` and the
  ``ServingEngine`` pre-serve hook are thin wrappers over this.
* **Stage counters** — :class:`StageCounters` instruments the feed/drain
  pipeline (coerce / pad / h2d / compile / dispatch / d2h) with near-zero
  overhead so ``bench.py`` can report where partition wall-clock actually
  goes.
"""

from __future__ import annotations

import os
import threading

from ..reliability.lock_sanitizer import new_lock
import time
from typing import Callable, Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from ..observability import counter as _metric_counter
from ..observability import tracing as _tracing
from ..observability import watch as _watch
from .padding import bucket_size

__all__ = ["enable_persistent_cache", "persistent_cache_dir", "StageCounters",
           "jit_cache_size", "warm_up_jitted", "warm_up_model",
           "resolve_input_specs"]

#: environment variable naming the persistent compilation cache directory
CACHE_DIR_ENV = "MMLSPARK_TPU_COMPILE_CACHE_DIR"

# Registry mirrors (docs/observability.md has the catalog). Stage counters
# stay per-model objects for snapshot parity with the reference; every
# StageCounters.add also feeds the process-global labeled counters below so
# GET /metrics sees aggregate pipeline time without any plumbing. The
# cache-outcome counters are shared with models/runner.py, which owns the
# per-dispatch attribution.
M_STAGE_SECONDS = _metric_counter(
    "mmlspark_runner_stage_seconds_total",
    "Cumulative feed/drain pipeline wall-clock by stage", ("stage",))
M_STAGE_CALLS = _metric_counter(
    "mmlspark_runner_stage_calls_total",
    "Feed/drain pipeline stage invocations", ("stage",))
M_STAGE_BYTES = _metric_counter(
    "mmlspark_runner_stage_bytes_total",
    "Bytes crossing the host<->device boundary by stage", ("stage",))
M_CACHE_HITS = _metric_counter(
    "mmlspark_compile_cache_hits_total",
    "Dispatches served by an already-compiled executable")
M_CACHE_MISSES = _metric_counter(
    "mmlspark_compile_cache_misses_total",
    "Dispatches that paid an inline XLA trace+compile")
M_STEADY_RECOMPILES = _metric_counter(
    "mmlspark_compile_cache_steady_state_recompiles_total",
    "Compiles observed by the dispatch loop, i.e. outside warm-up — "
    "nonzero means a bucket is missing from the warm_up vocabulary")
M_WARMUP_BUCKETS = _metric_counter(
    "mmlspark_compile_cache_warmup_buckets_total",
    "Padding buckets executed ahead of traffic by warm_up")
M_WARMUP_SECONDS = _metric_counter(
    "mmlspark_compile_cache_warmup_seconds_total",
    "Wall-clock spent in AOT warm-up")

_cache_lock = new_lock("ops.compile_cache._cache_lock")
_cache_dir: Optional[str] = None


def enable_persistent_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Resolution order: explicit argument → ``MMLSPARK_TPU_COMPILE_CACHE_DIR``
    → legacy ``MMLSPARK_TPU_COMPILE_CACHE`` (the package-import knob in
    :mod:`mmlspark_tpu.utils.jit_cache`, which now delegates here) →
    ``JAX_COMPILATION_CACHE_DIR`` (which JAX honors on its own; we only
    record it). Returns the active directory, or ``None`` when no directory
    is configured anywhere. Idempotent and thread-safe; the min-compile-time
    and min-entry-size gates are zeroed so small graphs (unit-test MLPs,
    per-bucket variants of one model) are cached too — the default 1 s gate
    would silently skip exactly the programs serving warm-up cares about.
    """
    global _cache_dir
    with _cache_lock:
        path = (cache_dir or os.environ.get(CACHE_DIR_ENV)
                or os.environ.get("MMLSPARK_TPU_COMPILE_CACHE")
                or os.environ.get("JAX_COMPILATION_CACHE_DIR"))
        if not path:
            return None
        if _cache_dir == path:
            return _cache_dir
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        for knob, val in [("jax_persistent_cache_min_compile_time_secs", 0.0),
                          ("jax_persistent_cache_min_entry_size_bytes", 0)]:
            try:
                jax.config.update(knob, val)
            except Exception:
                pass  # knob renamed/absent on this jax version
        _cache_dir = path
        return _cache_dir


def persistent_cache_dir() -> Optional[str]:
    """The directory wired by :func:`enable_persistent_cache`, if any."""
    return _cache_dir


def jit_cache_size(jitted) -> Optional[int]:
    """Entries in a jitted callable's in-process executable cache.

    ``None`` when the introspection hook is unavailable (older/newer jax) —
    callers must treat that as "unknown", not zero.
    """
    try:
        return int(jitted._cache_size())
    except Exception:
        return None


class StageCounters:
    """Lightweight per-stage timing/byte counters for the feed/drain pipeline.

    Stages are free-form strings; the runner uses ``coerce``, ``pad``,
    ``h2d``, ``compile``, ``dispatch``, ``d2h``. Thread-safe (partitions run
    concurrently); ~100 ns per ``add``, so it stays on in production. The
    compile/dispatch split is attributed by observing jit-cache growth
    around each dispatch, so under concurrent partitions a compile may be
    double-attributed — counters are diagnostics, not an audit log.
    """

    def __init__(self):
        self._lock = new_lock("ops.compile_cache.StageCounters._lock")
        self._stages: Dict[str, Dict[str, float]] = {}

    def add(self, stage: str, seconds: float, nbytes: int = 0,
            count: int = 1) -> None:
        with self._lock:
            s = self._stages.setdefault(
                stage, {"calls": 0, "seconds": 0.0, "bytes": 0})
            s["calls"] += count
            s["seconds"] += seconds
            s["bytes"] += nbytes
        # mirror into the process-global registry (aggregated over models)
        M_STAGE_SECONDS.inc(seconds, stage=stage)
        M_STAGE_CALLS.inc(count, stage=stage)
        if nbytes:
            M_STAGE_BYTES.inc(nbytes, stage=stage)

    class _Timer:
        __slots__ = ("_c", "_stage", "_nbytes", "_t0")

        def __init__(self, counters, stage, nbytes):
            self._c, self._stage, self._nbytes = counters, stage, nbytes

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._c.add(self._stage, time.perf_counter() - self._t0,
                        self._nbytes)
            return False

    def timer(self, stage: str, nbytes: int = 0) -> "StageCounters._Timer":
        return self._Timer(self, stage, nbytes)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: {"calls": int(v["calls"]),
                        "seconds": round(float(v["seconds"]), 6),
                        "bytes": int(v["bytes"])}
                    for k, v in sorted(self._stages.items())}

    def reset(self) -> None:
        with self._lock:
            self._stages.clear()

    def total_seconds(self, stage: str) -> float:
        with self._lock:
            s = self._stages.get(stage)
            return float(s["seconds"]) if s else 0.0


def resolve_input_specs(inputs: Iterable, feed: Dict[str, str],
                        transpose: Dict[str, Sequence[int]],
                        overrides: Optional[Dict[str, tuple]] = None
                        ) -> Dict[str, Tuple[np.dtype, tuple]]:
    """Per-row (dtype, shape) of each *fed* model input, for warm-up zeros.

    ``inputs`` are converted-model value infos (``.name``, ``.numpy_dtype``,
    ``.shape``). Inputs routed through ``transpose_dict`` are fed in the
    column's layout, so the declared (post-transpose) shape is run backwards
    through the permutation. ``overrides`` ({name: (dtype, row_shape)}) wins
    outright — required when the declared shape is symbolic, or when the
    column's dtype differs from the graph's (uint8 images into a float
    input).
    """
    overrides = dict(overrides or {})
    specs: Dict[str, Tuple[np.dtype, tuple]] = {}
    for vi in inputs:
        if vi.name not in feed:
            continue
        if vi.name in overrides:
            dt, shape = overrides[vi.name]
            specs[vi.name] = (np.dtype(dt), tuple(shape))
            continue
        declared = list(vi.shape)
        perm = transpose.get(vi.name)
        if perm is not None:
            if len(perm) != len(declared):
                raise ValueError(
                    f"transpose_dict[{vi.name!r}] permutes {len(perm)} axes "
                    f"but the input declares {len(declared)}")
            fed = [None] * len(declared)
            for i, p in enumerate(perm):
                fed[p] = declared[i]
            declared = fed
        row_shape = declared[1:]
        if any(not isinstance(d, int) for d in row_shape):
            raise ValueError(
                f"input {vi.name!r} has symbolic per-row shape {row_shape}; "
                f"pass input_specs={{{vi.name!r}: (dtype, row_shape)}} to "
                f"warm_up")
        specs[vi.name] = (np.dtype(vi.numpy_dtype), tuple(row_shape))
    return specs


def warm_up_jitted(jitted, params, specs: Dict[str, Tuple[np.dtype, tuple]],
                   batch_sizes: Sequence[int], shards: int = 1,
                   put: Optional[Callable] = None,
                   counters: Optional[StageCounters] = None,
                   buckets: Optional[Sequence[int]] = None,
                   prog: Optional[str] = None) -> dict:
    """Compile (and prime the caches for) every padding-bucket shape.

    For each requested batch size the *padded* feed size is derived exactly
    as the runner derives it (``bucket_size`` over the active ladder, then
    rounded up to a multiple of ``shards``), zero-filled feeds are placed
    with ``put`` and run through ``jitted`` once, blocking on the result.
    That single throwaway execution is what populates jax's in-process jit
    cache — a bare ``lower().compile()`` produces an executable but leaves
    the cache cold, so the first real batch would still pay tracing +
    compile. With :func:`enable_persistent_cache` active the compile also
    lands on disk for the next process.

    ``buckets`` is the runner's padding ladder (``None`` = power-of-two):
    warm-up derives each padded size through the *same* ladder, so it
    compiles exactly the shapes the runner can produce — a caller on a
    custom ladder no longer pays for power-of-two buckets its batches can
    never land in.

    ``prog`` names the program for the collective auditor
    (``parallel.collective_audit``): with the audit enabled, every
    warmed bucket's compiled HLO is walked for collectives right after
    its warm-up call (which has just primed jax's compilation cache, so
    the extra ``lower().compile()`` is a lookup, not a second compile).

    Returns ``{"buckets": [padded sizes], "compiles": n, "seconds": s}``.
    ``compiles`` is ``None`` when the jit cache is not introspectable.
    """
    import jax

    # lazy: ops must stay importable without pulling the parallel package
    from ..parallel import collective_audit as _collective_audit

    enable_persistent_cache()
    if put is None:
        put = jax.device_put
    ladder = None if not buckets else tuple(sorted({int(b)
                                                    for b in buckets}))
    buckets = sorted({-(-bucket_size(int(b), ladder) // max(1, shards))
                      * max(1, shards) for b in batch_sizes if int(b) > 0})
    before = jit_cache_size(jitted)
    t_start = time.perf_counter()
    with _tracing.start_span("compile_cache.warm_up", buckets=len(buckets)), \
            _watch("compile_warmup") as _w:
        for size in buckets:
            t_b = time.perf_counter()
            feeds = {name: put(np.zeros((size,) + shape, dtype=dt))
                     for name, (dt, shape) in specs.items()}
            outs = jitted(params, feeds)
            # tpulint: disable=TPU001 — warm-up MUST fence each bucket so
            # the timed window covers the compile, not later steady-state
            # batches
            jax.block_until_ready(outs)
            if prog is not None and _collective_audit.enabled():
                _collective_audit.get_auditor().record_lowered(
                    prog, jitted, params, feeds)
            # heartbeat per bucket: the stall budget covers ONE compile,
            # not the whole ladder
            _w.beat()
            _tracing.add_event("warm_bucket", padded=size,
                               seconds=round(time.perf_counter() - t_b, 4))
    elapsed = time.perf_counter() - t_start
    after = jit_cache_size(jitted)
    compiles = (after - before) if (after is not None and before is not None) \
        else None
    if counters is not None and buckets:
        counters.add("compile", elapsed, count=compiles or len(buckets))
    if buckets:
        M_WARMUP_BUCKETS.inc(len(buckets))
        M_WARMUP_SECONDS.inc(elapsed)
    return {"buckets": buckets, "compiles": compiles,
            "seconds": round(elapsed, 4)}


def warm_up_model(model, jitted, specs, batch_sizes,
                  background: bool = False,
                  buckets: Optional[Sequence[int]] = None):
    """Warm every placement a model's traffic can hit (shared by
    ``ONNXModel.warm_up`` / ``JaxModel.warm_up``).

    With round-robin chip pinning the jit cache keys on the committed
    device, so every local chip gets its own warm pass; with a default mesh
    (or unpinned default placement) one pass suffices. ``model`` supplies
    ``_placement_params(pidx)``, ``mesh_sharded``/``pin_devices`` and its
    ``stage_counters``. ``background=True`` runs on a daemon thread and
    returns it; otherwise returns aggregated
    ``{"buckets", "compiles", "seconds", "placements"}``.
    """
    from ..parallel.mesh import get_default_mesh, local_devices

    def _warm():
        n_placements = 1
        if not (model.get("mesh_sharded") and get_default_mesh()
                is not None) and model.pin_devices:
            n_placements = max(1, len(local_devices()))
        stats = {"buckets": [], "compiles": 0, "seconds": 0.0,
                 "placements": 0}
        seen = set()
        for pidx in range(n_placements):
            placement, params = model._placement_params(pidx)
            if placement.key in seen:
                continue
            seen.add(placement.key)
            s = warm_up_jitted(jitted, params, specs, batch_sizes,
                               shards=placement.shards, put=placement.put,
                               counters=model.stage_counters,
                               buckets=buckets)
            stats["buckets"] = sorted(set(stats["buckets"])
                                      | set(s["buckets"]))
            if s["compiles"] is None:
                stats["compiles"] = None
            elif stats["compiles"] is not None:
                stats["compiles"] += s["compiles"]
            stats["seconds"] = round(stats["seconds"] + s["seconds"], 4)
            stats["placements"] += 1
        return stats

    if background:
        # tpulint: disable=TPU025 — run-once background warm-up over a
        # finite placement list, not a service loop; a crash leaves the
        # cache cold (first real request compiles) and must not restart
        t = threading.Thread(target=_warm, daemon=True,
                             name=f"warmup-{model.uid}")
        t.start()
        return t
    return _warm()
