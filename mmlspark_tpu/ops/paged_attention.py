"""Paged attention — Pallas TPU decode kernel over the KV page pool.

PR 7's paged decode path is *gather-then-attend*: every tick copies each
row's pages into a contiguous ``(B, H, L, hd)`` scratch
(``models/zoo/transformer.py:paged_gather``), runs the ragged step, and
scatters the one fresh K/V position back (``_paged_writeback``). That
gather is an O(B·L)×layers HBM round-trip per decode tick that grows
linearly with context — pure data movement, zero FLOPs of value. This
module removes it: a vLLM-style PagedAttention kernel that walks the
BLOCK TABLE and reads K/V pages **in place**, carrying FlashAttention's
online-softmax accumulators in VMEM, so per-tick HBM traffic is one read
of the live pages plus one page-granular write — never a contiguous
materialization.

Design notes (TPU-first):

* grid = (B, P_max) with the page sweep innermost. Blocks carry the full
  head dimension — a page block is ``(1, H, page, hd)`` — so each page is
  DMA'd ONCE per row per layer, not once per head.
* the physical page for grid step ``(b, p)`` comes from a
  scalar-prefetched block table: the BlockSpec index_map reads
  ``bt[b, p]`` (``PrefetchScalarGridSpec``), which is exactly the
  indirection ``paged_gather`` used to materialize. Unallocated logical
  pages map to the TRASH page 0 in the table; their keys are masked out
  by the per-row length bound anyway.
* running ``m``/``l`` live in VMEM scratch shaped ``(H, W, LANE)``
  (lane-replicated, as in ``flash_attention.py``); the f32 context
  accumulator is ``(H, W, hd)``. Masked logits use ``-1e30`` — a fully
  masked row yields ``l == 0`` and the final divide guards it to zeros
  rather than NaN.
* the FUSED variant (:func:`paged_attention_window`) also scatters the
  window's fresh K/V rows into their pages in the same launch, replacing
  the separate per-tick writeback. The window rows ride along as direct
  ``(B, H, W, hd)`` inputs folded into the online softmax under an
  in-window causal mask, so pages only ever supply keys strictly before
  ``pos[b]`` — reading each page's *pre-scatter* content is therefore
  exact. The scatter itself goes through ``input_output_aliases``: the
  page-pool outputs alias the inputs and their index_map redirects every
  page outside the row's write range to trash page 0, so Pallas's
  write-on-index-change semantics make the real page writes O(1) per row
  instead of O(context).
* page-write exclusivity is a CALLER contract: a page inside any row's
  write range (``pos[b] .. pos[b]+W-1``) must be exclusively owned by
  that row. The pool's copy-on-write admission guarantees this — shared
  prefix pages are never written (serving/kv_pool.py).
* MESH MOUNT: a bare ``pallas_call`` inside a sharded jit is not
  GSPMD-partitionable — XLA would gather the whole pool onto one
  device. ``paged_attention``/``paged_attention_window`` therefore take
  ``mesh=`` and mount the kernel via ``jax.shard_map`` with heads split
  over the ``tp`` axis: Q, the page pools and the online-softmax VMEM
  scratch all shard on the head axis (specs
  ``P(slot_axis, head_axis, None, None)`` / ``P(None, head_axis, None,
  None)``), each shard runs the UNCHANGED kernel over its ``heads/tp``
  slice, and only the caller's post-attention projection pays an ICI
  collective (GSPMD inserts it, exactly as for ``transformer_apply``).
  Slots optionally shard over ``dp``. Under a mesh the mount is
  READ-ONLY — the fused in-kernel scatter cannot run per-shard when
  slots split over ``dp`` while the pool replicates over it (each dp
  shard would apply only its own rows' writes and the replicas would
  diverge) — so the window's fresh K/V rows are written OUTSIDE the
  mount by :func:`_pool_write_rows`, a GSPMD-partitionable scatter that
  writes bytes bit-identical to both ``_paged_writeback`` and the fused
  kernel's in-launch scatter.

Tiling contract: the page dimension sits in the SUBLANE slot of the
``(1, H, page, hd)`` block, so on a real TPU ``page_size`` must be a
multiple of the dtype's sublane tile — 8 (f32), 16 (bf16), 32 (int8);
see :func:`sublane_multiple` / :func:`aligned_page_size` and
``PagedKVPool.kernel_aligned_page_size``. Interpret mode (the CI path on
``JAX_PLATFORMS=cpu``, chosen automatically like ``flash_attention``'s
``_auto_interpret``) has no such constraint.

``MMLSPARK_TPU_PAGED_ATTN=gather`` selects PR 7's gather path as a
fallback; :func:`resolve_impl` is the one resolver every layer shares.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from .pallas_kernels import _LANE, _round_up
from .kv_quant import quantize_kv

__all__ = ["paged_attention", "paged_attention_window", "resolve_impl",
           "sublane_multiple", "aligned_page_size"]

_NEG = -1e30

#: env knob — process default for the paged-attention implementation.
ENV_KNOB = "MMLSPARK_TPU_PAGED_ATTN"

_IMPLS = {"kernel": "kernel", "fused": "kernel", "auto": "kernel",
          "default": "kernel", "": "kernel",
          "gather": "gather", "xla": "gather", "reference": "gather"}


def resolve_impl(override: Optional[str] = None) -> str:
    """Resolve the paged-attention implementation: an explicit
    ``override`` wins, else the ``MMLSPARK_TPU_PAGED_ATTN`` env knob,
    else ``"kernel"``. Returns ``"kernel"`` or ``"gather"``.

    Resolved EAGERLY by callers (the engine resolves once at
    construction and threads the choice into its compiled-program cache
    keys) — resolving inside a trace would bake one process-wide env
    read into every cached program."""
    raw = override if override is not None else os.environ.get(ENV_KNOB, "")
    key = str(raw).strip().lower()
    if key not in _IMPLS:
        raise ValueError(
            f"unknown paged-attention impl {raw!r} "
            f"(choose 'kernel' or 'gather')")
    return _IMPLS[key]


def sublane_multiple(dtype) -> int:
    """The TPU sublane tile for ``dtype`` — the unit ``page_size`` must
    divide into for the kernel's ``(1, H, page, hd)`` page blocks."""
    itemsize = jnp.dtype(dtype).itemsize
    return max(8, 32 // max(1, itemsize))


def aligned_page_size(page_size: int, dtype) -> int:
    """Round ``page_size`` up to the kernel-tileable multiple for
    ``dtype`` (identity whenever it already complies)."""
    return _round_up(max(1, int(page_size)), sublane_multiple(dtype))


def _auto_interpret() -> bool:
    from ..utils.device import is_tpu
    return not is_tpu()


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, dtype)


def _fold(m_scr, l_scr, acc_scr, s, valid, v):
    """One online-softmax update: fold the score block ``s`` (H, W, K)
    with key-validity ``valid`` (broadcastable) and values ``v``
    (H, K, hd) into the running (m, l, acc) VMEM state."""
    s = jnp.where(valid, s, _NEG)
    m_prev = m_scr[..., 0:1]                           # (H, W, 1)
    l_prev = l_scr[..., 0:1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # `valid` (not the _NEG sentinel) zeroes masked probabilities: for a
    # row with every key masked so far, m_new == _NEG and exp(s - m_new)
    # would be exp(0) == 1 on the masked entries.
    p = jnp.exp(s - m_new) * valid.astype(jnp.float32)
    corr = jnp.exp(m_prev - m_new)                      # <= 1
    l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p, v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)             # (H, W, hd)
    acc_scr[...] = acc_scr[...] * corr + pv
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)


def _finalize(o_ref, l_scr, acc_scr):
    l = l_scr[..., 0:1]
    o_ref[0] = (acc_scr[...] /
                jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def _page_scores(q, kp_ref, scale):
    kp = kp_ref[0].astype(jnp.float32)                  # (H, page, hd)
    return jax.lax.dot_general(
        q, kp, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale     # (H, W, page)


def _deq_block(p_ref, s_ref):
    """Dequantize one (1, H, page, hd) page block with its (1, H, page)
    scale block — the IN-KERNEL dequant: both blocks arrived through the
    same block-table index_map, so this multiply happens in VMEM right
    after the page DMA and the quantized bytes are all HBM ever moves."""
    return (p_ref[0].astype(jnp.float32) *
            s_ref[0].astype(jnp.float32)[:, :, None])   # (H, page, hd)


def _page_scores_q(q, kp_ref, ks_ref, scale):
    return jax.lax.dot_general(
        q, _deq_block(kp_ref, ks_ref), (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale     # (H, W, page)


def _pa_read_kernel(bt_ref, len_ref, q_ref, kp_ref, vp_ref, o_ref,
                    m_scr, l_scr, acc_scr, *, scale, page, n_pages):
    """One (b, p) grid step of the read-only page sweep: attend the
    queries over page ``p``'s keys, bounded by ``len_ref[b]``."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    bound = len_ref[b]

    @pl.when(p * page < bound)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                # (H, W, hd)
        s = _page_scores(q, kp_ref, scale)
        t = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page), 2)
        _fold(m_scr, l_scr, acc_scr, s, t < bound,
              vp_ref[0].astype(jnp.float32))

    @pl.when(p == n_pages - 1)
    def _fin():
        _finalize(o_ref, l_scr, acc_scr)


def _pa_fused_kernel(bt_ref, pos_ref, wlo_ref, whi_ref, q_ref, kn_ref,
                     vn_ref, kp_ref, vp_ref, o_ref, ko_ref, vo_ref,
                     m_scr, l_scr, acc_scr, *, scale, page, W, n_pages):
    """One (b, p) grid step of the fused decode-window sweep.

    Page keys are masked STRICTLY below ``pos[b]`` — the window's own
    rows arrive as the direct (H, W, hd) ``kn``/``vn`` inputs, folded
    once at p == 0 under the in-window causal mask, so the page blocks
    are always read pre-scatter. Pages inside the row's write range get
    their fresh rows overlaid and written back through the aliased
    page-pool outputs; every other grid step leaves its (trash-directed)
    output block untouched."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    p = pl.program_id(1)
    pos = pos_ref[b]
    Wp = q_ref.shape[2]

    @pl.when(p == 0)
    def _init_and_window():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)
        q = q_ref[0].astype(jnp.float32)                # (H, Wp, hd)
        kn = kn_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kn, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # (H, Wp, Wp)
        row = jax.lax.broadcasted_iota(jnp.int32, (1, Wp, Wp), 1)
        col = jax.lax.broadcasted_iota(jnp.int32, (1, Wp, Wp), 2)
        # query j sees window keys j' <= j; padding key rows never
        # (padding QUERY rows keep every real key — they need a nonzero
        # denominator and their output is sliced off host-side)
        valid = jnp.logical_and(
            jnp.logical_or(col <= row, row >= W), col < W)
        _fold(m_scr, l_scr, acc_scr, s, valid,
              vn_ref[0].astype(jnp.float32))

    @pl.when(p * page < pos)
    def _pages():
        q = q_ref[0].astype(jnp.float32)
        s = _page_scores(q, kp_ref, scale)
        t = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page), 2)
        _fold(m_scr, l_scr, acc_scr, s, t < pos,
              vp_ref[0].astype(jnp.float32))

    in_write_range = jnp.logical_and(p >= wlo_ref[b], p <= whi_ref[b])

    @pl.when(in_write_range)
    def _scatter():
        # overlay the window rows that land in THIS page, in the pool
        # dtype (no f32 round-trip: the written bytes are bit-identical
        # to _paged_writeback's)
        kblk = kp_ref[0]                                # (H, page, hd)
        vblk = vp_ref[0]
        ridx = jax.lax.broadcasted_iota(jnp.int32, (1, page, 1), 1)
        for j in range(W):                              # W static, small
            tgt = pos + j - p * page
            hit = ridx == tgt                           # all-False if out
            kblk = jnp.where(hit, kn_ref[0, :, j:j + 1, :], kblk)
            vblk = jnp.where(hit, vn_ref[0, :, j:j + 1, :], vblk)
        ko_ref[0] = kblk
        vo_ref[0] = vblk

    @pl.when(p == n_pages - 1)
    def _fin():
        _finalize(o_ref, l_scr, acc_scr)


def _pa_window_kernel(bt_ref, pos_ref, q_ref, kn_ref, vn_ref, kp_ref,
                      vp_ref, o_ref, m_scr, l_scr, acc_scr, *,
                      scale, page, W, n_pages):
    """One (b, p) grid step of the READ-ONLY decode-window sweep — the
    shard_map-mounted variant. Identical online-softmax math to
    :func:`_pa_fused_kernel` (window rows folded once at p == 0 under
    the in-window causal mask, pages masked strictly below ``pos[b]``),
    minus the in-kernel page scatter: under a mesh the fresh rows are
    written outside the mount (:func:`_pool_write_rows`), so only two
    scalar-prefetch operands (block table, pos) remain and no output
    aliases the pool."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    p = pl.program_id(1)
    pos = pos_ref[b]
    Wp = q_ref.shape[2]

    @pl.when(p == 0)
    def _init_and_window():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)
        q = q_ref[0].astype(jnp.float32)                # (H, Wp, hd)
        kn = kn_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kn, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # (H, Wp, Wp)
        row = jax.lax.broadcasted_iota(jnp.int32, (1, Wp, Wp), 1)
        col = jax.lax.broadcasted_iota(jnp.int32, (1, Wp, Wp), 2)
        valid = jnp.logical_and(
            jnp.logical_or(col <= row, row >= W), col < W)
        _fold(m_scr, l_scr, acc_scr, s, valid,
              vn_ref[0].astype(jnp.float32))

    @pl.when(p * page < pos)
    def _pages():
        q = q_ref[0].astype(jnp.float32)
        s = _page_scores(q, kp_ref, scale)
        t = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page), 2)
        _fold(m_scr, l_scr, acc_scr, s, t < pos,
              vp_ref[0].astype(jnp.float32))

    @pl.when(p == n_pages - 1)
    def _fin():
        _finalize(o_ref, l_scr, acc_scr)


# ---- quantized kernels ------------------------------------------------------
#
# Same grid, same online-softmax state, same masks as the bf16 kernels
# above — the only differences are (a) two extra (1, H, page) scale
# blocks riding the SAME block-table index_map as their page blocks,
# dequantized in VMEM by _deq_block before the dot, and (b) the fused
# variant's in-kernel scatter quantizing each window row through
# quantize_kv (the sanctioned helper — bit-identical to what
# _pool_write_rows/_paged_writeback write, so every writer agrees).

def _pa_read_kernel_q(bt_ref, len_ref, q_ref, kp_ref, vp_ref, ks_ref,
                      vs_ref, o_ref, m_scr, l_scr, acc_scr, *,
                      scale, page, n_pages):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    bound = len_ref[b]

    @pl.when(p * page < bound)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                # (H, W, hd)
        s = _page_scores_q(q, kp_ref, ks_ref, scale)
        t = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page), 2)
        _fold(m_scr, l_scr, acc_scr, s, t < bound,
              _deq_block(vp_ref, vs_ref))

    @pl.when(p == n_pages - 1)
    def _fin():
        _finalize(o_ref, l_scr, acc_scr)


def _window_fold(m_scr, l_scr, acc_scr, q_ref, kn_ref, vn_ref, scale, W):
    """The p == 0 window fold shared by the fused/window kernels: fresh
    rows arrive unquantized (they are direct inputs, not pages), folded
    under the in-window causal mask."""
    Wp = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32)                    # (H, Wp, hd)
    kn = kn_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, kn, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale     # (H, Wp, Wp)
    row = jax.lax.broadcasted_iota(jnp.int32, (1, Wp, Wp), 1)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, Wp, Wp), 2)
    valid = jnp.logical_and(
        jnp.logical_or(col <= row, row >= W), col < W)
    _fold(m_scr, l_scr, acc_scr, s, valid,
          vn_ref[0].astype(jnp.float32))


def _pa_fused_kernel_q(bt_ref, pos_ref, wlo_ref, whi_ref, q_ref, kn_ref,
                       vn_ref, kp_ref, vp_ref, ks_ref, vs_ref, o_ref,
                       ko_ref, vo_ref, kso_ref, vso_ref,
                       m_scr, l_scr, acc_scr, *, scale, page, W, n_pages):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    p = pl.program_id(1)
    pos = pos_ref[b]

    @pl.when(p == 0)
    def _init_and_window():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)
        _window_fold(m_scr, l_scr, acc_scr, q_ref, kn_ref, vn_ref,
                     scale, W)

    @pl.when(p * page < pos)
    def _pages():
        q = q_ref[0].astype(jnp.float32)
        s = _page_scores_q(q, kp_ref, ks_ref, scale)
        t = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page), 2)
        _fold(m_scr, l_scr, acc_scr, s, t < pos,
              _deq_block(vp_ref, vs_ref))

    in_write_range = jnp.logical_and(p >= wlo_ref[b], p <= whi_ref[b])

    @pl.when(in_write_range)
    def _scatter():
        kblk = kp_ref[0]                                # (H, page, hd)
        vblk = vp_ref[0]
        ksblk = ks_ref[0]                               # (H, page)
        vsblk = vs_ref[0]
        ridx = jax.lax.broadcasted_iota(jnp.int32, (1, page, 1), 1)
        sidx = jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
        for j in range(W):                              # W static, small
            tgt = pos + j - p * page
            hit = ridx == tgt                           # all-False if out
            shit = sidx == tgt
            kq, ksc = quantize_kv(kn_ref[0, :, j, :], kblk.dtype)
            vq, vsc = quantize_kv(vn_ref[0, :, j, :], vblk.dtype)
            kblk = jnp.where(hit, kq[:, None, :], kblk)
            vblk = jnp.where(hit, vq[:, None, :], vblk)
            ksblk = jnp.where(shit, ksc[:, None].astype(ksblk.dtype), ksblk)
            vsblk = jnp.where(shit, vsc[:, None].astype(vsblk.dtype), vsblk)
        ko_ref[0] = kblk
        vo_ref[0] = vblk
        kso_ref[0] = ksblk
        vso_ref[0] = vsblk

    @pl.when(p == n_pages - 1)
    def _fin():
        _finalize(o_ref, l_scr, acc_scr)


def _pa_window_kernel_q(bt_ref, pos_ref, q_ref, kn_ref, vn_ref, kp_ref,
                        vp_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr,
                        acc_scr, *, scale, page, W, n_pages):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    p = pl.program_id(1)
    pos = pos_ref[b]

    @pl.when(p == 0)
    def _init_and_window():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)
        _window_fold(m_scr, l_scr, acc_scr, q_ref, kn_ref, vn_ref,
                     scale, W)

    @pl.when(p * page < pos)
    def _pages():
        q = q_ref[0].astype(jnp.float32)
        s = _page_scores_q(q, kp_ref, ks_ref, scale)
        t = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page), 2)
        _fold(m_scr, l_scr, acc_scr, s, t < pos,
              _deq_block(vp_ref, vs_ref))

    @pl.when(p == n_pages - 1)
    def _fin():
        _finalize(o_ref, l_scr, acc_scr)


def _grid_spec(n_scalar, B, n_pages, in_specs, out_specs, H, Wp, hd):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_scalar, grid=(B, n_pages),
        in_specs=in_specs, out_specs=out_specs,
        scratch_shapes=[
            _vmem((H, Wp, _LANE), jnp.float32),   # running max m
            _vmem((H, Wp, _LANE), jnp.float32),   # running denominator l
            _vmem((H, Wp, hd), jnp.float32),      # f32 context accumulator
        ])


def _compiler_params(interpret: bool):
    if interpret:
        return None
    from jax.experimental.pallas import tpu as pltpu
    # both grid dims carry loop state (online-softmax accumulators and
    # the write-on-index-change page outputs) — never parallelizable
    return pltpu.TPUCompilerParams(
        dimension_semantics=("arbitrary", "arbitrary"))


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _pa_read_call(q, k_pages, v_pages, block_tables, lengths, *,
                  scale, interpret):
    from jax.experimental import pallas as pl

    B, H, Wp, hd = q.shape
    page = k_pages.shape[2]
    n_pages = block_tables.shape[1]
    kernel = functools.partial(_pa_read_kernel, scale=scale, page=page,
                               n_pages=n_pages)

    def _q_map(b, p, bt, lens):
        return (b, 0, 0, 0)

    def _page_map(b, p, bt, lens):
        return (bt[b, p], 0, 0, 0)

    def _o_map(b, p, bt, lens):
        return (b, 0, 0, 0)

    call = pl.pallas_call(
        kernel,
        grid_spec=_grid_spec(
            2, B, n_pages,
            in_specs=[
                pl.BlockSpec((1, H, Wp, hd), _q_map),
                pl.BlockSpec((1, H, page, hd), _page_map),
                pl.BlockSpec((1, H, page, hd), _page_map),
            ],
            out_specs=pl.BlockSpec((1, H, Wp, hd), _o_map),
            H=H, Wp=Wp, hd=hd),
        out_shape=jax.ShapeDtypeStruct((B, H, Wp, hd), q.dtype),
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )
    return call(block_tables, lengths, q, k_pages, v_pages)


@functools.partial(jax.jit, static_argnames=("W", "scale", "interpret"))
def _pa_fused_call(q, k_new, v_new, k_pages, v_pages, block_tables,
                   pos, wlo, whi, *, W, scale, interpret):
    from jax.experimental import pallas as pl

    B, H, Wp, hd = q.shape
    page = k_pages.shape[2]
    n_pages = block_tables.shape[1]
    kernel = functools.partial(_pa_fused_kernel, scale=scale, page=page,
                               W=W, n_pages=n_pages)

    def _row_map(b, p, bt, pos_, wlo_, whi_):
        return (b, 0, 0, 0)

    def _page_map(b, p, bt, pos_, wlo_, whi_):
        return (bt[b, p], 0, 0, 0)

    def _write_map(b, p, bt, pos_, wlo_, whi_):
        # pages outside the row's write range redirect to trash page 0:
        # Pallas only writes an output block back when its index CHANGES,
        # so the real page-pool writes stay O(1) per row per layer
        inr = jnp.logical_and(p >= wlo_[b], p <= whi_[b])
        return (jnp.where(inr, bt[b, p], 0), 0, 0, 0)

    pool_shape = jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype)
    call = pl.pallas_call(
        kernel,
        grid_spec=_grid_spec(
            4, B, n_pages,
            in_specs=[
                pl.BlockSpec((1, H, Wp, hd), _row_map),   # q
                pl.BlockSpec((1, H, Wp, hd), _row_map),   # k_new
                pl.BlockSpec((1, H, Wp, hd), _row_map),   # v_new
                pl.BlockSpec((1, H, page, hd), _page_map),  # k pages
                pl.BlockSpec((1, H, page, hd), _page_map),  # v pages
            ],
            out_specs=[
                pl.BlockSpec((1, H, Wp, hd), _row_map),
                pl.BlockSpec((1, H, page, hd), _write_map),
                pl.BlockSpec((1, H, page, hd), _write_map),
            ],
            H=H, Wp=Wp, hd=hd),
        out_shape=[jax.ShapeDtypeStruct((B, H, Wp, hd), q.dtype),
                   pool_shape, pool_shape],
        # operand indices COUNT the 4 scalar-prefetch args: k_pages is
        # operand 7, v_pages operand 8 — aliased onto outputs 1/2 so the
        # pool updates in place
        input_output_aliases={7: 1, 8: 2},
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )
    return call(block_tables, pos, wlo, whi, q, k_new, v_new,
                k_pages, v_pages)


@functools.partial(jax.jit, static_argnames=("W", "scale", "interpret"))
def _pa_window_read_call(q, k_new, v_new, k_pages, v_pages, block_tables,
                         pos, *, W, scale, interpret):
    from jax.experimental import pallas as pl

    B, H, Wp, hd = q.shape
    page = k_pages.shape[2]
    n_pages = block_tables.shape[1]
    kernel = functools.partial(_pa_window_kernel, scale=scale, page=page,
                               W=W, n_pages=n_pages)

    def _row_map(b, p, bt, pos_):
        return (b, 0, 0, 0)

    def _page_map(b, p, bt, pos_):
        return (bt[b, p], 0, 0, 0)

    call = pl.pallas_call(
        kernel,
        grid_spec=_grid_spec(
            2, B, n_pages,
            in_specs=[
                pl.BlockSpec((1, H, Wp, hd), _row_map),     # q
                pl.BlockSpec((1, H, Wp, hd), _row_map),     # k_new
                pl.BlockSpec((1, H, Wp, hd), _row_map),     # v_new
                pl.BlockSpec((1, H, page, hd), _page_map),  # k pages
                pl.BlockSpec((1, H, page, hd), _page_map),  # v pages
            ],
            out_specs=pl.BlockSpec((1, H, Wp, hd), _row_map),
            H=H, Wp=Wp, hd=hd),
        out_shape=jax.ShapeDtypeStruct((B, H, Wp, hd), q.dtype),
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )
    return call(block_tables, pos, q, k_new, v_new, k_pages, v_pages)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _pa_read_call_q(q, k_pages, v_pages, k_scale, v_scale, block_tables,
                    lengths, *, scale, interpret):
    from jax.experimental import pallas as pl

    B, H, Wp, hd = q.shape
    page = k_pages.shape[2]
    n_pages = block_tables.shape[1]
    kernel = functools.partial(_pa_read_kernel_q, scale=scale, page=page,
                               n_pages=n_pages)

    def _q_map(b, p, bt, lens):
        return (b, 0, 0, 0)

    def _page_map(b, p, bt, lens):
        return (bt[b, p], 0, 0, 0)

    def _scale_map(b, p, bt, lens):
        return (bt[b, p], 0, 0)

    call = pl.pallas_call(
        kernel,
        grid_spec=_grid_spec(
            2, B, n_pages,
            in_specs=[
                pl.BlockSpec((1, H, Wp, hd), _q_map),
                pl.BlockSpec((1, H, page, hd), _page_map),
                pl.BlockSpec((1, H, page, hd), _page_map),
                pl.BlockSpec((1, H, page), _scale_map),
                pl.BlockSpec((1, H, page), _scale_map),
            ],
            out_specs=pl.BlockSpec((1, H, Wp, hd), _q_map),
            H=H, Wp=Wp, hd=hd),
        out_shape=jax.ShapeDtypeStruct((B, H, Wp, hd), q.dtype),
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )
    return call(block_tables, lengths, q, k_pages, v_pages,
                k_scale, v_scale)


@functools.partial(jax.jit, static_argnames=("W", "scale", "interpret"))
def _pa_fused_call_q(q, k_new, v_new, k_pages, v_pages, k_scale, v_scale,
                     block_tables, pos, wlo, whi, *, W, scale, interpret):
    from jax.experimental import pallas as pl

    B, H, Wp, hd = q.shape
    page = k_pages.shape[2]
    n_pages = block_tables.shape[1]
    kernel = functools.partial(_pa_fused_kernel_q, scale=scale, page=page,
                               W=W, n_pages=n_pages)

    def _row_map(b, p, bt, pos_, wlo_, whi_):
        return (b, 0, 0, 0)

    def _page_map(b, p, bt, pos_, wlo_, whi_):
        return (bt[b, p], 0, 0, 0)

    def _scale_map(b, p, bt, pos_, wlo_, whi_):
        return (bt[b, p], 0, 0)

    def _write_map(b, p, bt, pos_, wlo_, whi_):
        inr = jnp.logical_and(p >= wlo_[b], p <= whi_[b])
        return (jnp.where(inr, bt[b, p], 0), 0, 0, 0)

    def _swrite_map(b, p, bt, pos_, wlo_, whi_):
        inr = jnp.logical_and(p >= wlo_[b], p <= whi_[b])
        return (jnp.where(inr, bt[b, p], 0), 0, 0)

    pool_shape = jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype)
    scale_shape = jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype)
    call = pl.pallas_call(
        kernel,
        grid_spec=_grid_spec(
            4, B, n_pages,
            in_specs=[
                pl.BlockSpec((1, H, Wp, hd), _row_map),     # q
                pl.BlockSpec((1, H, Wp, hd), _row_map),     # k_new
                pl.BlockSpec((1, H, Wp, hd), _row_map),     # v_new
                pl.BlockSpec((1, H, page, hd), _page_map),  # k pages
                pl.BlockSpec((1, H, page, hd), _page_map),  # v pages
                pl.BlockSpec((1, H, page), _scale_map),     # k scales
                pl.BlockSpec((1, H, page), _scale_map),     # v scales
            ],
            out_specs=[
                pl.BlockSpec((1, H, Wp, hd), _row_map),
                pl.BlockSpec((1, H, page, hd), _write_map),
                pl.BlockSpec((1, H, page, hd), _write_map),
                pl.BlockSpec((1, H, page), _swrite_map),
                pl.BlockSpec((1, H, page), _swrite_map),
            ],
            H=H, Wp=Wp, hd=hd),
        out_shape=[jax.ShapeDtypeStruct((B, H, Wp, hd), q.dtype),
                   pool_shape, pool_shape, scale_shape, scale_shape],
        # operand indices count the 4 scalar-prefetch args: k/v pages are
        # operands 7/8, their scale pools 9/10 — all four alias their
        # outputs so pages AND scales update in place through the same
        # trash-redirected write maps
        input_output_aliases={7: 1, 8: 2, 9: 3, 10: 4},
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )
    return call(block_tables, pos, wlo, whi, q, k_new, v_new,
                k_pages, v_pages, k_scale, v_scale)


@functools.partial(jax.jit, static_argnames=("W", "scale", "interpret"))
def _pa_window_read_call_q(q, k_new, v_new, k_pages, v_pages, k_scale,
                           v_scale, block_tables, pos, *, W, scale,
                           interpret):
    from jax.experimental import pallas as pl

    B, H, Wp, hd = q.shape
    page = k_pages.shape[2]
    n_pages = block_tables.shape[1]
    kernel = functools.partial(_pa_window_kernel_q, scale=scale, page=page,
                               W=W, n_pages=n_pages)

    def _row_map(b, p, bt, pos_):
        return (b, 0, 0, 0)

    def _page_map(b, p, bt, pos_):
        return (bt[b, p], 0, 0, 0)

    def _scale_map(b, p, bt, pos_):
        return (bt[b, p], 0, 0)

    call = pl.pallas_call(
        kernel,
        grid_spec=_grid_spec(
            2, B, n_pages,
            in_specs=[
                pl.BlockSpec((1, H, Wp, hd), _row_map),     # q
                pl.BlockSpec((1, H, Wp, hd), _row_map),     # k_new
                pl.BlockSpec((1, H, Wp, hd), _row_map),     # v_new
                pl.BlockSpec((1, H, page, hd), _page_map),  # k pages
                pl.BlockSpec((1, H, page, hd), _page_map),  # v pages
                pl.BlockSpec((1, H, page), _scale_map),     # k scales
                pl.BlockSpec((1, H, page), _scale_map),     # v scales
            ],
            out_specs=pl.BlockSpec((1, H, Wp, hd), _row_map),
            H=H, Wp=Wp, hd=hd),
        out_shape=jax.ShapeDtypeStruct((B, H, Wp, hd), q.dtype),
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )
    return call(block_tables, pos, q, k_new, v_new, k_pages, v_pages,
                k_scale, v_scale)


# ---- mesh mount (shard_map) -------------------------------------------------

def _mount_specs(slot_axis, head_axis):
    """The per-shard partition specs of the mount, derived mechanically
    from the engine's cache layout (``continuous.py``): batch rows over
    ``slot_axis`` ("dp" or None), heads over ``head_axis`` ("tp" or
    None), page/lane dims never split."""
    from jax.sharding import PartitionSpec as P
    row = P(slot_axis, head_axis, None, None)     # q / k_new / v_new / out
    pool = P(None, head_axis, None, None)         # the K/V page pools
    return row, pool, P(slot_axis, None), P(slot_axis)


def _scale_mount_spec(head_axis):
    """Partition spec of the (N, H, page) scale pools under a mesh —
    heads over ``head_axis``, like the page pools they scale."""
    from jax.sharding import PartitionSpec as P
    return P(None, head_axis, None)


def _check_mount(mesh, B, H, slot_axis, head_axis):
    if head_axis is not None:
        tp = mesh.shape[head_axis]
        if H % tp:
            raise ValueError(
                f"heads {H} not divisible by mesh {head_axis}={tp}")
    if slot_axis is not None:
        dp = mesh.shape[slot_axis]
        if B % dp:
            raise ValueError(
                f"batch {B} not divisible by mesh {slot_axis}={dp}")


def _pool_write_rows(pool, rows, block_tables, pos, active):
    """Scatter each row's W fresh K/V rows into its pages — the mesh
    path's page write, OUTSIDE the shard_map mount. Plain ``.at[].set``
    indexing that GSPMD partitions on the untouched head axis, writing
    bytes bit-identical to ``transformer._paged_writeback`` (same index
    math: physical page via the block table, offset ``pos+j`` mod page).
    Inactive rows redirect to trash page 0, like every other writer."""
    B, H, W, hd = rows.shape
    page = pool.shape[2]
    wpos = pos[:, None] + jnp.arange(W, dtype=jnp.int32)       # (B, W)
    phys = jnp.take_along_axis(block_tables, wpos // page, axis=1)
    if active is not None:
        phys = jnp.where(active[:, None], phys, 0)
    pf = phys.reshape(-1)
    of = (wpos % page).reshape(-1)
    vals = rows.transpose(0, 2, 1, 3).reshape(B * W, H, hd)
    return pool.at[pf, :, of].set(vals.astype(pool.dtype))


def _pool_write_rows_quant(pool, scales, rows, block_tables, pos, active):
    """Quantizing twin of :func:`_pool_write_rows`: the same index math,
    but each (H, hd) row goes through :func:`quantize_kv` first and its
    per-head scale lands in the ``(N, H, page)`` scale pool at the same
    (physical page, offset). Bit-identical bytes to the fused kernel's
    in-launch quantized scatter and to ``_paged_writeback``'s quant
    branch — same helper, same order of operations."""
    B, H, W, hd = rows.shape
    page = pool.shape[2]
    wpos = pos[:, None] + jnp.arange(W, dtype=jnp.int32)       # (B, W)
    phys = jnp.take_along_axis(block_tables, wpos // page, axis=1)
    if active is not None:
        phys = jnp.where(active[:, None], phys, 0)
    pf = phys.reshape(-1)
    of = (wpos % page).reshape(-1)
    vals = rows.transpose(0, 2, 1, 3).reshape(B * W, H, hd)
    q, sc = quantize_kv(vals, pool.dtype)
    return (pool.at[pf, :, of].set(q),
            scales.at[pf, :, of].set(sc.astype(scales.dtype)))


def _pad_window(t, Wp):
    W = t.shape[2]
    if W == Wp:
        return t
    return jnp.pad(t, ((0, 0), (0, 0), (0, Wp - W), (0, 0)))


def paged_attention(q, k_pages, v_pages, block_tables, lengths, *,
                    k_scale=None, v_scale=None,
                    scale: Optional[float] = None,
                    interpret: Optional[bool] = None,
                    mesh=None, slot_axis: Optional[str] = None,
                    head_axis: Optional[str] = None):
    """Read-only paged attention: queries ``q`` (B, H, W, hd) attend the
    first ``lengths[b]`` cached keys of row ``b``, read in place from
    the ``(N, H, page, hd)`` page pools through ``block_tables`` (B, P).
    A row with ``lengths[b] == 0`` yields zeros (the flash convention
    for fully-masked rows). Returns (B, H, W, hd) in ``q.dtype``.

    With ``k_scale``/``v_scale`` (the pool's ``(N, H, page)`` scale
    arrays) the pools hold QUANTIZED values: the scale blocks ride the
    same block-table index_map as their pages and the kernel dequantizes
    in VMEM — HBM only ever moves the quantized bytes.

    With ``mesh=`` the kernel is mounted via ``jax.shard_map``: heads
    split over ``head_axis`` (typically ``"tp"``) and rows optionally
    over ``slot_axis`` (``"dp"``); each shard runs the unchanged kernel
    over its head slice and the result carries the caller's row spec —
    no collective inside the mount."""
    if interpret is None:
        interpret = _auto_interpret()
    B, H, W, hd = q.shape
    if scale is None:
        scale = float(1.0 / math.sqrt(hd))
    Wp = _round_up(W, sublane_multiple(q.dtype))
    qp = _pad_window(q, Wp)
    bt = block_tables.astype(jnp.int32)
    lens = lengths.astype(jnp.int32)
    quant = k_scale is not None
    if mesh is None:
        if quant:
            out = _pa_read_call_q(qp, k_pages, v_pages, k_scale, v_scale,
                                  bt, lens, scale=scale,
                                  interpret=bool(interpret))
        else:
            out = _pa_read_call(qp, k_pages, v_pages, bt, lens,
                                scale=scale, interpret=bool(interpret))
        return out[:, :, :W]
    _check_mount(mesh, B, H, slot_axis, head_axis)
    from ..parallel.mesh import get_shard_map
    shard_map, unchecked = get_shard_map()
    row, pool, bt_spec, vec = _mount_specs(slot_axis, head_axis)
    if quant:
        spool = _scale_mount_spec(head_axis)

        def _shard_q(q_, kp_, vp_, ks_, vs_, bt_, len_):
            return _pa_read_call_q(q_, kp_, vp_, ks_, vs_, bt_, len_,
                                   scale=scale, interpret=bool(interpret))

        out = shard_map(_shard_q, mesh=mesh,
                        in_specs=(row, pool, pool, spool, spool,
                                  bt_spec, vec),
                        out_specs=row, **unchecked)(
            qp, k_pages, v_pages, k_scale, v_scale, bt, lens)
        return out[:, :, :W]

    def _shard(q_, kp_, vp_, bt_, len_):
        return _pa_read_call(q_, kp_, vp_, bt_, len_,
                             scale=scale, interpret=bool(interpret))

    out = shard_map(_shard, mesh=mesh,
                    in_specs=(row, pool, pool, bt_spec, vec),
                    out_specs=row, **unchecked)(
        qp, k_pages, v_pages, bt, lens)
    return out[:, :, :W]


def paged_attention_window(q, k_new, v_new, k_pages, v_pages,
                           block_tables, pos, *, active=None,
                           k_scale=None, v_scale=None,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           mesh=None, slot_axis: Optional[str] = None,
                           head_axis: Optional[str] = None):
    """Fused decode-window attention + page scatter, one launch.

    Row ``b``'s W queries sit at absolute positions
    ``pos[b] .. pos[b]+W-1``; they attend every cached key strictly
    below ``pos[b]`` (read in place from the pools) plus the window's
    own keys ``k_new``/``v_new`` (B, H, W, hd) under the in-window
    causal mask, and the fresh K/V rows are scattered into their pages
    in the same launch. Rows where ``active`` is False neither write
    their pages (their writes redirect to trash page 0) nor produce
    meaningful context. Returns ``(ctx, k_pages, v_pages)`` with the
    pool buffers updated in place (aliased).

    With ``k_scale``/``v_scale`` (the ``(N, H, page)`` scale pools) the
    page pools hold QUANTIZED values: page reads dequantize in VMEM and
    the in-launch scatter quantizes each fresh row through the
    sanctioned :func:`~mmlspark_tpu.ops.kv_quant.quantize_kv` before
    writing. The return grows to ``(ctx, k_pages, v_pages, k_scale,
    v_scale)`` — scales alias and update in place exactly like pages.

    With ``mesh=`` the attention mounts via ``jax.shard_map`` (heads
    over ``head_axis``, rows optionally over ``slot_axis``) in
    READ-ONLY form, and the fresh rows are scattered by
    :func:`_pool_write_rows` / :func:`_pool_write_rows_quant` outside
    the mount — the written bytes are bit-identical to the fused
    in-kernel scatter, so single-chip and mesh engines produce the same
    pages."""
    if interpret is None:
        interpret = _auto_interpret()
    B, H, W, hd = q.shape
    page = k_pages.shape[2]
    if scale is None:
        scale = float(1.0 / math.sqrt(hd))
    pos = pos.astype(jnp.int32)
    Wp = _round_up(W, sublane_multiple(q.dtype))
    bt = block_tables.astype(jnp.int32)
    quant = k_scale is not None
    if mesh is not None:
        _check_mount(mesh, B, H, slot_axis, head_axis)
        from ..parallel.mesh import get_shard_map
        shard_map, unchecked = get_shard_map()
        row, pool, bt_spec, vec = _mount_specs(slot_axis, head_axis)
        if quant:
            spool = _scale_mount_spec(head_axis)

            def _shard_q(q_, kn_, vn_, kp_, vp_, ks_, vs_, bt_, pos_):
                return _pa_window_read_call_q(
                    q_, kn_, vn_, kp_, vp_, ks_, vs_, bt_, pos_,
                    W=W, scale=scale, interpret=bool(interpret))

            ctx = shard_map(_shard_q, mesh=mesh,
                            in_specs=(row, row, row, pool, pool,
                                      spool, spool, bt_spec, vec),
                            out_specs=row, **unchecked)(
                _pad_window(q, Wp), _pad_window(k_new, Wp),
                _pad_window(v_new, Wp), k_pages, v_pages,
                k_scale, v_scale, bt, pos)
            kp, ks = _pool_write_rows_quant(k_pages, k_scale, k_new,
                                            bt, pos, active)
            vp, vs = _pool_write_rows_quant(v_pages, v_scale, v_new,
                                            bt, pos, active)
            return ctx[:, :, :W], kp, vp, ks, vs

        def _shard(q_, kn_, vn_, kp_, vp_, bt_, pos_):
            return _pa_window_read_call(q_, kn_, vn_, kp_, vp_, bt_, pos_,
                                        W=W, scale=scale,
                                        interpret=bool(interpret))

        ctx = shard_map(_shard, mesh=mesh,
                        in_specs=(row, row, row, pool, pool, bt_spec, vec),
                        out_specs=row, **unchecked)(
            _pad_window(q, Wp), _pad_window(k_new, Wp),
            _pad_window(v_new, Wp), k_pages, v_pages, bt, pos)
        kp = _pool_write_rows(k_pages, k_new, bt, pos, active)
        vp = _pool_write_rows(v_pages, v_new, bt, pos, active)
        return ctx[:, :, :W], kp, vp
    wlo = pos // page
    whi = (pos + W - 1) // page
    if active is not None:
        # an empty write range (lo > hi): the index_map sends every page
        # of the row to trash and the overlay never fires
        wlo = jnp.where(active, wlo, 1)
        whi = jnp.where(active, whi, 0)
    if quant:
        out, kp, vp, ks, vs = _pa_fused_call_q(
            _pad_window(q, Wp), _pad_window(k_new, Wp),
            _pad_window(v_new, Wp), k_pages, v_pages, k_scale, v_scale,
            bt, pos, wlo.astype(jnp.int32), whi.astype(jnp.int32),
            W=W, scale=scale, interpret=bool(interpret))
        return out[:, :, :W], kp, vp, ks, vs
    out, kp, vp = _pa_fused_call(
        _pad_window(q, Wp), _pad_window(k_new, Wp), _pad_window(v_new, Wp),
        k_pages, v_pages, bt, pos,
        wlo.astype(jnp.int32), whi.astype(jnp.int32),
        W=W, scale=scale, interpret=bool(interpret))
    return out[:, :, :W], kp, vp
