"""Append-only observation store for the autotuner.

One observation = one measured fact about the data plane: "model ``sig``
on placement ``p`` moved ``rows`` rows through bucket ``b`` under config
``(mini_batch_size, prefetch_depth, ladder)`` in ``seconds``, paying
``compiles`` compiles". :class:`~mmlspark_tpu.models.runner.BatchRunner`
emits them at drain time; the TVM-style measured sweep emits them per
probe; :func:`import_bench_records` backfills them from historical
``BENCH_r0*.json`` records, so the cost model's training set is the
repo's own perf trajectory.

Storage is one JSONL file (``observations.jsonl``) under
``MMLSPARK_TPU_TUNING_DIR`` — append-only and crash-tolerant by
construction: a torn final line (process killed mid-write) is counted and
skipped on load, never propagated. With no directory configured the store
is in-memory only: same-process decisions still work, nothing persists.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence

from ..observability import counter as _metric_counter
from ..observability import gauge as _metric_gauge

__all__ = ["TUNING_DIR_ENV", "Observation", "ObservationStore", "get_store",
           "set_store", "reset_store", "import_bench_records",
           "harvest_samples", "harvest_scorecard", "harvest_costs",
           "harvest_collectives"]

#: environment variable naming the persisted-observation directory (the
#: tuning analogue of ``MMLSPARK_TPU_COMPILE_CACHE_DIR``)
TUNING_DIR_ENV = "MMLSPARK_TPU_TUNING_DIR"

STORE_FILENAME = "observations.jsonl"

M_OBSERVATIONS = _metric_counter(
    "mmlspark_tuning_observations_total",
    "Autotuning observations recorded, by origin", ("source",))
M_CORRUPT_LINES = _metric_counter(
    "mmlspark_tuning_corrupt_lines_total",
    "Store lines skipped on load (torn writes, foreign garbage)")
M_STORE_ROWS = _metric_gauge(
    "mmlspark_tuning_store_rows",
    "Observations held by the process-global store (memory + disk)")

#: every observation row carries at least these keys
_REQUIRED = ("sig", "source")


class Observation(dict):
    """One measured sample (a dict with a validating constructor).

    Keys (``None`` where not applicable):

    * ``sig`` — model signature (content hash / import path);
    * ``placement`` — placement key string (chip, mesh, or ``default``);
    * ``source`` — ``runner`` (harvested from live traffic), ``probe``
      (measured sweep), or ``bench`` (imported bench record);
    * ``config`` — ``{"mini_batch_size", "prefetch_depth", "buckets"}``;
    * ``bucket`` / ``rows`` / ``batches`` — padded size, valid rows, and
      batch count of a per-bucket sample (``bucket=None`` for whole-run
      samples, which instead carry ``rows_per_sec``);
    * ``seconds`` / ``prep_seconds`` / ``compile_seconds`` / ``compiles``
      — where the time went;
    * ``t`` — unix timestamp.
    """

    def __init__(self, *, sig: str, source: str,
                 placement: str = "default",
                 config: Optional[dict] = None,
                 bucket: Optional[int] = None,
                 rows: int = 0, batches: int = 0,
                 seconds: float = 0.0, prep_seconds: float = 0.0,
                 compile_seconds: float = 0.0, compiles: int = 0,
                 rows_per_sec: Optional[float] = None,
                 t: Optional[float] = None):
        super().__init__(
            sig=str(sig), source=str(source), placement=str(placement),
            config=dict(config or {}),
            bucket=None if bucket is None else int(bucket),
            rows=int(rows), batches=int(batches),
            seconds=float(seconds), prep_seconds=float(prep_seconds),
            compile_seconds=float(compile_seconds), compiles=int(compiles),
            rows_per_sec=(None if rows_per_sec is None
                          else float(rows_per_sec)),
            t=float(t) if t is not None else time.time())


def _parse_line(line: str) -> Optional[dict]:
    line = line.strip()
    if not line:
        return None
    try:
        row = json.loads(line)
    except ValueError:
        raise
    if not isinstance(row, dict) or any(k not in row for k in _REQUIRED):
        raise ValueError("not an observation row")
    return row


class ObservationStore:
    """Append-only JSONL observation log with corrupt-line tolerance.

    ``path`` is a directory (the JSONL file lives inside it) or ``None``
    for a memory-only store. ``record`` appends one row (and one line,
    when persistent); ``rows`` filters by model signature / placement /
    source. Thread-safe: drains from concurrent partitions interleave at
    line granularity.
    """

    def __init__(self, path: Optional[str] = None):
        self.dir = path
        self._file = (os.path.join(path, STORE_FILENAME)
                      if path is not None else None)
        self._lock = threading.Lock()
        self._rows: List[dict] = []
        self.corrupt_lines = 0
        self._heal_newline = False
        if self._file is not None:
            os.makedirs(path, exist_ok=True)
            self._load()
        M_STORE_ROWS.set(len(self._rows))

    def _load(self) -> None:
        if not os.path.exists(self._file):
            return
        # a torn final line (no trailing newline) must not swallow the
        # next append — heal with a newline before the first write
        with open(self._file, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            if fh.tell() > 0:
                fh.seek(-1, os.SEEK_END)
                self._heal_newline = fh.read(1) != b"\n"
        with open(self._file, encoding="utf-8", errors="replace") as fh:
            for line in fh:
                try:
                    row = _parse_line(line)
                except ValueError:
                    # a torn tail or foreign garbage: count it, keep going
                    # — an append-only log must never be poisoned by one
                    # bad line
                    self.corrupt_lines += 1
                    M_CORRUPT_LINES.inc()
                    continue
                if row is not None:
                    self._rows.append(row)

    def record(self, obs: dict) -> None:
        if any(k not in obs for k in _REQUIRED):
            raise ValueError(f"observation missing one of {_REQUIRED}")
        row = dict(obs)
        with self._lock:
            self._rows.append(row)
            if self._file is not None:
                with open(self._file, "a", encoding="utf-8") as fh:
                    if self._heal_newline:
                        fh.write("\n")
                        self._heal_newline = False
                    fh.write(json.dumps(row, sort_keys=True) + "\n")
            M_STORE_ROWS.set(len(self._rows))
        M_OBSERVATIONS.inc(source=str(row.get("source", "unknown")))

    def record_many(self, observations: Iterable[dict]) -> int:
        n = 0
        for obs in observations:
            self.record(obs)
            n += 1
        return n

    def rows(self, sig: Optional[str] = None,
             placement: Optional[str] = None,
             source: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = list(self._rows)
        if sig is not None:
            out = [r for r in out if r.get("sig") == sig]
        if placement is not None:
            out = [r for r in out if r.get("placement") == placement]
        if source is not None:
            out = [r for r in out if r.get("source") == source]
        return out

    def signatures(self) -> List[str]:
        with self._lock:
            return sorted({r.get("sig") for r in self._rows})

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)


# -- the process-global store -------------------------------------------------

_store_lock = threading.Lock()
_store: Optional[ObservationStore] = None


def get_store() -> ObservationStore:
    """The process-global store, created on first use. Persistent when
    ``MMLSPARK_TPU_TUNING_DIR`` names a directory, memory-only otherwise
    (decisions still work within the process; nothing survives it)."""
    global _store
    with _store_lock:
        if _store is None:
            _store = ObservationStore(os.environ.get(TUNING_DIR_ENV) or None)
        return _store


def set_store(store: Optional[ObservationStore]) -> None:
    """Install a specific store (tests, embedding apps)."""
    global _store
    with _store_lock:
        _store = store


def reset_store() -> None:
    """Drop the global store so the next :func:`get_store` re-resolves the
    environment (test hook — mirrors ``observability.reset_all``)."""
    set_store(None)


# -- bench-record backfill ----------------------------------------------------

def _bench_observation(parsed: dict, source_file: str) -> Optional[dict]:
    """One whole-run observation from a bench JSON record (either the raw
    ``bench.py`` line or the driver wrapper holding it under ``parsed``)."""
    value = parsed.get("value")
    if not isinstance(value, (int, float)) or value <= 0:
        return None
    # headline bench config: BENCH_BATCH/BENCH_ROWS defaults unless the
    # record carries explicit fields (older records don't)
    cfg = {"mini_batch_size": int(parsed.get("batch", 512)),
           "prefetch_depth": int(parsed.get("prefetch_depth", 2)),
           "buckets": None}
    compile_s = 0.0
    compiles = 0
    stages = parsed.get("stage_counters") or {}
    if isinstance(stages.get("compile"), dict):
        compile_s = float(stages["compile"].get("seconds", 0.0))
        compiles = int(stages["compile"].get("calls", 0))
    return Observation(
        sig=str(parsed.get("metric", "bench")),
        source="bench",
        placement=str(parsed.get("device") or parsed.get("platform")
                      or "default"),
        config=cfg, rows_per_sec=float(value),
        compile_seconds=compile_s, compiles=compiles,
        t=os.path.getmtime(source_file)
        if os.path.exists(source_file) else None)


def _generation_observation(parsed: dict, source_file: str,
                            phase: str = "generation") -> Optional[dict]:
    """One observation from a bench record's ``generation`` phase (or the
    ``multichip_generation`` phase via ``phase=``).

    Carries ``paged_attn_impl`` (the attention implementation the engine
    decoded with — ``kernel`` or ``gather``) so the cost model can
    compare the two per signature across the trajectory, and
    ``mesh_shape`` (``"single"`` or ``"dp4xtp2"``-style) so a ladder
    learned on one chip topology is never transferred onto another."""
    gen = parsed.get(phase)
    if not isinstance(gen, dict):
        return None
    tps = gen.get("tok_per_sec")
    if not isinstance(tps, (int, float)) or tps <= 0:
        return None
    pa = gen.get("paged_attn") if isinstance(gen.get("paged_attn"),
                                             dict) else {}
    mesh = str(gen.get("mesh_shape") or "single")
    obs = Observation(
        sig="generation",
        source="bench",
        placement=str(parsed.get("device") or parsed.get("platform")
                      or "default"),
        config={"paged_attn_impl": pa.get("impl"),
                "kv_dtype": pa.get("kv_dtype"),
                "mesh_shape": mesh,
                "mini_batch_size": None, "prefetch_depth": None,
                "buckets": None},
        rows=int(gen.get("tokens", 0)),
        seconds=float(gen.get("wall_s", 0.0)),
        rows_per_sec=float(tps),
        t=os.path.getmtime(source_file)
        if os.path.exists(source_file) else None)
    # top-level for cheap grouping without digging into config
    obs["paged_attn_impl"] = pa.get("impl")
    obs["kv_dtype"] = pa.get("kv_dtype")
    obs["mesh_shape"] = mesh
    return obs


def import_bench_records(paths: Sequence[str],
                         store: Optional[ObservationStore] = None) -> int:
    """Backfill the store from ``BENCH_r0*.json`` records.

    Accepts both formats on disk: the driver wrapper
    (``{"rc", "tail", "parsed": {...}}``) and a raw ``bench.py`` record.
    Records without a positive headline value (crashed/truncated rounds)
    are skipped. Returns the number of observations imported; importing
    the same file twice appends twice — callers dedupe by wiping the
    store dir or importing once at bootstrap.
    """
    store = store if store is not None else get_store()
    n = 0
    for path in paths:
        try:
            with open(path, encoding="utf-8") as fh:
                raw = json.load(fh)
        except (OSError, ValueError):
            continue
        parsed = raw.get("parsed") if isinstance(raw.get("parsed"), dict) \
            else (raw if "value" in raw else None)
        if not parsed:
            continue
        obs = _bench_observation(parsed, path)
        if obs is not None:
            store.record(obs)
            n += 1
        for phase in ("generation", "multichip_generation"):
            gen = _generation_observation(parsed, path, phase=phase)
            if gen is not None:
                store.record(gen)
                n += 1
    return n


def harvest_samples(sig: str, placement: str, config: Dict,
                    samples: Iterable[dict],
                    store: Optional[ObservationStore] = None,
                    source: str = "runner") -> int:
    """Turn :class:`BatchRunner` per-bucket samples into store rows.

    ``samples`` is the runner's drain-time summary: one dict per bucket
    with ``bucket/rows/batches/seconds/prep_seconds/compile_seconds/
    compiles``. Shared by the live harvest and the measured sweep."""
    store = store if store is not None else get_store()
    n = 0
    for s in samples:
        store.record(Observation(
            sig=sig, source=source, placement=placement, config=config,
            bucket=s.get("bucket"), rows=s.get("rows", 0),
            batches=s.get("batches", 0), seconds=s.get("seconds", 0.0),
            prep_seconds=s.get("prep_seconds", 0.0),
            compile_seconds=s.get("compile_seconds", 0.0),
            compiles=s.get("compiles", 0),
            rows_per_sec=s.get("rows_per_sec")))
        n += 1
    return n


def harvest_scorecard(scorecard: dict,
                      store: Optional[ObservationStore] = None,
                      placement: str = "default") -> int:
    """Land an SLO scorecard (``observability.slo.SloTracker.scorecard``)
    in the store as one ``source="slo_scorecard"`` row per workload class.

    The cost model reads the same store, so quality facts (p99 under
    load, availability, burn rate) sit next to throughput facts and a
    config that wins on rows/sec but blows the latency objective can be
    penalised from data, not intuition. ``rows`` carries the class's
    cumulative request count and ``rows_per_sec`` its windowed request
    rate; the quality numbers ride under the extra ``slo`` key (the store
    accepts any JSON-safe extras beyond the required schema)."""
    store = store if store is not None else get_store()
    n = 0
    for cls in scorecard.get("classes", []):
        win = cls.get("window") or {}
        sig = "slo:{}/{}/{}".format(cls.get("transport", "?"),
                                    cls.get("route", "?"),
                                    cls.get("model", "?"))
        tenant = str(cls.get("tenant", "default"))
        if tenant != "default":
            # non-default tenants get their own sig; the default rides the
            # historical 3-part form so trajectories stay joinable
            sig += "@" + tenant
        obs = Observation(
            sig=sig,
            source="slo_scorecard", placement=placement,
            rows=int(cls.get("total", 0)),
            seconds=float(scorecard.get("window_seconds", 0.0)),
            rows_per_sec=win.get("rps"),
            t=scorecard.get("t"))
        obs["tenant"] = tenant
        # registry-resolved classes carry "name@version"; split so rows
        # are queryable by version and the cost model can tell a canary's
        # trajectory from its incumbent's
        model = str(cls.get("model", "?"))
        obs["model"] = model.partition("@")[0]
        obs["model_version"] = model.partition("@")[2] or None
        obs["slo"] = {
            "p50": cls.get("p50"), "p99": cls.get("p99"),
            "p999": cls.get("p999"),
            "availability": cls.get("availability"),
            "error_budget_burn": cls.get("error_budget_burn"),
            "errors_total": cls.get("errors_total"),
            "shed_total": cls.get("shed_total"),
            "p99_ok": cls.get("p99_ok"),
            "availability_ok": cls.get("availability_ok"),
        }
        store.record(obs)
        n += 1
    return n


def harvest_costs(snapshot: dict,
                  store: Optional[ObservationStore] = None,
                  placement: str = "default") -> int:
    """Land a cost-ledger snapshot (``observability.ledger.CostLedger.
    snapshot``) in the store as one ``source="cost_ledger"`` row per
    workload class.

    The cost model reads the same store, so attributed cost truth
    (device-seconds, transfer bytes, KV page-holds per class) sits next
    to throughput and SLO facts. ``rows`` carries the class's cumulative
    charge count and ``seconds`` its attributed device-seconds; the full
    per-resource breakdown rides under the extra ``cost`` key."""
    store = store if store is not None else get_store()
    n = 0
    for cls in snapshot.get("classes", []):
        res = cls.get("resources") or {}
        sig = "cost:{}/{}/{}".format(cls.get("transport", "?"),
                                     cls.get("route", "?"),
                                     cls.get("model", "?"))
        tenant = str(cls.get("tenant", "default"))
        if tenant != "default":
            sig += "@" + tenant
        obs = Observation(
            sig=sig, source="cost_ledger", placement=placement,
            rows=int(cls.get("charges", 0)),
            seconds=float(res.get("device_seconds", 0.0)),
            compile_seconds=float(res.get("compile_seconds", 0.0)),
            t=snapshot.get("t"))
        obs["tenant"] = tenant
        model = str(cls.get("model", "?"))
        obs["model"] = model.partition("@")[0]
        obs["model_version"] = model.partition("@")[2] or None
        obs["cost"] = dict(res)
        obs["weighted_cost"] = cls.get("weighted_cost")
        store.record(obs)
        n += 1
    return n


def harvest_collectives(table: dict,
                        store: Optional[ObservationStore] = None,
                        placement: str = "default") -> int:
    """Land a collective-audit table (``parallel.collective_audit.
    CollectiveAuditor.table``) in the store as one
    ``source="collective_audit"`` row per audited program.

    The cost model's ``collective_ms_per_tick_est`` so far extrapolated
    from mesh shape alone; these rows give it a *measured* per-program
    op-count basis — compiled-HLO truth, not topology arithmetic.
    ``rows`` carries the number of audited argument signatures; the
    per-kind ops/bytes breakdown rides under the extra ``collectives``
    key with ``ops_total``/``bytes_total`` roll-ups beside it."""
    store = store if store is not None else get_store()
    n = 0
    for prog in sorted(table):
        row = table[prog]
        kinds = {k: dict(v) for k, v in (row.get("kinds") or {}).items()}
        obs = Observation(sig="collective:" + prog,
                          source="collective_audit", placement=placement,
                          rows=int(row.get("sigs", 0)))
        obs["prog"] = prog
        obs["collectives"] = kinds
        obs["ops_total"] = sum(v.get("ops", 0) for v in kinds.values())
        obs["bytes_total"] = sum(v.get("bytes", 0)
                                 for v in kinds.values())
        store.record(obs)
        n += 1
    return n
