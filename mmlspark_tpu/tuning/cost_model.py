"""Fitted cost model + measured sweep: choose the data-plane config.

The model is deliberately small and stdlib-fitted (no solver deps): the
quantity that decides every knob is *seconds per dispatched batch as a
function of its padded size*, and the repo's own counters measure it
directly. Per observed bucket the store holds (rows, batches, seconds);
a least-squares line through ``(bucket, seconds/batch)`` gives

* ``alpha`` — the per-dispatch intercept (host sync + launch overhead:
  why fewer, larger batches win when the chip is fast), and
* ``beta`` — the per-padded-row slope (compute + transfer: why padding a
  66-row batch to 128 costs real time — the pad-overhead term).

Add a per-valid-row host-prep rate (coerce+pad, overlappable by
``prefetch_depth``) and a per-compile cost (amortized over the warm-up
vocabulary a candidate ladder implies) and every candidate
``(bucket ladder, mini_batch_size, prefetch_depth)`` gets a predicted
wall-clock for a given row-size histogram — "A Learned Performance Model
for TPUs" (arXiv:2008.01040) scoped down to the three knobs this data
plane actually exposes.

Where the store is cold the model abstains and
:func:`measured_sweep` runs the TVM loop (arXiv:1802.04799) instead:
propose a bounded candidate set, run each through the *real*
:class:`~mmlspark_tpu.models.runner.BatchRunner`, record every probe as
an observation — so the sweep both answers now and trains the model for
next time. Direct probe measurements of a config always outrank the
fitted prediction for that config.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from ..observability import counter as _metric_counter
from ..observability import tracing as _tracing
from ..ops.padding import bucket_size
from .observations import ObservationStore, get_store

__all__ = ["CostModel", "TuningDecision", "candidate_configs",
           "compare_kv_dtype", "compare_paged_attn", "measured_sweep",
           "predecessor_signature", "probe_budget", "resolve_tuning",
           "PROBE_BUDGET_ENV"]

#: bounds the measured sweep: at most this many candidate configs are run
PROBE_BUDGET_ENV = "MMLSPARK_TPU_TUNING_PROBES"
DEFAULT_PROBE_BUDGET = 6

M_DECISIONS = _metric_counter(
    "mmlspark_tuning_decisions_total",
    "Tuning decisions issued, by how they were reached", ("source",))
M_PROBES = _metric_counter(
    "mmlspark_tuning_probes_total",
    "Measured-sweep probe runs executed through the runner")

#: default compile cost (seconds) assumed before any compile was observed
_DEFAULT_COMPILE_COST = 0.05


def probe_budget() -> int:
    try:
        return max(1, int(os.environ.get(PROBE_BUDGET_ENV,
                                         DEFAULT_PROBE_BUDGET)))
    except ValueError:
        return DEFAULT_PROBE_BUDGET


def _config_key(mini_batch_size: int, prefetch_depth: int,
                buckets: Optional[Sequence[int]]) -> tuple:
    return (int(mini_batch_size), int(prefetch_depth),
            None if buckets is None else tuple(int(b) for b in buckets))


def _batch_sizes(n: int, m: int) -> List[int]:
    """Valid-row sizes of the batches a run of ``n`` rows produces."""
    if n <= 0:
        return []
    full, tail = divmod(n, m)
    return [m] * full + ([tail] if tail else [])


class TuningDecision:
    """The chosen config plus the evidence trail behind it."""

    def __init__(self, *, mini_batch_size: int, prefetch_depth: int,
                 buckets: Optional[Tuple[int, ...]],
                 warm_up_sizes: Tuple[int, ...],
                 vocabulary: Tuple[int, ...],
                 predicted_seconds: float,
                 predicted_rows_per_sec: Optional[float],
                 source: str, details: Optional[dict] = None):
        self.mini_batch_size = int(mini_batch_size)
        self.prefetch_depth = int(prefetch_depth)
        self.buckets = None if buckets is None \
            else tuple(int(b) for b in buckets)
        #: the batch sizes warm-up should request (valid-row sizes)
        self.warm_up_sizes = tuple(int(s) for s in warm_up_sizes)
        #: the padded buckets those sizes land in — the compile vocabulary
        self.vocabulary = tuple(int(v) for v in vocabulary)
        self.predicted_seconds = float(predicted_seconds)
        self.predicted_rows_per_sec = (
            None if predicted_rows_per_sec is None
            else float(predicted_rows_per_sec))
        self.source = str(source)   # "model" | "probe" | "default"
        self.details = dict(details or {})

    def as_dict(self) -> dict:
        return {"mini_batch_size": self.mini_batch_size,
                "prefetch_depth": self.prefetch_depth,
                "buckets": (None if self.buckets is None
                            else list(self.buckets)),
                "warm_up_sizes": list(self.warm_up_sizes),
                "vocabulary": list(self.vocabulary),
                "predicted_seconds": round(self.predicted_seconds, 6),
                "predicted_rows_per_sec": (
                    None if self.predicted_rows_per_sec is None
                    else round(self.predicted_rows_per_sec, 2)),
                "source": self.source}

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"TuningDecision(m={self.mini_batch_size}, "
                f"d={self.prefetch_depth}, buckets={self.buckets}, "
                f"source={self.source!r})")


def candidate_configs(histogram: Dict[int, int],
                      defaults: Tuple[int, int] = (64, 2),
                      depths: Sequence[int] = (0, 1, 2, 4),
                      ) -> List[Tuple[int, int, Optional[Tuple[int, ...]]]]:
    """The bounded candidate set ``[(mini_batch_size, depth, ladder)]``.

    Batch sizes: powers of two from 16 up to the largest run, the largest
    run itself (the no-split config), and the default. Ladders per batch
    size: ``None`` (power-of-two buckets) and the *exact* ladder — the
    sorted distinct batch sizes the config produces, i.e. zero padding.
    Deterministic order, so a fixed probe budget always sweeps the same
    prefix.
    """
    n_max = max((int(n) for n in histogram if int(n) > 0), default=64)
    sizes = {int(defaults[0]), n_max}
    m = 16
    while m < n_max:
        sizes.add(m)
        m <<= 1
    out: List[Tuple[int, int, Optional[Tuple[int, ...]]]] = []
    for size in sorted(sizes):
        produced = sorted({s for n, c in histogram.items() if c
                           for s in _batch_sizes(int(n), size)})
        exact = tuple(produced) if produced else None
        for depth in depths:
            out.append((size, int(depth), None))
            if exact is not None:
                out.append((size, int(depth), exact))
    return out


class CostModel:
    """Per-bucket linear throughput model fitted from store rows."""

    def __init__(self, *, alpha: float, beta: float, prep_rate: float,
                 compile_cost: float,
                 direct: Optional[Dict[tuple, float]] = None,
                 n_samples: int = 0):
        self.alpha = max(0.0, float(alpha))          # sec / dispatch
        self.beta = max(0.0, float(beta))            # sec / padded row
        self.prep_rate = max(0.0, float(prep_rate))  # sec / valid row
        self.compile_cost = max(0.0, float(compile_cost))
        #: config-key -> measured rows/sec (probe/bench rows): the ground
        #: truth that outranks the fit for configs that were actually run
        self.direct = dict(direct or {})
        self.n_samples = int(n_samples)

    # -- fitting -------------------------------------------------------------

    @classmethod
    def fit(cls, rows: Iterable[dict]) -> "CostModel":
        """Least-squares ``sec/batch = alpha + beta * bucket`` over the
        per-bucket samples, plus prep/compile rates and the direct
        config->rows/s table. Pure arithmetic — reproducible from the
        persisted rows alone."""
        per_bucket: Dict[int, Dict[str, float]] = {}
        prep_s = prep_rows = 0.0
        compile_s, compiles = 0.0, 0
        direct: Dict[tuple, List[float]] = {}
        n = 0
        for r in rows:
            n += 1
            compile_s += float(r.get("compile_seconds") or 0.0)
            compiles += int(r.get("compiles") or 0)
            rps = r.get("rows_per_sec")
            if rps:
                cfg = r.get("config") or {}
                key = _config_key(cfg.get("mini_batch_size", 0) or 0,
                                  cfg.get("prefetch_depth", 0) or 0,
                                  cfg.get("buckets"))
                direct.setdefault(key, []).append(float(rps))
            b = r.get("bucket")
            if b is None or not r.get("batches"):
                continue
            s = per_bucket.setdefault(
                int(b), {"seconds": 0.0, "batches": 0.0, "rows": 0.0})
            s["seconds"] += float(r.get("seconds") or 0.0)
            s["batches"] += float(r.get("batches") or 0)
            s["rows"] += float(r.get("rows") or 0)
            prep_s += float(r.get("prep_seconds") or 0.0)
            prep_rows += float(r.get("rows") or 0)
        pts = [(b, s["seconds"] / s["batches"], s["batches"])
               for b, s in sorted(per_bucket.items()) if s["batches"] > 0]
        alpha, beta = cls._weighted_lsq(pts)
        return cls(
            alpha=alpha, beta=beta,
            prep_rate=(prep_s / prep_rows) if prep_rows else 0.0,
            compile_cost=(compile_s / compiles) if compiles
            else _DEFAULT_COMPILE_COST,
            direct={k: sum(v) / len(v) for k, v in direct.items()},
            n_samples=n)

    @staticmethod
    def _weighted_lsq(pts: List[Tuple[float, float, float]]
                      ) -> Tuple[float, float]:
        """Weighted least squares of ``y = a + b x`` over (x, y, w);
        degenerate inputs degrade gracefully (one point: pure slope)."""
        if not pts:
            return 0.0, 0.0
        if len(pts) == 1:
            x, y, _ = pts[0]
            return 0.0, (y / x if x else 0.0)
        sw = sum(w for _, _, w in pts)
        mx = sum(w * x for x, _, w in pts) / sw
        my = sum(w * y for _, y, w in pts) / sw
        sxx = sum(w * (x - mx) ** 2 for x, _, w in pts)
        sxy = sum(w * (x - mx) * (y - my) for x, y, w in pts)
        if sxx <= 0.0:
            return 0.0, (my / mx if mx else 0.0)
        beta = sxy / sxx
        alpha = my - beta * mx
        if beta < 0.0:
            # noise-dominated: fall back to a flat per-dispatch cost
            return my, 0.0
        if alpha < 0.0:
            return 0.0, my / mx if mx else beta
        return alpha, beta

    # -- prediction ----------------------------------------------------------

    def predict_seconds(self, histogram: Dict[int, int],
                        mini_batch_size: int, prefetch_depth: int,
                        buckets: Optional[Sequence[int]] = None,
                        compile_weight: float = 1.0) -> float:
        """Predicted wall-clock to move the histogram's rows through a
        candidate config, warm-up compiles included at ``compile_weight``
        (lower it when the vocabulary amortizes over many processes via
        the persistent compile cache)."""
        direct = self.direct.get(
            _config_key(mini_batch_size, prefetch_depth, buckets))
        total_rows = sum(int(n) * int(c) for n, c in histogram.items())
        if direct and total_rows:
            return total_rows / direct
        m = max(1, int(mini_batch_size))
        d = max(0, int(prefetch_depth))
        total = 0.0
        vocab = set()
        for n, cnt in histogram.items():
            cnt = int(cnt)
            if cnt <= 0:
                continue
            run = 0.0
            for s in _batch_sizes(int(n), m):
                p = bucket_size(s, buckets)
                vocab.add(p)
                dev = self.alpha + self.beta * p
                prep = self.prep_rate * s
                # pipeline overlap: depth 0 serializes prep and device
                # work; each extra prepared batch hides more of the
                # smaller term, asymptoting to max(dev, prep)
                run += max(dev, prep) + min(dev, prep) / (d + 1.0)
            total += run * cnt
        total += compile_weight * self.compile_cost * len(vocab)
        return total

    def choose(self, histogram: Dict[int, int],
               defaults: Tuple[int, int] = (64, 2),
               candidates: Optional[List[tuple]] = None,
               compile_weight: float = 1.0) -> TuningDecision:
        """The best candidate config for the histogram (deterministic:
        ties break toward the earlier candidate, and the candidate list
        itself is deterministically ordered)."""
        cands = candidates if candidates is not None \
            else candidate_configs(histogram, defaults)
        total_rows = sum(int(n) * int(c) for n, c in histogram.items())
        best = None
        for m, d, ladder in cands:
            sec = self.predict_seconds(histogram, m, d, ladder,
                                       compile_weight=compile_weight)
            if best is None or sec < best[0]:
                best = (sec, m, d, ladder)
        sec, m, d, ladder = best
        sizes = sorted({s for n, c in histogram.items() if int(c) > 0
                        for s in _batch_sizes(int(n), m)})
        vocab = sorted({bucket_size(s, ladder) for s in sizes})
        key = _config_key(m, d, ladder)
        return TuningDecision(
            mini_batch_size=m, prefetch_depth=d, buckets=ladder,
            warm_up_sizes=tuple(sizes), vocabulary=tuple(vocab),
            predicted_seconds=sec,
            predicted_rows_per_sec=(total_rows / sec) if sec > 0 else None,
            source="probe" if key in self.direct else "model",
            details={"alpha": self.alpha, "beta": self.beta,
                     "prep_rate": self.prep_rate,
                     "compile_cost": self.compile_cost,
                     "n_samples": self.n_samples,
                     "n_candidates": len(cands)})


def compare_paged_attn(store: Optional[ObservationStore] = None,
                       sig: str = "generation") -> Dict[str, dict]:
    """Kernel-vs-gather generation throughput per placement.

    Groups the harvested ``generation`` observations (each stamped with
    ``paged_attn_impl`` by :func:`import_bench_records`) by placement and
    implementation, and reports mean tok/s plus the kernel/gather
    speedup where both impls have samples — the per-signature evidence
    ROADMAP item 4's cross-signature transfer will generalize from.
    Placements with no impl-stamped rows are omitted."""
    store = store if store is not None else get_store()
    by_placement: Dict[str, Dict[str, List[float]]] = {}
    for r in store.rows(sig=sig):
        impl = r.get("paged_attn_impl") or (r.get("config")
                                            or {}).get("paged_attn_impl")
        tps = r.get("rows_per_sec")
        if impl is None or not isinstance(tps, (int, float)) or tps <= 0:
            continue
        by_placement.setdefault(str(r.get("placement", "default")),
                                {}).setdefault(str(impl), []).append(
                                    float(tps))
    out: Dict[str, dict] = {}
    for placement, impls in by_placement.items():
        row = {impl: {"n": len(v),
                      "tok_per_sec_mean": round(sum(v) / len(v), 2)}
               for impl, v in impls.items()}
        k = row.get("kernel", {}).get("tok_per_sec_mean")
        g = row.get("gather", {}).get("tok_per_sec_mean")
        row["kernel_vs_gather_speedup"] = (
            round(k / g, 4) if k and g else None)
        out[placement] = row
    return out


def compare_kv_dtype(store: Optional[ObservationStore] = None,
                     sig: str = "generation") -> Dict[str, dict]:
    """Quantized-vs-bf16 KV-plane generation throughput per placement.

    The ``kv_dtype`` twin of :func:`compare_paged_attn`: groups the
    harvested generation observations by placement and the KV store
    dtype the engine decoded with (``int8``/``fp8``, or ``bf16`` when
    unstamped/None — the full-precision pool), and reports mean tok/s
    plus the quantized/bf16 speedup where both have samples. This is
    the evidence a CostModel candidate sweep over ``kv_dtype`` reads:
    on HBM-bound decode the ~2x byte reduction should show up here as
    realized tok/s, not just the counter-asserted byte ratio."""
    store = store if store is not None else get_store()
    by_placement: Dict[str, Dict[str, List[float]]] = {}
    for r in store.rows(sig=sig):
        dt = r.get("kv_dtype") or (r.get("config") or {}).get("kv_dtype")
        dt = str(dt) if dt else "bf16"
        tps = r.get("rows_per_sec")
        if not isinstance(tps, (int, float)) or tps <= 0:
            continue
        by_placement.setdefault(str(r.get("placement", "default")),
                                {}).setdefault(dt, []).append(float(tps))
    out: Dict[str, dict] = {}
    for placement, dts in by_placement.items():
        row = {dt: {"n": len(v),
                    "tok_per_sec_mean": round(sum(v) / len(v), 2)}
               for dt, v in dts.items()}
        q = (row.get("int8") or row.get("fp8")
             or {}).get("tok_per_sec_mean")
        b = row.get("bf16", {}).get("tok_per_sec_mean")
        row["quant_vs_bf16_speedup"] = (
            round(q / b, 4) if q and b else None)
        out[placement] = row
    return out


def predecessor_signature(sig: str,
                          known: Iterable[str]) -> Optional[str]:
    """The nearest sibling signature for a cold *versioned* model: a
    known signature naming the same model but a different ``@version``
    (sigs shaped ``cost:{transport}/{route}/{name@version}[@tenant]``).
    A freshly rolled-out version seeds its tuning decision from its
    predecessor's rows — the transfer move of "A Learned Performance
    Model for TPUs" (arXiv:2008.01040): variants of one workload share
    cost structure, so starting from the predecessor's fit beats
    starting cold. Picks the candidate sharing the longest common
    prefix with ``sig`` (ties: lexicographically greatest, i.e. the
    newest version string). None when ``sig`` is unversioned."""
    segment = sig.rsplit("/", 1)[-1]
    if "@" not in segment:
        return None
    # everything through the model name's '@' — siblings differ past it
    base = sig[:sig.rfind("/") + 1 + segment.index("@") + 1]
    cands = [s for s in known if s != sig and s.startswith(base)]
    if not cands:
        return None

    def common(a: str, b: str) -> int:
        n = 0
        for x, y in zip(a, b):
            if x != y:
                break
            n += 1
        return n

    cands.sort(key=lambda s: (common(s, sig), s))
    return cands[-1]


def _row_mesh(row: dict) -> str:
    """A stored row's mesh-shape stamp (``"single"`` when unstamped —
    every pre-mesh observation was measured on one chip)."""
    return str(row.get("mesh_shape")
               or (row.get("config") or {}).get("mesh_shape")
               or "single")


def resolve_tuning(sig: str, placement: str, histogram: Dict[int, int],
                   defaults: Tuple[int, int] = (64, 2),
                   store: Optional[ObservationStore] = None,
                   compile_weight: float = 1.0,
                   mesh_shape: Optional[str] = None
                   ) -> Optional[TuningDecision]:
    """Consult the store for ``sig`` and return a decision, or ``None``
    when the model is cold (no rows for this signature) — the caller
    keeps its defaults or runs :func:`measured_sweep`.

    Placement-matched rows are preferred; with none, every row of the
    signature trains the fit (a chip and its neighbor share cost
    structure — better than abstaining). When ``mesh_shape`` is given
    (``"single"``, ``"dp4xtp2"``, ...), only rows stamped with the SAME
    mesh shape train the fit — a single-chip ladder must never transfer
    onto a sharded engine (its per-tick cost surface includes ICI
    collectives a single chip never pays), and vice versa. A cold
    *versioned* signature (``name@version``) falls back to its
    :func:`predecessor_signature`'s rows before abstaining, preferring
    predecessor candidates whose rows match the mesh shape; such
    decisions carry ``source="transfer"`` and name the seed in
    ``details["seeded_from"]``."""
    store = store if store is not None else get_store()

    def _rows_for(s: str) -> list:
        got = (store.rows(sig=s, placement=placement)
               or store.rows(sig=s))
        if mesh_shape is not None:
            got = [r for r in got if _row_mesh(r) == mesh_shape]
        return got

    rows = _rows_for(sig)
    seeded_from = None
    if not rows:
        known = list(store.signatures())
        remaining = set(known)
        # walk predecessor candidates nearest-first until one has rows
        # (under a mesh_shape filter the nearest sibling may hold only
        # other-topology rows — the next-nearest can still seed)
        while remaining:
            pred = predecessor_signature(sig, remaining)
            if pred is None:
                break
            remaining.discard(pred)
            rows = _rows_for(pred)
            if rows:
                seeded_from = pred
                break
    if not rows:
        M_DECISIONS.inc(source="default")
        return None
    decision = CostModel.fit(rows).choose(histogram, defaults,
                                          compile_weight=compile_weight)
    if seeded_from is not None:
        decision.source = "transfer"
        decision.details["seeded_from"] = seeded_from
    if mesh_shape is not None:
        decision.details["mesh_shape"] = mesh_shape
    M_DECISIONS.inc(source=decision.source)
    _tracing.add_event("tuning_decision", sig=sig,
                       mini_batch_size=decision.mini_batch_size,
                       prefetch_depth=decision.prefetch_depth,
                       source=decision.source)
    return decision


def measured_sweep(make_runner: Callable, n_rows: int, *, sig: str,
                   placement: str = "default",
                   histogram: Optional[Dict[int, int]] = None,
                   candidates: Optional[List[tuple]] = None,
                   budget: Optional[int] = None,
                   store: Optional[ObservationStore] = None,
                   defaults: Tuple[int, int] = (64, 2),
                   ) -> TuningDecision:
    """TVM-style bounded sweep for a cold model: propose → run → record.

    ``make_runner(mini_batch_size, prefetch_depth, buckets)`` builds a
    :class:`BatchRunner` (over a representative workload) whose
    ``run_and_drain(n_rows)`` executes one probe; each probe's wall-clock
    lands in the store as a ``source="probe"`` observation (every probe
    is a future observation), and the decision is re-derived from the
    store through the normal fit — so deleting the model and re-fitting
    reproduces the same pick from the persisted rows alone.
    """
    import time as _time

    store = store if store is not None else get_store()
    histogram = histogram or {int(n_rows): 1}
    cands = candidates if candidates is not None \
        else candidate_configs(histogram, defaults)
    budget = budget if budget is not None else probe_budget()
    with _tracing.start_span("tuning.sweep", sig=sig,
                             candidates=min(len(cands), budget)):
        for m, d, ladder in cands[:max(1, int(budget))]:
            runner = make_runner(m, d, ladder)
            t0 = _time.perf_counter()
            runner.run_and_drain(int(n_rows))
            elapsed = _time.perf_counter() - t0
            M_PROBES.inc()
            store.record({
                "sig": sig, "source": "probe", "placement": placement,
                "config": {"mini_batch_size": int(m),
                           "prefetch_depth": int(d),
                           "buckets": (None if ladder is None
                                       else list(ladder))},
                "bucket": None, "rows": int(n_rows), "batches": 0,
                "seconds": elapsed, "prep_seconds": 0.0,
                "compile_seconds": 0.0, "compiles": 0,
                "rows_per_sec": (int(n_rows) / elapsed) if elapsed > 0
                else None,
                "t": _time.time()})
    decision = CostModel.fit(store.rows(sig=sig)).choose(
        histogram, defaults)
    M_DECISIONS.inc(source="probe")
    return decision
