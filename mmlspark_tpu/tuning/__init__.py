"""Measurement-driven autotuning of the compile/batch configuration.

Every hot-path knob of the inference data plane — the padding-bucket
ladder, ``mini_batch_size``, ``prefetch_depth``, the warm-up vocabulary —
used to be a hand-picked constant. This package replaces the constants
with *measured choices* (ROADMAP item 4; PAPERS.md: "A Learned Performance
Model for TPUs" arXiv:2008.01040, TVM's measure-and-search loop
arXiv:1802.04799):

* :mod:`~mmlspark_tpu.tuning.observations` — an append-only JSONL store of
  per-bucket throughput / pad-waste / compile-cost samples, harvested from
  every :class:`~mmlspark_tpu.models.runner.BatchRunner` drain and
  persisted under ``MMLSPARK_TPU_TUNING_DIR`` (alongside the compile
  cache). An importer backfills from historical ``BENCH_r0*.json``
  records so the very first process starts with the bench trajectory.
* :mod:`~mmlspark_tpu.tuning.cost_model` — a stdlib-fitted per-bucket
  linear cost model (dispatch intercept + per-padded-row slope, with
  pad-overhead and compile-amortization terms) that, given a row-size
  histogram, predicts wall-clock for a candidate ``(ladder,
  mini_batch_size, prefetch_depth)`` and returns the best one. Cold
  models fall back to a bounded measured sweep executed through the real
  runner, so every probe becomes a future observation.

Wiring: ``BatchRunner``, ``ONNXModel``/``JaxModel`` and ``ServingEngine``
accept ``tuning="auto"``; ``warm_up`` compiles exactly the chosen
vocabulary. See the "Measurement-driven autotuning" section of
docs/performance.md.
"""

from .cost_model import (CostModel, TuningDecision, candidate_configs,
                         compare_kv_dtype, compare_paged_attn,
                         measured_sweep, probe_budget, resolve_tuning)
from .observations import (TUNING_DIR_ENV, Observation, ObservationStore,
                           get_store, harvest_scorecard,
                           import_bench_records, reset_store, set_store)

__all__ = [
    "TUNING_DIR_ENV",
    "Observation",
    "ObservationStore",
    "get_store",
    "set_store",
    "reset_store",
    "import_bench_records",
    "harvest_scorecard",
    "CostModel",
    "TuningDecision",
    "candidate_configs",
    "compare_kv_dtype",
    "compare_paged_attn",
    "measured_sweep",
    "probe_budget",
    "resolve_tuning",
]
