# Hand-written stub (cost_model.py defines no PipelineStage, so codegen
# skips it); kept in sync by tpulint rule TPU006 (stub-drift).
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)

from .observations import ObservationStore

PROBE_BUDGET_ENV: str
DEFAULT_PROBE_BUDGET: int

def probe_budget() -> int: ...

class TuningDecision:
    mini_batch_size: int
    prefetch_depth: int
    buckets: Optional[Tuple[int, ...]]
    warm_up_sizes: Tuple[int, ...]
    vocabulary: Tuple[int, ...]
    predicted_seconds: float
    predicted_rows_per_sec: Optional[float]
    source: str
    details: Dict[str, Any]
    def __init__(self, *, mini_batch_size: int, prefetch_depth: int,
                 buckets: Optional[Tuple[int, ...]],
                 warm_up_sizes: Tuple[int, ...],
                 vocabulary: Tuple[int, ...], predicted_seconds: float,
                 predicted_rows_per_sec: Optional[float], source: str,
                 details: Optional[dict] = ...) -> None: ...
    def as_dict(self) -> dict: ...

def candidate_configs(histogram: Dict[int, int],
                      defaults: Tuple[int, int] = ...,
                      depths: Sequence[int] = ...,
                      ) -> List[Tuple[int, int, Optional[Tuple[int, ...]]]]: ...

class CostModel:
    alpha: float
    beta: float
    prep_rate: float
    compile_cost: float
    direct: Dict[tuple, float]
    n_samples: int
    def __init__(self, *, alpha: float, beta: float, prep_rate: float,
                 compile_cost: float,
                 direct: Optional[Dict[tuple, float]] = ...,
                 n_samples: int = ...) -> None: ...
    @classmethod
    def fit(cls, rows: Iterable[dict]) -> "CostModel": ...
    def predict_seconds(self, histogram: Dict[int, int],
                        mini_batch_size: int, prefetch_depth: int,
                        buckets: Optional[Sequence[int]] = ...,
                        compile_weight: float = ...) -> float: ...
    def choose(self, histogram: Dict[int, int],
               defaults: Tuple[int, int] = ...,
               candidates: Optional[List[tuple]] = ...,
               compile_weight: float = ...) -> TuningDecision: ...

def compare_kv_dtype(store: Optional[ObservationStore] = ...,
                     sig: str = ...) -> Dict[str, dict]: ...
def compare_paged_attn(store: Optional[ObservationStore] = ...,
                       sig: str = ...) -> Dict[str, dict]: ...
def resolve_tuning(sig: str, placement: str, histogram: Dict[int, int],
                   defaults: Tuple[int, int] = ...,
                   store: Optional[ObservationStore] = ...,
                   compile_weight: float = ...,
                   mesh_shape: Optional[str] = ...
                   ) -> Optional[TuningDecision]: ...
def measured_sweep(make_runner: Callable[..., Any], n_rows: int, *, sig: str,
                   placement: str = ...,
                   histogram: Optional[Dict[int, int]] = ...,
                   candidates: Optional[List[tuple]] = ...,
                   budget: Optional[int] = ...,
                   store: Optional[ObservationStore] = ...,
                   defaults: Tuple[int, int] = ...,
                   ) -> TuningDecision: ...

def __getattr__(name: str) -> Any: ...
