"""High-level train wrappers.

Parity surface: ``TrainClassifier`` (reference
``core/.../train/TrainClassifier.scala:50``) and ``TrainRegressor``
(``TrainRegressor.scala:21``): auto-featurize the input columns, index the
label, fit the wrapped learner, and return a model that featurizes + scores in
one transform.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasFeaturesCol, HasLabelCol, Param
from ..core.pipeline import Estimator, Model
from ..core.schema import set_label_metadata
from ..featurize import Featurize

__all__ = ["TrainClassifier", "TrainRegressor", "TrainedClassifierModel",
           "TrainedRegressorModel"]


class _TrainBase(Estimator, HasLabelCol, HasFeaturesCol):
    model = ComplexParam(default=None, doc="inner learner (Estimator)")
    num_features = Param(int, default=1 << 8, doc="hash space for text columns")

    def __init__(self, model: Optional[Estimator] = None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set(model=model)

    def _feature_cols(self, df: DataFrame):
        label = self.get("label_col")
        return [c for c in df.columns if c != label]

    def _fit_featurizer(self, df: DataFrame):
        feat = Featurize(self._feature_cols(df),
                         output_col=self.get("features_col"),
                         num_features=self.get("num_features"))
        fmodel = feat.fit(df)
        return fmodel, fmodel.transform(df)


class TrainClassifier(_TrainBase):
    """Auto-featurize + index labels + fit a classifier."""

    def _fit(self, df: DataFrame) -> "TrainedClassifierModel":
        from ..models.linear import LogisticRegression
        learner = self.get("model") or LogisticRegression()
        label = self.get("label_col")

        fmodel, featurized = self._fit_featurizer(df)
        classes, y = np.unique(df[label], return_inverse=True)
        featurized = featurized.with_column(label, y.astype(np.int64))
        featurized = set_label_metadata(featurized, label,
                                        num_classes=len(classes),
                                        classes=classes)
        learner = learner.copy({"features_col": self.get("features_col"),
                                "label_col": label})
        inner = learner.fit(featurized)
        m = TrainedClassifierModel()
        m.set(label_col=label, features_col=self.get("features_col"),
              featurizer=fmodel, inner_model=inner,
              classes=[c.item() if isinstance(c, np.generic) else c
                       for c in classes])
        return m


class TrainedClassifierModel(Model, HasLabelCol, HasFeaturesCol):
    featurizer = ComplexParam(default=None, doc="fitted FeaturizeModel")
    inner_model = ComplexParam(default=None, doc="fitted classifier")
    classes = Param(list, default=[], doc="original label values by index")

    def _transform(self, df: DataFrame) -> DataFrame:
        featurized = self.get("featurizer").transform(df)
        out = self.get("inner_model").transform(featurized)
        inner = self.get("inner_model")
        pred_col = inner.get("prediction_col") if inner.has_param(
            "prediction_col") else "prediction"
        classes = self.get("classes")
        if pred_col in out:
            pred = out[pred_col]
            if pred.dtype != object and np.issubdtype(pred.dtype, np.number):
                idx = np.clip(pred.astype(np.int64), 0, len(classes) - 1)
                mapped = np.asarray([classes[i] for i in idx])
                out = out.with_column(pred_col, mapped)
        return set_label_metadata(out, pred_col, num_classes=len(classes),
                                  classes=classes)


class TrainRegressor(_TrainBase):
    def _fit(self, df: DataFrame) -> "TrainedRegressorModel":
        from ..models.linear import LinearRegression
        learner = self.get("model") or LinearRegression()
        label = self.get("label_col")
        fmodel, featurized = self._fit_featurizer(df)
        learner = learner.copy({"features_col": self.get("features_col"),
                                "label_col": label})
        inner = learner.fit(featurized)
        m = TrainedRegressorModel()
        m.set(label_col=label, features_col=self.get("features_col"),
              featurizer=fmodel, inner_model=inner)
        return m


class TrainedRegressorModel(Model, HasLabelCol, HasFeaturesCol):
    featurizer = ComplexParam(default=None, doc="fitted FeaturizeModel")
    inner_model = ComplexParam(default=None, doc="fitted regressor")

    def _transform(self, df: DataFrame) -> DataFrame:
        featurized = self.get("featurizer").transform(df)
        return self.get("inner_model").transform(featurized)
