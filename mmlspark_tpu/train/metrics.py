"""Model evaluation metrics.

Parity surface: ``ComputeModelStatistics`` (reference
``core/.../train/ComputeModelStatistics.scala:59-474``: confusion matrix,
accuracy/precision/recall, AUC via ``MetricsLogger``; regression MSE/RMSE/R²/MAE)
and ``ComputePerInstanceStatistics`` (``ComputePerInstanceStatistics.scala:45``:
per-row losses). Metric math runs as vectorized array ops.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasLabelCol, Param
from ..core.pipeline import Transformer

__all__ = ["ComputeModelStatistics", "ComputePerInstanceStatistics",
           "roc_auc", "confusion_matrix"]


def confusion_matrix(y_true: np.ndarray, y_pred: np.ndarray, n: int) -> np.ndarray:
    cm = np.zeros((n, n), dtype=np.int64)
    np.add.at(cm, (y_true.astype(np.int64), y_pred.astype(np.int64)), 1)
    return cm


def roc_auc(y_true: np.ndarray, scores: np.ndarray) -> float:
    """AUC by the rank statistic (equivalent to trapezoidal ROC integration)."""
    y = np.asarray(y_true).astype(bool)
    s = np.asarray(scores, dtype=np.float64)
    n_pos, n_neg = int(y.sum()), int((~y).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(len(s), dtype=np.float64)
    ranks[order] = np.arange(1, len(s) + 1)
    # tie correction: average ranks within equal scores
    sorted_s = s[order]
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and sorted_s[j + 1] == sorted_s[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + j + 2) / 2.0
        i = j + 1
    return float((ranks[y].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


class ComputeModelStatistics(Transformer, HasLabelCol):
    """Emit a one-row DataFrame of metrics for a scored frame."""

    scores_col = Param(str, default="prediction", doc="prediction column")
    scored_probabilities_col = Param(str, default="probability",
                                     doc="probability column (classification)")
    evaluation_metric = Param(str, default="auto",
                              choices=["auto", "classification", "regression"],
                              doc="task type; auto sniffs the columns")

    def _task(self, df: DataFrame) -> str:
        mode = self.get("evaluation_metric")
        if mode != "auto":
            return mode
        return ("classification"
                if self.get("scored_probabilities_col") in df else "regression")

    def _transform(self, df: DataFrame) -> DataFrame:
        y = df[self.get("label_col")]
        pred = df[self.get("scores_col")]
        if self._task(df) == "classification":
            classes, y_idx = np.unique(y, return_inverse=True)
            table = {c.item() if isinstance(c, np.generic) else c: i
                     for i, c in enumerate(classes)}
            p_idx = np.asarray([table.get(
                v.item() if isinstance(v, np.generic) else v, -1)
                for v in pred])
            n = len(classes)
            cm = confusion_matrix(y_idx, np.clip(p_idx, 0, n - 1), n)
            acc = float((y_idx == p_idx).mean())
            tp = np.diag(cm).astype(np.float64)
            prec = float(np.nanmean(tp / np.maximum(cm.sum(axis=0), 1)))
            rec = float(np.nanmean(tp / np.maximum(cm.sum(axis=1), 1)))
            row = {"accuracy": acc, "precision": prec, "recall": rec,
                   "confusion_matrix": cm}
            prob_col = self.get("scored_probabilities_col")
            if n == 2 and prob_col in df:
                probs = df[prob_col]
                pos_scores = np.asarray([np.asarray(p).ravel()[-1]
                                         for p in probs])
                row["AUC"] = roc_auc(y_idx == 1, pos_scores)
            return DataFrame.from_rows([row])
        yf = y.astype(np.float64)
        pf = pred.astype(np.float64)
        err = yf - pf
        mse = float(np.mean(err ** 2))
        ss_tot = float(np.sum((yf - yf.mean()) ** 2))
        return DataFrame.from_rows([{
            "mean_squared_error": mse,
            "root_mean_squared_error": float(np.sqrt(mse)),
            "mean_absolute_error": float(np.mean(np.abs(err))),
            "R^2": 1.0 - float(np.sum(err ** 2)) / ss_tot if ss_tot else
            float("nan"),
        }])


class ComputePerInstanceStatistics(Transformer, HasLabelCol):
    """Append per-row loss columns (reference
    ``ComputePerInstanceStatistics.scala:45``)."""

    scores_col = Param(str, default="prediction", doc="prediction column")
    scored_probabilities_col = Param(str, default="probability",
                                     doc="probability column (classification)")
    evaluation_metric = Param(str, default="auto",
                              choices=["auto", "classification", "regression"],
                              doc="task type")

    def _transform(self, df: DataFrame) -> DataFrame:
        y = df[self.get("label_col")]
        prob_col = self.get("scored_probabilities_col")
        is_cls = (self.get("evaluation_metric") == "classification"
                  or (self.get("evaluation_metric") == "auto" and prob_col in df))
        if is_cls:
            classes, y_idx = np.unique(y, return_inverse=True)
            probs = np.stack([np.asarray(p).ravel() for p in df[prob_col]])
            p_true = probs[np.arange(len(y_idx)), np.clip(y_idx, 0,
                                                          probs.shape[1] - 1)]
            return df.with_column("log_loss", -np.log(np.maximum(p_true, 1e-15)))
        pf = df[self.get("scores_col")].astype(np.float64)
        err = y.astype(np.float64) - pf
        return (df.with_column("L1_loss", np.abs(err))
                  .with_column("L2_loss", err ** 2))
