"""Model evaluation metrics.

Parity surface: ``ComputeModelStatistics`` (reference
``core/.../train/ComputeModelStatistics.scala:59-474``: confusion matrix,
accuracy/precision/recall, AUC via ``MetricsLogger``; regression MSE/RMSE/R²/MAE)
and ``ComputePerInstanceStatistics`` (``ComputePerInstanceStatistics.scala:45``:
per-row losses). Metric math runs as vectorized array ops.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasLabelCol, Param
from ..core.pipeline import Transformer

__all__ = ["ComputeModelStatistics", "ComputePerInstanceStatistics",
           "roc_auc", "confusion_matrix"]


def _plain(v):
    return v.item() if isinstance(v, np.generic) else v


def _class_order(df: DataFrame, scores_col: str, label_col: str,
                 y: np.ndarray, pred: np.ndarray) -> list:
    """Class values in *model* order: the label metadata a trained model
    attaches to its prediction column wins; otherwise the sorted union of
    observed labels and predictions (an eval frame may contain only a subset
    of the model's classes)."""
    from ..core.schema import get_label_metadata
    seen = {_plain(v) for v in y} | {_plain(v) for v in pred}
    for col in (scores_col, label_col):
        meta = get_label_metadata(df, col)
        if meta.get("classes"):
            classes = [_plain(c) for c in meta["classes"]]
            # tolerate labels the model never saw: append after model classes
            extras = sorted(seen - set(classes),
                            key=lambda v: (str(type(v)), v))
            return classes + extras
    return sorted(seen, key=lambda v: (str(type(v)), v))


def confusion_matrix(y_true: np.ndarray, y_pred: np.ndarray, n: int) -> np.ndarray:
    cm = np.zeros((n, n), dtype=np.int64)
    np.add.at(cm, (y_true.astype(np.int64), y_pred.astype(np.int64)), 1)
    return cm


def roc_auc(y_true: np.ndarray, scores: np.ndarray) -> float:
    """AUC by the rank statistic (equivalent to trapezoidal ROC integration)."""
    y = np.asarray(y_true).astype(bool)
    s = np.asarray(scores, dtype=np.float64)
    n_pos, n_neg = int(y.sum()), int((~y).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(len(s), dtype=np.float64)
    ranks[order] = np.arange(1, len(s) + 1)
    # tie correction: average ranks within equal scores
    sorted_s = s[order]
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and sorted_s[j + 1] == sorted_s[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + j + 2) / 2.0
        i = j + 1
    return float((ranks[y].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


class ComputeModelStatistics(Transformer, HasLabelCol):
    """Emit a one-row DataFrame of metrics for a scored frame."""

    scores_col = Param(str, default="prediction", doc="prediction column")
    scored_probabilities_col = Param(str, default="probability",
                                     doc="probability column (classification)")
    evaluation_metric = Param(str, default="auto",
                              choices=["auto", "classification", "regression"],
                              doc="task type; auto sniffs the columns")

    def _task(self, df: DataFrame) -> str:
        mode = self.get("evaluation_metric")
        if mode != "auto":
            return mode
        return ("classification"
                if self.get("scored_probabilities_col") in df else "regression")

    def _transform(self, df: DataFrame) -> DataFrame:
        y = df[self.get("label_col")]
        pred = df[self.get("scores_col")]
        if self._task(df) == "classification":
            classes = _class_order(df, self.get("scores_col"),
                                   self.get("label_col"), y, pred)
            table = {c: i for i, c in enumerate(classes)}
            y_idx = np.asarray([table[_plain(v)] for v in y])
            p_idx = np.asarray([table[_plain(v)] for v in pred])
            n = len(classes)
            cm = confusion_matrix(y_idx, p_idx, n)
            acc = float((y_idx == p_idx).mean())
            tp = np.diag(cm).astype(np.float64)
            prec = float(np.nanmean(tp / np.maximum(cm.sum(axis=0), 1)))
            rec = float(np.nanmean(tp / np.maximum(cm.sum(axis=1), 1)))
            row = {"accuracy": acc, "precision": prec, "recall": rec,
                   "confusion_matrix": cm}
            prob_col = self.get("scored_probabilities_col")
            if n == 2 and prob_col in df:
                probs = df[prob_col]
                pos_scores = np.asarray([np.asarray(p).ravel()[-1]
                                         for p in probs])
                row["AUC"] = roc_auc(y_idx == 1, pos_scores)
            return DataFrame.from_rows([row])
        yf = y.astype(np.float64)
        pf = pred.astype(np.float64)
        err = yf - pf
        mse = float(np.mean(err ** 2))
        ss_tot = float(np.sum((yf - yf.mean()) ** 2))
        return DataFrame.from_rows([{
            "mean_squared_error": mse,
            "root_mean_squared_error": float(np.sqrt(mse)),
            "mean_absolute_error": float(np.mean(np.abs(err))),
            "R^2": 1.0 - float(np.sum(err ** 2)) / ss_tot if ss_tot else
            float("nan"),
        }])


class ComputePerInstanceStatistics(Transformer, HasLabelCol):
    """Append per-row loss columns (reference
    ``ComputePerInstanceStatistics.scala:45``)."""

    scores_col = Param(str, default="prediction", doc="prediction column")
    scored_probabilities_col = Param(str, default="probability",
                                     doc="probability column (classification)")
    evaluation_metric = Param(str, default="auto",
                              choices=["auto", "classification", "regression"],
                              doc="task type")

    def _transform(self, df: DataFrame) -> DataFrame:
        y = df[self.get("label_col")]
        prob_col = self.get("scored_probabilities_col")
        is_cls = (self.get("evaluation_metric") == "classification"
                  or (self.get("evaluation_metric") == "auto" and prob_col in df))
        if is_cls:
            pred = df[self.get("scores_col")] if self.get("scores_col") in df else y
            classes = _class_order(df, self.get("scores_col"),
                                   self.get("label_col"), y, pred)
            table = {c: i for i, c in enumerate(classes)}
            y_idx = np.asarray([table[_plain(v)] for v in y])
            probs = np.stack([np.asarray(p).ravel() for p in df[prob_col]])
            from ..core.schema import get_label_metadata
            has_meta = any(get_label_metadata(df, c).get("classes")
                           for c in (self.get("scores_col"),
                                     self.get("label_col")))
            if probs.shape[1] != len(classes) and not has_meta:
                raise ValueError(
                    f"probability vectors have {probs.shape[1]} entries but "
                    f"{len(classes)} distinct label/prediction values were "
                    "observed; without label metadata the class order is "
                    "ambiguous — attach it via set_label_metadata")
            if probs.shape[1] < len(classes):
                raise ValueError(
                    f"probability column has {probs.shape[1]} entries but "
                    f"{len(classes)} classes are in play")
            p_true = probs[np.arange(len(y_idx)), y_idx]
            return df.with_column("log_loss", -np.log(np.maximum(p_true, 1e-15)))
        pf = df[self.get("scores_col")].astype(np.float64)
        err = y.astype(np.float64) - pf
        return (df.with_column("L1_loss", np.abs(err))
                  .with_column("L2_loss", err ** 2))
