from .metrics import (ComputeModelStatistics, ComputePerInstanceStatistics,
                      confusion_matrix, roc_auc)
from .train import (TrainClassifier, TrainedClassifierModel,
                    TrainedRegressorModel, TrainRegressor)

__all__ = [
    "TrainClassifier", "TrainRegressor",
    "TrainedClassifierModel", "TrainedRegressorModel",
    "ComputeModelStatistics", "ComputePerInstanceStatistics",
    "confusion_matrix", "roc_auc",
]
