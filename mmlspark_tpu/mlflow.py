"""Portable model artifacts: an mlflow-compatible save/log/load round-trip.

Parity surface: the reference's generated PyTest fuzzing saves every fitted
model through mlflow and loads it back as a generic pyfunc
(``core/src/test/scala/com/microsoft/azure/synapse/ml/core/test/fuzzing/
Fuzzing.scala:135-140`` — ``mlflow.spark.save_model`` /
``mlflow.pyfunc.load_model`` → ``loaded.predict(df)``). The capability that
proves is a *self-describing, externally-loadable* model directory with a
generic predict entry — independent of the class that produced it.

Layout (mlflow's own on-disk format, so a genuine mlflow install can load
these artifacts via its pyfunc flavor without this package being mlflow-aware
at save time):

    <path>/MLmodel            YAML descriptor: flavors, uuid, signature
    <path>/stage/             the stage tree (core.serialize format)
    <path>/requirements.txt   pip requirements of the loader
    <path>/input_example.json optional sampled input

The ``python_function`` flavor points ``loader_module`` at THIS module, whose
:func:`_load_pyfunc` is the exact hook ``mlflow.pyfunc.load_model`` calls; the
``mmlspark_tpu`` flavor records the stage class for direct
:func:`load_model` loading without mlflow installed (this image has none).
"""

from __future__ import annotations

import json
import os
import uuid as _uuid
from typing import Optional

import numpy as np

from .core import DataFrame
from .core.pipeline import PipelineStage
from .core.serialize import load_stage, save_stage

__all__ = ["save_model", "log_model", "load_model", "PyFuncModel",
           "infer_signature"]

_FLAVOR = "mmlspark_tpu"


def _col_spec(name, values):
    arr = values if isinstance(values, np.ndarray) else np.asarray(values)
    if arr.dtype == object and len(arr):
        inner = np.asarray(arr[0])
        kind = (f"array<{inner.dtype.name}>"
                if inner.dtype != object else "object")
        return {"name": name, "type": kind}
    return {"name": name, "type": arr.dtype.name}


def infer_signature(inputs: DataFrame, outputs: Optional[DataFrame] = None):
    """Column name/dtype schema of inputs (and outputs) — the role of
    ``mlflow.models.infer_signature``."""
    sig = {"inputs": [_col_spec(c, inputs[c]) for c in inputs.columns]}
    if outputs is not None:
        sig["outputs"] = [_col_spec(c, outputs[c]) for c in outputs.columns]
    return sig


def _yaml_dump(obj, indent=0) -> str:
    """Minimal YAML emitter (mappings/lists/scalars) — avoids a hard yaml
    dependency in the library (tests use PyYAML to parse these back)."""
    pad = "  " * indent
    out = []
    if isinstance(obj, dict):
        for k, v in obj.items():
            if isinstance(v, (dict, list)) and v:
                out.append(f"{pad}{k}:")
                out.append(_yaml_dump(v, indent + 1))
            else:
                out.append(f"{pad}{k}: {_yaml_scalar(v)}")
    elif isinstance(obj, list):
        for v in obj:
            if isinstance(v, (dict, list)) and v:
                first, *rest = _yaml_dump(v, indent + 1).splitlines()
                out.append(f"{pad}- {first.strip()}")
                out.extend(rest)
            else:
                out.append(f"{pad}- {_yaml_scalar(v)}")
    else:
        out.append(f"{pad}{_yaml_scalar(obj)}")
    return "\n".join(out)


def _yaml_scalar(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    s = str(v)
    if s == "" or any(ch in s for ch in ":#{}[]\n'\"") or s.strip() != s:
        return json.dumps(s)
    return s


def save_model(model: PipelineStage, path: str,
               input_example: Optional[DataFrame] = None,
               signature: Optional[dict] = None,
               overwrite: bool = False) -> None:
    """Write ``model`` (any Transformer/fitted Model/PipelineModel) as a
    self-describing artifact directory at ``path``.

    An existing non-empty ``path`` is refused (genuine mlflow does the
    same) unless ``overwrite=True`` — re-saving into a populated directory
    would leave stale files (an old input_example.json, say) pairing with
    the new model. Overwrite is atomic: the new artifact is built in a
    sibling temp dir and swapped in, so a mid-save failure cannot destroy
    the previous good artifact."""
    existing = os.path.isdir(path) and bool(os.listdir(path))
    if existing and not overwrite:
        raise FileExistsError(
            f"refusing to save into non-empty {path!r}; pass "
            "overwrite=True to replace it")
    if signature is None and input_example is not None:
        try:
            signature = infer_signature(input_example,
                                        model.transform(input_example))
        except Exception:
            signature = infer_signature(input_example)
    if existing:
        import shutil
        import tempfile
        parent = os.path.dirname(os.path.abspath(path)) or "."
        tmp = tempfile.mkdtemp(prefix=".mlartifact_", dir=parent)
        try:
            _write_artifact(model, tmp, input_example, signature,
                            name=os.path.basename(path))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        shutil.rmtree(path)
        os.replace(tmp, path)
    else:
        _write_artifact(model, path, input_example, signature,
                        name=os.path.basename(path))


def _write_artifact(model: PipelineStage, path: str,
                    input_example: Optional[DataFrame],
                    signature: Optional[dict], name: str) -> None:
    os.makedirs(path, exist_ok=True)
    save_stage(model, os.path.join(path, "stage"))
    mlmodel = {
        "artifact_path": name,
        "flavors": {
            "python_function": {
                "loader_module": "mmlspark_tpu.mlflow",
                "data": "stage",
                "env": "requirements.txt",
            },
            _FLAVOR: {
                "stage_class": f"{type(model).__module__}:"
                               f"{type(model).__qualname__}",
                "format_version": 1,
                "data": "stage",
            },
        },
        "model_uuid": _uuid.uuid4().hex,
    }
    if signature is not None:
        # mlflow stores signature columns as json-encoded strings
        mlmodel["signature"] = {
            k: json.dumps(v) for k, v in signature.items()}
    with open(os.path.join(path, "MLmodel"), "w", encoding="utf-8") as fh:
        fh.write(_yaml_dump(mlmodel) + "\n")
    with open(os.path.join(path, "requirements.txt"), "w",
              encoding="utf-8") as fh:
        fh.write("mmlspark-tpu\njax\nnumpy\n")
    if input_example is not None:
        ex = {c: np.asarray(input_example[c][:5]).tolist()
              for c in input_example.columns
              if np.asarray(input_example[c][:1]).dtype != object}
        with open(os.path.join(path, "input_example.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(ex, fh)


def log_model(model: PipelineStage, artifact_path: str,
              tracking_dir: Optional[str] = None,
              input_example: Optional[DataFrame] = None) -> str:
    """File-store ``log_model``: saves under
    ``<tracking_dir>/<run_id>/artifacts/<artifact_path>`` (mlflow's local
    ``mlruns`` layout) and returns that path. ``tracking_dir`` defaults to
    ``$MLFLOW_TRACKING_DIR`` or ``./mlruns/0``."""
    tracking_dir = tracking_dir or os.environ.get(
        "MLFLOW_TRACKING_DIR", os.path.join(".", "mlruns", "0"))
    run_id = _uuid.uuid4().hex
    dest = os.path.join(tracking_dir, run_id, "artifacts", artifact_path)
    save_model(model, dest, input_example=input_example)
    return dest


class PyFuncModel:
    """Generic predict entry over a loaded artifact — the shape of
    ``mlflow.pyfunc.PyFuncModel``: ``load_model(path).predict(data)``."""

    def __init__(self, stage: PipelineStage, metadata: dict):
        self.stage = stage
        self.metadata = metadata

    def predict(self, data):
        if isinstance(data, DataFrame):
            return self.stage.transform(data)
        if hasattr(data, "to_dict") and hasattr(data, "columns"):
            # pandas in → pandas out, the mlflow.pyfunc contract
            from .interop import transform_pandas
            return transform_pandas(self.stage, data)
        return self.stage.transform(DataFrame(data))

    def __repr__(self):
        flavor = self.metadata.get("flavors", {}).get(_FLAVOR, {})
        return (f"PyFuncModel(stage={flavor.get('stage_class', '?')}, "
                f"uuid={self.metadata.get('model_uuid', '?')[:8]})")


def _read_mlmodel(path: str) -> dict:
    """Parse the MLmodel descriptor. Uses PyYAML when available (genuine
    mlflow artifacts may use flow style); falls back to a line parser that
    handles exactly what :func:`_yaml_dump` emits."""
    text = open(os.path.join(path, "MLmodel"), encoding="utf-8").read()
    try:
        import yaml
        return yaml.safe_load(text)
    except ImportError:
        pass
    root: dict = {}
    stack = [(root, -1)]
    for line in text.splitlines():
        if not line.strip() or line.lstrip().startswith("#"):
            continue
        indent = len(line) - len(line.lstrip())
        key, _, val = line.strip().partition(":")
        while stack and indent <= stack[-1][1]:
            stack.pop()
        cur = stack[-1][0]
        if val.strip():
            v = val.strip()
            cur[key] = json.loads(v) if v.startswith('"') else v
        else:
            cur[key] = {}
            stack.append((cur[key], indent))
    return root


def load_model(path: str) -> PyFuncModel:
    """Load an artifact directory saved by :func:`save_model` (or by genuine
    mlflow with this package's flavor) into a generic :class:`PyFuncModel`."""
    meta = _read_mlmodel(path)
    flavors = meta.get("flavors", {})
    data = (flavors.get(_FLAVOR) or flavors.get("python_function")
            or {}).get("data", "stage")
    stage = load_stage(os.path.join(path, data))
    return PyFuncModel(stage, meta)


def _load_pyfunc(data_path: str) -> PyFuncModel:
    """The ``mlflow.pyfunc`` loader hook: mlflow calls
    ``loader_module._load_pyfunc(<artifact>/<data>)`` and wraps the returned
    object's ``predict``. ``data_path`` points at the stage tree itself."""
    stage = load_stage(data_path)
    return PyFuncModel(stage, {"flavors": {_FLAVOR: {}}})
