"""Search service transformers/sinks.

Parity: ``cognitive/.../AzureSearch.scala`` (356 LoC index sink) and
``BingImageSearch.scala`` (309 LoC).
"""

from __future__ import annotations

import json
from typing import Optional, Sequence

from ..core.dataframe import DataFrame
from ..core.params import Param
from ..core.serialize import to_jsonable
from ..io.http.clients import post_json_batches
from ..io.http.schema import HeaderData, HTTPRequestData
from .base import ServiceParam, ServiceTransformer

__all__ = ["AzureSearchWriter", "BingImageSearch"]


class BingImageSearch(ServiceTransformer):
    """Parity: ``BingImageSearch`` — GET /images/search?q=... with offset/
    count paging params; output is the raw value array."""

    query = ServiceParam(str, is_required=True, is_url_param=True,
                         payload_name="q", doc="search query")
    count = ServiceParam(int, is_url_param=True, doc="results per page")
    offset = ServiceParam(int, is_url_param=True, doc="result offset")
    image_type = ServiceParam(str, is_url_param=True, payload_name="imageType",
                              doc="photo/clipart/...")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(method="GET")

    def _parse(self, body):
        if isinstance(body, dict):
            return body.get("value", body)
        return body

    @staticmethod
    def download_from_urls(df: DataFrame, url_col: str, out_col: str = "bytes",
                           concurrency: int = 4, timeout: float = 30.0
                           ) -> DataFrame:
        """Parity: ``BingImageSearch.downloadFromUrls`` helper."""
        from ..core.dataframe import object_col
        from ..io.http.clients import AsyncHTTPClient
        reqs = [None if u is None else HTTPRequestData(url=u, method="GET")
                for u in df[url_col]]
        client = AsyncHTTPClient(concurrency, timeout=timeout)
        outs = [None if r is None or r.status_code != 200
                else (r.entity.content if r.entity else None)
                for r in client.send(iter(reqs))]
        return df.with_column(out_col, object_col(outs))


class AzureSearchWriter:
    """Index-upload sink (parity: ``AzureSearchWriter.write``): POSTs
    ``{"value": [{"@search.action": "upload", ...row}, ...]}`` batches."""

    def __init__(self, url: str, api_key: str = "", batch_size: int = 100,
                 action: str = "upload"):
        self.url = url
        self.api_key = api_key
        self.batch_size = batch_size
        self.action = action

    def write(self, df: DataFrame, cols: Optional[Sequence[str]] = None) -> int:
        names = list(cols) if cols else df.columns

        def docs():
            for row in df.iter_rows():
                doc = {"@search.action": self.action}
                doc.update({k: to_jsonable(row[k]) for k in names})
                yield doc

        return post_json_batches(
            self.url, docs(), self.batch_size, wrap=lambda b: {"value": b},
            headers=[HeaderData("api-key", self.api_key)],
            what="search index upload")
