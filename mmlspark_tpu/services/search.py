"""Search service transformers/sinks.

Parity: ``cognitive/.../AzureSearch.scala`` (356 LoC index sink) and
``BingImageSearch.scala`` (309 LoC).
"""

from __future__ import annotations

import json
from typing import Optional, Sequence

from ..core.dataframe import DataFrame
from ..core.params import Param
from ..core.serialize import to_jsonable
from ..io.http.clients import post_json_batches
from ..io.http.schema import EntityData, HeaderData, HTTPRequestData
from .base import ServiceParam, ServiceTransformer

__all__ = ["AddDocuments", "AzureSearchWriter", "BingImageSearch"]


class BingImageSearch(ServiceTransformer):
    """Parity: ``BingImageSearch`` — GET /images/search?q=... with offset/
    count paging params; output is the raw value array."""

    query = ServiceParam(str, is_required=True, is_url_param=True,
                         payload_name="q", doc="search query")
    count = ServiceParam(int, is_url_param=True, doc="results per page")
    offset = ServiceParam(int, is_url_param=True, doc="result offset")
    image_type = ServiceParam(str, is_url_param=True, payload_name="imageType",
                              doc="photo/clipart/...")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(method="GET")

    def _parse(self, body):
        if isinstance(body, dict):
            return body.get("value", body)
        return body

    @staticmethod
    def download_from_urls(df: DataFrame, url_col: str, out_col: str = "bytes",
                           concurrency: int = 4, timeout: float = 30.0
                           ) -> DataFrame:
        """Parity: ``BingImageSearch.downloadFromUrls`` helper."""
        from ..core.dataframe import object_col
        from ..io.http.clients import AsyncHTTPClient
        reqs = [None if u is None else HTTPRequestData(url=u, method="GET")
                for u in df[url_col]]
        client = AsyncHTTPClient(concurrency, timeout=timeout)
        outs = [None if r is None or r.status_code != 200
                else (r.entity.content if r.entity else None)
                for r in client.send(iter(reqs))]
        return df.with_column(out_col, object_col(outs))


class AddDocuments(ServiceTransformer):
    """Parity: ``AddDocuments`` (``AzureSearch.scala:14-120``) — the
    transformer form of the index sink: rows batch into
    ``{"value": [{action_col: ..., ...row}, ...]}`` uploads and every row
    of a batch receives that batch's indexing response (per-key status).
    The reference requires the action column in the DataFrame; rows
    missing it default to 'upload' here and the key header is the search
    convention ``api-key``."""

    action_col = Param(str, default="@search.action",
                       doc="column holding the per-row index action")
    batch_size = Param(int, default=100, doc="documents per upload request")
    key_header = Param(str, default="api-key",
                       doc="header carrying the API key (search convention)")

    def _transform(self, df: DataFrame) -> DataFrame:
        from ..core.dataframe import object_col
        from ..io.http.clients import AsyncHTTPClient, \
            SingleThreadedHTTPClient
        from ..io.http.http_transformer import ErrorUtils
        if self.get("url") is None:
            raise ValueError(f"{type(self).__name__}: url must be set")
        rows = list(df.iter_rows())
        action = self.get("action_col")
        bs = max(1, int(self.get("batch_size")))
        # the API key must never ride into the index: exclude the bound
        # column (column-bound keys live under that column's name)
        skip = {"subscription_key"}
        tagged = self.get_or_none("subscription_key")
        if tagged is not None and tagged["kind"] == "col":
            skip.add(tagged["value"])
        groups = [list(range(i, min(i + bs, len(rows))))
                  for i in range(0, len(rows), bs)]
        requests_ = []
        for idxs in groups:
            docs = []
            for i in idxs:
                doc = {k: to_jsonable(v) for k, v in rows[i].items()
                       if k not in skip}
                doc.setdefault(action, "upload")
                docs.append(doc)
            requests_.append(HTTPRequestData(
                url=self._full_url(rows[idxs[0]]), method="POST",
                headers=self._headers(rows[idxs[0]]),
                entity=EntityData.from_string(
                    json.dumps({"value": docs}))))
        c = self.get("concurrency")
        client = (AsyncHTTPClient(c, handler=self._handle) if c > 1
                  else SingleThreadedHTTPClient(handler=self._handle))
        outs = [None] * len(rows)
        errs = [None] * len(rows)
        for idxs, resp in zip(groups, client.send(iter(requests_))):
            ok, err = ErrorUtils.split(resp)
            for i in idxs:
                if ok is None:
                    errs[i] = err
                else:
                    outs[i] = ok.json_content()
        return (df.with_column(self.get("output_col"), object_col(outs))
                  .with_column(self.get("error_col"), object_col(errs)))


class AzureSearchWriter:
    """Index-upload sink (parity: ``AzureSearchWriter.write``): POSTs
    ``{"value": [{"@search.action": "upload", ...row}, ...]}`` batches."""

    def __init__(self, url: str, api_key: str = "", batch_size: int = 100,
                 action: str = "upload"):
        self.url = url
        self.api_key = api_key
        self.batch_size = batch_size
        self.action = action

    def write(self, df: DataFrame, cols: Optional[Sequence[str]] = None) -> int:
        names = list(cols) if cols else df.columns

        def docs():
            for row in df.iter_rows():
                doc = {"@search.action": self.action}
                doc.update({k: to_jsonable(row[k]) for k in names})
                yield doc

        return post_json_batches(
            self.url, docs(), self.batch_size, wrap=lambda b: {"value": b},
            headers=[HeaderData("api-key", self.api_key)],
            what="search index upload")
