"""Anomaly-detection service transformers.

Parity: ``cognitive/.../AnomalyDetection.scala`` (249 LoC):
``DetectLastAnomaly`` / ``DetectEntireSeries(DetectAnomalies)`` POST a
``{"series": [{timestamp, value}], "granularity": ...}`` payload;
``SimpleDetectAnomalies`` groups rows by key and attaches per-row results.

Because a TPU cluster has no Azure dependency, ``SimpleDetectAnomalies``
can also run fully local (``local_fallback=True``): a jitted
median/MAD z-score detector — same output shape, no service required.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame, object_col
from .base import ServiceParam, ServiceTransformer
from ..core.params import Param

__all__ = ["AnomalyBase", "DetectLastAnomaly", "DetectAnomalies",
           "SimpleDetectAnomalies"]


def _group_indices(groups) -> "dict":
    """One-pass {group: np.ndarray(row indices)}, insertion-ordered."""
    bucket: dict = {}
    for i, g in enumerate(groups):
        bucket.setdefault(g, []).append(i)
    return {g: np.asarray(ix) for g, ix in bucket.items()}


class AnomalyBase(ServiceTransformer):
    series = ServiceParam(list, is_required=True,
                          doc="list of {timestamp, value} points")
    granularity = ServiceParam(str, default="daily", doc="series granularity")
    max_anomaly_ratio = ServiceParam(float, payload_name="maxAnomalyRatio",
                                     doc="expected max anomaly fraction")
    sensitivity = ServiceParam(int, doc="detector sensitivity 0-99")

    def _payload(self, row: dict):
        p = {"series": self.get_value_opt(row, "series"),
             "granularity": self.get_value_opt(row, "granularity")}
        for extra in ("max_anomaly_ratio", "sensitivity"):
            v = self.get_value_opt(row, extra)
            if v is not None:
                sp = self.params()[extra]
                p[sp.payload_name or extra] = v
        return p


class DetectLastAnomaly(AnomalyBase):
    """Parity: ``DetectLastAnomaly`` — /last endpoint semantics."""


class DetectAnomalies(AnomalyBase):
    """Parity: ``DetectEntireSeries`` — whole-series batch detection."""


class SimpleDetectAnomalies(AnomalyBase):
    """Grouped per-key detection (parity: ``SimpleDetectAnomalies``), with an
    optional local jitted MAD z-score detector when no service URL is set."""

    group_col = Param(str, default="group", doc="series grouping column")
    timestamp_col = Param(str, default="timestamp", doc="timestamp column")
    value_col = Param(str, default="value", doc="value column")
    local_threshold = Param(float, default=3.5, doc="local MAD z threshold")

    def _transform(self, df: DataFrame) -> DataFrame:
        if self.get_or_none("url") is not None:
            return self._service_transform(df)
        return self._local_transform(df)

    def _service_transform(self, df: DataFrame) -> DataFrame:
        # grouped mode aggregates rows per key, so column-bound service
        # params (other than the synthesized series) cannot be resolved
        for n, p in self._service_params().items():
            tagged = self.get_or_none(n)
            if n != "series" and tagged is not None and tagged["kind"] == "col":
                raise ValueError(
                    f"SimpleDetectAnomalies: service param {n!r} is bound to a "
                    "column; grouped mode only supports scalar params")
        group_rows = _group_indices(df[self.get("group_col")])
        ts = df[self.get("timestamp_col")]
        vals = df[self.get("value_col")]
        series_col = []
        for idxs in group_rows.values():
            series_col.append([{"timestamp": str(ts[i]), "value": float(vals[i])}
                               for i in idxs])
        # ONE batched probe transform: every group's request goes through the
        # same client at the transformer's concurrency
        probe = DetectAnomalies(url=self.get("url"),
                                concurrency=self.get("concurrency"),
                                timeout=self.get("timeout"),
                                key_header=self.get("key_header"),
                                method=self.get("method"),
                                output_col="__out__", error_col="__err__")
        for n in self._service_params():   # scalar service params (key, …)
            if n != "series" and self.get_or_none(n) is not None:
                probe.set(**{n: self.get(n)})
        probe.set_vector_param("series", "__series__")
        res = probe.transform(DataFrame({"__series__": object_col(series_col)}))

        out = np.empty(len(df), dtype=object)
        errs = np.empty(len(df), dtype=object)
        for g_i, idxs in enumerate(group_rows.values()):
            parsed, err = res["__out__"][g_i], res["__err__"][g_i]
            flags = (parsed or {}).get("isAnomaly", [None] * len(idxs))
            for j, i in enumerate(idxs):
                out[i] = {"isAnomaly": flags[j] if j < len(flags) else None}
                errs[i] = err
        return (df.with_column(self.get("output_col"), out)
                  .with_column(self.get("error_col"), errs))

    def _local_transform(self, df: DataFrame) -> DataFrame:
        from ..utils.jit_cache import jitted

        def mad_z(v):
            import jax.numpy as jnp
            med = jnp.median(v)
            mad = jnp.median(jnp.abs(v - med)) + 1e-9
            return 0.6745 * jnp.abs(v - med) / mad

        fn = jitted("services.anomaly.mad_z", mad_z)
        vals = np.asarray(df[self.get("value_col")], dtype=np.float32)
        out = np.empty(len(df), dtype=object)
        thr = self.get("local_threshold")
        for idxs in _group_indices(df[self.get("group_col")]).values():
            z = np.asarray(fn(vals[idxs]))
            for j, i in enumerate(idxs):
                out[i] = {"isAnomaly": bool(z[j] > thr),
                          "score": float(z[j])}
        return (df.with_column(self.get("output_col"), out)
                  .with_column(self.get("error_col"),
                               object_col([None] * len(df))))
