"""Speech service transformers.

Parity surface:

* ``SpeechToText`` (``cognitive/.../SpeechToText.scala:22-90``): POST raw
  .wav bytes, URL params ``language``/``format``/``profanity``, JSON
  transcription response.
* ``SpeechToTextSDK`` (``SpeechToTextSDK.scala``, 579 LoC): the reference
  streams audio through the Speech SDK and emits one result per recognized
  utterance. Here the streaming contract is kept — audio is split into
  fixed-duration chunks (``AudioStreams.scala``-style buffering) and each
  chunk is transcribed; the output column holds the list of per-chunk
  results.
* ``TextToSpeech`` (``TextToSpeech.scala:27-140``): synthesize text and
  write the returned audio bytes to ``output_file_col`` paths; errors land
  in the error column.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import Param
from ..io.http.schema import EntityData, HeaderData, HTTPRequestData
from .base import ServiceParam, ServiceTransformer

__all__ = ["SpeechToText", "SpeechToTextSDK", "ConversationTranscription",
           "TextToSpeech"]


class SpeechToText(ServiceTransformer):
    """POST audio bytes → transcription JSON."""

    audio_data = ServiceParam(bytes, is_required=True,
                              doc="wav audio bytes (scalar or column)")
    language = ServiceParam(str, default="en-US", is_url_param=True,
                            is_required=True, doc="spoken language")
    format = ServiceParam(str, is_url_param=True,
                          doc="result format: simple or detailed")
    profanity = ServiceParam(str, is_url_param=True,
                             doc="masked / removed / raw")

    def _build_request(self, row: dict) -> Optional[HTTPRequestData]:
        if self.should_skip(row):
            return None
        audio = self.get_value_opt(row, "audio_data")
        headers = [h for h in self._headers(row)
                   if h.name.lower() != "content-type"]
        headers.append(HeaderData("Content-Type",
                                  "audio/wav; codecs=audio/pcm"))
        return HTTPRequestData(
            url=self._full_url(row), method="POST", headers=headers,
            entity=EntityData(content=bytes(audio),
                              content_length=len(audio)))


class SpeechToTextSDK(SpeechToText):
    """Chunked (streaming-style) recognition: one result per audio chunk."""

    chunk_bytes = Param(int, default=32768,
                        doc="bytes per streamed chunk (one request each)")

    #: per-chunk transformer type (ConversationTranscription swaps in a
    #: participants-aware variant)
    _inner_cls = SpeechToText

    def _transform(self, df: DataFrame) -> DataFrame:
        size = self.get("chunk_bytes")
        tagged = self.get_or_none("audio_data")
        if tagged is None or tagged["kind"] != "col":
            raise ValueError("SpeechToTextSDK requires audio_data bound to a "
                             "column (set_vector_param)")
        col = tagged["value"]
        audio = df[col]
        # other column-bound service params must travel with each chunk row
        extra_cols = [t["value"] for n, t in
                      ((n, self.get_or_none(n)) for n in self._service_params())
                      if n != "audio_data" and t is not None
                      and t["kind"] == "col"]
        # explode every row's audio into chunks, transcribe flat, regroup
        flat, owners = [], []
        for i, a in enumerate(audio):
            if a is None:
                continue
            for off in range(0, len(a), size):
                flat.append(a[off:off + size])
                owners.append(i)
        sub = None
        if flat:
            data = {col: object_col(flat)}
            for c in extra_cols:
                data[c] = object_col([df[c][i] for i in owners])
            sub = DataFrame(data)
        outs = np.empty(len(df), dtype=object)
        errs = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            outs[i] = [] if audio[i] is not None else None
        if sub is not None:
            inner = type(self)._inner_cls(
                url=self.get("url"), concurrency=self.get("concurrency"),
                timeout=self.get("timeout"),
                key_header=self.get("key_header"),
                output_col="__out__", error_col="__err__")
            for n in self._service_params():
                if n != "audio_data" and self.get_or_none(n) is not None:
                    inner.set(**{n: self.get(n)})
            inner.set_vector_param("audio_data", col)
            res = inner.transform(sub)
            for j, i in enumerate(owners):
                outs[i].append(res["__out__"][j])
                if res["__err__"][j] is not None:
                    errs[i] = res["__err__"][j]
        return (df.with_column(self.get("output_col"), outs)
                  .with_column(self.get("error_col"), errs))


class _ConversationChunk(SpeechToText):
    """Per-chunk request builder for ConversationTranscription: validates
    and forwards the participants declaration."""

    participants_json = ServiceParam(
        str, is_url_param=True, payload_name="participants",
        doc="JSON array of {name, preferredLanguage, voiceSignature}")

    def _build_request(self, row: dict):
        import json as _json
        if self.should_skip(row):  # null required params skip, not 400
            return None
        pj = self.get_value_opt(row, "participants_json")
        if pj is not None:
            try:
                parsed = _json.loads(pj)
            except _json.JSONDecodeError as e:
                raise ValueError(f"participants_json is not valid JSON: {e}")
            if not isinstance(parsed, list):
                raise ValueError("participants_json must be a JSON array")
        return super()._build_request(row)


class ConversationTranscription(SpeechToTextSDK):
    """Parity: ``ConversationTranscription``
    (``SpeechToTextSDK.scala:491-579``) — multi-speaker transcription over
    the same chunked streaming contract as ``SpeechToTextSDK``;
    ``participants_json`` (``:134-141``) declares speakers (name /
    preferredLanguage / voiceSignature) and rides as a URL param so the
    service can attribute utterances (speaker ids come back in the
    per-chunk results)."""

    participants_json = ServiceParam(
        str, is_url_param=True, payload_name="participants",
        doc="JSON array of {name, preferredLanguage, voiceSignature}")

    _inner_cls = _ConversationChunk


class TextToSpeech(ServiceTransformer):
    """Synthesize speech; audio bytes are written to per-row output files."""

    text = ServiceParam(str, is_required=True, doc="text to speak")
    language = ServiceParam(str, default="en-US", doc="synthesis language")
    voice_name = ServiceParam(str, default="en-US-JennyNeural",
                              doc="voice to use")
    output_format = ServiceParam(str, default="riff-24khz-16bit-mono-pcm",
                                 doc="audio output format header")
    output_file_col = Param(str, default="outputFile",
                            doc="column holding the destination file path")

    def _build_request(self, row: dict) -> Optional[HTTPRequestData]:
        if self.should_skip(row):
            return None
        from xml.sax.saxutils import escape, quoteattr
        text = escape(str(self.get_value_opt(row, "text")))
        lang = quoteattr(str(self.get_value_opt(row, "language")))
        voice = quoteattr(str(self.get_value_opt(row, "voice_name")))
        ssml = (f"<speak version='1.0' xml:lang={lang}>"
                f"<voice xml:lang={lang} name={voice}>"
                f"{text}</voice></speak>")
        headers = [h for h in self._headers(row)
                   if h.name.lower() != "content-type"]
        headers.append(HeaderData("Content-Type", "application/ssml+xml"))
        headers.append(HeaderData("X-Microsoft-OutputFormat",
                                  self.get_value_opt(row, "output_format")))
        body = ssml.encode("utf-8")
        return HTTPRequestData(url=self._full_url(row), method="POST",
                               headers=headers,
                               entity=EntityData(content=body,
                                                 content_length=len(body)))

    def _transform(self, df: DataFrame) -> DataFrame:
        from ..io.http.clients import AsyncHTTPClient, SingleThreadedHTTPClient
        from ..io.http.http_transformer import ErrorUtils
        rows = list(df.iter_rows())
        requests_ = [self._build_request(r) for r in rows]
        c = self.get("concurrency")
        client = (AsyncHTTPClient(c, handler=self._handle) if c > 1
                  else SingleThreadedHTTPClient(handler=self._handle))
        errs = []
        paths = df[self.get("output_file_col")]
        for i, (req, resp) in enumerate(zip(requests_,
                                            client.send(iter(requests_)))):
            if req is None:
                errs.append(None)
                continue
            ok, err = ErrorUtils.split(resp)
            if ok is None:
                errs.append(err)
                continue
            with open(paths[i], "wb") as f:
                f.write(ok.entity.content if ok.entity else b"")
            errs.append(None)
        return df.with_column(self.get("error_col"), object_col(errs))
