"""Translation service transformers.

Parity: ``cognitive/.../TextTranslator.scala`` (550 LoC): ``Translate``,
``Transliterate``, ``Detect``, ``BreakSentence`` — POST
``[{"Text": ...}]`` arrays with to/from/script URL params.
"""

from __future__ import annotations

from .base import HasAsyncReply, ServiceParam, ServiceTransformer

__all__ = ["TranslatorBase", "Translate", "Transliterate", "DetectLanguage",
           "DocumentTranslator",
           "BreakSentence"]


class TranslatorBase(ServiceTransformer):
    text = ServiceParam(str, is_required=True, doc="text to process")

    def _payload(self, row: dict):
        return [{"Text": self.get_value_opt(row, "text")}]

    def _parse(self, body):
        if isinstance(body, list) and body:
            return body[0]
        return body


class Translate(TranslatorBase):
    to_language = ServiceParam(str, is_url_param=True, payload_name="to",
                               is_required=True, doc="target language(s)")
    from_language = ServiceParam(str, is_url_param=True, payload_name="from",
                                 doc="source language (auto-detect if unset)")

    def _parse(self, body):
        first = super()._parse(body)
        if isinstance(first, dict):
            return first.get("translations", first)
        return first


class Transliterate(TranslatorBase):
    language = ServiceParam(str, is_url_param=True, is_required=True,
                            doc="language of the text")
    from_script = ServiceParam(str, is_url_param=True, payload_name="fromScript",
                               is_required=True, doc="source script")
    to_script = ServiceParam(str, is_url_param=True, payload_name="toScript",
                             is_required=True, doc="target script")


class DetectLanguage(TranslatorBase):
    """Parity: translator ``Detect``."""


class BreakSentence(TranslatorBase):
    language = ServiceParam(str, is_url_param=True, doc="language hint")

    def _parse(self, body):
        first = super()._parse(body)
        if isinstance(first, dict):
            return first.get("sentLen", first)
        return first


class DocumentTranslator(ServiceTransformer, HasAsyncReply):
    """Batch document translation (parity: ``DocumentTranslator.scala``,
    167 LoC): POST ``{"inputs": [{source, targets}]}`` to ``/batches``;
    the 202 + Operation-Location long-poll is inherited from HasAsyncReply."""

    source_url = ServiceParam(str, is_required=True,
                              doc="container URL of source documents")
    target_url = ServiceParam(str, is_required=True,
                              doc="container URL for translated output")
    target_language = ServiceParam(str, is_required=True,
                                   doc="language code to translate to")
    storage_type = ServiceParam(str, doc="Folder or File")

    def _payload(self, row: dict):
        target = {"targetUrl": self.get_value_opt(row, "target_url"),
                  "language": self.get_value_opt(row, "target_language")}
        inp = {"source": {"sourceUrl": self.get_value_opt(row, "source_url")},
               "targets": [target]}
        st = self.get_value_opt(row, "storage_type")
        if st is not None:
            inp["storageType"] = st
        return {"inputs": [inp]}
