"""Translation service transformers — full reference breadth.

Parity: ``cognitive/.../TextTranslator.scala`` (550 LoC) op-for-op:
``Translate`` (all twelve option params, ``:206-377``), ``Transliterate``,
``Detect``, ``BreakSentence``, ``DictionaryLookup`` (``:456-466``) and
``DictionaryExamples`` (``:487-540``, the text+translation pair body).
Shared translator conventions (``TextTranslatorBase``): every request
carries ``api-version=3.0``, an optional ``Ocp-Apim-Subscription-Region``
header, and a JSON array body ``[{"Text": ...}, ...]`` — one element per
text in the row's (possibly list-valued) text param; responses align
positionally. ``DocumentTranslator`` parity: ``DocumentTranslator.scala``
(167 LoC).
"""

from __future__ import annotations

import json as _json
from typing import Optional

import numpy as np

from ..io.http.schema import EntityData, HeaderData, HTTPRequestData
from .base import HasAsyncReply, ServiceParam, ServiceTransformer

__all__ = ["TranslatorBase", "Translate", "Transliterate", "DetectLanguage",
           "DocumentTranslator", "BreakSentence", "DictionaryLookup",
           "DictionaryExamples"]


class TranslatorBase(ServiceTransformer):
    """Array-body translator conventions (``TextTranslator.scala:150-200``):
    ``api-version=3.0`` on every URL, optional region header, ``Text``
    array body from a scalar or list text value. A list-valued text row
    returns the whole per-text result array; a scalar returns its single
    element."""

    text = ServiceParam(str, is_required=True,
                        doc="text (str) or texts (list) to process")
    subscription_region = ServiceParam(
        str, doc="Ocp-Apim-Subscription-Region header value")
    api_version = ServiceParam(str, default="3.0", is_url_param=True,
                               payload_name="api-version",
                               doc="service API version")

    def _texts(self, row: dict):
        t = self.get_value_opt(row, "text")
        if t is None:
            return None, False
        if isinstance(t, (list, tuple, np.ndarray)):
            return [None if x is None else str(x) for x in list(t)], True
        return [str(t)], False

    def _headers(self, row: dict):
        hdrs = super()._headers(row)
        region = self.get_value_opt(row, "subscription_region")
        if region:
            hdrs.append(HeaderData("Ocp-Apim-Subscription-Region", region))
        return hdrs

    def _body(self, row: dict):
        texts, _ = self._texts(row)
        return [{"Text": t or ""} for t in texts or []]

    def _is_batch_row(self, row: dict) -> bool:
        _, batched = self._texts(row)
        return batched

    def _build_request(self, row: dict) -> Optional[HTTPRequestData]:
        if self.should_skip(row):
            return None
        body = self._body(row)
        if not body:
            return None
        return HTTPRequestData(
            url=self._full_url(row), method="POST",
            headers=self._headers(row),
            entity=EntityData.from_string(_json.dumps(body)))

    def _parse_one(self, item):
        """Hook: per-text result extraction."""
        return item

    def _parse(self, body):
        if not isinstance(body, list):
            return body
        return [self._parse_one(x) for x in body]

    def _transform(self, df):
        # responses are positional arrays; scalar-text rows unwrap to their
        # single element so the output shape follows the input shape
        out_df = super()._transform(df)
        out_col = self.get("output_col")
        vals = list(out_df[out_col])
        for i, row in enumerate(df.iter_rows()):
            if (vals[i] is not None and isinstance(vals[i], list)
                    and len(vals[i]) == 1 and not self._is_batch_row(row)):
                vals[i] = vals[i][0]
        from ..core.dataframe import object_col
        return out_df.with_column(out_col, object_col(vals))


class Translate(TranslatorBase):
    """Parity: ``Translate`` (``TextTranslator.scala:206-377``) — all
    option params ride as URL params; ``to`` joins a list with commas
    (the reference's ``toValueString = seq.mkString(",")``)."""

    to_language = ServiceParam(list, is_url_param=True, payload_name="to",
                               is_required=True, doc="target language(s)")
    from_language = ServiceParam(str, is_url_param=True, payload_name="from",
                                 doc="source language (auto-detect if unset)")
    text_type = ServiceParam(str, is_url_param=True, payload_name="textType",
                             doc="'plain' or 'html'")
    category = ServiceParam(str, is_url_param=True,
                            doc="translation category/custom system")
    profanity_action = ServiceParam(str, is_url_param=True,
                                    payload_name="profanityAction",
                                    doc="NoAction/Marked/Deleted")
    profanity_marker = ServiceParam(str, is_url_param=True,
                                    payload_name="profanityMarker",
                                    doc="Asterisk/Tag")
    include_alignment = ServiceParam(bool, is_url_param=True,
                                     payload_name="includeAlignment",
                                     doc="include alignment projection")
    include_sentence_length = ServiceParam(
        bool, is_url_param=True, payload_name="includeSentenceLength",
        doc="include sentence boundaries")
    suggested_from = ServiceParam(str, is_url_param=True,
                                  payload_name="suggestedFrom",
                                  doc="fallback source language")
    from_script = ServiceParam(str, is_url_param=True,
                               payload_name="fromScript",
                               doc="script of the input text")
    to_script = ServiceParam(str, is_url_param=True, payload_name="toScript",
                             doc="script of the translated text")
    allow_fallback = ServiceParam(bool, is_url_param=True,
                                  payload_name="allowFallback",
                                  doc="allow general-system fallback")

    def get_url_params(self, row):
        q = super().get_url_params(row)
        to = q.get("to")
        if isinstance(to, (list, tuple, np.ndarray)):
            q["to"] = ",".join(str(x) for x in to)
        return q

    def _parse_one(self, item):
        if isinstance(item, dict):
            return item.get("translations", item)
        return item


class Transliterate(TranslatorBase):
    """Parity: ``Transliterate`` (``TextTranslator.scala:379-410``)."""

    language = ServiceParam(str, is_url_param=True, is_required=True,
                            doc="language of the text")
    from_script = ServiceParam(str, is_url_param=True,
                               payload_name="fromScript",
                               is_required=True, doc="source script")
    to_script = ServiceParam(str, is_url_param=True, payload_name="toScript",
                             is_required=True, doc="target script")


class DetectLanguage(TranslatorBase):
    """Parity: translator ``Detect`` (``TextTranslator.scala:414-423``)."""


class BreakSentence(TranslatorBase):
    """Parity: ``BreakSentence`` (``TextTranslator.scala:427-452``)."""

    language = ServiceParam(str, is_url_param=True, doc="language hint")
    script = ServiceParam(str, is_url_param=True, doc="script hint")

    def _parse_one(self, item):
        if isinstance(item, dict):
            return item.get("sentLen", item)
        return item


class DictionaryLookup(TranslatorBase):
    """Parity: ``DictionaryLookup`` (``TextTranslator.scala:456-466``) —
    alternative translations for a word/phrase; from/to are required."""

    from_language = ServiceParam(str, is_url_param=True, payload_name="from",
                                 is_required=True, doc="source language")
    to_language = ServiceParam(str, is_url_param=True, payload_name="to",
                               is_required=True, doc="target language")


def _single_pair(v) -> bool:
    return (isinstance(v, (list, tuple)) and len(v) == 2
            and all(isinstance(x, str) for x in v)) or isinstance(v, dict)


class DictionaryExamples(TranslatorBase):
    """Parity: ``DictionaryExamples`` (``TextTranslator.scala:487-540``) —
    usage examples for (text, translation) pairs previously returned by
    DictionaryLookup. ``text_and_translation`` is one pair ``(text,
    translation)`` / ``{"text":..., "translation":...}`` or a list of
    pairs; the body carries ``Text``+``Translation`` per pair."""

    text = ServiceParam(str, doc="unused (pairs carry the text)")
    text_and_translation = ServiceParam(
        list, is_required=True,
        doc="(text, translation) pair or list of pairs")
    from_language = ServiceParam(str, is_url_param=True, payload_name="from",
                                 is_required=True, doc="source language")
    to_language = ServiceParam(str, is_url_param=True, payload_name="to",
                               is_required=True, doc="target language")

    @staticmethod
    def _pair(p):
        if isinstance(p, dict):
            t = p.get("text", p.get("Text"))
            tr = p.get("translation", p.get("Translation"))
        elif isinstance(p, (list, tuple)) and len(p) == 2:
            t, tr = p
        else:
            raise ValueError(
                f"text_and_translation entries must be (text, translation) "
                f"pairs, got {p!r}")
        if t is None or tr is None:
            raise ValueError(
                f"text_and_translation pair needs text AND translation, "
                f"got {p!r}")
        return {"Text": str(t), "Translation": str(tr)}

    def _pairs(self, row: dict):
        v = self.get_value_opt(row, "text_and_translation")
        if v is None:
            return None, False
        if _single_pair(v):
            return [v], False
        if not isinstance(v, (list, tuple, np.ndarray)):
            # ValueError (not TypeError) so the per-row catch keeps one
            # malformed row from aborting the batch
            raise ValueError(
                f"text_and_translation must be a (text, translation) pair "
                f"or a list of pairs, got {v!r}")
        return list(v), True

    def _is_batch_row(self, row: dict) -> bool:
        _, batched = self._pairs(row)
        return batched

    def _body(self, row: dict):
        pairs, _ = self._pairs(row)
        return [self._pair(p) for p in pairs or []]


class DocumentTranslator(ServiceTransformer, HasAsyncReply):
    """Batch document translation (parity: ``DocumentTranslator.scala``,
    167 LoC): POST ``{"inputs": [{source, targets}]}`` to ``/batches``;
    the 202 + Operation-Location long-poll is inherited from HasAsyncReply."""

    source_url = ServiceParam(str, is_required=True,
                              doc="container URL of source documents")
    target_url = ServiceParam(str, is_required=True,
                              doc="container URL for translated output")
    target_language = ServiceParam(str, is_required=True,
                                   doc="language code to translate to")
    storage_type = ServiceParam(str, doc="Folder or File")

    def _payload(self, row: dict):
        target = {"targetUrl": self.get_value_opt(row, "target_url"),
                  "language": self.get_value_opt(row, "target_language")}
        inp = {"source": {"sourceUrl": self.get_value_opt(row, "source_url")},
               "targets": [target]}
        st = self.get_value_opt(row, "storage_type")
        if st is not None:
            inp["storageType"] = st
        return {"inputs": [inp]}
