"""True streaming speech-to-text: continuous recognition over a websocket.

Parity surface: ``SpeechToTextSDK`` (``cognitive/.../SpeechToTextSDK.scala:579``)
— the reference streams audio through the Speech SDK (websocket transport
under the hood), fires ``recognizing``/``recognized`` events as hypotheses
firm up, and emits **one output row per recognized utterance**; audio enters
through push/pull streams (``AudioStreams.scala:94``).

TPU-framework equivalents:

* :class:`SpeechRecognitionSession` — a full-duplex session over
  :mod:`mmlspark_tpu.io.ws`: a sender thread pumps fixed-duration audio
  frames from a push/pull stream up the socket; a receiver thread parses
  JSON events down the socket and fires callbacks. Wire protocol (mirrors
  the Speech SDK's message shapes):

  - client → server: text ``{"type": "speech.config", "format": {...}}``
    then binary PCM frames, then text ``{"type": "audio.end"}``
  - server → client: ``{"type": "speech.hypothesis", "text": ...}``
    (interim), ``{"type": "speech.phrase", "text", "offset", "duration"}``
    (final utterance), ``{"type": "speech.end"}``

* :class:`SpeechToTextStreaming` — the DataFrame stage: each row's audio
  column streams through a session; the output column holds the list of
  final utterances (dicts with text/offset/duration), one element per
  recognized phrase — the row-per-utterance contract, grouped per input row.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, List, Optional
from urllib.parse import urlparse

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, identity
from ..io.ws import OP_CLOSE, OP_TEXT, client_connect
from .audio import AudioFormat, PullAudioStream
from .base import ServiceParam, ServiceTransformer

__all__ = ["SpeechRecognitionSession", "SpeechToTextStreaming"]


class SpeechRecognitionSession:
    """One continuous-recognition session against a streaming endpoint.

    ``recognizing``/``recognized`` callbacks fire on the receiver thread
    (reference: the SDK's event model, ``SpeechToTextSDK.scala:300-360``).
    ``run(stream)`` pumps the whole stream and returns the final phrases.
    """

    def __init__(self, url: str, headers: Optional[dict] = None,
                 frame_millis: int = 100,
                 recognizing: Optional[Callable[[dict], None]] = None,
                 recognized: Optional[Callable[[dict], None]] = None,
                 timeout: float = 30.0):
        if not url:
            raise ValueError("streaming url must be set (ws://host:port/path)")
        u = urlparse(url)
        if u.scheme != "ws":
            # no TLS layer here; wss endpoints need a terminating proxy
            raise ValueError(
                f"streaming url scheme must be ws:// (got {url!r})")
        self._host = u.hostname
        self._port = u.port or 80
        self._path = u.path or "/"
        self._headers = dict(headers or {})
        self.frame_millis = frame_millis
        self.recognizing = recognizing
        self.recognized = recognized
        self.timeout = timeout
        self.phrases: List[dict] = []
        self._error: Optional[Exception] = None

    # -- session ------------------------------------------------------------
    def run(self, stream) -> List[dict]:
        """Stream ``stream`` (Push/PullAudioStream) to completion; returns
        the list of final phrase events."""
        conn = client_connect(self._host, self._port, self._path,
                              headers=self._headers, timeout=self.timeout)
        try:
            fmt: AudioFormat = stream.format
            conn.send_text(json.dumps({
                "type": "speech.config",
                "format": {"sample_rate": fmt.sample_rate,
                           "bits_per_sample": fmt.bits_per_sample,
                           "channels": fmt.channels}}))
            done = threading.Event()
            # tpulint: disable=TPU025 — session-scoped receiver, joined
            # when the stream ends; a crash tears down this one session
            # (surfaced by the closed connection), and restarting it would
            # replay partial phrase events into the transcript
            receiver = threading.Thread(
                target=self._recv_loop, args=(conn, done), daemon=True)
            receiver.start()

            frame = fmt.frame_bytes(self.frame_millis)
            send_exc = None
            try:
                while not done.is_set():  # a terminal event stops the pump
                    chunk = stream.read(frame, timeout=self.timeout)
                    if not chunk:
                        break
                    conn.send_binary(chunk)
                if not done.is_set():
                    conn.send_text(json.dumps({"type": "audio.end"}))
            except OSError as e:
                # a dead socket usually means the server already sent a
                # terminal event — prefer that error over the pipe error
                send_exc = e
            if not done.wait(self.timeout):
                raise send_exc or TimeoutError("no speech.end from server")
            if self._error is not None:
                raise self._error
            if send_exc is not None:
                raise send_exc
            return list(self.phrases)
        finally:
            conn.close()

    def _recv_loop(self, conn, done: threading.Event) -> None:
        try:
            while True:
                opcode, payload = conn.recv()
                if opcode == OP_CLOSE:
                    break
                if opcode != OP_TEXT:
                    continue
                evt = json.loads(payload.decode("utf-8"))
                kind = evt.get("type")
                if kind == "speech.hypothesis":
                    if self.recognizing:
                        self.recognizing(evt)
                elif kind == "speech.phrase":
                    self.phrases.append(evt)
                    if self.recognized:
                        self.recognized(evt)
                elif kind == "speech.error":
                    # terminal: stop listening so run() reports this error
                    # instead of pumping audio into a dead session until a
                    # timeout masks it
                    self._error = RuntimeError(
                        evt.get("message", "speech service error"))
                    break
                elif kind == "speech.end":
                    break
        except Exception as e:  # surfaced to run()
            self._error = self._error or e
        finally:
            done.set()


class SpeechToTextStreaming(ServiceTransformer):
    """Continuous recognition over each row's audio (wav or raw PCM).

    Output column: list of final utterance dicts (text/offset/duration) per
    row — the reference's one-row-per-utterance, grouped (flatten with
    ``FlattenBatch`` for literal row-per-utterance parity)."""

    audio_data = ServiceParam(bytes, is_required=True,
                              doc="wav (RIFF) or raw 16k/16-bit PCM bytes")
    language = ServiceParam(str, default="en-US", is_url_param=True,
                            doc="spoken language")
    frame_millis = Param(int, default=100, doc="audio frame size streamed "
                                               "per websocket message")
    interim_col = Param(str, default=None, converter=identity,
                        doc="optional column receiving interim hypothesis "
                            "texts (list per row)")

    def _transform(self, df: DataFrame) -> DataFrame:
        tagged = self.get_or_none("audio_data")
        if tagged is None or tagged["kind"] != "col":
            raise ValueError("SpeechToTextStreaming requires audio_data "
                             "bound to a column (set_vector_param)")
        audio = df[tagged["value"]]
        url = self.get("url")
        interim_col = self.get_or_none("interim_col")
        outs = np.empty(len(df), dtype=object)
        interims = np.empty(len(df), dtype=object)
        errs = np.empty(len(df), dtype=object)
        headers = {h.name: h.value for h in self._headers({})}

        def run_row(i):
            a = audio[i]
            if a is None:
                return
            hyp: List[str] = []
            try:
                raw = bytes(a)
                if raw[:4] == b"RIFF":
                    # a real WAV: parse errors (non-PCM codec, truncated
                    # chunks) must surface, not degrade into streaming the
                    # container bytes as PCM noise
                    stream = PullAudioStream.from_wav(raw)
                else:
                    stream = PullAudioStream(raw)  # raw PCM, default format
                sess = SpeechRecognitionSession(
                    url, headers=headers,
                    frame_millis=self.frame_millis,
                    recognizing=lambda e: hyp.append(e.get("text", "")),
                    timeout=self.get("timeout"))
                outs[i] = sess.run(stream)
                interims[i] = hyp
            except Exception as e:
                errs[i] = {"error": str(e)}

        conc = max(1, self.get("concurrency"))
        if conc == 1:
            for i in range(len(df)):
                run_row(i)
        else:
            # each row is an independent websocket session → sessions in
            # flight = concurrency (the contract every ServiceTransformer
            # honors via AsyncHTTPClient)
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(conc) as ex:
                list(ex.map(run_row, range(len(df))))
        out = (df.with_column(self.get("output_col"), outs)
                 .with_column(self.get("error_col"), errs))
        if interim_col:
            out = out.with_column(interim_col, interims)
        return out
