"""Service-transformer framework.

Parity: ``cognitive/.../CognitiveServiceBase.scala``:

* :class:`ServiceParam` — a param set either to a scalar (applies to every
  row) or to a column name (per-row values): the ``Either[T, String]``
  duality of ``HasServiceParams:29-126``.
* :class:`ServiceTransformer` — assembles one HTTP request per row from
  service params (URL params vs body params), skips rows whose required
  params are null (``shouldSkip:93-95``), sends with bounded concurrency
  through the io/http clients, splits errors, and parses JSON output —
  the ``getInternalTransformer`` composition at ``:271-336``.
* :class:`HasAsyncReply` — 202-Accepted + Operation-Location long-polling
  (``ComputerVision.scala:290-330``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import HasErrorCol, HasOutputCol, Param, Params, identity
from ..core.pipeline import Transformer
from ..io.http.clients import AsyncHTTPClient, SingleThreadedHTTPClient, \
    advanced_handler
from ..io.http.http_transformer import ErrorUtils
from ..io.http.schema import EntityData, HeaderData, HTTPRequestData, \
    HTTPResponseData

__all__ = ["ServiceParam", "HasServiceParams", "ServiceTransformer",
           "HasAsyncReply"]

_SCALAR, _COL = "scalar", "col"


class ServiceParam(Param):
    """Scalar-or-column param. Values are tagged dicts
    ``{"kind": "scalar"|"col", "value": ...}`` so they stay JSON-serializable.
    Setting a plain value means scalar; use ``set_vector_param`` (or a
    ``{"col": name}`` dict) to bind a column."""

    def __init__(self, dtype=None, default=Param._NO_DEFAULT, doc: str = "",
                 is_required: bool = False, is_url_param: bool = False,
                 payload_name: Optional[str] = None):
        super().__init__(None, Param._NO_DEFAULT, doc, converter=identity)
        self.value_dtype = dtype
        self.is_required = is_required
        self.is_url_param = is_url_param
        self.payload_name = payload_name  # name in query/body (defaults to param name)
        if default is not Param._NO_DEFAULT:
            self.default = {"kind": _SCALAR, "value": default}

    def convert(self, value):
        if value is None:
            return None
        if isinstance(value, dict) and set(value) == {"kind", "value"}:
            return value
        if isinstance(value, dict) and set(value) == {"col"}:
            return {"kind": _COL, "value": value["col"]}
        return {"kind": _SCALAR, "value": value}


class HasServiceParams(Params):
    """Row-aware accessors over ServiceParams (``HasServiceParams:29-126``)."""

    def set_scalar_param(self, name: str, value) -> "HasServiceParams":
        return self.set(**{name: {"kind": _SCALAR, "value": value}})

    def set_vector_param(self, name: str, col: str) -> "HasServiceParams":
        return self.set(**{name: {"kind": _COL, "value": col}})

    def _service_params(self) -> Dict[str, ServiceParam]:
        return {n: p for n, p in self.params().items()
                if isinstance(p, ServiceParam)}

    def get_value_opt(self, row: dict, name: str):
        tagged = self.get_or_none(name)
        if tagged is None:
            return None
        if tagged["kind"] == _COL:
            v = row.get(tagged["value"])
        else:
            v = tagged["value"]
        # numpy scalars from DataFrame rows must behave like Python scalars
        # everywhere downstream (JSON bodies, urlencode, bool checks)
        if isinstance(v, np.generic):
            v = v.item()
        return v

    def should_skip(self, row: dict) -> bool:
        """True if any required service param is null for this row."""
        for n, p in self._service_params().items():
            if p.is_required and self.get_value_opt(row, n) is None:
                return True
        return False

    def get_value_map(self, row: dict, exclude=()) -> Dict[str, Any]:
        out = {}
        for n, p in self._service_params().items():
            if n in exclude or p.is_url_param:
                continue
            v = self.get_value_opt(row, n)
            if v is not None:
                out[p.payload_name or n] = v
        return out

    def get_url_params(self, row: dict) -> Dict[str, str]:
        out = {}
        for n, p in self._service_params().items():
            if p.is_url_param:
                v = self.get_value_opt(row, n)
                if v is not None:
                    if isinstance(v, bool):
                        v = "true" if v else "false"   # not Python's str(bool)
                    out[p.payload_name or n] = v
        return out


class HasAsyncReply(Params):
    """202 + long-poll replies (``ComputerVision.scala:290-330``).

    The skeleton (202 check → location header → sleep/poll loop →
    synthesized 504 on exhaustion) is shared; service conventions differ
    only in the three hooks below — the cognitive default polls
    ``Operation-Location`` until a JSON ``status`` field completes, the
    Azure-Maps variant (``geospatial.MapsAsyncReply``) polls ``Location``
    until the HTTP status flips from 202.
    """

    polling_delay_ms = Param(int, default=300, doc="delay between polls")
    max_polling_retries = Param(int, default=100, doc="max poll attempts")

    #: response header carrying the poll URL
    _poll_location_header = "operation-location"

    def _poll_url(self, loc: str, request: HTTPRequestData) -> str:
        """Hook: decorate the poll URL (e.g. re-attach query auth)."""
        return loc

    def _poll_done(self, resp: HTTPResponseData) -> bool:
        """Hook: is this poll response terminal?"""
        import json as _json
        try:
            status = str(resp.json_content().get("status", "")).lower()
        except (_json.JSONDecodeError, ValueError):
            return False
        return status in ("succeeded", "failed", "partiallycompleted")

    def _poll(self, session, initial: HTTPResponseData,
              request: HTTPRequestData, timeout: float) -> HTTPResponseData:
        if initial.status_code != 202:
            return initial
        loc = next((h.value for h in initial.headers
                    if h.name.lower() == self._poll_location_header), None)
        if loc is None:
            return initial
        loc = self._poll_url(loc, request)
        for _ in range(self.get("max_polling_retries")):
            time.sleep(self.get("polling_delay_ms") / 1000.0)
            resp = _send(session, HTTPRequestData(url=loc, method="GET",
                                                  headers=list(request.headers)),
                         timeout)
            if resp is None:
                continue
            if self._poll_done(resp):
                return resp
        # polling exhausted: surface a timeout error instead of returning the
        # bare 202 (202 counts as OK downstream and would read as success)
        from ..io.http.schema import StatusLineData
        return HTTPResponseData(
            status_line=StatusLineData(status_code=504,
                                       reason_phrase="async polling timed out"))


def _send(session, request: HTTPRequestData,
          timeout: float) -> Optional[HTTPResponseData]:
    return advanced_handler(timeout=timeout)(session, request)


class ServiceTransformer(Transformer, HasServiceParams, HasOutputCol,
                         HasErrorCol):
    """Base for one-request-per-row service stages.

    Subclasses define ``_build_request(row) -> HTTPRequestData | None`` (a
    default JSON-POST builder is provided) and ``_parse(json) -> value``.
    """

    url = Param(str, default=None, doc="service endpoint URL")
    subscription_key = ServiceParam(str, doc="API key header value")
    key_header = Param(str, default="Ocp-Apim-Subscription-Key",
                       doc="header carrying the API key")
    method = Param(str, default="POST", doc="HTTP method")
    concurrency = Param(int, default=1, doc="max in-flight requests")
    timeout = Param(float, default=60.0, doc="per-request timeout seconds")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(output_col=f"{self.uid}_output",
                          error_col=f"{self.uid}_error")

    # -- request building ----------------------------------------------------
    def _headers(self, row: dict) -> List[HeaderData]:
        hdrs = [HeaderData("Content-Type", "application/json")]
        key = self.get_value_opt(row, "subscription_key")
        if key:
            hdrs.append(HeaderData(self.get("key_header"), key))
        return hdrs

    def _full_url(self, row: dict) -> str:
        from urllib.parse import urlencode
        url = self.get("url")
        if url is None:
            raise ValueError(f"{type(self).__name__}: url must be set")
        q = self.get_url_params(row)
        if q:
            sep = "&" if "?" in url else "?"
            url = url + sep + urlencode(q)
        return url

    def _payload(self, row: dict):
        return self.get_value_map(row, exclude=("subscription_key",))

    def _build_request(self, row: dict) -> Optional[HTTPRequestData]:
        if self.should_skip(row):
            return None
        import json as _json
        payload = self._payload(row)
        method = self.get("method")
        entity = None
        if method in ("POST", "PUT", "PATCH"):
            entity = EntityData.from_string(_json.dumps(payload))
        return HTTPRequestData(url=self._full_url(row), method=method,
                               headers=self._headers(row), entity=entity)

    # -- response parsing ----------------------------------------------------
    def _parse(self, body):
        return body

    def _parse_response(self, resp: HTTPResponseData):
        """Full-response hook; default = parse the JSON body. Binary
        endpoints (thumbnails) override this to return entity bytes —
        the reference swaps in a ``CustomOutputParser`` for the same
        purpose (``ComputerVision.scala:446-449``)."""
        return self._parse(resp.json_content())

    def _handle(self, session, request: HTTPRequestData
                ) -> Optional[HTTPResponseData]:
        resp = _send(session, request, self.get("timeout"))
        if resp is not None and isinstance(self, HasAsyncReply):
            resp = self._poll(session, resp, request, self.get("timeout"))
        return resp

    # -- execution -----------------------------------------------------------
    def _transform(self, df: DataFrame) -> DataFrame:
        # stage-level misconfiguration fails LOUDLY before any row work —
        # the per-row catch below must not demote "url never set" to a
        # silently all-errored batch
        if self.get("url") is None:
            raise ValueError(f"{type(self).__name__}: url must be set")
        rows = list(df.iter_rows())
        # per-row build failures (e.g. a column-bound param holding an
        # invalid value) land in the ERROR COLUMN like every other per-row
        # failure — one malformed row must not abort the other 999
        requests_: List[Optional[HTTPRequestData]] = []
        build_errs: List[Optional[dict]] = []
        for r in rows:
            try:
                requests_.append(self._build_request(r))
                build_errs.append(None)
            except ValueError as e:
                requests_.append(None)
                build_errs.append({"statusCode": 400,
                                   "reasonPhrase":
                                       f"request build failed: {e}"})
        c = self.get("concurrency")
        client = (AsyncHTTPClient(c, handler=self._handle) if c > 1
                  else SingleThreadedHTTPClient(handler=self._handle))
        outs, errs = [], []
        for i, (req, resp) in enumerate(zip(requests_,
                                            client.send(iter(requests_)))):
            if req is None:  # skipped (null required param) or build error
                outs.append(None)
                errs.append(build_errs[i])
                continue
            ok, err = ErrorUtils.split(resp)
            if ok is None:
                outs.append(None)
                errs.append(err)
                continue
            try:
                outs.append(self._parse_response(ok))
                errs.append(None)
            except Exception as e:
                # a 200 with an unparseable body must be distinguishable
                # from a skipped row: record it in the error column
                outs.append(None)
                errs.append({"statusCode": ok.status_code,
                             "reasonPhrase": f"response parse failed: {e}",
                             "entity": ok.string_content()[:2000]})
        return (df.with_column(self.get("output_col"), object_col(outs))
                  .with_column(self.get("error_col"), object_col(errs)))
