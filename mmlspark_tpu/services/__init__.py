"""Web-service transformers (cognitive-services parity).

Parity surface: the reference's ``cognitive`` module (8.5k LoC of Azure
REST transformers, all built on ``CognitiveServiceBase.scala``):

* ``ServiceParam[T]`` scalar-or-column duality (``HasServiceParams:29-126``)
* request assembly → ``SimpleHTTPTransformer`` composition (``:271-336``)
* async long-poll replies (``HasAsyncReply``, ``ComputerVision.scala:290-330``)
* service families: text analytics, vision, face, anomaly detection,
  translation, form recognition, search sinks.

The rebuild keeps the full request-building/response-parsing machinery and
the family APIs (URL templates, payload shapes, header auth) — pointed at a
configurable endpoint instead of hard-coded Azure hosts, since a TPU
cluster has no Azure affinity. Everything is testable against a local mock
server, as the reference tests do with recorded replies.
"""

from .base import (HasServiceParams, ServiceParam, ServiceTransformer,
                   HasAsyncReply)
from .text import (EntityDetector, EntityDetectorSDK, Healthcare,
                   HealthcareSDK, KeyPhraseExtractor, KeyPhraseExtractorSDK,
                   LanguageDetector, LanguageDetectorSDK, NER, NERSDK, PII,
                   PIISDK, TextAnalyze, TextSentiment, TextSentimentSDK)
from .vision import (AnalyzeImage, DescribeImage, GenerateThumbnails, OCR,
                     ReadImage, RecognizeDomainSpecificContent,
                     RecognizeText, TagImage, flatten_ocr, flatten_read)
from .anomaly import DetectAnomalies, DetectLastAnomaly, SimpleDetectAnomalies
from .translate import (BreakSentence, DetectLanguage, DictionaryExamples,
                        DictionaryLookup, DocumentTranslator, Translate,
                        Transliterate)
from .face import (DetectFace, FindSimilarFace, GroupFaces, IdentifyFaces,
                   VerifyFaces)
from .form import (AnalyzeBusinessCards, AnalyzeCustomModel,
                   AnalyzeIDDocuments, AnalyzeInvoices, AnalyzeLayout,
                   AnalyzeReceipts, FormOntologyLearner,
                   FormOntologyTransformer, GetCustomModel, ListCustomModels,
                   flatten_document_results, flatten_model_list,
                   flatten_page_results, flatten_read_results)
from .search import AddDocuments, AzureSearchWriter, BingImageSearch
from .speech import (ConversationTranscription, SpeechToText,
                     SpeechToTextSDK, TextToSpeech)
from .mvad import DetectMultivariateAnomaly, FitMultivariateAnomaly
from .geospatial import (AddressGeocoder, CheckPointInPolygon,
                         ReverseAddressGeocoder)

__all__ = [
    "ServiceParam", "HasServiceParams", "ServiceTransformer", "HasAsyncReply",
    "TextSentiment", "LanguageDetector", "EntityDetector", "NER",
    "KeyPhraseExtractor", "PII", "TextAnalyze", "Healthcare",
    "TextSentimentSDK", "LanguageDetectorSDK", "EntityDetectorSDK", "NERSDK",
    "KeyPhraseExtractorSDK", "PIISDK", "HealthcareSDK",
    "AnalyzeImage", "OCR", "DescribeImage", "TagImage",
    "RecognizeText", "ReadImage", "GenerateThumbnails",
    "RecognizeDomainSpecificContent", "flatten_ocr", "flatten_read",
    "DetectLastAnomaly", "DetectAnomalies", "SimpleDetectAnomalies",
    "Translate", "Transliterate", "DetectLanguage", "BreakSentence",
    "DictionaryLookup", "DictionaryExamples",
    "DetectFace", "FindSimilarFace", "VerifyFaces", "GroupFaces",
    "IdentifyFaces",
    "AnalyzeLayout", "AnalyzeInvoices", "AnalyzeReceipts",
    "AnalyzeBusinessCards", "AnalyzeIDDocuments", "ListCustomModels",
    "GetCustomModel", "AnalyzeCustomModel", "flatten_read_results",
    "flatten_page_results", "flatten_document_results", "flatten_model_list",
    "AddDocuments", "AzureSearchWriter", "BingImageSearch",
    "DocumentTranslator", "FormOntologyLearner", "FormOntologyTransformer",
    "SpeechToText", "SpeechToTextSDK", "ConversationTranscription",
    "TextToSpeech",
    "FitMultivariateAnomaly", "DetectMultivariateAnomaly",
    "AddressGeocoder", "ReverseAddressGeocoder", "CheckPointInPolygon",
]
