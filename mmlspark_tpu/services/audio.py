"""Audio stream plumbing for streaming speech.

Parity surface: the reference's ``AudioStreams.scala`` (94 LoC) — the
``PullAudioInputStream``/``PushAudioInputStream`` pair the Speech SDK reads
audio through, plus WAV header handling (the SDK's
``AudioStreamFormat.getWaveFormatPCM``). Pure-Python equivalents:

* :class:`AudioFormat` — PCM wave format (rate / bits / channels), parsed
  from RIFF/WAVE headers or declared directly.
* :class:`PushAudioStream` — thread-safe producer/consumer byte stream
  (caller pushes chunks, the recognizer pulls frames).
* :class:`PullAudioStream` — wraps bytes / file-like objects.
* :func:`parse_wav` — RIFF chunk walk → (AudioFormat, pcm_payload).
"""

from __future__ import annotations

import io
import struct
import threading
from dataclasses import dataclass
from typing import Optional, Tuple, Union

__all__ = ["AudioFormat", "PushAudioStream", "PullAudioStream", "parse_wav"]


@dataclass(frozen=True)
class AudioFormat:
    sample_rate: int = 16000
    bits_per_sample: int = 16
    channels: int = 1

    @property
    def bytes_per_second(self) -> int:
        return self.sample_rate * (self.bits_per_sample // 8) * self.channels

    def frame_bytes(self, millis: int) -> int:
        """Whole-sample-aligned byte count for a frame of ``millis``."""
        step = (self.bits_per_sample // 8) * self.channels
        n = self.bytes_per_second * millis // 1000
        return max(step, n - n % step)


def parse_wav(data: bytes) -> Tuple[AudioFormat, bytes]:
    """RIFF/WAVE → (format, PCM payload). Non-PCM codecs are rejected the
    way the reference surfaces unsupported formats (fail fast, not noise)."""
    if len(data) < 12 or data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise ValueError("not a RIFF/WAVE file")
    fmt: Optional[AudioFormat] = None
    payload: Optional[bytes] = None
    off = 12
    while off + 8 <= len(data):
        cid = data[off:off + 4]
        size = struct.unpack("<I", data[off + 4:off + 8])[0]
        body = data[off + 8:off + 8 + size]
        if cid == b"fmt ":
            if len(body) < 16:
                raise ValueError("truncated fmt chunk")
            codec, channels, rate = struct.unpack("<HHI", body[:8])
            bits = struct.unpack("<H", body[14:16])[0]
            if codec not in (1, 0xFFFE):  # PCM / extensible
                raise ValueError(f"unsupported WAV codec {codec}; only PCM")
            fmt = AudioFormat(rate, bits, channels)
        elif cid == b"data":
            payload = body
        off += 8 + size + (size & 1)  # chunks are word-aligned
    if fmt is None or payload is None:
        raise ValueError("WAV missing fmt or data chunk")
    return fmt, payload


class PushAudioStream:
    """Producer pushes chunks; consumer reads frames. ``close()`` signals
    end-of-audio (reference: ``PushAudioInputStream.close``)."""

    def __init__(self, fmt: AudioFormat = AudioFormat()):
        self.format = fmt
        self._buf = bytearray()
        self._closed = False
        self._cond = threading.Condition()

    def write(self, chunk: bytes) -> None:
        with self._cond:
            if self._closed:
                raise ValueError("push stream already closed")
            self._buf.extend(chunk)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def read(self, n: int, timeout: Optional[float] = None) -> bytes:
        """Up to ``n`` bytes; blocks until data or close. b'' = end of
        audio; a stalled producer raises TimeoutError instead of silently
        truncating the stream."""
        with self._cond:
            while not self._buf and not self._closed:
                if not self._cond.wait(timeout):
                    raise TimeoutError(
                        f"no audio pushed within {timeout}s (close() the "
                        f"stream to signal end-of-audio)")
            take = bytes(self._buf[:n])
            del self._buf[:n]
            return take


class PullAudioStream:
    """Reads from bytes or a binary file-like object."""

    def __init__(self, source: Union[bytes, bytearray, io.IOBase],
                 fmt: AudioFormat = AudioFormat()):
        if isinstance(source, (bytes, bytearray)):
            source = io.BytesIO(bytes(source))
        self._f = source
        self.format = fmt

    @classmethod
    def from_wav(cls, data: bytes) -> "PullAudioStream":
        fmt, payload = parse_wav(data)
        return cls(payload, fmt)

    def read(self, n: int, timeout: Optional[float] = None) -> bytes:
        return self._f.read(n) or b""

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass
