"""Computer-vision service transformers.

Parity: ``cognitive/.../ComputerVision.scala`` (630 LoC): ``AnalyzeImage``,
``OCR``, ``DescribeImage``, ``TagImage`` — POST either ``{"url": ...}`` or
raw image bytes; OCR-style calls long-poll via ``HasAsyncReply``
(``ComputerVision.scala:290-330``).
"""

from __future__ import annotations

from typing import Optional

from ..io.http.schema import EntityData, HeaderData, HTTPRequestData
from .base import HasAsyncReply, ServiceParam, ServiceTransformer

__all__ = ["VisionBase", "AnalyzeImage", "OCR", "DescribeImage", "TagImage"]


class VisionBase(ServiceTransformer):
    image_url = ServiceParam(str, doc="URL of the image to analyze")
    image_bytes = ServiceParam(bytes, doc="raw image bytes (alternative to url)")

    def _build_request(self, row: dict) -> Optional[HTTPRequestData]:
        url_v = self.get_value_opt(row, "image_url")
        bytes_v = self.get_value_opt(row, "image_bytes")
        if url_v is None and bytes_v is None:
            return None
        if self.should_skip(row):
            return None
        headers = self._headers(row)
        if bytes_v is not None:
            headers = [h for h in headers if h.name != "Content-Type"]
            headers.append(HeaderData("Content-Type", "application/octet-stream"))
            entity = EntityData(content=bytes(bytes_v),
                                content_length=len(bytes_v))
        else:
            import json as _json
            entity = EntityData.from_string(_json.dumps({"url": url_v}))
        return HTTPRequestData(url=self._full_url(row), method="POST",
                               headers=headers, entity=entity)


class AnalyzeImage(VisionBase):
    """Parity: ``AnalyzeImage`` — visualFeatures/details/language URL params."""

    visual_features = ServiceParam(str, is_url_param=True,
                                   payload_name="visualFeatures",
                                   doc="comma-joined feature list")
    details = ServiceParam(str, is_url_param=True, doc="celebrity/landmark")
    language = ServiceParam(str, is_url_param=True, default="en",
                            doc="response language")


class OCR(VisionBase, HasAsyncReply):
    """Parity: ``OCR``/``ReadImage`` — async 202 + Operation-Location poll."""

    detect_orientation = ServiceParam(bool, is_url_param=True,
                                      payload_name="detectOrientation",
                                      doc="detect text orientation")
    language = ServiceParam(str, is_url_param=True, doc="OCR language")


class DescribeImage(VisionBase):
    max_candidates = ServiceParam(int, is_url_param=True,
                                  payload_name="maxCandidates", default=1,
                                  doc="number of caption candidates")


class TagImage(VisionBase):
    language = ServiceParam(str, is_url_param=True, default="en",
                            doc="response language")

    def _parse(self, body):
        return body.get("tags", body)
