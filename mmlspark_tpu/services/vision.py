"""Computer-vision service transformers.

Parity: ``cognitive/.../ComputerVision.scala`` (630 LoC) — op-for-op:
``OCR``, ``RecognizeText``, ``ReadImage``, ``GenerateThumbnails``,
``AnalyzeImage``, ``RecognizeDomainSpecificContent``, ``TagImage``,
``DescribeImage``. Each POSTs either ``{"url": ...}`` or raw image bytes;
the Read/RecognizeText family long-polls the 202 Operation-Location
(``HasAsyncReply``, ``ComputerVision.scala:290-330``);
``GenerateThumbnails`` returns raw binary (its reference overrides the
output parser to the entity bytes, ``ComputerVision.scala:437-455``);
``RecognizeDomainSpecificContent`` builds its URL per row from the model
name (``ComputerVision.scala:544-565``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..io.http.schema import EntityData, HeaderData, HTTPRequestData
from .base import HasAsyncReply, ServiceParam, ServiceTransformer

__all__ = ["VisionBase", "AnalyzeImage", "OCR", "RecognizeText",
           "ReadImage", "GenerateThumbnails",
           "RecognizeDomainSpecificContent", "DescribeImage", "TagImage",
           "flatten_ocr", "flatten_read"]


class VisionBase(ServiceTransformer):
    image_url = ServiceParam(str, doc="URL of the image to analyze")
    image_bytes = ServiceParam(bytes, doc="raw image bytes (alternative to url)")

    def _build_request(self, row: dict) -> Optional[HTTPRequestData]:
        url_v = self.get_value_opt(row, "image_url")
        bytes_v = self.get_value_opt(row, "image_bytes")
        if url_v is None and bytes_v is None:
            return None
        if self.should_skip(row):
            return None
        headers = self._headers(row)
        if bytes_v is not None:
            headers = [h for h in headers if h.name != "Content-Type"]
            headers.append(HeaderData("Content-Type", "application/octet-stream"))
            entity = EntityData(content=bytes(bytes_v),
                                content_length=len(bytes_v))
        else:
            import json as _json
            entity = EntityData.from_string(_json.dumps({"url": url_v}))
        return HTTPRequestData(url=self._full_url(row), method="POST",
                               headers=headers, entity=entity)


class AnalyzeImage(VisionBase):
    """Parity: ``AnalyzeImage`` — visualFeatures/details/language URL params."""

    visual_features = ServiceParam(str, is_url_param=True,
                                   payload_name="visualFeatures",
                                   doc="comma-joined feature list")
    details = ServiceParam(str, is_url_param=True, doc="celebrity/landmark")
    language = ServiceParam(str, is_url_param=True, default="en",
                            doc="response language")


class OCR(VisionBase, HasAsyncReply):
    """Parity: ``OCR``/``ReadImage`` — async 202 + Operation-Location poll."""

    detect_orientation = ServiceParam(bool, is_url_param=True,
                                      payload_name="detectOrientation",
                                      doc="detect text orientation")
    language = ServiceParam(str, is_url_param=True, doc="OCR language")


class RecognizeText(VisionBase, HasAsyncReply):
    """Parity: ``RecognizeText`` (``ComputerVision.scala:358-386``) —
    async Printed/Handwritten recognition; ``mode`` is a URL param with
    the reference's closed value set."""

    mode = ServiceParam(str, is_url_param=True,
                        doc="'Printed' or 'Handwritten'")

    def _build_request(self, row):
        m = self.get_value_opt(row, "mode")
        if m is not None and m not in ("Printed", "Handwritten"):
            raise ValueError(f"mode must be Printed or Handwritten, got {m!r}")
        return super()._build_request(row)


class ReadImage(VisionBase, HasAsyncReply):
    """Parity: ``ReadImage`` (``ComputerVision.scala:404-433``) — the Read
    v3.x async API; ``language`` forces a specific BCP-47 code from the
    reference's supported set (unset = auto-detect)."""

    _LANGS = ("en", "nl", "fr", "de", "it", "pt", "es")
    language = ServiceParam(str, is_url_param=True,
                            doc="BCP-47 code forcing the doc language")

    def _build_request(self, row):
        lang = self.get_value_opt(row, "language")
        if lang is not None and lang not in self._LANGS:
            raise ValueError(
                f"language must be one of {self._LANGS}, got {lang!r}")
        return super()._build_request(row)


class GenerateThumbnails(VisionBase):
    """Parity: ``GenerateThumbnails`` (``ComputerVision.scala:437-455``) —
    returns the thumbnail BYTES (the reference swaps in a custom output
    parser returning the raw entity)."""

    width = ServiceParam(int, is_url_param=True, is_required=True,
                         doc="thumbnail width")
    height = ServiceParam(int, is_url_param=True, is_required=True,
                          doc="thumbnail height")
    smart_cropping = ServiceParam(bool, is_url_param=True,
                                  payload_name="smartCropping",
                                  doc="crop around the region of interest")

    def _parse_response(self, resp):
        return bytes(resp.entity.content) if resp.entity else None


class RecognizeDomainSpecificContent(VisionBase):
    """Parity: ``RecognizeDomainSpecificContent``
    (``ComputerVision.scala:544-565``) — the model name becomes a URL
    segment (``/models/{model}/analyze``), built per row like the
    reference's ``prepareUrl``."""

    model = ServiceParam(str, is_required=True,
                         doc="domain model: celebrities or landmarks")

    def _full_url(self, row: dict) -> str:
        base = super()._full_url(row)
        model = self.get_value_opt(row, "model")
        return f"{base.rstrip('/')}/models/{model}/analyze"

    def _payload(self, row: dict):
        out = super()._payload(row)
        out.pop("model", None)          # rides in the URL, not the body
        return out


class DescribeImage(VisionBase):
    max_candidates = ServiceParam(int, is_url_param=True,
                                  payload_name="maxCandidates", default=1,
                                  doc="number of caption candidates")


class TagImage(VisionBase):
    language = ServiceParam(str, is_url_param=True, default="en",
                            doc="response language")

    def _parse(self, body):
        return body.get("tags", body)


def flatten_ocr(col: np.ndarray) -> np.ndarray:
    """OCR responses → one text string per row (parity:
    ``OCR.flatten``, ``ComputerVision.scala:163-181``)."""
    out = np.empty(len(col), dtype=object)
    for i, body in enumerate(col):
        if not isinstance(body, dict):
            out[i] = None
            continue
        out[i] = " ".join(
            " ".join(" ".join(w.get("text", "") for w in ln.get("words", []))
                     for ln in region.get("lines", []))
            for region in body.get("regions", []))
    return out


def flatten_read(col: np.ndarray) -> np.ndarray:
    """Read/RecognizeText responses → one text string per row (parity:
    ``ReadImage.flatten``/``RecognizeText.flatten``,
    ``ComputerVision.scala:197-210,389-402``)."""
    out = np.empty(len(col), dtype=object)
    for i, body in enumerate(col):
        if not isinstance(body, dict):
            out[i] = None
            continue
        if "analyzeResult" in body:      # Read v3.x
            pages = body["analyzeResult"].get("readResults", [])
        else:                            # RecognizeText v2.0
            rr = body.get("recognitionResult")
            pages = [rr] if rr else []
        out[i] = " ".join(
            " ".join(ln.get("text", "") for ln in page.get("lines", []))
            for page in pages)
    return out
