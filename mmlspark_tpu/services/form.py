"""Form-recognizer service transformers.

Parity: ``cognitive/.../FormRecognizer.scala`` (353 LoC): layout/invoice/
receipt analysis — async 202 + Operation-Location polling like OCR.
"""

from __future__ import annotations

from ..core.dataframe import DataFrame, object_col
from ..core.params import Param
from ..core.pipeline import Estimator, Model
from .base import HasAsyncReply, ServiceParam
from .vision import VisionBase

__all__ = ["FormRecognizerBase", "AnalyzeLayout", "AnalyzeInvoices",
           "AnalyzeReceipts", "FormOntologyLearner",
           "FormOntologyTransformer"]


class FormRecognizerBase(VisionBase, HasAsyncReply):
    """POST document url/bytes, long-poll the analyzeResults."""

    def _parse(self, body):
        if isinstance(body, dict) and "analyzeResult" in body:
            return body["analyzeResult"]
        return body


class AnalyzeLayout(FormRecognizerBase):
    pass


class AnalyzeInvoices(FormRecognizerBase):
    include_text_details = ServiceParam(bool, is_url_param=True,
                                        payload_name="includeTextDetails",
                                        doc="include raw OCR lines")


class AnalyzeReceipts(FormRecognizerBase):
    include_text_details = ServiceParam(bool, is_url_param=True,
                                        payload_name="includeTextDetails",
                                        doc="include raw OCR lines")


class FormOntologyLearner(Estimator):
    """Learn a unified field ontology from form-analysis outputs.

    Parity: ``cognitive/.../FormOntologyLearner.scala:42-75`` — merge the
    ``fields`` structures of every row's AnalyzeResponse into one schema;
    the fitted transformer projects each response onto that schema as a
    plain {field: value} struct column.
    """

    input_col = Param(str, default="form", doc="column of analyze outputs")
    output_col = Param(str, default="ontology", doc="extracted struct column")

    @staticmethod
    def _fields_of(resp) -> dict:
        if resp is None:
            return {}
        ar = resp.get("analyzeResult", resp) if isinstance(resp, dict) else {}
        docs = ar.get("documentResults") or []
        return (docs[0] or {}).get("fields", {}) if docs else {}

    def _fit(self, df: DataFrame) -> "FormOntologyTransformer":
        merged: dict = {}
        for resp in df[self.get("input_col")]:
            for name, spec in self._fields_of(resp).items():
                t = (spec or {}).get("type", "string")
                prev = merged.get(name)
                # type union: conflicting types widen to string
                merged[name] = t if prev in (None, t) else "string"
        m = FormOntologyTransformer()
        m.set(input_col=self.get("input_col"),
              output_col=self.get("output_col"),
              ontology={k: merged[k] for k in sorted(merged)})
        return m


class FormOntologyTransformer(Model):
    input_col = Param(str, default="form", doc="column of analyze outputs")
    output_col = Param(str, default="ontology", doc="extracted struct column")
    ontology = Param(dict, default={}, doc="field name → type")

    _VALUE_KEYS = {"number": "valueNumber", "date": "valueDate",
                   "time": "valueTime", "phoneNumber": "valuePhoneNumber",
                   "integer": "valueInteger", "string": "valueString"}

    def _transform(self, df: DataFrame) -> DataFrame:
        onto = self.get("ontology")
        out = []
        for resp in df[self.get("input_col")]:
            fields = FormOntologyLearner._fields_of(resp)
            row = {}
            for name, t in onto.items():
                spec = fields.get(name) or {}
                row[name] = spec.get(self._VALUE_KEYS.get(t, "valueString"),
                                     spec.get("text"))
            out.append(row)
        return df.with_column(self.get("output_col"), object_col(out))
