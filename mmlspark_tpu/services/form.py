"""Form-recognizer service transformers.

Parity: ``cognitive/.../FormRecognizer.scala`` (353 LoC): layout/invoice/
receipt analysis — async 202 + Operation-Location polling like OCR.
"""

from __future__ import annotations

from .base import HasAsyncReply, ServiceParam
from .vision import VisionBase

__all__ = ["FormRecognizerBase", "AnalyzeLayout", "AnalyzeInvoices",
           "AnalyzeReceipts"]


class FormRecognizerBase(VisionBase, HasAsyncReply):
    """POST document url/bytes, long-poll the analyzeResults."""

    def _parse(self, body):
        if isinstance(body, dict) and "analyzeResult" in body:
            return body["analyzeResult"]
        return body


class AnalyzeLayout(FormRecognizerBase):
    pass


class AnalyzeInvoices(FormRecognizerBase):
    include_text_details = ServiceParam(bool, is_url_param=True,
                                        payload_name="includeTextDetails",
                                        doc="include raw OCR lines")


class AnalyzeReceipts(FormRecognizerBase):
    include_text_details = ServiceParam(bool, is_url_param=True,
                                        payload_name="includeTextDetails",
                                        doc="include raw OCR lines")
