"""Form-recognizer service transformers.

Parity: ``cognitive/.../FormRecognizer.scala`` (438 LoC) op-for-op:
``AnalyzeLayout`` (language/pages/readingOrder, ``:170-199``),
``AnalyzeReceipts``, ``AnalyzeBusinessCards``, ``AnalyzeInvoices``,
``AnalyzeIDDocuments`` (prebuilt models, async 202 + Operation-Location
polling), the custom-model trio ``ListCustomModels`` (GET + ``op``,
``:259-280``) / ``GetCustomModel`` (GET ``/{modelId}`` + ``includeKeys``,
``:284-322``) / ``AnalyzeCustomModel`` (``/{modelId}/analyze``,
``:326-360``), and the ``FormsFlatteners`` UDF quartet (``:84-166``) as
plain column functions like vision's ``flatten_ocr``.
"""

from __future__ import annotations

import json as _json

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import Param
from ..core.pipeline import Estimator, Model
from .base import HasAsyncReply, ServiceParam, ServiceTransformer
from .vision import VisionBase

__all__ = ["FormRecognizerBase", "AnalyzeLayout", "AnalyzeInvoices",
           "AnalyzeReceipts", "AnalyzeBusinessCards", "AnalyzeIDDocuments",
           "ListCustomModels", "GetCustomModel", "AnalyzeCustomModel",
           "FormOntologyLearner", "FormOntologyTransformer",
           "flatten_read_results", "flatten_page_results",
           "flatten_document_results", "flatten_model_list"]


class FormRecognizerBase(VisionBase, HasAsyncReply):
    """POST document url/bytes, long-poll the analyzeResults."""

    def _parse(self, body):
        if isinstance(body, dict) and "analyzeResult" in body:
            return body["analyzeResult"]
        return body


class AnalyzeLayout(FormRecognizerBase):
    """Parity: ``AnalyzeLayout`` (``FormRecognizer.scala:170-199``)."""

    language = ServiceParam(str, is_url_param=True,
                            doc="BCP-47 language code of the text")
    pages = ServiceParam(str, is_url_param=True,
                         doc="page selection, e.g. '1-3,5'")
    reading_order = ServiceParam(str, is_url_param=True,
                                 payload_name="readingOrder",
                                 doc="'basic' or 'natural'")

    def _build_request(self, row):
        if self.should_skip(row):  # null required params skip, not 400
            return None
        ro = self.get_value_opt(row, "reading_order")
        if ro is not None and ro not in ("basic", "natural"):
            raise ValueError(
                f"reading_order must be basic or natural, got {ro!r}")
        return super()._build_request(row)


class AnalyzeInvoices(FormRecognizerBase):
    """Parity: ``AnalyzeInvoices`` (``FormRecognizer.scala:231-241``)."""

    include_text_details = ServiceParam(bool, is_url_param=True,
                                        payload_name="includeTextDetails",
                                        doc="include raw OCR lines")
    pages = ServiceParam(str, is_url_param=True,
                         doc="page selection, e.g. '1-3,5'")
    locale = ServiceParam(str, is_url_param=True,
                          doc="document locale, e.g. en-US")


class AnalyzeReceipts(FormRecognizerBase):
    """Parity: ``AnalyzeReceipts`` (``FormRecognizer.scala:203-213``)."""

    include_text_details = ServiceParam(bool, is_url_param=True,
                                        payload_name="includeTextDetails",
                                        doc="include raw OCR lines")
    pages = ServiceParam(str, is_url_param=True,
                         doc="page selection, e.g. '1-3,5'")
    locale = ServiceParam(str, is_url_param=True,
                          doc="receipt locale, e.g. en-US")


class AnalyzeBusinessCards(FormRecognizerBase):
    """Parity: ``AnalyzeBusinessCards`` (``FormRecognizer.scala:217-227``)."""

    include_text_details = ServiceParam(bool, is_url_param=True,
                                        payload_name="includeTextDetails",
                                        doc="include raw OCR lines")
    pages = ServiceParam(str, is_url_param=True,
                         doc="page selection, e.g. '1-3,5'")
    locale = ServiceParam(str, is_url_param=True,
                          doc="card locale, e.g. en-US")


class AnalyzeIDDocuments(FormRecognizerBase):
    """Parity: ``AnalyzeIDDocuments`` (``FormRecognizer.scala:245-255``)."""

    include_text_details = ServiceParam(bool, is_url_param=True,
                                        payload_name="includeTextDetails",
                                        doc="include raw OCR lines")
    pages = ServiceParam(str, is_url_param=True,
                         doc="page selection, e.g. '1-3,5'")


def _model_url(base_url: str, model_id, q: dict, suffix: str = "") -> str:
    """``{base}/{modelId}{suffix}?{query}`` with the model id escaped and
    any query already on the base URL preserved (the base class handles
    this merge for plain endpoints; custom-model URLs splice a path
    segment so they rebuild here)."""
    from urllib.parse import quote, urlencode
    if base_url is None:
        raise ValueError("url must be set")
    base, _, existing = base_url.partition("?")
    url = f"{base.rstrip('/')}/{quote(str(model_id), safe='')}{suffix}"
    query = "&".join(x for x in (existing, urlencode(q)) if x)
    return url + (f"?{query}" if query else "")


class ListCustomModels(ServiceTransformer):
    """Parity: ``ListCustomModels`` (``FormRecognizer.scala:259-280``) —
    GET the trained-model inventory; ``op`` selects summary vs full."""

    method = Param(str, default="GET", doc="HTTP method")
    op = ServiceParam(str, is_url_param=True,
                      doc="'summary' or 'full' model listing")


class GetCustomModel(ServiceTransformer):
    """Parity: ``GetCustomModel`` (``FormRecognizer.scala:284-322``) —
    GET ``/{modelId}``; ``includeKeys`` adds extracted keys."""

    method = Param(str, default="GET", doc="HTTP method")
    model_id = ServiceParam(str, is_required=True, doc="model identifier")
    include_keys = ServiceParam(bool, is_url_param=True,
                                payload_name="includeKeys",
                                doc="include extracted keys")

    def _full_url(self, row: dict) -> str:
        return _model_url(self.get("url"),
                          self.get_value_opt(row, "model_id"),
                          self.get_url_params(row))


class AnalyzeCustomModel(FormRecognizerBase):
    """Parity: ``AnalyzeCustomModel`` (``FormRecognizer.scala:326-360``) —
    ``/{modelId}/analyze`` built per row like the reference's prepareUrl."""

    model_id = ServiceParam(str, is_required=True, doc="model identifier")
    include_text_details = ServiceParam(bool, is_url_param=True,
                                        payload_name="includeTextDetails",
                                        doc="include raw OCR lines")

    def _full_url(self, row: dict) -> str:
        return _model_url(self.get("url"),
                          self.get_value_opt(row, "model_id"),
                          self.get_url_params(row), suffix="/analyze")


# -- FormsFlatteners (FormRecognizer.scala:84-166) as column functions ------

def _as_analyze_result(body):
    if not isinstance(body, dict):
        return {}
    return body.get("analyzeResult", body)


def flatten_read_results(col: np.ndarray) -> np.ndarray:
    """AnalyzeResponse → all OCR line text joined (parity:
    ``FormsFlatteners.flattenReadResults``)."""
    out = np.empty(len(col), dtype=object)
    for i, body in enumerate(col):
        ar = _as_analyze_result(body)
        out[i] = " ".join(
            " ".join(ln.get("text", "") for ln in page.get("lines", []))
            for page in ar.get("readResults", [])) if ar else None
    return out


def flatten_page_results(col: np.ndarray) -> np.ndarray:
    """AnalyzeResponse → key-value pairs + table text (parity:
    ``FormsFlatteners.flattenPageResults``)."""
    out = np.empty(len(col), dtype=object)
    for i, body in enumerate(col):
        ar = _as_analyze_result(body)
        if not ar:
            out[i] = None
            continue
        pages = ar.get("pageResults", [])
        kvs = "\n\n".join(
            "\n".join(f"key: {(kv.get('key') or {}).get('text')} "
                      f"value: {(kv.get('value') or {}).get('text')}"
                      for kv in page.get("keyValuePairs", []))
            for page in pages)
        tables = "\n\n".join(
            "\n".join(" | ".join(c.get("text", "")
                                 for c in tbl.get("cells", []))
                      for tbl in page.get("tables", []))
            for page in pages)
        out[i] = f"KeyValuePairs: {kvs}\n\n\nTables: {tables}"
    return out


def flatten_document_results(col: np.ndarray) -> np.ndarray:
    """AnalyzeResponse → document ``fields`` JSON per row (parity:
    ``FormsFlatteners.flattenDocumentResults``)."""
    out = np.empty(len(col), dtype=object)
    for i, body in enumerate(col):
        ar = _as_analyze_result(body)
        out[i] = "\n".join(
            _json.dumps((doc or {}).get("fields", {}), sort_keys=True)
            for doc in ar.get("documentResults", [])) if ar else None
    return out


def flatten_model_list(col: np.ndarray) -> np.ndarray:
    """ListCustomModels response → space-joined model ids (parity:
    ``FormsFlatteners.flattenModelList``)."""
    out = np.empty(len(col), dtype=object)
    for i, body in enumerate(col):
        if not isinstance(body, dict):
            out[i] = None
            continue
        out[i] = " ".join(m.get("modelId", "")
                          for m in body.get("modelList", []))
    return out


class FormOntologyLearner(Estimator):
    """Learn a unified field ontology from form-analysis outputs.

    Parity: ``cognitive/.../FormOntologyLearner.scala:42-75`` — merge the
    ``fields`` structures of every row's AnalyzeResponse into one schema;
    the fitted transformer projects each response onto that schema as a
    plain {field: value} struct column.
    """

    input_col = Param(str, default="form", doc="column of analyze outputs")
    output_col = Param(str, default="ontology", doc="extracted struct column")

    @staticmethod
    def _fields_of(resp) -> dict:
        if resp is None:
            return {}
        ar = resp.get("analyzeResult", resp) if isinstance(resp, dict) else {}
        docs = ar.get("documentResults") or []
        return (docs[0] or {}).get("fields", {}) if docs else {}

    def _fit(self, df: DataFrame) -> "FormOntologyTransformer":
        merged: dict = {}
        for resp in df[self.get("input_col")]:
            for name, spec in self._fields_of(resp).items():
                t = (spec or {}).get("type", "string")
                prev = merged.get(name)
                # type union: conflicting types widen to string
                merged[name] = t if prev in (None, t) else "string"
        m = FormOntologyTransformer()
        m.set(input_col=self.get("input_col"),
              output_col=self.get("output_col"),
              ontology={k: merged[k] for k in sorted(merged)})
        return m


class FormOntologyTransformer(Model):
    input_col = Param(str, default="form", doc="column of analyze outputs")
    output_col = Param(str, default="ontology", doc="extracted struct column")
    ontology = Param(dict, default={}, doc="field name → type")

    _VALUE_KEYS = {"number": "valueNumber", "date": "valueDate",
                   "time": "valueTime", "phoneNumber": "valuePhoneNumber",
                   "integer": "valueInteger", "string": "valueString"}

    def _transform(self, df: DataFrame) -> DataFrame:
        onto = self.get("ontology")
        out = []
        for resp in df[self.get("input_col")]:
            fields = FormOntologyLearner._fields_of(resp)
            row = {}
            for name, t in onto.items():
                spec = fields.get(name) or {}
                row[name] = spec.get(self._VALUE_KEYS.get(t, "valueString"),
                                     spec.get("text"))
            out.append(row)
        return df.with_column(self.get("output_col"), object_col(out))
