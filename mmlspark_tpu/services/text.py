"""Text-analytics service transformers.

Parity: ``cognitive/.../TextAnalytics.scala`` (626 LoC): ``TextSentiment``,
``LanguageDetector``, ``EntityDetector``, ``NER``, ``KeyPhraseExtractor`` —
all POST ``{"documents": [{id, text, language}]}`` and unpack the per-doc
result. Rows are batched per request like the reference's minibatched text
analytics (one row per document id here).
"""

from __future__ import annotations

from typing import Optional

from .base import ServiceParam, ServiceTransformer

__all__ = ["TextAnalyticsBase", "TextSentiment", "LanguageDetector",
           "EntityDetector", "NER", "KeyPhraseExtractor"]


class TextAnalyticsBase(ServiceTransformer):
    text = ServiceParam(str, is_required=True, doc="document text")
    language = ServiceParam(str, doc="document language hint")

    def _payload(self, row: dict):
        doc = {"id": "0", "text": self.get_value_opt(row, "text")}
        lang = self.get_value_opt(row, "language")
        if lang:
            doc["language"] = lang
        return {"documents": [doc]}

    def _parse(self, body):
        docs = body.get("documents") or []
        return docs[0] if docs else None


class TextSentiment(TextAnalyticsBase):
    """Parity: ``TextSentiment`` — sentiment label + confidence scores."""

    def _parse(self, body):
        doc = super()._parse(body)
        if doc is None:
            return None
        return {"sentiment": doc.get("sentiment"),
                "confidenceScores": doc.get("confidenceScores"),
                "sentences": doc.get("sentences")}


class LanguageDetector(TextAnalyticsBase):
    """Parity: ``LanguageDetector`` — detectedLanguage per document."""

    def _parse(self, body):
        doc = super()._parse(body)
        return None if doc is None else doc.get("detectedLanguage", doc)


class EntityDetector(TextAnalyticsBase):
    """Parity: ``EntityDetector`` (linked entities)."""

    def _parse(self, body):
        doc = super()._parse(body)
        return None if doc is None else doc.get("entities", doc)


class NER(TextAnalyticsBase):
    """Parity: ``NER`` (named entity recognition)."""

    def _parse(self, body):
        doc = super()._parse(body)
        return None if doc is None else doc.get("entities", doc)


class KeyPhraseExtractor(TextAnalyticsBase):
    """Parity: ``KeyPhraseExtractor``."""

    def _parse(self, body):
        doc = super()._parse(body)
        return None if doc is None else doc.get("keyPhrases", doc)
