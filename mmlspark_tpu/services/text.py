"""Text-analytics service transformers — full reference breadth.

Parity: ``cognitive/.../TextAnalytics.scala`` (626 LoC) op-for-op:
``TextSentiment`` (+ ``opinionMining`` URL param, ``:287-310``),
``LanguageDetector``, ``EntityDetector``, ``NER``, ``KeyPhraseExtractor``,
``PII`` (+ ``domain``/``piiCategories`` URL params, ``:338-360``) and the
async multi-task ``TextAnalyze`` (``:414-560``: five task lists, one
``/analyze`` job per document batch, 202 + Operation-Location long-poll
with ``$top=25`` forced onto the poll URL so a full 25-doc batch comes
back in one page, ``modifyPollingURI :490-509``). The v3 mixin params
(``model-version``/``showStats``/``stringIndexType``,
``TextAnalytics.scala:193-216``) ride as URL params.

Parity: ``cognitive/.../TextAnalyticsSDK.scala`` (751 LoC): the SDK
variants batch documents per request — string columns auto-batch through
``FixedMiniBatchTransformer`` (default 5) and unpack per-document results
back onto rows (``shouldAutoBatch``/``transform``,
``TextAnalyticsSDK.scala:139-186``; doc/error matching by integer id as
in ``TextAnalytics.scala:115-134`` ``unpackBatchUDF``). Here the same
behavior lives in :class:`TextAnalyticsBase` directly: a row whose bound
``text`` value is a LIST is one user-batched request (array output); rows
with scalar text are grouped ``batch_size`` docs per request and results
scatter back one per row. ``*SDK`` aliases pin the SDK default
``batchSize=5`` and carry ``HealthcareSDK`` (``:312-341``), which has no
plain-REST sibling in the reference.
"""

from __future__ import annotations

import json as _json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import Param
from ..io.http.http_transformer import ErrorUtils
from ..io.http.schema import EntityData, HTTPRequestData
from .base import HasAsyncReply, ServiceParam, ServiceTransformer

__all__ = ["TextAnalyticsBase", "TextSentiment", "LanguageDetector",
           "EntityDetector", "NER", "KeyPhraseExtractor", "PII",
           "TextAnalyze", "Healthcare",
           "TextSentimentSDK", "LanguageDetectorSDK", "EntityDetectorSDK",
           "NERSDK", "KeyPhraseExtractorSDK", "PIISDK", "HealthcareSDK"]

#: closed value set of the reference's stringIndexType param
#: (``TextAnalytics.scala:209-216``)
_STRING_INDEX_TYPES = ("TextElements_v8", "UnicodeCodePoint",
                       "Utf16CodeUnit")


class TextAnalyticsBase(ServiceTransformer):
    """Shared documents/errors request-reply shape
    (``TextAnalytics.scala:53-183``): POST
    ``{"documents": [{id, text, language?}]}``, unpack ``documents`` +
    ``errors`` matched by integer id. ``batch_size`` groups scalar-text
    rows into one request (the SDK variant's auto-batching); a list-typed
    text value is one user-batched request whose output is the per-doc
    array."""

    text = ServiceParam(str, is_required=True,
                        doc="document text (str) or document batch (list)")
    language = ServiceParam(str, doc="language hint: str broadcast to the "
                                     "batch, or per-document list")
    model_version = ServiceParam(str, is_url_param=True,
                                 payload_name="model-version",
                                 doc="service model version, e.g. 'latest'")
    show_stats = ServiceParam(bool, is_url_param=True,
                              payload_name="showStats",
                              doc="return per-document statistics")
    batch_size = Param(int, default=1,
                       doc="scalar-text rows grouped per request")

    # -- per-row document spec ----------------------------------------------
    def _doc_spec(self, row: dict
                  ) -> Optional[Tuple[List[Optional[str]],
                                      List[Optional[str]], bool]]:
        """(texts, langs, user_batched) for a row, or None to skip it."""
        if self.should_skip(row):
            return None
        t = self.get_value_opt(row, "text")
        if t is None:
            return None
        lang = self.get_value_opt(row, "language")
        if isinstance(t, (list, tuple, np.ndarray)):
            texts = [None if x is None else str(x) for x in list(t)]
            if isinstance(lang, (list, tuple, np.ndarray)):
                langs = [None if x is None else str(x) for x in list(lang)]
                if len(langs) == 1:  # single hint broadcasts to the batch
                    langs = langs * len(texts)
            elif lang is None:
                langs = [None] * len(texts)
            else:
                langs = [str(lang)] * len(texts)
            if len(langs) != len(texts):
                raise ValueError(
                    f"language batch has {len(langs)} entries for "
                    f"{len(texts)} documents")
            return texts, langs, True
        if isinstance(lang, (list, tuple, np.ndarray)):
            lang = list(lang)[0] if len(lang) else None
        return [str(t)], [None if lang is None else str(lang)], False

    @staticmethod
    def _docs_payload(texts, langs) -> List[Dict[str, Any]]:
        docs = []
        for k, (t, lang) in enumerate(zip(texts, langs)):
            d: Dict[str, Any] = {"id": str(k), "text": t or ""}
            if lang:
                d["language"] = lang
            docs.append(d)
        return docs

    def _group_payload(self, docs: List[Dict[str, Any]]) -> Dict[str, Any]:
        return {"documents": docs}

    def _build_docs_request(self, lead_row: dict,
                            docs: List[Dict[str, Any]]) -> HTTPRequestData:
        """One request for a document group; URL/headers/query params come
        from the group's lead row (the reference's batched row carries one
        value per batch the same way)."""
        return HTTPRequestData(
            url=self._full_url(lead_row), method="POST",
            headers=self._headers(lead_row),
            entity=EntityData.from_string(
                _json.dumps(self._group_payload(docs))))

    # -- response unpacking --------------------------------------------------
    def _doc_maps(self, body) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """id->document and id->error maps from a response body
        (``unpackBatchUDF``, ``TextAnalytics.scala:115-134``)."""
        docs = {str(d.get("id")): d for d in body.get("documents") or []}
        errs = {str(e.get("id")): e.get("error", e)
                for e in body.get("errors") or []}
        return docs, errs

    def _parse_doc(self, doc):
        """Hook: per-document result extraction."""
        return doc

    # -- execution -----------------------------------------------------------
    def _transform(self, df: DataFrame) -> DataFrame:
        if self.get("url") is None:
            raise ValueError(f"{type(self).__name__}: url must be set")
        rows = list(df.iter_rows())
        n = len(rows)
        outs: List[Any] = [None] * n
        errs: List[Any] = [None] * n

        # group rows: user-batched rows are one request each; scalar rows
        # chunk batch_size docs per request
        groups: List[Tuple[List[int], int, bool]] = []  # (indices, ndocs, user)
        group_docs: List[List[Dict[str, Any]]] = []
        bs = max(1, int(self.get("batch_size") or 1))
        pend_idx: List[int] = []
        pend_docs: List[Dict[str, Any]] = []

        def flush():
            if pend_idx:
                # re-id the chunk 0..k-1 so response matching is positional
                docs = [{**d, "id": str(k)} for k, d in enumerate(pend_docs)]
                groups.append((list(pend_idx), len(docs), False))
                group_docs.append(docs)
                pend_idx.clear()
                pend_docs.clear()

        for i, r in enumerate(rows):
            try:
                spec = self._doc_spec(r)
            except ValueError as e:
                errs[i] = {"statusCode": 400,
                           "reasonPhrase": f"request build failed: {e}"}
                continue
            if spec is None:
                continue  # skipped row: both columns stay null
            texts, langs, user_b = spec
            if user_b:
                groups.append(([i], len(texts), True))
                group_docs.append(self._docs_payload(texts, langs))
            else:
                pend_idx.append(i)
                pend_docs.extend(self._docs_payload(texts, langs))
                if len(pend_idx) >= bs:
                    flush()
        flush()

        requests_: List[Optional[HTTPRequestData]] = []
        build_errs: List[Optional[dict]] = []
        for (idxs, _, _), docs in zip(groups, group_docs):
            try:
                requests_.append(self._build_docs_request(rows[idxs[0]], docs))
                build_errs.append(None)
            except ValueError as e:
                requests_.append(None)
                build_errs.append({"statusCode": 400,
                                   "reasonPhrase":
                                       f"request build failed: {e}"})

        from ..io.http.clients import AsyncHTTPClient, \
            SingleThreadedHTTPClient
        c = self.get("concurrency")
        client = (AsyncHTTPClient(c, handler=self._handle) if c > 1
                  else SingleThreadedHTTPClient(handler=self._handle))
        for g, ((idxs, ndocs, user_b), resp) in enumerate(
                zip(groups, client.send(iter(requests_)))):
            if requests_[g] is None:
                for i in idxs:
                    errs[i] = build_errs[g]
                continue
            ok, err = ErrorUtils.split(resp)
            if ok is None:
                for i in idxs:
                    errs[i] = err
                continue
            try:
                docs, derrs = self._doc_maps(ok.json_content())
            except Exception as e:
                perr = {"statusCode": ok.status_code,
                        "reasonPhrase": f"response parse failed: {e}",
                        "entity": ok.string_content()[:2000]}
                for i in idxs:
                    errs[i] = perr
                continue
            if user_b:
                # array output: one slot per submitted document; an errored
                # doc rides in its slot (the reference's per-element
                # error-message field)
                outs[idxs[0]] = [
                    self._parse_doc(docs[str(k)]) if str(k) in docs
                    else {"error": derrs.get(str(k))}
                    for k in range(ndocs)]
            else:
                for k, i in enumerate(idxs):
                    kid = str(k)
                    if kid in docs:
                        outs[i] = self._parse_doc(docs[kid])
                    else:
                        errs[i] = {"statusCode": ok.status_code,
                                   "reasonPhrase": "document error",
                                   "error": derrs.get(kid)}
        return (df.with_column(self.get("output_col"), object_col(outs))
                  .with_column(self.get("error_col"), object_col(errs)))


class _HasStringIndexType(ServiceTransformer):
    """``stringIndexType`` URL param with the reference's closed value set
    (``TextAnalytics.scala:209-216``)."""

    string_index_type = ServiceParam(str, is_url_param=True,
                                     payload_name="stringIndexType",
                                     doc="offset/length unit: "
                                         + "/".join(_STRING_INDEX_TYPES))

    def _build_docs_request(self, lead_row, docs):
        sit = self.get_value_opt(lead_row, "string_index_type")
        if sit is not None and sit not in _STRING_INDEX_TYPES:
            raise ValueError(f"string_index_type must be one of "
                             f"{_STRING_INDEX_TYPES}, got {sit!r}")
        return super()._build_docs_request(lead_row, docs)


class TextSentiment(_HasStringIndexType, TextAnalyticsBase):
    """Parity: ``TextSentiment`` (``TextAnalytics.scala:287-310``) —
    sentiment label + confidence scores per document; ``opinionMining``
    adds aspect-based results to each sentence."""

    opinion_mining = ServiceParam(bool, is_url_param=True,
                                  payload_name="opinionMining",
                                  doc="include aspect-based sentiment "
                                      "(opinion mining) results")

    def _parse_doc(self, doc):
        return {"sentiment": doc.get("sentiment"),
                "confidenceScores": doc.get("confidenceScores"),
                "sentences": doc.get("sentences")}


class LanguageDetector(TextAnalyticsBase):
    """Parity: ``LanguageDetector`` (``TextAnalytics.scala:363-372``)."""

    def _parse_doc(self, doc):
        return doc.get("detectedLanguage", doc)


class EntityDetector(_HasStringIndexType, TextAnalyticsBase):
    """Parity: ``EntityDetector`` (linked entities,
    ``TextAnalytics.scala:376-386``)."""

    def _parse_doc(self, doc):
        return doc.get("entities", doc)


class NER(_HasStringIndexType, TextAnalyticsBase):
    """Parity: ``NER`` (``TextAnalytics.scala:326-337``)."""

    def _parse_doc(self, doc):
        return doc.get("entities", doc)


class KeyPhraseExtractor(TextAnalyticsBase):
    """Parity: ``KeyPhraseExtractor`` (``TextAnalytics.scala:313-322``)."""

    def _parse_doc(self, doc):
        return doc.get("keyPhrases", doc)


class PII(_HasStringIndexType, TextAnalyticsBase):
    """Parity: ``PII`` (``TextAnalytics.scala:340-360``) — PII entity
    recognition; ``domain`` restricts to a category subset ('PHI' or
    'none'), ``piiCategories`` selects explicit categories."""

    domain = ServiceParam(str, is_url_param=True,
                          doc="PII domain filter: 'PHI' or 'none'")
    pii_categories = ServiceParam(list, is_url_param=True,
                                  payload_name="piiCategories",
                                  doc="explicit PII categories to return")

    def _build_docs_request(self, lead_row, docs):
        dom = self.get_value_opt(lead_row, "domain")
        if dom is not None and dom not in ("PHI", "none"):
            raise ValueError(f"domain must be 'PHI' or 'none', got {dom!r}")
        return super()._build_docs_request(lead_row, docs)

    def get_url_params(self, row):
        q = super().get_url_params(row)
        cats = q.get("piiCategories")
        if isinstance(cats, (list, tuple, np.ndarray)):
            q["piiCategories"] = ",".join(str(c) for c in cats)
        return q

    def _parse_doc(self, doc):
        return {"entities": doc.get("entities"),
                "redactedText": doc.get("redactedText")}


#: wire task-list name -> per-document result field
#: (``TAAnalyzeResponseTasks``/``TAAnalyzeResult``,
#: ``TextAnalyticsAnalyzeSchemas.scala:38-70``)
_ANALYZE_TASKS = (("entityRecognitionTasks", "entityRecognition"),
                  ("entityLinkingTasks", "entityLinking"),
                  ("entityRecognitionPiiTasks", "entityRecognitionPii"),
                  ("keyPhraseExtractionTasks", "keyPhraseExtraction"),
                  ("sentimentAnalysisTasks", "sentimentAnalysis"))


def _check_tasks(name: str, tasks) -> List[Dict[str, Any]]:
    """Validate the reference's task shape: each task is exactly
    ``{"parameters": {...}}`` (``TextAnalyzeTaskParam``,
    ``TextAnalytics.scala:388-412``)."""
    out = []
    for t in tasks or []:
        if not isinstance(t, dict) or "parameters" not in t:
            raise ValueError(f"{name}: each task must include 'parameters'")
        if len(t) > 1:
            raise ValueError(f"{name}: task options should only include "
                             f"'parameters'")
        if not isinstance(t["parameters"], dict):
            raise ValueError(f"{name}: 'parameters' must be a mapping")
        out.append({"parameters": {k: str(v)
                                   for k, v in t["parameters"].items()}})
    return out


class TextAnalyze(TextAnalyticsBase, HasAsyncReply):
    """Parity: ``TextAnalyze`` (``TextAnalytics.scala:414-560``) — one
    async ``/analyze`` job per document batch running up to five task
    families; the poll URL gets ``$top=25`` prefixed so the full 25-doc
    batch returns in one page (``modifyPollingURI :490-509``). Output per
    document: the ``TAAnalyzeResult`` shape — one
    ``{"result":..., "error":...}`` entry per task under
    ``entityRecognition`` / ``entityLinking`` / ``entityRecognitionPii`` /
    ``keyPhraseExtraction`` / ``sentimentAnalysis``."""

    entity_recognition_tasks = Param(list, default=(),
                                     doc="entity recognition tasks")
    entity_recognition_pii_tasks = Param(list, default=(),
                                         doc="PII recognition tasks")
    entity_linking_tasks = Param(list, default=(),
                                 doc="entity linking tasks")
    key_phrase_extraction_tasks = Param(list, default=(),
                                        doc="key phrase tasks")
    sentiment_analysis_tasks = Param(list, default=(),
                                     doc="sentiment analysis tasks")
    display_name = Param(str, default="mmlspark-tpu",
                         doc="job display name")

    def _group_payload(self, docs):
        tasks = {
            "entityRecognitionTasks":
                _check_tasks("entity_recognition_tasks",
                             self.get("entity_recognition_tasks")),
            "entityLinkingTasks":
                _check_tasks("entity_linking_tasks",
                             self.get("entity_linking_tasks")),
            "entityRecognitionPiiTasks":
                _check_tasks("entity_recognition_pii_tasks",
                             self.get("entity_recognition_pii_tasks")),
            "keyPhraseExtractionTasks":
                _check_tasks("key_phrase_extraction_tasks",
                             self.get("key_phrase_extraction_tasks")),
            "sentimentAnalysisTasks":
                _check_tasks("sentiment_analysis_tasks",
                             self.get("sentiment_analysis_tasks")),
        }
        return {"displayName": self.get("display_name"),
                "analysisInput": {"documents": docs},
                "tasks": tasks}

    def _poll_url(self, loc: str, request: HTTPRequestData) -> str:
        # the async API pages at 20 results; force the full 25-doc batch
        # (reference prefixes $top so the API's first-value-wins applies)
        base, _, query = loc.partition("?")
        return f"{base}?$top=25" + (f"&{query}" if query else "")

    def _doc_maps(self, body):
        per_doc: Dict[str, Any] = {}
        for wire, field in _ANALYZE_TASKS:
            for task in (body.get("tasks") or {}).get(wire) or []:
                results = (task or {}).get("results") or {}
                rdocs = {str(d.get("id")): d
                         for d in results.get("documents") or []}
                rerrs = {str(e.get("id")): e.get("error", e)
                         for e in results.get("errors") or []}
                for did in set(rdocs) | set(rerrs):
                    slot = per_doc.setdefault(
                        did, {f: [] for _, f in _ANALYZE_TASKS})
                    slot[field].append({"result": rdocs.get(did),
                                        "error": rerrs.get(did)})
        return per_doc, {}


class Healthcare(TextAnalyticsBase, HasAsyncReply):
    """Parity: ``HealthcareSDK`` (``TextAnalyticsSDK.scala:312-341``) —
    healthcare entity/relation extraction. The REST shape is the v3.1
    ``/entities/health/jobs`` async convention: 202 + Operation-Location,
    terminal body carries ``results.documents``/``results.errors``."""

    def _doc_maps(self, body):
        return super()._doc_maps(body.get("results") or body)

    def _parse_doc(self, doc):
        return {"entities": doc.get("entities"),
                "relations": doc.get("relations")}


# -- SDK variants ------------------------------------------------------------
# The reference ships a second, SDK-backed family whose distinguishing
# behaviors are document batching (default 5) and the same per-document
# outputs (``TextAnalyticsSDK.scala:85-196``). Those behaviors live in
# TextAnalyticsBase here; the aliases pin the SDK batch default so a
# reference user finds the exact class names.

class TextSentimentSDK(TextSentiment):
    """Parity: ``TextSentimentSDK`` (``TextAnalyticsSDK.scala:256-282``)."""
    batch_size = Param(int, default=5, doc="documents per request")


class LanguageDetectorSDK(LanguageDetector):
    """Parity: ``LanguageDetectorSDK`` (``TextAnalyticsSDK.scala:198-223``)."""
    batch_size = Param(int, default=5, doc="documents per request")


class EntityDetectorSDK(EntityDetector):
    """Parity: ``EntityDetectorSDK`` (``TextAnalyticsSDK.scala:345-369``)."""
    batch_size = Param(int, default=5, doc="documents per request")


class NERSDK(NER):
    """Parity: ``NERSDK`` (``TextAnalyticsSDK.scala:373-397``)."""
    batch_size = Param(int, default=5, doc="documents per request")


class KeyPhraseExtractorSDK(KeyPhraseExtractor):
    """Parity: ``KeyPhraseExtractorSDK`` (``TextAnalyticsSDK.scala:227-252``)."""
    batch_size = Param(int, default=5, doc="documents per request")


class PIISDK(PII):
    """Parity: ``PIISDK`` (``TextAnalyticsSDK.scala:286-310``)."""
    batch_size = Param(int, default=5, doc="documents per request")


class HealthcareSDK(Healthcare):
    """Parity: ``HealthcareSDK`` (``TextAnalyticsSDK.scala:314-341``)."""
    batch_size = Param(int, default=5, doc="documents per request")
