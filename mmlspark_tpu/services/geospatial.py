"""Geospatial (Azure-Maps-style) service transformers.

Parity: ``cognitive/.../geospatial/Geocoders.scala`` (``AddressGeocoder``,
``ReverseAddressGeocoder`` — batch POST ``{"batchItems": [...]}`` to the
search endpoints, output the ``batchItems`` array) and
``CheckPointInPolygon.scala`` (GET per point against a stored geofence).
Subscription key rides as the ``subscription-key`` URL param, as Azure Maps
expects (``AzureMapsTraits.scala``).
"""

from __future__ import annotations

from .base import HasAsyncReply, ServiceParam, ServiceTransformer

__all__ = ["AddressGeocoder", "ReverseAddressGeocoder",
           "CheckPointInPolygon", "MapsAsyncReply"]


class MapsAsyncReply(HasAsyncReply):
    """Azure-Maps async convention (``AzureMapsTraits.scala:90-130``),
    expressed as the three ``HasAsyncReply`` hooks: the poll URL comes
    from the ``Location`` header (NOT Operation-Location), it must carry
    the subscription key the initial POST used as a query param (an
    unauthenticated poll 401s forever), and completion is the HTTP
    status flipping from 202 — there is no JSON ``status`` field."""

    _poll_location_header = "location"

    def _poll_url(self, loc: str, request) -> str:
        from urllib.parse import parse_qs, quote, urlparse
        key = parse_qs(urlparse(request.url).query).get(
            "subscription-key", [None])[0]
        if key and "subscription-key=" not in loc:
            sep = "&" if "?" in loc else "?"
            loc = f"{loc}{sep}subscription-key={quote(key)}"
        return loc

    def _poll_done(self, resp) -> bool:
        return resp.status_code != 202  # 200 = done; errors surface as-is


class _MapsBase(ServiceTransformer):
    """Azure-Maps auth: key goes in the query string, not a header."""

    def _headers(self, row):
        from ..io.http.schema import HeaderData
        return [HeaderData("Content-Type", "application/json")]

    def _full_url(self, row: dict) -> str:
        from urllib.parse import quote
        url = super()._full_url(row)
        key = self.get_value_opt(row, "subscription_key")
        if key:
            sep = "&" if "?" in url else "?"
            url = f"{url}{sep}subscription-key={quote(str(key))}"
        return url


class AddressGeocoder(_MapsBase, MapsAsyncReply):
    """Batch forward geocoding: address strings → candidate coordinates.
    Async per the Maps batch convention (``Geocoders.scala:30-75`` with
    ``MapsAsyncReply``)."""

    address = ServiceParam(list, is_required=True,
                           doc="list of address strings per row (a batch)")

    def _payload(self, row: dict):
        addrs = self.get_value_opt(row, "address")
        return {"batchItems": [{"query": f"?query={a}"} for a in addrs]}

    def _parse(self, body):
        if isinstance(body, dict):
            return body.get("batchItems", body)
        return body


class ReverseAddressGeocoder(_MapsBase, MapsAsyncReply):
    """Batch reverse geocoding: (lat, lon) pairs → addresses. Async per
    the Maps batch convention (``Geocoders.scala:79-130``)."""

    coordinates = ServiceParam(list, is_required=True,
                               doc="list of [lat, lon] pairs per row")

    def _payload(self, row: dict):
        pts = self.get_value_opt(row, "coordinates")
        return {"batchItems": [{"query": f"?query={lat},{lon}"}
                               for lat, lon in pts]}

    def _parse(self, body):
        if isinstance(body, dict):
            return body.get("batchItems", body)
        return body


class CheckPointInPolygon(_MapsBase):
    """Point-in-geofence check (GET per row)."""

    lat = ServiceParam(float, is_required=True, is_url_param=True,
                       doc="point latitude")
    lon = ServiceParam(float, is_required=True, is_url_param=True,
                       doc="point longitude")
    user_data_identifier = ServiceParam(str, is_url_param=True,
                                        payload_name="udid",
                                        doc="uploaded polygon id")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(method="GET")
