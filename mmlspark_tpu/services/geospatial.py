"""Geospatial (Azure-Maps-style) service transformers.

Parity: ``cognitive/.../geospatial/Geocoders.scala`` (``AddressGeocoder``,
``ReverseAddressGeocoder`` — batch POST ``{"batchItems": [...]}`` to the
search endpoints, output the ``batchItems`` array) and
``CheckPointInPolygon.scala`` (GET per point against a stored geofence).
Subscription key rides as the ``subscription-key`` URL param, as Azure Maps
expects (``AzureMapsTraits.scala``).
"""

from __future__ import annotations

from .base import ServiceParam, ServiceTransformer

__all__ = ["AddressGeocoder", "ReverseAddressGeocoder", "CheckPointInPolygon"]


class _MapsBase(ServiceTransformer):
    """Azure-Maps auth: key goes in the query string, not a header."""

    def _headers(self, row):
        from ..io.http.schema import HeaderData
        return [HeaderData("Content-Type", "application/json")]

    def _full_url(self, row: dict) -> str:
        from urllib.parse import quote
        url = super()._full_url(row)
        key = self.get_value_opt(row, "subscription_key")
        if key:
            sep = "&" if "?" in url else "?"
            url = f"{url}{sep}subscription-key={quote(str(key))}"
        return url


class AddressGeocoder(_MapsBase):
    """Batch forward geocoding: address strings → candidate coordinates."""

    address = ServiceParam(list, is_required=True,
                           doc="list of address strings per row (a batch)")

    def _payload(self, row: dict):
        addrs = self.get_value_opt(row, "address")
        return {"batchItems": [{"query": f"?query={a}"} for a in addrs]}

    def _parse(self, body):
        if isinstance(body, dict):
            return body.get("batchItems", body)
        return body


class ReverseAddressGeocoder(_MapsBase):
    """Batch reverse geocoding: (lat, lon) pairs → addresses."""

    coordinates = ServiceParam(list, is_required=True,
                               doc="list of [lat, lon] pairs per row")

    def _payload(self, row: dict):
        pts = self.get_value_opt(row, "coordinates")
        return {"batchItems": [{"query": f"?query={lat},{lon}"}
                               for lat, lon in pts]}

    def _parse(self, body):
        if isinstance(body, dict):
            return body.get("batchItems", body)
        return body


class CheckPointInPolygon(_MapsBase):
    """Point-in-geofence check (GET per row)."""

    lat = ServiceParam(float, is_required=True, is_url_param=True,
                       doc="point latitude")
    lon = ServiceParam(float, is_required=True, is_url_param=True,
                       doc="point longitude")
    user_data_identifier = ServiceParam(str, is_url_param=True,
                                        payload_name="udid",
                                        doc="uploaded polygon id")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(method="GET")
