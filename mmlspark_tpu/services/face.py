"""Face service transformers.

Parity: ``cognitive/.../Face.scala`` (351 LoC): ``DetectFace``,
``VerifyFaces``, ``GroupFaces``, ``IdentifyFaces``.
"""

from __future__ import annotations

from .base import ServiceParam, ServiceTransformer

__all__ = ["DetectFace", "VerifyFaces", "GroupFaces", "IdentifyFaces"]


class DetectFace(ServiceTransformer):
    image_url = ServiceParam(str, is_required=True, payload_name="url",
                             doc="image URL")
    return_face_id = ServiceParam(bool, is_url_param=True,
                                  payload_name="returnFaceId", default=True,
                                  doc="return detected face ids")
    return_face_landmarks = ServiceParam(bool, is_url_param=True,
                                         payload_name="returnFaceLandmarks",
                                         doc="return 27-point landmarks")
    return_face_attributes = ServiceParam(str, is_url_param=True,
                                          payload_name="returnFaceAttributes",
                                          doc="comma-joined attribute list")


class VerifyFaces(ServiceTransformer):
    face_id1 = ServiceParam(str, is_required=True, payload_name="faceId1",
                            doc="first face id")
    face_id2 = ServiceParam(str, is_required=True, payload_name="faceId2",
                            doc="second face id")


class GroupFaces(ServiceTransformer):
    face_ids = ServiceParam(list, is_required=True, payload_name="faceIds",
                            doc="face ids to cluster")


class IdentifyFaces(ServiceTransformer):
    face_ids = ServiceParam(list, is_required=True, payload_name="faceIds",
                            doc="face ids to identify")
    person_group_id = ServiceParam(str, payload_name="personGroupId",
                                   doc="person group to search")
    max_candidates = ServiceParam(int, payload_name="maxNumOfCandidatesReturned",
                                  doc="max candidates per face")
    confidence_threshold = ServiceParam(float, payload_name="confidenceThreshold",
                                        doc="identification threshold")
