"""Face service transformers.

Parity: ``cognitive/.../Face.scala`` (351 LoC) op-for-op: ``DetectFace``,
``FindSimilarFace``, ``VerifyFaces``, ``GroupFaces``, ``IdentifyFaces``.
"""

from __future__ import annotations

from .base import ServiceParam, ServiceTransformer

__all__ = ["DetectFace", "FindSimilarFace", "VerifyFaces", "GroupFaces",
           "IdentifyFaces"]


class DetectFace(ServiceTransformer):
    image_url = ServiceParam(str, is_required=True, payload_name="url",
                             doc="image URL")
    return_face_id = ServiceParam(bool, is_url_param=True,
                                  payload_name="returnFaceId", default=True,
                                  doc="return detected face ids")
    return_face_landmarks = ServiceParam(bool, is_url_param=True,
                                         payload_name="returnFaceLandmarks",
                                         doc="return 27-point landmarks")
    return_face_attributes = ServiceParam(str, is_url_param=True,
                                          payload_name="returnFaceAttributes",
                                          doc="comma-joined attribute list")


class FindSimilarFace(ServiceTransformer):
    """Parity: ``FindSimilarFace`` (``Face.scala:96-182``) — similar-face
    search for one query face against exactly one of ``faceListId`` /
    ``largeFaceListId`` / ``faceIds``; ``mode`` is matchPerson (default)
    or matchFace."""

    face_id = ServiceParam(str, is_required=True, payload_name="faceId",
                           doc="query face id from DetectFace")
    face_list_id = ServiceParam(str, payload_name="faceListId",
                                doc="face list to search")
    large_face_list_id = ServiceParam(str, payload_name="largeFaceListId",
                                      doc="large face list to search")
    face_ids = ServiceParam(list, payload_name="faceIds",
                            doc="candidate face id array (max 1000)")
    max_candidates = ServiceParam(int,
                                  payload_name="maxNumOfCandidatesReturned",
                                  doc="max candidates returned (1-1000)")
    mode = ServiceParam(str, doc="matchPerson or matchFace")

    def _build_request(self, row):
        if self.should_skip(row):  # null required params skip, not 400
            return None
        m = self.get_value_opt(row, "mode")
        if m is not None and m not in ("matchPerson", "matchFace"):
            raise ValueError(
                f"mode must be matchPerson or matchFace, got {m!r}")
        targets = [self.get_value_opt(row, n) is not None
                   for n in ("face_list_id", "large_face_list_id",
                             "face_ids")]
        if sum(targets) != 1:
            raise ValueError(
                "exactly one of face_list_id, large_face_list_id, face_ids "
                "must be set")
        return super()._build_request(row)


class VerifyFaces(ServiceTransformer):
    face_id1 = ServiceParam(str, is_required=True, payload_name="faceId1",
                            doc="first face id")
    face_id2 = ServiceParam(str, is_required=True, payload_name="faceId2",
                            doc="second face id")


class GroupFaces(ServiceTransformer):
    face_ids = ServiceParam(list, is_required=True, payload_name="faceIds",
                            doc="face ids to cluster")


class IdentifyFaces(ServiceTransformer):
    face_ids = ServiceParam(list, is_required=True, payload_name="faceIds",
                            doc="face ids to identify")
    person_group_id = ServiceParam(str, payload_name="personGroupId",
                                   doc="person group to search")
    max_candidates = ServiceParam(int, payload_name="maxNumOfCandidatesReturned",
                                  doc="max candidates per face")
    confidence_threshold = ServiceParam(float, payload_name="confidenceThreshold",
                                        doc="identification threshold")
