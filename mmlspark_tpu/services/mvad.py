"""Multivariate anomaly detection (train-then-detect service pair).

Parity: ``cognitive/.../MultivariateAnomalyDetection.scala`` —
``FitMultivariateAnomaly`` (``:312-437``) POSTs a training request to
``.../multivariate/models``, reads the new ``modelId`` from the Location
header, polls model status until READY/FAILED, and returns a
``DetectMultivariateAnomaly`` model (``:439+``) that POSTs a detection
request, polls the result id, and joins per-timestamp anomaly verdicts back
onto the frame by timestamp.
"""

from __future__ import annotations

import json
import time

from ..core.dataframe import DataFrame, object_col
from ..core.params import Param
from ..core.pipeline import Estimator, Model
from ..io.http.clients import shared_session
from ..io.http.schema import EntityData, HeaderData, HTTPRequestData
from .base import _send

__all__ = ["FitMultivariateAnomaly", "DetectMultivariateAnomaly"]


def _json_request(url, method, key, key_header, payload=None):
    headers = [HeaderData("Content-Type", "application/json")]
    if key:
        headers.append(HeaderData(key_header, key))
    entity = None
    if payload is not None:
        body = json.dumps(payload).encode()
        entity = EntityData(content=body, content_length=len(body))
    return HTTPRequestData(url=url, method=method, headers=headers,
                           entity=entity)


class FitMultivariateAnomaly(Estimator):
    """POST training window → poll model status → DetectMultivariateAnomaly."""

    url = Param(str, default=None, doc="service base URL "
                                       "(.../multivariate/models)")
    subscription_key = Param(str, default=None, doc="API key")
    key_header = Param(str, default="Ocp-Apim-Subscription-Key",
                       doc="header carrying the API key")
    source = Param(str, default=None,
                   doc="blob/SAS url of the zipped training csvs")
    start_time = Param(str, default=None, doc="training window start (ISO)")
    end_time = Param(str, default=None, doc="training window end (ISO)")
    sliding_window = Param(int, default=300, doc="model sliding window")
    align_mode = Param(str, default="Outer", doc="timestamp alignment")
    fill_na_method = Param(str, default="Linear", doc="missing-value fill")
    polling_delay_ms = Param(int, default=200, doc="delay between polls")
    max_polling_retries = Param(int, default=100, doc="max poll attempts")
    timestamp_col = Param(str, default="timestamp", doc="timestamp column")
    output_col = Param(str, default="result", doc="detection output column")
    error_col = Param(str, default="error", doc="detection error column")
    timeout = Param(float, default=60.0, doc="per-request timeout")

    def _fit(self, df: DataFrame) -> "DetectMultivariateAnomaly":
        url = self.get("url")
        if url is None:
            raise ValueError("url must be set")
        payload = {
            "source": self.get_or_none("source"),
            "startTime": self.get_or_none("start_time"),
            "endTime": self.get_or_none("end_time"),
            "slidingWindow": self.get("sliding_window"),
            "alignPolicy": {"alignMode": self.get("align_mode"),
                            "fillNAMethod": self.get("fill_na_method")},
        }
        session = shared_session.get()
        resp = _send(session, _json_request(url, "POST",
                                            self.get_or_none("subscription_key"),
                                            self.get("key_header"), payload),
                     self.get("timeout"))
        if resp is None or resp.status_code not in (201, 202):
            raise RuntimeError(f"MVAD training request failed: "
                               f"{None if resp is None else resp.status_code}")
        loc = next((h.value for h in resp.headers
                    if h.name.lower() == "location"), None)
        if loc is None:
            raise RuntimeError("MVAD training response missing Location header")
        model_id = loc.rstrip("/").rsplit("/", 1)[-1]

        # poll model status until READY (reference :66-110)
        status = "CREATED"
        for _ in range(self.get("max_polling_retries")):
            time.sleep(self.get("polling_delay_ms") / 1000.0)
            r = _send(session, _json_request(
                f"{url.rstrip('/')}/{model_id}", "GET",
                self.get_or_none("subscription_key"),
                self.get("key_header")), self.get("timeout"))
            if r is None:
                continue
            try:
                info = r.json_content().get("modelInfo", {})
            except (json.JSONDecodeError, ValueError):
                continue   # transient non-JSON body: keep polling
            status = str(info.get("status", "")).upper()
            if status in ("READY", "FAILED"):
                break
        if status != "READY":
            raise RuntimeError(f"MVAD model {model_id} not ready: {status}")

        m = DetectMultivariateAnomaly()
        m.set(url=url, model_id=model_id,
              subscription_key=self.get_or_none("subscription_key"),
              key_header=self.get("key_header"),
              source=self.get_or_none("source"),
              start_time=self.get_or_none("start_time"),
              end_time=self.get_or_none("end_time"),
              timestamp_col=self.get("timestamp_col"),
              output_col=self.get("output_col"),
              error_col=self.get("error_col"),
              polling_delay_ms=self.get("polling_delay_ms"),
              max_polling_retries=self.get("max_polling_retries"),
              timeout=self.get("timeout"))
        return m


class DetectMultivariateAnomaly(Model):
    """POST detect → poll resultId → join anomaly states by timestamp."""

    url = Param(str, default=None, doc="service base URL")
    model_id = Param(str, default=None, doc="trained model id")
    subscription_key = Param(str, default=None, doc="API key")
    key_header = Param(str, default="Ocp-Apim-Subscription-Key",
                       doc="header carrying the API key")
    source = Param(str, default=None, doc="blob/SAS url of detection data")
    start_time = Param(str, default=None, doc="detection window start")
    end_time = Param(str, default=None, doc="detection window end")
    timestamp_col = Param(str, default="timestamp", doc="timestamp column")
    output_col = Param(str, default="result", doc="output column")
    error_col = Param(str, default="error", doc="error column")
    polling_delay_ms = Param(int, default=200, doc="delay between polls")
    max_polling_retries = Param(int, default=100, doc="max poll attempts")
    timeout = Param(float, default=60.0, doc="per-request timeout")

    def _transform(self, df: DataFrame) -> DataFrame:
        url = self.get("url").rstrip("/")
        mid = self.get("model_id")
        session = shared_session.get()
        key = self.get_or_none("subscription_key")
        payload = {"source": self.get_or_none("source"),
                   "startTime": self.get_or_none("start_time"),
                   "endTime": self.get_or_none("end_time")}
        resp = _send(session, _json_request(f"{url}/{mid}/detect", "POST",
                                            key, self.get("key_header"),
                                            payload), self.get("timeout"))
        n = len(df)
        if resp is None or resp.status_code not in (201, 202):
            err = {"statusCode": None if resp is None else resp.status_code,
                   "reasonPhrase": "detect request failed"}
            return (df.with_column(self.get("output_col"),
                                   object_col([None] * n))
                      .with_column(self.get("error_col"),
                                   object_col([err] * n)))
        loc = next((h.value for h in resp.headers
                    if h.name.lower() == "location"), "")
        result_id = loc.rstrip("/").rsplit("/", 1)[-1]

        results = None
        for _ in range(self.get("max_polling_retries")):
            time.sleep(self.get("polling_delay_ms") / 1000.0)
            r = _send(session, _json_request(
                f"{url.rsplit('/models', 1)[0]}/results/{result_id}", "GET",
                key, self.get("key_header")), self.get("timeout"))
            if r is None:
                continue
            try:
                body = r.json_content()
            except (json.JSONDecodeError, ValueError):
                continue   # transient non-JSON body: keep polling
            if str(body.get("summary", {}).get("status", "")).upper() == "READY":
                results = body.get("results", [])
                break
        by_ts = {r.get("timestamp"): r.get("value") for r in (results or [])}
        ts = df[self.get("timestamp_col")]
        outs = object_col([by_ts.get(str(t)) for t in ts])
        err_val = (None if results is not None
                   else {"statusCode": None,
                         "reasonPhrase": "result polling timed out"})
        return (df.with_column(self.get("output_col"), outs)
                  .with_column(self.get("error_col"),
                               object_col([err_val] * n)))
