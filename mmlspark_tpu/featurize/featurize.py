"""Automatic featurization.

Parity surface: ``Featurize`` (reference
``core/.../featurize/Featurize.scala:37``): inspect each input column's type
and assemble a per-type sub-pipeline (impute numerics, index/one-hot
categoricals, hash text), concatenating everything into one dense features
vector — the column every trainer consumes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import HasInputCols, HasOutputCol, Param
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import get_categorical_levels

__all__ = ["Featurize", "FeaturizeModel", "VectorAssembler"]


def _is_numeric(col: np.ndarray) -> bool:
    return col.dtype != object and np.issubdtype(col.dtype, np.number)


def _is_text(col: np.ndarray) -> bool:
    if col.dtype.kind in ("U", "S"):
        return True
    return col.dtype == object and len(col) > 0 and isinstance(col[0], str)


def _is_vector(col: np.ndarray) -> bool:
    if col.dtype == object:
        return len(col) > 0 and isinstance(col[0], (np.ndarray, list, tuple))
    return col.ndim > 1


class Featurize(Estimator, HasInputCols, HasOutputCol):
    one_hot_encode_categoricals = Param(bool, default=True,
                                        doc="one-hot string/categorical columns")
    num_features = Param(int, default=1 << 8,
                         doc="hash space for high-cardinality text")
    impute_missing = Param(bool, default=True, doc="mean-impute numeric NaNs")

    def __init__(self, input_cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        self._set_default(output_col="features")
        if input_cols is not None:
            self.set(input_cols=list(input_cols))

    def _fit(self, df: DataFrame) -> "FeaturizeModel":
        plans: List[dict] = []
        for c in self.get("input_cols"):
            col = df[c]
            if _is_vector(col):
                plans.append({"col": c, "kind": "vector"})
            elif _is_numeric(col):
                fill = None
                if self.get("impute_missing"):
                    # unconditional training mean: serving data may have NaNs
                    # even when the training sample had none
                    fill = float(np.nanmean(col.astype(np.float64)))
                plans.append({"col": c, "kind": "numeric", "fill": fill})
            elif _is_text(col):
                levels = get_categorical_levels(df, c)
                if levels is None:
                    levels = sorted({str(v) for v in col})
                if (self.get("one_hot_encode_categoricals")
                        and len(levels) <= self.get("num_features")):
                    plans.append({"col": c, "kind": "onehot",
                                  "levels": [str(l) for l in levels]})
                else:
                    plans.append({"col": c, "kind": "hash",
                                  "n": self.get("num_features")})
            else:
                raise TypeError(f"cannot featurize column {c!r} of "
                                f"type {df.schema()[c]}")
        m = FeaturizeModel()
        m.set(input_cols=self.get("input_cols"), output_col=self.get("output_col"),
              plans=plans)
        return m


class FeaturizeModel(Model, HasInputCols, HasOutputCol):
    plans = Param(list, default=[], doc="per-column featurization plan")

    def _transform(self, df: DataFrame) -> DataFrame:
        from .text import _fnv1a
        parts: List[np.ndarray] = []
        n = len(df)
        for plan in self.get("plans"):
            col = df[plan["col"]]
            kind = plan["kind"]
            if kind == "vector":
                if col.dtype == object:
                    part = np.stack([np.asarray(v, dtype=np.float64).ravel()
                                     for v in col])
                else:
                    part = np.asarray(col, dtype=np.float64).reshape(n, -1)
            elif kind == "numeric":
                part = col.astype(np.float64)[:, None].copy()
                if plan["fill"] is not None:
                    part[np.isnan(part)] = plan["fill"]
            elif kind == "onehot":
                levels = plan["levels"]
                table = {v: i for i, v in enumerate(levels)}
                part = np.zeros((n, len(levels)))
                for i, v in enumerate(col):
                    j = table.get(str(v))
                    if j is not None:
                        part[i, j] = 1.0
            elif kind == "hash":
                nf = plan["n"]
                part = np.zeros((n, nf))
                for i, v in enumerate(col):
                    for tok in str(v).lower().split():
                        part[i, _fnv1a(tok, nf)] += 1.0
            else:
                raise ValueError(f"unknown plan kind {kind!r}")
            parts.append(part)
        X = np.concatenate(parts, axis=1) if parts else np.zeros((n, 0))
        return df.with_column(self.get("output_col"), object_col(X))


class VectorAssembler(Transformer, HasInputCols, HasOutputCol):
    """Concatenate numeric/vector columns into one feature vector per row.

    Parity: the reference's ``FastVectorAssembler``
    (``org/apache/spark/ml/feature/FastVectorAssembler.scala`` — its
    Spark-injection rewrite of VectorAssembler that skips per-row metadata
    work). Columnar-native here: scalars and fixed-width vector columns
    concatenate as one dense (n, total_width) block — one allocation, no
    per-row boxing until the object-column boundary.
    """

    handle_invalid = Param(str, default="error", choices=["error", "keep"],
                           doc="'error' raises on NaN/None; 'keep' passes "
                               "NaN through")

    def _transform(self, df: DataFrame) -> DataFrame:
        from ..core.schema import assemble_vector

        cols = self.get("input_cols")
        if not cols:
            raise ValueError(f"{self.uid}: input_cols is empty")
        X = assemble_vector(df, cols, allow_none=True)
        if self.handle_invalid == "error" and not np.isfinite(X).all():
            bad = int(np.argwhere(~np.isfinite(X).all(axis=1)).ravel()[0])
            raise ValueError(
                f"non-finite values in assembled features (first bad row "
                f"{bad}); set handle_invalid='keep' to pass NaN through")
        return df.with_column(self.get("output_col"), object_col(X))
