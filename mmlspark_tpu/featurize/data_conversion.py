"""Column type conversion.

Parity surface: ``DataConversion`` (reference
``core/.../featurize/DataConversion.scala:22``): cast listed columns to a
target type; date parsing via a format string.
"""

from __future__ import annotations

from datetime import datetime

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCols, Param
from ..core.pipeline import Transformer

__all__ = ["DataConversion"]

_CASTS = {
    "boolean": np.bool_, "byte": np.int8, "short": np.int16, "integer": np.int32,
    "long": np.int64, "float": np.float32, "double": np.float64,
}


class DataConversion(Transformer, HasInputCols):
    convert_to = Param(str, default="double",
                       choices=list(_CASTS) + ["string", "toCategorical",
                                               "clearCategorical", "date"],
                       doc="target type")
    date_time_format = Param(str, default="%Y-%m-%d %H:%M:%S",
                             doc="strptime format for date conversion")

    def _transform(self, df: DataFrame) -> DataFrame:
        target = self.get("convert_to")
        out = df
        for c in self.get("input_cols"):
            col = df[c]
            if target in _CASTS:
                out = out.with_column(c, col.astype(_CASTS[target]))
            elif target == "string":
                arr = np.empty(len(col), dtype=object)
                for i, v in enumerate(col):
                    arr[i] = str(v)
                out = out.with_column(c, arr)
            elif target == "date":
                fmt = self.get("date_time_format")
                arr = np.empty(len(col), dtype=object)
                for i, v in enumerate(col):
                    arr[i] = datetime.strptime(str(v), fmt)
                out = out.with_column(c, arr)
            elif target == "toCategorical":
                from .value_indexer import ValueIndexer
                model = ValueIndexer(input_col=c, output_col=c).fit(out)
                out = model.transform(out)
            elif target == "clearCategorical":
                from ..core.schema import CATEGORICAL_KEY, get_categorical_levels
                levels = get_categorical_levels(out, c)
                if levels is not None:
                    idx = out[c].astype(np.int64)
                    vals = np.asarray([levels[k] for k in idx])
                    md = {k: v for k, v in out.column_metadata(c).items()
                          if k != CATEGORICAL_KEY}
                    out = out.with_column(c, vals)
                    out._metadata[c] = md
        return out
