from .clean_missing import CleanMissingData, CleanMissingDataModel
from .count_selector import CountSelector, CountSelectorModel
from .data_conversion import DataConversion
from .featurize import Featurize, FeaturizeModel, VectorAssembler
from .tokenizer import BertTokenizer, build_wordpiece_vocab
from .text import (IDF, HashingTF, IDFModel, MultiNGram, NGram, PageSplitter,
                   TextFeaturizer, TextFeaturizerModel, Tokenizer)
from .value_indexer import IndexToValue, ValueIndexer, ValueIndexerModel

__all__ = [
    "BertTokenizer", "build_wordpiece_vocab", "VectorAssembler",
    "CleanMissingData", "CleanMissingDataModel",
    "CountSelector", "CountSelectorModel",
    "DataConversion",
    "Featurize", "FeaturizeModel",
    "ValueIndexer", "ValueIndexerModel", "IndexToValue",
    "Tokenizer", "NGram", "MultiNGram", "HashingTF", "IDF", "IDFModel",
    "TextFeaturizer", "TextFeaturizerModel", "PageSplitter",
]
