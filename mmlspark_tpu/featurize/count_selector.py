"""Zero-variance slot pruning for vector columns.

Parity surface: ``CountSelector`` (reference
``core/.../featurize/CountSelector.scala:23``): drop vector slots that are
zero for every row.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Estimator, Model

__all__ = ["CountSelector", "CountSelectorModel"]


def _as_matrix(col: np.ndarray) -> np.ndarray:
    if col.dtype == object:
        return np.stack([np.asarray(v, dtype=np.float64) for v in col])
    return np.asarray(col, dtype=np.float64)


class CountSelector(Estimator, HasInputCol, HasOutputCol):
    def _fit(self, df: DataFrame) -> "CountSelectorModel":
        X = _as_matrix(df[self.get("input_col")])
        keep = np.flatnonzero((X != 0).any(axis=0))
        m = CountSelectorModel()
        m.set(input_col=self.get("input_col"), output_col=self.get("output_col"),
              indices=[int(i) for i in keep])
        return m


class CountSelectorModel(Model, HasInputCol, HasOutputCol):
    indices = Param(list, default=[], doc="vector slots to keep")

    def _transform(self, df: DataFrame) -> DataFrame:
        X = _as_matrix(df[self.get("input_col")])
        out = X[:, np.asarray(self.get("indices"), dtype=np.int64)]
        col = np.empty(len(out), dtype=object)
        for i in range(len(out)):
            col[i] = out[i]
        return df.with_column(self.get("output_col"), col)
