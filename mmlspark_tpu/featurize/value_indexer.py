"""Categorical value indexing.

Parity surface: ``ValueIndexer:57`` / ``ValueIndexerModel:107`` /
``IndexToValue:29`` (reference ``core/.../featurize/ValueIndexer.scala``,
``IndexToValue.scala``) plus the ``Categoricals`` metadata they attach
(``core/schema/Categoricals.scala``).
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import get_categorical_levels, set_categorical_metadata

__all__ = ["ValueIndexer", "ValueIndexerModel", "IndexToValue"]


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Map distinct column values to dense indices [0, n)."""

    def _fit(self, df: DataFrame) -> "ValueIndexerModel":
        col = df[self.get("input_col")]
        values = sorted({v.item() if isinstance(v, np.generic) else v
                         for v in col}, key=lambda v: (str(type(v)), v))
        m = ValueIndexerModel()
        m.set(input_col=self.get("input_col"), output_col=self.get("output_col"),
              levels=values)
        return m


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    levels = Param(list, default=[], doc="distinct values; index = position")

    def _transform(self, df: DataFrame) -> DataFrame:
        levels = self.get("levels")
        table = {v: i for i, v in enumerate(levels)}
        col = df[self.get("input_col")]
        idx = np.empty(len(col), dtype=np.int64)
        for i, v in enumerate(col):
            v = v.item() if isinstance(v, np.generic) else v
            if v not in table:
                raise ValueError(f"unseen value {v!r} in {self.get('input_col')}")
            idx[i] = table[v]
        out = df.with_column(self.get("output_col"), idx)
        return set_categorical_metadata(out, self.get("output_col"), levels)


class IndexToValue(Transformer, HasInputCol, HasOutputCol):
    """Inverse of ValueIndexerModel, using the categorical metadata."""

    def _transform(self, df: DataFrame) -> DataFrame:
        levels = get_categorical_levels(df, self.get("input_col"))
        if levels is None:
            raise ValueError(f"column {self.get('input_col')!r} has no "
                             "categorical metadata")
        idx = df[self.get("input_col")].astype(np.int64)
        values = np.empty(len(idx), dtype=object)
        for i, k in enumerate(idx):
            values[i] = levels[k]
        try:
            values = np.asarray(list(values))
        except Exception:
            pass
        return df.with_column(self.get("output_col"), values)
