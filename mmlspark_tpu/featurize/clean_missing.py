"""Missing-value cleaning.

Parity surface: ``CleanMissingData`` (reference
``core/.../featurize/CleanMissingData.scala:48``): fit computes per-column
replacement values (mean / median / custom), transform fills NaN/None.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCols, HasOutputCols, Param
from ..core.pipeline import Estimator, Model

__all__ = ["CleanMissingData", "CleanMissingDataModel"]


class CleanMissingData(Estimator, HasInputCols, HasOutputCols):
    cleaning_mode = Param(str, default="Mean",
                          choices=["Mean", "Median", "Custom"],
                          doc="replacement strategy")
    custom_value = Param(float, default=None, doc="fill value for Custom mode")

    def __init__(self, input_cols: Optional[Sequence[str]] = None,
                 output_cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if input_cols is not None:
            self.set(input_cols=list(input_cols))
        if output_cols is not None:
            self.set(output_cols=list(output_cols))

    def _fit(self, df: DataFrame) -> "CleanMissingDataModel":
        mode = self.get("cleaning_mode")
        fills = []
        for c in self.get("input_cols"):
            col = df[c].astype(np.float64)
            if mode == "Mean":
                fills.append(float(np.nanmean(col)))
            elif mode == "Median":
                fills.append(float(np.nanmedian(col)))
            else:
                fills.append(float(self.get("custom_value")))
        m = CleanMissingDataModel()
        m.set(input_cols=self.get("input_cols"),
              output_cols=self.get("output_cols") or self.get("input_cols"),
              fill_values=fills)
        return m


class CleanMissingDataModel(Model, HasInputCols, HasOutputCols):
    fill_values = Param(list, default=[], doc="replacement value per column")

    def _transform(self, df: DataFrame) -> DataFrame:
        out = df
        outs = self.get("output_cols") or self.get("input_cols")
        for c, o, fill in zip(self.get("input_cols"), outs, self.get("fill_values")):
            col = df[c].astype(np.float64).copy()
            col[np.isnan(col)] = fill
            out = out.with_column(o, col)
        return out
