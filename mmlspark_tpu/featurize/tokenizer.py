"""WordPiece tokenization: text columns → token-id tensors.

The reference leans on upstream tooling for subword tokenization (its text
stages are hashing/n-gram based, ``featurize/text``); a standalone TPU
framework running BERT-class ONNX/JAX models needs the text→ids step
in-pipeline. This is a dependency-free WordPiece implementation with the
standard BERT semantics:

* basic tokenization: lowercasing (optional), punctuation splitting,
  whitespace normalization;
* greedy longest-match-first WordPiece with ``##`` continuation pieces and
  ``[UNK]`` fallback;
* fixed-length output (``[CLS]`` ... ``[SEP]`` + padding) so the id/mask
  columns are dense ``(n, max_len)`` tensors ready for ``device_put``.

``build_wordpiece_vocab`` derives a workable vocab from a corpus
(frequency-ranked words + their prefixes/suffix pieces) for self-contained
pipelines and tests; production vocabs load via ``vocab=list`` or
``vocab_file``.
"""

from __future__ import annotations

import unicodedata
from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, Param
from ..core.pipeline import Transformer

__all__ = ["BertTokenizer", "build_wordpiece_vocab"]

PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
SPECIALS = [PAD, UNK, CLS, SEP, MASK]


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) \
            or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def basic_tokenize(text: str, lowercase: bool = True) -> List[str]:
    if lowercase:
        text = text.lower()
    out: List[str] = []
    word = []
    for ch in text:
        if ch.isspace():
            if word:
                out.append("".join(word))
                word = []
        elif _is_punct(ch):
            if word:
                out.append("".join(word))
                word = []
            out.append(ch)
        else:
            word.append(ch)
    if word:
        out.append("".join(word))
    return out


def wordpiece(word: str, vocab: Dict[str, int],
              max_chars: int = 100) -> List[str]:
    """Greedy longest-match-first (the BERT algorithm)."""
    if len(word) > max_chars:
        return [UNK]
    pieces: List[str] = []
    start = 0
    while start < len(word):
        end = len(word)
        piece = None
        while start < end:
            sub = word[start:end]
            if start > 0:
                sub = "##" + sub
            if sub in vocab:
                piece = sub
                break
            end -= 1
        if piece is None:
            return [UNK]
        pieces.append(piece)
        start = end
    return pieces


def build_wordpiece_vocab(corpus: Sequence[str], size: int = 8000,
                          lowercase: bool = True) -> List[str]:
    """Frequency-derived vocab: specials + single chars (+ their ##
    continuations) + the most frequent whole words, then frequent suffix
    pieces — enough coverage that common words tokenize whole and rare
    words split instead of hitting [UNK]."""
    words = Counter()
    chars = Counter()
    for text in corpus:
        for w in basic_tokenize(text, lowercase):
            words[w] += 1
            chars.update(w)
    vocab: List[str] = list(SPECIALS)
    seen = set(vocab)

    def add(tok: str):
        if tok and tok not in seen:
            vocab.append(tok)
            seen.add(tok)

    for ch, _ in chars.most_common():
        add(ch)
        add("##" + ch)
    for w, _ in words.most_common():
        if len(vocab) >= size:
            break
        add(w)
    # suffix pieces of frequent words give partial-match coverage
    for w, _ in words.most_common(2000):
        if len(vocab) >= size:
            break
        for i in range(1, len(w)):
            add("##" + w[i:])
            if len(vocab) >= size:
                break
    return vocab[:size]


class BertTokenizer(Transformer, HasInputCol):
    """Text column → dense ``(n, max_len)`` int32 ``ids``/``mask`` columns.

    ``vocab`` is a ComplexParam (persisted with the stage); ``vocab_file``
    (one token per line, BERT format) is the interop path."""

    vocab = ComplexParam(default=None, doc="token list, index = id")
    vocab_file = Param(str, default=None,
                       converter=lambda v: v,
                       doc="path to a BERT-format vocab.txt (one token "
                           "per line); loaded when `vocab` is unset")
    max_len = Param(int, default=128, doc="output sequence length")
    lowercase = Param(bool, default=True, doc="lowercase before splitting")
    ids_col = Param(str, default="ids", doc="output token-id column")
    mask_col = Param(str, default="mask", doc="output attention-mask column")
    add_special_tokens = Param(bool, default=True,
                               doc="wrap with [CLS] ... [SEP]")

    def __init__(self, vocab: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if vocab is not None:
            self.set(vocab=list(vocab))
        self._index: Optional[Dict[str, int]] = None

    def set(self, **kwargs):
        out = super().set(**kwargs)
        if kwargs and hasattr(self, "_index"):
            self._index = None  # vocab/vocab_file changes invalidate cache
        return out

    def copy(self, extra=None):
        other = super().copy(extra)
        other._index = None  # param overrides must not see a stale index
        return other

    def _vocab_index(self) -> Dict[str, int]:
        if self._index is None:
            vocab = self.get_or_none("vocab")
            if vocab is None:
                path = self.get_or_none("vocab_file")
                if not path:
                    raise ValueError(f"{self.uid}: set vocab or vocab_file")
                with open(path) as f:
                    vocab = [ln.rstrip("\n") for ln in f if ln.strip()]
                self.set(vocab=vocab)
            self._index = {tok: i for i, tok in enumerate(vocab)}
            for sp in (PAD, UNK, CLS, SEP):
                if sp not in self._index:
                    raise ValueError(f"vocab missing special token {sp}")
        return self._index

    def encode(self, text: str,
               max_pieces: Optional[int] = None) -> List[int]:
        """``max_pieces`` stops tokenization once the budget is met — long
        documents must not pay full wordpiece cost for discarded tokens."""
        index = self._vocab_index()
        pieces: List[str] = []
        for w in basic_tokenize(text, self.lowercase):
            pieces.extend(wordpiece(w, index))
            if max_pieces is not None and len(pieces) >= max_pieces:
                break
        if max_pieces is not None:
            pieces = pieces[:max_pieces]
        return [index[p] for p in pieces]

    def _transform(self, df: DataFrame) -> DataFrame:
        index = self._vocab_index()
        L = self.max_len
        special = self.add_special_tokens
        body = L - (2 if special else 0)
        if body < 1:
            raise ValueError(
                f"max_len={L} leaves no room for tokens"
                + (" after [CLS]/[SEP]" if special else ""))
        n = len(df)
        ids = np.full((n, L), index[PAD], dtype=np.int32)
        mask = np.zeros((n, L), dtype=np.int32)
        col = df[self.input_col]
        for i in range(n):
            text = col[i]
            toks = self.encode("" if text is None else str(text),
                               max_pieces=body)
            if special:
                toks = [index[CLS]] + toks + [index[SEP]]
            ids[i, :len(toks)] = toks
            mask[i, :len(toks)] = 1
        return (df.with_column(self.ids_col, ids)
                  .with_column(self.mask_col, mask))

    def _load_extra(self, path: str) -> None:
        self._index = None
