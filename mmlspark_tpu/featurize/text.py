"""Text featurization.

Parity surface: ``TextFeaturizer:197`` (tokenize → n-grams → hashing TF →
IDF), ``MultiNGram:25`` (several n-gram widths concatenated), ``PageSplitter:23``
(split documents into byte-bounded pages) — reference
``core/.../featurize/text/*.scala``. The hashing-TF → IDF product is a dense
matmul-shaped op, so fitted transforms stay vectorized numpy feeding the
device path.
"""

from __future__ import annotations

import re
from typing import List

import numpy as np

try:                            # guarded like models/gbdt/binning.py
    import scipy.sparse as _sp
except Exception:               # pragma: no cover - scipy is in the image
    _sp = None

from ..core.dataframe import DataFrame
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Estimator, Model, Transformer

__all__ = ["Tokenizer", "NGram", "MultiNGram", "HashingTF", "IDF", "IDFModel",
           "TextFeaturizer", "TextFeaturizerModel", "PageSplitter"]


def _fnv1a(token: str, n_features: int) -> int:
    h = 0x811C9DC5
    for b in token.encode("utf-8"):
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h % n_features


class Tokenizer(Transformer, HasInputCol, HasOutputCol):
    pattern = Param(str, default=r"\s+", doc="split regex")
    to_lowercase = Param(bool, default=True, doc="lowercase before split")
    min_token_length = Param(int, default=1, doc="drop shorter tokens")

    def _transform(self, df: DataFrame) -> DataFrame:
        rx = re.compile(self.get("pattern"))
        out = np.empty(len(df), dtype=object)
        for i, text in enumerate(df[self.get("input_col")]):
            t = str(text)
            if self.get("to_lowercase"):
                t = t.lower()
            out[i] = [tok for tok in rx.split(t)
                      if len(tok) >= self.get("min_token_length")]
        return df.with_column(self.get("output_col"), out)


class NGram(Transformer, HasInputCol, HasOutputCol):
    n = Param(int, default=2, doc="gram width")

    def _transform(self, df: DataFrame) -> DataFrame:
        n = self.get("n")
        out = np.empty(len(df), dtype=object)
        for i, toks in enumerate(df[self.get("input_col")]):
            out[i] = [" ".join(toks[j:j + n]) for j in range(len(toks) - n + 1)]
        return df.with_column(self.get("output_col"), out)


class MultiNGram(Transformer, HasInputCol, HasOutputCol):
    """Concatenate n-grams for several widths
    (reference ``featurize/text/MultiNGram.scala:25``)."""

    lengths = Param((list, int), default=[1, 2, 3], doc="gram widths")

    def _transform(self, df: DataFrame) -> DataFrame:
        widths = self.get("lengths")
        out = np.empty(len(df), dtype=object)
        for i, toks in enumerate(df[self.get("input_col")]):
            grams: List[str] = []
            for n in widths:
                grams.extend(" ".join(toks[j:j + n])
                             for j in range(len(toks) - n + 1))
            out[i] = grams
        return df.with_column(self.get("output_col"), out)


class HashingTF(Transformer, HasInputCol, HasOutputCol):
    # dense vectors by default (they feed device matmuls), so the default
    # hash space is far below the reference's sparse 2^18; ``sparse=True``
    # emits scipy CSR row vectors (Spark HashingTF's SparseVector shape),
    # which lets num_features grow to the reference's 2^18+ and feeds the
    # sparse GBDT / EFB path without densifying
    num_features = Param(int, default=1 << 12, doc="hash space size")
    binary = Param(bool, default=False, doc="presence instead of counts")
    sparse = Param(bool, default=False,
                   doc="emit scipy CSR row vectors instead of dense")

    def _transform(self, df: DataFrame) -> DataFrame:
        n = self.get("num_features")
        use_sparse = self.get("sparse")
        out = np.empty(len(df), dtype=object)
        if use_sparse and _sp is None:     # pragma: no cover
            raise ImportError("HashingTF(sparse=True) requires scipy")
        for i, toks in enumerate(df[self.get("input_col")]):
            if use_sparse:
                hashed = np.fromiter((_fnv1a(t, n) for t in toks),
                                     dtype=np.int64, count=len(toks))
                idx, counts = np.unique(hashed, return_counts=True)
                vals = (np.ones(len(idx), np.float32) if self.get("binary")
                        else counts.astype(np.float32))
                out[i] = _sp.csr_matrix(
                    (vals, idx, np.array([0, len(idx)])), shape=(1, n))
                continue
            vec = np.zeros(n, dtype=np.float32)
            for tok in toks:
                vec[_fnv1a(tok, n)] += 1.0
            if self.get("binary"):
                vec = (vec > 0).astype(np.float32)
            out[i] = vec
        return df.with_column(self.get("output_col"), out)


class IDF(Estimator, HasInputCol, HasOutputCol):
    min_doc_freq = Param(int, default=0, doc="zero out rare terms")

    def _fit(self, df: DataFrame) -> "IDFModel":
        col = df[self.get("input_col")]
        # incremental docfreq: never materialize the (n_docs, n_features) stack
        docfreq = None
        for v in col:
            if _sp is not None and _sp.issparse(v):
                v = v.tocsr()
                if docfreq is None:
                    docfreq = np.zeros(v.shape[1], dtype=np.int64)
                # unique: a non-canonical CSR with a repeated index must
                # count once per document (dense presence semantics)
                np.add.at(docfreq, np.unique(v.indices[v.data > 0]), 1)
                continue
            row = np.asarray(v) > 0
            docfreq = row.astype(np.int64) if docfreq is None else docfreq + row
        n = len(col)
        if docfreq is None:
            docfreq = np.zeros(0, dtype=np.int64)
        idf = np.log((n + 1.0) / (docfreq + 1.0))
        idf[docfreq < self.get("min_doc_freq")] = 0.0
        m = IDFModel()
        m.set(input_col=self.get("input_col"), output_col=self.get("output_col"),
              idf=idf.astype(np.float32))
        return m


class IDFModel(Model, HasInputCol, HasOutputCol):
    from ..core.params import ComplexParam as _CP
    idf = _CP(default=None, doc="per-slot idf weights")

    def _transform(self, df: DataFrame) -> DataFrame:
        idf = np.asarray(self.get("idf"))
        col = df[self.get("input_col")]
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col):
            if _sp is not None and _sp.issparse(v):
                r = v.tocsr().astype(np.float32)
                r.data = r.data * idf[r.indices].astype(np.float32)
                out[i] = r
            else:
                out[i] = (np.asarray(v, dtype=np.float32) * idf)
        return df.with_column(self.get("output_col"), out)


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    """Composed tokenize → [n-gram] → hashing TF → [IDF] pipeline
    (reference ``featurize/text/TextFeaturizer.scala:197``)."""

    use_tokenizer = Param(bool, default=True, doc="split text into tokens")
    tokenizer_pattern = Param(str, default=r"\s+", doc="split regex")
    to_lowercase = Param(bool, default=True, doc="lowercase text")
    use_ngram = Param(bool, default=False, doc="insert an n-gram stage")
    n_gram_length = Param(int, default=2, doc="gram width")
    num_features = Param(int, default=1 << 12, doc="hash space size")
    binary = Param(bool, default=False, doc="binary term counts")
    use_idf = Param(bool, default=True, doc="apply inverse document frequency")
    min_doc_freq = Param(int, default=1, doc="IDF min document frequency")
    sparse = Param(bool, default=False,
                   doc="emit scipy CSR row vectors (enables reference-scale "
                       "2^18 hash spaces; feeds the sparse GBDT/EFB path)")

    def _fit(self, df: DataFrame) -> "TextFeaturizerModel":
        from ..core.pipeline import Pipeline
        inp, outp = self.get("input_col"), self.get("output_col")
        stages: List = []
        cur = inp
        if self.get("use_tokenizer"):
            stages.append(Tokenizer(input_col=cur, output_col="_tf_tokens",
                                    pattern=self.get("tokenizer_pattern"),
                                    to_lowercase=self.get("to_lowercase")))
            cur = "_tf_tokens"
        if self.get("use_ngram"):
            stages.append(NGram(input_col=cur, output_col="_tf_ngrams",
                                n=self.get("n_gram_length")))
            cur = "_tf_ngrams"
        tf_out = "_tf_counts" if self.get("use_idf") else outp
        stages.append(HashingTF(input_col=cur, output_col=tf_out,
                                num_features=self.get("num_features"),
                                binary=self.get("binary"),
                                sparse=self.get("sparse")))
        if self.get("use_idf"):
            stages.append(IDF(input_col=tf_out, output_col=outp,
                              min_doc_freq=self.get("min_doc_freq")))
        pipeline_model = Pipeline(stages).fit(df)
        m = TextFeaturizerModel()
        m.set(input_col=inp, output_col=outp, pipeline=pipeline_model)
        return m


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol):
    from ..core.params import ComplexParam as _CP
    pipeline = _CP(default=None, doc="fitted internal pipeline")

    def _transform(self, df: DataFrame) -> DataFrame:
        out = self.get("pipeline").transform(df)
        return out.drop("_tf_tokens", "_tf_ngrams", "_tf_counts")


class PageSplitter(Transformer, HasInputCol, HasOutputCol):
    """Split documents into byte-bounded pages on whitespace/word boundaries
    (reference ``featurize/text/PageSplitter.scala:23``)."""

    maximum_page_length = Param(int, default=5000, doc="max bytes per page")
    minimum_page_length = Param(int, default=4500,
                                doc="prefer boundaries after this many bytes")
    boundary_regex = Param(str, default=r"\s", doc="soft break pattern")

    def _transform(self, df: DataFrame) -> DataFrame:
        lo, hi = self.get("minimum_page_length"), self.get("maximum_page_length")
        rx = re.compile(self.get("boundary_regex"))
        out = np.empty(len(df), dtype=object)
        for i, text in enumerate(df[self.get("input_col")]):
            t = str(text)
            nbytes = [len(ch.encode("utf-8")) for ch in t]
            pages, start = [], 0
            while start < len(t):
                # greedily take chars while the page stays within hi BYTES,
                # remembering the last soft boundary past lo bytes
                size, j, soft = 0, start, None
                while j < len(t) and size + nbytes[j] <= hi:
                    size += nbytes[j]
                    j += 1
                    if size >= lo and rx.match(t[j - 1]):
                        soft = j
                if j >= len(t):
                    pages.append(t[start:])
                    break
                cut = soft if soft is not None else max(j, start + 1)
                pages.append(t[start:cut])
                start = cut
            out[i] = pages
        return df.with_column(self.get("output_col"), out)
