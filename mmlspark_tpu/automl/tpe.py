"""Tree-structured Parzen Estimator — adaptive hyperparameter proposals.

Beyond the reference's random/grid search (``TuneHyperparameters.scala``):
TPE models the observed trials as two densities — l(x) over the top
``gamma`` fraction by metric, g(x) over the rest — and proposes the
candidate maximizing l(x)/g(x), concentrating trials near what already
works. Dimensions are treated independently (the standard TPE
simplification): continuous/log/int ranges get a Parzen (Gaussian-KDE)
density in their transformed space, categoricals a smoothed count ratio.

Used by ``TuneHyperparameters(search_strategy='tpe')``; proposals come in
batches of ``parallelism`` so trial evaluation keeps its thread pool.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from .hyperparam import DiscreteHyperParam, RangeHyperParam

__all__ = ["TPESampler"]


class _ContinuousDim:
    def __init__(self, hp: RangeHyperParam):
        self.hp = hp
        self.lo, self.hi = float(hp.low), float(hp.high)
        if hp.is_log:
            self.lo, self.hi = np.log(self.lo), np.log(self.hi)

    def transform(self, v) -> float:
        v = float(v)
        return float(np.log(v)) if self.hp.is_log else v

    def restore(self, t: float):
        v = float(np.exp(t)) if self.hp.is_log else float(t)
        v = min(max(v, float(self.hp.low)), float(self.hp.high))
        return int(round(v)) if self.hp.is_int else v

    def _kde(self, pts: np.ndarray):
        # Parzen with Scott-like bandwidth, floored so single/identical
        # points still propose a usable neighborhood
        bw = max(np.std(pts) * (len(pts) ** -0.2), (self.hi - self.lo) / 20,
                 1e-12)

        def sample(rng, n):
            centers = rng.choice(pts, size=n)
            return np.clip(centers + rng.normal(0, bw, n), self.lo, self.hi)

        def logpdf(x):
            d = (x[:, None] - pts[None, :]) / bw
            return np.log(np.mean(np.exp(-0.5 * d * d), axis=1)
                          / (bw * np.sqrt(2 * np.pi)) + 1e-300)

        return sample, logpdf

    def propose(self, rng, good: Sequence, bad: Sequence, n_cand: int):
        if not good or not bad:
            return self.restore(rng.uniform(self.lo, self.hi))
        g_pts = np.asarray([self.transform(v) for v in good])
        b_pts = np.asarray([self.transform(v) for v in bad])
        l_sample, l_logpdf = self._kde(g_pts)
        _, g_logpdf = self._kde(b_pts)
        cand = l_sample(rng, n_cand)
        best = cand[np.argmax(l_logpdf(cand) - g_logpdf(cand))]
        return self.restore(best)


class _CategoricalDim:
    def __init__(self, hp: DiscreteHyperParam):
        self.values = list(hp.values)

    def propose(self, rng, good: Sequence, bad: Sequence, n_cand: int):
        idx = {self._key(v): i for i, v in enumerate(self.values)}
        gc = np.ones(len(self.values))          # +1 smoothing
        bc = np.ones(len(self.values))
        for v in good:
            gc[idx[self._key(v)]] += 1
        for v in bad:
            bc[idx[self._key(v)]] += 1
        ratio = (gc / gc.sum()) / (bc / bc.sum())
        p = ratio / ratio.sum()
        return self.values[rng.choice(len(self.values), p=p)]

    @staticmethod
    def _key(v):
        return v if not isinstance(v, (list, dict)) else repr(v)


class TPESampler:
    """Propose parameter maps adaptively from observed (params, metric)
    trials. ``tell()`` records results; ``propose(k)`` returns the next k
    maps (random until ``n_startup`` trials exist)."""

    def __init__(self, space: Dict[str, object], seed: int = 0,
                 gamma: float = 0.25, n_startup: int = 5,
                 n_ei_candidates: int = 24, maximize: bool = True):
        if not 0.0 < gamma < 1.0:
            raise ValueError(f"gamma must be in (0, 1), got {gamma}")
        self.space = space
        self.dims = {}
        for name, hp in space.items():
            if isinstance(hp, RangeHyperParam):
                self.dims[name] = _ContinuousDim(hp)
            elif isinstance(hp, DiscreteHyperParam):
                self.dims[name] = _CategoricalDim(hp)
            else:
                raise ValueError(f"unsupported hyperparam type for "
                                 f"{name!r}: {type(hp).__name__}")
        self.rng = np.random.default_rng(seed)
        self.gamma = float(gamma)
        self.n_startup = int(n_startup)
        self.n_cand = int(n_ei_candidates)
        self.maximize = bool(maximize)
        self.trials: List[Tuple[dict, float]] = []

    def tell(self, params: dict, metric: float) -> None:
        self.trials.append((dict(params), float(metric)))

    def _split(self):
        scores = np.asarray([m for _p, m in self.trials])
        order = np.argsort(-scores if self.maximize else scores)
        n_good = max(1, int(np.ceil(self.gamma * len(order))))
        good = [self.trials[i][0] for i in order[:n_good]]
        bad = [self.trials[i][0] for i in order[n_good:]]
        return good, bad

    def _random_map(self) -> dict:
        return {k: hp.sample(self.rng) for k, hp in self.space.items()}

    def propose(self, k: int = 1) -> List[dict]:
        out = []
        for _ in range(k):
            if len(self.trials) < self.n_startup:
                out.append(self._random_map())
                continue
            good, bad = self._split()
            if not bad:
                out.append(self._random_map())
                continue
            pm = {name: dim.propose(self.rng,
                                    [g[name] for g in good],
                                    [b[name] for b in bad],
                                    self.n_cand)
                  for name, dim in self.dims.items()}
            out.append(pm)
        return out
