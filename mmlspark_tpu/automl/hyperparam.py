"""Hyperparameter spaces.

Parity surface: ``HyperparamBuilder``, ``RandomSpace``/``GridSpace``
(reference ``core/.../automl/ParamSpace.scala:25,34``, ``HyperparamBuilder``),
``DiscreteHyperParam``/``RangeHyperParam``.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterator, List, Sequence

import numpy as np

__all__ = ["DiscreteHyperParam", "RangeHyperParam", "HyperparamBuilder",
           "GridSpace", "RandomSpace"]


class DiscreteHyperParam:
    def __init__(self, values: Sequence):
        self.values = list(values)

    def sample(self, rng: np.random.Generator):
        return self.values[int(rng.integers(len(self.values)))]

    def grid(self) -> List:
        return list(self.values)


class RangeHyperParam:
    def __init__(self, low, high, is_log: bool = False, is_int: bool = False):
        self.low, self.high = low, high
        self.is_log, self.is_int = is_log, is_int

    def sample(self, rng: np.random.Generator):
        if self.is_log:
            v = float(np.exp(rng.uniform(np.log(self.low), np.log(self.high))))
        else:
            v = float(rng.uniform(self.low, self.high))
        return int(round(v)) if self.is_int else v

    def grid(self, n: int = 5) -> List:
        if self.is_log:
            vals = np.exp(np.linspace(np.log(self.low), np.log(self.high), n))
        else:
            vals = np.linspace(self.low, self.high, n)
        return [int(round(v)) if self.is_int else float(v) for v in vals]


class HyperparamBuilder:
    def __init__(self):
        self._space: Dict[str, object] = {}

    def add_hyperparam(self, name: str, param) -> "HyperparamBuilder":
        self._space[name] = param
        return self

    def build(self) -> Dict[str, object]:
        return dict(self._space)


class GridSpace:
    """Cartesian product of every hyperparam's grid."""

    def __init__(self, space: Dict[str, object]):
        self.space = space

    def param_maps(self) -> Iterator[dict]:
        names = list(self.space)
        grids = [p.grid() if isinstance(p, DiscreteHyperParam) else p.grid()
                 for p in self.space.values()]
        for combo in itertools.product(*grids):
            yield dict(zip(names, combo))


class RandomSpace:
    """Independent random draws from every hyperparam."""

    def __init__(self, space: Dict[str, object], seed: int = 0):
        self.space = space
        self.seed = seed

    def param_maps(self, n: int) -> Iterator[dict]:
        rng = np.random.default_rng(self.seed)
        for _ in range(n):
            yield {k: p.sample(rng) for k, p in self.space.items()}
