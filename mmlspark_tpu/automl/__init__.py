from .hyperparam import (DiscreteHyperParam, GridSpace, HyperparamBuilder,
                         RangeHyperParam, RandomSpace)
from .tune import FindBestModel, FindBestModelResult, TuneHyperparameters

__all__ = [
    "DiscreteHyperParam", "RangeHyperParam", "HyperparamBuilder",
    "GridSpace", "RandomSpace",
    "TuneHyperparameters", "FindBestModel", "FindBestModelResult",
]
