"""Hyperparameter search and model selection.

Parity surface: ``TuneHyperparameters`` (reference
``core/.../automl/TuneHyperparameters.scala:36-225`` — parallel random/grid
search across executors with train/validation split) and ``FindBestModel``
(``FindBestModel.scala:50`` — evaluate candidate models, keep the best).

The reference parallelizes trials across Spark executors; here trials run as
threads (each trial's device compute is already XLA-parallel), matching the
model/ensemble-parallel row of SURVEY §2.8.
"""

from __future__ import annotations

import concurrent.futures
from typing import List, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasLabelCol, Param
from ..core.pipeline import Estimator, Model, Transformer
from ..train.metrics import ComputeModelStatistics

__all__ = ["TuneHyperparameters", "FindBestModel", "FindBestModelResult"]

_MAXIMIZE = {"accuracy", "precision", "recall", "AUC", "R^2"}


def _evaluate(model: Transformer, df: DataFrame, label_col: str,
              metric: str) -> float:
    scored = model.transform(df)
    pred_col = (model.get("prediction_col")
                if model.has_param("prediction_col") else "prediction")
    prob_col = (model.get("probability_col")
                if model.has_param("probability_col") else "probability")
    stats = ComputeModelStatistics(
        label_col=label_col, scores_col=pred_col,
        scored_probabilities_col=prob_col).transform(scored)
    if metric not in stats:
        raise ValueError(f"metric {metric!r} not in {stats.columns}")
    return float(stats[metric][0])


def _apply_params(est: Estimator, pm: dict) -> Estimator:
    """Copy ``est`` with overrides, routing unknown keys to a wrapped inner
    estimator (``model`` param) — so tuning a ``TrainClassifier(model=lr)``
    can target the learner's hyperparameters directly."""
    own = {k: v for k, v in pm.items() if est.has_param(k)}
    inner_overrides = {k: v for k, v in pm.items() if not est.has_param(k)}
    out = est.copy(own)
    if inner_overrides:
        if not est.has_param("model"):
            unknown = sorted(inner_overrides)
            raise KeyError(f"{type(est).__name__} has no params {unknown} "
                           "and no inner 'model' to route them to")
        inner = out.get("model")
        out.set(model=inner.copy(inner_overrides))
    return out


class TuneHyperparameters(Estimator, HasLabelCol):
    """Hyperparameter search over an estimator.

    ``search_strategy='full'`` (default) fits every candidate at full
    budget — the reference's behavior (``TuneHyperparameters.scala:36-225``).
    ``'halving'`` is successive halving (beyond the reference): all
    candidates start at ``min_resource`` of ``resource_param``; each rung
    keeps the top ``1/halving_factor`` and multiplies the resource by
    ``halving_factor`` until ``max_resource`` — total compute grows with
    log(candidates) instead of linearly, which is what makes wide sweeps
    affordable on a single chip.
    """

    model = ComplexParam(default=None, doc="estimator to tune")
    search_space = ComplexParam(default=None,
                                doc="GridSpace or RandomSpace instance")
    number_of_iterations = Param(int, default=10,
                                 doc="trial budget (random and tpe "
                                     "strategies; grids enumerate fully)")
    evaluation_metric = Param(str, default="accuracy", doc="selection metric")
    train_fraction = Param(float, default=0.8, doc="train/validation split")
    parallelism = Param(int, default=4, doc="concurrent trials")
    seed = Param(int, default=0, doc="split seed")
    search_strategy = Param(str, default="full",
                            choices=["full", "halving", "tpe"],
                            doc="full = fit every candidate at full budget; "
                                "halving = successive halving rungs; "
                                "tpe = adaptive Parzen-estimator proposals "
                                "(needs a dict/RandomSpace search space)")
    tpe_startup_trials = Param(int, default=5,
                               doc="tpe: random trials before the model "
                                   "starts proposing")
    tpe_gamma = Param(float, default=0.25,
                      doc="tpe: top fraction of trials modeled as 'good'")
    resource_param = Param(str, default="num_iterations",
                           doc="halving: estimator param that scales cost")
    min_resource = Param(int, default=4, doc="halving: first-rung resource")
    max_resource = Param(int, default=64, doc="halving: final-rung resource")
    halving_factor = Param(int, default=3,
                           doc="halving: keep top 1/factor, grow resource "
                               "by factor, per rung")

    best_metric: Optional[float] = None
    best_params: Optional[dict] = None

    def _fit(self, df: DataFrame) -> Model:
        from .hyperparam import GridSpace, RandomSpace
        space = self.get("search_space")
        if isinstance(space, dict):
            space = RandomSpace(space, seed=self.get("seed"))
        tpe = self.get("search_strategy") == "tpe"
        if tpe:
            # validated BEFORE any candidate materialization: a large grid
            # would enumerate its whole Cartesian product just to be
            # rejected, and a RandomSpace would draw maps tpe never uses
            if isinstance(space, GridSpace):
                raise ValueError("tpe needs a dict/RandomSpace search "
                                 "space (it proposes NEW points; a grid "
                                 "is a fixed candidate list)")
            if int(self.get("number_of_iterations")) < 1:
                raise ValueError("tpe needs number_of_iterations >= 1 "
                                 "(its total trial budget)")
            param_maps = None
        elif isinstance(space, GridSpace):
            param_maps = list(space.param_maps())
        else:
            param_maps = list(space.param_maps(self.get("number_of_iterations")))
        if not tpe and not param_maps:
            raise ValueError("empty search space")

        shuffled = df.shuffle(self.get("seed"))
        n_train = int(round(self.get("train_fraction") * len(df)))
        train = shuffled.take(np.arange(n_train))
        valid = shuffled.take(np.arange(n_train, len(df)))

        est: Estimator = self.get("model")
        metric = self.get("evaluation_metric")
        maximize = metric in _MAXIMIZE

        def run_rung(maps, extra=None):
            def trial(pm: dict):
                eff = {**pm, **(extra or {})}
                model = _apply_params(est, eff).fit(train)
                return (_evaluate(model, valid, self.get("label_col"),
                                  metric), model, pm)
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=max(1, self.get("parallelism"))) as ex:
                return list(ex.map(trial, maps))

        if tpe:
            from .tpe import TPESampler
            sampler = TPESampler(space.space, seed=self.get("seed"),
                                 gamma=float(self.get("tpe_gamma")),
                                 n_startup=int(self.get(
                                     "tpe_startup_trials")),
                                 maximize=maximize)
            budget = int(self.get("number_of_iterations"))
            batch = max(1, int(self.get("parallelism")))
            results = []
            while len(results) < budget:
                maps = sampler.propose(min(batch, budget - len(results)))
                for score, model, pm in run_rung(maps):
                    sampler.tell(pm, score)
                    results.append((score, model, pm))
        elif self.get("search_strategy") == "halving":
            eta = int(self.get("halving_factor"))
            rp = self.get("resource_param")
            r = int(self.get("min_resource"))
            R = int(self.get("max_resource"))
            if eta < 2:
                raise ValueError(f"halving_factor must be >= 2, got {eta}")
            if not (1 <= r <= R):
                raise ValueError(f"need 1 <= min_resource <= max_resource, "
                                 f"got min_resource={r}, max_resource={R}")
            if any(rp in pm for pm in param_maps):
                # eff = {**pm, rp: r} would silently clobber the sampled
                # value, and best_params would report a config that never ran
                raise ValueError(
                    f"search space samples {rp!r}, which halving controls as "
                    f"the resource; remove it from the space or change "
                    f"resource_param")
            survivors = param_maps
            while r < R and len(survivors) > 1:
                results = run_rung(survivors, {rp: r})
                results.sort(key=lambda t: t[0], reverse=maximize)
                survivors = [pm for _s, _m, pm in
                             results[:max(1, len(survivors) // eta)]]
                r = min(R, r * eta)
            results = run_rung(survivors, {rp: R})
        else:
            results = run_rung(param_maps)

        best = (max if maximize else min)(results, key=lambda t: t[0])
        self.best_metric, best_model, self.best_params = best
        return best_model


class FindBestModelResult(Model):
    best_model = ComplexParam(default=None, doc="winning fitted model")
    all_model_metrics = Param(list, default=[], doc="[(index, metric)] per candidate")

    def _transform(self, df: DataFrame) -> DataFrame:
        return self.get("best_model").transform(df)


class FindBestModel(Estimator, HasLabelCol):
    """Evaluate pre-fitted candidate models on the given frame; keep the best."""

    models = ComplexParam(default=[], doc="candidate fitted models")
    evaluation_metric = Param(str, default="accuracy", doc="selection metric")

    def __init__(self, models: Optional[Sequence[Transformer]] = None, **kw):
        super().__init__(**kw)
        if models is not None:
            self.set(models=list(models))

    def _fit(self, df: DataFrame) -> FindBestModelResult:
        metric = self.get("evaluation_metric")
        maximize = metric in _MAXIMIZE
        scores: List[float] = []
        for m in self.get("models"):
            scores.append(_evaluate(m, df, self.get("label_col"), metric))
        best_i = int(np.argmax(scores) if maximize else np.argmin(scores))
        res = FindBestModelResult()
        res.set(best_model=self.get("models")[best_i],
                all_model_metrics=[[i, s] for i, s in enumerate(scores)])
        return res
