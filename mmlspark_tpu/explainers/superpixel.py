"""SLIC-style superpixel segmentation.

Parity surface: ``Superpixel`` (reference ``core/.../lime/Superpixel.scala:148``
— SLIC-like clustering used to build image interpretable features for
ImageLIME/ImageSHAP). Vectorized numpy k-means over (x, y, rgb) space.
"""

from __future__ import annotations

import numpy as np

from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer

__all__ = ["slic_superpixels", "mask_image", "SuperpixelTransformer"]


def slic_superpixels(image: np.ndarray, cell_size: int = 16,
                     modifier: float = 10.0, iters: int = 5) -> np.ndarray:
    """Segment an (H, W, C) image into superpixels.

    Returns an (H, W) int array of segment labels. ``cell_size`` plays the
    role of the reference's ``cellSize``; ``modifier`` balances color vs
    spatial distance.
    """
    H, W = image.shape[:2]
    img = image.astype(np.float64)
    if img.ndim == 2:
        img = img[..., None]
    gy = np.arange(cell_size // 2, H, cell_size)
    gx = np.arange(cell_size // 2, W, cell_size)
    # tiny images: degrade to (at least) a single centered cell
    if len(gy) == 0:
        gy = np.array([H // 2])
    if len(gx) == 0:
        gx = np.array([W // 2])
    centers_yx = np.array([(y, x) for y in gy for x in gx], dtype=np.float64)
    k = len(centers_yx)
    centers_rgb = img[centers_yx[:, 0].astype(int), centers_yx[:, 1].astype(int)]

    yy, xx = np.mgrid[0:H, 0:W]
    coords = np.stack([yy.ravel(), xx.ravel()], axis=1).astype(np.float64)
    pix = img.reshape(-1, img.shape[-1])
    spatial_scale = modifier / cell_size

    labels = np.zeros(H * W, dtype=np.int64)
    for _ in range(iters):
        # distance to every center: color + scaled spatial
        d_sp = ((coords[:, None, :] - centers_yx[None]) ** 2).sum(-1)
        d_col = ((pix[:, None, :] - centers_rgb[None]) ** 2).sum(-1)
        labels = np.argmin(d_col + (spatial_scale ** 2) * d_sp, axis=1)
        for c in range(k):
            m = labels == c
            if m.any():
                centers_yx[c] = coords[m].mean(axis=0)
                centers_rgb[c] = pix[m].mean(axis=0)
    # compact label ids
    _, labels = np.unique(labels, return_inverse=True)
    return labels.reshape(H, W)


def mask_image(image: np.ndarray, segments: np.ndarray, keep: np.ndarray,
               background: float = 0.0) -> np.ndarray:
    """Zero out (or fill) all segments not in ``keep`` (a bool vector over
    segment ids) — the LIME image perturbation."""
    mask = keep[segments]
    out = np.where(mask[..., None] if image.ndim == 3 else mask,
                   image, background)
    return out.astype(image.dtype)


class SuperpixelTransformer(Transformer, HasInputCol, HasOutputCol):
    """Decompose each image row into superpixel segment labels.

    Parity surface: ``SuperpixelTransformer``
    (``core/.../lime/SuperpixelTransformer.scala:37-64`` — cellSize/modifier
    params over the SLIC clustering). Output rows are (H, W) int arrays of
    segment ids, the form :func:`mask_image` and the image explainers
    consume (the reference's SuperpixelData cluster lists are the same
    partition, stored the JVM way).
    """

    cell_size = Param(int, default=16, doc="superpixel grid cell size")
    modifier = Param(float, default=10.0,
                     doc="spatial-vs-color distance trade-off")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="image", output_col="superpixels")

    def _transform(self, df):
        ic, oc = self.get("input_col"), self.get("output_col")
        cs, mod = int(self.get("cell_size")), float(self.get("modifier"))
        out = np.empty(len(df), dtype=object)
        from ..image.schema import ImageSchema
        for i, img in enumerate(df[ic]):
            if img is None:                 # undecodable upstream image rows
                out[i] = None               # propagate, like sibling stages
                continue
            if ImageSchema.is_image(img):
                img = np.asarray(img["data"])
            out[i] = slic_superpixels(np.asarray(img), cell_size=cs,
                                      modifier=mod)
        return df.with_column(oc, out)
