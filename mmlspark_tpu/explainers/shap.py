"""KernelSHAP explainers.

Parity surface: ``KernelSHAPBase.transform`` = coalition sample → score →
weighted least squares (reference ``explainers/KernelSHAPBase.scala:43-94``,
sample-count logic ``:126-139``), variants ``TabularSHAP``/``VectorSHAP``/
``TextSHAP``/``ImageSHAP.scala:131``, sampler ``KernelSHAPSampler.scala``.

Output layout matches the reference: attribution vector = [base_value,
phi_1..phi_d] so sum(vector) ≈ f(x).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, HasInputCols, Param
from .base import dense_matrix, LocalExplainer, shapley_kernel_weights
from .regression import batched_weighted_lstsq
from .superpixel import mask_image, slic_superpixels

__all__ = ["VectorSHAP", "TabularSHAP", "TextSHAP", "ImageSHAP"]


def _coalitions(m: int, d: int, rng: np.random.Generator) -> np.ndarray:
    """Binary coalition masks with the empty & full rows pinned first."""
    masks = rng.random((m, d)) > 0.5
    masks[0] = False
    if m > 1:
        masks[1] = True
    return masks


def _shap_solve(masks: np.ndarray, scores: np.ndarray) -> np.ndarray:
    """masks: (B, m, d) with rows 0/1 pinned to empty/full; scores: (B, m)
    → phis (B, d+1) incl. base value.

    The efficiency constraint sum(phi) = f(x) − base is enforced by
    eliminating the last feature (the SHAP-library formulation), keeping the
    weight range float32-friendly instead of using 1e6 constraint weights.
    """
    B, m, d = masks.shape
    base, fx = scores[:, 0], scores[:, 1]
    if d == 1:
        return np.stack([base, fx - base], axis=1)
    Z = masks.astype(np.float64)
    w = np.stack([shapley_kernel_weights(masks[b]) for b in range(B)])
    # substitute phi_d = (fx - base) - sum(phi_1..d-1)
    Zr = Z[:, :, :-1] - Z[:, :, -1:]
    yr = scores - base[:, None] - Z[:, :, -1] * (fx - base)[:, None]
    coefs, _ = batched_weighted_lstsq(Zr, yr, w, fit_intercept=False)
    phi_last = (fx - base) - coefs.sum(axis=1)
    return np.concatenate([base[:, None], coefs, phi_last[:, None]], axis=1)


class _SHAPParams(LocalExplainer):
    background_data = ComplexParam(default=None,
                                   doc="background frame for masked values")


class VectorSHAP(_SHAPParams, HasInputCol):
    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="features")

    def _transform(self, df: DataFrame) -> DataFrame:
        col = self.get("input_col")
        X = dense_matrix(df[col])
        bg = self.get("background_data")
        bgX = X if bg is None else dense_matrix(bg[col])
        base = bgX.mean(axis=0)
        n, d = X.shape
        m = self.get("num_samples")
        rng = np.random.default_rng(self.get("seed"))
        masks = np.stack([_coalitions(m, d, rng) for _ in range(n)])
        samples = np.where(masks, X[:, None, :], base[None, None, :])
        flat = samples.reshape(n * m, d)
        scol = np.empty(n * m, dtype=object)
        for i in range(n * m):
            scol[i] = flat[i]
        scores = self._score_frame(DataFrame({col: scol})).reshape(n, m)
        phis = _shap_solve(masks, scores)
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = phis[i]
        return df.with_column(self.get("output_col"), out)


class TabularSHAP(_SHAPParams, HasInputCols):
    def _transform(self, df: DataFrame) -> DataFrame:
        cols: List[str] = self.get("input_cols")
        X = np.stack([df[c].astype(np.float64) for c in cols], axis=1)
        bg = self.get("background_data")
        bgX = X if bg is None else np.stack(
            [bg[c].astype(np.float64) for c in cols], axis=1)
        base = bgX.mean(axis=0)
        n, d = X.shape
        m = self.get("num_samples")
        rng = np.random.default_rng(self.get("seed"))
        masks = np.stack([_coalitions(m, d, rng) for _ in range(n)])
        samples = np.where(masks, X[:, None, :], base[None, None, :])
        flat = samples.reshape(n * m, d)
        scores = self._score_frame(DataFrame(
            {c: flat[:, j] for j, c in enumerate(cols)})).reshape(n, m)
        phis = _shap_solve(masks, scores)
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = phis[i]
        return df.with_column(self.get("output_col"), out)


class TextSHAP(_SHAPParams, HasInputCol):
    tokens_col = Param(str, default="tokens", doc="emit token list here")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="text")

    def _transform(self, df: DataFrame) -> DataFrame:
        col = self.get("input_col")
        m = self.get("num_samples")
        rng = np.random.default_rng(self.get("seed"))
        n = len(df)
        token_lists = [str(t).split() for t in df[col]]

        texts, masks_per_row = [], []
        for toks in token_lists:
            d = max(1, len(toks))
            masks = _coalitions(m, d, rng)
            for s in masks:
                texts.append(" ".join(t for t, keep in zip(toks, s) if keep))
            masks_per_row.append(masks)
        scores = self._score_frame(DataFrame({col: texts}))

        out = np.empty(n, dtype=object)
        toks_col = np.empty(n, dtype=object)
        for i in range(n):
            phis = _shap_solve(masks_per_row[i][None].astype(np.float64),
                               scores[i * m:(i + 1) * m][None])
            out[i] = phis[0]
            toks_col[i] = token_lists[i]
        return (df.with_column(self.get("output_col"), out)
                  .with_column(self.get("tokens_col"), toks_col))


class ImageSHAP(_SHAPParams, HasInputCol):
    cell_size = Param(int, default=16, doc="superpixel target size")
    modifier = Param(float, default=10.0, doc="SLIC color/space balance")
    superpixel_col = Param(str, default="superpixels",
                           doc="emit the (H, W) segment map here")
    background_value = Param(float, default=0.0, doc="masked-pixel fill")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="image")

    def _transform(self, df: DataFrame) -> DataFrame:
        col = self.get("input_col")
        m = self.get("num_samples")
        rng = np.random.default_rng(self.get("seed"))
        n = len(df)

        masked, seg_maps, masks_per_row = [], [], []
        for v in df[col]:
            img = np.asarray(v)
            segs = slic_superpixels(img, self.get("cell_size"),
                                    self.get("modifier"))
            k = int(segs.max()) + 1
            masks = _coalitions(m, k, rng)
            for s in masks:
                masked.append(mask_image(img, segs, s,
                                         self.get("background_value")))
            seg_maps.append(segs)
            masks_per_row.append(masks)

        mcol = np.empty(len(masked), dtype=object)
        for i, im in enumerate(masked):
            mcol[i] = im
        scores = self._score_frame(DataFrame({col: mcol})).reshape(n, m)

        out = np.empty(n, dtype=object)
        segs_col = np.empty(n, dtype=object)
        for i in range(n):
            phis = _shap_solve(masks_per_row[i][None].astype(np.float64),
                               scores[i][None])
            out[i] = phis[0]
            segs_col[i] = seg_maps[i]
        return (df.with_column(self.get("output_col"), out)
                  .with_column(self.get("superpixel_col"), segs_col))
