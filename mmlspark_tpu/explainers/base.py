"""Local explainer base.

Parity surface: ``LocalExplainer`` (reference
``explainers/LocalExplainer.scala:16-72``) — shared plumbing for LIME/SHAP:
wrap an inner model, score perturbed samples through it, and emit one
attribution vector per explained row.

TPU-first: all rows' perturbations are concatenated into ONE frame and scored
in ONE ``model.transform`` call (the reference scores per row), so the inner
model sees a large static batch; surrogate fits then run as a single vmapped
solve (``regression.py``).
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Transformer

__all__ = ["LocalExplainer", "shapley_kernel_weights", "dense_row",
           "dense_matrix"]

try:                            # guarded like models/gbdt/binning.py
    import scipy.sparse as _sp
except Exception:               # pragma: no cover - scipy is in the image
    _sp = None


def dense_row(v) -> np.ndarray:
    """One features-column row → flat float64 vector; scipy sparse rows
    densify here (explainers perturb in dense space — a row's worth at a
    time, so this never materializes the full sparse matrix)."""
    if _sp is not None and _sp.issparse(v):
        return v.toarray().astype(np.float64).ravel()
    return np.asarray(v, dtype=np.float64).ravel()


def dense_matrix(col) -> np.ndarray:
    """A features column (dense or sparse rows) → (n, d) float64 matrix."""
    return np.stack([dense_row(v) for v in col])


class LocalExplainer(Transformer):
    model = ComplexParam(default=None, doc="inner model to explain")
    target_col = Param(str, default="probability",
                       doc="model output column to explain")
    target_classes = Param((list, int), default=[1],
                           doc="class indices summed into the scalar target")
    output_col = Param(str, default="explanation",
                       doc="per-row attribution vector column")
    num_samples = Param(int, default=256, doc="perturbations per row")
    seed = Param(int, default=0, doc="sampling seed")

    def _score_frame(self, samples_df: DataFrame) -> np.ndarray:
        """Run the inner model over a frame of perturbed samples; reduce the
        target column to one scalar per row."""
        out = self.get("model").transform(samples_df)
        col = out[self.get("target_col")]
        targets = self.get("target_classes")
        if col.dtype == object:
            vals = np.stack([np.asarray(v, dtype=np.float64).ravel()
                             for v in col])
        else:
            vals = np.asarray(col, dtype=np.float64)
            if vals.ndim == 1:
                return vals  # already one scalar per row
            vals = vals.reshape(len(col), -1)  # dense (n, classes) column
        bad = [t for t in targets if t >= vals.shape[1]]
        if bad:
            raise ValueError(
                f"target_classes {bad} out of range for "
                f"{self.get('target_col')!r} vectors of length "
                f"{vals.shape[1]}")
        return vals[:, targets].sum(axis=1)


def shapley_kernel_weights(masks: np.ndarray,
                           pinned_weight: float = 0.0) -> np.ndarray:
    """KernelSHAP weights for binary coalition masks (m, d)
    (reference ``KernelSHAPBase.scala:43-94`` sampling weights).

    Empty/full coalitions get ``pinned_weight``: the solver handles the
    f(empty)=base and f(full)=fx constraints by elimination, not by the
    huge-weight trick (whose 1e6..1e-9 dynamic range is unsolvable in the
    float32 the device math runs in). Weights are normalized to max 1.
    """
    from math import comb
    d = masks.shape[1]
    sizes = masks.sum(axis=1).astype(int)
    w = np.empty(len(masks), dtype=np.float64)
    for i, s in enumerate(sizes):
        if s == 0 or s == d:
            w[i] = pinned_weight
        else:
            w[i] = (d - 1) / (comb(d, s) * s * (d - s))
    peak = w.max()
    return w / peak if peak > 0 else w
