"""LIME explainers.

Parity surface: ``LIMEBase.transform`` = sample → score-with-inner-model →
per-row lasso fit (reference ``explainers/LIMEBase.scala:67-115``), with
variants ``TabularLIME.scala:160``, ``VectorLIME``, ``TextLIME.scala:88``,
``ImageLIME.scala:133`` and the samplers in ``Sampler.scala``/``LIMESampler.scala``.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, HasInputCols, Param
from .base import dense_matrix, LocalExplainer
from .regression import batched_lasso
from .superpixel import mask_image, slic_superpixels

__all__ = ["VectorLIME", "TabularLIME", "TextLIME", "ImageLIME"]


class _LIMEParams(LocalExplainer):
    kernel_width = Param(float, default=0.75, doc="locality kernel width")
    regularization = Param(float, default=0.01, doc="lasso alpha")
    background_data = ComplexParam(default=None,
                                   doc="DataFrame of background rows "
                                       "(defaults to the explained frame)")


def _lime_fit(states: np.ndarray, scores: np.ndarray, dists: np.ndarray,
              kernel_width: float, alpha: float):
    """states: (B, m, d) surrogate inputs; scores: (B, m); dists: (B, m)."""
    w = np.exp(-(dists ** 2) / (kernel_width ** 2))
    coefs, _ = batched_lasso(states, scores, w, alpha=alpha)
    return coefs


class VectorLIME(_LIMEParams, HasInputCol):
    """Explain a model consuming a dense vector column. Perturbations are
    gaussian around the row, scaled by background stds."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="features")

    def _transform(self, df: DataFrame) -> DataFrame:
        col = self.get("input_col")
        X = dense_matrix(df[col])
        bg = self.get("background_data")
        bgX = X if bg is None else dense_matrix(bg[col])
        sigma = bgX.std(axis=0) + 1e-12
        n, d = X.shape
        m = self.get("num_samples")
        rng = np.random.default_rng(self.get("seed"))
        noise = rng.normal(0, 1, (n, m, d))
        samples = X[:, None, :] + noise * sigma[None, None, :]

        flat = samples.reshape(n * m, d)
        scol = np.empty(n * m, dtype=object)
        for i in range(n * m):
            scol[i] = flat[i]
        scores = self._score_frame(DataFrame({col: scol})).reshape(n, m)

        states = noise  # standardized offsets are the surrogate inputs
        dists = np.sqrt((noise ** 2).mean(axis=2))
        coefs = _lime_fit(states, scores, dists, self.get("kernel_width"),
                          self.get("regularization"))
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = coefs[i] / sigma  # per original-unit attribution
        return df.with_column(self.get("output_col"), out)


class TabularLIME(_LIMEParams, HasInputCols):
    """Explain a model consuming plain numeric columns."""

    def _transform(self, df: DataFrame) -> DataFrame:
        cols: List[str] = self.get("input_cols")
        X = np.stack([df[c].astype(np.float64) for c in cols], axis=1)
        bg = self.get("background_data")
        bgX = X if bg is None else np.stack(
            [bg[c].astype(np.float64) for c in cols], axis=1)
        sigma = bgX.std(axis=0) + 1e-12
        n, d = X.shape
        m = self.get("num_samples")
        rng = np.random.default_rng(self.get("seed"))
        noise = rng.normal(0, 1, (n, m, d))
        samples = X[:, None, :] + noise * sigma[None, None, :]
        flat = samples.reshape(n * m, d)
        scores = self._score_frame(DataFrame(
            {c: flat[:, j] for j, c in enumerate(cols)})).reshape(n, m)
        dists = np.sqrt((noise ** 2).mean(axis=2))
        coefs = _lime_fit(noise, scores, dists, self.get("kernel_width"),
                          self.get("regularization"))
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = coefs[i] / sigma
        return df.with_column(self.get("output_col"), out)


class TextLIME(_LIMEParams, HasInputCol):
    """Token-masking LIME for text models: surrogate features are
    keep/drop bits per token (reference ``TextLIME.scala:88``)."""

    tokens_col = Param(str, default="tokens", doc="emit the token list here")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="text")

    def _transform(self, df: DataFrame) -> DataFrame:
        col = self.get("input_col")
        m = self.get("num_samples")
        rng = np.random.default_rng(self.get("seed"))
        token_lists = [str(t).split() for t in df[col]]
        n = len(df)

        all_texts, all_states, all_dists, spans = [], [], [], []
        for toks in token_lists:
            d = max(1, len(toks))
            states = rng.random((m, d)) > 0.5
            states[0] = True  # include the unperturbed row
            for s in states:
                kept = [t for t, keep in zip(toks, s) if keep]
                all_texts.append(" ".join(kept))
            all_states.append(states)
            all_dists.append(1.0 - states.mean(axis=1))
            spans.append(d)

        scores = self._score_frame(DataFrame({col: all_texts}))
        out = np.empty(n, dtype=object)
        for i in range(n):
            sc = scores[i * m:(i + 1) * m]
            coefs = _lime_fit(all_states[i][None].astype(np.float64),
                              sc[None], all_dists[i][None],
                              self.get("kernel_width"),
                              self.get("regularization"))
            out[i] = coefs[0]
        toks_col = np.empty(n, dtype=object)
        for i, t in enumerate(token_lists):
            toks_col[i] = t
        return (df.with_column(self.get("output_col"), out)
                  .with_column(self.get("tokens_col"), toks_col))


class ImageLIME(_LIMEParams, HasInputCol):
    """Superpixel-masking LIME for image models
    (reference ``ImageLIME.scala:133`` + ``Superpixel.scala``)."""

    cell_size = Param(int, default=16, doc="superpixel target size")
    modifier = Param(float, default=10.0, doc="SLIC color/space balance")
    superpixel_col = Param(str, default="superpixels",
                           doc="emit the (H, W) segment map here")
    background_value = Param(float, default=0.0, doc="masked-pixel fill")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="image")

    def _transform(self, df: DataFrame) -> DataFrame:
        col = self.get("input_col")
        m = self.get("num_samples")
        rng = np.random.default_rng(self.get("seed"))
        n = len(df)

        images, seg_maps, states_per_row, masked = [], [], [], []
        for v in df[col]:
            img = np.asarray(v)
            segs = slic_superpixels(img, self.get("cell_size"),
                                    self.get("modifier"))
            k = int(segs.max()) + 1
            states = rng.random((m, k)) > 0.5
            states[0] = True
            for s in states:
                masked.append(mask_image(img, segs, s,
                                         self.get("background_value")))
            images.append(img)
            seg_maps.append(segs)
            states_per_row.append(states)

        mcol = np.empty(len(masked), dtype=object)
        for i, im in enumerate(masked):
            mcol[i] = im
        scores = self._score_frame(DataFrame({col: mcol})).reshape(n, m)

        out = np.empty(n, dtype=object)
        segs_col = np.empty(n, dtype=object)
        for i in range(n):
            states = states_per_row[i].astype(np.float64)
            dists = 1.0 - states.mean(axis=1)
            coefs = _lime_fit(states[None], scores[i][None], dists[None],
                              self.get("kernel_width"),
                              self.get("regularization"))
            out[i] = coefs[0]
            segs_col[i] = seg_maps[i]
        return (df.with_column(self.get("output_col"), out)
                  .with_column(self.get("superpixel_col"), segs_col))
