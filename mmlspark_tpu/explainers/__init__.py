from .base import LocalExplainer, shapley_kernel_weights
from .ice import ICETransformer
from .lime import ImageLIME, TabularLIME, TextLIME, VectorLIME
from .regression import batched_lasso, batched_weighted_lstsq
from .shap import ImageSHAP, TabularSHAP, TextSHAP, VectorSHAP
from .superpixel import (SuperpixelTransformer, mask_image,
                         slic_superpixels)

__all__ = [
    "LocalExplainer", "shapley_kernel_weights",
    "VectorLIME", "TabularLIME", "TextLIME", "ImageLIME",
    "VectorSHAP", "TabularSHAP", "TextSHAP", "ImageSHAP",
    "ICETransformer",
    "batched_lasso", "batched_weighted_lstsq",
    "slic_superpixels", "mask_image", "SuperpixelTransformer",
]
