"""Batched surrogate regressions for explainers.

Parity surface: the reference's per-row Breeze solvers —
``LassoRegression.scala:88`` / ``LeastSquaresRegression.scala`` /
``RegressionBase.scala:151`` — called once per explained row inside
``LIMEBase.transform`` and ``KernelSHAPBase.transform``.

TPU-first redesign: one ``vmap`` over explained rows, so every row's
surrogate fit is a lane of a single XLA program (the reference loops rows on
the JVM). Lasso is ISTA in a ``lax.scan``; weighted least squares is a
batched normal-equations solve.
"""

from __future__ import annotations

import numpy as np

__all__ = ["batched_weighted_lstsq", "batched_lasso"]


def batched_weighted_lstsq(X: np.ndarray, y: np.ndarray, w: np.ndarray,
                           fit_intercept: bool = True):
    """Solve argmin ||sqrt(w) (X b - y)||² for a batch.

    X: (B, m, d), y: (B, m), w: (B, m) → coefs (B, d), intercept (B,).
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def solve(X, y, w):
        def one(Xi, yi, wi):
            if fit_intercept:
                Xi = jnp.concatenate([Xi, jnp.ones((Xi.shape[0], 1))], axis=1)
            sw = jnp.sqrt(jnp.maximum(wi, 0.0))
            A = Xi * sw[:, None]
            b = yi * sw
            # ridge-stabilized normal equations: batched d×d solve on the MXU
            G = A.T @ A + 1e-8 * jnp.eye(A.shape[1])
            coef = jnp.linalg.solve(G, A.T @ b)
            return coef

        return jax.vmap(one)(X, y, w)

    coefs = np.asarray(solve(jnp.asarray(X, jnp.float32),
                             jnp.asarray(y, jnp.float32),
                             jnp.asarray(w, jnp.float32)))
    if fit_intercept:
        return coefs[:, :-1], coefs[:, -1]
    return coefs, np.zeros(len(coefs))


def batched_lasso(X: np.ndarray, y: np.ndarray, w: np.ndarray,
                  alpha: float = 0.01, steps: int = 200):
    """Batched weighted lasso via ISTA in a ``lax.scan``.

    X: (B, m, d), y: (B, m), w: (B, m) → coefs (B, d), intercept (B,).
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def solve(X, y, w):
        def one(Xi, yi, wi):
            wi = wi / jnp.maximum(wi.sum(), 1e-12)
            # center by weighted means so the intercept drops out of ISTA
            xm = (Xi * wi[:, None]).sum(axis=0)
            ym = (yi * wi).sum()
            Xc = Xi - xm
            yc = yi - ym
            A = Xc * wi[:, None]
            G = Xc.T @ A                     # weighted gram (d, d)
            c = A.T @ yc                     # weighted correlation (d,)
            L = jnp.trace(G) + 1e-6          # cheap Lipschitz bound
            t = 1.0 / L

            def step(beta, _):
                grad = G @ beta - c
                z = beta - t * grad
                beta = jnp.sign(z) * jnp.maximum(jnp.abs(z) - t * alpha, 0.0)
                return beta, None

            beta, _ = jax.lax.scan(step, jnp.zeros(Xi.shape[1]), None,
                                   length=steps)
            intercept = ym - beta @ xm
            return beta, intercept

        return jax.vmap(one)(X, y, w)

    coefs, inter = solve(jnp.asarray(X, jnp.float32),
                         jnp.asarray(y, jnp.float32),
                         jnp.asarray(w, jnp.float32))
    return np.asarray(coefs), np.asarray(inter)
