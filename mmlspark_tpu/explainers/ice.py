"""Individual Conditional Expectation.

Parity surface: ``ICEExplainer`` (reference ``explainers/ICETransformer.scala``
278 LoC): for each requested feature, sweep a grid of values, score the model
with that feature replaced for every instance, and emit per-instance curves
(kind="individual") or their average, the partial-dependence plot
(kind="average").
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param
from .base import LocalExplainer

__all__ = ["ICETransformer"]


class ICETransformer(LocalExplainer):
    kind = Param(str, default="individual", choices=["individual", "average"],
                 doc="per-instance curves or the PDP average")
    numeric_features = Param((list, str), default=[],
                             doc="numeric columns to sweep")
    categorical_features = Param((list, str), default=[],
                                 doc="categorical columns to sweep")
    num_splits = Param(int, default=10, doc="grid points per numeric feature")

    def _grid_for(self, df: DataFrame, feat: str, categorical: bool):
        col = df[feat]
        if categorical:
            return list(dict.fromkeys(
                v.item() if isinstance(v, np.generic) else v for v in col))
        f = col.astype(np.float64)
        return list(np.linspace(np.nanmin(f), np.nanmax(f),
                                self.get("num_splits")))

    def _transform(self, df: DataFrame) -> DataFrame:
        n = len(df)
        out = df
        feats = ([(f, False) for f in self.get("numeric_features")]
                 + [(f, True) for f in self.get("categorical_features")])
        for feat, is_cat in feats:
            grid = self._grid_for(df, feat, is_cat)
            g = len(grid)
            # one scoring frame: every instance × every grid value
            reps: Dict[str, np.ndarray] = {}
            for c in df.columns:
                col = df[c]
                reps[c] = np.tile(col, g) if col.dtype != object else \
                    np.concatenate([col] * g)
            swept = np.concatenate(
                [np.full(n, v, dtype=object if is_cat else np.float64)
                 for v in grid])
            reps[feat] = swept
            scores = self._score_frame(DataFrame(reps)).reshape(g, n).T
            curves = np.empty(n, dtype=object)
            if self.get("kind") == "average":
                pdp = scores.mean(axis=0)
                for i in range(n):
                    curves[i] = pdp
            else:
                for i in range(n):
                    curves[i] = scores[i]
            out = out.with_column(f"{feat}_dependence", curves)
            out = out.with_column_metadata(
                f"{feat}_dependence",
                {"ice_grid": [float(v) if not is_cat else v for v in grid]})
        return out
