from .batching import (DynamicBufferedBatcher, DynamicMiniBatchTransformer,
                       FixedMiniBatchTransformer, FlattenBatch, HasMiniBatcher,
                       PrefetchIterator, TimeIntervalBatcher,
                       TimeIntervalMiniBatchTransformer)
from .misc import (Cacher, ClassBalancer, ClassBalancerModel, DropColumns,
                   EnsembleByKey, Explode, Lambda, MultiColumnAdapter,
                   PartitionConsolidator, RenameColumn, Repartition,
                   SelectColumns, StratifiedRepartition, SummarizeData,
                   TextPreprocessor, Timer, TimerModel, UDFTransformer,
                   UnicodeNormalize)

__all__ = [
    "FixedMiniBatchTransformer", "DynamicMiniBatchTransformer",
    "TimeIntervalMiniBatchTransformer", "FlattenBatch", "HasMiniBatcher",
    "DynamicBufferedBatcher", "TimeIntervalBatcher", "PrefetchIterator",
    "Cacher", "DropColumns", "SelectColumns", "RenameColumn", "Repartition",
    "Explode", "Lambda", "UDFTransformer", "MultiColumnAdapter",
    "ClassBalancer", "ClassBalancerModel", "EnsembleByKey",
    "StratifiedRepartition", "SummarizeData", "TextPreprocessor",
    "UnicodeNormalize", "Timer", "TimerModel", "PartitionConsolidator",
]
