from .batching import (DynamicBufferedBatcher, DynamicMiniBatchTransformer,
                       FixedMiniBatchTransformer, FlattenBatch, HasMiniBatcher,
                       TimeIntervalBatcher, TimeIntervalMiniBatchTransformer)

__all__ = [
    "FixedMiniBatchTransformer", "DynamicMiniBatchTransformer",
    "TimeIntervalMiniBatchTransformer", "FlattenBatch", "HasMiniBatcher",
    "DynamicBufferedBatcher", "TimeIntervalBatcher",
]
