"""Minibatching: the DataFrame → batch → tensor boundary.

Parity surface (reference ``stages/MiniBatchTransformer.scala:17-251`` and
``stages/Batchers.scala:12-152``):

* ``FixedMiniBatchTransformer`` — groups every ``batch_size`` rows into one
  batch row whose cells are stacked arrays (the reference transposes
  rows→columnar batches in ``MiniBatchBase.transform``).
* ``DynamicMiniBatchTransformer`` — batches whatever is buffered, bounded by
  ``max_batch_size``; in the eager columnar world this means one batch per
  partition chunk.
* ``TimeIntervalMiniBatchTransformer`` — batches a *stream* by wall-clock
  interval (used by serving); operates on row iterators.
* ``FlattenBatch`` — the inverse transpose (``MiniBatchTransformer.scala:187-251``).
* Iterator batchers with a background prefetch thread mirror
  ``DynamicBufferedBatcher`` (``Batchers.scala:12-56``).

Batched columns are object arrays whose elements are per-batch ndarrays
(numeric columns) or lists (string/struct columns).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Iterable, Iterator, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, Params, identity
from ..core.pipeline import Transformer

__all__ = ["FixedMiniBatchTransformer", "DynamicMiniBatchTransformer",
           "TimeIntervalMiniBatchTransformer", "FlattenBatch", "HasMiniBatcher",
           "DynamicBufferedBatcher", "TimeIntervalBatcher", "PrefetchIterator",
           "batch_slices"]


def _stack_cell(col: np.ndarray) -> object:
    """Rows of one column for one batch → a single batch cell."""
    if col.dtype == object:
        vals = list(col)
        if vals and isinstance(vals[0], np.ndarray):
            shapes = {v.shape for v in vals}
            if len(shapes) == 1:
                return np.stack(vals)
        return vals
    return np.asarray(col)


def batch_slices(n: int, batch_size: int) -> List[slice]:
    return [slice(i, min(i + batch_size, n)) for i in range(0, n, batch_size)]


class _MiniBatchBase(Transformer):
    """Shared transpose logic: slices of rows → one batch-row per slice."""

    def _slices(self, part: DataFrame) -> List[slice]:
        raise NotImplementedError

    def _transform(self, df: DataFrame) -> DataFrame:
        def per_part(part: DataFrame, _i: int) -> DataFrame:
            slices = self._slices(part)
            cols: Dict[str, np.ndarray] = {}
            for name in part.columns:
                col = part[name]
                cell = np.empty(len(slices), dtype=object)
                for j, sl in enumerate(slices):
                    cell[j] = _stack_cell(col[sl])
                cols[name] = cell
            return DataFrame(cols, 1, metadata={c: part.column_metadata(c)
                                                for c in part.columns})

        return df.map_partitions(per_part)


class FixedMiniBatchTransformer(_MiniBatchBase):
    """Reference: ``FixedMiniBatchTransformer`` (MiniBatchTransformer.scala:151)."""

    batch_size = Param(int, default=10, doc="rows per batch")

    def _slices(self, part: DataFrame) -> List[slice]:
        return batch_slices(len(part), self.batch_size)


class DynamicMiniBatchTransformer(_MiniBatchBase):
    """Reference: ``DynamicMiniBatchTransformer`` (MiniBatchTransformer.scala:53)."""

    max_batch_size = Param(int, default=1 << 30, doc="upper bound on batch size")

    def _slices(self, part: DataFrame) -> List[slice]:
        return batch_slices(len(part), min(self.max_batch_size, max(1, len(part))))


class TimeIntervalMiniBatchTransformer(_MiniBatchBase):
    """Reference: ``TimeIntervalMiniBatchTransformer`` (MiniBatchTransformer.scala:77).

    The reference's batcher groups rows by *arrival* wall-clock windows. On a
    materialized DataFrame arrival time is gone, so windows come from an
    event-time column instead: set ``timestamp_col`` (epoch millis, epoch
    seconds as float, or datetime64) and each batch covers rows whose
    timestamps fall within ``millis_to_wait`` of the batch's first row, in
    row order. Without a ``timestamp_col`` the interval degenerates to one
    batch per partition (the wall-clock semantics live on streams — use
    :class:`TimeIntervalBatcher` for those).
    """

    millis_to_wait = Param(int, default=1000, doc="batch window in milliseconds")
    max_batch_size = Param(int, default=1 << 30, doc="upper bound on batch size")
    timestamp_col = Param(str, default=None, converter=identity,
                          doc="event-time column defining the windows "
                              "(epoch millis, epoch seconds, or datetime64)")

    @staticmethod
    def _to_millis(col: np.ndarray) -> np.ndarray:
        arr = np.asarray(col)
        if np.issubdtype(arr.dtype, np.datetime64):
            return arr.astype("datetime64[ms]").astype(np.int64)
        if np.issubdtype(arr.dtype, np.floating):
            return (arr * 1000.0).astype(np.int64)  # epoch seconds
        return arr.astype(np.int64)                 # epoch millis

    def _slices(self, part: DataFrame) -> List[slice]:
        cap = min(self.max_batch_size, max(1, len(part)))
        ts_col = self.get_or_none("timestamp_col")
        if not ts_col:
            return batch_slices(len(part), cap)
        ts = self._to_millis(part[ts_col])
        window = int(self.millis_to_wait)
        slices: List[slice] = []
        start = 0
        for i in range(1, len(ts) + 1):
            if i == len(ts) or ts[i] - ts[start] >= window \
                    or i - start >= cap:
                slices.append(slice(start, i))
                start = i
        return slices


class FlattenBatch(Transformer):
    """Inverse transpose (reference ``FlattenBatch``, MiniBatchTransformer.scala:187)."""

    def _transform(self, df: DataFrame) -> DataFrame:
        def per_part(part: DataFrame, _i: int) -> DataFrame:
            out_cols: Dict[str, List] = {c: [] for c in part.columns}
            lengths: List[int] = []
            for bi in range(len(part)):
                cell_lens = set()
                for c in part.columns:
                    cell = part[c][bi]
                    cell_lens.add(len(cell))
                if len(cell_lens) != 1:
                    raise ValueError(
                        f"ragged batch row {bi}: cell lengths {cell_lens}")
                lengths.append(cell_lens.pop())
            for c in part.columns:
                col = part[c]
                vals: List = []
                for bi in range(len(part)):
                    cell = col[bi]
                    vals.extend(list(cell))
                out_cols[c] = vals
            return DataFrame(out_cols, 1, metadata={c: part.column_metadata(c)
                                                    for c in part.columns})

        return df.map_partitions(per_part)


class HasMiniBatcher(Params):
    """Reference: ``HasMiniBatcher`` (MiniBatchTransformer.scala:108)."""

    from ..core.params import ComplexParam as _CP
    mini_batcher = _CP(default=None, doc="minibatch transformer to apply first")

    def get_mini_batcher(self) -> Optional[Transformer]:
        return self.get_or_none("mini_batcher")


# ---------------------------------------------------------------------------
# Streaming batchers (serving / iterator paths)
# ---------------------------------------------------------------------------

class _QueueProducer:
    """A daemon thread draining ``it`` into a bounded queue.

    The shared producer half of every streaming batcher here (reference
    ``DynamicBufferedBatcher``, Batchers.scala:12-56): items flow into
    ``self.queue`` capped at ``max_buffer_size`` (this bound is what keeps
    host memory finite when the producer outruns the consumer), a sentinel
    marks exhaustion, and a producer-side exception is parked for the
    consumer to re-raise.
    """

    SENTINEL = object()

    def __init__(self, it: Iterable, max_buffer_size: int):
        self.queue: "queue.Queue" = queue.Queue(maxsize=max_buffer_size)
        self._error: List[BaseException] = []

        def produce():
            try:
                for item in it:
                    self.queue.put(item)
            except BaseException as e:  # surfaced on the consumer side
                self._error.append(e)
            finally:
                self.queue.put(self.SENTINEL)

        # tpulint: disable=TPU025 — producer crash IS contained: the
        # BaseException is captured for raise_pending() on the consumer
        # side and the sentinel still lands in finally; a restart would
        # re-iterate the source and duplicate items
        self.thread = threading.Thread(target=produce, daemon=True)
        self.thread.start()

    def raise_pending(self) -> None:
        if self._error:
            raise self._error[0]


class PrefetchIterator:
    """Bounded in-order background prefetch over any iterator.

    ``depth`` items are computed ahead on the producer thread while the
    consumer works on the current one — the host-side half of the device
    pipeline (coerce/pad of batch k+1 overlapping dispatch of batch k), with
    the queue bound capping host memory at ``depth`` prepared batches. Unlike
    :class:`DynamicBufferedBatcher`, items come out one at a time and in
    order: device feeds must stay aligned with their row slices.
    """

    def __init__(self, it: Iterable, depth: int = 2):
        self._producer = _QueueProducer(it, max_buffer_size=max(1, int(depth)))

    def __iter__(self) -> Iterator:
        q = self._producer.queue
        while True:
            item = q.get()
            if item is _QueueProducer.SENTINEL:
                break
            yield item
        self._producer.raise_pending()


class DynamicBufferedBatcher:
    """Background-thread prefetching batcher over a row iterator.

    Reference: ``DynamicBufferedBatcher`` (Batchers.scala:12-56) — a producer
    thread fills a bounded queue while the consumer drains *everything
    currently available* into one batch.
    """

    def __init__(self, it: Iterable, max_buffer_size: int = 1024):
        self._producer = _QueueProducer(it, max_buffer_size)
        self._done = False

    def __iter__(self) -> Iterator[List]:
        q = self._producer.queue
        while not self._done:
            first = q.get()
            if first is _QueueProducer.SENTINEL:
                self._done = True
                break
            batch = [first]
            while True:
                try:
                    nxt = q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _QueueProducer.SENTINEL:
                    self._done = True
                    break
                batch.append(nxt)
            yield batch
        self._producer.raise_pending()


class TimeIntervalBatcher:
    """Wall-clock-windowed batcher (reference ``TimeIntervalBatcher``,
    Batchers.scala:95-152).

    Consumes its own producer queue with timed ``get`` so a pending batch is
    flushed when the window elapses even if the source stream stalls.
    """

    def __init__(self, it: Iterable, millis: int = 1000,
                 max_batch_size: int = 1 << 30, max_buffer_size: int = 1024):
        self._millis = millis
        self._max_batch = max_batch_size
        self._producer = _QueueProducer(it, max_buffer_size)

    def __iter__(self) -> Iterator[List]:
        q = self._producer.queue
        pending: List = []
        window = self._millis / 1e3
        deadline = time.monotonic() + window
        done = False
        while not done:
            timeout = max(0.0, deadline - time.monotonic())
            try:
                item = q.get(timeout=timeout)
                if item is _QueueProducer.SENTINEL:
                    done = True
                else:
                    pending.append(item)
            except queue.Empty:
                pass
            now = time.monotonic()
            while len(pending) >= self._max_batch:
                yield pending[:self._max_batch]
                pending = pending[self._max_batch:]
                deadline = now + window
            if (now >= deadline or done) and pending:
                yield pending
                pending = []
                deadline = now + window
        self._producer.raise_pending()
