"""Pipeline utility transformers.

Parity surface: the ~20 utility stages under ``core/.../stages/`` in the
reference (``Cacher``, ``ClassBalancer:25``, ``DropColumns``,
``EnsembleByKey:20``, ``Explode``, ``Lambda:22``, ``MultiColumnAdapter:19``,
``PartitionConsolidator:21-137``, ``RenameColumn``, ``Repartition``,
``SelectColumns``, ``StratifiedRepartition:31``, ``SummarizeData:101``,
``TextPreprocessor:98``, ``Timer:55``, ``UDFTransformer:26``,
``UnicodeNormalize:22``). All are host-side column ops — cheap next to device
compute — so they stay vectorized numpy over the columnar DataFrame.
"""

from __future__ import annotations

import time
import unicodedata
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, HasLabelCol, HasOutputCol, HasSeed, Param
from ..core.pipeline import Estimator, Model, Transformer

__all__ = [
    "Cacher", "DropColumns", "SelectColumns", "RenameColumn", "Repartition",
    "Explode", "Lambda", "UDFTransformer", "MultiColumnAdapter",
    "ClassBalancer", "ClassBalancerModel", "EnsembleByKey",
    "StratifiedRepartition", "SummarizeData", "TextPreprocessor",
    "UnicodeNormalize", "Timer", "TimerModel", "PartitionConsolidator",
]


class Cacher(Transformer):
    """Materialization hint (reference ``stages/Cacher.scala``). Our frames
    are already materialized columns, so this is the identity."""

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.cache()


class DropColumns(Transformer):
    cols = Param((list, str), default=[], doc="columns to drop")

    def __init__(self, cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if cols is not None:
            self.set(cols=list(cols))

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.drop(*self.get("cols"))


class SelectColumns(Transformer):
    cols = Param((list, str), default=[], doc="columns to keep")

    def __init__(self, cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if cols is not None:
            self.set(cols=list(cols))

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.select(self.get("cols"))


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    def _transform(self, df: DataFrame) -> DataFrame:
        return df.rename({self.get("input_col"): self.get("output_col")})


class Repartition(Transformer):
    n = Param(int, default=1, doc="target partition count")
    disable = Param(bool, default=False, doc="no-op switch")

    def _transform(self, df: DataFrame) -> DataFrame:
        if self.get("disable"):
            return df
        return df.repartition(self.get("n"))


class Explode(Transformer, HasInputCol, HasOutputCol):
    """One output row per element of a list-valued column
    (reference ``stages/Explode.scala``)."""

    def _transform(self, df: DataFrame) -> DataFrame:
        src = df[self.get("input_col")]
        counts = np.array([len(v) for v in src])
        idx = np.repeat(np.arange(len(df)), counts)
        out = df.take(idx)
        flat = np.empty(int(counts.sum()), dtype=object)
        k = 0
        for v in src:
            for item in v:
                flat[k] = item
                k += 1
        return out.with_column(self.get("output_col"), flat)


class Lambda(Transformer):
    """Arbitrary DataFrame→DataFrame function as a stage
    (reference ``stages/Lambda.scala:22``). The callable is transient for
    serialization — re-attach after load."""

    transform_fn = ComplexParam(default=None, doc="DataFrame -> DataFrame")

    def __init__(self, transform_fn: Optional[Callable] = None, **kw):
        super().__init__(**kw)
        if transform_fn is not None:
            self.set(transform_fn=transform_fn)

    def _transform(self, df: DataFrame) -> DataFrame:
        fn = self.get("transform_fn")
        if fn is None:
            raise ValueError("Lambda.transform_fn is not set (transient after load)")
        return fn(df)


class UDFTransformer(Transformer, HasInputCol, HasOutputCol):
    """Apply a per-row (or vectorized) function to one or more columns
    (reference ``stages/UDFTransformer.scala:26``)."""

    udf = ComplexParam(default=None, doc="row function; transient on save")
    input_cols = Param((list, str), default=[], doc="multi-input mode columns")
    vectorized = Param(bool, default=False,
                       doc="if true, udf receives whole column arrays")

    def __init__(self, udf: Optional[Callable] = None, **kw):
        super().__init__(**kw)
        if udf is not None:
            self.set(udf=udf)

    def _transform(self, df: DataFrame) -> DataFrame:
        fn = self.get("udf")
        if fn is None:
            raise ValueError("UDFTransformer.udf is not set (transient after load)")
        cols = self.get("input_cols") or [self.get("input_col")]
        arrays = [df[c] for c in cols]
        if self.get("vectorized"):
            result = fn(*arrays)
        else:
            result = np.empty(len(df), dtype=object)
            for i in range(len(df)):
                result[i] = fn(*(a[i] for a in arrays))
            # collapse to numeric when possible
            try:
                result = np.asarray([r for r in result])
            except Exception:
                pass
        return df.with_column(self.get("output_col"), result)


class MultiColumnAdapter(Transformer):
    """Run a single-column stage over many column pairs
    (reference ``stages/MultiColumnAdapter.scala:19``)."""

    base_stage = ComplexParam(default=None, doc="stage with input_col/output_col")
    input_cols = Param((list, str), default=[], doc="input columns")
    output_cols = Param((list, str), default=[], doc="output columns")

    def _transform(self, df: DataFrame) -> DataFrame:
        base = self.get("base_stage")
        ins, outs = self.get("input_cols"), self.get("output_cols")
        if len(ins) != len(outs):
            raise ValueError("input_cols and output_cols must align")
        cur = df
        for i, o in zip(ins, outs):
            stage = base.copy({"input_col": i, "output_col": o})
            cur = stage.transform(cur)
        return cur


class ClassBalancer(Estimator, HasInputCol, HasOutputCol):
    """Compute inverse-frequency weights per label value
    (reference ``stages/ClassBalancer.scala:25``)."""

    broadcast_join = Param(bool, default=True, doc="parity flag; unused here")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="label", output_col="weight")

    def _fit(self, df: DataFrame) -> "ClassBalancerModel":
        labels = df[self.get("input_col")]
        values, counts = np.unique(labels, return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        m = ClassBalancerModel()
        m.set(input_col=self.get("input_col"), output_col=self.get("output_col"),
              values=[v.item() if isinstance(v, np.generic) else v for v in values],
              weights=[float(w) for w in weights])
        return m


class ClassBalancerModel(Model, HasInputCol, HasOutputCol):
    values = Param(list, default=[], doc="distinct label values")
    weights = Param(list, default=[], doc="weight per label value")

    def _transform(self, df: DataFrame) -> DataFrame:
        table = dict(zip(self.get("values"), self.get("weights")))
        labels = df[self.get("input_col")]
        w = np.array([table[l.item() if isinstance(l, np.generic) else l]
                      for l in labels])
        return df.with_column(self.get("output_col"), w)


class EnsembleByKey(Transformer):
    """Group rows by key columns and average the value columns
    (reference ``stages/EnsembleByKey.scala:20``). Vector columns average
    elementwise."""

    keys = Param((list, str), default=[], doc="grouping key columns")
    cols = Param((list, str), default=[], doc="columns to average")
    col_names = Param((list, str), default=[], doc="output names (default mean(col))")
    collapse_group = Param(bool, default=True,
                           doc="one row per key if true, else broadcast back")
    strategy = Param(str, default="mean", choices=["mean"], doc="aggregation")

    def _transform(self, df: DataFrame) -> DataFrame:
        keys, cols = self.get("keys"), self.get("cols")
        names = self.get("col_names") or [f"mean({c})" for c in cols]
        key_rows = list(zip(*(df[k] for k in keys)))
        order: Dict = {}
        for i, kr in enumerate(key_rows):
            order.setdefault(kr, []).append(i)
        groups = list(order.items())
        agg: Dict[str, list] = {k: [] for k in keys}
        means: Dict[str, list] = {n: [] for n in names}
        for kr, idxs in groups:
            for k, kv in zip(keys, kr):
                agg[k].append(kv)
            for c, n in zip(cols, names):
                vals = df[c][idxs]
                if vals.dtype == object:
                    means[n].append(np.mean(np.stack([np.asarray(v) for v in vals]),
                                            axis=0))
                else:
                    means[n].append(float(np.mean(vals)))
        if self.get("collapse_group"):
            return DataFrame({**agg, **means})
        expanded: Dict[str, np.ndarray] = {}
        lookup = {kr: gi for gi, (kr, _) in enumerate(groups)}
        gidx = np.array([lookup[kr] for kr in key_rows])
        for n in names:
            col = means[n]
            if col and isinstance(col[0], np.ndarray):
                arr = np.empty(len(df), dtype=object)
                for i, g in enumerate(gidx):
                    arr[i] = col[g]
            else:
                arr = np.asarray(col)[gidx]
            expanded[n] = arr
        return df.with_columns(expanded)


class StratifiedRepartition(Transformer, HasLabelCol, HasSeed):
    """Reorder rows so every partition sees every label value
    (reference ``stages/StratifiedRepartition.scala:31``). With range
    partitions, round-robin interleaving by label achieves the equal-spread
    mode."""

    mode = Param(str, default="equal", choices=["equal", "original", "mixed"],
                 doc="spread strategy")

    def _transform(self, df: DataFrame) -> DataFrame:
        if self.get("mode") == "original":
            return df
        import collections
        labels = df[self.get("label_col")]
        rng = np.random.default_rng(self.get("seed"))
        queues = [collections.deque(rng.permutation(np.flatnonzero(labels == v)))
                  for v in np.unique(labels)]
        caps = [hi - lo for lo, hi in df.partition_bounds()]
        parts: List[List[int]] = [[] for _ in caps]
        # phase 1: one row of every label to every partition (while supplies
        # last) — the actual contract of the reference's equal mode
        for q in queues:
            for p in range(len(parts)):
                if q and len(parts[p]) < caps[p]:
                    parts[p].append(int(q.popleft()))
        # phase 2: fill remaining capacity cycling the label queues
        li = 0
        for p in range(len(parts)):
            while len(parts[p]) < caps[p]:
                for k in range(len(queues)):
                    q = queues[(li + k) % len(queues)]
                    if q:
                        parts[p].append(int(q.popleft()))
                        li = (li + k + 1) % len(queues)
                        break
        order = [i for part in parts for i in part]
        return df.take(np.array(order))


class SummarizeData(Transformer):
    """Per-column summary statistics table
    (reference ``stages/SummarizeData.scala:101``: counts/percentiles/basic)."""

    counts = Param(bool, default=True, doc="emit count/unique/missing")
    basic = Param(bool, default=True, doc="emit mean/std/min/max")
    percentiles = Param(bool, default=True, doc="emit p25/p50/p75")
    error_threshold = Param(float, default=0.0, doc="parity: percentile error")

    def _transform(self, df: DataFrame) -> DataFrame:
        rows = []
        for name in df.columns:
            col = df[name]
            row: Dict = {"feature": name}
            numeric = col.dtype != object and np.issubdtype(col.dtype, np.number)
            if self.get("counts"):
                row["count"] = len(col)
                if numeric:
                    row["unique_value_count"] = len(np.unique(col)) if len(col) else 0
                    row["missing_value_count"] = int(np.isnan(
                        col.astype(np.float64)).sum())
                else:
                    # object columns can hold None / unhashable values
                    # (e.g. feature vectors); key by bytes/repr in that case
                    seen = set()
                    for v in col:
                        if isinstance(v, np.ndarray):
                            seen.add(v.tobytes())
                        else:
                            try:
                                seen.add(v)
                            except TypeError:
                                seen.add(repr(v))
                    row["unique_value_count"] = len(seen)
                    row["missing_value_count"] = sum(v is None for v in col)
            if self.get("basic"):
                if numeric and len(col):
                    f = col.astype(np.float64)
                    row.update(mean=float(np.nanmean(f)), stddev=float(np.nanstd(f)),
                               min=float(np.nanmin(f)), max=float(np.nanmax(f)))
                else:
                    row.update(mean=np.nan, stddev=np.nan, min=np.nan, max=np.nan)
            if self.get("percentiles"):
                if numeric and len(col):
                    f = col.astype(np.float64)
                    p = np.nanpercentile(f, [25, 50, 75])
                    row.update(p25=float(p[0]), median=float(p[1]), p75=float(p[2]))
                else:
                    row.update(p25=np.nan, median=np.nan, p75=np.nan)
            rows.append(row)
        return DataFrame.from_rows(rows)


class _Trie:
    """Longest-match token replacement (reference ``TextPreprocessor``'s Trie,
    ``stages/TextPreprocessor.scala:98``)."""

    def __init__(self, mapping: Dict[str, str]):
        self.root: Dict = {}
        for k, v in mapping.items():
            node = self.root
            for ch in k:
                node = node.setdefault(ch, {})
            node["\0"] = v

    def translate(self, text: str) -> str:
        out, i, n = [], 0, len(text)
        while i < n:
            node, j, best, best_j = self.root, i, None, i
            while j < n and text[j] in node:
                node = node[text[j]]
                j += 1
                if "\0" in node:
                    best, best_j = node["\0"], j
            if best is not None:
                out.append(best)
                i = best_j
            else:
                out.append(text[i])
                i += 1
        return "".join(out)


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    map = Param(dict, default={}, doc="substring -> replacement map")
    normalize_func = Param(str, default=None,
                           doc="optional pre-normalization: lower|upper")

    def _transform(self, df: DataFrame) -> DataFrame:
        trie = _Trie(self.get("map"))
        norm = self.get("normalize_func")
        src = df[self.get("input_col")]
        out = np.empty(len(src), dtype=object)
        for i, text in enumerate(src):
            t = str(text)
            if norm == "lower":
                t = t.lower()
            elif norm == "upper":
                t = t.upper()
            out[i] = trie.translate(t)
        return df.with_column(self.get("output_col"), out)


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    form = Param(str, default="NFKD", choices=["NFC", "NFD", "NFKC", "NFKD"],
                 doc="unicode normal form")
    lower = Param(bool, default=True, doc="lowercase after normalization")

    def _transform(self, df: DataFrame) -> DataFrame:
        src = df[self.get("input_col")]
        out = np.empty(len(src), dtype=object)
        for i, text in enumerate(src):
            t = unicodedata.normalize(self.get("form"), str(text))
            out[i] = t.lower() if self.get("lower") else t
        return df.with_column(self.get("output_col"), out)


class Timer(Estimator):
    """Wrap a stage and record its wall time
    (reference ``stages/Timer.scala:55``)."""

    stage = ComplexParam(default=None, doc="inner stage to time")
    log_to_scala = Param(bool, default=True, doc="parity flag; logs via python")
    disable_materialization = Param(bool, default=False, doc="parity flag")

    last_fit_seconds: Optional[float] = None

    def _fit(self, df: DataFrame) -> "TimerModel":
        inner = self.get("stage")
        t0 = time.perf_counter()
        if isinstance(inner, Estimator):
            fitted = inner.fit(df)
        else:
            fitted = inner
        self.last_fit_seconds = time.perf_counter() - t0
        m = TimerModel()
        m.set(stage=fitted)
        return m


class TimerModel(Model):
    stage = ComplexParam(default=None, doc="inner fitted transformer")

    last_transform_seconds: Optional[float] = None

    def _transform(self, df: DataFrame) -> DataFrame:
        t0 = time.perf_counter()
        out = self.get("stage").transform(df)
        self.last_transform_seconds = time.perf_counter() - t0
        return out


class PartitionConsolidator(Transformer, HasInputCol, HasOutputCol):
    """Funnel all partitions' rows into a single partition
    (reference ``stages/PartitionConsolidator.scala:21-137`` — used so
    rate-limited services see one worker per host). Row-range partitions make
    this a repartition-to-1."""

    concurrency = Param(int, default=1, doc="parity: downstream concurrency")
    concurrent_timeout = Param(float, default=None, doc="parity flag")

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.repartition(1)
