"""HTTP-on-DataFrame: embed web services as pipeline stages.

Parity surface: the reference's HTTP-on-Spark package
(``core/src/main/scala/com/microsoft/azure/synapse/ml/io/http/``):
request/response row bindings (``HTTPSchema.scala:26-208``), pooled +
async clients with a retry ladder honouring 429 Retry-After
(``HTTPClients.scala:27-170``, ``HandlingUtils.sendWithRetries:75-125``),
input/output parsers (``Parsers.scala``), and the
``HTTPTransformer``/``SimpleHTTPTransformer`` stages
(``HTTPTransformer.scala:91-146``, ``SimpleHTTPTransformer.scala:64-171``).

TPU-first framing: outbound HTTP is host-side work and never touches the
device; concurrency is a thread pool with bounded in-flight futures
(the reference's ``AsyncUtils.bufferedAwait`` pattern) so a service stage
can saturate the network while the accelerator pipeline keeps streaming.
"""

from .schema import (EntityData, HeaderData, HTTPRequestData,
                     HTTPResponseData, StatusLineData)
from .clients import (AsyncHTTPClient, SingleThreadedHTTPClient,
                      advanced_handler, basic_handler, send_with_retries)
from .parsers import (CustomInputParser, CustomOutputParser, JSONInputParser,
                      JSONOutputParser, StringOutputParser)
from .http_transformer import HTTPTransformer, SimpleHTTPTransformer

__all__ = [
    "HeaderData", "EntityData", "StatusLineData", "HTTPRequestData",
    "HTTPResponseData", "send_with_retries", "advanced_handler",
    "basic_handler", "SingleThreadedHTTPClient", "AsyncHTTPClient",
    "JSONInputParser", "CustomInputParser", "JSONOutputParser",
    "StringOutputParser", "CustomOutputParser", "HTTPTransformer",
    "SimpleHTTPTransformer",
]
