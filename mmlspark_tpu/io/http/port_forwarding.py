"""TCP port forwarding for serving behind NAT/firewalls.

Parity surface: the reference's ``PortForwarding``
(``core/src/main/scala/com/microsoft/azure/synapse/ml/io/http/PortForwarding.scala``),
which opens ssh tunnels (jsch) so a driver can reach executor-hosted serving
ports. Redesigned for this runtime:

* :class:`PortForwarder` — a dependency-free, in-process TCP relay
  (accept → connect → two pump threads per connection) with connect retry
  and clean shutdown. This covers the in-cluster case where a plain TCP
  hop suffices (worker → worker, driver → worker routing).
* :func:`forward_port_via_ssh` — the ssh-tunnel case (parity with the
  reference's ``forwardPortToRemote``): builds/starts an ``ssh -N -L``
  process when an ssh binary exists, with the same bind-address semantics.
"""

from __future__ import annotations

import shutil
import socket
import subprocess
import threading
import time
from typing import List, Optional

__all__ = ["PortForwarder", "forward_port_via_ssh"]

_BUF = 64 * 1024


class PortForwarder:
    """Relay ``bind_host:local_port`` → ``remote_host:remote_port``.

    ``local_port=0`` picks a free port (read it from ``.local_port`` after
    ``start()``). Backend connect failures are retried with exponential
    backoff up to ``connect_retries`` before the client connection closes —
    the retry ladder the reference gets from ssh reconnect policies.
    """

    def __init__(self, remote_host: str, remote_port: int,
                 local_port: int = 0, bind_host: str = "127.0.0.1",
                 connect_retries: int = 3, backoff_s: float = 0.2):
        self.remote_host = remote_host
        self.remote_port = remote_port
        self.local_port = local_port
        self.bind_host = bind_host
        self.connect_retries = connect_retries
        self.backoff_s = backoff_s
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        self._stopping = threading.Event()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "PortForwarder":
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.bind_host, self.local_port))
        srv.listen(32)
        # a blocked accept() does not reliably wake on close(); poll so
        # stop() can always reclaim the port
        srv.settimeout(0.2)
        self.local_port = srv.getsockname()[1]
        self._server = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"pfwd-{self.local_port}",
            daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def __enter__(self) -> "PortForwarder":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals ----------------------------------------------------------
    def _connect_backend(self) -> Optional[socket.socket]:
        delay = self.backoff_s
        # deliberate un-jittered ladder: connect_retries/backoff_s are this
        # class's public parity knobs (reference PortForwarding semantics)
        # and the stop event must interrupt the wait mid-ladder
        for attempt in range(self.connect_retries + 1):  # tpulint: disable=TPU009
            if self._stopping.is_set():
                return None
            try:
                return socket.create_connection(
                    (self.remote_host, self.remote_port), timeout=10)
            except OSError:
                if attempt == self.connect_retries:
                    return None
                time.sleep(delay)
                delay *= 2
        return None

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                client, _ = self._server.accept()
                client.settimeout(None)
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed by stop()
            backend = self._connect_backend()
            if backend is None:
                client.close()
                continue
            with self._lock:
                self._conns += [client, backend]
            remaining = [2]  # pump directions still running
            for src, dst in ((client, backend), (backend, client)):
                threading.Thread(target=self._pump,
                                 args=(src, dst, remaining),
                                 daemon=True).start()

    def _pump(self, src: socket.socket, dst: socket.socket,
              remaining: List[int]) -> None:
        try:
            while True:
                data = src.recv(_BUF)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            # half-close so the peer pump drains the other direction; the
            # last pump out fully closes both and drops the registry refs
            # (a long-lived relay must not leak one fd pair per connection)
            for s, how in ((dst, socket.SHUT_WR), (src, socket.SHUT_RD)):
                try:
                    s.shutdown(how)
                except OSError:
                    pass
            with self._lock:
                remaining[0] -= 1
                last = remaining[0] == 0
                if last:
                    for s in (src, dst):
                        if s in self._conns:
                            self._conns.remove(s)
            if last:
                for s in (src, dst):
                    try:
                        s.close()
                    except OSError:
                        pass


def forward_port_via_ssh(remote_host: str, remote_port: int,
                         local_port: int, ssh_host: str,
                         ssh_user: Optional[str] = None,
                         key_file: Optional[str] = None,
                         bind_address: str = "127.0.0.1",
                         extra_args: Optional[List[str]] = None,
                         start: bool = True):
    """``ssh -N -L bind:local:remote_host:remote_port [user@]ssh_host``.

    Returns ``(argv, process_or_None)``; ``process`` is None when
    ``start=False`` or no ssh binary is on PATH (argv is still returned so
    callers can run it elsewhere). Parity: ``PortForwarding.forwardPortToRemote``.
    """
    argv = ["ssh", "-N", "-o", "StrictHostKeyChecking=no",
            "-o", "ExitOnForwardFailure=yes",
            "-L", f"{bind_address}:{local_port}:{remote_host}:{remote_port}"]
    if key_file:
        argv += ["-i", key_file]
    argv += list(extra_args or [])
    argv.append(f"{ssh_user}@{ssh_host}" if ssh_user else ssh_host)
    proc = None
    if start and shutil.which("ssh"):
        proc = subprocess.Popen(argv, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
    return argv, proc
