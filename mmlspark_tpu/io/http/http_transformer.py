"""HTTP transformer stages: send a request column, get a response column.

Parity:

* ``HTTPTransformer`` (``io/http/HTTPTransformer.scala:91-146``) — maps a
  column of :class:`HTTPRequestData` to a column of
  :class:`HTTPResponseData` per partition, sharing one pooled client per
  process (``:101-113``) and using the async client when ``concurrency > 1``.
* ``SimpleHTTPTransformer`` (``io/http/SimpleHTTPTransformer.scala:64-171``)
  — composes input parser → HTTP → error split (non-2xx rows land in
  ``error_col`` with a null output, ``:33-63,137-140``) → output parser.
"""

from __future__ import annotations

from typing import Optional


from ...core.dataframe import DataFrame, object_col
from ...core.params import (ComplexParam, HasErrorCol, HasInputCol,
                            HasOutputCol, Param)
from ...core.pipeline import Transformer
from .clients import AsyncHTTPClient, SingleThreadedHTTPClient, advanced_handler
from .parsers import JSONOutputParser
from .schema import HTTPResponseData

__all__ = ["HTTPTransformer", "SimpleHTTPTransformer", "ErrorUtils"]


class HTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """Column of requests → column of responses."""

    concurrency = Param(int, default=1, doc="max in-flight requests per partition")
    partition_parallelism = Param(int, default=1,
                                  doc="partitions processed at once; total "
                                      "in-flight = this × concurrency (the "
                                      "Spark analogue is concurrent tasks × "
                                      "concurrency), so the default keeps the "
                                      "user-set concurrency cap exact")
    timeout = Param(float, default=60.0, doc="per-request timeout seconds")
    backoffs_ms = Param((list, int), default=[100, 500, 1000],
                        doc="retry backoff ladder in milliseconds")
    handler = ComplexParam(default=None, saver=None,
                           doc="optional fn(session, HTTPRequestData) -> "
                               "HTTPResponseData override (transient)")

    def _client(self):
        handler = self.get_or_none("handler") or advanced_handler(
            *self.get("backoffs_ms"), timeout=self.get("timeout"))
        c = self.get("concurrency")
        if c > 1:
            return AsyncHTTPClient(c, handler)
        return SingleThreadedHTTPClient(handler)

    def _transform(self, df: DataFrame) -> DataFrame:
        in_col, out_col = self.get("input_col"), self.get("output_col")

        def run(part: DataFrame, _i: int) -> DataFrame:
            client = self._client()
            resps = list(client.send(iter(part[in_col])))
            return part.with_column(out_col, object_col(resps))

        return df.map_partitions(run,
                                 max_workers=self.get("partition_parallelism"))


class ErrorUtils:
    """Split responses into (ok_value, error_value) — parity with the
    error-splitting UDF of ``SimpleHTTPTransformer.scala:33-63``."""

    OK_CODES = (200, 201, 202)

    @staticmethod
    def split(resp: Optional[HTTPResponseData]):
        if resp is None:
            return None, {"statusCode": None, "reasonPhrase": "request failed",
                          "entity": None}
        if resp.status_code in ErrorUtils.OK_CODES:
            return resp, None
        return None, {"statusCode": resp.status_code,
                      "reasonPhrase": resp.status_line.reason_phrase,
                      "entity": resp.string_content()}


class SimpleHTTPTransformer(Transformer, HasInputCol, HasOutputCol, HasErrorCol):
    """input parser → HTTP → error split → output parser, as one stage."""

    input_parser = ComplexParam(default=None,
                                doc="HTTPInputParser stage (e.g. JSONInputParser)")
    output_parser = ComplexParam(default=None,
                                 doc="HTTPOutputParser stage; default JSON")
    concurrency = Param(int, default=1, doc="max in-flight requests")
    timeout = Param(float, default=60.0, doc="per-request timeout seconds")
    handler = ComplexParam(default=None, saver=None,
                           doc="optional custom handler fn (transient)")

    _REQ = "__http_request__"
    _RESP = "__http_response__"

    def flatten_stages(self):
        """The internal pipeline, for introspection (parity:
        ``SimpleHTTPTransformer.makePipeline:118-160``)."""
        inp = self.get_or_none("input_parser")
        if inp is None:
            raise ValueError("input_parser must be set (e.g. JSONInputParser)")
        outp = self.get_or_none("output_parser") or JSONOutputParser()
        inp = inp.copy({"input_col": self.get("input_col"), "output_col": self._REQ})
        outp = outp.copy({"input_col": self._RESP, "output_col": self.get("output_col")})
        http = HTTPTransformer(input_col=self._REQ, output_col=self._RESP,
                               concurrency=self.get("concurrency"),
                               timeout=self.get("timeout"))
        if self.get_or_none("handler") is not None:
            http.set(handler=self.get("handler"))
        return inp, http, outp

    def _transform(self, df: DataFrame) -> DataFrame:
        inp, http, outp = self.flatten_stages()
        cur = http.transform(inp.transform(df))
        oks, errs = [], []
        for resp in cur[self._RESP]:
            ok, err = ErrorUtils.split(resp)
            oks.append(ok)
            errs.append(err)
        cur = cur.with_column(self._RESP, object_col(oks))
        cur = cur.with_column(self.get("error_col"), object_col(errs))
        cur = outp.transform(cur)
        return cur.drop(self._REQ, self._RESP)
