"""Typed request/response values carried in DataFrame columns.

Parity: the reference models HTTP requests/responses as Spark rows through
``SparkBindings`` case classes (``io/http/HTTPSchema.scala``: ``HeaderData:26``,
``EntityData:38``, ``StatusLineData:76``, ``HTTPResponseData:90``,
``HTTPRequestData:166``). Here they are slotted dataclasses stored in object
columns; ``to_dict``/``from_dict`` give the JSON-shaped form used by
persistence and the serving wire format.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["HeaderData", "EntityData", "StatusLineData", "HTTPRequestData",
           "HTTPResponseData"]


@dataclass
class HeaderData:
    name: str
    value: str

    def to_dict(self):
        return {"name": self.name, "value": self.value}

    @staticmethod
    def from_dict(d):
        return HeaderData(d["name"], d["value"])


@dataclass
class EntityData:
    """Body bytes + the content metadata the reference tracks
    (``HTTPSchema.scala:38-75``)."""
    content: bytes = b""
    content_encoding: Optional[HeaderData] = None
    content_length: Optional[int] = None
    content_type: Optional[HeaderData] = None
    is_chunked: bool = False
    is_repeatable: bool = True
    is_streaming: bool = False

    @staticmethod
    def from_string(s: str, content_type: str = "application/json") -> "EntityData":
        b = s.encode("utf-8")
        return EntityData(content=b, content_length=len(b),
                          content_type=HeaderData("Content-Type", content_type))

    def string_content(self) -> str:
        return self.content.decode("utf-8", errors="replace")

    def to_dict(self):
        return {
            "content": self.content.decode("latin-1"),
            "contentEncoding": self.content_encoding.to_dict() if self.content_encoding else None,
            "contentLength": self.content_length,
            "contentType": self.content_type.to_dict() if self.content_type else None,
            "isChunked": self.is_chunked,
            "isRepeatable": self.is_repeatable,
            "isStreaming": self.is_streaming,
        }

    @staticmethod
    def from_dict(d):
        return EntityData(
            content=d.get("content", "").encode("latin-1"),
            content_encoding=HeaderData.from_dict(d["contentEncoding"])
            if d.get("contentEncoding") else None,
            content_length=d.get("contentLength"),
            content_type=HeaderData.from_dict(d["contentType"])
            if d.get("contentType") else None,
            is_chunked=d.get("isChunked", False),
            is_repeatable=d.get("isRepeatable", True),
            is_streaming=d.get("isStreaming", False),
        )


@dataclass
class StatusLineData:
    protocol_version: str = "HTTP/1.1"
    status_code: int = 200
    reason_phrase: str = "OK"

    def to_dict(self):
        return {"protocolVersion": self.protocol_version,
                "statusCode": self.status_code,
                "reasonPhrase": self.reason_phrase}

    @staticmethod
    def from_dict(d):
        return StatusLineData(d.get("protocolVersion", "HTTP/1.1"),
                              d["statusCode"], d.get("reasonPhrase", ""))


@dataclass
class HTTPRequestData:
    """Parity: ``HTTPSchema.scala:166-208`` (method/URI/headers/entity)."""
    url: str = ""
    method: str = "GET"
    headers: List[HeaderData] = field(default_factory=list)
    entity: Optional[EntityData] = None

    @staticmethod
    def from_json(url: str, payload, method: str = "POST",
                  headers: Optional[List[HeaderData]] = None) -> "HTTPRequestData":
        return HTTPRequestData(
            url=url, method=method, headers=list(headers or []),
            entity=EntityData.from_string(json.dumps(payload)))

    def header_map(self) -> dict:
        h = {hd.name: hd.value for hd in self.headers}
        if self.entity and self.entity.content_type:
            h.setdefault(self.entity.content_type.name, self.entity.content_type.value)
        return h

    def to_dict(self):
        return {"url": self.url, "method": self.method,
                "headers": [h.to_dict() for h in self.headers],
                "entity": self.entity.to_dict() if self.entity else None}

    @staticmethod
    def from_dict(d):
        return HTTPRequestData(
            url=d.get("url", ""), method=d.get("method", "GET"),
            headers=[HeaderData.from_dict(h) for h in d.get("headers", [])],
            entity=EntityData.from_dict(d["entity"]) if d.get("entity") else None)


@dataclass
class HTTPResponseData:
    headers: List[HeaderData] = field(default_factory=list)
    entity: Optional[EntityData] = None
    status_line: StatusLineData = field(default_factory=StatusLineData)
    locale: str = "en_US"

    @property
    def status_code(self) -> int:
        return self.status_line.status_code

    def string_content(self) -> str:
        return self.entity.string_content() if self.entity else ""

    def json_content(self):
        return json.loads(self.string_content())

    def to_dict(self):
        return {"headers": [h.to_dict() for h in self.headers],
                "entity": self.entity.to_dict() if self.entity else None,
                "statusLine": self.status_line.to_dict(),
                "locale": self.locale}

    @staticmethod
    def from_dict(d):
        return HTTPResponseData(
            headers=[HeaderData.from_dict(h) for h in d.get("headers", [])],
            entity=EntityData.from_dict(d["entity"]) if d.get("entity") else None,
            status_line=StatusLineData.from_dict(d["statusLine"]),
            locale=d.get("locale", "en_US"))
