"""Input/output parser stages bridging typed columns ↔ HTTP values.

Parity: ``io/http/Parsers.scala`` — ``JSONInputParser:35`` (row value →
POSTed JSON ``HTTPRequestData``), ``CustomInputParser:92`` (user function),
``JSONOutputParser:154`` (``HTTPResponseData`` → parsed JSON value),
``StringOutputParser:210`` (entity → string), ``CustomOutputParser:231``.
"""

from __future__ import annotations

from typing import Callable, Optional

from ...core.dataframe import DataFrame, object_col
from ...core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ...core.serialize import to_jsonable
from ...core.pipeline import Transformer
from .schema import HeaderData, HTTPRequestData

__all__ = ["HTTPInputParser", "JSONInputParser", "CustomInputParser",
           "HTTPOutputParser", "JSONOutputParser", "StringOutputParser",
           "CustomOutputParser"]


class HTTPInputParser(Transformer, HasInputCol, HasOutputCol):
    """Base: column of values → column of :class:`HTTPRequestData`."""


class JSONInputParser(HTTPInputParser):
    """JSON-encode each input value and POST it to ``url``
    (parity: ``Parsers.scala:35-90``)."""

    url = Param(str, doc="target URL for every request")
    method = Param(str, default="POST", doc="HTTP method")
    headers = Param(dict, default={}, doc="static headers added to each request")

    def _transform(self, df: DataFrame) -> DataFrame:
        hdrs = [HeaderData(k, v) for k, v in self.get("headers").items()]
        url, method = self.get("url"), self.get("method")
        col = df[self.get("input_col")]
        reqs = [HTTPRequestData.from_json(url, to_jsonable(v), method, hdrs)
                for v in col]
        return df.with_column(self.get("output_col"), object_col(reqs))


class CustomInputParser(HTTPInputParser):
    """User function value → :class:`HTTPRequestData`
    (parity: ``Parsers.scala:92-120``)."""

    udf = ComplexParam(saver=None, doc="fn(value) -> HTTPRequestData (transient)")

    def _transform(self, df: DataFrame) -> DataFrame:
        fn: Callable = self.get("udf")
        col = df[self.get("input_col")]
        return df.with_column(self.get("output_col"),
                              object_col([fn(v) for v in col]))


class HTTPOutputParser(Transformer, HasInputCol, HasOutputCol):
    """Base: column of :class:`HTTPResponseData` → column of values."""


class JSONOutputParser(HTTPOutputParser):
    """Parse each response entity as JSON; optional ``post_process`` hook
    (parity: ``Parsers.scala:154-208``)."""

    post_process = ComplexParam(default=None, saver=None,
                                doc="optional fn(parsed_json) -> value (transient)")

    def _transform(self, df: DataFrame) -> DataFrame:
        post: Optional[Callable] = self.get_or_none("post_process")
        out = []
        for resp in df[self.get("input_col")]:
            if resp is None:
                out.append(None)
                continue
            try:
                v = resp.json_content()
            except Exception:
                v = None
            out.append(post(v) if (post is not None and v is not None) else v)
        return df.with_column(self.get("output_col"), object_col(out))


class StringOutputParser(HTTPOutputParser):
    """Entity bytes → string column (parity: ``Parsers.scala:210-229``)."""

    def _transform(self, df: DataFrame) -> DataFrame:
        vals = [None if r is None else r.string_content()
                for r in df[self.get("input_col")]]
        return df.with_column(self.get("output_col"), object_col(vals))


class CustomOutputParser(HTTPOutputParser):
    """User function response → value (parity: ``Parsers.scala:231-258``)."""

    udf = ComplexParam(saver=None, doc="fn(HTTPResponseData) -> value (transient)")

    def _transform(self, df: DataFrame) -> DataFrame:
        fn: Callable = self.get("udf")
        vals = [None if r is None else fn(r) for r in df[self.get("input_col")]]
        return df.with_column(self.get("output_col"), object_col(vals))
