"""Pooled + async HTTP clients with the reference's retry ladder.

Parity: ``io/http/HTTPClients.scala`` / ``Clients.scala``:

* ``send_with_retries`` — status handling of ``HandlingUtils.sendWithRetries``
  (``HTTPClients.scala:75-125``): 200/201/202/400 succeed, 429 sleeps for the
  ``Retry-After`` header and does NOT consume a retry, anything else burns one
  entry of the backoff ladder (default 100/500/1000 ms).
* ``advanced_handler`` / ``basic_handler`` — ``HandlingUtils.advanced/basic``
  (``:126-155``); socket timeouts return ``None`` like the reference.
* ``SingleThreadedHTTPClient`` / ``AsyncHTTPClient`` — the sync and
  bounded-concurrency clients (``Clients.scala:26-62``); the async variant
  keeps at most ``concurrency`` requests in flight via
  :func:`mmlspark_tpu.utils.async_utils.map_buffered`, the futures+
  ``bufferedAwait`` pattern of the reference.

Sessions are pooled per thread (``requests.Session`` is not thread-safe),
mirroring the intent of the reference's per-JVM client sharing
(``HTTPTransformer.scala:101-113``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, Iterator, List, Optional

import requests

from ...utils.async_utils import map_buffered
from .schema import (EntityData, HeaderData, HTTPRequestData,
                     HTTPResponseData, StatusLineData)

__all__ = ["send_with_retries", "advanced_handler", "basic_handler",
           "SingleThreadedHTTPClient", "AsyncHTTPClient", "shared_session",
           "post_json_batches"]

DEFAULT_BACKOFFS_MS = (100, 500, 1000)


class _ThreadLocalSession:
    """One pooled ``requests.Session`` per thread. The reference shares one
    thread-safe ``CloseableHttpClient`` per JVM; ``requests.Session`` is NOT
    thread-safe (cookie jar mutation), so the per-process sharing happens at
    thread granularity here."""

    def __init__(self):
        self._local = threading.local()

    def get(self) -> requests.Session:
        s = getattr(self._local, "session", None)
        if s is None:
            s = requests.Session()
            self._local.session = s
        return s


#: per-process pooled sessions (reference: SharedVariable[CloseableHttpClient])
shared_session = _ThreadLocalSession()


def _to_response(resp: requests.Response) -> HTTPResponseData:
    headers = [HeaderData(k, v) for k, v in resp.headers.items()]
    ct = resp.headers.get("Content-Type")
    entity = EntityData(
        content=resp.content or b"",
        content_length=len(resp.content or b""),
        content_type=HeaderData("Content-Type", ct) if ct else None)
    return HTTPResponseData(
        headers=headers, entity=entity,
        status_line=StatusLineData("HTTP/1.1", resp.status_code, resp.reason or ""))


def _execute(session: requests.Session, request: HTTPRequestData,
             timeout: float) -> requests.Response:
    body = request.entity.content if request.entity else None
    return session.request(request.method, request.url,
                           headers=request.header_map(), data=body,
                           timeout=timeout)


def send_with_retries(session: requests.Session, request: HTTPRequestData,
                      backoffs_ms: Iterable[int] = DEFAULT_BACKOFFS_MS,
                      timeout: float = 60.0) -> HTTPResponseData:
    """Reference semantics of ``HandlingUtils.sendWithRetries:75-125``."""
    retries: List[int] = list(backoffs_ms)
    # reference-parity retry ladder: fixed backoff list, Retry-After
    # honored, 429 doesn't consume a retry — RetryPolicy's jittered
    # exponential schedule would change observable reference semantics
    while True:  # tpulint: disable=TPU009
        resp = _execute(session, request, timeout)
        code = resp.status_code
        if code in (200, 201, 202, 400):
            return _to_response(resp)
        if code == 429:
            retry_after = resp.headers.get("Retry-After")
            if retry_after is not None:
                try:
                    time.sleep(float(retry_after))
                except ValueError:
                    pass
            # rate limiting does not consume a retry (reference :115-118)
            if not retries:
                return _to_response(resp)
            time.sleep(retries[0] / 1000.0)
            continue
        if not retries:
            return _to_response(resp)
        time.sleep(retries.pop(0) / 1000.0)


def advanced_handler(*backoffs_ms: int, timeout: float = 60.0
                     ) -> Callable[[requests.Session, HTTPRequestData],
                                   Optional[HTTPResponseData]]:
    """``HandlingUtils.advanced`` — retries; timeout → None (``:126-144``)."""
    ladder = backoffs_ms or DEFAULT_BACKOFFS_MS

    def handle(session, request):
        try:
            return send_with_retries(session, request, ladder, timeout)
        except requests.RequestException:
            # any transport-level failure (timeout, connection, malformed
            # URL, ...) becomes a per-row error, never a whole-transform crash
            return None

    return handle


def basic_handler(session: requests.Session,
                  request: HTTPRequestData) -> HTTPResponseData:
    """``HandlingUtils.basic`` — one shot, no retries (``:147-152``)."""
    return _to_response(_execute(session, request, 60.0))


class SingleThreadedHTTPClient:
    """Sequential client (reference ``SingleThreadedHTTPClient``)."""

    def __init__(self, handler=None, timeout: float = 60.0):
        self.handler = handler or advanced_handler(timeout=timeout)

    def send(self, requests_it: Iterable[Optional[HTTPRequestData]]
             ) -> Iterator[Optional[HTTPResponseData]]:
        session = shared_session.get()
        for req in requests_it:
            yield None if req is None else self.handler(session, req)


class AsyncHTTPClient:
    """Bounded-concurrency client: ≤ ``concurrency`` requests in flight,
    results yielded in submission order (reference ``AsyncClient`` +
    ``AsyncUtils.bufferedAwait``, ``Clients.scala:48-62``)."""

    def __init__(self, concurrency: int, handler=None, timeout: float = 60.0):
        if concurrency < 1:
            raise ValueError("concurrency must be >= 1")
        self.concurrency = concurrency
        self.handler = handler or advanced_handler(timeout=timeout)

    def send(self, requests_it: Iterable[Optional[HTTPRequestData]]
             ) -> Iterator[Optional[HTTPResponseData]]:
        def one(req):
            # resolve the session inside the worker thread: sessions are
            # thread-local, not process-global
            return None if req is None else self.handler(shared_session.get(), req)

        yield from map_buffered(one, requests_it, self.concurrency)


def post_json_batches(url: str, rows: Iterable[dict], batch_size: int,
                      wrap, headers=(),
                      backoffs_ms: Iterable[int] = DEFAULT_BACKOFFS_MS,
                      what: str = "batched POST") -> int:
    """Accumulate ``rows`` into batches of ``batch_size``, POST each as
    ``wrap(batch)`` JSON, raise on a terminally-failed batch. Shared by the
    PowerBI and search-index sinks. Returns the number of batches sent."""
    session = shared_session.get()
    batch, sent = [], 0

    def flush():
        req = HTTPRequestData.from_json(url, wrap(batch), headers=list(headers))
        resp = send_with_retries(session, req, list(backoffs_ms))
        if resp.status_code not in (200, 201, 202):
            raise IOError(f"{what} failed: {resp.status_code} "
                          f"{resp.string_content()[:200]}")

    for row in rows:
        batch.append(row)
        if len(batch) >= batch_size:
            flush()
            sent += 1
            batch = []
    if batch:
        flush()
        sent += 1
    return sent
