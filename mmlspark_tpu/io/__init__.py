"""IO: HTTP-on-DataFrame, binary/image file ingestion, POST sinks.

Parity surface: the reference's ``core/.../ml/io`` package (http, binary,
image, powerbi) — see the submodules for per-component citations.
"""

from .binary import list_binary_files, read_binary_files
from .image_io import read_images
from .libsvm import read_libsvm
from .parquet import read_csv, read_parquet, write_parquet
from .powerbi import PowerBIWriter, write_to_powerbi

__all__ = ["list_binary_files", "read_binary_files", "read_images",
           "read_libsvm", "read_parquet", "write_parquet", "read_csv",
           "PowerBIWriter", "write_to_powerbi"]
