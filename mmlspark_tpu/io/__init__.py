"""IO: HTTP-on-DataFrame, binary/image file ingestion, POST sinks.

Parity surface: the reference's ``core/.../ml/io`` package (http, binary,
image, powerbi) — see the submodules for per-component citations.
"""

from .binary import list_binary_files, read_binary_files
from .image_io import read_images
from .powerbi import PowerBIWriter, write_to_powerbi

__all__ = ["list_binary_files", "read_binary_files", "read_images",
           "PowerBIWriter", "write_to_powerbi"]
