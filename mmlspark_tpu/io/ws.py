"""Minimal RFC 6455 WebSocket client + server.

The reference's streaming speech path rides the Azure Speech SDK, whose
transport is a websocket pushing audio frames up and recognition events down
(``cognitive/.../SpeechToTextSDK.scala:579``, ``AudioStreams.scala:94``).
This module is the dependency-free transport for that pattern: enough of
RFC 6455 for full-duplex framed messaging between cooperating endpoints —
handshake, text/binary/ping/pong/close frames, client-side masking.
No extensions, no compression.

Used by :mod:`mmlspark_tpu.services.speech_streaming`; reusable by any
service transformer needing a persistent bidirectional stream.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
import threading
from typing import Optional, Tuple

__all__ = ["WebSocketConn", "client_connect", "server_handshake",
           "OP_TEXT", "OP_BINARY", "OP_CLOSE", "OP_PING", "OP_PONG"]

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BINARY = 0x0, 0x1, 0x2
OP_CLOSE, OP_PING, OP_PONG = 0x8, 0x9, 0xA


def _accept_key(key: str) -> str:
    digest = hashlib.sha1((key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()


class WebSocketConn:
    """A connected websocket endpoint (either side).

    ``send(payload, opcode)`` / ``recv() -> (opcode, payload)``. ``recv``
    transparently answers pings and reassembles fragmented messages.
    ``send`` is thread-safe (one writer lock), so a receiver thread's
    automatic pong cannot interleave with a concurrent data frame.
    """

    def __init__(self, sock: socket.socket, mask_outgoing: bool,
                 initial_bytes: bytes = b""):
        self.sock = sock
        self.mask_outgoing = mask_outgoing  # clients mask, servers don't
        self._closed = False
        self._send_lock = threading.Lock()
        self._rbuf = initial_bytes  # bytes read past the handshake

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        if self._rbuf:
            buf, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("websocket peer closed mid-frame")
            buf += chunk
        return buf

    # -- frames -------------------------------------------------------------
    def send(self, payload, opcode: int = OP_TEXT) -> None:
        if isinstance(payload, str):
            payload = payload.encode("utf-8")
        head = bytes([0x80 | opcode])
        n = len(payload)
        mask_bit = 0x80 if self.mask_outgoing else 0
        if n < 126:
            head += bytes([mask_bit | n])
        elif n < (1 << 16):
            head += bytes([mask_bit | 126]) + struct.pack(">H", n)
        else:
            head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
        if self.mask_outgoing:
            mask = os.urandom(4)
            masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
            frame = head + mask + masked
        else:
            frame = head + payload
        with self._send_lock:
            # the socket write IS the critical section: _send_lock exists
            # to keep concurrently-sent frames from interleaving on the
            # wire (a split frame is a protocol error, not a slow call)
            self.sock.sendall(frame)  # tpulint: disable=TPU014

    def send_text(self, s: str) -> None:
        self.send(s, OP_TEXT)

    def send_binary(self, b: bytes) -> None:
        self.send(b, OP_BINARY)

    def _read_frame(self) -> Tuple[bool, int, bytes]:
        b1, b2 = self._recv_exact(2)
        fin = bool(b1 & 0x80)
        opcode = b1 & 0x0F
        masked = bool(b2 & 0x80)
        n = b2 & 0x7F
        if n == 126:
            n = struct.unpack(">H", self._recv_exact(2))[0]
        elif n == 127:
            n = struct.unpack(">Q", self._recv_exact(8))[0]
        mask = self._recv_exact(4) if masked else None
        payload = self._recv_exact(n) if n else b""
        if mask:
            payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        return fin, opcode, payload

    def recv(self) -> Tuple[int, bytes]:
        """Next full message as (opcode, payload); answers pings inline.
        Returns (OP_CLOSE, payload) when the peer closes."""
        message = b""
        msg_op = None
        while True:
            fin, opcode, payload = self._read_frame()
            if opcode == OP_PING:
                self.send(payload, OP_PONG)
                continue
            if opcode == OP_PONG:
                continue
            if opcode == OP_CLOSE:
                if not self._closed:
                    try:
                        self.send(payload, OP_CLOSE)  # echo close
                    except OSError:
                        pass
                    self._closed = True
                return OP_CLOSE, payload
            if opcode in (OP_TEXT, OP_BINARY):
                msg_op = opcode
            message += payload
            if fin:
                return msg_op if msg_op is not None else opcode, message

    def close(self, code: int = 1000) -> None:
        if not self._closed:
            self._closed = True
            try:
                self.send(struct.pack(">H", code), OP_CLOSE)
            except OSError:
                pass
        try:
            self.sock.close()
        except OSError:
            pass


# -- handshakes -------------------------------------------------------------

def client_connect(host: str, port: int, path: str = "/",
                   headers: Optional[dict] = None,
                   timeout: float = 30.0) -> WebSocketConn:
    """Open a client websocket to ``ws://host:port{path}``."""
    sock = socket.create_connection((host, port), timeout=timeout)
    key = base64.b64encode(os.urandom(16)).decode()
    req = [f"GET {path} HTTP/1.1",
           f"Host: {host}:{port}",
           "Upgrade: websocket",
           "Connection: Upgrade",
           f"Sec-WebSocket-Key: {key}",
           "Sec-WebSocket-Version: 13"]
    for k, v in (headers or {}).items():
        req.append(f"{k}: {v}")
    sock.sendall(("\r\n".join(req) + "\r\n\r\n").encode())
    # read the 101 response head
    head = b""
    while b"\r\n\r\n" not in head:
        chunk = sock.recv(4096)
        if not chunk:
            raise ConnectionError("websocket handshake: peer closed")
        head += chunk
        if len(head) > 65536:
            raise ConnectionError("websocket handshake: oversized response")
    status = head.split(b"\r\n", 1)[0].decode(errors="replace")
    if " 101 " not in status + " ":
        raise ConnectionError(f"websocket handshake rejected: {status}")
    head, _, leftover = head.partition(b"\r\n\r\n")
    lines = head.decode().split("\r\n")[1:]
    hdrs = {k.strip().lower(): v.strip() for k, _, v in
            (ln.partition(":") for ln in lines if ":" in ln)}
    if hdrs.get("sec-websocket-accept") != _accept_key(key):
        raise ConnectionError("websocket handshake: bad accept key")
    # frames the server sent right behind the 101 must not be dropped
    return WebSocketConn(sock, mask_outgoing=True, initial_bytes=leftover)


def server_handshake(sock: socket.socket,
                     request_head: bytes) -> Tuple[WebSocketConn, str]:
    """Answer an Upgrade request already read into ``request_head``
    (through the blank line). Returns (conn, request_path)."""
    head, _, leftover = request_head.partition(b"\r\n\r\n")
    lines = head.decode().split("\r\n")
    path = lines[0].split(" ")[1] if len(lines[0].split(" ")) > 1 else "/"
    hdrs = {k.strip().lower(): v.strip() for k, _, v in
            (ln.partition(":") for ln in lines[1:] if ":" in ln)}
    key = hdrs.get("sec-websocket-key")
    if not key:
        raise ConnectionError("not a websocket upgrade request")
    resp = ["HTTP/1.1 101 Switching Protocols",
            "Upgrade: websocket",
            "Connection: Upgrade",
            f"Sec-WebSocket-Accept: {_accept_key(key)}"]
    sock.sendall(("\r\n".join(resp) + "\r\n\r\n").encode())
    return WebSocketConn(sock, mask_outgoing=False,
                         initial_bytes=leftover), path
