"""libsvm/svmlight reader — LightGBM's native text format.

The reference ingests libsvm through Spark's ``libsvm`` datasource before
handing rows to LightGBM (``LightGBMBase.scala`` consumes the assembled
vector column); here the parser is the C++ fastpath
(``native/fastpath.cpp:parse_libsvm``, pure-Python fallback) and the result
is a columnar DataFrame ready for the GBDT estimators: a dense float32
``features`` column, ``label``, and — when ``qid:`` tokens are present —
a ``group`` column for the ranker.
"""

from __future__ import annotations

import warnings
from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..native import parse_libsvm

__all__ = ["read_libsvm"]

_warned_one_based: list = []    # once-per-process latch for the 1-based nudge


def read_libsvm(path: str, n_features: Optional[int] = None,
                zero_based: Optional[bool] = None,
                label_col: str = "label", features_col: str = "features",
                group_col: str = "group",
                npartitions: int = 1, sparse: bool = False) -> DataFrame:
    """Read a libsvm file into a DataFrame with dense feature rows.

    ``zero_based=None`` auto-detects: files whose minimum feature index is 0
    are taken as 0-based, else 1-based (the svmlight convention). ``qid:``
    tokens become a ``group`` column (the ranker's query ids); rows without
    qid omit the column entirely.

    ``sparse=True`` keeps the parsed CSR structure: the features column
    holds scipy CSR row vectors that ``assemble_features`` re-stacks into
    one CSR matrix, so a wide sparse file (text hashes, one-hot ids)
    reaches the GBDT binning layer without ever densifying — the
    ingestion shape of the reference's sparse path
    (``DatasetAggregator.scala:127-183``).
    """
    with open(path, "rb") as f:
        labels, qids, indptr, indices, values = parse_libsvm(f.read())
    n = len(labels)
    if zero_based is None:
        zero_based = bool(len(indices) == 0 or indices.min() == 0)
        if not zero_based and not _warned_one_based:
            # a genuinely 0-based file whose smallest present index is >= 1
            # would be silently shifted down a column here; n_features does
            # not protect (a downshift only shrinks indices, so the range
            # check never fires). Once per process: 1-based is the format's
            # documented convention, so repeating it would be pure noise.
            _warned_one_based.append(True)
            warnings.warn(
                "libsvm: auto-detected 1-based indices (min index "
                f"{int(indices.min())}); pass zero_based explicitly if the "
                "file is 0-based with no feature 0 present", stacklevel=2)
    idx = indices if zero_based else indices - 1
    if len(idx) and idx.min() < 0:
        raise ValueError("libsvm: negative feature index after 1-based "
                         "adjustment; pass zero_based=True if indices "
                         "start at 0")
    F = int(n_features if n_features is not None
            else (idx.max() + 1 if len(idx) else 0))
    if len(idx) and idx.max() >= F:
        raise ValueError(f"libsvm: feature index {int(idx.max())} >= "
                         f"n_features {F}")
    col = np.empty(n, dtype=object)
    rows = np.repeat(np.arange(n), np.diff(indptr))
    if sparse:
        import scipy.sparse as sp
        # duplicate indices in a row: keep the LAST occurrence — the same
        # semantics as the dense path's scatter below (CSR construction
        # would otherwise SUM duplicates, silently diverging from dense)
        keys = rows.astype(np.int64) * max(F, 1) + idx
        _, last_rev = np.unique(keys[::-1], return_index=True)
        keep = np.sort(len(keys) - 1 - last_rev)
        vals32 = values[keep].astype(np.float32)
        idx_k = idx[keep]
        counts = np.bincount(rows[keep], minlength=n)
        offs = np.concatenate([[0], np.cumsum(counts)])
        for i in range(n):
            lo, hi = offs[i], offs[i + 1]
            # built straight from array views — per-row csr slicing of a
            # big matrix costs a binary search per row
            col[i] = sp.csr_matrix(
                (vals32[lo:hi], idx_k[lo:hi], np.array([0, hi - lo])),
                shape=(1, F))
    else:
        dense = np.zeros((n, F), dtype=np.float32)
        dense[rows, idx] = values
        col[:] = list(dense)
    cols = {features_col: col, label_col: labels}
    has_qid = qids >= 0
    if has_qid.any():
        if not has_qid.all():
            # a -1 run would silently become a real lambdarank query of
            # unrelated documents
            raise ValueError(
                f"libsvm: {int((~has_qid).sum())} of {n} rows lack qid:; "
                "a ranking file must tag every row (or none)")
        cols[group_col] = qids
    df = DataFrame(cols)
    return df.repartition(npartitions) if npartitions > 1 else df
