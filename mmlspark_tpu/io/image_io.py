"""Image file ingestion.

Parity: ``io/image/ImageUtils.scala:163`` + the patched Spark image source
(``org/apache/spark/ml/source/image/PatchedImageFileFormat.scala``):
read files/dirs into an image-struct column, silently dropping (or keeping
as null) undecodable files like Spark's ``dropImageFailures``.
"""

from __future__ import annotations

from typing import Optional

from ..core.dataframe import DataFrame, object_col
from ..image.schema import decode_image
from .binary import read_binary_files

__all__ = ["read_images"]


def read_images(path: str, recursive: bool = True,
                pattern: Optional[str] = None,
                drop_failures: bool = True, sample_ratio: float = 1.0,
                seed: int = 0, npartitions: int = 1,
                image_col: str = "image") -> DataFrame:
    raw = read_binary_files(path, recursive, pattern, sample_ratio, seed,
                            inspect_zip=True, npartitions=npartitions)
    images = [decode_image(b, origin=p)
              for p, b in zip(raw["path"], raw["bytes"])]
    df = DataFrame({"path": raw["path"], image_col: object_col(images)},
                   npartitions=npartitions)
    if drop_failures:
        import numpy as np
        mask = np.asarray([im is not None for im in images], dtype=bool)
        df = df.filter(mask)
    return df
