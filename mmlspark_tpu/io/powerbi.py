"""Streaming/batch POST sink for row data.

Parity: ``io/powerbi/PowerBIWriter.scala:114`` — serialize row batches to
JSON and POST them to a push endpoint, with the shared retry ladder
(429 Retry-After handled by :mod:`mmlspark_tpu.io.http.clients`).
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..core.dataframe import DataFrame
from .http.clients import post_json_batches

__all__ = ["write_to_powerbi", "PowerBIWriter"]


def _json_rows(df: DataFrame, cols: Optional[Sequence[str]]):
    from ..core.serialize import to_jsonable
    names = list(cols) if cols else df.columns
    for row in df.iter_rows():
        yield {k: to_jsonable(row[k]) for k in names}


def write_to_powerbi(df: DataFrame, url: str, batch_size: int = 1000,
                     cols: Optional[Sequence[str]] = None,
                     backoffs_ms: Sequence[int] = (100, 500, 1000)) -> int:
    """POST rows in batches; returns the number of batches sent. Raises on a
    terminally-failed batch (parity: writer fails the stream task)."""
    return post_json_batches(url, _json_rows(df, cols), batch_size,
                             wrap=lambda b: {"rows": b},
                             backoffs_ms=backoffs_ms, what="PowerBI push")


class PowerBIWriter:
    """Object form mirroring ``PowerBIWriter``'s stream/batch API."""

    def __init__(self, url: str, batch_size: int = 1000):
        self.url = url
        self.batch_size = batch_size

    def write(self, df: DataFrame) -> int:
        return write_to_powerbi(df, self.url, self.batch_size)
