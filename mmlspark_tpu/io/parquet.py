"""Parquet IO for the DataFrame layer.

The reference reads datasets through Spark's native parquet source; this is
the standalone-framework equivalent, built on pyarrow with a
**row-group/file → partition** mapping so file layout drives partition
parallelism the way Spark's splits do (partitions then pin to local chips
in `map_partitions`, parity: `ONNXModel.scala:499-508`).

pyarrow is an optional dependency (`pip install mmlspark_tpu[io]`); these
functions raise a clear ImportError without it.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import List, Optional, Sequence, Union

from ..core.dataframe import DataFrame, concat

__all__ = ["read_parquet", "write_parquet", "read_csv"]


def _pa():
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet as pq
        return pq
    except ImportError as e:
        raise ImportError(
            "parquet IO requires pyarrow (pip install mmlspark_tpu[io])"
        ) from e


def _expand(path: Union[str, Sequence[str]]) -> List[str]:
    if isinstance(path, (list, tuple)):
        files: List[str] = []
        for p in path:
            files.extend(_expand(p))
        return files
    if os.path.isdir(path):
        return sorted(_glob.glob(os.path.join(path, "*.parquet")))
    if any(ch in path for ch in "*?["):
        return sorted(_glob.glob(path))
    return [path]


def read_parquet(path: Union[str, Sequence[str]],
                 columns: Optional[Sequence[str]] = None,
                 partition_per: str = "row_group") -> DataFrame:
    """Read parquet file(s)/dir/glob into a DataFrame.

    ``partition_per``: ``"row_group"`` (default — each parquet row group
    becomes one partition, the Spark split model) or ``"file"``.
    """
    pq = _pa()
    if partition_per not in ("row_group", "file"):
        raise ValueError(f"partition_per must be 'row_group' or 'file', "
                         f"got {partition_per!r}")
    files = _expand(path)
    if not files:
        raise FileNotFoundError(f"no parquet files match {path!r}")
    parts: List[DataFrame] = []
    for f in files:
        pf = pq.ParquetFile(f)
        if partition_per == "row_group" and pf.num_row_groups > 1:
            for rg in range(pf.num_row_groups):
                parts.append(DataFrame.from_arrow(
                    pf.read_row_group(rg, columns=list(columns)
                                      if columns else None)))
        else:
            parts.append(DataFrame.from_arrow(
                pf.read(columns=list(columns) if columns else None)))
    if len(parts) == 1:
        return parts[0]
    out = concat(parts)
    # exact (possibly uneven) row-group/file boundaries become the
    # partition boundaries — the documented split model
    return DataFrame(dict(out._columns), metadata=out._metadata,
                     partition_sizes=[len(p) for p in parts])


def write_parquet(df: DataFrame, path: str,
                  partitioned: bool = False) -> List[str]:
    """Write a DataFrame to parquet. ``partitioned=True`` writes one file
    per partition under ``path/`` (the executor-parallel layout);
    otherwise one file at ``path``. Returns the written paths."""
    pq = _pa()
    if partitioned:
        os.makedirs(path, exist_ok=True)
        # overwrite semantics: stale part files from a previous, larger
        # write must not survive (read_parquet would silently merge them)
        for old in _glob.glob(os.path.join(path, "part-*.parquet")):
            os.remove(old)
        written = []
        for i, part in enumerate(df.partitions()):
            f = os.path.join(path, f"part-{i:05d}.parquet")
            pq.write_table(part.to_arrow(), f)
            written.append(f)
        return written
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    pq.write_table(df.to_arrow(), path)
    return [path]


def read_csv(path: str, npartitions: int = 1, **pandas_kwargs) -> DataFrame:
    """CSV via pandas (header inference, dtypes, the lot)."""
    import pandas as pd

    return DataFrame.from_pandas(pd.read_csv(path, **pandas_kwargs),
                                 npartitions)
